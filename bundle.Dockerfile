# OLM bundle image (reference: bundle.Dockerfile — label names are the
# OLM registry+v1 contract; values are this operator's).
FROM scratch

LABEL operators.operatorframework.io.bundle.mediatype.v1=registry+v1
LABEL operators.operatorframework.io.bundle.manifests.v1=manifests/
LABEL operators.operatorframework.io.bundle.metadata.v1=metadata/
LABEL operators.operatorframework.io.bundle.package.v1=tpu-operator
LABEL operators.operatorframework.io.bundle.channels.v1=alpha
LABEL operators.operatorframework.io.bundle.channel.default.v1=alpha

LABEL operators.operatorframework.io.test.mediatype.v1=scorecard+v1
LABEL operators.operatorframework.io.test.config.v1=tests/scorecard/

COPY bundle/manifests /manifests/
COPY bundle/metadata /metadata/
COPY bundle/tests/scorecard /tests/scorecard/
