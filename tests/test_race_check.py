"""`make race-check`: LockTracer-wrapped concurrency storms.

The static half of race-check is the opslint `lock-order-graph` +
`resource-lifecycle` pass (exercised by tests/test_opslint_v2.py and
run directly by the Makefile target); this file is the DYNAMIC half —
the highest-contention components driven under
`testing.locktrace.traced()`, which fails on any lock-order inversion
the run exhibits even when no deadlock actually fires. Components are
constructed INSIDE the traced region so their locks are the patched,
edge-recording kind.

Seeded workloads, bounded thread counts: these storms run in tier-1
(`make test`) as well as under `-m race`.
"""

import concurrent.futures
import threading

import pytest

from dpu_operator_tpu.testing.locktrace import traced

pytestmark = pytest.mark.race

SEED = 20260804


def _storm(n_threads, fn):
    barrier = threading.Barrier(n_threads)

    def wrapped(i):
        barrier.wait()
        return fn(i)

    with concurrent.futures.ThreadPoolExecutor(n_threads) as pool:
        futures = [pool.submit(wrapped, i) for i in range(n_threads)]
        return [f.result() for f in futures]


def test_serve_scheduler_has_no_lock_inversions_under_contention():
    """The scheduler's documented order (_state_lock before _lock,
    scheduler before pool/ledger/flight) must hold while submitters,
    a stepper, cancellers and snapshot readers collide — the exact
    thread mix of DecodeService + HTTP ingress + device plugin."""
    from dpu_operator_tpu.workloads import serve

    with traced() as tracer:
        sched = serve.Scheduler(serve.ServeConfig(
            slots=4, kv_blocks=64, kv_block_size=16, queue_limit=256,
            prefill_chunk_tokens=32, prefix_sharing=True))
        reqs = serve.open_loop_arrivals(SEED, 40.0, 2.0)

        def submit(i):
            for req in reqs[i::3]:
                sched.submit(req)
            return True

        def drive(_):
            for _step in range(400):
                if not sched.step():
                    break
            return True

        def observe(_):
            for _n in range(50):
                sched.snapshot()
                sched.capacity()
                sched.cancel(f"absent-{_n}")
            return True

        assert all(_storm(6, lambda i: (submit, drive, observe)
                          [i % 3](i)))
        sched.run()
    assert tracer.find_cycles() == []


def test_kv_pool_sharing_storm_has_no_lock_inversions():
    from dpu_operator_tpu.workloads.kv_pool import KvBlockPool, chain_keys

    with traced() as tracer:
        pool = KvBlockPool(128, 8, sharing=True)
        prompt = tuple(range(32))
        keys = chain_keys(prompt, 8)

        def lifecycle(i):
            owner = f"r{i}"
            mapped = pool.map_prefix(owner, keys)
            need = pool.blocks_for_tokens(len(prompt)) - mapped
            if pool.alloc(owner, need) is None:
                pool.free(owner)
                return 0
            if i == 0:
                pool.register_prefix(owner, keys, len(prompt))
            for pos in range(len(prompt)):
                pool.write_token(owner, pos)
            pool.set_used_tokens(owner, len(prompt))
            snapshot = pool.snapshot()
            pool.free(owner)
            return snapshot["usedBlocks"]

        _storm(8, lifecycle)
        assert pool.outstanding() == 0
    assert tracer.find_cycles() == []


def test_workqueue_informer_storm_has_no_lock_inversions():
    """The watch core's queue + store are the fleet gate's hottest
    locks; adders, workers and re-queuers must order cleanly."""
    from dpu_operator_tpu.k8s.workqueue import RateLimitingQueue

    with traced() as tracer:
        queue = RateLimitingQueue()

        def add(i):
            for n in range(40):
                queue.add(f"key-{(i * 40 + n) % 17}")
            return True

        def work(_):
            for _n in range(40):
                key = queue.get(timeout=0.2)
                if key is None:
                    break
                queue.done(key)
            return True

        _storm(6, lambda i: (add if i % 2 else work)(i))
        queue.shutdown()
    assert tracer.find_cycles() == []
