"""Serve-trace e2e (`make obs-check`): request-lifecycle tracing over
the streaming ingress.

One `POST /v1/generate` with a caller traceparent against a CHUNKED
scheduler with a forced preemption must produce:

- ONE trace_id — the caller's — on the ingress `serve.request` span,
  every `serve.prefill_chunk` span, the `serve.decode` spans and the
  FirstToken flight entry;
- a `tpuctl serve trace <rid>` phase timeline reading queued → prefill
  chunks → decode → preempted → re-prefill → decode → complete;
- a span tree that is BIT-IDENTICAL across two seeded runs (virtual
  clock start/durations, sha256-derived span ids — no wall clock, no
  uuid4 anywhere in the phase path);
- OpenMetrics exemplars on the serve histograms that are grammar-valid
  and join back to flight-recorded FirstToken trace ids, with classic
  0.0.4 scrapes byte-unchanged.

The scheduler is stepped MANUALLY on the test thread (the DecodeService
loop is never started), so the interleaving of POSTs and iterations —
and therefore the virtual span tree — is a pure function of the
scenario.
"""

import itertools
import json
import re
import threading
import time

import pytest

from dpu_operator_tpu import tpuctl
from dpu_operator_tpu.utils import flight, metrics, tracing
from dpu_operator_tpu.workloads import serve

pytestmark = pytest.mark.obs

#: fixed caller trace contexts: the same traceparent both runs, so the
#: adopted trace ids (and the parent span ids the phase spans hang
#: under) are identical run-to-run
BG_TRACE = "ab" * 16
BG_PARENT = f"00-{BG_TRACE}-{'12' * 8}-01"
FG_TRACE = "cd" * 16
FG_PARENT = f"00-{FG_TRACE}-{'34' * 8}-01"


def _stream_post(port, body, traceparent):
    """POST /v1/generate and read the whole chunked NDJSON stream."""
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("POST", "/v1/generate", json.dumps(body),
                     {"Content-Type": "application/json",
                      "traceparent": traceparent})
        resp = conn.getresponse()
        raw = resp.read()
    finally:
        conn.close()
    return [json.loads(line) for line in raw.split(b"\n") if line]


def _pending_count(sched):
    with sched._lock:
        return len(sched._pending)


def _wait_for(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while not cond():
        assert time.monotonic() < deadline, "condition never held"
        time.sleep(0.002)


def _run_scenario():
    """The forced-preemption scenario: a streamed batch-class request
    is admitted and decoding when a streamed interactive request
    arrives on the single slot — the victim is evicted mid-decode,
    waits out the interactive request, re-prefills and completes. Both
    requests ride HTTP with caller traceparents; the scheduler is only
    ever stepped from this thread."""
    flight.RECORDER.clear()
    cfg = serve.ServeConfig(slots=1, kv_blocks=16, kv_block_size=4,
                            prefill_chunk_tokens=4, queue_limit=8)
    sched = serve.Scheduler(cfg)
    service = serve.DecodeService(sched)
    port = service.start_http()
    streams = {}

    def post(name, body, parent):
        streams[name] = _stream_post(port, body, parent)

    bg = threading.Thread(target=post, args=(
        "bg", {"rid": "bg", "prompt_len": 10, "output_len": 6,
               "slo_class": "batch"}, BG_PARENT))
    bg.start()
    _wait_for(lambda: _pending_count(sched) == 1)
    # admit + chunk-prefill the batch request until it is decoding
    steps = 0
    while not any(r.tokens for r in sched._active.values()):
        assert sched.step() and steps < 50
        steps += 1
    fg = threading.Thread(target=post, args=(
        "fg", {"rid": "fg", "prompt_len": 6, "output_len": 2,
               "slo_class": "interactive"}, FG_PARENT))
    fg.start()
    _wait_for(lambda: _pending_count(sched) == 1)
    steps = 0
    while sched.completed_total < 2:
        assert sched.step() and steps < 200
        steps += 1
    bg.join(timeout=10)
    fg.join(timeout=10)
    service.stop()
    events = flight.RECORDER.snapshot()["events"]
    assert sched.preemptions == 1  # the scenario's whole point
    return events, streams


def _serve_events(events, rid):
    return [e for e in events if e.get("kind") == "serve"
            and (e.get("attributes") or {}).get("rid") == rid]


def _span_tree(events):
    """The determinism artifact: every serve-kind event minus the
    wall-clock ring fields (ts, seq)."""
    return [(e["name"], e.get("trace_id"), e.get("span_id"),
             e.get("duration_s"),
             tuple(sorted((e.get("attributes") or {}).items())))
            for e in events if e.get("kind") == "serve"]


def test_one_trace_id_from_ingress_to_every_phase_span():
    events, streams = _run_scenario()
    # the streams themselves completed
    assert streams["bg"][-1] == {"done": True, "tokens": 6}
    assert streams["fg"][-1] == {"done": True, "tokens": 2}
    for rid, trace_id in (("bg", BG_TRACE), ("fg", FG_TRACE)):
        mine = _serve_events(events, rid)
        assert mine, f"no serve events for {rid}"
        # EVERY phase span and lifecycle entry carries the caller's id
        assert {e.get("trace_id") for e in mine} == {trace_id}
        names = [e["name"] for e in mine]
        assert "serve.queued" in names
        assert "serve.prefill_chunk" in names
        assert "serve.decode" in names
        assert any(e["name"] == "FirstToken" for e in mine)
        # the ingress serve.request span adopted the same trace
        ingress = [e for e in events if e.get("kind") == "span"
                   and e.get("name") == "serve.request"
                   and (e.get("attributes") or {}).get("rid") == rid]
        assert ingress and ingress[0]["trace_id"] == trace_id
    # the victim's decode episodes: one ended by the preemption, one
    # by completion
    decodes = [e for e in _serve_events(events, "bg")
               if e["name"] == "serve.decode"]
    assert [(e["attributes"] or {}).get("outcome") for e in decodes] \
        == ["preempted", "complete"]


def test_tpuctl_timeline_reads_the_whole_lifecycle():
    events, _ = _run_scenario()
    view = tpuctl.render_serve_trace(events, "bg")
    assert view["found"] and view["terminal"] == "Completed"
    assert view["traceId"] == BG_TRACE
    assert view["ttftSeconds"] is not None
    order = [k for k, _ in itertools.groupby(
        p["phase"] for p in view["phases"])]
    assert order == ["serve.queued", "serve.prefill_chunk",
                     "serve.decode", "serve.preempted",
                     "serve.prefill_chunk", "serve.decode"]
    # phases are timeline-ordered with durations
    starts = [p["startSeconds"] for p in view["phases"]]
    assert starts == sorted(starts)
    assert all(p["durationSeconds"] >= 0.0 for p in view["phases"])
    # the preempted wait covers the gap between the two residencies
    preempted = next(p for p in view["phases"]
                     if p["phase"] == "serve.preempted")
    assert preempted["durationSeconds"] > 0.0


def test_span_tree_bit_identical_across_two_runs():
    events1, _ = _run_scenario()
    events2, _ = _run_scenario()
    assert _span_tree(events1) == _span_tree(events2)


def test_tpuctl_serve_trace_and_top_over_http():
    """The full CLI path: tpuctl fetches /debug/flight for the
    timeline and /debug/serve{,/ledger} for the top view from a live
    MetricsServer."""
    from dpu_operator_tpu.utils.metrics import MetricsServer

    events, _ = _run_scenario()  # leaves the scenario in the ring
    cfg = serve.ServeConfig(slots=1, kv_blocks=16, kv_block_size=4,
                            prefill_chunk_tokens=4)
    sched = serve.Scheduler(cfg)
    sched.submit(serve.Request(rid="t0", prompt_len=6, output_len=2,
                               arrival_s=0.0))
    sched.run()
    service = serve.DecodeService(sched)
    server = MetricsServer(host="127.0.0.1", port=0,
                           debug_handlers=service.debug_handlers())
    server.start()
    try:
        def args(**kw):
            base = {"cmd": "serve", "metrics_addr":
                    f"127.0.0.1:{server.port}", "token": "",
                    "window": 60.0, "last": 10, "rid": "",
                    "agent_socket": "", "vsp_socket": "",
                    "daemon_addr": ""}
            base.update(kw)
            return type("A", (), base)()

        trace = tpuctl.run(args(action="trace", rid="bg"))
        assert trace["found"] and trace["traceId"] == BG_TRACE
        assert trace["phases"]
        top = tpuctl.run(args(action="top", last=5))
        assert top["iterations"] > 0
        assert set(top["phaseSeconds"]) <= set(serve.LEDGER_PHASES)
        assert top["reconciliation"]["ok"]
    finally:
        server.stop()


# -- exemplar rendering on the serve histograms -------------------------------

_EXEMPLAR_RE = re.compile(
    r' # \{trace_id="([0-9a-f]{32})"\} [0-9][0-9.e+-]*$')


def test_openmetrics_exemplars_join_flight_first_tokens_mid_storm():
    """An OpenMetrics scrape taken mid-storm renders grammar-valid
    exemplars on the serve TTFT histogram whose trace ids resolve to
    flight-recorded FirstToken entries (histograms are process-global,
    so other suites' exemplars may occupy untouched buckets — the join
    is asserted on intersection, the grammar on every exemplar)."""
    flight.RECORDER.clear()
    cfg = serve.ServeConfig(slots=2, kv_blocks=64, kv_block_size=8,
                            prefill_chunk_tokens=16, queue_limit=512)
    sched = serve.Scheduler(cfg)
    sched.submit_all(serve.open_loop_arrivals(
        seed=20260804, rate_rps=8.0, horizon_s=4.0, id_prefix="om"))
    sched.run()
    first_ids = {e.get("trace_id")
                 for e in flight.RECORDER.events(kind="serve")
                 if e["name"] == "FirstToken"}
    assert first_ids
    om = metrics.REGISTRY.render(openmetrics=True)
    assert om.rstrip().endswith("# EOF")
    exemplar_ids = set()
    for line in om.splitlines():
        if not line.startswith("tpu_serve_ttft_seconds_bucket"):
            continue
        if " # " not in line:
            continue
        m = _EXEMPLAR_RE.search(line)
        assert m, f"exemplar violates the OpenMetrics grammar: {line}"
        exemplar_ids.add(m.group(1))
    assert exemplar_ids, "storm produced no TTFT exemplars"
    assert exemplar_ids & first_ids, (
        "no TTFT exemplar joins a flight-recorded FirstToken")


def test_classic_scrape_stays_byte_unchanged_by_exemplars():
    """The 0.0.4 text parser rejects exemplars, so a classic scrape of
    a histogram WITH exemplars must be byte-identical to one without:
    exemplars exist only in the OpenMetrics negotiation."""
    from dpu_operator_tpu.utils.metrics import Histogram
    bare = Histogram("tpu_serve_ttft_seconds", "ttft",
                     buckets=(0.1, 1.0))
    exemplared = Histogram("tpu_serve_ttft_seconds", "ttft",
                           buckets=(0.1, 1.0))
    for value in (0.05, 0.4, 2.0):
        bare.observe(value)
        exemplared.observe(value,
                           exemplar={"trace_id": tracing.det_trace_id(
                               f"x{value}")})
    assert bare._render() == exemplared._render()
    assert not any(" # {" in line for line in exemplared._render())
    assert any(" # {" in line
               for line in exemplared._render(openmetrics=True))
