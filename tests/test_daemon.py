"""Daemon + side-manager integration tests.

Reference analog: daemon_test.go:24-88 (full detect→VSP→serve loop),
hostsidemanager_test.go:235-263 (CNI ADD through real shim → real server →
fake tpu-side daemon asserting attachment count),
dpusidemanager_test.go:22-49 (node reports allocatable with mock devices).
"""

import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
import threading

import pytest

from dpu_operator_tpu.cni import CniShim
from dpu_operator_tpu.daemon import Daemon, HostSideManager, TpuSideManager
from dpu_operator_tpu.deviceplugin import FakeKubelet
from dpu_operator_tpu.platform import (
    DetectorManager,
    FakePlatform,
    TpuDetector,
)
from dpu_operator_tpu.utils.path_manager import PathManager
from dpu_operator_tpu.vsp import GrpcPlugin, MockTpuVsp, VspServer


@pytest.fixture
def pm(short_tmp):
    return PathManager(short_tmp)


def _mock_vsp_on_socket(pm, **kw):
    mock = MockTpuVsp(**kw)
    sock = pm.vendor_plugin_socket()
    pm.ensure_socket_dir(sock)
    server = VspServer(mock, socket_path=sock)
    server.start()
    return mock, server


def _plugin(pm, tpu_mode):
    det = TpuDetector().detection_result(tpu_mode=tpu_mode, identifier="t")
    return GrpcPlugin(det, path_manager=pm, init_timeout=5.0)


def _cni_env(command="ADD", container="sbx1", ifname="net1"):
    return {
        "CNI_COMMAND": command,
        "CNI_CONTAINERID": container,
        "CNI_NETNS": "/var/run/netns/test",
        "CNI_IFNAME": ifname,
        "CNI_ARGS": "K8S_POD_NAMESPACE=default;K8S_POD_NAME=p",
    }


def _cni_conf(device, mode="chip"):
    return json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                       "mode": mode, "deviceID": device})


def test_tpu_side_manager_full_stack(pm, kube, node_agent):
    """TPU-side daemon: VSP + cross-boundary server + device plugin +
    kubelet registration → node allocatable; NF CNI wires after 2 ADDs."""
    node_agent.register_node("tpu-vm-0", labels={"tpu": "true"})
    kubelet = FakeKubelet(pm, node_agent=node_agent, node_name="tpu-vm-0")
    kubelet.start()
    mock, vsp_server = _mock_vsp_on_socket(pm, port=0)
    mgr = TpuSideManager(_plugin(pm, True), pm, client=kube)
    mgr.device_plugin.poll_interval = 0.1
    try:
        mgr.start_vsp()
        mgr.setup_devices()
        mgr.listen()
        mgr.serve()
        assert kubelet.wait_for_devices("google.com/tpu", 4)
        node = kube.get("v1", "Node", "tpu-vm-0")
        assert node["status"]["allocatable"]["google.com/tpu"] == "4"

        # ICI ports auto-advertised from the VSP-reported topology
        # (v5e-4 = 2x2: 4 chips x 2 ports, all on host 0)
        assert mgr.ici_device_plugin is not None
        assert kubelet.wait_for_devices("google.com/ici-port", 8)

        # cross-boundary TCP server forwards into the VSP
        from dpu_operator_tpu.vsp.rpc import VspChannel
        ch = VspChannel(f"127.0.0.1:{mgr.bound_port}")
        ch.call("SliceService", "CreateSliceAttachment",
                {"name": "host0-2", "chip_index": 2})
        ch.close()
        assert "host0-2" in mock.slice_attachments

        # NF CNI: two ADDs for one sandbox wire a network function
        shim = CniShim(pm.cni_server_socket())
        r1 = shim.invoke(_cni_env(container="nfpod1", ifname="net1"),
                         _cni_conf("chip-0", mode="network-function"))
        assert r1.result["tpu"]["networkFunction"] is False
        r2 = shim.invoke(_cni_env(container="nfpod1", ifname="net2"),
                         _cni_conf("chip-1", mode="network-function"))
        assert r2.result["tpu"]["networkFunction"] is True
        assert len(mock.network_functions) == 1
    finally:
        mgr.stop()
        vsp_server.stop()
        kubelet.stop()


def test_host_side_manager_cni_add_creates_slice_attachment(pm, short_tmp):
    """Host-side CNI ADD → allocator + CreateSliceAttachment on the (fake)
    tpu-side daemon — bridgePorts==1 assertion parity."""
    # fake tpu-side daemon: a slice server on TCP backed by a recording mock
    tpu_mock = MockTpuVsp()
    tpu_server = VspServer(tpu_mock, tcp_addr=("127.0.0.1", 0))
    tpu_server.start()

    # host-side VSP returns the fake tpu daemon's addr from Init
    host_mock = MockTpuVsp(port=tpu_server.bound_port)
    # host-side devices must be PCI addresses
    host_mock.get_devices = lambda req: {"devices": {
        "0000:00:04.0": {"id": "0000:00:04.0", "healthy": True,
                         "dev_path": "", "coords": [], "chip_index": 0}}}
    sock = pm.vendor_plugin_socket()
    pm.ensure_socket_dir(sock)
    vsp_server = VspServer(host_mock, socket_path=sock)
    vsp_server.start()

    mgr = HostSideManager(_plugin(pm, False), pm)
    try:
        mgr.start_vsp()
        mgr.setup_devices()
        mgr.listen()
        shim = CniShim(pm.cni_server_socket())
        resp = shim.invoke(_cni_env(), _cni_conf("0000:00:04.0"))
        assert resp.error == ""
        assert resp.result["tpu"]["attachment"] == "host0-0"
        assert len(tpu_mock.slice_attachments) == 1

        # double-ADD for a different sandbox must fail (allocator)
        resp2 = shim.invoke(_cni_env(container="other"),
                            _cni_conf("0000:00:04.0"))
        assert "already allocated" in resp2.error

        # DEL releases and removes the attachment
        resp3 = shim.invoke(_cni_env(command="DEL"),
                            _cni_conf("0000:00:04.0"))
        assert resp3.error == ""
        assert len(tpu_mock.slice_attachments) == 0
    finally:
        mgr.stop()
        vsp_server.stop()
        tpu_server.stop()


def test_daemon_detect_loop_builds_manager(pm, kube):
    """Detection loop: nothing → (hotplug) → tpu side manager runs
    (daemon_test.go:24-88 pattern)."""
    platform = FakePlatform()  # nothing to detect yet
    mock, vsp_server = _mock_vsp_on_socket(pm)
    kubelet = FakeKubelet(pm)
    kubelet.start()
    daemon = Daemon(
        platform, mode="auto", path_manager=pm, client=None,
        detector_manager=DetectorManager([TpuDetector()]),
        vsp_plugin_factory=lambda det: _plugin(pm, det.tpu_mode),
        detect_interval=0.05,
        flavour="kind",
    )
    t = threading.Thread(target=daemon.serve, daemon=True)
    t.start()
    try:
        import time
        time.sleep(0.2)
        assert daemon.manager is None
        platform.set_accel_devices(["/dev/accel0"])  # hotplug
        assert daemon.wait_ready(10)
        assert isinstance(daemon.manager, TpuSideManager)
    finally:
        daemon.stop()
        t.join(timeout=5)
        vsp_server.stop()
        kubelet.stop()


def test_daemon_prepare_installs_shim(pm, short_tmp):
    daemon = Daemon(FakePlatform(), path_manager=pm, flavour="kind")
    daemon.prepare()
    shim_path = os.path.join(pm.cni_host_dir("kind"), "tpu-cni")
    assert os.path.exists(shim_path)
    assert os.access(shim_path, os.X_OK)


def test_daemon_mode_pinning(pm):
    """mode=host must ignore tpu-platform detection (operator pins the side)."""
    platform = FakePlatform(accel=["/dev/accel0"])
    daemon = Daemon(platform, mode="host", path_manager=pm)
    assert daemon.detect_once() is None
    daemon_auto = Daemon(platform, mode="auto", path_manager=pm)
    assert daemon_auto.detect_once().tpu_mode


def test_resize_chips_shrink_drains_then_uncordons(pm, kube, node_agent):
    """VERDICT r2 #7 (beats the reference's TODO, dpudevicehandler.go:78-83):
    shrinking the advertised chip set cordons the node, evicts the
    chip-consuming pod, drops allocatable, and uncordons; growth restores
    without draining."""
    node_agent.register_node("tpu-vm-0", labels={"tpu": "true"})
    kubelet = FakeKubelet(pm, node_agent=node_agent, node_name="tpu-vm-0")
    kubelet.start()
    mock, vsp_server = _mock_vsp_on_socket(pm, port=0)
    mgr = TpuSideManager(_plugin(pm, True), pm, client=kube)
    mgr.device_plugin.poll_interval = 0.05
    try:
        mgr.start_vsp()
        mgr.setup_devices()
        mgr.listen()
        mgr.serve()
        assert kubelet.wait_for_devices("google.com/tpu", 4)

        kube.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "consumer", "namespace": "default"},
            "spec": {"nodeName": "tpu-vm-0", "containers": [{
                "name": "w", "image": "img",
                "resources": {"requests": {"google.com/tpu": "1"},
                              "limits": {"google.com/tpu": "1"}}}]}})
        kube.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "bystander", "namespace": "default"},
            "spec": {"nodeName": "tpu-vm-0",
                     "containers": [{"name": "c", "image": "img"}]}})

        evicted = mgr.resize_chips(2, node_name="tpu-vm-0")
        assert evicted == ["consumer"]
        assert kube.get("v1", "Pod", "consumer", namespace="default") is None
        # non-consuming pod survives the drain
        assert kube.get("v1", "Pod", "bystander",
                        namespace="default") is not None
        # allocatable drops via the ListAndWatch poll
        assert kubelet.wait_for_devices("google.com/tpu", 2)
        node = kube.get("v1", "Node", "tpu-vm-0")
        assert node["status"]["allocatable"]["google.com/tpu"] == "2"
        # uncordoned afterward so the scheduler can place pods again
        assert node["spec"]["unschedulable"] is False

        # growth is non-disruptive: no drain, allocatable restored
        kube.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "consumer2", "namespace": "default"},
            "spec": {"nodeName": "tpu-vm-0", "containers": [{
                "name": "w", "image": "img",
                "resources": {"requests": {"google.com/tpu": "1"}}}]}})
        assert mgr.resize_chips(4, node_name="tpu-vm-0") == []
        assert kube.get("v1", "Pod", "consumer2",
                        namespace="default") is not None
        assert kubelet.wait_for_devices("google.com/tpu", 4)
    finally:
        mgr.stop()
        vsp_server.stop()
        kubelet.stop()


def test_daemon_main_handles_sigterm(short_tmp):
    """Pod termination parity (reference: ctrl.SetupSignalHandler):
    SIGTERM to the daemon process triggers the orderly manager teardown
    and the process exits promptly (not the 30 s kubelet kill window)."""
    import signal
    import subprocess
    import sys
    import time

    import queue
    import threading as _threading

    proc = subprocess.Popen(
        [sys.executable, "-c", (
            "import sys; sys.path.insert(0, %r);"
            "from dpu_operator_tpu.daemon.__main__ import main;"
            "main(['--root', %r, '--mode', 'tpu'])"
        ) % (REPO, short_tmp)],
        # hermetic: HOME at the tmp dir so a developer's ~/.kube/config
        # can never leak into the child's RealKube construction
        env={**os.environ, "NODE_NAME": "n0",
             "KUBERNETES_SERVICE_HOST": "", "HOME": short_tmp},
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True)
    try:
        # wait for the post-registration log line ("installed CNI shim"
        # is emitted by prepare(), which runs AFTER the handlers are
        # set) — a SIGTERM during interpreter start-up would hit the
        # default disposition and prove nothing. Read via a thread so a
        # silent hang fails at the deadline instead of blocking forever.
        lines: "queue.Queue[str]" = queue.Queue()

        def _reader():
            for line in proc.stderr:
                lines.put(line)

        _threading.Thread(target=_reader, daemon=True).start()
        deadline = time.monotonic() + 30
        ready = False
        while time.monotonic() < deadline:
            try:
                if "installed CNI shim" in lines.get(timeout=0.5):
                    ready = True
                    break
            except queue.Empty:
                if proc.poll() is not None:
                    break
        assert ready, "daemon never reached the serve loop"
        time.sleep(0.5)
        assert proc.poll() is None
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=10)
        assert rc == 0, rc
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=5)
