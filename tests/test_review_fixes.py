"""Regression tests for review findings: stable chip indices, per-interface
NF DEL, late-ADD rollback after CNI timeout, fast-fail on app errors."""

import threading
import time

import pytest

from dpu_operator_tpu.cni import CniServer
from dpu_operator_tpu.cni.types import CniRequest
from dpu_operator_tpu.daemon import TpuSideManager
from dpu_operator_tpu.platform.platform import FakePlatform, PciDevice
from dpu_operator_tpu.utils.path_manager import PathManager
from dpu_operator_tpu.vsp.google import GoogleTpuVsp


def _tpu_pci(addr):
    return PciDevice(address=addr, vendor_id="1ae0", device_id="0062")


def test_host_chip_index_stable_across_hot_add():
    """A device added later but sorting earlier must not shift existing
    chip indices (attachment names would collide across pods)."""
    plat = FakePlatform(pci=[_tpu_pci("0000:00:04.0")])
    vsp = GoogleTpuVsp(plat)
    d1 = vsp.get_devices({})["devices"]
    assert d1["0000:00:04.0"]["chip_index"] == 0
    plat.set_pci_devices([_tpu_pci("0000:00:03.0"),
                          _tpu_pci("0000:00:04.0")])
    d2 = vsp.get_devices({})["devices"]
    assert d2["0000:00:04.0"]["chip_index"] == 0  # unchanged
    assert d2["0000:00:03.0"]["chip_index"] == 1  # appended


class _RecordingVsp:
    def __init__(self, fail_wires=0):
        self.wired = []
        self.unwired = []
        self.fail_wires = fail_wires

    def create_network_function(self, a, b):
        if self.fail_wires > 0:
            self.fail_wires -= 1
            raise RuntimeError("dataplane busy")
        self.wired.append((a, b))

    def delete_network_function(self, a, b):
        self.unwired.append((a, b))


    def create_slice_attachment(self, att):
        return att

    def delete_slice_attachment(self, name):
        pass

def _nf_manager(tmp_path, vsp):
    mgr = TpuSideManager.__new__(TpuSideManager)
    mgr.vsp = vsp
    mgr.client = None
    mgr._attach_store = {}
    mgr._attach_lock = threading.Lock()
    mgr._chain_store = {}
    mgr._chain_hops = {}
    import tempfile as _tf
    from dpu_operator_tpu.cni import NetConfCache as _NCC
    _d = _tf.mkdtemp(prefix="nf-ipam-")
    mgr.ipam_dir = _d + "/ipam"
    mgr.nf_cache = _NCC(_d + "/nf")
    return mgr


class _Req:
    def __init__(self, sandbox, device, ifname="net1"):
        self.sandbox_id = sandbox
        self.device_id = device
        self.ifname = ifname
        self.pod_name = "p"
        self.pod_namespace = "default"
        self.netns = "/var/run/netns/x"

        class _NC:
            cni_version = "0.4.0"
            name = ""
            ipam = {}
            ici_ports = []
        self.netconf = _NC()


def test_nf_del_single_interface_preserves_other(tmp_path):
    """DEL of one interface must keep the other's attachment so a retried
    ADD can still reach two attachments and wire the NF."""
    vsp = _RecordingVsp()
    mgr = _nf_manager(tmp_path, vsp)
    mgr._cni_nf_add(_Req("sandboxAAAA", "chip-0"))
    r = mgr._cni_nf_add(_Req("sandboxAAAA", "chip-1", "net2"))
    assert r["tpu"]["networkFunction"] is True
    # per-interface DEL of net2 unwires but keeps net1's attachment
    mgr._cni_nf_del(_Req("sandboxAAAA", "chip-1", "net2"))
    assert len(vsp.unwired) == 1
    entry = mgr._attach_store["sandboxAAAA"]
    assert entry["atts"] == ["nf-sandboxAAAA-chip-0"]
    assert entry["wired"] is False
    # retried ADD reaches two attachments again and re-wires
    r2 = mgr._cni_nf_add(_Req("sandboxAAAA", "chip-1", "net2"))
    assert r2["tpu"]["networkFunction"] is True
    assert len(vsp.wired) == 2


def test_nf_del_without_device_tears_down_sandbox(tmp_path):
    vsp = _RecordingVsp()
    mgr = _nf_manager(tmp_path, vsp)
    mgr._cni_nf_add(_Req("sandboxBBBB", "chip-0"))
    mgr._cni_nf_add(_Req("sandboxBBBB", "chip-1", "net2"))
    mgr._cni_nf_del(_Req("sandboxBBBB", None))
    assert "sandboxBBBB" not in mgr._attach_store
    assert len(vsp.unwired) == 1


def test_nf_wire_failure_allows_retry(tmp_path):
    vsp = _RecordingVsp(fail_wires=1)
    mgr = _nf_manager(tmp_path, vsp)
    mgr._cni_nf_add(_Req("sandboxCCCC", "chip-0"))
    with pytest.raises(RuntimeError):
        mgr._cni_nf_add(_Req("sandboxCCCC", "chip-1", "net2"))
    # wiring claim released; retry succeeds
    r = mgr._cni_nf_add(_Req("sandboxCCCC", "chip-1", "net2"))
    assert r["tpu"]["networkFunction"] is True


def _cni_request(command, container="late1"):
    return CniRequest(
        env={"CNI_COMMAND": command, "CNI_CONTAINERID": container,
             "CNI_NETNS": "/var/run/netns/x", "CNI_IFNAME": "net1",
             "CNI_ARGS": "K8S_POD_NAMESPACE=default;K8S_POD_NAME=p"},
        config={"cniVersion": "0.4.0", "type": "tpu-cni"})


def test_late_add_success_after_timeout_is_rolled_back(short_tmp):
    """A handler finishing after the deadline must not leave committed
    state behind: its effects are undone via the DEL handler."""
    added = []
    deleted = []
    done = threading.Event()

    def slow_add(req):
        time.sleep(0.5)
        added.append(req.sandbox_id)
        return {}

    def on_del(req):
        deleted.append(req.sandbox_id)
        done.set()
        return {}

    server = CniServer(short_tmp + "/cni.sock", add_handler=slow_add,
                       del_handler=on_del, timeout=0.1)
    resp = server._handle(_cni_request("ADD"))
    assert "timed out" in resp.error
    assert done.wait(timeout=5)
    assert added == ["late1"] and deleted == ["late1"]
    server.stop()


def test_timed_out_add_failure_is_not_rolled_back(short_tmp):
    deleted = []

    def slow_fail(req):
        time.sleep(0.3)
        raise RuntimeError("boom")

    server = CniServer(short_tmp + "/cni.sock", add_handler=slow_fail,
                       del_handler=lambda r: deleted.append(r.sandbox_id),
                       timeout=0.1)
    resp = server._handle(_cni_request("ADD"))
    assert "timed out" in resp.error
    time.sleep(0.5)
    assert deleted == []
    server.stop()
