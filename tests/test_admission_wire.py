"""Admission webhooks invoked OVER THE WIRE by the apiserver fixture.

VERDICT r2 item 3: the reference's envtest has the apiserver call the
validating webhook over HTTPS (api/v1/webhook_suite_test.go) and the NRI
mutates pods via apiserver admission (cmd/nri/networkresourcesinjector.go:
136-146). Here MiniApiServer invokes registered Validating-/Mutating-
WebhookConfiguration endpoints on create/update, with the REAL WebhookServer
(TLS serving, AdmissionReview JSON, base64 JSON-Patch, cert hot-reload)
behind them — nothing is called in-process.
"""

import base64
import os
import ssl
import time

import pytest
import requests

from dpu_operator_tpu.api.types import API_VERSION
from dpu_operator_tpu.k8s.real import RealKube
from dpu_operator_tpu.utils import vars as v
from dpu_operator_tpu.webhook.server import WebhookServer

from apiserver_fixture import MiniApiServer, make_self_signed_cert


@pytest.fixture
def apiserver():
    srv = MiniApiServer().start()
    yield srv
    srv.stop()


@pytest.fixture
def real_kube(apiserver, tmp_path):
    path = apiserver.write_kubeconfig(str(tmp_path / "kubeconfig"))
    return RealKube(kubeconfig=path)


@pytest.fixture
def webhook(apiserver, real_kube, tmp_path):
    """Real WebhookServer on TLS; NAD/control-switch lookups go back through
    RealKube, so the webhook's own reads cross the wire too."""
    certdir = str(tmp_path / "serving")
    os.makedirs(certdir)
    cert, key = make_self_signed_cert(certdir)
    srv = WebhookServer(client=real_kube, certfile=cert, keyfile=key,
                        switch_poll_interval=3600)
    srv.start()
    yield srv
    srv.stop()


def _ca_bundle(certfile: str) -> str:
    with open(certfile, "rb") as f:
        return base64.b64encode(f.read()).decode()


def _validating_config(webhook, url_path="/validate", **overrides) -> dict:
    wh = {
        "name": "vtpuoperatorconfig.kb.io",
        "admissionReviewVersions": ["v1"],
        "sideEffects": "None",
        "clientConfig": {
            "url": f"https://127.0.0.1:{webhook.port}{url_path}",
            "caBundle": _ca_bundle(webhook.certfile),
        },
        "rules": [{"apiGroups": ["config.tpu.openshift.io"],
                   "apiVersions": ["v1"],
                   "operations": ["CREATE", "UPDATE"],
                   "resources": ["tpuoperatorconfigs"]}],
    }
    wh.update(overrides)
    return {"apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "ValidatingWebhookConfiguration",
            "metadata": {"name": "tpu-operator-validating-webhook"},
            "webhooks": [wh]}


def _mutating_config(webhook) -> dict:
    return {"apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "MutatingWebhookConfiguration",
            "metadata": {"name": "tpu-network-resources-injector"},
            "webhooks": [{
                "name": "injector.tpu.openshift.io",
                "admissionReviewVersions": ["v1"],
                "sideEffects": "None",
                "clientConfig": {
                    "url": f"https://127.0.0.1:{webhook.port}/mutate",
                    "caBundle": _ca_bundle(webhook.certfile),
                },
                "rules": [{"apiGroups": [""], "apiVersions": ["v1"],
                           "operations": ["CREATE"],
                           "resources": ["pods"]}],
            }]}


def _cfg(mode="tpu", name=None) -> dict:
    return {"apiVersion": API_VERSION, "kind": "TpuOperatorConfig",
            "metadata": {"name": name or v.CONFIG_NAME},
            "spec": {"mode": mode}}


def _nad_pod(name, networks="tpunfcni-conf") -> dict:
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default",
                         "annotations": {
                             "k8s.v1.cni.cncf.io/networks": networks}},
            "spec": {"containers": [{"name": "w", "image": "img"}]}}


# -- validating webhook through the wire -------------------------------------

def test_bad_cr_rejected_through_the_wire(apiserver, real_kube, webhook):
    real_kube.create(_validating_config(webhook))
    with pytest.raises(requests.HTTPError) as exc:
        real_kube.create(_cfg(mode="bogus"))
    assert exc.value.response.status_code == 403
    assert "invalid mode" in exc.value.response.json()["message"]
    # nothing persisted
    assert real_kube.get(API_VERSION, "TpuOperatorConfig",
                         v.CONFIG_NAME) is None


def test_good_cr_admitted_and_bad_update_rejected(apiserver, real_kube,
                                                  webhook):
    real_kube.create(_validating_config(webhook))
    created = real_kube.create(_cfg(mode="tpu"))
    assert created["spec"]["mode"] == "tpu"

    created["spec"]["mode"] = "bogus"
    with pytest.raises(requests.HTTPError) as exc:
        real_kube.update(created)
    assert exc.value.response.status_code == 403
    # the stored object kept the admitted spec
    got = real_kube.get(API_VERSION, "TpuOperatorConfig", v.CONFIG_NAME)
    assert got["spec"]["mode"] == "tpu"


def test_singleton_name_enforced_through_the_wire(apiserver, real_kube,
                                                  webhook):
    real_kube.create(_validating_config(webhook))
    with pytest.raises(requests.HTTPError) as exc:
        real_kube.create(_cfg(name="not-the-singleton"))
    assert exc.value.response.status_code == 403
    assert "singleton" in exc.value.response.json()["message"]


# -- mutating webhook through the wire ---------------------------------------

def test_pod_comes_back_mutated_through_the_wire(apiserver, real_kube,
                                                 webhook):
    real_kube.create({
        "apiVersion": "k8s.cni.cncf.io/v1",
        "kind": "NetworkAttachmentDefinition",
        "metadata": {"name": "tpunfcni-conf", "namespace": "default",
                     "annotations": {
                         "k8s.v1.cni.cncf.io/resourceName":
                             "google.com/tpu"}},
        "spec": {"config": "{}"}})
    real_kube.create(_mutating_config(webhook))

    created = real_kube.create(_nad_pod("worker"))
    res = created["spec"]["containers"][0]["resources"]
    assert res["requests"]["google.com/tpu"] == "1"
    assert res["limits"]["google.com/tpu"] == "1"
    # persisted object carries the injection (what the scheduler sees)
    stored = real_kube.get("v1", "Pod", "worker", namespace="default")
    assert stored["spec"]["containers"][0]["resources"]["requests"][
        "google.com/tpu"] == "1"


def test_pod_without_networks_passes_unmutated(apiserver, real_kube, webhook):
    real_kube.create(_mutating_config(webhook))
    pod = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "plain", "namespace": "default"},
           "spec": {"containers": [{"name": "c", "image": "img"}]}}
    created = real_kube.create(pod)
    assert "resources" not in created["spec"]["containers"][0]


# -- service-ref resolution, failure policy, TLS ------------------------------

def test_service_client_config_resolves_through_endpoints(apiserver,
                                                          real_kube, webhook):
    """The production webhook.yaml registers a Service clientConfig; the
    fixture routes it via the Endpoints object like kube-proxy would."""
    real_kube.create({"apiVersion": "v1", "kind": "Endpoints",
                      "metadata": {"name": "tpu-operator-webhook-service",
                                   "namespace": v.NAMESPACE},
                      "subsets": [{"addresses": [{"ip": "127.0.0.1"}],
                                   "ports": [{"port": webhook.port}]}]})
    cfg = _validating_config(webhook)
    # production shape: Service port 443, real backend port only in the
    # Endpoints (kube-proxy's targetPort resolution) — the fixture must
    # dial the Endpoints port, not 443
    cfg["webhooks"][0]["clientConfig"] = {
        "service": {"name": "tpu-operator-webhook-service",
                    "namespace": v.NAMESPACE, "path": "/validate",
                    "port": 443},
        "caBundle": _ca_bundle(webhook.certfile),
    }
    real_kube.create(cfg)
    with pytest.raises(requests.HTTPError) as exc:
        real_kube.create(_cfg(mode="bogus"))
    assert exc.value.response.status_code == 403
    real_kube.create(_cfg(mode="tpu"))


def test_failure_policy_fail_blocks_and_ignore_admits(apiserver, real_kube,
                                                      webhook):
    # unreachable endpoint: nothing listens on the apiserver's own port + 1
    dead = f"https://127.0.0.1:1/validate"
    cfg = _validating_config(webhook)
    cfg["webhooks"][0]["clientConfig"]["url"] = dead
    cfg["webhooks"][0]["failurePolicy"] = "Fail"
    cfg["webhooks"][0]["timeoutSeconds"] = 1
    real_kube.create(cfg)
    with pytest.raises(requests.HTTPError) as exc:
        real_kube.create(_cfg(mode="tpu"))
    assert exc.value.response.status_code == 500

    cfg = real_kube.get("admissionregistration.k8s.io/v1",
                        "ValidatingWebhookConfiguration",
                        "tpu-operator-validating-webhook")
    cfg["webhooks"][0]["failurePolicy"] = "Ignore"
    real_kube.update(cfg)
    real_kube.create(_cfg(mode="tpu"))  # admitted despite the dead webhook


def test_apply_patch_goes_through_admission(apiserver, real_kube, webhook):
    """The controller's render path persists via server-side apply
    (render/render.py); webhooks must fire on that verb too."""
    real_kube.create(_validating_config(webhook))
    with pytest.raises(requests.HTTPError) as exc:
        real_kube.apply(_cfg(mode="bogus"))
    assert exc.value.response.status_code == 403
    applied = real_kube.apply(_cfg(mode="tpu"))
    assert applied["spec"]["mode"] == "tpu"


def test_delete_runs_admission_chain(apiserver, real_kube, webhook):
    """DELETE runs the chain with oldObject set: a DELETE-matching webhook
    behind a dead endpoint (Fail policy) blocks the delete; pointing it at
    the live server admits it (review_validate allows DELETE)."""
    real_kube.create(_validating_config(webhook))
    real_kube.create(_cfg(mode="tpu"))

    cfg = real_kube.get("admissionregistration.k8s.io/v1",
                        "ValidatingWebhookConfiguration",
                        "tpu-operator-validating-webhook")
    cfg["webhooks"][0]["rules"][0]["operations"] = ["DELETE"]
    cfg["webhooks"][0]["clientConfig"]["url"] = "https://127.0.0.1:1/validate"
    cfg["webhooks"][0]["timeoutSeconds"] = 1
    real_kube.update(cfg)
    with pytest.raises(requests.HTTPError) as exc:
        real_kube.delete(API_VERSION, "TpuOperatorConfig", v.CONFIG_NAME)
    assert exc.value.response.status_code == 500
    assert real_kube.get(API_VERSION, "TpuOperatorConfig",
                         v.CONFIG_NAME) is not None

    cfg = real_kube.get("admissionregistration.k8s.io/v1",
                        "ValidatingWebhookConfiguration",
                        "tpu-operator-validating-webhook")
    cfg["webhooks"][0]["clientConfig"]["url"] = (
        f"https://127.0.0.1:{webhook.port}/validate")
    real_kube.update(cfg)
    real_kube.delete(API_VERSION, "TpuOperatorConfig", v.CONFIG_NAME)
    assert real_kube.get(API_VERSION, "TpuOperatorConfig",
                         v.CONFIG_NAME) is None


@pytest.fixture
def malformed_webhook(tmp_path):
    """TLS endpoint that answers every POST with 200 + '{}' — a webhook
    whose response is not an AdmissionReview."""
    import json as _json
    import ssl as _ssl
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    certdir = str(tmp_path / "malformed")
    os.makedirs(certdir)
    cert, key = make_self_signed_cert(certdir)

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0) or 0))
            body = _json.dumps({}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key)
    httpd.socket = ctx.wrap_socket(httpd.socket, server_side=True)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    yield {"port": httpd.server_address[1], "certfile": cert}
    httpd.shutdown()
    httpd.server_close()


def test_malformed_response_respects_failure_policy(apiserver, real_kube,
                                                    webhook,
                                                    malformed_webhook):
    """A 200 response that is not an AdmissionReview is a webhook FAILURE
    (policy applies), not a denial: Ignore admits, Fail blocks with 500."""
    cfg = _validating_config(webhook)
    cfg["webhooks"][0]["clientConfig"] = {
        "url": f"https://127.0.0.1:{malformed_webhook['port']}/validate",
        "caBundle": _ca_bundle(malformed_webhook["certfile"]),
    }
    cfg["webhooks"][0]["failurePolicy"] = "Ignore"
    real_kube.create(cfg)
    real_kube.create(_cfg(mode="tpu"))  # admitted under Ignore

    cfg = real_kube.get("admissionregistration.k8s.io/v1",
                        "ValidatingWebhookConfiguration",
                        "tpu-operator-validating-webhook")
    cfg["webhooks"][0]["failurePolicy"] = "Fail"
    real_kube.update(cfg)
    created = real_kube.get(API_VERSION, "TpuOperatorConfig", v.CONFIG_NAME)
    created["spec"]["logLevel"] = 2
    with pytest.raises(requests.HTTPError) as exc:
        real_kube.update(created)
    assert exc.value.response.status_code == 500


def test_tls_cert_hot_reload_serves_new_cert(apiserver, real_kube, webhook,
                                             tmp_path):
    """VERDICT r2 weak #7: drive the webhook's ssl context + cert hot-reload
    with actual HTTPS requests — rotate the serving certs on disk, trigger
    the reload poll, and verify new handshakes get the new cert and
    admission still works against an updated caBundle."""
    real_kube.create(_validating_config(webhook))
    with pytest.raises(requests.HTTPError):
        real_kube.create(_cfg(mode="bogus"))  # old cert serves

    before = ssl.get_server_certificate(("127.0.0.1", webhook.port))

    # rotate: write a fresh self-signed pair over the same paths
    newdir = str(tmp_path / "rotated")
    os.makedirs(newdir)
    new_cert, new_key = make_self_signed_cert(newdir)
    for src, dst in ((new_cert, webhook.certfile), (new_key, webhook.keyfile)):
        with open(src, "rb") as f:
            data = f.read()
        with open(dst, "wb") as f:
            f.write(data)
    future = time.time() + 10  # ensure mtime strictly advances
    os.utime(webhook.certfile, (future, future))
    os.utime(webhook.keyfile, (future, future))
    webhook._maybe_reload_certs()

    after = ssl.get_server_certificate(("127.0.0.1", webhook.port))
    assert after != before

    # stale caBundle now fails verification -> Fail policy blocks even a
    # valid CR; refreshing the bundle restores admission
    with pytest.raises(requests.HTTPError) as exc:
        real_kube.create(_cfg(mode="tpu"))
    assert exc.value.response.status_code == 500
    cfg = real_kube.get("admissionregistration.k8s.io/v1",
                        "ValidatingWebhookConfiguration",
                        "tpu-operator-validating-webhook")
    cfg["webhooks"][0]["clientConfig"]["caBundle"] = _ca_bundle(
        webhook.certfile)
    real_kube.update(cfg)
    real_kube.create(_cfg(mode="tpu"))


def test_sfc_validated_through_the_wire(apiserver, real_kube, webhook):
    """SFC admission over genuine HTTPS: the production webhook rule set
    (servicefunctionchains in the resources list, matching
    config/webhook/webhook.yaml) routes SFC creates to /validate, which
    denies a malformed boundary binding and admits a clean chain."""
    cfg = _validating_config(webhook)
    cfg["webhooks"][0]["rules"][0]["resources"] = [
        "tpuoperatorconfigs", "servicefunctionchains"]
    real_kube.create(cfg)

    bad = {"apiVersion": API_VERSION, "kind": "ServiceFunctionChain",
           "metadata": {"name": "bad", "namespace": "default"},
           "spec": {"ingress": "not-an-attachment",
                    "networkFunctions": [{"name": "a", "image": "i"}]}}
    with pytest.raises(requests.HTTPError) as exc:
        real_kube.create(bad)
    assert exc.value.response.status_code == 403
    assert "invalid ingress" in exc.value.response.text

    good = {"apiVersion": API_VERSION, "kind": "ServiceFunctionChain",
            "metadata": {"name": "good", "namespace": "default"},
            "spec": {"ingress": "host0-0", "egress": "host0-1",
                     "networkFunctions": [{"name": "a", "image": "i"}]}}
    created = real_kube.create(good)
    assert created["metadata"]["name"] == "good"
