"""tpuctl CLI tests against the live native agent and a VSP server
(p4rt-ctl analog, cmd/intelvsp/p4runtime-2023.11.0)."""

import json
import os
import subprocess
import sys

import pytest

from dpu_operator_tpu.vsp.mock import MockTpuVsp
from dpu_operator_tpu.vsp.native_dp import AgentProcess
from dpu_operator_tpu.vsp.rpc import VspServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="session")
def agent_binary():
    subprocess.run(["make", "-C", os.path.join(REPO, "native")], check=True,
                   capture_output=True)
    return os.path.join(REPO, "native", "build", "tpu_cp_agent")


def _ctl(*argv):
    out = subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.tpuctl", *argv],
        capture_output=True, text=True, cwd=REPO, check=True)
    return json.loads(out.stdout)


def test_tpuctl_agent_roundtrip(agent_binary, short_tmp):
    proc = AgentProcess(agent_binary, short_tmp + "/a.sock")
    proc.start()
    try:
        sock = ["--agent-socket", proc.socket_path]
        info = _ctl(*sock, "init", "v5e-4")
        assert info["num_chips"] == 4
        chips = _ctl(*sock, "enum")["chips"]
        assert len(chips) == 4
        _ctl(*sock, "attach", "0")
        state = _ctl(*sock, "link-state", "0")
        assert all(p["wired"] for p in state["ports"])
        _ctl(*sock, "wire", "a", "b")
        _ctl(*sock, "unwire", "a", "b")
        _ctl(*sock, "detach", "0")
        assert not any(
            p["wired"] for p in _ctl(*sock, "link-state", "0")["ports"])
    finally:
        proc.stop()


def test_tpuctl_vsp_devices(short_tmp):
    server = VspServer(MockTpuVsp(), socket_path=short_tmp + "/vsp.sock")
    server.start()
    try:
        out = _ctl("--vsp-socket", short_tmp + "/vsp.sock", "devices")
        assert len(out["devices"]) == 4
        att = _ctl("--vsp-socket", short_tmp + "/vsp.sock",
                   "create-attachment", "host0-1", "--chip", "1")
        assert att["name"] == "host0-1"
    finally:
        server.stop()


def test_tpuctl_resize_chips_drains_via_daemon(short_tmp, kube, node_agent):
    """tpuctl resize-chips hits the daemon's AdminService (cross-boundary
    TCP), which drains before shrinking — the production caller for
    TpuSideManager.resize_chips (raw set-num-chips bypasses the drain)."""
    from dpu_operator_tpu.daemon import TpuSideManager
    from dpu_operator_tpu.deviceplugin import FakeKubelet
    from dpu_operator_tpu.platform import TpuDetector
    from dpu_operator_tpu.utils.path_manager import PathManager
    from dpu_operator_tpu.vsp import GrpcPlugin
    from dpu_operator_tpu import tpuctl

    node_agent.register_node("tpu-vm-0", labels={"tpu": "true"})
    kubelet = FakeKubelet(PathManager(short_tmp), node_agent=node_agent,
                          node_name="tpu-vm-0")
    kubelet.start()
    pm = PathManager(short_tmp)
    mock = MockTpuVsp(port=0)
    sock = pm.vendor_plugin_socket()
    pm.ensure_socket_dir(sock)
    vsp_server = VspServer(mock, socket_path=sock)
    vsp_server.start()
    det = TpuDetector().detection_result(tpu_mode=True, identifier="t")
    mgr = TpuSideManager(GrpcPlugin(det, path_manager=pm, init_timeout=5.0),
                         pm, client=kube, node_name="tpu-vm-0")
    mgr.device_plugin.poll_interval = 0.05
    try:
        mgr.start_vsp()
        mgr.setup_devices()
        mgr.listen()
        mgr.serve()
        assert kubelet.wait_for_devices("google.com/tpu", 4)
        kube.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "consumer", "namespace": "default"},
            "spec": {"nodeName": "tpu-vm-0", "containers": [{
                "name": "w", "image": "img",
                "resources": {"requests": {"google.com/tpu": "1"}}}]}})

        args = type("A", (), {
            "cmd": "resize-chips", "count": 2, "node": "tpu-vm-0",
            "daemon_addr": f"127.0.0.1:{mgr.bound_port}",
            "agent_socket": "", "vsp_socket": ""})()
        out = tpuctl.run(args)
        assert out["evicted"] == ["consumer"]
        assert kube.get("v1", "Pod", "consumer", namespace="default") is None
        assert kubelet.wait_for_devices("google.com/tpu", 2)
        node = kube.get("v1", "Node", "tpu-vm-0")
        assert node["spec"]["unschedulable"] is False
    finally:
        mgr.stop()
        vsp_server.stop()
        kubelet.stop()


def test_admin_resize_rejects_bad_count_and_foreign_node(short_tmp, kube,
                                                         node_agent):
    """The unauthenticated admin plane must not drain arbitrary nodes or
    accept a zero/absent count (a missing count would otherwise read as
    shrink-to-0 and evict everything)."""
    import grpc

    from dpu_operator_tpu.daemon import TpuSideManager
    from dpu_operator_tpu.platform import TpuDetector
    from dpu_operator_tpu.utils.path_manager import PathManager
    from dpu_operator_tpu.vsp import GrpcPlugin
    from dpu_operator_tpu.vsp.rpc import VspChannel

    node_agent.register_node("tpu-vm-0", labels={"tpu": "true"})
    pm = PathManager(short_tmp)
    mock = MockTpuVsp(port=0)
    sock = pm.vendor_plugin_socket()
    pm.ensure_socket_dir(sock)
    vsp_server = VspServer(mock, socket_path=sock)
    vsp_server.start()
    det = TpuDetector().detection_result(tpu_mode=True, identifier="t")
    mgr = TpuSideManager(GrpcPlugin(det, path_manager=pm, init_timeout=5.0),
                         pm, client=kube, node_name="tpu-vm-0")
    try:
        mgr.start_vsp()
        mgr.setup_devices()
        mgr.listen()
        ch = VspChannel(f"127.0.0.1:{mgr.bound_port}")
        try:
            with pytest.raises(grpc.RpcError, match="must be >= 1"):
                ch.call("AdminService", "ResizeChips", {"count": 0})
            with pytest.raises(grpc.RpcError, match="must be >= 1"):
                ch.call("AdminService", "ResizeChips", {})
            with pytest.raises(grpc.RpcError, match="local-node only"):
                ch.call("AdminService", "ResizeChips",
                        {"count": 2, "node_name": "some-other-node"})
            # no drain happened: the node was never cordoned
            node = kube.get("v1", "Node", "tpu-vm-0")
            assert not node.get("spec", {}).get("unschedulable")
        finally:
            ch.close()
    finally:
        mgr.stop()
        vsp_server.stop()


def test_tpuctl_repair_chains_via_daemon(short_tmp, kube, node_agent):
    """tpuctl repair-chains triggers the daemon's self-healing pass over
    the admin plane (manual twin of the periodic loop)."""
    from dpu_operator_tpu.daemon import TpuSideManager
    from dpu_operator_tpu.platform import TpuDetector
    from dpu_operator_tpu.utils.path_manager import PathManager
    from dpu_operator_tpu.vsp import GrpcPlugin
    from dpu_operator_tpu import tpuctl

    pm = PathManager(short_tmp)
    mock = MockTpuVsp(port=0)
    sock = pm.vendor_plugin_socket()
    pm.ensure_socket_dir(sock)
    vsp_server = VspServer(mock, socket_path=sock)
    vsp_server.start()
    det = TpuDetector().detection_result(tpu_mode=True, identifier="t")
    mgr = TpuSideManager(GrpcPlugin(det, path_manager=pm, init_timeout=5.0),
                         pm, client=kube)
    try:
        mgr.start_vsp()
        mgr.setup_devices()
        mgr.listen()
        # plant a broken hop + a prober that reports its port down
        mgr._chain_store[("default", "s")] = {
            0: {"in": "a-in", "out": "a-out", "sandbox": "sA",
                "ports": []},
            1: {"in": "b-in", "out": "b-out", "sandbox": "sB",
                "ports": []}}
        mgr._chain_hops[("default", "s", 0)] = ("ici-1-x+", "b-in")
        mgr.link_prober = lambda chip: [
            {"port": "x+", "up": False, "wired": True}]
        args = type("A", (), {
            "cmd": "repair-chains",
            "daemon_addr": f"127.0.0.1:{mgr.bound_port}",
            "agent_socket": "", "vsp_socket": ""})()
        out = tpuctl.run(args)
        assert out["repaired"][0]["old"] == ["ici-1-x+", "b-in"]
        assert out["repaired"][0]["new"] == ["a-out", "b-in"]
        assert mgr._chain_hops[("default", "s", 0)] == ("a-out", "b-in")
    finally:
        mgr.stop()
        vsp_server.stop()
