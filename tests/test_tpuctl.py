"""tpuctl CLI tests against the live native agent and a VSP server
(p4rt-ctl analog, cmd/intelvsp/p4runtime-2023.11.0)."""

import json
import os
import subprocess
import sys

import pytest

from dpu_operator_tpu.vsp.mock import MockTpuVsp
from dpu_operator_tpu.vsp.native_dp import AgentProcess
from dpu_operator_tpu.vsp.rpc import VspServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="session")
def agent_binary():
    subprocess.run(["make", "-C", os.path.join(REPO, "native")], check=True,
                   capture_output=True)
    return os.path.join(REPO, "native", "build", "tpu_cp_agent")


def _ctl(*argv):
    out = subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.tpuctl", *argv],
        capture_output=True, text=True, cwd=REPO, check=True)
    return json.loads(out.stdout)


def test_tpuctl_agent_roundtrip(agent_binary, short_tmp):
    proc = AgentProcess(agent_binary, short_tmp + "/a.sock")
    proc.start()
    try:
        sock = ["--agent-socket", proc.socket_path]
        info = _ctl(*sock, "init", "v5e-4")
        assert info["num_chips"] == 4
        chips = _ctl(*sock, "enum")["chips"]
        assert len(chips) == 4
        _ctl(*sock, "attach", "0")
        state = _ctl(*sock, "link-state", "0")
        assert all(p["wired"] for p in state["ports"])
        _ctl(*sock, "wire", "a", "b")
        _ctl(*sock, "unwire", "a", "b")
        _ctl(*sock, "detach", "0")
        assert not any(
            p["wired"] for p in _ctl(*sock, "link-state", "0")["ports"])
    finally:
        proc.stop()


def test_tpuctl_vsp_devices(short_tmp):
    server = VspServer(MockTpuVsp(), socket_path=short_tmp + "/vsp.sock")
    server.start()
    try:
        out = _ctl("--vsp-socket", short_tmp + "/vsp.sock", "devices")
        assert len(out["devices"]) == 4
        att = _ctl("--vsp-socket", short_tmp + "/vsp.sock",
                   "create-attachment", "host0-1", "--chip", "1")
        assert att["name"] == "host0-1"
    finally:
        server.stop()
