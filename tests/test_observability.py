"""Metrics, health endpoints, and drain facade tests (SURVEY.md §5)."""

import urllib.request

import pytest

from dpu_operator_tpu.utils.drain import Drainer
from dpu_operator_tpu.utils.metrics import (Counter, Gauge, Histogram,
                                            MetricsServer, Registry)


def test_counter_labels_and_render():
    reg = Registry()
    c = reg.counter("test_total", "help text")
    c.inc(controller="a")
    c.inc(controller="a")
    c.inc(controller="b")
    text = reg.render()
    assert 'test_total{controller="a"} 2' in text
    assert 'test_total{controller="b"} 1' in text
    assert "# TYPE test_total counter" in text


def test_gauge_set():
    reg = Registry()
    g = reg.gauge("devs", "h")
    g.set(4, resource="google.com/tpu")
    g.set(2, resource="google.com/tpu")
    assert 'devs{resource="google.com/tpu"} 2' in reg.render()


def test_histogram_buckets():
    reg = Registry()
    h = reg.histogram("lat", "h", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.render()
    assert 'lat_bucket{le="0.1"} 1' in text
    assert 'lat_bucket{le="1"} 2' in text
    assert 'lat_bucket{le="+Inf"} 3' in text
    assert "lat_count 3" in text


def test_metrics_server_endpoints():
    reg = Registry()
    reg.counter("up_total", "h").inc()
    ready = {"ok": False}
    server = MetricsServer(host="127.0.0.1", registry=reg,
                           ready_check=lambda: ready["ok"])
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        body = urllib.request.urlopen(base + "/metrics", timeout=5).read()
        assert b"up_total 1" in body
        assert urllib.request.urlopen(base + "/healthz",
                                      timeout=5).status == 200
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base + "/readyz", timeout=5)
        assert exc.value.code == 503
        ready["ok"] = True
        assert urllib.request.urlopen(base + "/readyz",
                                      timeout=5).status == 200
    finally:
        server.stop()


def test_reconcile_metrics_emitted(kube):
    from dpu_operator_tpu.k8s.manager import Manager
    from dpu_operator_tpu.utils.metrics import RECONCILE_TOTAL

    class Rec:
        watches = ("v1", "ConfigMap")

        def reconcile(self, client, req):
            return None

    before = RECONCILE_TOTAL.value(controller="Rec")
    mgr = Manager(kube)
    mgr.add_reconciler(Rec())
    mgr.start()
    kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                 "metadata": {"name": "x", "namespace": "default"}})
    assert mgr.wait_idle(5)
    mgr.stop()
    assert RECONCILE_TOTAL.value(controller="Rec") == before + 1


# -- drain --------------------------------------------------------------------

def _pod(name, node, tpu=True):
    res = ({"requests": {"google.com/tpu": "2"}} if tpu else {})
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"nodeName": node,
                     "containers": [{"name": "c", "resources": res}]}}


def test_drain_evicts_only_tpu_consumers(kube, node_agent):
    node_agent.register_node("n1", allocatable={"google.com/tpu": "4"})
    kube.create(_pod("tpu-pod", "n1", tpu=True))
    kube.create(_pod("sys-pod", "n1", tpu=False))
    d = Drainer(kube)
    evicted = d.drain("n1")
    assert evicted == ["tpu-pod"]
    assert kube.get("v1", "Pod", "sys-pod", namespace="default") is not None
    node = kube.get("v1", "Node", "n1")
    assert node["spec"]["unschedulable"] is True
    d.uncordon("n1")
    assert kube.get("v1", "Node", "n1")["spec"]["unschedulable"] is False


def test_drain_missing_node_raises(kube):
    with pytest.raises(KeyError):
        Drainer(kube).cordon("ghost")
