"""Metrics, health endpoints, flight recorder, and drain facade tests
(SURVEY.md §5)."""

import json
import urllib.request

import pytest

from dpu_operator_tpu.utils import flight, tracing
from dpu_operator_tpu.utils.drain import Drainer
from dpu_operator_tpu.utils.metrics import (Counter, Gauge, Histogram,
                                            MetricsServer, Registry)


def test_counter_labels_and_render():
    reg = Registry()
    c = reg.counter("test_total", "help text")
    c.inc(controller="a")
    c.inc(controller="a")
    c.inc(controller="b")
    text = reg.render()
    assert 'test_total{controller="a"} 2' in text
    assert 'test_total{controller="b"} 1' in text
    assert "# TYPE test_total counter" in text


def test_gauge_set():
    reg = Registry()
    g = reg.gauge("devs", "h")
    g.set(4, resource="google.com/tpu")
    g.set(2, resource="google.com/tpu")
    assert 'devs{resource="google.com/tpu"} 2' in reg.render()


def test_histogram_buckets():
    reg = Registry()
    h = reg.histogram("lat", "h", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.render()
    assert 'lat_bucket{le="0.1"} 1' in text
    assert 'lat_bucket{le="1"} 2' in text
    assert 'lat_bucket{le="+Inf"} 3' in text
    assert "lat_count 3" in text


def test_label_values_escaped_per_exposition_format():
    """A `"`, `\\` or newline in a label value must not terminate the
    quoted value early and corrupt the whole scrape."""
    reg = Registry()
    c = reg.counter("esc_total", "h")
    c.inc(site='say "hi"\\path\nnewline')
    text = reg.render()
    assert r'esc_total{site="say \"hi\"\\path\nnewline"} 1' in text
    assert "\nnewline" not in text  # no raw newline inside a sample line


def test_histogram_sum_consistent_under_lock():
    h = Histogram("lat", "h", buckets=(1.0,))
    h.observe(0.25)
    h.observe(0.5)
    assert h.sum == 0.75
    assert h.count == 2


def test_exemplars_render_only_on_openmetrics():
    reg = Registry()
    h = reg.histogram("lat_seconds", "h", buckets=(0.1, 1.0))
    h.observe(0.05, exemplar={"trace_id": "a" * 32})
    h.observe(0.5)  # no exemplar on this bucket
    classic = reg.render()
    assert "trace_id" not in classic  # 0.0.4 parsers reject exemplars
    om = reg.render(openmetrics=True)
    assert f'lat_seconds_bucket{{le="0.1"}} 1 # {{trace_id="{"a" * 32}"}} '\
        "0.05" in om
    assert om.rstrip().endswith("# EOF")


def test_openmetrics_counter_family_drops_total_suffix():
    """OM names counter FAMILIES without _total (samples keep it);
    `# TYPE x_total counter` makes real OM parsers reject the scrape."""
    reg = Registry()
    reg.counter("tpu_thing_total", "h").inc(site="a")
    om = reg.render(openmetrics=True)
    assert "# TYPE tpu_thing counter" in om
    assert "# TYPE tpu_thing_total" not in om
    assert 'tpu_thing_total{site="a"} 1' in om  # sample keeps the suffix
    classic = reg.render()
    assert "# TYPE tpu_thing_total counter" in classic  # 0.0.4 unchanged


def test_histogram_vec_exemplar_and_timer_exemplar():
    from dpu_operator_tpu.utils.metrics import HistogramVec
    vec = HistogramVec("verb_seconds", "h", label="verb", buckets=(1.0,))
    vec.observe("get", 0.1, exemplar={"trace_id": "t1"})
    om = "\n".join(vec._render(openmetrics=True))
    assert 'trace_id="t1"' in om
    h = Histogram("timed_seconds", "h", buckets=(10.0,))
    with h.time(exemplar=lambda: {"trace_id": "t2"}):
        pass
    assert 'trace_id="t2"' in "\n".join(h._render(openmetrics=True))


def test_flight_recorder_ring_bounds_and_filtering():
    rec = flight.FlightRecorder(capacity=4)
    for i in range(10):
        rec.record("span", f"s{i}", trace_id=f"t{i % 2}")
    snap = rec.snapshot()
    assert snap["recorded"] == 10
    assert [e["name"] for e in snap["events"]] == ["s6", "s7", "s8", "s9"]
    assert [e["name"] for e in rec.events(trace_id="t1")] == ["s7", "s9"]
    rec.clear()
    assert rec.snapshot()["events"] == []


def test_flight_endpoint_serves_ring_and_joins_traces():
    flight.RECORDER.clear()
    tracing.reset_for_tests()
    with tracing.span("incident.request") as ctx:
        flight.record("swallowed_error", "x_total",
                      attributes={"site": "test"})
    server = MetricsServer(host="127.0.0.1")
    server.start()
    try:
        snap = flight.fetch(f"127.0.0.1:{server.port}")
    finally:
        server.stop()
    kinds = {e["kind"] for e in snap["events"]}
    assert {"span", "swallowed_error"} <= kinds
    # the swallowed error carries the trace it happened under, and the
    # span ring has the request itself — the join a post-incident
    # snapshot needs
    swallowed = [e for e in snap["events"]
                 if e["kind"] == "swallowed_error"][-1]
    assert swallowed["trace_id"] == ctx.trace_id
    assert any(e["kind"] == "span" and e["name"] == "incident.request"
               and e["trace_id"] == ctx.trace_id for e in snap["events"])


def test_flight_endpoint_shares_metrics_auth():
    server = MetricsServer(host="127.0.0.1", auth=lambda token: False)
    server.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/flight", timeout=5)
        assert exc.value.code == 401
    finally:
        server.stop()


def test_openmetrics_content_negotiation():
    reg = Registry()
    reg.histogram("neg_seconds", "h", buckets=(1.0,)).observe(
        0.1, exemplar={"trace_id": "neg"})
    server = MetricsServer(host="127.0.0.1", registry=reg)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}/metrics"
        plain = urllib.request.urlopen(base, timeout=5)
        assert "0.0.4" in plain.headers["Content-Type"]
        assert b"trace_id" not in plain.read()
        req = urllib.request.Request(base, headers={
            "Accept": "application/openmetrics-text"})
        om = urllib.request.urlopen(req, timeout=5)
        assert "openmetrics-text" in om.headers["Content-Type"]
        assert b'trace_id="neg"' in om.read()
    finally:
        server.stop()


def test_metrics_server_endpoints():
    reg = Registry()
    reg.counter("up_total", "h").inc()
    ready = {"ok": False}
    server = MetricsServer(host="127.0.0.1", registry=reg,
                           ready_check=lambda: ready["ok"])
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        body = urllib.request.urlopen(base + "/metrics", timeout=5).read()
        assert b"up_total 1" in body
        assert urllib.request.urlopen(base + "/healthz",
                                      timeout=5).status == 200
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(base + "/readyz", timeout=5)
        assert exc.value.code == 503
        ready["ok"] = True
        assert urllib.request.urlopen(base + "/readyz",
                                      timeout=5).status == 200
    finally:
        server.stop()


def test_reconcile_metrics_emitted(kube):
    from dpu_operator_tpu.k8s.manager import Manager
    from dpu_operator_tpu.utils.metrics import RECONCILE_TOTAL

    class Rec:
        watches = ("v1", "ConfigMap")

        def reconcile(self, client, req):
            return None

    before = RECONCILE_TOTAL.value(controller="Rec")
    mgr = Manager(kube)
    mgr.add_reconciler(Rec())
    mgr.start()
    kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                 "metadata": {"name": "x", "namespace": "default"}})
    assert mgr.wait_idle(5)
    mgr.stop()
    assert RECONCILE_TOTAL.value(controller="Rec") == before + 1


# -- drain --------------------------------------------------------------------

def _pod(name, node, tpu=True):
    res = ({"requests": {"google.com/tpu": "2"}} if tpu else {})
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"nodeName": node,
                     "containers": [{"name": "c", "resources": res}]}}


def test_drain_evicts_only_tpu_consumers(kube, node_agent):
    node_agent.register_node("n1", allocatable={"google.com/tpu": "4"})
    kube.create(_pod("tpu-pod", "n1", tpu=True))
    kube.create(_pod("sys-pod", "n1", tpu=False))
    d = Drainer(kube)
    evicted = d.drain("n1")
    assert evicted == ["tpu-pod"]
    assert kube.get("v1", "Pod", "sys-pod", namespace="default") is not None
    node = kube.get("v1", "Node", "n1")
    assert node["spec"]["unschedulable"] is True
    d.uncordon("n1")
    assert kube.get("v1", "Node", "n1")["spec"]["unschedulable"] is False


def test_drain_missing_node_raises(kube):
    with pytest.raises(KeyError):
        Drainer(kube).cordon("ghost")


# -- render vs. observe storm (health-engine satellite) -----------------------

#: one exposition line: comment, blank, or `name{labels} value [exemplar]`
import re  # noqa: E402

_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'                  # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'  # first label
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r' -?[0-9.e+\-]+(?:inf|nan)?'                 # value
    r'( # \{[^}]*\} -?[0-9.e+\-]+)?$')            # optional exemplar


def _assert_grammar_valid(text, openmetrics):
    lines = text.splitlines()
    assert lines, "render produced nothing"
    if openmetrics:
        assert lines[-1] == "# EOF"
        lines = lines[:-1]
    for line in lines:
        if not line or line.startswith("# HELP") \
                or line.startswith("# TYPE"):
            continue
        assert _SAMPLE_RE.match(line), f"malformed sample line: {line!r}"


def test_concurrent_render_vs_observe_storm_stays_grammar_valid():
    """Seeded writer threads hammer Histogram.observe/Gauge.set while
    the main thread renders both exposition formats: no exception, and
    every intermediate render parses (a torn render corrupts the whole
    scrape for real collectors)."""
    import random
    import threading

    registry = Registry()
    hist = registry.histogram("tpu_storm_seconds", "storm latencies")
    gauge = registry.gauge("tpu_storm_level", "storm gauge")
    counter = registry.counter("tpu_storm_total", "storm counter")
    vec = registry.histogram_vec("tpu_storm_by_verb_seconds",
                                 "per-verb storm", label="verb")
    start = threading.Barrier(5)
    errors = []

    def writer(seed):
        rng = random.Random(seed)
        try:
            start.wait(timeout=10)
            for i in range(400):
                v = rng.random() * 10
                hist.observe(v, exemplar={"trace_id": f"{seed:032x}"}
                             if rng.random() < 0.3 else None)
                gauge.set(v, shard=str(seed))
                counter.inc(result="ok" if rng.random() < 0.9
                            else 'err"\\\n')  # hostile label value
                vec.observe(("get", "list")[i % 2], v)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(seed,))
               for seed in range(4)]
    for t in threads:
        t.start()
    start.wait(timeout=10)
    renders = []
    for i in range(50):
        om = i % 2 == 1
        renders.append((registry.render(openmetrics=om), om))
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    assert all(not t.is_alive() for t in threads)
    # final render plus every mid-storm render is grammar-valid
    renders.append((registry.render(openmetrics=False), False))
    renders.append((registry.render(openmetrics=True), True))
    for text, om in renders:
        _assert_grammar_valid(text, om)
    # post-join totals are exact: nothing torn or lost
    assert hist.count == 4 * 400
    assert counter.total() == 4 * 400


def test_flight_ring_counts_evictions_per_kind():
    """The ring used to overwrite silently; now every eviction is
    accounted per kind — in the snapshot AND in
    tpu_flight_dropped_total, so a storm outrunning the ring is
    visible."""
    from dpu_operator_tpu.utils import metrics

    rec = flight.FlightRecorder(capacity=4)
    for i in range(4):
        rec.record("span", f"keep{i}")
    assert rec.snapshot()["dropped"] == {}
    span_before = metrics.FLIGHT_DROPPED.value(kind="span")
    serve_before = metrics.FLIGHT_DROPPED.value(kind="serve")
    for i in range(3):
        rec.record("serve", f"storm{i}")  # evicts three span entries
    rec.record("breaker", "flip")         # evicts the last span
    rec.record("watch", "relist")         # evicts a serve entry
    snap = rec.snapshot()
    assert snap["dropped"] == {"span": 4, "serve": 1}
    assert snap["recorded"] == 9 and len(snap["events"]) == 4
    assert metrics.FLIGHT_DROPPED.value(kind="span") == span_before + 4
    assert metrics.FLIGHT_DROPPED.value(kind="serve") \
        == serve_before + 1
    rec.clear()
    assert rec.snapshot()["dropped"] == {}


def test_tpuctl_flight_surfaces_dropped_counts():
    from dpu_operator_tpu import tpuctl

    flight.RECORDER.clear()
    overflow = flight.RECORDER.capacity + 5
    for i in range(overflow):
        flight.record("span", f"storm{i}")
    server = MetricsServer(host="127.0.0.1")
    server.start()
    try:
        args = type("A", (), {"cmd": "flight", "trace": "", "kind": "",
                              "token": "",
                              "metrics_addr": f"127.0.0.1:{server.port}",
                              "agent_socket": "", "vsp_socket": "",
                              "daemon_addr": ""})()
        out = tpuctl.run(args)
    finally:
        server.stop()
        flight.RECORDER.clear()
    assert out["dropped"].get("span") == 5
    assert out["recorded"] == overflow


def test_debug_index_lists_registered_handlers():
    """GET /debug enumerates the debug surface — built-ins plus every
    registered handler — behind the same token filter."""
    server = MetricsServer(
        host="127.0.0.1", health_check=lambda: {"healthy": True},
        debug_handlers={"/debug/serve": lambda: {},
                        "/debug/serve/ledger": lambda: {}})
    server.start()
    try:
        body = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/debug", timeout=5).read())
    finally:
        server.stop()
    assert body["debugHandlers"] == [
        "/debug/flight", "/debug/health", "/debug/serve",
        "/debug/serve/ledger"]

    # no health snapshot wired -> /debug/health is not advertised
    server = MetricsServer(host="127.0.0.1")
    server.start()
    try:
        body = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/debug", timeout=5).read())
    finally:
        server.stop()
    assert body["debugHandlers"] == ["/debug/flight"]


def test_debug_index_shares_metrics_auth():
    server = MetricsServer(host="127.0.0.1", auth=lambda token: False)
    server.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug", timeout=5)
        assert exc.value.code == 401
    finally:
        server.stop()
