"""CNI subsystem tests: shim → unix-HTTP server → handlers, cache, allocator.

Reference analog: cniserver_test.go (request conversion), cnihelper_test.go
(config parse), hostsidemanager_test.go:235-263 (end-to-end ADD through real
shim + real server + stub backend).
"""

import json
import os

import pytest

from dpu_operator_tpu.cni import (
    ChipAllocator,
    CniRequest,
    CniServer,
    CniShim,
    NetConf,
    NetConfCache,
)
from dpu_operator_tpu.cni.types import PodRequest


def _env(command="ADD", container="abc123", netns="/var/run/netns/x",
         ifname="net1", pod="mypod", ns="default"):
    return {
        "CNI_COMMAND": command,
        "CNI_CONTAINERID": container,
        "CNI_NETNS": netns,
        "CNI_IFNAME": ifname,
        "CNI_ARGS": f"K8S_POD_NAMESPACE={ns};K8S_POD_NAME={pod}",
    }


def _conf(mode="chip", device="chip-1"):
    return {"cniVersion": "0.4.0", "name": "tpunfcni-conf",
            "type": "tpu-cni", "mode": mode, "deviceID": device,
            "resourceName": "google.com/tpu"}


def test_shim_trace_context_rejects_sloppy_hex(monkeypatch):
    """int(x,16) would accept '+'/'_'-padded fields; a non-strict adopt
    would orphan the shim span from the server's strictly-parsed
    trace. Only exact lowercase-hex TRACEPARENT values are joined."""
    from dpu_operator_tpu.cni.shim import _trace_context
    good = "00-" + "a" * 32 + "-" + "b" * 16 + "-01"
    monkeypatch.setenv("TRACEPARENT", good)
    trace_id, _, parent_id = _trace_context()
    assert (trace_id, parent_id) == ("a" * 32, "b" * 16)
    for bad in ("00-+" + "a" * 31 + "-" + "b" * 16 + "-01",
                "00-" + "a" * 31 + "_-" + "b" * 16 + "-01",
                "zz-" + "a" * 32 + "-" + "b" * 16 + "-01",
                "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",
                "00-" + "0" * 32 + "-" + "b" * 16 + "-01"):
        monkeypatch.setenv("TRACEPARENT", bad)
        trace_id, _, parent_id = _trace_context()
        assert parent_id is None and trace_id != bad.split("-")[1]


def test_pod_request_parsing():
    req = CniRequest(env=_env(), config=_conf())
    pr = PodRequest.from_cni_request(req)
    assert pr.command == "ADD"
    assert pr.pod_name == "mypod"
    assert pr.pod_namespace == "default"
    assert pr.device_id == "chip-1"
    assert pr.netconf.mode == "chip"


def test_pod_request_rejects_bad_command():
    req = CniRequest(env=_env(command="FROB"), config=_conf())
    with pytest.raises(ValueError, match="CNI_COMMAND"):
        PodRequest.from_cni_request(req)


def test_netconf_roundtrip():
    nc = NetConf.from_dict(_conf())
    assert NetConf.from_dict(nc.to_dict()).device_id == "chip-1"


def test_server_shim_end_to_end(short_tmp):
    """Full hop: shim client → unix socket HTTP → injected handler."""
    seen = {}

    def add(pr):
        seen["add"] = pr
        return {"cniVersion": "0.4.0", "tpu": {"chip": 1}}

    def delete(pr):
        seen["del"] = pr
        return {}

    sock = os.path.join(short_tmp, "cni.sock")
    server = CniServer(sock, add_handler=add, del_handler=delete)
    server.start()
    try:
        shim = CniShim(sock)
        resp = shim.invoke(_env(), json.dumps(_conf()))
        assert resp.error == ""
        assert resp.result["tpu"]["chip"] == 1
        assert seen["add"].pod_name == "mypod"

        resp = shim.invoke(_env(command="DEL"), json.dumps(_conf()))
        assert resp.error == ""
        assert seen["del"].command == "DEL"

        # CHECK is a client-side no-op
        resp = shim.invoke(_env(command="CHECK"), json.dumps(_conf()))
        assert resp.result == {}
    finally:
        server.stop()


def test_server_handler_error_surfaces(short_tmp):
    def add(pr):
        raise RuntimeError("chip on fire")

    sock = os.path.join(short_tmp, "cni2.sock")
    server = CniServer(sock, add_handler=add)
    server.start()
    try:
        resp = CniShim(sock).invoke(_env(), json.dumps(_conf()))
        assert "chip on fire" in resp.error
    finally:
        server.stop()


def test_server_socket_is_root_only(short_tmp):
    sock = os.path.join(short_tmp, "cni3.sock")
    server = CniServer(sock, add_handler=lambda pr: {})
    server.start()
    try:
        assert oct(os.stat(sock).st_mode & 0o777) == "0o600"
    finally:
        server.stop()


def test_netconf_cache_roundtrip(tmp_path):
    cache = NetConfCache(str(tmp_path / "cache"))
    cache.save("sandbox1", "net1", {"chip": 2})
    assert cache.load("sandbox1", "net1") == {"chip": 2}
    cache.delete("sandbox1", "net1")
    assert cache.load("sandbox1", "net1") is None
    # defensive: loading never-saved state is None, not an error
    assert cache.load("ghost", "net9") is None


def test_chip_allocator(tmp_path):
    alloc = ChipAllocator(str(tmp_path / "alloc"))
    assert alloc.allocate("chip-0", "sandboxA")
    assert alloc.allocate("chip-0", "sandboxA")  # idempotent re-claim
    assert not alloc.allocate("chip-0", "sandboxB")  # held by A
    assert alloc.owner("chip-0") == "sandboxA"
    assert not alloc.release("chip-0", "sandboxB")  # wrong owner
    assert alloc.release("chip-0", "sandboxA")
    assert alloc.allocate("chip-0", "sandboxB")  # free again
