"""Tracing span + trace-context propagation + per-invocation CNI logging
tests (SURVEY.md §5 gaps the TPU build fills)."""

import json
import logging
import os
import threading

import pytest

from dpu_operator_tpu.utils import flight, tracing


@pytest.fixture(autouse=True)
def _reset():
    tracing.reset_for_tests()
    yield
    tracing.reset_for_tests()
    os.environ.pop("TPU_OPERATOR_TRACE", None)


def test_span_without_sink_still_yields_context(tmp_path):
    """No TPU_OPERATOR_TRACE: no sink file, but the context is live (it
    must propagate across seams and feed the flight recorder even with
    no trace sink configured)."""
    flight.RECORDER.clear()
    with tracing.span("x") as ctx:
        assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
        assert tracing.current() == ctx
    assert tracing.current() is None
    assert [e["name"] for e in flight.RECORDER.events(kind="span")] == ["x"]


def test_span_records_nesting_and_errors(tmp_path):
    trace_file = str(tmp_path / "trace.jsonl")
    os.environ["TPU_OPERATOR_TRACE"] = trace_file
    with tracing.span("outer", kind="test"):
        with tracing.span("inner"):
            pass
    with pytest.raises(ValueError):
        with tracing.span("failing"):
            raise ValueError("boom")
    records = [json.loads(l) for l in open(trace_file)]
    by_name = {r["name"]: r for r in records}
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["inner"]["trace_id"] == by_name["outer"]["trace_id"]
    assert by_name["outer"]["parent_id"] is None
    assert by_name["failing"]["trace_id"] != by_name["outer"]["trace_id"]
    assert by_name["outer"]["attributes"] == {"kind": "test"}
    assert "ValueError: boom" in by_name["failing"]["error"]
    assert all(r["duration_s"] >= 0 for r in records)


def test_traceparent_inject_extract_round_trip():
    assert tracing.inject_traceparent() is None  # nothing to propagate
    with tracing.span("client") as ctx:
        header = tracing.inject_traceparent()
        assert header == f"00-{ctx.trace_id}-{ctx.span_id}-01"
        restored = tracing.extract_traceparent(header)
        assert restored == ctx
    # server-side adoption: a child span under the restored context
    # stays on the client's trace
    with tracing.context_scope(restored):
        with tracing.span("server") as server_ctx:
            assert server_ctx.trace_id == ctx.trace_id
            assert server_ctx.span_id != ctx.span_id


@pytest.mark.parametrize("hostile", [
    None,                                             # missing header
    12345,                                            # non-string
    "",                                               # empty
    "garbage",                                        # not 4 fields
    "00-" + "a" * 32 + "-" + "b" * 16,                # missing flags
    "00-" + "A" * 32 + "-" + "b" * 16 + "-01",        # uppercase hex
    "00-" + "g" * 32 + "-" + "b" * 16 + "-01",        # non-hex
    "00-" + "a" * 31 + "-" + "b" * 16 + "-01",        # short trace id
    "00-" + "a" * 32 + "-" + "b" * 15 + "-01",        # short span id
    "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",        # forbidden version
    "00-" + "0" * 32 + "-" + "b" * 16 + "-01",        # all-zero trace
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",        # all-zero span
    "00-" + "a" * 32 + "-" + "b" * 16 + "-01\r\nX: y",  # header splitting
    "00-" + "a" * 32 + "-" + "b" * 16 + "-01" + "x" * 40,  # overlong
])
def test_extract_traceparent_rejects_hostile_values(hostile):
    assert tracing.extract_traceparent(hostile) is None


def test_wrap_context_carries_trace_across_thread_pool():
    from concurrent.futures import ThreadPoolExecutor

    seen = {}

    def work(key):
        with tracing.span("pooled") as ctx:
            seen[key] = ctx.trace_id

    with ThreadPoolExecutor(max_workers=1) as pool:
        with tracing.span("request") as ctx:
            pool.submit(tracing.wrap_context(work), "wrapped").result(5)
            # unwrapped: the pool thread has no ambient context, so the
            # span roots a fresh trace instead of joining the request's
            pool.submit(work, "bare").result(5)
    assert seen["wrapped"] == ctx.trace_id
    assert seen["bare"] != ctx.trace_id


def test_setup_race_opens_sink_exactly_once(tmp_path, monkeypatch):
    """Two threads racing the first span must not double-open the sink
    (the loser's handle used to leak, splitting buffered records)."""
    import builtins

    trace_file = str(tmp_path / "race.jsonl")
    os.environ["TPU_OPERATOR_TRACE"] = trace_file
    opens = []
    real_open = builtins.open

    def counting_open(path, *a, **kw):
        if path == trace_file:
            opens.append(path)
        return real_open(path, *a, **kw)

    monkeypatch.setattr(builtins, "open", counting_open)
    barrier = threading.Barrier(8)

    def first_span():
        barrier.wait(5)
        with tracing.span("racer"):
            pass

    threads = [threading.Thread(target=first_span) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(5)
    assert opens == [trace_file]
    records = [json.loads(l) for l in real_open(trace_file)]
    # count only our racers: a background span (a lease renewer, a
    # health-engine tick) landing in the sink window must not flake this
    assert len([r for r in records if r["name"] == "racer"]) == 8


def test_reconcile_emits_span(kube, tmp_path):
    trace_file = str(tmp_path / "trace.jsonl")
    os.environ["TPU_OPERATOR_TRACE"] = trace_file
    from dpu_operator_tpu.k8s.manager import Manager

    class Rec:
        watches = ("v1", "Secret")

        def reconcile(self, client, req):
            return None

    mgr = Manager(kube)
    mgr.add_reconciler(Rec())
    mgr.start()
    kube.create({"apiVersion": "v1", "kind": "Secret",
                 "metadata": {"name": "s", "namespace": "default"}})
    assert mgr.wait_idle(5)
    mgr.stop()
    records = [json.loads(l) for l in open(trace_file)]
    assert any(r["name"] == "reconcile"
               and r["attributes"]["controller"] == "Rec" for r in records)


def test_cni_request_logger_routes_to_netconf_file(tmp_path):
    from dpu_operator_tpu.cni.logging import request_logger
    from dpu_operator_tpu.cni.types import NetConf

    class Req:
        sandbox_id = "sandbox123456"
        ifname = "net1"
        netns = "/var/run/netns/x"
        netconf = NetConf(log_level="debug",
                          log_file=str(tmp_path / "cni.log"))

    logger = request_logger(Req())
    logger.debug("hello from %s", "test")
    for h in logging.getLogger(
            "cni.sandbox12345.net1").handlers:
        h.flush()
    content = open(tmp_path / "cni.log").read()
    assert "hello from test" in content
