"""Tracing span + per-invocation CNI logging tests (SURVEY.md §5 gaps the
TPU build fills)."""

import json
import logging
import os

import pytest

from dpu_operator_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _reset():
    tracing.reset_for_tests()
    yield
    tracing.reset_for_tests()
    os.environ.pop("TPU_OPERATOR_TRACE", None)


def test_span_noop_when_disabled():
    with tracing.span("x") as sid:
        assert sid is None


def test_span_records_nesting_and_errors(tmp_path):
    trace_file = str(tmp_path / "trace.jsonl")
    os.environ["TPU_OPERATOR_TRACE"] = trace_file
    with tracing.span("outer", kind="test"):
        with tracing.span("inner"):
            pass
    with pytest.raises(ValueError):
        with tracing.span("failing"):
            raise ValueError("boom")
    records = [json.loads(l) for l in open(trace_file)]
    by_name = {r["name"]: r for r in records}
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["outer"]["parent_id"] is None
    assert by_name["outer"]["attributes"] == {"kind": "test"}
    assert "ValueError: boom" in by_name["failing"]["error"]
    assert all(r["duration_s"] >= 0 for r in records)


def test_reconcile_emits_span(kube, tmp_path):
    trace_file = str(tmp_path / "trace.jsonl")
    os.environ["TPU_OPERATOR_TRACE"] = trace_file
    from dpu_operator_tpu.k8s.manager import Manager

    class Rec:
        watches = ("v1", "Secret")

        def reconcile(self, client, req):
            return None

    mgr = Manager(kube)
    mgr.add_reconciler(Rec())
    mgr.start()
    kube.create({"apiVersion": "v1", "kind": "Secret",
                 "metadata": {"name": "s", "namespace": "default"}})
    assert mgr.wait_idle(5)
    mgr.stop()
    records = [json.loads(l) for l in open(trace_file)]
    assert any(r["name"] == "reconcile"
               and r["attributes"]["controller"] == "Rec" for r in records)


def test_cni_request_logger_routes_to_netconf_file(tmp_path):
    from dpu_operator_tpu.cni.logging import request_logger
    from dpu_operator_tpu.cni.types import NetConf

    class Req:
        sandbox_id = "sandbox123456"
        ifname = "net1"
        netns = "/var/run/netns/x"
        netconf = NetConf(log_level="debug",
                          log_file=str(tmp_path / "cni.log"))

    logger = request_logger(Req())
    logger.debug("hello from %s", "test")
    for h in logging.getLogger(
            "cni.sandbox12345.net1").handlers:
        h.flush()
    content = open(tmp_path / "cni.log").read()
    assert "hello from test" in content
