"""Scripted-fault (chaos) matrix for the resilience layer.

Every fault the ISSUE names — apiserver reset, VSP crash mid-call, CNI
ADD transient failure, journal truncation — is injected deterministically
(fixed seeds, fake clocks, no real sleeps) through the harness in
dpu_operator_tpu/testing/chaos.py, and each recovery path must complete
WITHOUT manual intervention, with retry/breaker state visible on the
utils/metrics.py counters.
"""

import json
import random
import threading

import pytest

from dpu_operator_tpu.api import NetworkFunction, ServiceFunctionChain
from dpu_operator_tpu.cni.server import CniServer
from dpu_operator_tpu.cni.types import NetConf, PodRequest
from dpu_operator_tpu.daemon import SfcReconciler
from dpu_operator_tpu.daemon.tpusidemanager import TpuSideManager
from dpu_operator_tpu.k8s import Manager
from dpu_operator_tpu.k8s.manager import Request
from dpu_operator_tpu.testing import (
    ChaosChannel,
    ChaosKube,
    Fail,
    FailAfter,
    FaultPlan,
    Ok,
    truncate_file,
)
from dpu_operator_tpu.utils import metrics, resilience
from dpu_operator_tpu.vsp.plugin import GrpcPlugin

pytestmark = pytest.mark.chaos

SEED = 1337


def _policy(**kw):
    """Deterministic, sleepless retry policy for tests."""
    kw.setdefault("rng", random.Random(SEED))
    kw.setdefault("sleep", lambda s: None)
    return resilience.RetryPolicy(**kw)


def _sfc(name="chaos-sfc", nfs=("nf-a", "nf-b")):
    return ServiceFunctionChain(
        name=name,
        network_functions=[NetworkFunction(n, f"img-{n}") for n in nfs],
    ).to_obj()


def _req(name="chaos-sfc"):
    return Request("config.tpu.openshift.io/v1", "ServiceFunctionChain",
                   name, "default")


# -- FaultPlan semantics ------------------------------------------------------

def test_fault_plan_times_zero_means_no_fault():
    """Fail(times=0) — 'no failures' when parameterizing a matrix over a
    failure count — must pass the call through, not inject once, and a
    spent head must not shadow the fault scripted behind it."""
    plan = FaultPlan(seed=SEED)
    plan.script("op", Fail(times=0))
    assert plan.run("op", lambda: "ok") == "ok"
    assert plan.injected == []
    plan.script("op", Fail(times=0), Fail(times=1))
    with pytest.raises(ConnectionResetError):
        plan.run("op", lambda: "ok")
    assert plan.exhausted()


# -- RetryPolicy / CircuitBreaker primitives ---------------------------------

def test_retry_policy_full_jitter_backoff_is_bounded_and_seeded():
    p1 = _policy(base=0.1, cap=2.0)
    p2 = _policy(base=0.1, cap=2.0)
    seq1 = [p1.backoff(a) for a in range(6)]
    seq2 = [p2.backoff(a) for a in range(6)]
    assert seq1 == seq2  # same seed -> same jitter stream
    for attempt, delay in enumerate(seq1):
        assert 0.0 <= delay <= min(2.0, 0.1 * 2 ** attempt)


def test_retry_policy_recovers_then_reports_ok():
    before = metrics.RESILIENCE_RETRIES.value(site="t.ok", outcome="ok")
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionResetError("flap")
        return "fine"

    assert _policy(max_attempts=3).call(flaky, site="t.ok") == "fine"
    assert len(calls) == 3
    assert metrics.RESILIENCE_RETRIES.value(
        site="t.ok", outcome="ok") == before + 1


def test_retry_policy_timeout_means_fail():
    calls = []

    def hung():
        calls.append(1)
        raise TimeoutError("deadline")

    with pytest.raises(TimeoutError):
        _policy(max_attempts=5).call(hung, site="t.timeout")
    assert len(calls) == 1  # never retried


def test_retry_policy_deadline_budget_stops_retries():
    clock = [0.0]

    def tick():
        clock[0] += 10.0  # each attempt "costs" 10s
        raise ConnectionResetError("flap")

    p = resilience.RetryPolicy(max_attempts=10, base=0.0, cap=0.0,
                               deadline=25.0, sleep=lambda s: None,
                               clock=lambda: clock[0])
    calls_before = clock[0]
    with pytest.raises(ConnectionResetError):
        p.call(tick, site="t.deadline")
    # 3 attempts: 10s, 20s elapsed < 25; at 30s the budget is blown
    assert clock[0] == calls_before + 30.0


def test_breaker_opens_half_opens_and_recloses():
    now = [0.0]
    br = resilience.CircuitBreaker("t.br", failure_threshold=3,
                                   reset_timeout=10.0,
                                   clock=lambda: now[0])
    for _ in range(3):
        br.record_failure()
    assert br.state == resilience.CircuitBreaker.OPEN
    with pytest.raises(resilience.BreakerOpen):
        br.before_call()
    assert metrics.BREAKER_STATE.value(site="t.br") == 2
    now[0] = 11.0  # past reset_timeout: one probe allowed
    assert br.state == resilience.CircuitBreaker.HALF_OPEN
    br.before_call()
    with pytest.raises(resilience.BreakerOpen):
        br.before_call()  # half_open_max=1: second probe rejected
    br.record_success()
    assert br.state == resilience.CircuitBreaker.CLOSED
    assert metrics.BREAKER_STATE.value(site="t.br") == 0


def test_breaker_failed_probe_reopens_and_restarts_clock():
    now = [0.0]
    br = resilience.CircuitBreaker("t.br2", failure_threshold=1,
                                   reset_timeout=10.0,
                                   clock=lambda: now[0])
    br.record_failure()
    now[0] = 10.5
    br.before_call()  # half-open probe admitted
    br.record_failure()  # probe failed
    assert br.state == resilience.CircuitBreaker.OPEN
    now[0] = 15.0  # clock restarted at 10.5: still open
    with pytest.raises(resilience.BreakerOpen):
        br.before_call()


# -- apiserver reset (k8s seam) ----------------------------------------------

def test_apiserver_reset_during_reconcile_recovers(kube):
    """Send-phase connection resets on NF pod creation retry in place:
    the chain lands whole with no manual intervention."""
    chaos = ChaosKube(kube, seed=SEED)
    chaos.plan.script("create", Fail(times=2))
    kube.create(_sfc())
    rec = SfcReconciler(workload_image="img", retry=_policy())
    rec.reconcile(chaos, _req())
    assert kube.get("v1", "Pod", "chaos-sfc-nf-a",
                    namespace="default") is not None
    assert kube.get("v1", "Pod", "chaos-sfc-nf-b",
                    namespace="default") is not None
    assert chaos.plan.exhausted()
    assert metrics.RESILIENCE_RETRIES.value(
        site="sfc.create_nf_pod", outcome="ok") >= 1


def test_mid_response_reset_never_duplicates_the_create(kube):
    """Reset mid-RESPONSE: the apiserver committed the pod, the client
    saw an error. The retry surfaces AlreadyExists and the adopt path
    takes over — exactly one pod, no crash loop."""
    chaos = ChaosKube(kube, seed=SEED)
    chaos.plan.script("create", FailAfter(times=1))
    kube.create(_sfc(nfs=("nf-a",)))
    rec = SfcReconciler(workload_image="img", retry=_policy())
    rec.reconcile(chaos, _req())
    pods = kube.list("v1", "Pod", namespace="default",
                     label_selector={"sfc": "chaos-sfc"})
    assert len(pods) == 1


def test_hard_create_failure_rolls_back_partial_chain(kube):
    """Non-transient failure on NF #2 after NF #1 was created: the pass
    rolls its pods back instead of parking a half-programmed chain."""
    chaos = ChaosKube(kube, seed=SEED)
    chaos.plan.script(
        "create", Ok(),
        Fail(exc=lambda: RuntimeError("quota denied"), times=3))
    kube.create(_sfc())
    rec = SfcReconciler(workload_image="img", retry=_policy())
    with pytest.raises(RuntimeError):
        rec.reconcile(chaos, _req())
    assert kube.list("v1", "Pod", namespace="default",
                     label_selector={"sfc": "chaos-sfc"}) == []


def test_apiserver_flap_storm_converges_through_manager(kube):
    """A seeded flap storm across verbs: the manager's backoff requeue +
    in-place retries converge the chain with zero operator action."""
    chaos = ChaosKube(kube, seed=SEED)
    chaos.plan.script("get", Fail(times=1))
    chaos.plan.script("update_status", Fail(times=1))
    mgr = Manager(chaos)
    mgr.RETRY_BASE = 0.05  # keep the error-retry fast for the test
    mgr.add_reconciler(SfcReconciler(workload_image="img",
                                     retry=_policy()))
    mgr.start()
    try:
        kube.create(_sfc(name="storm"))
        assert mgr.wait_idle(timeout=15.0)
        deadline = 50
        while not chaos.plan.exhausted() and deadline:
            mgr.wait_idle(timeout=1.0)
            deadline -= 1
        assert kube.get("v1", "Pod", "storm-nf-a",
                        namespace="default") is not None
    finally:
        mgr.stop()


# -- RealKube retry seam (no live apiserver needed) --------------------------

class _ScriptedPool:
    """HttpsConnectionPool stand-in driven by a FaultPlan."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.requests = []

    def request(self, method, path, params=None, body=None, headers=None,
                timeout=None):
        self.requests.append(method)

        def ok(*_a, **_kw):
            from dpu_operator_tpu.k8s.pool import PooledResponse
            return PooledResponse(200, {}, b'{"items": []}', path)

        return self.plan.run(method, ok)


def _bare_realkube(plan):
    from dpu_operator_tpu.k8s.real import RealKube

    class _Session:
        headers = {}

    rk = RealKube.__new__(RealKube)
    rk.base = "https://apiserver:6443"
    rk.session = _Session()
    rk.pool = _ScriptedPool(plan)
    rk.request_timeout = 5.0
    rk.retry = _policy(max_attempts=3)
    return rk


def test_realkube_retries_idempotent_verbs_not_create():
    plan = FaultPlan(SEED).script("GET", Fail(times=2))
    rk = _bare_realkube(plan)
    r = rk._request("get", "GET", rk.base + "/api/v1/pods")
    assert r.status_code == 200
    assert rk.pool.requests.count("GET") == 3  # 2 failures + success

    plan = FaultPlan(SEED).script("POST", Fail(times=1))
    rk = _bare_realkube(plan)
    with pytest.raises(ConnectionResetError):
        rk._request("create", "POST", rk.base + "/api/v1/pods",
                    json_obj={"kind": "Pod"})
    assert rk.pool.requests.count("POST") == 1  # never retried


def test_realkube_timeout_is_never_retried():
    plan = FaultPlan(SEED).script(
        "GET", Fail(exc=lambda: TimeoutError("read timed out"), times=1))
    rk = _bare_realkube(plan)
    with pytest.raises(TimeoutError):
        rk._request("get", "GET", rk.base + "/api/v1/pods")
    assert rk.pool.requests.count("GET") == 1


# -- VSP crash mid-call (vsp seam) -------------------------------------------

def _plugin(channel, breaker=None):
    p = GrpcPlugin(detection=None, retry=_policy(max_attempts=3),
                   breaker=breaker)
    p._channel = channel
    p._new_channel = lambda: channel  # keep the scripted channel wired
    return p


def test_vsp_crash_mid_call_reconnects_and_recovers():
    backend = ChaosChannel(
        lambda svc, m, req, timeout: {"devices": {"chip-0": {}}},
        seed=SEED)
    backend.plan.script("DeviceService.GetDevices", Fail(times=2))
    plugin = _plugin(backend)
    assert plugin.get_devices() == {"chip-0": {}}
    assert backend.calls == 3
    assert backend.plan.exhausted()


def test_vsp_persistent_crash_opens_breaker_and_reports_degraded():
    now = [0.0]
    breaker = resilience.CircuitBreaker("vsp", failure_threshold=3,
                                        reset_timeout=10.0,
                                        clock=lambda: now[0])
    backend = ChaosChannel(lambda svc, m, req, timeout: {"supported": True},
                           seed=SEED)
    backend.plan.script("*", Fail(times=10))
    plugin = _plugin(backend, breaker=breaker)
    with pytest.raises(ConnectionResetError):
        plugin.get_devices()  # 3 attempts = 3 failures -> breaker opens
    assert breaker.is_open
    assert plugin.degraded_sites() == ["vsp"]
    calls_before = backend.calls
    with pytest.raises(resilience.BreakerOpen):
        plugin.get_devices()  # short-circuited: the VSP is walled off
    assert backend.calls == calls_before
    rejections = metrics.BREAKER_REJECTIONS.value(site="vsp")
    assert rejections >= 1
    # reset_timeout later a half-open probe finds the VSP healthy again
    now[0] = 11.0
    backend.plan._scripts.clear()
    assert plugin.get_devices() == {}
    assert not breaker.is_open
    assert plugin.degraded_sites() == []


def test_sustained_outage_reads_as_one_degraded_span():
    """Degraded must NOT flap off every reset_timeout during a hard
    outage: half-open (reset timer fired, recovery unproven) is still
    degraded; only a SUCCESSFUL probe clears it. The state gauge and
    the degraded signal must agree throughout."""
    now = [0.0]
    br = resilience.CircuitBreaker("t.span", failure_threshold=1,
                                   reset_timeout=10.0,
                                   clock=lambda: now[0])
    br.record_failure()  # outage starts
    assert br.degraded
    now[0] = 10.5  # reset timer fired, dependency still dead
    assert br.degraded  # NO healthy window before a probe succeeds
    assert metrics.BREAKER_STATE.value(site="t.span") == 1  # gauge agrees
    br.before_call()
    br.record_failure()  # probe fails: still one continuous span
    assert br.degraded
    now[0] = 21.0
    br.before_call()
    br.record_success()  # recovery PROVEN: span ends
    assert not br.degraded
    assert metrics.BREAKER_STATE.value(site="t.span") == 0


def test_vsp_app_errors_do_not_trip_the_breaker():
    """A misconfigured caller looping on a deterministic server-side
    rejection (gRPC UNKNOWN) must NOT wall off a healthy VSP for every
    other caller on the node — app errors are answers, not faults."""
    class _Code:
        name = "UNKNOWN"

    class _AppError(Exception):
        def code(self):
            return _Code()

    breaker = resilience.CircuitBreaker("vsp", failure_threshold=2,
                                        reset_timeout=10.0)
    backend = ChaosChannel(lambda *a: {}, seed=SEED)
    backend.plan.script("*", *[Fail(exc=_AppError, times=1)
                               for _ in range(6)])
    plugin = _plugin(backend, breaker=breaker)
    for _ in range(6):
        with pytest.raises(_AppError):
            plugin.get_devices()
    assert not breaker.is_open  # healthy VSP stays reachable
    assert plugin.degraded_sites() == []


def test_app_error_recloses_a_half_open_breaker():
    """During a half-open probe, an application-level answer proves the
    transport works: the breaker must re-close, not wedge half-open."""
    now = [0.0]
    br = resilience.CircuitBreaker("t.app", failure_threshold=1,
                                   reset_timeout=5.0,
                                   clock=lambda: now[0])
    br.record_failure()  # open
    now[0] = 6.0

    def app_error():
        raise ValueError("bad request, healthy server")

    with pytest.raises(ValueError):
        _policy(max_attempts=1).call(app_error, site="t.app", breaker=br)
    assert br.state == resilience.CircuitBreaker.CLOSED


def test_open_breaker_surfaces_degraded_condition_on_sfc(kube):
    """The daemon reports Degraded on the CR instead of crashing while
    the VSP breaker is open."""
    sites = ["vsp"]
    kube.create(_sfc(name="degraded-sfc", nfs=("nf-a",)))
    rec = SfcReconciler(workload_image="img", retry=_policy(),
                        degraded_provider=lambda: sites)
    rec.reconcile(kube, _req("degraded-sfc"))
    obj = kube.get("config.tpu.openshift.io/v1", "ServiceFunctionChain",
                   "degraded-sfc", namespace="default")
    conds = {c["type"]: c for c in obj["status"]["conditions"]}
    assert conds["Degraded"]["status"] == "True"
    assert conds["Degraded"]["reason"] == "CircuitBreakerOpen"
    assert "vsp" in conds["Degraded"]["message"]
    # breaker closes -> the condition disappears on the next resync
    sites.clear()
    rec.reconcile(kube, _req("degraded-sfc"))
    obj = kube.get("config.tpu.openshift.io/v1", "ServiceFunctionChain",
                   "degraded-sfc", namespace="default")
    assert "Degraded" not in {c["type"]
                              for c in obj["status"]["conditions"]}


def test_healthz_reports_degraded_sites_while_breaker_open():
    """Operators see degradation on /healthz as a structured JSON
    component breakdown (still 200 — alive and partially serving, and
    kubelet probes only read the status code), not discover it from
    missing wires."""
    import json
    import urllib.request

    sites = ["vsp"]
    srv = metrics.MetricsServer(host="127.0.0.1", port=0,
                                degraded_check=lambda: sites)
    srv.start()
    try:
        url = f"http://127.0.0.1:{srv.port}/healthz"
        with urllib.request.urlopen(url) as r:
            assert r.status == 200
            assert json.loads(r.read()) == {"status": "degraded",
                                            "components": ["vsp"]}
        sites.clear()
        with urllib.request.urlopen(url) as r:
            assert r.read() == b"ok"
    finally:
        srv.stop()


# -- CNI ADD transient failure / idempotent DEL (cni seam) -------------------

def _pod_request(command):
    return PodRequest(command=command, pod_namespace="default",
                      pod_name="p", sandbox_id="sbx-1", netns="/ns",
                      ifname="net1", device_id="chip-0",
                      netconf=NetConf())


def test_cni_add_transient_failure_retries_in_dispatch(short_tmp):
    calls = []

    def add(req):
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionResetError("vsp flap")
        return {"cniVersion": "0.4.0", "ok": True}

    srv = CniServer(short_tmp + "/cni.sock", add_handler=add,
                    timeout=5.0, retry=_policy(max_attempts=3))
    resp = srv._dispatch(add, _pod_request("ADD"))
    assert resp.error == ""
    assert resp.result["ok"] is True
    assert len(calls) == 3


def test_cni_add_non_transient_failure_fails_fast(short_tmp):
    calls = []

    def add(req):
        calls.append(1)
        raise ValueError("bad netconf")

    srv = CniServer(short_tmp + "/cni.sock", add_handler=add,
                    timeout=5.0, retry=_policy(max_attempts=3))
    with pytest.raises(ValueError):
        srv._dispatch(add, _pod_request("ADD"))
    assert len(calls) == 1


def test_cni_del_tolerates_already_gone_state(short_tmp):
    from dpu_operator_tpu.cni import AlreadyGone

    def dele(req):
        raise AlreadyGone(req.sandbox_id)  # state gone: daemon restarted

    srv = CniServer(short_tmp + "/cni.sock", del_handler=dele,
                    timeout=5.0, retry=_policy())
    resp = srv._dispatch(dele, _pod_request("DEL"))
    assert resp.error == ""  # idempotent success, kubelet stops retrying

    def dele_fnf(req):
        raise FileNotFoundError("cache file vanished")

    resp = srv._dispatch(dele_fnf, _pod_request("DEL"))
    assert resp.error == ""


def test_cni_del_bare_keyerror_is_NOT_swallowed(short_tmp):
    """A malformed cache entry (handler bug) must surface as an error so
    kubelet retries — not convert to silent success + leaked devices."""
    def buggy(req):
        return {}["chip"]  # accidental KeyError, not an already-gone

    srv = CniServer(short_tmp + "/cni.sock", del_handler=buggy,
                    timeout=5.0, retry=_policy())
    with pytest.raises(KeyError):
        srv._dispatch(buggy, _pod_request("DEL"))


# -- journal truncation (crash mid-write) ------------------------------------

class _UnknownWiresVsp:
    def list_network_functions(self):
        return None  # dataplane cannot enumerate: journal trusted as-is


def _partial_manager(chains_file):
    m = TpuSideManager.__new__(TpuSideManager)
    m.vsp = _UnknownWiresVsp()
    m._attach_store = {}
    m._attach_lock = threading.Lock()
    m._chain_store = {}
    m._chain_hops = {}
    m._degraded_hops = set()
    m._chains_file = chains_file
    return m


def test_truncated_journal_falls_back_to_last_good(short_tmp):
    path = short_tmp + "/chains.json"
    writer = _partial_manager(path)
    with writer._attach_lock:
        writer._chain_hops[("default", "c", 0)] = ("a-out", "b-in")
        writer._save_chains_locked()
    writer._flush_chains()  # snapshot v1 (no last-good yet)
    with writer._attach_lock:
        writer._chain_hops[("default", "c", 1)] = ("b-out", "c-in")
        writer._save_chains_locked()
    writer._flush_chains()  # snapshot v2; last-good = v1
    before = metrics.JOURNAL_RECOVERIES.value(result="last_good")
    truncate_file(path, seed=SEED)  # crash mid-write of v2
    reader = _partial_manager(path)
    reader._recover_chains()
    # v1's hop is back; the truncated v2 delta is lost (at most one
    # batch), NOT a crash during daemon prepare()
    assert reader._chain_hops[("default", "c", 0)] == ("a-out", "b-in")
    assert metrics.JOURNAL_RECOVERIES.value(
        result="last_good") == before + 1


def test_both_journal_copies_corrupt_starts_empty(short_tmp):
    path = short_tmp + "/chains.json"
    with open(path, "w") as f:
        f.write('{"chains": [')  # torn
    with open(path + ".last-good", "w") as f:
        f.write("not json either")
    before = metrics.JOURNAL_RECOVERIES.value(result="empty")
    reader = _partial_manager(path)
    reader._recover_chains()  # must not raise
    assert reader._chain_hops == {}
    assert metrics.JOURNAL_RECOVERIES.value(result="empty") == before + 1


def test_clean_journal_counts_primary_recovery(short_tmp):
    path = short_tmp + "/chains.json"
    with open(path, "w") as f:
        json.dump({"chains": [], "hops": [
            {"namespace": "default", "name": "c", "index": 0,
             "ids": ["x", "y"]}], "mirrors": [], "sandboxes": {}}, f)
    before = metrics.JOURNAL_RECOVERIES.value(result="primary")
    reader = _partial_manager(path)
    reader._recover_chains()
    assert reader._chain_hops[("default", "c", 0)] == ("x", "y")
    assert metrics.JOURNAL_RECOVERIES.value(
        result="primary") == before + 1


# -- VSP server bind retry (satellite) ---------------------------------------

def test_vsp_server_bind_retries_over_ephemeral_range():
    import socket

    from dpu_operator_tpu.vsp.rpc import VspServer

    class _Impl:
        def get_devices(self, req):
            return {"devices": {}}

    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    taken = blocker.getsockname()[1]
    srv = VspServer(_Impl(), tcp_addr=("127.0.0.1", taken))
    try:
        srv.start()  # must NOT raise: falls through to an ephemeral port
        assert srv.bound_port not in (0, taken)
    finally:
        srv.stop()
        blocker.close()


# -- drain typed errors (satellite) ------------------------------------------

def test_cordon_raises_typed_node_not_found(kube):
    from dpu_operator_tpu.utils.drain import Drainer, NodeNotFound

    with pytest.raises(NodeNotFound) as ei:
        Drainer(kube).cordon("ghost")
    assert "ghost" in str(ei.value)
    assert isinstance(ei.value, KeyError)  # old call sites keep working


def test_uncordon_is_idempotent(kube, node_agent):
    from dpu_operator_tpu.utils.drain import Drainer

    node_agent.register_node("n1", allocatable={"google.com/tpu": "4"})
    d = Drainer(kube)
    d.uncordon("n1")  # already schedulable: no-op, no error
    d.cordon("n1")
    d.cordon("n1")  # idempotent cordon too
    d.uncordon("n1")
    d.uncordon("n1")
    assert kube.get("v1", "Node", "n1")["spec"]["unschedulable"] is False
    d.uncordon("gone-node")  # missing node: desired end state, no raise
