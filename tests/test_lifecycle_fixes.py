"""Regression tests for the resource-lifecycle defect audit (opslint v2).

The new `resource-lifecycle` rule found three real leaks on its first
whole-tree run; these tests pin the fixes so they cannot regress:

- `cni/announce._helper_main`: a failing `os.setns` left the netns fd
  open in the handler's `print(0); return 0` path — one leaked fd per
  failed announce in the spawned helper.
- `vsp/native_dp.AgentClient.__init__`: the connect-retry loop rebound
  `s = socket.socket(...)` each 50 ms attempt without closing the
  failed socket — up to ~100 leaked fds per construction while the
  agent came up (and all of them on the terminal re-raise).
- `daemon/handoff.adopt_into`: `settimeout` ran between the socket's
  creation and its try/finally (covered by the repo-green lint gate).
"""

import os
import socket

import pytest

from dpu_operator_tpu.cni import announce
from dpu_operator_tpu.vsp.native_dp import AgentClient


def test_announce_helper_closes_netns_fd_when_setns_fails(
        tmp_path, monkeypatch, capsys):
    """A netns handle opened for a setns that then fails must be closed
    on the failure path, not leaked into the helper's exit."""
    netns = tmp_path / "netns"
    netns.write_text("")
    opened, closed = [], []
    real_open, real_close = os.open, os.close

    def tracking_open(path, *a, **kw):
        fd = real_open(path, *a, **kw)
        if str(path) == str(netns):
            opened.append(fd)
        return fd

    def tracking_close(fd):
        if fd in opened:
            closed.append(fd)
        return real_close(fd)

    def failing_setns(fd, flags):
        raise OSError("setns: operation not permitted")

    monkeypatch.setattr(os, "open", tracking_open)
    monkeypatch.setattr(os, "close", tracking_close)
    # os.setns/CLONE_NEWNET only exist on 3.12+; the helper's
    # except OSError is the path under test either way
    monkeypatch.setattr(os, "setns", failing_setns, raising=False)
    monkeypatch.setattr(os, "CLONE_NEWNET", 0x40000000, raising=False)
    assert announce._helper_main([str(netns), "eth0", "10.0.0.8/24"]) == 0
    assert capsys.readouterr().out.strip() == "0"
    assert opened, "the helper never opened the netns handle"
    assert closed == opened, "netns fd leaked on the setns failure path"


def test_announce_helper_closes_netns_fd_on_success(
        tmp_path, monkeypatch, capsys):
    netns = tmp_path / "netns"
    netns.write_text("")
    opened, closed = [], []
    real_open, real_close = os.open, os.close
    monkeypatch.setattr(
        os, "open",
        lambda p, *a, **kw: (opened.append(fd := real_open(p, *a, **kw))
                             or fd if str(p) == str(netns)
                             else real_open(p, *a, **kw)))
    monkeypatch.setattr(
        os, "close",
        lambda fd: (closed.append(fd) if fd in opened else None,
                    real_close(fd))[1])
    monkeypatch.setattr(os, "setns", lambda fd, flags: None,
                        raising=False)
    monkeypatch.setattr(os, "CLONE_NEWNET", 0x40000000, raising=False)
    assert announce._helper_main([str(netns), "eth0", "10.0.0.9/24"]) == 0
    assert closed == opened


def test_agent_client_closes_every_failed_connect_socket(
        tmp_path, monkeypatch):
    """Each 50 ms connect retry must close its failed socket before
    reacquiring: the old loop leaked one fd per attempt for the whole
    construction window, and all of them on the terminal raise."""
    created = []
    real_socket = socket.socket

    def tracking_socket(*a, **kw):
        s = real_socket(*a, **kw)
        created.append(s)
        return s

    monkeypatch.setattr(socket, "socket", tracking_socket)
    with pytest.raises(OSError):
        AgentClient(str(tmp_path / "no-agent.sock"),
                    connect_timeout=0.15)
    assert len(created) >= 2, "expected multiple connect attempts"
    leaked = [s for s in created if s.fileno() != -1]
    for s in leaked:  # keep the test box clean before asserting
        s.close()
    assert not leaked, (f"{len(leaked)}/{len(created)} retry sockets "
                        "left open after a failed construction")
