"""Informer core units (k8s/informer.py): Store semantics + indexes,
SharedInformer fanout (per-handler queues, overflow degradation,
initial sync), relist diffing, resync, and the CachedClient facade."""

from __future__ import annotations

import threading
import time

from dpu_operator_tpu.k8s import FakeKube
from dpu_operator_tpu.k8s.informer import (
    SYNC,
    CachedClient,
    InformerFactory,
    SharedInformer,
    Store,
    cached_list,
)

from utils import assert_eventually


def obj_(name, ns=None, rv="1", labels=None, **extra):
    o = {"apiVersion": "v1", "kind": "ConfigMap",
         "metadata": {"name": name, "namespace": ns,
                      "resourceVersion": rv}}
    if labels is not None:
        o["metadata"]["labels"] = labels
    o.update(extra)
    return o


def cm(kube, name, data=None, ns="default"):
    return kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                        "metadata": {"name": name, "namespace": ns},
                        "data": data or {}})


# -- Store --------------------------------------------------------------------

def test_store_replace_diffs_added_modified_deleted():
    s = Store()
    s.apply_event("ADDED", obj_("keep", rv="1"))
    s.apply_event("ADDED", obj_("change", rv="1"))
    s.apply_event("ADDED", obj_("drop", rv="1"))
    added, modified, deleted = s.replace([
        obj_("keep", rv="1"), obj_("change", rv="9"), obj_("new", rv="2")])
    assert [o["metadata"]["name"] for o in added] == ["new"]
    assert [o["metadata"]["name"] for o in modified] == ["change"]
    assert [o["metadata"]["name"] for o in deleted] == ["drop"]
    assert s.get("drop") is None
    assert s.get("new") is not None


def test_store_reads_are_copies():
    s = Store()
    s.apply_event("ADDED", obj_("a", rv="1"))
    got = s.get("a")
    got["metadata"]["name"] = "mutated"
    assert s.get("a")["metadata"]["name"] == "a"


def test_store_secondary_index():
    s = Store(indexers={"app": lambda o: [
        (o.get("metadata", {}).get("labels") or {}).get("app", "")]})
    s.apply_event("ADDED", obj_("a", labels={"app": "x"}))
    s.apply_event("ADDED", obj_("b", labels={"app": "x"}))
    s.apply_event("ADDED", obj_("c", labels={"app": "y"}))
    assert {o["metadata"]["name"] for o in s.by_index("app", "x")} \
        == {"a", "b"}
    # index follows mutation and delete
    s.apply_event("MODIFIED", obj_("a", rv="2", labels={"app": "y"}))
    assert {o["metadata"]["name"] for o in s.by_index("app", "y")} \
        == {"a", "c"}
    s.apply_event("DELETED", obj_("c"))
    assert {o["metadata"]["name"] for o in s.by_index("app", "y")} == {"a"}


def test_store_label_selector_list():
    s = Store()
    s.apply_event("ADDED", obj_("a", labels={"t": "1"}))
    s.apply_event("ADDED", obj_("b", labels={"t": "2"}))
    assert [o["metadata"]["name"]
            for o in s.list(label_selector={"t": "1"})] == ["a"]


# -- SharedInformer -----------------------------------------------------------

def test_informer_initial_sync_and_live_events(kube):
    cm(kube, "pre")
    inf = SharedInformer(kube, "v1", "ConfigMap").start()
    try:
        assert inf.wait_synced(5)
        events = []
        inf.add_handler(lambda e, o: events.append(
            (e, o["metadata"]["name"])))
        assert_eventually(lambda: ("ADDED", "pre") in events)
        cm(kube, "live")
        assert_eventually(lambda: ("ADDED", "live") in events)
        kube.delete("v1", "ConfigMap", "live", namespace="default")
        assert_eventually(lambda: ("DELETED", "live") in events)
        assert inf.store.get("live", namespace="default") is None
    finally:
        inf.stop()


def test_one_stream_fans_out_to_n_handlers(kube):
    """One upstream watch serves every handler — no per-consumer
    apiserver stream."""
    inf = SharedInformer(kube, "v1", "ConfigMap").start()
    try:
        assert inf.wait_synced(5)
        sinks = [[] for _ in range(5)]
        for sink in sinks:
            inf.add_handler(
                lambda e, o, s=sink: s.append((e, o["metadata"]["name"])))
        cm(kube, "x")
        for sink in sinks:
            assert_eventually(lambda s=sink: ("ADDED", "x") in s)
        with kube._lock:
            n_streams = sum(len(qs) for qs in kube._streams.values())
        assert n_streams == 1, "each handler opened its own stream"
    finally:
        inf.stop()


def test_slow_handler_does_not_block_siblings(kube):
    inf = SharedInformer(kube, "v1", "ConfigMap").start()
    try:
        assert inf.wait_synced(5)
        release = threading.Event()
        fast: list = []
        inf.add_handler(lambda e, o: release.wait(10))
        inf.add_handler(lambda e, o: fast.append(o["metadata"]["name"]))
        cm(kube, "a")
        cm(kube, "b")
        # the fast handler sees both while the slow one is parked
        assert_eventually(lambda: {"a", "b"} <= set(fast))
        release.set()
    finally:
        release.set()
        inf.stop()


def test_handler_overflow_degrades_to_sync_replay(kube):
    """A handler too slow for the storm gets per-key SYNC replay from
    the store once it catches up — level-triggered, nothing lost."""
    inf = SharedInformer(kube, "v1", "ConfigMap").start()
    try:
        assert inf.wait_synced(5)
        release = threading.Event()
        seen: list = []
        started = threading.Event()

        def slow(e, o):
            started.set()
            release.wait(10)
            seen.append((e, o["metadata"]["name"]))
        inf.add_handler(slow, queue_size=2)
        cm(kube, "first")  # occupies the handler
        assert started.wait(5)
        for i in range(10):  # overflows the size-2 queue
            cm(kube, f"burst-{i}")
        release.set()
        assert_eventually(
            lambda: {f"burst-{i}" for i in range(10)}
            <= {name for _, name in seen},
            message="overflowed keys never replayed")
        # replayed entries arrive as SYNC (or queued ADDED for the ones
        # that fit) — correctness is the KEY set, not the event types
    finally:
        release.set()
        inf.stop()


def test_forced_relist_emits_missed_events(kube):
    """Watch outage + 410: events missed while disconnected surface as
    relist diff — no staleness."""
    cm(kube, "stays")
    cm(kube, "dies")
    inf = SharedInformer(kube, "v1", "ConfigMap")
    inf.MAX_STREAM_FAILURES = 10_000  # only the 410 path may relist
    inf.STREAM_RETRY_S = 0.02
    inf.start()
    try:
        assert inf.wait_synced(5)
        events = []
        inf.add_handler(lambda e, o: events.append(
            (e, o["metadata"]["name"])), initial_sync=False)
        kube.block_watches("v1", "ConfigMap")
        kube.delete("v1", "ConfigMap", "dies", namespace="default")
        cm(kube, "born")
        obj = kube.get("v1", "ConfigMap", "stays", namespace="default")
        obj["data"] = {"k": "v"}
        kube.update(obj)
        kube.compact_history("v1", "ConfigMap")
        kube.unblock_watches("v1", "ConfigMap")
        assert_eventually(lambda: ("DELETED", "dies") in events
                          and ("ADDED", "born") in events
                          and ("MODIFIED", "stays") in events,
                          message="relist diff incomplete")
        assert inf.store.get("dies", namespace="default") is None
        assert inf.store.get("born", namespace="default") is not None
        assert inf.store.get("stays",
                             namespace="default")["data"] == {"k": "v"}
        assert inf.relists >= 2  # initial + gone
    finally:
        inf.stop()


def test_resync_emits_sync_events(kube):
    cm(kube, "obj")
    inf = SharedInformer(kube, "v1", "ConfigMap", resync=0.05).start()
    try:
        assert inf.wait_synced(5)
        events = []
        inf.add_handler(lambda e, o: events.append(e), initial_sync=False)
        assert_eventually(lambda: SYNC in events,
                          message="resync never fired")
    finally:
        inf.stop()


def test_informer_factory_shares_per_gvk(kube):
    factory = InformerFactory(kube)
    a = factory.informer_for("v1", "ConfigMap")
    b = factory.informer_for("v1", "ConfigMap")
    c = factory.informer_for("v1", "Secret")
    try:
        assert a is b
        assert c is not a
    finally:
        factory.stop_all()


# -- CachedClient -------------------------------------------------------------

def test_cached_client_serves_reads_and_delegates_writes(kube):
    factory = InformerFactory(kube)
    client = CachedClient(kube, factory)
    try:
        cm(kube, "a", data={"x": "1"})
        # uncached kind: read-through
        assert client.get("v1", "ConfigMap", "a",
                          namespace="default")["data"] == {"x": "1"}
        # cached: served from the store once synced
        got = client.cached_list("v1", "ConfigMap", namespace="default")
        assert [o["metadata"]["name"] for o in got] == ["a"]
        inf = factory.peek("v1", "ConfigMap")
        assert inf is not None and inf.has_synced()
        # a write delegates and the cache converges
        obj = client.get("v1", "ConfigMap", "a", namespace="default")
        obj["data"] = {"x": "2"}
        client.update(obj)
        assert_eventually(
            lambda: (inf.store.get("a", namespace="default")
                     or {}).get("data") == {"x": "2"})
        # cache MISS falls through live (created after the snapshot but
        # not yet watched back — must not read as NotFound)
        fresh = cm(kube, "fresh")
        assert client.get("v1", "ConfigMap", "fresh",
                          namespace="default") is not None
        assert fresh
    finally:
        factory.stop_all()


def test_cached_list_helper_against_bare_client(kube):
    """Reconcilers driven directly against FakeKube (no manager) get a
    plain LIST — the fallback the lister seam promises."""
    cm(kube, "a")
    out = cached_list(kube, "v1", "ConfigMap", namespace="default")
    assert [o["metadata"]["name"] for o in out] == ["a"]


def test_stopped_informer_releases_stream(kube):
    inf = SharedInformer(kube, "v1", "ConfigMap").start()
    assert inf.wait_synced(5)
    inf.stop()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with kube._lock:
            if not any(kube._streams.values()):
                break
        time.sleep(0.02)
    with kube._lock:
        assert not any(kube._streams.values()), "stream leaked past stop"
