"""Static C CNI shim (native/cnishim/shim.c) driven as kubelet would.

VERDICT r2 #5: the shim must be a self-contained artifact executing with an
EMPTY PATH and no repo checkout — kubelet/multus exec it in a mount
namespace where no Python runtime is guaranteed (reference ships a static
Go binary, dpu-cni/dpu-cni.go:17-42). Every test here runs the real binary
in a scrubbed environment against the real CNI unix-socket server.
"""

import json
import os
import subprocess

import pytest

from dpu_operator_tpu.cni import CniServer
from dpu_operator_tpu.cni.types import CniResponse

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SHIM_BIN = os.path.join(REPO, "native", "build", "tpu-cni")


@pytest.fixture(scope="session")
def shim_binary():
    subprocess.run(["make", "-C", os.path.join(REPO, "native")], check=True,
                   capture_output=True)
    return SHIM_BIN


@pytest.fixture
def cni_server(short_tmp):
    requests = []

    def add(pod_req):
        requests.append(pod_req)
        if pod_req.netconf.name == "explode":
            raise RuntimeError("dataplane on fire")
        return {"cniVersion": "0.4.0",
                "tpu": {"device": pod_req.device_id}}

    def delete(pod_req):
        requests.append(pod_req)
        return {}

    sock = short_tmp + "/cni.sock"
    srv = CniServer(sock, add_handler=add, del_handler=delete)
    srv.start()
    yield sock, requests
    srv.stop()


def _run_shim(binary, sock, env_extra, stdin_data, cwd="/"):
    """Exec the shim the hostile way: empty PATH, minimal env, cwd=/."""
    env = {"PATH": "", "TPU_CNI_SOCKET": sock}
    env.update(env_extra)
    return subprocess.run([binary], input=stdin_data, env=env, cwd=cwd,
                          capture_output=True, text=True, timeout=30)


def _cni_env(command="ADD", container="sbx-static", ifname="net1"):
    return {"CNI_COMMAND": command, "CNI_CONTAINERID": container,
            "CNI_NETNS": "/var/run/netns/x", "CNI_IFNAME": ifname,
            "CNI_ARGS": "K8S_POD_NAMESPACE=default;K8S_POD_NAME=p"}


def test_add_roundtrip_with_empty_path(shim_binary, cni_server):
    sock, requests = cni_server
    conf = json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                       "mode": "chip", "deviceID": "chip-2"})
    proc = _run_shim(shim_binary, sock, _cni_env(), conf)
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["tpu"]["device"] == "chip-2"
    assert requests[-1].command == "ADD"
    assert requests[-1].sandbox_id == "sbx-static"
    assert requests[-1].pod_name == "p"


def test_shim_sends_traceparent_and_joins_exported_trace(
        shim_binary, cni_server, tmp_path, monkeypatch):
    """The static shim is hop zero of the trace: it mints (or, with
    TRACEPARENT exported, joins) the 128-bit trace id the CNI server
    adopts — asserted through the server's recorded span."""
    from dpu_operator_tpu.utils import tracing
    sock, _ = cni_server
    trace_file = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("TPU_OPERATOR_TRACE", trace_file)
    tracing.reset_for_tests()
    conf = json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                       "mode": "chip", "deviceID": "chip-2"})
    try:
        # minted: the server adopts SOME remote context (non-null parent)
        proc = _run_shim(shim_binary, sock, _cni_env(), conf)
        assert proc.returncode == 0, proc.stderr
        # joined: an exported (strictly valid) TRACEPARENT wins
        exported = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        proc = _run_shim(shim_binary, sock,
                         dict(_cni_env(), TRACEPARENT=exported), conf)
        assert proc.returncode == 0, proc.stderr
        # sloppy values are NOT joined (strict lowercase-hex parsing)
        proc = _run_shim(shim_binary, sock,
                         dict(_cni_env(), TRACEPARENT="00-+junk-x-01"),
                         conf)
        assert proc.returncode == 0, proc.stderr
    finally:
        tracing.reset_for_tests()
    adds = [json.loads(l) for l in open(trace_file)
            if json.loads(l)["name"] == "cni.add"]
    assert len(adds) == 3
    minted, joined, sloppy = adds
    assert minted["parent_id"] and len(minted["trace_id"]) == 32
    assert joined["trace_id"] == "ab" * 16
    assert sloppy["trace_id"] not in ("ab" * 16, minted["trace_id"])


def test_del_and_check(shim_binary, cni_server):
    sock, requests = cni_server
    conf = json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                       "deviceID": "chip-0"})
    proc = _run_shim(shim_binary, sock, _cni_env(command="DEL"), conf)
    assert proc.returncode == 0, proc.stderr
    assert requests[-1].command == "DEL"

    # CHECK is a local no-op: succeeds even with no server listening
    proc = _run_shim(shim_binary, "/nonexistent.sock",
                     _cni_env(command="CHECK"), conf)
    assert proc.returncode == 0
    assert json.loads(proc.stdout) == {}


def test_handler_error_becomes_cni_error_json(shim_binary, cni_server):
    sock, _ = cni_server
    conf = json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                       "name": "explode", "deviceID": "chip-1"})
    proc = _run_shim(shim_binary, sock, _cni_env(), conf)
    assert proc.returncode == 1
    err = json.loads(proc.stdout)
    assert err["code"] == 999
    assert "dataplane on fire" in err["msg"]


def test_connect_failure_is_cni_error(shim_binary, short_tmp):
    proc = _run_shim(shim_binary, short_tmp + "/nope.sock", _cni_env(),
                     "{}")
    assert proc.returncode == 1
    err = json.loads(proc.stdout)
    assert err["code"] == 999
    assert "connect" in err["msg"]


def test_env_values_json_escaped(shim_binary, cni_server):
    """CNI_ARGS can carry quotes/backslashes; the shim must escape them
    into valid JSON rather than corrupt the request body."""
    sock, requests = cni_server
    env = _cni_env()
    env["CNI_ARGS"] = 'K8S_POD_NAMESPACE=default;K8S_POD_NAME=p"\\weird'
    proc = _run_shim(shim_binary, sock, env,
                     json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                                 "deviceID": "chip-0"}))
    assert proc.returncode == 0, proc.stdout
    assert requests[-1].pod_name == 'p"\\weird'


def test_empty_stdin_defaults_to_empty_netconf(shim_binary, cni_server):
    sock, requests = cni_server
    proc = _run_shim(shim_binary, sock, _cni_env(), "")
    assert proc.returncode == 0, proc.stdout
    # empty stdin became an empty {} netconf (all defaults, no device)
    assert requests[-1].netconf.device_id == ""
    assert requests[-1].netconf.name == ""


def test_daemon_prepare_installs_static_binary(shim_binary, short_tmp,
                                               monkeypatch):
    """prepare() must install the static binary (byte-identical,
    executable) when it is available — the Python shim is only the
    no-binary fallback."""
    from dpu_operator_tpu.daemon.daemon import Daemon
    from dpu_operator_tpu.platform import FakePlatform
    from dpu_operator_tpu.utils.path_manager import PathManager

    monkeypatch.setenv("TPU_CNI_SHIM_BIN", shim_binary)
    pm = PathManager(short_tmp)
    d = Daemon(FakePlatform(), path_manager=pm)
    d.prepare()
    target = os.path.join(pm.cni_host_dir("kind"), "tpu-cni")
    with open(target, "rb") as f, open(shim_binary, "rb") as g:
        assert f.read() == g.read()
    assert os.access(target, os.X_OK)


def test_daemon_prepare_falls_back_to_python_shim(short_tmp, monkeypatch):
    """With every candidate missing, the REAL locator (isfile+X_OK loop)
    reports no binary and prepare() installs the Python shim source."""
    from dpu_operator_tpu.daemon import daemon as daemon_mod
    from dpu_operator_tpu.platform import FakePlatform
    from dpu_operator_tpu.utils.path_manager import PathManager

    monkeypatch.setattr(
        daemon_mod, "_shim_candidates",
        lambda: ("/definitely/not/there", "/also/not/there",
                 short_tmp + "/never-built/tpu-cni"))
    assert daemon_mod._static_shim_binary() is None
    pm = PathManager(short_tmp)
    d = daemon_mod.Daemon(FakePlatform(), path_manager=pm)
    d.prepare()
    target = os.path.join(pm.cni_host_dir("kind"), "tpu-cni")
    with open(target) as f:
        assert "CNI shim" in f.read()  # the Python source was installed


def test_locator_rejects_non_executable_candidate(short_tmp, monkeypatch):
    from dpu_operator_tpu.daemon import daemon as daemon_mod

    not_exec = short_tmp + "/tpu-cni"
    with open(not_exec, "w") as f:
        f.write("binary")
    os.chmod(not_exec, 0o644)
    monkeypatch.setattr(daemon_mod, "_shim_candidates",
                        lambda: ("", not_exec))
    assert daemon_mod._static_shim_binary() is None
    os.chmod(not_exec, 0o755)
    assert daemon_mod._static_shim_binary() == not_exec
