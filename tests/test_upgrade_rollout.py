"""Blue-green VSP rollout (spec.upgradeStrategy) — make upgrade-check.

The controller stages the target VSP as the inactive color, gates
promotion on pod readiness + the health engine snapshot (a burn-rate
alert HOLDS the rollout with an UpgradeHeld Event while the old VSP
keeps serving), then drains the old color and records
UpgradeStarted/UpgradeCompleted — the fleet-level half of the
zero-downtime upgrade story (doc/architecture.md).
"""

import pytest

from dpu_operator_tpu.api import (
    TpuOperatorConfig,
    TpuOperatorConfigSpec,
    UpgradeStrategy,
    ValidationError,
    validate_tpu_operator_config,
)
from dpu_operator_tpu.controller import TpuOperatorConfigReconciler
from dpu_operator_tpu.k8s import Manager
from dpu_operator_tpu.utils import NAMESPACE

from utils import assert_eventually

pytestmark = pytest.mark.upgrade


class _Health:
    """Controllable health-engine snapshot (the /debug/health fold)."""

    def __init__(self):
        self.degraded: dict = {}

    def __call__(self):
        components = {name: {"healthy": False, "reasons": [reason]}
                      for name, reason in self.degraded.items()}
        return {"healthy": not components, "components": components}


@pytest.fixture
def health():
    return _Health()


@pytest.fixture
def manager(kube, node_agent, images, tmp_path, health):
    from dpu_operator_tpu.utils.filesystem_mode_detector import (
        FilesystemModeDetector,
    )
    from dpu_operator_tpu.utils.path_manager import PathManager
    node_agent.register_node("tpu-vm-0", labels={"tpu": "true"})
    mgr = Manager(kube)
    mgr.add_reconciler(TpuOperatorConfigReconciler(
        images,
        path_manager=PathManager(str(tmp_path)),
        fs_detector=FilesystemModeDetector(str(tmp_path)),
        health_provider=health))
    mgr.start()
    yield mgr
    mgr.stop()


def _cfg(image, type_="blueGreen"):
    return TpuOperatorConfig(spec=TpuOperatorConfigSpec(
        mode="tpu",
        upgrade_strategy=UpgradeStrategy(
            type=type_, vsp_image=image, check_interval=0.05)))


def _status_upgrade(kube):
    obj = kube.get(*("config.tpu.openshift.io/v1", "TpuOperatorConfig",
                     "tpu-operator-config"))
    return (obj.get("status") or {}).get("upgrade") or {}


def _ds_image(kube, color):
    ds = kube.get("apps/v1", "DaemonSet", f"tpu-vsp-{color}",
                  namespace=NAMESPACE)
    if ds is None:
        return None
    return ds["spec"]["template"]["spec"]["containers"][0]["image"]


def _events(kube, reason):
    return [e for e in kube.list("v1", "Event", namespace=NAMESPACE)
            if e.get("reason") == reason]


def _retarget(kube, image, type_="blueGreen"):
    obj = kube.get("config.tpu.openshift.io/v1", "TpuOperatorConfig",
                   "tpu-operator-config")
    obj["spec"]["upgradeStrategy"] = UpgradeStrategy(
        type=type_, vsp_image=image, check_interval=0.05).to_dict()
    kube.update(obj)


def test_first_managed_deploy_no_upgrade_events(kube, manager):
    kube.create(_cfg("vsp:v1").to_obj())
    assert manager.wait_idle()
    assert_eventually(lambda: _ds_image(kube, "blue") == "vsp:v1",
                      message="initial VSP DaemonSet")
    up = _status_upgrade(kube)
    assert up["currentImage"] == "vsp:v1"
    assert up["phase"] == "Complete"
    # no rollout happened: nothing to announce
    assert _events(kube, "UpgradeStarted") == []
    assert _events(kube, "UpgradeCompleted") == []


def test_blue_green_rollout_stages_gates_promotes(kube, manager):
    kube.create(_cfg("vsp:v1").to_obj())
    assert manager.wait_idle()
    assert_eventually(lambda: _ds_image(kube, "blue") == "vsp:v1")
    _retarget(kube, "vsp:v2")
    # staged as green, gated on its pods Running + health clean, then
    # promoted: blue drained, currentImage advanced
    assert_eventually(
        lambda: _status_upgrade(kube).get("currentImage") == "vsp:v2",
        message="rollout completion")
    assert _ds_image(kube, "green") == "vsp:v2"
    assert _ds_image(kube, "blue") is None  # old color drained
    up = _status_upgrade(kube)
    assert up["color"] == "green" and up["phase"] == "Complete"
    assert len(_events(kube, "UpgradeStarted")) == 1
    assert len(_events(kube, "UpgradeCompleted")) == 1
    assert "vsp:v2" in _events(kube, "UpgradeCompleted")[0]["message"]
    # a second rollout flips back: green -> blue
    _retarget(kube, "vsp:v3")
    assert_eventually(
        lambda: _status_upgrade(kube).get("currentImage") == "vsp:v3",
        message="second rollout completion")
    assert _ds_image(kube, "blue") == "vsp:v3"
    assert _ds_image(kube, "green") is None


def test_burn_rate_alert_holds_rollout_until_clear(kube, manager,
                                                   health):
    kube.create(_cfg("vsp:v1").to_obj())
    assert manager.wait_idle()
    assert_eventually(lambda: _ds_image(kube, "blue") == "vsp:v1")
    # an SLO burn-rate page fires mid-rollout: automatic hold
    health.degraded["kube-client"] = "SloAlert:kube-client:page"
    _retarget(kube, "vsp:v2")
    assert_eventually(
        lambda: _status_upgrade(kube).get("phase") == "Held",
        message="rollout hold on burn-rate alert")
    up = _status_upgrade(kube)
    assert "kube-client" in up["heldReason"]
    # held, not promoted: the OLD VSP keeps serving, the new one stays
    # staged, and operators see why
    assert _ds_image(kube, "blue") == "vsp:v1"
    assert _ds_image(kube, "green") == "vsp:v2"
    assert _status_upgrade(kube).get("currentImage") == "vsp:v1"
    held = _events(kube, "UpgradeHeld")
    assert len(held) >= 1 and "kube-client" in held[0]["message"]
    # the alert clears -> the held rollout resumes and completes
    health.degraded.clear()
    assert_eventually(
        lambda: _status_upgrade(kube).get("currentImage") == "vsp:v2",
        message="held rollout resuming after the alert cleared")
    assert _ds_image(kube, "blue") is None
    assert len(_events(kube, "UpgradeCompleted")) == 1
    # the flapping hold deduplicated into ONE Event (count bumps)
    assert len(_events(kube, "UpgradeHeld")) == 1


def test_reverted_target_cleans_up_abandoned_stage(kube, manager,
                                                   health):
    """Reverting spec.upgradeStrategy.vspImage back to the serving
    image mid-rollout must tear the staged other-color DaemonSet down
    — not leave it running the abandoned image on every node."""
    kube.create(_cfg("vsp:v1").to_obj())
    assert manager.wait_idle()
    assert_eventually(lambda: _ds_image(kube, "blue") == "vsp:v1")
    # hold the rollout so the stage stays parked mid-flight
    health.degraded["kube-client"] = "SloAlert:kube-client:page"
    _retarget(kube, "vsp:v2")
    assert_eventually(
        lambda: _status_upgrade(kube).get("phase") == "Held",
        message="rollout held before the revert")
    assert _ds_image(kube, "green") == "vsp:v2"
    # operator aborts the upgrade: target back to the serving image
    _retarget(kube, "vsp:v1")
    assert_eventually(
        lambda: _ds_image(kube, "green") is None,
        message="abandoned green stage deleted")
    up = _status_upgrade(kube)
    assert up["phase"] == "Complete" and up["currentImage"] == "vsp:v1"
    assert up["targetImage"] == ""
    assert _ds_image(kube, "blue") == "vsp:v1"


def test_recreate_strategy_replaces_in_place(kube, manager):
    kube.create(_cfg("vsp:v1", type_="recreate").to_obj())
    assert manager.wait_idle()
    assert_eventually(lambda: _ds_image(kube, "blue") == "vsp:v1")
    _retarget(kube, "vsp:v2", type_="recreate")
    assert_eventually(
        lambda: _ds_image(kube, "blue") == "vsp:v2",
        message="in-place recreate")
    up = _status_upgrade(kube)
    assert up["currentImage"] == "vsp:v2" and up["color"] == "blue"
    assert _ds_image(kube, "green") is None
    assert len(_events(kube, "UpgradeStarted")) == 1
    assert len(_events(kube, "UpgradeCompleted")) == 1


def test_removed_strategy_mid_rollout_cleans_up_stage(kube, manager,
                                                      health):
    """Deleting spec.upgradeStrategy while a rollout is staged must
    tear the staged other-color DS down (the serving color is left
    alone — never tear down a live dataplane on a spec removal)."""
    kube.create(_cfg("vsp:v1").to_obj())
    assert manager.wait_idle()
    assert_eventually(lambda: _ds_image(kube, "blue") == "vsp:v1")
    health.degraded["kube-client"] = "SloAlert:kube-client:page"
    _retarget(kube, "vsp:v2")
    assert_eventually(
        lambda: _status_upgrade(kube).get("phase") == "Held",
        message="rollout held before the strategy removal")
    assert _ds_image(kube, "green") == "vsp:v2"
    obj = kube.get("config.tpu.openshift.io/v1", "TpuOperatorConfig",
                   "tpu-operator-config")
    del obj["spec"]["upgradeStrategy"]
    kube.update(obj)
    assert_eventually(lambda: _ds_image(kube, "green") is None,
                      message="abandoned stage deleted on removal")
    assert _ds_image(kube, "blue") == "vsp:v1"  # serving DS untouched
    # the stage deletion and the status write are separate apiserver
    # writes within one reconcile: poll, don't assert instantaneous
    # consistency between them
    assert_eventually(
        lambda: (_status_upgrade(kube).get("targetImage") == ""
                 and _status_upgrade(kube).get("phase") == "Complete"),
        message="status settles after the strategy removal")


def test_degraded_sfc_condition_holds_rollout(kube, manager):
    """The node daemons surface dataplane health as Degraded /
    ChainDegraded conditions on the SFC CRs they reconcile — the
    cross-process signal the gate consults (the operator-local health
    snapshot cannot see daemons or the staged VSP on other nodes). A
    True condition mid-rollout holds promotion until the daemon clears
    it."""
    kube.create(_cfg("vsp:v1").to_obj())
    assert manager.wait_idle()
    assert_eventually(lambda: _ds_image(kube, "blue") == "vsp:v1")
    kube.create({
        "apiVersion": "config.tpu.openshift.io/v1",
        "kind": "ServiceFunctionChain",
        "metadata": {"name": "chain-a", "namespace": "default"},
        "spec": {},
        "status": {"conditions": [
            {"type": "Degraded", "status": "True",
             "reason": "CircuitBreakerOpen"}]},
    })
    _retarget(kube, "vsp:v2")
    assert_eventually(
        lambda: _status_upgrade(kube).get("phase") == "Held",
        message="rollout hold on degraded SFC CR")
    up = _status_upgrade(kube)
    assert "chain-a" in up["heldReason"]
    assert "Degraded" in up["heldReason"]
    # held, not promoted: the old VSP keeps serving
    assert _ds_image(kube, "blue") == "vsp:v1"
    assert _ds_image(kube, "green") == "vsp:v2"
    # the daemon repairs the chain and clears the condition -> resume
    obj = kube.get("config.tpu.openshift.io/v1", "ServiceFunctionChain",
                   "chain-a", namespace="default")
    obj["status"]["conditions"] = []
    kube.update(obj)
    assert_eventually(
        lambda: _status_upgrade(kube).get("currentImage") == "vsp:v2",
        message="held rollout resuming after the condition cleared")
    assert _ds_image(kube, "blue") is None


def test_gate_holds_on_pods_running_stale_image(kube):
    """phase=Running is not enough to promote: after a mid-rollout
    retarget the staged color's pods can still be running the previous
    image while the DS controller catches up — the gate must hold
    until every pod is on the TARGET image."""
    from dpu_operator_tpu.controller.vsp_rollout import VspRollout
    rollout = VspRollout(health_provider=lambda: {"components": {}})
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "tpu-vsp-green-0", "namespace": NAMESPACE,
                     "labels": {"tpu.openshift.io/vsp-color": "green"}},
        "spec": {"containers": [{"name": "vsp", "image": "vsp:v2"}]},
        "status": {"phase": "Running"},
    })
    strategy = UpgradeStrategy(vsp_image="vsp:v3")
    hold = rollout._gate(kube, strategy, "green", "vsp:v3")
    assert "not yet on target image" in hold
    pod = kube.get("v1", "Pod", "tpu-vsp-green-0", namespace=NAMESPACE)
    pod["spec"]["containers"][0]["image"] = "vsp:v3"
    kube.update(pod)
    assert rollout._gate(kube, strategy, "green", "vsp:v3") == ""


def test_gate_matches_vsp_container_by_name(kube):
    """An admission webhook can inject a sidecar at containers[0]: the
    image check must find the 'vsp' container BY NAME — checking index
    0 either holds forever (sidecar image != target) or, if the images
    happened to collide, promotes an unverified VSP."""
    from dpu_operator_tpu.controller.vsp_rollout import VspRollout
    rollout = VspRollout(health_provider=lambda: {"components": {}})
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "tpu-vsp-green-0", "namespace": NAMESPACE,
                     "labels": {"tpu.openshift.io/vsp-color": "green"}},
        "spec": {"containers": [
            {"name": "mesh-proxy", "image": "sidecar:v9"},
            {"name": "vsp", "image": "vsp:v3"}]},
        "status": {"phase": "Running"},
    })
    strategy = UpgradeStrategy(vsp_image="vsp:v3")
    assert rollout._gate(kube, strategy, "green", "vsp:v3") == ""
    pod = kube.get("v1", "Pod", "tpu-vsp-green-0", namespace=NAMESPACE)
    pod["spec"]["containers"][1]["image"] = "vsp:v2"  # vsp stale
    kube.update(pod)
    assert "not yet on target image" in rollout._gate(
        kube, strategy, "green", "vsp:v3")


def test_sfc_degraded_holds_even_with_health_gate_disabled(kube):
    """healthGate=false disables only the operator-local health-engine
    snapshot (its stated purpose: dev clusters with no engine running);
    the SFC-CR Degraded signal comes from the node daemons through the
    apiserver and must hold the rollout regardless — a staged VSP that
    walled itself off never promotes by draining the last working
    one."""
    from dpu_operator_tpu.controller.vsp_rollout import VspRollout

    def forbidden_health():
        raise AssertionError(
            "health provider consulted with healthGate=false")

    rollout = VspRollout(health_provider=forbidden_health)
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "tpu-vsp-green-0", "namespace": NAMESPACE,
                     "labels": {"tpu.openshift.io/vsp-color": "green"}},
        "spec": {"containers": [{"name": "vsp", "image": "vsp:v3"}]},
        "status": {"phase": "Running"},
    })
    kube.create({
        "apiVersion": "config.tpu.openshift.io/v1",
        "kind": "ServiceFunctionChain",
        "metadata": {"name": "chain-walled", "namespace": "default"},
        "spec": {},
        "status": {"conditions": [
            {"type": "Degraded", "status": "True",
             "reason": "CircuitBreakerOpen"}]},
    })
    strategy = UpgradeStrategy(vsp_image="vsp:v3", health_gate=False)
    hold = rollout._gate(kube, strategy, "green", "vsp:v3")
    assert "chain-walled" in hold and "Degraded" in hold
    # condition cleared -> gate passes, still without touching the
    # (disabled) health provider
    obj = kube.get("config.tpu.openshift.io/v1", "ServiceFunctionChain",
                   "chain-walled", namespace="default")
    obj["status"]["conditions"] = []
    kube.update(obj)
    assert rollout._gate(kube, strategy, "green", "vsp:v3") == ""


def test_upgrade_strategy_admission_validation():
    ok = _cfg("vsp:v1").to_obj()
    validate_tpu_operator_config(ok)  # well-formed passes
    bad_type = _cfg("vsp:v1").to_obj()
    bad_type["spec"]["upgradeStrategy"]["type"] = "yolo"
    with pytest.raises(ValidationError, match="upgradeStrategy.type"):
        validate_tpu_operator_config(bad_type)
    bad_interval = _cfg("vsp:v1").to_obj()
    bad_interval["spec"]["upgradeStrategy"]["checkIntervalSeconds"] = 0
    with pytest.raises(ValidationError, match="checkIntervalSeconds"):
        validate_tpu_operator_config(bad_interval)
    not_a_map = _cfg("vsp:v1").to_obj()
    not_a_map["spec"]["upgradeStrategy"] = "blueGreen"
    with pytest.raises(ValidationError, match="mapping"):
        validate_tpu_operator_config(not_a_map)
    # a non-string image would pass admission and then wedge the
    # rollout at DaemonSet apply time — reject it up front
    bad_image = _cfg("vsp:v1").to_obj()
    bad_image["spec"]["upgradeStrategy"]["vspImage"] = 5
    with pytest.raises(ValidationError, match="vspImage"):
        validate_tpu_operator_config(bad_image)
    bad_gate = _cfg("vsp:v1").to_obj()
    bad_gate["spec"]["upgradeStrategy"]["healthGate"] = "yes"
    with pytest.raises(ValidationError, match="healthGate"):
        validate_tpu_operator_config(bad_gate)


def test_upgrade_strategy_round_trips_through_spec():
    spec = TpuOperatorConfigSpec.from_dict(
        {"mode": "tpu", "upgradeStrategy": {"vspImage": "vsp:v9",
                                            "healthGate": False}})
    assert spec.upgrade_strategy.vsp_image == "vsp:v9"
    assert spec.upgrade_strategy.health_gate is False
    assert spec.upgrade_strategy.type == "blueGreen"
    assert (spec.to_dict()["upgradeStrategy"]["vspImage"] == "vsp:v9")
    # absent strategy stays absent (no controller-managed VSP)
    bare = TpuOperatorConfigSpec.from_dict({"mode": "host"})
    assert bare.upgrade_strategy is None
    assert "upgradeStrategy" not in bare.to_dict()
