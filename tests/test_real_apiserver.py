"""Real-apiserver tier: RealKube (the production HTTP client) driven against
an in-process HTTPS apiserver speaking the genuine Kubernetes REST protocol.

This is the envtest-equivalent the round-1 verdict called for: every request
crosses TLS + bearer auth + JSON wire format + REST path mapping — the parts
of ``k8s/real.py`` no FakeKube test can touch. Reference analog:
internal/testutils/kindcluster.go:47-64 and
internal/controller/dpuoperatorconfig_controller_test.go:116-170.
"""

import threading
import time

import pytest
import requests

from dpu_operator_tpu.api import TpuOperatorConfig, TpuOperatorConfigSpec
from dpu_operator_tpu.controller import TpuOperatorConfigReconciler
from dpu_operator_tpu.k8s import Manager
from dpu_operator_tpu.k8s.real import RealKube
from dpu_operator_tpu.utils import DEFAULT_NAD_NAME, NAMESPACE

from apiserver_fixture import MiniApiServer
from utils import assert_eventually


@pytest.fixture(scope="module")
def apiserver():
    srv = MiniApiServer().start()
    yield srv
    srv.stop()


@pytest.fixture
def real_kube(apiserver, tmp_path):
    # module-scoped server, fresh store per test
    apiserver.kube._store.clear()
    path = apiserver.write_kubeconfig(str(tmp_path / "kubeconfig"))
    return RealKube(kubeconfig=path)


def _pod(name, ns="default", labels=None):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": ns,
                         "labels": labels or {}},
            "spec": {"containers": [{"name": "c", "image": "img"}]}}


# -- wire-level CRUD ---------------------------------------------------------

def test_create_get_list_delete_roundtrip(real_kube):
    created = real_kube.create(_pod("p1", labels={"app": "a"}))
    assert created["metadata"]["uid"] and created["metadata"]["resourceVersion"]
    real_kube.create(_pod("p2", labels={"app": "b"}))

    got = real_kube.get("v1", "Pod", "p1", namespace="default")
    assert got["metadata"]["name"] == "p1"
    assert real_kube.get("v1", "Pod", "absent", namespace="default") is None

    assert len(real_kube.list("v1", "Pod", namespace="default")) == 2
    only_a = real_kube.list("v1", "Pod", namespace="default",
                            label_selector={"app": "a"})
    assert [p["metadata"]["name"] for p in only_a] == ["p1"]

    real_kube.delete("v1", "Pod", "p1", namespace="default")
    assert real_kube.get("v1", "Pod", "p1", namespace="default") is None
    real_kube.delete("v1", "Pod", "p1", namespace="default")  # 404 tolerated


def test_update_and_conflict(real_kube):
    obj = real_kube.create(_pod("u1"))
    obj["metadata"]["labels"] = {"x": "y"}
    updated = real_kube.update(obj)
    assert updated["metadata"]["labels"] == {"x": "y"}
    # stale resourceVersion → 409 surfaces as HTTPError
    obj["metadata"]["resourceVersion"] = "1"
    with pytest.raises(requests.HTTPError):
        real_kube.update(obj)


def test_apply_create_or_merge(real_kube):
    cm = {"apiVersion": "v1", "kind": "ConfigMap",
          "metadata": {"name": "c1", "namespace": "default"},
          "data": {"a": "1"}}
    real_kube.apply(cm)
    cm2 = {"apiVersion": "v1", "kind": "ConfigMap",
           "metadata": {"name": "c1", "namespace": "default"},
           "data": {"b": "2"}}
    merged = real_kube.apply(cm2)
    assert merged["data"] == {"a": "1", "b": "2"}


def test_update_status_subresource(real_kube):
    obj = real_kube.create(_pod("s1"))
    obj["status"] = {"phase": "Running"}
    out = real_kube.update_status(obj)
    assert out["status"]["phase"] == "Running"
    assert real_kube.get("v1", "Pod", "s1",
                         namespace="default")["status"]["phase"] == "Running"


def test_cluster_scoped_and_custom_resources(real_kube):
    node = {"apiVersion": "v1", "kind": "Node", "metadata": {"name": "n1"}}
    real_kube.create(node)
    assert real_kube.get("v1", "Node", "n1") is not None
    cfg = TpuOperatorConfig(spec=TpuOperatorConfigSpec(mode="host"))
    real_kube.create(cfg.to_obj())
    got = real_kube.get("config.tpu.openshift.io/v1", "TpuOperatorConfig",
                        cfg.to_obj()["metadata"]["name"])
    assert got["spec"]["mode"] == "host"


def test_watch_relist_delivers_events(real_kube):
    events = []
    done = threading.Event()

    def cb(event, obj):
        events.append((event, obj["metadata"]["name"]))
        if ("DELETED", "w1") in events:
            done.set()

    cancel = real_kube.watch("v1", "Pod", cb, poll=0.1)
    try:
        real_kube.create(_pod("w1"))
        assert_eventually(lambda: ("ADDED", "w1") in events)
        obj = real_kube.get("v1", "Pod", "w1", namespace="default")
        obj["metadata"]["labels"] = {"mod": "1"}
        real_kube.update(obj)
        assert_eventually(lambda: ("MODIFIED", "w1") in events)
        real_kube.delete("v1", "Pod", "w1", namespace="default")
        assert done.wait(5.0)
    finally:
        cancel()


# -- auth --------------------------------------------------------------------

def test_bad_token_rejected(apiserver, tmp_path):
    path = apiserver.write_kubeconfig(str(tmp_path / "bad-kubeconfig"),
                                      token="wrong-token")
    kube = RealKube(kubeconfig=path)
    with pytest.raises(requests.HTTPError) as ei:
        kube.list("v1", "Pod")
    assert ei.value.response.status_code == 401


def test_tls_verification_enforced(apiserver, tmp_path):
    # a client that doesn't trust the fixture CA must refuse the connection
    with pytest.raises(requests.exceptions.SSLError):
        requests.get(apiserver.url + "/api/v1/pods", timeout=5)


def test_unsupported_kubeconfig_auth_rejected(apiserver, tmp_path):
    import yaml
    path = str(tmp_path / "noauth-kubeconfig")
    apiserver.write_kubeconfig(path)
    with open(path) as f:
        cfg = yaml.safe_load(f)
    cfg["users"][0]["user"] = {"exec": {"command": "aws"}}
    with open(path, "w") as f:
        yaml.safe_dump(cfg, f)
    with pytest.raises(ValueError, match="unsupported kubeconfig auth"):
        RealKube(kubeconfig=path)


# -- leader election over the wire -------------------------------------------

def test_leader_lease_over_http(real_kube, apiserver, tmp_path):
    lost = threading.Event()
    cancel = real_kube.acquire_leader_lease(
        "tpu-operator-lock", namespace="default", lease_seconds=2,
        poll=0.1, on_lost=lost.set)
    lease = real_kube.get("coordination.k8s.io/v1", "Lease",
                          "tpu-operator-lock", namespace="default")
    holder = lease["spec"]["holderIdentity"]
    assert holder

    # a second contender cannot take an actively-renewed lease
    kube2 = RealKube(
        kubeconfig=apiserver.write_kubeconfig(str(tmp_path / "kc2")))
    acquired2 = threading.Event()
    cancel2 = []  # the contender's renew-loop cancel fn, once acquired

    def contend():
        cancel2.append(kube2.acquire_leader_lease(
            "tpu-operator-lock", namespace="default", lease_seconds=2,
            poll=0.1, identity="contender", on_lost=lambda: None))
        acquired2.set()

    t = threading.Thread(target=contend, daemon=True)
    t.start()
    try:
        time.sleep(1.0)
        assert not acquired2.is_set()
        assert not lost.is_set()

        # holder releases (stops renewing) → contender takes over after
        # expiry
        cancel()
        assert acquired2.wait(10.0)
        lease = real_kube.get("coordination.k8s.io/v1", "Lease",
                              "tpu-operator-lock", namespace="default")
        assert lease["spec"]["holderIdentity"] == "contender"
    finally:
        cancel()  # idempotent: stop the holder even on early failure
        # stop the CONTENDER's renew loop too: leaked, it keeps hitting
        # the apiserver every lease_seconds/3 for the rest of the suite
        # (and its kube.request spans pollute later tests' trace sinks).
        # Join first: on an early assertion failure the contender may
        # not have acquired YET — with the holder cancelled above it
        # will within its poll interval, and cancelling before it does
        # would miss the renew loop it then starts.
        t.join(timeout=15.0)
        for c in cancel2:
            c()


# -- the controller over the wire --------------------------------------------

@pytest.fixture
def real_manager(real_kube, images, tmp_path):
    from dpu_operator_tpu.utils.filesystem_mode_detector import (
        FilesystemModeDetector,
    )
    from dpu_operator_tpu.utils.path_manager import PathManager
    mgr = Manager(real_kube)
    mgr.add_reconciler(TpuOperatorConfigReconciler(
        images,
        path_manager=PathManager(str(tmp_path)),
        fs_detector=FilesystemModeDetector(str(tmp_path))))
    # fast relist so wait_idle-style asserts converge quickly
    real_kube.watch = (lambda av, k, cb, poll=0.2, _w=real_kube.watch:
                       _w(av, k, cb, poll=0.2))
    mgr.start()
    yield mgr
    mgr.stop()


def test_controller_reconciles_over_real_wire(real_kube, real_manager):
    """The round-1 verdict's done-criterion: RealKube (not FakeKube) backs
    the controller reconcile — CR in, DaemonSet + NAD + injector out, all
    over HTTPS."""
    cfg = TpuOperatorConfig(spec=TpuOperatorConfigSpec(mode="host"))
    real_kube.create(cfg.to_obj())

    assert_eventually(
        lambda: real_kube.get("apps/v1", "DaemonSet", "tpu-daemon",
                              namespace=NAMESPACE) is not None,
        timeout=15.0)
    ds = real_kube.get("apps/v1", "DaemonSet", "tpu-daemon",
                       namespace=NAMESPACE)
    assert ds["spec"]["template"]["spec"]["nodeSelector"] == {"tpu": "true"}

    assert_eventually(
        lambda: real_kube.get("k8s.cni.cncf.io/v1",
                              "NetworkAttachmentDefinition",
                              DEFAULT_NAD_NAME, namespace="default")
        is not None, timeout=15.0)

    assert_eventually(
        lambda: real_kube.get("apps/v1", "Deployment",
                              "network-resources-injector",
                              namespace=NAMESPACE) is not None,
        timeout=15.0)

    # status lands through the /status subresource over the wire
    name = cfg.to_obj()["metadata"]["name"]
    assert_eventually(
        lambda: (real_kube.get("config.tpu.openshift.io/v1",
                               "TpuOperatorConfig", name) or {})
        .get("status", {}).get("observedGeneration") is not None,
        timeout=15.0)

    # deleting the CR garbage-collects owned children (server-side GC)
    real_kube.delete("config.tpu.openshift.io/v1", "TpuOperatorConfig", name)
    assert_eventually(
        lambda: real_kube.get("apps/v1", "DaemonSet", "tpu-daemon",
                              namespace=NAMESPACE) is None, timeout=15.0)


# -- the webhook's apiserver interactions over the wire ----------------------

def test_webhook_control_switches_poll_over_real_wire(real_kube):
    from dpu_operator_tpu.webhook.server import (
        CONTROL_SWITCHES_CONFIGMAP,
        WebhookServer,
    )
    server = WebhookServer(client=real_kube)
    server.refresh_switches()
    assert server.injection_enabled  # no ConfigMap → enabled

    real_kube.create({
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": CONTROL_SWITCHES_CONFIGMAP,
                     "namespace": NAMESPACE},
        "data": {"config.json": '{"networkResourceInjection": false}'}})
    server.refresh_switches()
    assert not server.injection_enabled


def test_webhook_nad_lookup_over_real_wire(real_kube):
    from dpu_operator_tpu.webhook.injector import RESOURCE_NAME_ANNOTATION
    from dpu_operator_tpu.webhook.server import WebhookServer
    real_kube.create({
        "apiVersion": "k8s.cni.cncf.io/v1",
        "kind": "NetworkAttachmentDefinition",
        "metadata": {"name": "tpunfcni-conf", "namespace": "default",
                     "annotations": {
                         RESOURCE_NAME_ANNOTATION: "google.com/tpu"}},
        "spec": {"config": "{}"}})
    server = WebhookServer(client=real_kube)
    assert server._nad_resource("default", "tpunfcni-conf") == \
        "google.com/tpu"
    assert server._nad_resource("default", "absent") is None
