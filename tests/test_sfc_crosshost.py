"""Cross-host SFC chain steering + wire-table restart recovery.

VERDICT r4 #2: chain state was daemon-local memory — (a) an SFC whose NF
pods schedule onto different hosts of a multi-host slice never got its
cross-host hop wired; (b) a daemon restart lost the wire table, so
repair/teardown of pre-restart hops silently stopped until pod churn.

Tier 1 here runs TWO real daemons (full TpuSideManager stacks, real gRPC
on unix sockets + real TCP cross-boundary servers) against one shared
FakeKube: NF i lands on host A, NF i+1 on host B, and the hop wires on
BOTH dataplanes through the peer plane (reference to beat:
marvell/main.go:488-563 chain rules, single-DPU only). Tier 2 covers the
journal: a restarted manager rebuilds its hop table reconciled against
the dataplane's persisted wire list and keeps repairing/tearing down
pre-restart hops."""

import json
import threading

import pytest

from dpu_operator_tpu.daemon import TpuSideManager
from dpu_operator_tpu.k8s import FakeKube
from dpu_operator_tpu.utils import vars as v

SFC_API = "config.tpu.openshift.io/v1"


class _Req:
    def __init__(self, sandbox, device, ifname, pod, ns="default",
                 ici_ports=()):
        self.sandbox_id = sandbox
        self.device_id = device
        self.ifname = ifname
        self.pod_name = pod
        self.pod_namespace = ns
        self.netns = f"/var/run/netns/{sandbox}"

        class _NC:
            cni_version = "0.4.0"
            name = ""
            ipam = {}
        _NC.ici_ports = list(ici_ports)
        self.netconf = _NC()


def _nf_pod(kube, name, sfc, index, node):
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default",
                     "annotations": {"tpu.openshift.io/sfc": sfc,
                                     "tpu.openshift.io/sfc-index":
                                         str(index)}},
        "spec": {"containers": [{"name": "c"}], "nodeName": node},
    })


def _sfc(kube, name, nf_names):
    kube.create({
        "apiVersion": SFC_API, "kind": "ServiceFunctionChain",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"networkFunctions": [{"name": n, "image": "img"}
                                      for n in nf_names]}})


def _wire_pod(mgr, sandbox, pod, chips):
    mgr._cni_nf_add(_Req(sandbox, chips[0], "net1", pod))
    return mgr._cni_nf_add(_Req(sandbox, chips[1], "net2", pod))


# -- tier 1: two real daemons -------------------------------------------------

@pytest.fixture
def two_daemons():
    from dpu_operator_tpu.platform.vendordetector import TpuDetector
    from dpu_operator_tpu.utils.path_manager import PathManager
    from dpu_operator_tpu.vsp.mock import MockTpuVsp
    from dpu_operator_tpu.vsp.plugin import GrpcPlugin
    from dpu_operator_tpu.vsp.rpc import VspServer

    # short tmp root: PathManager's socket paths must fit sun_path (108)
    import shutil
    import tempfile
    tmp_path = tempfile.mkdtemp(prefix="xh-", dir="/tmp")

    kube = FakeKube()
    for node in ("node-a", "node-b"):
        kube.create({"apiVersion": "v1", "kind": "Node",
                     "metadata": {"name": node}})
    daemons, cleanups = {}, []
    for node in ("node-a", "node-b"):
        pm = PathManager(tmp_path + "/" + node)
        mock = MockTpuVsp(port=0)
        sock = pm.vendor_plugin_socket()
        pm.ensure_socket_dir(sock)
        vsp_server = VspServer(mock, socket_path=sock)
        vsp_server.start()
        det = TpuDetector().detection_result(tpu_mode=True, identifier=node)
        mgr = TpuSideManager(
            GrpcPlugin(det, path_manager=pm, init_timeout=5.0), pm,
            client=kube, node_name=node)
        mgr.start_vsp()
        mgr.listen()
        mgr._advertise_address()
        daemons[node] = (mgr, mock)
        cleanups.append((mgr, vsp_server))
    yield kube, daemons
    for mgr, vsp_server in cleanups:
        mgr.stop()
        vsp_server.stop()
    shutil.rmtree(tmp_path, ignore_errors=True)


def test_daemon_advertises_cross_boundary_address(two_daemons):
    kube, daemons = two_daemons
    for node, (mgr, _) in daemons.items():
        ann = kube.get("v1", "Node", node)["metadata"]["annotations"]
        addr = ann[v.CROSS_BOUNDARY_ADDR_ANNOTATION]
        assert addr.endswith(f":{mgr.bound_port}")


def test_cross_host_hop_wires_on_both_dataplanes(two_daemons):
    kube, daemons = two_daemons
    mgr_a, mock_a = daemons["node-a"]
    mgr_b, mock_b = daemons["node-b"]
    _sfc(kube, "xh", ["f0", "f1"])
    _nf_pod(kube, "xh-f0", "xh", 0, "node-a")
    _nf_pod(kube, "xh-f1", "xh", 1, "node-b")
    _wire_pod(mgr_a, "sbxA0000000", "xh-f0", ["chip-0", "chip-1"])
    # only NF0's own pod-internal wire so far; the hop waits for NF1
    assert len(mock_a.network_functions) == 1
    _wire_pod(mgr_b, "sbxB0000000", "xh-f1", ["chip-2", "chip-3"])
    # B does NOT own hop 0 (its NF is the downstream side)
    assert len(mock_b.network_functions) == 1
    # the upstream owner converges on its next resync
    mgr_a.sync_cross_host_hops("default", "xh")
    hop = ("nf-sbxA0000000-chip-1", "nf-sbxB0000000-chip-2")
    assert hop in mock_a.network_functions  # egress half on host A
    assert hop in mock_b.network_functions  # ingress half on host B
    hop_key = ("default", "xh", 0)
    assert mgr_a._chain_hops[hop_key] == hop
    node_b_addr = kube.get("v1", "Node", "node-b")["metadata"][
        "annotations"][v.CROSS_BOUNDARY_ADDR_ANNOTATION]
    assert mgr_a._remote_hops[hop_key] == node_b_addr
    # the wire-path trigger also converges without an explicit resync:
    # re-running sync is idempotent
    before = list(mock_a.network_functions)
    mgr_a.sync_cross_host_hops("default", "xh")
    assert mock_a.network_functions == before


def test_cross_host_hop_teardown_unwires_remote_half(two_daemons):
    kube, daemons = two_daemons
    mgr_a, mock_a = daemons["node-a"]
    mgr_b, mock_b = daemons["node-b"]
    _sfc(kube, "xh2", ["f0", "f1"])
    _nf_pod(kube, "xh2-f0", "xh2", 0, "node-a")
    _nf_pod(kube, "xh2-f1", "xh2", 1, "node-b")
    _wire_pod(mgr_a, "sbxA1111111", "xh2-f0", ["chip-0", "chip-1"])
    _wire_pod(mgr_b, "sbxB1111111", "xh2-f1", ["chip-2", "chip-3"])
    mgr_a.sync_cross_host_hops("default", "xh2")
    hop = ("nf-sbxA1111111-chip-1", "nf-sbxB1111111-chip-2")
    assert hop in mock_b.network_functions
    # upstream sandbox torn down: the hop unwires on BOTH hosts
    mgr_a._cni_nf_del(_Req("sbxA1111111", None, "net1", "xh2-f0"))
    assert hop not in mock_a.network_functions
    assert hop not in mock_b.network_functions
    assert ("default", "xh2", 0) not in mgr_a._chain_hops


def test_remote_nf_gone_tears_down_cross_host_hop(two_daemons):
    kube, daemons = two_daemons
    mgr_a, mock_a = daemons["node-a"]
    mgr_b, mock_b = daemons["node-b"]
    _sfc(kube, "xh3", ["f0", "f1"])
    _nf_pod(kube, "xh3-f0", "xh3", 0, "node-a")
    _nf_pod(kube, "xh3-f1", "xh3", 1, "node-b")
    _wire_pod(mgr_a, "sbxA2222222", "xh3-f0", ["chip-0", "chip-1"])
    _wire_pod(mgr_b, "sbxB2222222", "xh3-f1", ["chip-2", "chip-3"])
    mgr_a.sync_cross_host_hops("default", "xh3")
    hop = ("nf-sbxA2222222-chip-1", "nf-sbxB2222222-chip-2")
    assert hop in mock_a.network_functions
    # downstream NF dies on host B (B's own teardown path runs there,
    # then the pod object disappears)
    mgr_b._cni_nf_del(_Req("sbxB2222222", None, "net1", "xh3-f1"))
    kube.delete("v1", "Pod", "xh3-f1", namespace="default")
    mgr_a.sync_cross_host_hops("default", "xh3")
    assert hop not in mock_a.network_functions
    assert ("default", "xh3", 0) not in mgr_a._chain_hops
    assert ("default", "xh3", 0) not in mgr_a._remote_hops


def test_resync_does_not_undo_cross_host_repair(two_daemons):
    """A repaired (degraded) cross-host hop must NOT be re-wired back
    onto its dead ICI port by the 5 s resync — that would undo
    repair_chains every cycle (wire/unwire ping-pong onto a dead link).
    A replacement downstream NF still converges."""
    kube, daemons = two_daemons
    mgr_a, mock_a = daemons["node-a"]
    mgr_b, mock_b = daemons["node-b"]
    _sfc(kube, "xh5", ["f0", "f1"])
    _nf_pod(kube, "xh5-f0", "xh5", 0, "node-a")
    _nf_pod(kube, "xh5-f1", "xh5", 1, "node-b")
    mgr_a._cni_nf_add(_Req("sbxA4444444", "chip-0", "net1", "xh5-f0"))
    mgr_a._cni_nf_add(_Req("sbxA4444444", "chip-1", "net2", "xh5-f0",
                           ici_ports=["ici-0-x+", "ici-1-x+"]))
    mgr_b._cni_nf_add(_Req("sbxB4444444", "chip-2", "net1", "xh5-f1"))
    mgr_b._cni_nf_add(_Req("sbxB4444444", "chip-3", "net2", "xh5-f1",
                           ici_ports=["ici-2-x+", "ici-3-x+"]))
    mgr_a.sync_cross_host_hops("default", "xh5")
    hop_key = ("default", "xh5", 0)
    assert mgr_a._chain_hops[hop_key] == ("ici-1-x+", "ici-2-x+")
    # the allocated egress port goes dark; repair re-steers the local
    # side onto the attachment endpoint
    link_state = {1: [{"port": "x+", "up": False, "wired": True}]}
    mgr_a.link_prober = lambda chip: link_state.get(
        chip, [{"port": "x+", "up": True, "wired": True}])
    repaired = mgr_a.repair_chains()
    assert [k for k, _, _ in repaired] == [hop_key]
    steered = ("nf-sbxA4444444-chip-1", "ici-2-x+")
    assert mgr_a._chain_hops[hop_key] == steered
    # resync must LEAVE the repair in place
    mgr_a.sync_cross_host_hops("default", "xh5")
    assert mgr_a._chain_hops[hop_key] == steered
    assert hop_key in mgr_a._degraded_hops
    # but a REPLACEMENT downstream NF (new endpoints) still converges
    mgr_b._cni_nf_del(_Req("sbxB4444444", None, "net1", "xh5-f1"))
    kube.delete("v1", "Pod", "xh5-f1", namespace="default")
    _nf_pod(kube, "xh5-f1", "xh5", 1, "node-b")
    mgr_b._cni_nf_add(_Req("sbxB5555555", "chip-2", "net1", "xh5-f1"))
    mgr_b._cni_nf_add(_Req("sbxB5555555", "chip-3", "net2", "xh5-f1",
                           ici_ports=["ici-2-y+", "ici-3-y+"]))
    mgr_a.sync_cross_host_hops("default", "xh5")
    # downstream side changed -> re-wired (upstream side recomputed from
    # the still-allocated port list; repair will re-degrade it while the
    # link stays dark, which is the make-before-break contract)
    assert mgr_a._chain_hops[hop_key][1] == "ici-2-y+"


def test_migrated_downstream_nf_rewires_locally(two_daemons):
    """The downstream NF pod is recreated onto the OWNER's node: the
    stale cross-host hop must be torn down on both dataplanes and the
    local pair wired — otherwise traffic steers into the peer's dead
    ingress until the upstream NF churns."""
    kube, daemons = two_daemons
    mgr_a, mock_a = daemons["node-a"]
    mgr_b, mock_b = daemons["node-b"]
    _sfc(kube, "xh6", ["f0", "f1"])
    _nf_pod(kube, "xh6-f0", "xh6", 0, "node-a")
    _nf_pod(kube, "xh6-f1", "xh6", 1, "node-b")
    _wire_pod(mgr_a, "sbxA6666666", "xh6-f0", ["chip-0", "chip-1"])
    _wire_pod(mgr_b, "sbxB6666666", "xh6-f1", ["chip-2", "chip-3"])
    mgr_a.sync_cross_host_hops("default", "xh6")
    old_hop = ("nf-sbxA6666666-chip-1", "nf-sbxB6666666-chip-2")
    hop_key = ("default", "xh6", 0)
    assert mgr_a._chain_hops[hop_key] == old_hop
    # pod recreated on node-a (scheduler moved it); its CNI ADD now runs
    # on A — the stale cross-host hop blocks _update_chain's wire, until
    # the resync converts it
    kube.delete("v1", "Pod", "xh6-f1", namespace="default")
    _nf_pod(kube, "xh6-f1", "xh6", 1, "node-a")
    _wire_pod(mgr_a, "sbxA7777777", "xh6-f1", ["chip-2", "chip-3"])
    mgr_a.sync_cross_host_hops("default", "xh6")
    new_hop = ("nf-sbxA6666666-chip-1", "nf-sbxA7777777-chip-2")
    assert mgr_a._chain_hops[hop_key] == new_hop
    assert new_hop in mock_a.network_functions
    assert old_hop not in mock_a.network_functions  # old local half gone
    assert old_hop not in mock_b.network_functions  # peer half pruned
    assert hop_key not in mgr_a._remote_hops


def test_failed_repair_mirror_is_redriven_on_resync(two_daemons):
    """A peer unreachable exactly during the repair mirror must not
    leave its dataplane steering the dead pair forever: the mirror is
    parked and re-driven by the next resync."""
    kube, daemons = two_daemons
    mgr_a, mock_a = daemons["node-a"]
    mgr_b, mock_b = daemons["node-b"]
    _sfc(kube, "xh7", ["f0", "f1"])
    _nf_pod(kube, "xh7-f0", "xh7", 0, "node-a")
    _nf_pod(kube, "xh7-f1", "xh7", 1, "node-b")
    mgr_a._cni_nf_add(_Req("sbxA8888888", "chip-0", "net1", "xh7-f0"))
    mgr_a._cni_nf_add(_Req("sbxA8888888", "chip-1", "net2", "xh7-f0",
                           ici_ports=["ici-0-x+", "ici-1-x+"]))
    mgr_b._cni_nf_add(_Req("sbxB8888888", "chip-2", "net1", "xh7-f1"))
    mgr_b._cni_nf_add(_Req("sbxB8888888", "chip-3", "net2", "xh7-f1",
                           ici_ports=["ici-2-x+", "ici-3-x+"]))
    mgr_a.sync_cross_host_hops("default", "xh7")
    hop_key = ("default", "xh7", 0)
    old = ("ici-1-x+", "ici-2-x+")
    assert mgr_a._chain_hops[hop_key] == old
    # peer goes dark for the mirror: make remote calls fail once
    real_call = mgr_a._remote_call
    fail = {"on": True}

    def flaky_call(addr, svc, method, req, timeout=5.0):
        if fail["on"]:
            raise ConnectionError("peer restarting")
        return real_call(addr, svc, method, req, timeout)

    mgr_a._remote_call = flaky_call
    link_state = {1: [{"port": "x+", "up": False, "wired": True}]}
    mgr_a.link_prober = lambda chip: link_state.get(
        chip, [{"port": "x+", "up": True, "wired": True}])
    repaired = mgr_a.repair_chains()
    steered = ("nf-sbxA8888888-chip-1", "ici-2-x+")
    assert [k for k, _, _ in repaired] == [hop_key]
    assert mgr_a._chain_hops[hop_key] == steered
    # the peer never saw the re-steer (mirror failed); old pair still
    # wired there
    assert old in mock_b.network_functions
    assert steered not in mock_b.network_functions
    # peer comes back: the next resync re-drives the mirror
    fail["on"] = False
    mgr_a.sync_cross_host_hops("default", "xh7")
    assert steered in mock_b.network_functions
    assert old not in mock_b.network_functions
    assert not mgr_a._mirror_pending


def test_unreachable_peer_keeps_existing_hop(two_daemons):
    """A peer daemon restart must not read as an NF teardown: when the
    remote daemon is unreachable the hop is left wired."""
    kube, daemons = two_daemons
    mgr_a, mock_a = daemons["node-a"]
    mgr_b, mock_b = daemons["node-b"]
    _sfc(kube, "xh4", ["f0", "f1"])
    _nf_pod(kube, "xh4-f0", "xh4", 0, "node-a")
    _nf_pod(kube, "xh4-f1", "xh4", 1, "node-b")
    _wire_pod(mgr_a, "sbxA3333333", "xh4-f0", ["chip-0", "chip-1"])
    _wire_pod(mgr_b, "sbxB3333333", "xh4-f1", ["chip-2", "chip-3"])
    mgr_a.sync_cross_host_hops("default", "xh4")
    hop_key = ("default", "xh4", 0)
    assert hop_key in mgr_a._chain_hops
    # point node-b's advertised address at a dead port
    node = kube.get("v1", "Node", "node-b")
    node["metadata"]["annotations"][
        v.CROSS_BOUNDARY_ADDR_ANNOTATION] = "127.0.0.1:1"
    kube.update(node)
    mgr_a.sync_cross_host_hops("default", "xh4")
    assert hop_key in mgr_a._chain_hops  # NOT torn down
    hop = mgr_a._chain_hops[hop_key]
    assert hop in mock_a.network_functions


# -- tier 2: wire-table restart recovery --------------------------------------

class _RecordingVsp:
    """Lean VSP double with a live wire list (the ground truth a real
    VSP reads from the native agent's persisted state)."""

    def __init__(self):
        self.wired = []
        self.unwired = []
        self.attached = []
        self.detached = []
        self.wires = []

    def create_network_function(self, a, b):
        self.wired.append((a, b))
        self.wires.append((a, b))

    def delete_network_function(self, a, b):
        self.unwired.append((a, b))
        try:
            self.wires.remove((a, b))
        except ValueError:
            pass

    def create_slice_attachment(self, att):
        self.attached.append(att["name"])
        return att

    def delete_slice_attachment(self, name):
        self.detached.append(name)

    def list_network_functions(self):
        return list(self.wires)


def _lean_mgr(kube, tmp_path, vsp, tag="m"):
    from dpu_operator_tpu.cni import NetConfCache
    m = TpuSideManager.__new__(TpuSideManager)
    m.vsp = vsp
    m.client = kube
    m._attach_store = {}
    m._attach_lock = threading.Lock()
    m._chain_store = {}
    m._chain_hops = {}
    m._degraded_hops = set()
    m._repair_pass_lock = threading.Lock()
    m._repair_frozen = threading.Event()
    m.link_prober = None
    m.ipam_dir = str(tmp_path / "ipam")
    m.nf_cache = NetConfCache(str(tmp_path / "nf"))
    m._chains_file = str(tmp_path / "cache" / "chains.json")
    return m


def _restarted(kube, tmp_path, vsp):
    """A fresh manager over the same journal + dataplane — the daemon
    process restarting."""
    fresh = _lean_mgr(kube, tmp_path, vsp)
    fresh._recover_chains()
    return fresh


def test_restart_recovers_hops_and_repair_still_steers(kube, tmp_path):
    vsp = _RecordingVsp()
    mgr = _lean_mgr(kube, tmp_path, vsp)
    _sfc(kube, "rsfc", ["f0", "f1"])
    _nf_pod(kube, "rsfc-f0", "rsfc", 0, "")
    _nf_pod(kube, "rsfc-f1", "rsfc", 1, "")
    mgr._cni_nf_add(_Req("sbxR0000000", "chip-0", "net1", "rsfc-f0"))
    mgr._cni_nf_add(
        _Req("sbxR0000000", "chip-1", "net2", "rsfc-f0",
             ici_ports=["ici-0-x+", "ici-1-x+"]))
    mgr._cni_nf_add(_Req("sbxR1111111", "chip-2", "net1", "rsfc-f1"))
    mgr._cni_nf_add(
        _Req("sbxR1111111", "chip-3", "net2", "rsfc-f1",
             ici_ports=["ici-2-x+", "ici-3-x+"]))
    hop_key = ("default", "rsfc", 0)
    assert mgr._chain_hops[hop_key] == ("ici-1-x+", "ici-2-x+")

    fresh = _restarted(kube, tmp_path, vsp)
    assert fresh._chain_hops[hop_key] == ("ici-1-x+", "ici-2-x+")
    assert fresh._chain_store[("default", "rsfc")][0]["sandbox"] == \
        "sbxR0000000"
    # the pre-restart hop is still covered by self-healing: its
    # allocated egress port goes dark and repair re-steers it
    link_state = {1: [{"port": "x+", "up": False, "wired": True}]}
    fresh.link_prober = lambda chip: link_state.get(
        chip, [{"port": "x+", "up": True, "wired": True}])
    repaired = fresh.repair_chains()
    assert [k for k, _, _ in repaired] == [hop_key]
    assert fresh._chain_hops[hop_key] == ("nf-sbxR0000000-chip-1",
                                          "ici-2-x+")


def test_restart_teardown_of_pre_restart_sandbox_unwires(kube, tmp_path):
    vsp = _RecordingVsp()
    mgr = _lean_mgr(kube, tmp_path, vsp)
    _nf_pod(kube, "tsfc-f0", "tsfc", 0, "")
    _nf_pod(kube, "tsfc-f1", "tsfc", 1, "")
    _wire_pod(mgr, "sbxT0000000", "tsfc-f0", ["chip-0", "chip-1"])
    _wire_pod(mgr, "sbxT1111111", "tsfc-f1", ["chip-2", "chip-3"])
    hop = ("nf-sbxT0000000-chip-1", "nf-sbxT1111111-chip-2")
    assert hop in vsp.wires

    fresh = _restarted(kube, tmp_path, vsp)
    fresh._cni_nf_del(_Req("sbxT1111111", None, "net1", "tsfc-f1"))
    assert hop in fresh.vsp.unwired  # pre-restart hop torn down
    assert ("default", "tsfc", 0) not in fresh._chain_hops


def test_recovery_drops_hops_absent_from_dataplane(kube, tmp_path):
    """The journal is reconciled against the dataplane's persisted wire
    list: a hop whose wire never landed (crash between journal write and
    agent ack loss) must not be resurrected."""
    vsp = _RecordingVsp()
    mgr = _lean_mgr(kube, tmp_path, vsp)
    _nf_pod(kube, "dsfc-f0", "dsfc", 0, "")
    _nf_pod(kube, "dsfc-f1", "dsfc", 1, "")
    _wire_pod(mgr, "sbxD0000000", "dsfc-f0", ["chip-0", "chip-1"])
    _wire_pod(mgr, "sbxD1111111", "dsfc-f1", ["chip-2", "chip-3"])
    hop = ("nf-sbxD0000000-chip-1", "nf-sbxD1111111-chip-2")
    vsp.wires.remove(hop)  # dataplane says this wire does not exist
    fresh = _restarted(kube, tmp_path, vsp)
    assert ("default", "dsfc", 0) not in fresh._chain_hops
    # the chain entries themselves are still recovered (teardown of the
    # sandboxes keeps working)
    assert 0 in fresh._chain_store[("default", "dsfc")]


def test_recovery_trusts_journal_when_dataplane_cannot_enumerate(
        kube, tmp_path):
    vsp = _RecordingVsp()
    mgr = _lean_mgr(kube, tmp_path, vsp)
    _nf_pod(kube, "usfc-f0", "usfc", 0, "")
    _nf_pod(kube, "usfc-f1", "usfc", 1, "")
    _wire_pod(mgr, "sbxU0000000", "usfc-f0", ["chip-0", "chip-1"])
    _wire_pod(mgr, "sbxU1111111", "usfc-f1", ["chip-2", "chip-3"])

    # a vsp WITHOUT the lister at all: recovery must treat the wire
    # list as UNKNOWN and keep the journaled hops
    class _Plain:
        def delete_network_function(self, a, b):
            pass
    fresh = _lean_mgr(kube, tmp_path, _Plain())
    fresh._recover_chains()
    assert ("default", "usfc", 0) in fresh._chain_hops  # trusted as-is


def test_degraded_marker_survives_restart(kube, tmp_path):
    vsp = _RecordingVsp()
    mgr = _lean_mgr(kube, tmp_path, vsp)
    _nf_pod(kube, "gsfc-f0", "gsfc", 0, "")
    _nf_pod(kube, "gsfc-f1", "gsfc", 1, "")
    mgr._cni_nf_add(_Req("sbxG0000000", "chip-0", "net1", "gsfc-f0"))
    mgr._cni_nf_add(_Req("sbxG0000000", "chip-1", "net2", "gsfc-f0",
                         ici_ports=["ici-0-x+", "ici-1-x+"]))
    mgr._cni_nf_add(_Req("sbxG1111111", "chip-2", "net1", "gsfc-f1"))
    mgr._cni_nf_add(_Req("sbxG1111111", "chip-3", "net2", "gsfc-f1",
                         ici_ports=["ici-2-x+", "ici-3-x+"]))
    link_state = {1: [{"port": "x+", "up": False, "wired": True}]}
    mgr.link_prober = lambda chip: link_state.get(
        chip, [{"port": "x+", "up": True, "wired": True}])
    mgr.repair_chains()
    hop_key = ("default", "gsfc", 0)
    assert hop_key in mgr._degraded_hops

    fresh = _restarted(kube, tmp_path, vsp)
    assert hop_key in fresh._degraded_hops
    status = fresh.chain_status("default", "gsfc")
    assert status and status[0]["degraded"] is True


def test_journal_file_is_valid_json_snapshot(kube, tmp_path):
    vsp = _RecordingVsp()
    mgr = _lean_mgr(kube, tmp_path, vsp)
    _nf_pod(kube, "jsfc-f0", "jsfc", 0, "")
    _nf_pod(kube, "jsfc-f1", "jsfc", 1, "")
    _wire_pod(mgr, "sbxJ0000000", "jsfc-f0", ["chip-0", "chip-1"])
    _wire_pod(mgr, "sbxJ1111111", "jsfc-f1", ["chip-2", "chip-3"])
    with open(mgr._chains_file) as f:
        data = json.load(f)
    assert data["hops"][0]["ids"] == ["nf-sbxJ0000000-chip-1",
                                      "nf-sbxJ1111111-chip-2"]
    # teardown prunes the journal too
    mgr._cni_nf_del(_Req("sbxJ0000000", None, "net1", "jsfc-f0"))
    mgr._cni_nf_del(_Req("sbxJ1111111", None, "net1", "jsfc-f1"))
    with open(mgr._chains_file) as f:
        data = json.load(f)
    assert data["hops"] == []
