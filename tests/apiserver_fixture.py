"""In-process HTTPS Kubernetes apiserver fixture.

The envtest analog for this environment (no Kind/docker/etcd available):
serves the real Kubernetes REST wire protocol — TLS, bearer-token auth,
JSON bodies, apply-patch, status subresources, list/labelSelector — over
the proven :class:`FakeKube` object store, so ``RealKube`` (the production
apiserver client) and everything above it (controller manager, webhook
ConfigMap polling, leader election) is exercised end-to-end through genuine
HTTP instead of in-process method calls.

Reference analog: internal/testutils/kindcluster.go:47-64 (envtest CRDs +
UseExistingCluster) — the trick there is a real apiserver with fake
hardware; the trick here is a real wire protocol with a fake store.
"""

from __future__ import annotations

import base64
import datetime
import ipaddress
import json
import ssl
import tempfile
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import yaml

from dpu_operator_tpu.k8s.fake import AlreadyExists, Conflict, FakeKube
from dpu_operator_tpu.k8s.real import plural

#: kinds the fixture can route by plural path segment; extend as needed
KNOWN_KINDS = [
    "Pod", "Node", "Namespace", "ConfigMap", "Secret", "Service",
    "ServiceAccount", "Event", "Endpoints", "DaemonSet", "Deployment",
    "ReplicaSet", "StatefulSet", "ClusterRole", "ClusterRoleBinding",
    "Role", "RoleBinding", "Lease", "NetworkAttachmentDefinition",
    "CustomResourceDefinition", "TpuOperatorConfig", "ServiceFunctionChain",
    "MutatingWebhookConfiguration", "ValidatingWebhookConfiguration",
]
_PLURAL_TO_KIND = {plural(k): k for k in KNOWN_KINDS}


def make_self_signed_cert(tmpdir: str) -> tuple[str, str]:
    """Self-signed cert for 127.0.0.1; doubles as its own CA.
    Returns (cert_path, key_path)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "127.0.0.1")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name).issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(x509.SubjectAlternativeName(
            [x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
             x509.DNSName("localhost")]), critical=False)
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .sign(key, hashes.SHA256())
    )
    cert_path = tmpdir + "/apiserver.crt"
    key_path = tmpdir + "/apiserver.key"
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(key_path, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
    return cert_path, key_path


def _status(code: int, reason: str, message: str) -> dict:
    return {"kind": "Status", "apiVersion": "v1", "code": code,
            "reason": reason, "message": message}


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "MiniApiServer/1.0"

    # quiet request logging
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    @property
    def kube(self) -> FakeKube:
        return self.server.kube

    def _send(self, code: int, obj: dict):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _authed(self) -> bool:
        got = self.headers.get("Authorization", "")
        if got == f"Bearer {self.server.token}":
            return True
        self._send(401, _status(401, "Unauthorized", "bad or missing token"))
        return False

    def _parse(self):
        """Return (api_version, kind, namespace, name, subresource, query)
        or None after sending an error."""
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        query = {k: v[0] for k, v in parse_qs(url.query).items()}
        if len(parts) >= 2 and parts[0] == "api":
            api_version, rest = parts[1], parts[2:]
        elif len(parts) >= 3 and parts[0] == "apis":
            api_version, rest = f"{parts[1]}/{parts[2]}", parts[3:]
        else:
            self._send(404, _status(404, "NotFound", self.path))
            return None
        namespace = None
        if rest and rest[0] == "namespaces":
            if len(rest) <= 2:
                # the Namespace resource itself: /api/v1/namespaces[/name]
                return (api_version, "Namespace", None,
                        rest[1] if len(rest) == 2 else None, None, query)
            namespace, rest = rest[1], rest[2:]
        if not rest:
            self._send(404, _status(404, "NotFound", self.path))
            return None
        kind = _PLURAL_TO_KIND.get(rest[0])
        if kind is None:
            self._send(404, _status(
                404, "NotFound", f"unknown resource {rest[0]!r}"))
            return None
        name = rest[1] if len(rest) >= 2 else None
        subresource = rest[2] if len(rest) >= 3 else None
        return api_version, kind, namespace, name, subresource, query

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", "0") or "0")
        raw = self.rfile.read(length) if length else b"{}"
        return json.loads(raw or b"{}")

    # -- verbs ---------------------------------------------------------------
    def do_GET(self):  # noqa: N802
        if not self._authed():
            return
        parsed = self._parse()
        if parsed is None:
            return
        api_version, kind, namespace, name, _, query = parsed
        if name:
            obj = self.kube.get(api_version, kind, name, namespace=namespace)
            if obj is None:
                self._send(404, _status(404, "NotFound", name))
            else:
                self._send(200, obj)
            return
        selector = None
        if query.get("labelSelector"):
            selector = dict(kv.split("=", 1)
                            for kv in query["labelSelector"].split(","))
        items = self.kube.list(api_version, kind, namespace=namespace,
                               label_selector=selector)
        self._send(200, {"kind": f"{kind}List", "apiVersion": api_version,
                         "items": items})

    def do_POST(self):  # noqa: N802
        # drain the body first: an error response with the body unread
        # would poison the keep-alive connection for the next request
        obj = self._read_body()
        if not self._authed():
            return
        if self._parse() is None:
            return
        try:
            self._send(201, self.kube.create(obj))
        except AlreadyExists as e:
            self._send(409, _status(409, "AlreadyExists", str(e)))

    def do_PUT(self):  # noqa: N802
        obj = self._read_body()
        if not self._authed():
            return
        parsed = self._parse()
        if parsed is None:
            return
        _, _, _, _, subresource, _ = parsed
        try:
            if subresource == "status":
                self._send(200, self.kube.update_status(obj))
            else:
                self._send(200, self.kube.update(obj))
        except KeyError as e:
            self._send(404, _status(404, "NotFound", str(e)))
        except Conflict as e:
            self._send(409, _status(409, "Conflict", str(e)))

    def do_PATCH(self):  # noqa: N802
        obj = self._read_body()
        if not self._authed():
            return
        if self._parse() is None:
            return
        ctype = self.headers.get("Content-Type", "")
        if "apply-patch" not in ctype:
            self._send(415, _status(415, "UnsupportedMediaType", ctype))
            return
        try:
            self._send(200, self.kube.apply(obj))
        except Conflict as e:
            self._send(409, _status(409, "Conflict", str(e)))

    def do_DELETE(self):  # noqa: N802
        if not self._authed():
            return
        parsed = self._parse()
        if parsed is None:
            return
        api_version, kind, namespace, name, _, _ = parsed
        if name is None:
            self._send(405, _status(405, "MethodNotAllowed", "collection"))
            return
        existed = self.kube.get(api_version, kind, name,
                                namespace=namespace) is not None
        self.kube.delete(api_version, kind, name, namespace=namespace)
        if existed:
            self._send(200, _status(200, "Success", name))
        else:
            self._send(404, _status(404, "NotFound", name))


class MiniApiServer:
    """HTTPS apiserver over a FakeKube store, plus kubeconfig authoring."""

    def __init__(self, kube: FakeKube | None = None,
                 token: str = "test-bearer-token"):
        self.kube = kube or FakeKube()
        self.token = token
        self._tmp = tempfile.mkdtemp(prefix="miniapi-")
        self.cert_path, self.key_path = make_self_signed_cert(self._tmp)
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        self.httpd.kube = self.kube
        self.httpd.token = token
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_path, self.key_path)
        self.httpd.socket = ctx.wrap_socket(self.httpd.socket,
                                            server_side=True)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="mini-apiserver")

    def start(self) -> "MiniApiServer":
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    @property
    def url(self) -> str:
        return f"https://127.0.0.1:{self.port}"

    def write_kubeconfig(self, path: str, token: str | None = None) -> str:
        with open(self.cert_path, "rb") as f:
            ca_data = base64.b64encode(f.read()).decode()
        cfg = {
            "apiVersion": "v1", "kind": "Config",
            "current-context": "mini",
            "clusters": [{"name": "mini", "cluster": {
                "server": self.url,
                "certificate-authority-data": ca_data}}],
            "contexts": [{"name": "mini", "context": {
                "cluster": "mini", "user": "mini-user"}}],
            "users": [{"name": "mini-user", "user": {
                "token": token if token is not None else self.token}}],
        }
        with open(path, "w") as f:
            yaml.safe_dump(cfg, f)
        return path
