"""In-process HTTPS Kubernetes apiserver fixture.

The envtest analog for this environment (no Kind/docker/etcd available):
serves the real Kubernetes REST wire protocol — TLS, bearer-token auth,
JSON bodies, apply-patch, status subresources, list/labelSelector — over
the proven :class:`FakeKube` object store, so ``RealKube`` (the production
apiserver client) and everything above it (controller manager, webhook
ConfigMap polling, leader election) is exercised end-to-end through genuine
HTTP instead of in-process method calls.

Reference analog: internal/testutils/kindcluster.go:47-64 (envtest CRDs +
UseExistingCluster) — the trick there is a real apiserver with fake
hardware; the trick here is a real wire protocol with a fake store.
"""

from __future__ import annotations

import base64
import datetime
import ipaddress
import json
import ssl
import tempfile
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import yaml

from dpu_operator_tpu.k8s.fake import AlreadyExists, Conflict, FakeKube
from dpu_operator_tpu.k8s.real import plural

#: kinds the fixture can route by plural path segment; extend as needed
KNOWN_KINDS = [
    "Pod", "Node", "Namespace", "ConfigMap", "Secret", "Service",
    "ServiceAccount", "Event", "Endpoints", "DaemonSet", "Deployment",
    "ReplicaSet", "StatefulSet", "ClusterRole", "ClusterRoleBinding",
    "Role", "RoleBinding", "Lease", "NetworkAttachmentDefinition",
    "CustomResourceDefinition", "TpuOperatorConfig", "ServiceFunctionChain",
    "MutatingWebhookConfiguration", "ValidatingWebhookConfiguration",
    "TokenReview", "SubjectAccessReview",
]
_PLURAL_TO_KIND = {plural(k): k for k in KNOWN_KINDS}


def _make_cert_openssl(tmpdir: str) -> tuple[str, str]:
    """Cert generation via the openssl CLI — fallback for environments
    without the ``cryptography`` package (``req -x509`` already marks the
    cert CA:TRUE; adding basicConstraints again would duplicate the
    extension and break verification)."""
    import subprocess

    cert_path = tmpdir + "/apiserver.crt"
    key_path = tmpdir + "/apiserver.key"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048",
         "-keyout", key_path, "-out", cert_path, "-days", "1", "-nodes",
         "-subj", "/CN=127.0.0.1",
         "-addext", "subjectAltName=IP:127.0.0.1,DNS:localhost"],
        check=True, capture_output=True)
    return cert_path, key_path


def make_self_signed_cert(tmpdir: str) -> tuple[str, str]:
    """Self-signed cert for 127.0.0.1; doubles as its own CA.
    Returns (cert_path, key_path)."""
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID
    except ImportError:
        return _make_cert_openssl(tmpdir)

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "127.0.0.1")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name).issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(x509.SubjectAlternativeName(
            [x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
             x509.DNSName("localhost")]), critical=False)
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .sign(key, hashes.SHA256())
    )
    cert_path = tmpdir + "/apiserver.crt"
    key_path = tmpdir + "/apiserver.key"
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(key_path, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
    return cert_path, key_path


def _status(code: int, reason: str, message: str) -> dict:
    return {"kind": "Status", "apiVersion": "v1", "code": code,
            "reason": reason, "message": message}


class AdmissionDenied(Exception):
    """A webhook (or its failurePolicy) rejected the request."""

    def __init__(self, message: str, code: int = 403):
        super().__init__(message)
        self.code = code


def _apply_json_patch(obj: dict, patches: list) -> dict:
    """Minimal RFC-6902 applier (add/replace/remove, ~0/~1 escapes, list
    append via '-') — what the apiserver does with a mutating webhook's
    JSONPatch response."""
    import copy

    obj = copy.deepcopy(obj)
    for patch in patches:
        op, path = patch["op"], patch["path"]
        tokens = [t.replace("~1", "/").replace("~0", "~")
                  for t in path.lstrip("/").split("/")]
        parent = obj
        for tok in tokens[:-1]:
            parent = parent[int(tok)] if isinstance(parent, list) else parent[tok]
        last = tokens[-1]
        if isinstance(parent, list):
            if op == "add":
                idx = len(parent) if last == "-" else int(last)
                parent.insert(idx, patch["value"])
            elif op == "replace":
                parent[int(last)] = patch["value"]
            elif op == "remove":
                del parent[int(last)]
            else:
                raise ValueError(f"unsupported patch op {op!r}")
        else:
            if op in ("add", "replace"):
                parent[last] = patch["value"]
            elif op == "remove":
                del parent[last]
            else:
                raise ValueError(f"unsupported patch op {op!r}")
    return obj


def _in(values, x) -> bool:
    """Wildcard-or-member rule matching shared by admission + RBAC."""
    return "*" in (values or []) or x in (values or [])


def _rule_matches(rule: dict, group: str, version: str, resource: str,
                  op: str) -> bool:
    return (_in(rule.get("apiGroups"), group)
            and _in(rule.get("apiVersions"), version)
            and _in(rule.get("resources"), resource)
            and _in(rule.get("operations"), op))


def _resolve_client_config(kube: FakeKube, cc: dict) -> tuple[str, str]:
    """clientConfig -> (url, caBundle-b64). Service refs resolve through the
    store's Endpoints the way kube-proxy would route the Service."""
    if cc.get("url"):
        return cc["url"], cc.get("caBundle", "")
    svc = cc.get("service") or {}
    ep = kube.get("v1", "Endpoints", svc.get("name", ""),
                  namespace=svc.get("namespace"))
    if ep is None:
        raise ConnectionError(
            f"no Endpoints for webhook service "
            f"{svc.get('namespace')}/{svc.get('name')}")
    subset = (ep.get("subsets") or [{}])[0]
    addr = (subset.get("addresses") or [{}])[0].get("ip")
    # Endpoints ports are the RESOLVED backend (targetPort) ports — the
    # Service-level clientConfig port (usually 443) is only a fallback when
    # the Endpoints entry carries none, mirroring kube-proxy's routing.
    port = ((subset.get("ports") or [{}])[0].get("port")
            or svc.get("port") or 443)
    if not addr:
        raise ConnectionError("webhook Endpoints has no addresses")
    return (f"https://{addr}:{port}{svc.get('path', '/')}",
            cc.get("caBundle", ""))


def _call_webhook(url: str, ca_bundle_b64: str, review: dict,
                  timeout: float) -> dict:
    import urllib.request

    if ca_bundle_b64:
        ctx = ssl.create_default_context(
            cadata=base64.b64decode(ca_bundle_b64).decode())
        ctx.check_hostname = False  # IP SANs; verification is via the CA
    else:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    req = urllib.request.Request(
        url, data=json.dumps(review).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, context=ctx, timeout=timeout) as r:
        return json.loads(r.read() or b"{}")


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "MiniApiServer/1.0"
    # Nagle + client delayed-ACK turns the headers-then-body write pair
    # into a ~40 ms stall per response on some kernels; real apiservers
    # set TCP_NODELAY too (Go's net/http does it on every conn)
    disable_nagle_algorithm = True

    # quiet request logging
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    @property
    def kube(self) -> FakeKube:
        return self.server.kube

    def _send(self, code: int, obj: dict):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _authed(self) -> bool:
        got = self.headers.get("Authorization", "")
        if got == f"Bearer {self.server.token}":
            self._subject = None  # the suite's admin token: no RBAC
            return True
        for token, subject in self.server.owner.token_subjects.items():
            if got == f"Bearer {token}":
                self._subject = subject
                return True
        self._send(401, _status(401, "Unauthorized", "bad or missing token"))
        return False

    def _body_matches_url(self, obj: dict, api_version: str,
                          kind: str) -> bool:
        """Writes must target the URL's resource: a body whose kind
        differs (e.g. a ClusterRoleBinding POSTed to /configmaps) would
        otherwise bypass the per-resource RBAC grant. Real apiservers
        400 on the mismatch."""
        b_av, b_kind = obj.get("apiVersion", ""), obj.get("kind", "")
        if b_av == api_version and b_kind == kind:
            return True
        self._send(400, _status(
            400, "BadRequest",
            f"body is {b_av}/{b_kind} but URL addresses "
            f"{api_version}/{plural(kind)}"))
        return False

    # -- RBAC (reference: config/rbac/ exercised implicitly by envtest) ------
    @staticmethod
    def _rule_allows(rule: dict, verb: str, group: str,
                     full_resource: str, name: str | None) -> bool:
        """One PolicyRule vs one request — k8s semantics including
        resourceNames: a name-scoped rule never matches a request
        without a single object name (create/list/watch), and only
        matches named requests naming one of its resourceNames."""
        if not (_in(rule.get("apiGroups"), group)
                and _in(rule.get("resources"), full_resource)
                and _in(rule.get("verbs"), verb)):
            return False
        scoped = rule.get("resourceNames")
        if scoped:
            return name is not None and name in scoped
        return True

    def _roles_for_subject(self, namespace: str | None):
        """Yield every Role/ClusterRole bound to the authenticated
        subject: ClusterRoleBindings (cluster-wide) plus RoleBindings in
        *namespace* (which may reference a Role or a ClusterRole —
        granting the latter only within that namespace)."""
        for binding in self.kube.list("rbac.authorization.k8s.io/v1",
                                      "ClusterRoleBinding"):
            if not any(self._subject_matches(s)
                       for s in binding.get("subjects") or []):
                continue
            ref = binding.get("roleRef") or {}
            if ref.get("kind") != "ClusterRole":
                continue
            role = self.kube.get("rbac.authorization.k8s.io/v1",
                                 "ClusterRole", ref.get("name", ""))
            if role is not None:
                yield role
        if namespace:
            for binding in self.kube.list(
                    "rbac.authorization.k8s.io/v1", "RoleBinding",
                    namespace=namespace):
                if not any(self._subject_matches(s)
                           for s in binding.get("subjects") or []):
                    continue
                ref = binding.get("roleRef") or {}
                role = None
                if ref.get("kind") == "Role":
                    role = self.kube.get(
                        "rbac.authorization.k8s.io/v1", "Role",
                        ref.get("name", ""), namespace=namespace)
                elif ref.get("kind") == "ClusterRole":
                    role = self.kube.get(
                        "rbac.authorization.k8s.io/v1", "ClusterRole",
                        ref.get("name", ""))
                if role is not None:
                    yield role

    def _authorized(self, verb: str, group: str, resource: str,
                    subresource: str | None, name: str | None = None,
                    namespace: str | None = None) -> bool:
        """Role/ClusterRole rule evaluation for the authenticated
        subject, including resourceNames scoping and namespaced
        RoleBindings. The admin token (subject None) bypasses, matching
        envtest's cluster-admin default; tokens registered in
        token_subjects get real rule evaluation (VERDICT r2 #9: role.yaml
        must be validated by something that can fail)."""
        if self._subject is None or not self.server.owner.rbac_enabled:
            return True
        full_resource = (f"{resource}/{subresource}" if subresource
                         else resource)
        for role in self._roles_for_subject(namespace):
            for rule in role.get("rules") or []:
                if self._rule_allows(rule, verb, group, full_resource,
                                     name):
                    return True
        return False

    def _subject_matches(self, subject: dict) -> bool:
        mine = self._subject or {}
        if subject.get("kind") != mine.get("kind"):
            return False
        if subject.get("name") != mine.get("name"):
            return False
        if subject.get("kind") == "ServiceAccount":
            return subject.get("namespace") == mine.get("namespace")
        return True

    # -- authn/authz review APIs (TokenReview / SubjectAccessReview) ---------
    @staticmethod
    def _username_for(subject: dict) -> str:
        if subject.get("kind") == "ServiceAccount":
            return (f"system:serviceaccount:"
                    f"{subject.get('namespace', '')}:"
                    f"{subject.get('name', '')}")
        return subject.get("name", "")

    @staticmethod
    def _subject_for_username(username: str) -> dict:
        if username.startswith("system:serviceaccount:"):
            _, _, rest = username.partition("system:serviceaccount:")
            ns, _, name = rest.partition(":")
            return {"kind": "ServiceAccount", "name": name,
                    "namespace": ns}
        return {"kind": "User", "name": username}

    def _review(self, kind: str, obj: dict) -> dict:
        """Serve authentication.k8s.io TokenReview and authorization.k8s.io
        SubjectAccessReview — what the operator's metrics-auth filter
        POSTs to authenticate and authorize scrapers (the reference's
        WithAuthenticationAndAuthorization, cmd/main.go:66-70, backed by
        exactly these two APIs). Caller RBAC already checked (create on
        tokenreviews/subjectaccessreviews — metrics_auth_role.yaml)."""
        spec = obj.get("spec") or {}
        if kind == "TokenReview":
            token = spec.get("token", "")
            subject = None
            if token == self.server.token:
                subject = {"kind": "User", "name": "fixture-admin"}
            else:
                subject = self.server.owner.token_subjects.get(token)
            status = {"authenticated": subject is not None}
            if subject is not None:
                status["user"] = {"username": self._username_for(subject),
                                  "groups": ["system:authenticated"]}
            return dict(obj, status=status)
        # SubjectAccessReview: evaluate the SPEC'd user (not the caller)
        username = spec.get("user", "")
        subject = self._subject_for_username(username)
        nra = spec.get("nonResourceAttributes") or {}
        allowed = False
        if username == "fixture-admin":
            allowed = True
        elif nra:
            saved = self._subject
            self._subject = subject
            try:
                for role in self._roles_for_subject(None):
                    for rule in role.get("rules") or []:
                        if (_in(rule.get("nonResourceURLs"),
                                nra.get("path", ""))
                                and _in(rule.get("verbs"),
                                        nra.get("verb", ""))):
                            allowed = True
                            break
                    if allowed:
                        break
            finally:
                self._subject = saved
        else:
            ra = spec.get("resourceAttributes") or {}
            saved = self._subject
            self._subject = subject
            try:
                allowed = self._authorized(
                    ra.get("verb", ""), ra.get("group", ""),
                    ra.get("resource", ""), ra.get("subresource") or None,
                    name=ra.get("name") or None,
                    namespace=ra.get("namespace") or None)
            finally:
                self._subject = saved
        return dict(obj, status={"allowed": allowed})

    def _check_rbac(self, verb: str, api_version: str, resource_kind: str,
                    subresource: str | None, name: str | None = None,
                    namespace: str | None = None) -> bool:
        """Send 403 and return False when the subject lacks the verb."""
        group = api_version.rpartition("/")[0]
        resource = plural(resource_kind)
        if self._authorized(verb, group, resource, subresource, name=name,
                            namespace=namespace):
            return True
        mine = self._subject or {}
        self._send(403, _status(
            403, "Forbidden",
            f"{resource}{'/' + subresource if subresource else ''} is "
            f"forbidden: subject {mine.get('name', '?')!r} cannot {verb} "
            f"in apiGroup {group!r}"))
        return False

    def _parse(self):
        """Return (api_version, kind, namespace, name, subresource, query)
        or None after sending an error."""
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        query = {k: v[0] for k, v in parse_qs(url.query).items()}
        if len(parts) >= 2 and parts[0] == "api":
            api_version, rest = parts[1], parts[2:]
        elif len(parts) >= 3 and parts[0] == "apis":
            api_version, rest = f"{parts[1]}/{parts[2]}", parts[3:]
        else:
            self._send(404, _status(404, "NotFound", self.path))
            return None
        namespace = None
        if rest and rest[0] == "namespaces":
            if len(rest) <= 2:
                # the Namespace resource itself: /api/v1/namespaces[/name]
                return (api_version, "Namespace", None,
                        rest[1] if len(rest) == 2 else None, None, query)
            namespace, rest = rest[1], rest[2:]
        if not rest:
            self._send(404, _status(404, "NotFound", self.path))
            return None
        kind = _PLURAL_TO_KIND.get(rest[0])
        if kind is None:
            self._send(404, _status(
                404, "NotFound", f"unknown resource {rest[0]!r}"))
            return None
        name = rest[1] if len(rest) >= 2 else None
        subresource = rest[2] if len(rest) >= 3 else None
        return api_version, kind, namespace, name, subresource, query

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", "0") or "0")
        raw = self.rfile.read(length) if length else b"{}"
        return json.loads(raw or b"{}")

    def _send_denied(self, e: "AdmissionDenied"):
        self._send(e.code, _status(
            e.code, "Forbidden" if e.code == 403 else "InternalError",
            str(e)))

    # -- admission chain (webhook invocation over the wire) ------------------
    def _run_admission(self, obj: dict, operation: str) -> dict:
        """Invoke registered Mutating- then ValidatingWebhookConfigurations
        whose rules match, over real HTTPS with AdmissionReview JSON —
        what the reference's envtest apiserver does for its webhook suite
        (api/v1/webhook_suite_test.go). Returns the (possibly mutated)
        object; raises AdmissionDenied on rejection or Fail-policy errors.

        For operation DELETE, *obj* is the existing object: the review
        carries it as oldObject with object null, and patches are ignored
        (nothing to mutate), matching apiserver semantics.
        """
        import uuid

        api_version = obj.get("apiVersion", "v1")
        group, _, version = api_version.rpartition("/")
        resource = plural(obj.get("kind", ""))
        md = obj.get("metadata") or {}
        deleting = operation == "DELETE"

        def review_for(current: dict) -> dict:
            return {
                "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
                "request": {
                    "uid": str(uuid.uuid4()),
                    "kind": {"group": group, "version": version,
                             "kind": obj.get("kind", "")},
                    "resource": {"group": group, "version": version,
                                 "resource": resource},
                    "name": md.get("name", ""),
                    "namespace": md.get("namespace", ""),
                    "operation": operation,
                    "object": None if deleting else current,
                    "oldObject": current if deleting else None,
                },
            }

        for config_kind, mutating in (("MutatingWebhookConfiguration", True),
                                      ("ValidatingWebhookConfiguration",
                                       False)):
            configs = sorted(
                self.kube.list("admissionregistration.k8s.io/v1",
                               config_kind),
                key=lambda o: o["metadata"]["name"])
            for cfg in configs:
                for wh in cfg.get("webhooks") or []:
                    if not any(_rule_matches(r, group, version, resource,
                                             operation)
                               for r in wh.get("rules") or []):
                        continue
                    ignore = wh.get("failurePolicy", "Fail") == "Ignore"
                    name = wh.get("name", "?")
                    try:
                        url, ca = _resolve_client_config(
                            self.kube, wh.get("clientConfig") or {})
                        resp = _call_webhook(
                            url, ca, review_for(obj),
                            timeout=wh.get("timeoutSeconds", 10))
                        r = resp.get("response")
                        if not isinstance(r, dict) or "allowed" not in r:
                            raise ValueError(
                                "malformed AdmissionReview response")
                    except Exception as e:  # noqa: BLE001 — policy decides
                        if ignore:
                            continue
                        raise AdmissionDenied(
                            f"calling webhook {name!r}: {e}",
                            code=500) from e
                    if not r["allowed"]:
                        msg = ((r.get("status") or {}).get("message")
                               or "denied the request")
                        raise AdmissionDenied(
                            f"admission webhook {name!r} "
                            f"denied the request: {msg}")
                    if mutating and r.get("patch") and not deleting:
                        # a malformed patch is a webhook FAILURE (policy
                        # applies), not a denial
                        try:
                            if r.get("patchType") != "JSONPatch":
                                raise ValueError(
                                    f"unsupported patchType "
                                    f"{r.get('patchType')!r}")
                            patches = json.loads(base64.b64decode(r["patch"]))
                            obj = _apply_json_patch(obj, patches)
                        except Exception as e:  # noqa: BLE001
                            if ignore:
                                continue
                            raise AdmissionDenied(
                                f"webhook {name!r} patch failed: {e}",
                                code=500) from e
        return obj

    # -- verbs ---------------------------------------------------------------
    def do_GET(self):  # noqa: N802
        if not self._authed():
            return
        parsed = self._parse()
        if parsed is None:
            return
        api_version, kind, namespace, name, subresource, query = parsed
        if not self._check_rbac("get" if name else "list", api_version,
                                kind, subresource, name=name,
                                namespace=namespace):
            return
        if name:
            obj = self.kube.get(api_version, kind, name, namespace=namespace)
            if obj is None:
                self._send(404, _status(404, "NotFound", name))
            else:
                self._send(200, obj)
            return
        if query.get("watch") in ("1", "true"):
            self._serve_watch(api_version, kind, query)
            return
        selector = None
        if query.get("labelSelector"):
            selector = dict(kv.split("=", 1)
                            for kv in query["labelSelector"].split(","))
        items, rv = self.kube.list_collection(api_version, kind,
                                              namespace=namespace,
                                              label_selector=selector)
        self._send(200, {"kind": f"{kind}List", "apiVersion": api_version,
                         "metadata": {"resourceVersion": rv},
                         "items": items})

    # -- streaming watch (the real wire protocol's chunked event feed) -------
    def _serve_watch(self, api_version: str, kind: str, query: dict):
        """Serve ``?watch=1``: chunked transfer encoding, one JSON watch
        event per line, resourceVersion resume through FakeKube's event
        history, BOOKMARK events, and the in-stream 410 ERROR event the
        real apiserver answers a compacted resourceVersion with."""
        from dpu_operator_tpu.k8s.fake import (StaleResourceVersion,
                                               WatchDisconnected)

        timeout = float(query.get("timeoutSeconds", 240))
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def emit(event: str, obj: dict) -> None:
            data = json.dumps({"type": event, "object": obj}).encode() \
                + b"\n"
            self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
            self.wfile.flush()

        try:
            try:
                self.kube.watch_from(
                    api_version, kind, emit,
                    resource_version=query.get("resourceVersion"),
                    timeout=timeout)
            except StaleResourceVersion as e:
                emit("ERROR", dict(
                    _status(410, "Expired", str(e)),
                    reason="Expired"))
            # clean end-of-stream (timeoutSeconds reached): final chunk
            self.wfile.write(b"0\r\n\r\n")
        except WatchDisconnected:
            # test-injected outage (block/disconnect_watches): abrupt
            # close with no terminal chunk, as a crashed apiserver
            # would — the client sees a transport error and re-dials
            self.close_connection = True
        except (BrokenPipeError, ConnectionResetError, OSError):
            # client went away mid-stream; watch_from unregistered its
            # queue in its finally — nothing to clean up here
            self.close_connection = True

    def do_POST(self):  # noqa: N802
        # drain the body first: an error response with the body unread
        # would poison the keep-alive connection for the next request
        obj = self._read_body()
        if not self._authed():
            return
        parsed = self._parse()
        if parsed is None:
            return
        if not self._body_matches_url(obj, parsed[0], parsed[1]):
            return
        if not self._check_rbac("create", parsed[0], parsed[1], None,
                                namespace=parsed[2]):
            return
        if parsed[1] in ("TokenReview", "SubjectAccessReview"):
            self._send(201, self._review(parsed[1], obj))
            return
        try:
            obj = self._run_admission(obj, "CREATE")
        except AdmissionDenied as e:
            self._send_denied(e)
            return
        try:
            self._send(201, self.kube.create(obj))
        except AlreadyExists as e:
            self._send(409, _status(409, "AlreadyExists", str(e)))

    def do_PUT(self):  # noqa: N802
        obj = self._read_body()
        if not self._authed():
            return
        parsed = self._parse()
        if parsed is None:
            return
        _, _, p_namespace, p_name, subresource, _ = parsed
        if not self._body_matches_url(obj, parsed[0], parsed[1]):
            return
        if not self._check_rbac("update", parsed[0], parsed[1], subresource,
                                name=p_name, namespace=p_namespace):
            return
        if subresource is None:
            try:
                obj = self._run_admission(obj, "UPDATE")
            except AdmissionDenied as e:
                self._send_denied(e)
                return
        try:
            if subresource == "status":
                self._send(200, self.kube.update_status(obj))
            else:
                self._send(200, self.kube.update(obj))
        except KeyError as e:
            self._send(404, _status(404, "NotFound", str(e)))
        except Conflict as e:
            self._send(409, _status(409, "Conflict", str(e)))

    def do_PATCH(self):  # noqa: N802
        obj = self._read_body()
        if not self._authed():
            return
        parsed = self._parse()
        if parsed is None:
            return
        api_version, kind, namespace, name, _, _ = parsed
        ctype = self.headers.get("Content-Type", "")
        if "apply-patch" not in ctype:
            self._send(415, _status(415, "UnsupportedMediaType", ctype))
            return
        if not self._body_matches_url(obj, api_version, kind):
            return
        if not self._check_rbac("patch", api_version, kind, None,
                                name=name, namespace=namespace):
            return
        # server-side apply is CREATE-or-UPDATE; webhooks fire on the apply
        # intent (our apply bodies are full manifests, so the admitted
        # object is what gets merged — fixture-grade approximation of the
        # real apiserver admitting the merged result)
        existing = self.kube.get(api_version, kind, name,
                                 namespace=namespace)
        try:
            obj = self._run_admission(
                obj, "UPDATE" if existing is not None else "CREATE")
        except AdmissionDenied as e:
            self._send_denied(e)
            return
        try:
            self._send(200, self.kube.apply(obj))
        except Conflict as e:
            self._send(409, _status(409, "Conflict", str(e)))

    def do_DELETE(self):  # noqa: N802
        if not self._authed():
            return
        parsed = self._parse()
        if parsed is None:
            return
        api_version, kind, namespace, name, _, _ = parsed
        if name is None:
            self._send(405, _status(405, "MethodNotAllowed", "collection"))
            return
        if not self._check_rbac("delete", api_version, kind, None,
                                name=name, namespace=namespace):
            return
        existing = self.kube.get(api_version, kind, name,
                                 namespace=namespace)
        if existing is not None:
            try:
                self._run_admission(existing, "DELETE")
            except AdmissionDenied as e:
                self._send_denied(e)
                return
        self.kube.delete(api_version, kind, name, namespace=namespace)
        if existing is not None:
            self._send(200, _status(200, "Success", name))
        else:
            self._send(404, _status(404, "NotFound", name))


class MiniApiServer:
    """HTTPS apiserver over a FakeKube store, plus kubeconfig authoring."""

    def __init__(self, kube: FakeKube | None = None,
                 token: str = "test-bearer-token"):
        self.kube = kube or FakeKube()
        self.token = token
        #: extra bearer tokens -> RBAC subjects, e.g.
        #: {"sa-token": {"kind": "ServiceAccount", "name": "tpu-operator",
        #:               "namespace": "tpu-operator-system"}};
        #: enforced against ClusterRole/Binding objects in the store when
        #: rbac_enabled (the admin `token` always bypasses)
        self.token_subjects: dict = {}
        self.rbac_enabled = False
        self._tmp = tempfile.mkdtemp(prefix="miniapi-")
        self.cert_path, self.key_path = make_self_signed_cert(self._tmp)
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        self.httpd.kube = self.kube
        self.httpd.token = token
        self.httpd.owner = self
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_path, self.key_path)
        self.httpd.socket = ctx.wrap_socket(self.httpd.socket,
                                            server_side=True)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="mini-apiserver")

    def start(self) -> "MiniApiServer":
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    @property
    def url(self) -> str:
        return f"https://127.0.0.1:{self.port}"

    def write_kubeconfig(self, path: str, token: str | None = None) -> str:
        with open(self.cert_path, "rb") as f:
            ca_data = base64.b64encode(f.read()).decode()
        cfg = {
            "apiVersion": "v1", "kind": "Config",
            "current-context": "mini",
            "clusters": [{"name": "mini", "cluster": {
                "server": self.url,
                "certificate-authority-data": ca_data}}],
            "contexts": [{"name": "mini", "context": {
                "cluster": "mini", "user": "mini-user"}}],
            "users": [{"name": "mini-user", "user": {
                "token": token if token is not None else self.token}}],
        }
        with open(path, "w") as f:
            yaml.safe_dump(cfg, f)
        return path
