"""Cross-node flight/trace federation e2e (`make fleet-obs-check`).

Two simulated nodes, each with its OWN flight ring served by its own
MetricsServer — node A runs a real CNI ADD (shim → CNI server → VSP
gRPC over real unix sockets), node B serves a real streamed request
through the HTTP ingress → scheduler. Both adopt the SAME caller
traceparent, so `tpuctl fleet trace <trace_id>` must fan out to both
/debug/flight endpoints (bounded concurrency, per-node timeout) and
reassemble ONE parent-linked span tree spanning both nodes; killing
one node must degrade the answer to a partial result, never an error.
"""

import contextlib
import io
import json
import os
import threading
import time

import pytest

from dpu_operator_tpu import tpuctl
from dpu_operator_tpu.cni import CniServer, CniShim
from dpu_operator_tpu.platform import TpuDetector
from dpu_operator_tpu.utils import flight, tracing
from dpu_operator_tpu.utils.metrics import MetricsServer
from dpu_operator_tpu.utils.path_manager import PathManager
from dpu_operator_tpu.vsp import GrpcPlugin, MockTpuVsp, VspServer
from dpu_operator_tpu.workloads import serve

pytestmark = pytest.mark.obs

#: the client's trace: both the CNI ADD and the serve request join it,
#: which is exactly what makes the cross-node tree a single trace_id
TRACE = "ab" * 16
CLIENT_SPAN = "12" * 8
TRACEPARENT = f"00-{TRACE}-{CLIENT_SPAN}-01"


def _env(container="fede2e0001", ifname="net1"):
    return {
        "CNI_COMMAND": "ADD",
        "CNI_CONTAINERID": container,
        "CNI_NETNS": "/var/run/netns/x",
        "CNI_IFNAME": ifname,
        "CNI_ARGS": "K8S_POD_NAMESPACE=default;K8S_POD_NAME=fedpod",
    }


def _conf():
    return {"cniVersion": "0.4.0", "name": "tpunfcni-conf",
            "type": "tpu-cni", "mode": "chip", "deviceID": "chip-1",
            "resourceName": "google.com/tpu"}


def _stream_post(port, body, traceparent):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("POST", "/v1/generate", json.dumps(body),
                     {"Content-Type": "application/json",
                      "traceparent": traceparent})
        resp = conn.getresponse()
        raw = resp.read()
    finally:
        conn.close()
    return [json.loads(line) for line in raw.split(b"\n") if line]


def _wait_for(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while not cond():
        assert time.monotonic() < deadline, "condition never held"
        time.sleep(0.005)


def _tpuctl(argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        tpuctl.main(argv)
    return json.loads(buf.getvalue())


def _tree_rows(tree):
    for span in tree:
        yield span
        yield from _tree_rows(span["children"])


@pytest.fixture
def nodes(short_tmp, monkeypatch):
    """Two nodes' worth of real machinery, one flight ring each."""
    tracing.reset_for_tests()
    ring_a = flight.FlightRecorder(2048)
    ring_b = flight.FlightRecorder(2048)

    # -- node A: VSP server + CNI server over real unix sockets
    pm = PathManager(short_tmp)
    vsp_sock = pm.vendor_plugin_socket()
    pm.ensure_socket_dir(vsp_sock)
    vsp_server = VspServer(MockTpuVsp(), vsp_sock)
    vsp_server.start()
    det = TpuDetector().detection_result(tpu_mode=True,
                                         identifier="fed-tpu")
    plugin = GrpcPlugin(det, path_manager=pm, init_timeout=5.0)
    plugin.start(tpu_mode=True)

    def add(pod_req):
        plugin.create_slice_attachment(
            {"name": f"att-{pod_req.sandbox_id[:8]}", "chip_index": 1})
        return {"cniVersion": pod_req.netconf.cni_version, "ok": True}

    cni_sock = os.path.join(short_tmp, "cni-fed.sock")
    cni_server = CniServer(cni_sock, add_handler=add)
    cni_server.start()

    # ONE CNI ADD joins the client trace (the shim honors an exported
    # TRACEPARENT) while the process-global ring is node A's
    monkeypatch.setenv("TRACEPARENT", TRACEPARENT)
    monkeypatch.setattr(flight, "RECORDER", ring_a)
    resp = CniShim(cni_sock).invoke(_env(), json.dumps(_conf()))
    assert not resp.error

    # -- node B: the decode service's streaming HTTP ingress
    monkeypatch.setattr(flight, "RECORDER", ring_b)
    sched = serve.Scheduler(serve.ServeConfig(
        slots=2, kv_blocks=32, kv_block_size=4, queue_limit=8))
    service = serve.DecodeService(sched, idle_interval_s=0.005)
    service.start()
    port = service.start_http()
    lines = _stream_post(
        port, {"rid": "fed", "prompt_len": 4, "output_len": 3},
        TRACEPARENT)
    assert lines, "the streamed request produced no output"
    _wait_for(lambda: any(e.get("name") == "Completed"
                          for e in ring_b.events(kind="serve")))
    service.stop()

    # each node serves ITS ring on its own metrics endpoint
    srv_a = MetricsServer(host="127.0.0.1", flight_recorder=ring_a)
    srv_a.start()
    srv_b = MetricsServer(host="127.0.0.1", flight_recorder=ring_b)
    srv_b.start()
    addr_a = f"127.0.0.1:{srv_a.port}"
    addr_b = f"127.0.0.1:{srv_b.port}"

    # the operator's rollup names each node's metrics address — the
    # discovery path `tpuctl fleet trace` walks when --nodes is absent
    rollup = {
        "nodes": {"total": 2, "fresh": 2, "stale": 0},
        "staleNodes": [],
        "serveSlots": {"total": 26, "free": 11, "advertisable": 9},
        "freeKvBlocks": 32, "quarantined": {}, "sloBurnRate": {},
        "sloAlerts": [], "watchdogStalls": [],
        "perNode": {"node-a": {"metricsAddr": addr_a},
                    "node-b": {"metricsAddr": addr_b}},
    }
    srv_op = MetricsServer(host="127.0.0.1",
                           debug_handlers={"/debug/fleet":
                                           lambda: rollup})
    srv_op.start()
    try:
        yield {"addr_a": addr_a, "addr_b": addr_b,
               "operator": f"127.0.0.1:{srv_op.port}",
               "srv_b": srv_b}
    finally:
        srv_op.stop()
        srv_a.stop()
        srv_b.stop()
        cni_server.stop()
        plugin.close()
        vsp_server.stop()
        tracing.reset_for_tests()


def test_cross_node_trace_stitch_and_partial_degradation(nodes):
    out = _tpuctl(["fleet", "trace", TRACE,
                   "--operator-addr", nodes["operator"],
                   "--nodes",
                   f"{nodes['addr_a']},{nodes['addr_b']}"])
    assert out["found"] and not out["partial"]
    # both nodes contributed spans of the SAME trace
    assert out["nodes"][nodes["addr_a"]] > 0
    assert out["nodes"][nodes["addr_b"]] > 0
    rows = list(_tree_rows(out["tree"]))
    by_name = {r["name"]: r for r in rows}
    # node A's CNI path: CNI server → VSP, parent-linked across the
    # in-node process boundaries (the shim itself records only to the
    # trace FILE — its span id still shows up as cni.add's parent)
    assert {"cni.add", "vsp.call"} <= set(by_name)
    assert by_name["cni.add"]["node"] == nodes["addr_a"]
    assert by_name["vsp.call"]["parentId"] \
        == by_name["cni.add"]["spanId"]
    # cni.add's parent is the shim's span — never captured in any
    # flight ring — so it surfaces as a root of the stitched tree
    assert by_name["cni.add"]["parentId"]
    assert by_name["cni.add"] in out["tree"]
    # the VSP SERVER span (crossed the gRPC metadata seam) hangs below
    # the client span
    assert by_name["vsp.SliceService.CreateSliceAttachment"][
        "parentId"] == by_name["vsp.call"]["spanId"]
    # node B's serve path: the ingress span plus the scheduler's phase
    # spans, all under the same trace_id
    assert by_name["serve.request"]["node"] == nodes["addr_b"]
    assert any(r["name"].startswith("serve.") and r["kind"] == "serve"
               for r in rows)
    # the non-span flight entries of the trace (FirstToken, Completed)
    # ride along for context
    extras = {e["name"] for e in out["events"]}
    assert "FirstToken" in extras

    # one node dies: the federation degrades to a PARTIAL result with
    # the dead node named — node A's half of the story still renders
    nodes["srv_b"].stop()
    out = _tpuctl(["fleet", "trace", TRACE,
                   "--operator-addr", nodes["operator"],
                   "--nodes",
                   f"{nodes['addr_a']},{nodes['addr_b']}"])
    assert out["found"] and out["partial"]
    assert [u["addr"] for u in out["unreachable"]] \
        == [nodes["addr_b"]]
    rows = list(_tree_rows(out["tree"]))
    assert any(r["name"] == "cni.add" for r in rows)
    assert not any(r["name"] == "serve.request" for r in rows)


def test_fleet_trace_discovers_nodes_through_rollup(nodes):
    # no --nodes: the endpoints come from the rollup's metricsAddr
    out = _tpuctl(["fleet", "trace", TRACE,
                   "--operator-addr", nodes["operator"]])
    assert out["found"] and not out["partial"]
    assert set(out["nodes"]) == {nodes["addr_a"], nodes["addr_b"]}
    names = {r["name"] for r in _tree_rows(out["tree"])}
    assert "cni.add" in names and "serve.request" in names


def test_fleet_top_renders_rollup(nodes):
    out = _tpuctl(["fleet", "top",
                   "--operator-addr", nodes["operator"]])
    assert out["reachable"]
    assert out["nodes"] == {"total": 2, "fresh": 2, "stale": 0}
    assert out["serveSlots"]["advertisable"] == 9
    assert set(out["perNode"]) == {"node-a", "node-b"}


def test_fleet_top_graceful_when_operator_unreachable():
    out = _tpuctl(["fleet", "top",
                   "--operator-addr", "127.0.0.1:1"])
    assert out == {"reachable": False, "error": out["error"]}
