"""Native control-plane agent tests: build, mailbox protocol, wiring
semantics parity with the Python topology model, crash/restart recovery,
and GoogleTpuVsp over the NativeIciDataplane end to end."""

import os
import subprocess

import pytest

from dpu_operator_tpu.ici import SliceTopology
from dpu_operator_tpu.platform.platform import FakePlatform
from dpu_operator_tpu.vsp.google import GoogleTpuVsp
from dpu_operator_tpu.vsp.native_dp import (AgentClient, AgentError,
                                            AgentProcess, NativeIciDataplane)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
AGENT_BIN = os.path.join(REPO, "native", "build", "tpu_cp_agent")


@pytest.fixture(scope="session")
def agent_binary():
    subprocess.run(["make", "-C", os.path.join(REPO, "native")], check=True,
                   capture_output=True)
    return AGENT_BIN


@pytest.fixture
def agent(agent_binary, short_tmp):
    proc = AgentProcess(agent_binary, short_tmp + "/tpucp.sock",
                        state_file=short_tmp + "/tpucp.state",
                        dev_dir=short_tmp, allow_regular_dev=True)
    proc.start()
    client = AgentClient(proc.socket_path)
    yield proc, client
    client.close()
    proc.stop()


def _fake_accel(tmp, n):
    for i in range(n):
        open(f"{tmp}/accel{i}", "w").close()


def test_init_and_enum_match_python_topology(agent, short_tmp):
    _, client = agent
    info = client.init("v5e-16")
    topo = SliceTopology("v5e-16")
    assert info["num_chips"] == 16
    assert info["shape"][:2] == tuple(topo.shape)
    chips = client.enumerate()
    assert len(chips) == 16
    for c, pc in zip(chips, topo.chips):
        assert c["coords"][:2] == tuple(pc.coords)
        assert c["nports"] == len(topo.links_from(pc.index))


def test_3d_topology_ports(agent):
    _, client = agent
    client.init("v5p-8")  # 2x2x2 cube: every dim extent 2 → 3 ports/chip
    chips = client.enumerate()
    assert all(c["nports"] == 3 for c in chips)
    topo = SliceTopology("v5p-8")
    assert all(c["nports"] == len(topo.links_from(c["index"]))
               for c in chips)


def test_attach_detach_and_link_state(agent):
    _, client = agent
    client.init("v5e-4")  # 2x2
    client.attach(0)  # all torus ports
    states = client.link_state(0)
    assert states and all(s["wired"] and s["up"] for s in states)
    client.detach(0)
    assert all(not s["wired"] for s in client.link_state(0))


def test_attach_invalid_port_rejected(agent):
    _, client = agent
    client.init("v5e-4")
    with pytest.raises(AgentError):
        client.attach(0, ["z+"])  # 2D slice has no z axis
    with pytest.raises(AgentError):
        client.attach(99)


def test_attach_requires_topology(agent):
    _, client = agent
    with pytest.raises(AgentError):
        client.attach(0)


def test_wire_nf_duplicate_and_missing(agent):
    _, client = agent
    client.init("v5e-4")
    client.wire_nf("nf-a", "nf-b")
    with pytest.raises(AgentError):
        client.wire_nf("nf-a", "nf-b")
    client.unwire_nf("nf-a", "nf-b")
    with pytest.raises(AgentError):
        client.unwire_nf("nf-a", "nf-b")


def test_health_from_dev_dir(agent, short_tmp):
    _, client = agent
    _fake_accel(short_tmp, 2)
    client.init("v5e-4")
    chips = client.enumerate()
    assert [c["healthy"] for c in chips] == [True, True, False, False]


def test_regular_dev_unhealthy_without_optin(agent_binary, short_tmp):
    """ADVICE r1: without --allow-regular-dev a regular file standing at
    accel<N> must not pass the health probe (stale-file hazard)."""
    proc = AgentProcess(agent_binary, short_tmp + "/strict.sock",
                        dev_dir=short_tmp)  # no allow_regular_dev
    proc.start()
    client = AgentClient(short_tmp + "/strict.sock")
    try:
        _fake_accel(short_tmp, 2)
        client.init("v5e-4")
        chips = client.enumerate()
        assert [c["healthy"] for c in chips] == [False] * 4
    finally:
        client.close()
        proc.stop()


def test_state_survives_restart(agent_binary, short_tmp):
    sock = short_tmp + "/a.sock"
    state = short_tmp + "/a.state"
    proc = AgentProcess(agent_binary, sock, state_file=state)
    proc.start()
    client = AgentClient(sock)
    client.init("v5e-8")
    client.attach(3)
    client.wire_nf("in0", "out0")
    client.set_link(3, "x+", up=False)  # injected fault must survive too
    client.close()
    proc.stop()

    proc2 = AgentProcess(agent_binary, sock, state_file=state)
    proc2.start()
    client2 = AgentClient(sock)
    chips = client2.enumerate()
    assert len(chips) == 8
    assert chips[3]["attached"] is True
    with pytest.raises(AgentError):
        client2.wire_nf("in0", "out0")  # wire persisted → duplicate
    # the fault state replayed: the dark port is still dark (and still
    # reported faulted to the device plugin), its neighbors still up
    states = {p["port"]: p for p in client2.link_state(3)}
    assert states["x+"]["fault"] and not states["x+"]["up"]
    assert not states["y+"]["fault"]
    client2.close()
    proc2.stop()


def test_google_vsp_over_native_dataplane(agent, short_tmp):
    """End to end: GoogleTpuVsp drives the native agent through the
    IciDataplane seam (init → attach via slice attachment → NF wire)."""
    _, client = agent
    _fake_accel(short_tmp, 4)
    plat = FakePlatform(accelerator_type="v5litepod-4",
                        accel=[f"{short_tmp}/accel{i}" for i in range(4)])
    vsp = GoogleTpuVsp(plat, dataplane=NativeIciDataplane(client))
    vsp.init({"tpu_mode": True})
    att = vsp.create_slice_attachment({"name": "host0-1", "chip_index": 1})
    assert att["ici_ports"]  # ports filled from topology
    states = client.link_state(1)
    assert any(s["wired"] for s in states)
    vsp.create_network_function({"input": "nf-i", "output": "nf-o"})
    with pytest.raises(AgentError):
        client.wire_nf("nf-i", "nf-o")  # already wired via the VSP
    vsp.delete_slice_attachment({"name": "host0-1"})
    assert all(not s["wired"] for s in client.link_state(1))


def test_v5p_32_wiring_parity(agent):
    """Ladder config 4: v5p-32 (2x4x4 torus) — native agent and Python
    model agree on every chip's port count."""
    _, client = agent
    info = client.init("v5p-32")
    assert info["num_chips"] == 32
    topo = SliceTopology("v5p-32")
    assert info["shape"] == tuple(topo.shape)
    chips = client.enumerate()
    for c, pc in zip(chips, topo.chips):
        assert c["coords"] == tuple(pc.coords)
        assert c["nports"] == len(topo.links_from(pc.index))


def test_fault_injection_link_down_marks_chip_unhealthy(agent, short_tmp):
    """SURVEY.md §5 gap filled: inject a link fault, watch it surface as
    device unhealthiness so Allocate refuses the chip."""
    _, client = agent
    _fake_accel(short_tmp, 4)
    plat = FakePlatform(accelerator_type="v5litepod-4",
                        accel=[f"{short_tmp}/accel{i}" for i in range(4)])
    vsp = GoogleTpuVsp(plat, dataplane=NativeIciDataplane(client))
    vsp.init({"tpu_mode": True})
    vsp.create_slice_attachment({"name": "host0-1", "chip_index": 1})
    assert vsp.get_devices({})["devices"]["chip-1"]["healthy"] is True

    ports = client.link_state(1)
    client.set_link(1, ports[0]["port"], up=False)
    states = {p["port"]: p for p in client.link_state(1)}
    assert states[ports[0]["port"]]["up"] is False
    assert vsp.get_devices({})["devices"]["chip-1"]["healthy"] is False
    # other chips unaffected
    assert vsp.get_devices({})["devices"]["chip-0"]["healthy"] is True

    client.set_link(1, ports[0]["port"], up=True)
    assert vsp.get_devices({})["devices"]["chip-1"]["healthy"] is True


def test_fault_injection_invalid_port_rejected(agent):
    _, client = agent
    client.init("v5e-4")
    with pytest.raises(AgentError):
        client.set_link(0, "z+", up=False)


def test_link_fault_survives_restart(agent_binary, short_tmp):
    sock = short_tmp + "/f.sock"
    state = short_tmp + "/f.state"
    proc = AgentProcess(agent_binary, sock, state_file=state)
    proc.start()
    client = AgentClient(sock)
    client.init("v5e-4")
    client.attach(0)
    client.set_link(0, "x+", up=False)
    client.close()
    proc.stop()
    proc2 = AgentProcess(agent_binary, sock, state_file=state)
    proc2.start()
    client2 = AgentClient(sock)
    states = {p["port"]: p for p in client2.link_state(0)}
    assert states["x+"]["up"] is False
    client2.close()
    proc2.stop()


def test_reinit_same_topology_preserves_state(agent, short_tmp):
    """A restarting daemon re-runs VSP Init -> agent Init while pods
    still hold live wiring: same-topology re-Init must be idempotent,
    NOT clear the db — erased wires would orphan every running NF and
    hollow out the daemon's journal-vs-dataplane recovery."""
    _, client = agent
    client.init("v5e-8")
    client.attach(0, ["x+"])
    client.wire_nf("ici-0-x+", "ici-1-x-")
    info = client.init("v5e-8")  # the daemon came back
    assert info["num_chips"] == 8
    assert ("ici-0-x+", "ici-1-x-") in client.list_wires()
    chips = client.enumerate()
    assert chips[0]["attached"] is True
    # a genuine reshape still resets
    client.init("v5e-4")
    assert client.list_wires() == []
    assert client.enumerate()[0]["attached"] is False
