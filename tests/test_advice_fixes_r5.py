"""Regression tests for round-4 ADVICE findings: the bootstrap
diagnostic points at the job spec (not the device plugin), SIGTERM
handlers only set the stop event (no lock/join inside a signal handler),
the host-side topology fetch single-flights its dial, a truncated slice
join is surfaced as degraded, and DevicePlugin.stop() wakes refresh
barrier waiters immediately."""

import threading
import time

import pytest

from dpu_operator_tpu.daemon.daemon import Daemon
from dpu_operator_tpu.daemon.hostsidemanager import HostSideManager
from dpu_operator_tpu.daemon import slicejoin


def test_bootstrap_error_blames_the_job_spec():
    """ADVICE r4 #1: TPU_WORKER_COUNT/TPU_COORDINATOR_ADDRESS come from
    the JOB spec; the old message sent operators to the device plugin."""
    from dpu_operator_tpu.workloads.bootstrap import distributed_env
    with pytest.raises(RuntimeError) as ei:
        distributed_env({"TPU_WORKER_COUNT": "4"})
    msg = str(ei.value)
    assert "JOB" in msg
    assert "device plugin" not in msg
    # the operator-exported vars are named so the reader learns the split
    assert "TPU_WORKER_ID" in msg


def test_request_stop_is_safe_while_mgr_stop_lock_is_held():
    """ADVICE r4 #2: a signal landing while the main thread holds
    _mgr_stop_lock must not deadlock — the handler path (request_stop)
    only sets the event."""
    d = Daemon.__new__(Daemon)
    d._stop = threading.Event()
    d._mgr_stop_lock = threading.Lock()
    d._mgr_stopped = False
    d.manager = object()
    with d._mgr_stop_lock:  # the serve-loop exit path owns the lock
        t = threading.Thread(target=d.request_stop)
        t.start()
        t.join(timeout=2)
        assert not t.is_alive(), "request_stop blocked on _mgr_stop_lock"
    assert d._stop.is_set()


def test_daemon_main_handlers_use_request_stop():
    """The installed SIGTERM/SIGINT handlers must route through the
    handler-safe entry point, not stop()."""
    import inspect
    import dpu_operator_tpu.daemon.__main__ as main_mod
    src = inspect.getsource(main_mod)
    assert "request_stop()" in src
    assert "lambda *_: daemon.stop()" not in src


def test_topology_fetch_single_flights_concurrent_callers(monkeypatch):
    """ADVICE r4 #3: concurrent callers must not double-dial — exactly
    one pays the deadline, the rest serve the cached topology."""
    calls = []

    def slow_fetch(addr, timeout=2.0):
        calls.append(addr)
        time.sleep(0.2)
        return {"topology": "v5e-4"}

    monkeypatch.setattr(slicejoin, "fetch_slice_info", slow_fetch)
    m = HostSideManager.__new__(HostSideManager)
    m._slice_topology = None
    m._topology_ok_at = 0.0
    m._topology_attempt_at = -1e9
    m._topology_lock = threading.Lock()
    m._tpu_daemon_addr = ("127.0.0.1", 9999)

    results = [None] * 4
    threads = [threading.Thread(
        target=lambda i=i: results.__setitem__(
            i, m._fetch_slice_topology())) for i in range(4)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5)
    elapsed = time.monotonic() - t0
    assert len(calls) == 1, f"double-dialed: {len(calls)} fetches"
    # losers returned the cache immediately instead of queueing behind
    # the dial (4 serialized dials would be >= 0.8 s)
    assert elapsed < 0.6
    # the winner cached the result for everyone after it
    assert m._fetch_slice_topology().topology == "v5e-4"
    assert len(calls) == 1  # fresh: no new dial


def test_slice_join_truncation_is_surfaced(monkeypatch, caplog):
    """ADVICE r4 #4: a walk stopped at max_slices must not report a
    complete-looking group."""
    graph = {f"10.0.0.{i}:1": {"topology": "v5e-4",
                               "dcn_peers": [f"10.0.0.{i + 1}:1"]}
             for i in range(6)}
    graph["10.0.0.5:1"]["dcn_peers"] = []  # end of the chain

    monkeypatch.setattr(slicejoin, "fetch_slice_info",
                        lambda addr, timeout=5.0: graph[addr])
    import logging
    with caplog.at_level(logging.WARNING,
                         logger="dpu_operator_tpu.daemon.slicejoin"):
        result = slicejoin.join_slices("10.0.0.0:1", max_slices=3)
    assert len(result.members) == 3
    assert result.truncated is True
    assert result.degraded is True  # collectives must not trust a prefix
    assert any("truncated" in r.message for r in caplog.records)
    # an untruncated walk stays clean
    small = slicejoin.join_slices("10.0.0.4:1", max_slices=64)
    assert small.truncated is False
    assert small.degraded is False


def test_device_plugin_stop_wakes_refresh_waiters(tmp_path):
    """ADVICE r4 #5: stop() must notify _refresh_cond so a blocked
    refresh() barrier returns immediately, not after its full timeout."""
    from dpu_operator_tpu.deviceplugin.server import DevicePlugin
    from dpu_operator_tpu.utils.path_manager import PathManager

    class _Handler:
        def get_devices(self):
            return {}

    dp = DevicePlugin(_Handler(), path_manager=PathManager(str(tmp_path)))
    dp._active_streams = 1  # a stream exists but never serves the gen
    done = {}

    def blocked_refresh():
        t0 = time.monotonic()
        done["result"] = dp.refresh(wait=10.0)
        done["elapsed"] = time.monotonic() - t0

    t = threading.Thread(target=blocked_refresh)
    t.start()
    time.sleep(0.2)  # let it enter wait_for
    dp.stop()
    t.join(timeout=3)
    assert not t.is_alive(), "refresh() still blocked after stop()"
    assert done["elapsed"] < 2.0, f"waited {done['elapsed']:.1f}s"
    assert done["result"] is False
