"""Workload checkpoint/resume tests: save sharded train state, restore
onto a differently-factored mesh (pod rescheduled elsewhere in the slice)."""

import jax
import numpy as np
import pytest

from dpu_operator_tpu.workloads import (TransformerConfig,
                                        make_example_batch, make_mesh,
                                        make_train_step)
from dpu_operator_tpu.workloads.checkpoint import TrainCheckpointer


@pytest.fixture
def cfg():
    return TransformerConfig(n_layers=1, d_model=64, n_heads=4, d_ff=128,
                             max_seq=16, vocab=64)


def _train(cfg, mesh, steps=3):
    step, init_state, place = make_train_step(cfg, mesh)
    params, opt = init_state(jax.random.key(0))
    batch = place(make_example_batch(cfg, batch=4, seq=16))
    loss = None
    for _ in range(steps):
        params, opt, loss = step(params, opt, batch)
    return step, params, opt, batch, float(loss)


def test_save_restore_roundtrip(cfg, tmp_path):
    mesh = make_mesh(("data", "model"), axis_sizes=(2, 4))
    step, params, opt, batch, loss3 = _train(cfg, mesh)
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    ckpt.save(3, params, opt)
    assert ckpt.latest_step() == 3

    # fresh state on the same mesh; restore must continue the run exactly
    _, init_state, _ = make_train_step(cfg, mesh)
    p0, o0 = init_state(jax.random.key(1))
    p, o, step_no = ckpt.restore(p0, o0)
    assert step_no == 3
    np.testing.assert_allclose(
        np.asarray(p["embed"], np.float32),
        np.asarray(params["embed"], np.float32))
    p2, o2, loss4a = step(p, o, batch)
    _, _, loss4b = step(params, opt, batch)
    assert abs(float(loss4a) - float(loss4b)) < 1e-5
    ckpt.close()


def test_restore_onto_different_mesh_factoring(cfg, tmp_path):
    mesh_a = make_mesh(("data", "model"), axis_sizes=(2, 4))
    _, params, opt, _, _ = _train(cfg, mesh_a)
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    ckpt.save(1, params, opt)

    mesh_b = make_mesh(("data", "model"), axis_sizes=(4, 2))
    step_b, init_state_b, place_b = make_train_step(cfg, mesh_b)
    p0, o0 = init_state_b(jax.random.key(2))
    p, o, _ = ckpt.restore(p0, o0)
    wqkv = p["layers"][0]["wqkv"]
    assert wqkv.sharding.mesh.shape["model"] == 2  # re-sharded
    batch = place_b(make_example_batch(cfg, batch=4, seq=16))
    _, _, loss = step_b(p, o, batch)
    assert np.isfinite(float(loss))
    ckpt.close()


def test_restore_empty_dir_raises(cfg, tmp_path):
    ckpt = TrainCheckpointer(str(tmp_path / "empty"))
    with pytest.raises(FileNotFoundError):
        ckpt.restore({}, {})
    ckpt.close()


def test_checkpoint_moe_params_roundtrip(tmp_path):
    """MoE trees (expert-stacked weights, ep shardings) checkpoint and
    restore like dense ones — an NF pod in ep mode resumes."""
    import jax
    import numpy as np

    from dpu_operator_tpu.workloads import (TransformerConfig, make_mesh,
                                            make_train_step)
    from dpu_operator_tpu.workloads.checkpoint import TrainCheckpointer

    cfg = TransformerConfig(n_layers=2, d_model=32, n_heads=4, d_ff=64,
                            max_seq=32, vocab=64, moe_experts=8)
    mesh = make_mesh(("data", "model"), axis_sizes=(2, 4))
    _, init_state, _ = make_train_step(cfg, mesh)
    params, opt = init_state(jax.random.key(0))

    ckpt = TrainCheckpointer(str(tmp_path / "moe-ckpt"))
    ckpt.save(3, params, opt)
    p2, o2, step = ckpt.restore(params, opt)
    assert step == 3
    ckpt.close()
    w1 = params["layers"][1]["moe"]["w1"]
    np.testing.assert_array_equal(np.asarray(w1, np.float32),
                                  np.asarray(p2["layers"][1]["moe"]["w1"],
                                             np.float32))


def test_checkpoint_pipeline_params_roundtrip(tmp_path):
    """Stage-stacked pipeline params (P("pipe") shardings) survive
    save/restore."""
    import jax
    import numpy as np

    from dpu_operator_tpu.workloads import TransformerConfig, make_mesh
    from dpu_operator_tpu.workloads import pipeline
    from dpu_operator_tpu.workloads.checkpoint import TrainCheckpointer

    cfg = TransformerConfig(n_layers=4, d_model=32, n_heads=4, d_ff=64,
                            max_seq=16, vocab=64)
    mesh = make_mesh(("pipe", "data"), axis_sizes=(4, 2))
    _, init_state, _ = pipeline.make_pipeline_train_step(cfg, mesh,
                                                        n_micro=4)
    params, opt = init_state(jax.random.key(0))
    ckpt = TrainCheckpointer(str(tmp_path / "pp-ckpt"))
    ckpt.save(1, params, opt)
    p2, _, _ = ckpt.restore(params, opt)
    ckpt.close()
    np.testing.assert_array_equal(
        np.asarray(params["stages"]["wqkv"], np.float32),
        np.asarray(p2["stages"]["wqkv"], np.float32))


def test_multislice_checkpoint_resumes_on_single_slice(cfg, tmp_path):
    """Slice-loss failover: train state saved on a 2-slice (dcn, data,
    model) mesh restores onto a SINGLE-slice mesh half the size — the
    workload half of the multi-slice degrade story (a dead peer degrades
    the join, daemon/slicejoin.py; the survivor resumes from
    checkpoint)."""
    import numpy as np

    from dpu_operator_tpu.workloads import (make_example_batch, make_mesh,
                                            make_train_step)
    from dpu_operator_tpu.workloads.checkpoint import TrainCheckpointer

    big = make_mesh(("dcn", "data", "model"), axis_sizes=(2, 2, 2))
    step, init_state, place = make_train_step(cfg, big)
    params, opt = init_state(jax.random.key(0))
    params, opt, _ = step(params, opt,
                          place(make_example_batch(cfg, batch=8)))
    ckpt = TrainCheckpointer(str(tmp_path / "ms"))
    ckpt.save(1, params, opt)

    # the surviving slice: 4 devices, no dcn axis
    small = make_mesh(("data", "model"), devices=jax.devices()[:4],
                      axis_sizes=(2, 2))
    sstep, sinit, splace = make_train_step(cfg, small)
    sparams, sopt = sinit(jax.random.key(9))
    rparams, ropt, _ = ckpt.restore(sparams, sopt)
    # numerics carried over exactly (params replicate across dcn)
    np.testing.assert_array_equal(
        np.asarray(jax.tree_util.tree_leaves(params)[0]),
        np.asarray(jax.tree_util.tree_leaves(rparams)[0]))
    # and training continues on the degraded mesh
    _, _, loss = sstep(rparams, ropt,
                       splace(make_example_batch(cfg, batch=4)))
    assert float(loss) > 0
