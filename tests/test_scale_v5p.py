"""Operator-path scale check on a v5p-256 slice (VERDICT r4 #8 /
SURVEY §7 hard-part (d)): 256 chips x 6 ICI ports = 1,536 port devices
advertised through the real device-plugin wire (v1beta1 gRPC), with
bounded ListAndWatch and GetPreferredAllocation latency. The bounds are
generous for shared CI hosts — their job is catching accidental
quadratic blowups in the advertisement or selection paths, not
micro-benchmarking."""

import time

import pytest

from dpu_operator_tpu.daemon.device_handler import IciPortDeviceHandler
from dpu_operator_tpu.deviceplugin import DevicePlugin, FakeKubelet
from dpu_operator_tpu.deviceplugin.server import preferred_ici_ports
from dpu_operator_tpu.ici import SliceTopology
from dpu_operator_tpu.utils.path_manager import PathManager

TOPOLOGY = "v5p-256"
TOTAL_PORTS = 1536


class _FullSliceHandler:
    """Merge every host's IciPortDeviceHandler view: the full slice's
    port inventory through one plugin — the worst case one controller
    can face (64 hosts x 24 ports)."""

    def __init__(self, topo: SliceTopology):
        self._handlers = [
            IciPortDeviceHandler(lambda h=h: (topo, h))
            for h in range(topo.num_hosts)]

    def get_devices(self) -> dict:
        devs: dict = {}
        for handler in self._handlers:
            devs.update(handler.get_devices())
        return devs


@pytest.fixture(scope="module")
def topo():
    return SliceTopology(TOPOLOGY)


def test_v5p_256_port_inventory_shape(topo):
    assert topo.num_chips == 256
    assert topo.num_hosts == 64
    handler = _FullSliceHandler(topo)
    t0 = time.perf_counter()
    devs = handler.get_devices()
    enum_s = time.perf_counter() - t0
    assert len(devs) == TOTAL_PORTS
    # every port knows its chip + 3D coords (the selection inputs)
    sample = next(iter(devs.values()))
    assert len(sample["coords"]) == 3
    assert enum_s < 2.0, f"port enumeration took {enum_s:.2f}s"


def test_v5p_256_list_and_watch_and_allocation_latency(topo, short_tmp):
    pm = PathManager(short_tmp)
    kubelet = FakeKubelet(pm)
    kubelet.start()
    recent = [f"chip-{i}" for i in (17, 42)]  # a pod's chip allocation

    def preferred(available, must, size, devices):
        return preferred_ici_ports(available, must, size, devices,
                                   recent_chips=list(recent))

    plugin = DevicePlugin(
        _FullSliceHandler(topo), resource="google.com/ici-port",
        path_manager=pm, poll_interval=5.0, preferred_fn=preferred)
    plugin.start()
    try:
        t0 = time.perf_counter()
        plugin.register_with_kubelet()
        assert kubelet.wait_for_devices("google.com/ici-port",
                                        TOTAL_PORTS, timeout=15.0)
        list_s = time.perf_counter() - t0
        assert list_s < 10.0, \
            f"ListAndWatch took {list_s:.2f}s for {TOTAL_PORTS} devices"

        # pod admission: pick 2 ports aligned with the pod's chips
        t0 = time.perf_counter()
        _, ids = kubelet.allocate_preferred("google.com/ici-port", 2)
        pick2_s = time.perf_counter() - t0
        assert pick2_s < 5.0, f"2-port admission took {pick2_s:.2f}s"
        assert len(ids) == 2
        # affinity held even at 1,536 devices
        assert {int(p.split("-")[1]) for p in ids} == {17, 42}

        # a whole host's worth of ports in one request (24 = the largest
        # single-pod ask a v5p host can serve)
        t0 = time.perf_counter()
        _, ids24 = kubelet.allocate_preferred("google.com/ici-port", 24)
        pick24_s = time.perf_counter() - t0
        assert pick24_s < 5.0, f"24-port admission took {pick24_s:.2f}s"
        assert len(set(ids24)) == 24
        assert not set(ids24) & set(ids)  # kubelet never double-books
    finally:
        plugin.stop()
        kubelet.stop()


def test_v5p_256_preferred_selection_is_subquadratic(topo):
    """Direct selection-path timing at full inventory: 128 successive
    picks (a busy admission burst) stay bounded."""
    handler = _FullSliceHandler(topo)
    devices = handler.get_devices()
    available = sorted(devices)
    t0 = time.perf_counter()
    for i in range(128):
        picked = preferred_ici_ports(
            available, [], 6, devices,
            recent_chips=[f"chip-{(i * 4) % 256}"])
        assert len(picked) == 6
    burst_s = time.perf_counter() - t0
    assert burst_s < 5.0, f"128 picks took {burst_s:.2f}s"
