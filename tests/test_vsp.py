"""VSP seam tests: real gRPC over a real unix socket.

Reference analog: MockVsp serving on the real socket path
(mock-vsp/mockvsp.go:39-50) driven through the GrpcPlugin client with Init
retry (vendorplugin.go:82-115), plus GoogleTpuVsp behavior on a fake platform.
"""

import threading

import pytest

from dpu_operator_tpu.platform import FakePlatform, TpuDetector
from dpu_operator_tpu.utils.path_manager import PathManager
from dpu_operator_tpu.vsp import (
    DebugIciDataplane,
    GoogleTpuVsp,
    GrpcPlugin,
    MockTpuVsp,
    VspServer,
)
from dpu_operator_tpu.vsp.google import accelerator_type_to_topology


@pytest.fixture
def pm(short_tmp):
    # unix socket paths are capped at ~107 chars; pytest's tmp_path nests too
    # deep, so socket tests use a short /tmp dir (see conftest short_tmp)
    return PathManager(short_tmp)


def _serve(impl, pm):
    sock = pm.vendor_plugin_socket()
    pm.ensure_socket_dir(sock)
    server = VspServer(impl, sock)
    server.start()
    return server


def _plugin(pm, tpu_mode=True):
    det = TpuDetector().detection_result(tpu_mode=tpu_mode,
                                         identifier="test-tpu")
    return GrpcPlugin(det, path_manager=pm, init_timeout=5.0)


def test_mock_vsp_init_and_devices(pm):
    mock = MockTpuVsp()
    server = _serve(mock, pm)
    try:
        plugin = _plugin(pm)
        ip, port = plugin.start(tpu_mode=True)
        assert (ip, port) == ("127.0.0.1", 50051)
        assert mock.init_requests[0]["tpu_mode"] is True
        devices = plugin.get_devices()
        assert len(devices) == 4  # v5e-4 mock slice
        assert devices["chip-0"]["healthy"]
        plugin.set_num_chips(2)
        assert len(plugin.get_devices()) == 2
        plugin.close()
    finally:
        server.stop()


def test_init_retries_until_server_up(pm):
    """The daemon dials before the VSP container is up; Init must retry
    (vendorplugin.go:82-115)."""
    plugin = _plugin(pm)
    result = {}

    def connect():
        result["ipport"] = plugin.start(tpu_mode=True)

    t = threading.Thread(target=connect)
    t.start()
    # start the server ~after the first dial attempts failed
    import time
    time.sleep(0.5)
    server = _serve(MockTpuVsp(), pm)
    t.join(timeout=10)
    try:
        assert result["ipport"] == ("127.0.0.1", 50051)
    finally:
        plugin.close()
        server.stop()


def test_init_timeout_when_no_server(pm):
    plugin = _plugin(pm)
    plugin.init_timeout = 0.5
    with pytest.raises(TimeoutError):
        plugin.start(tpu_mode=True)
    plugin.close()


def test_slice_attachment_roundtrip(pm):
    mock = MockTpuVsp()
    server = _serve(mock, pm)
    try:
        plugin = _plugin(pm)
        plugin.start(tpu_mode=True)
        att = plugin.create_slice_attachment(
            {"name": "host0-1", "chip_index": 1, "topology": "v5e-4"})
        assert att["name"] == "host0-1"
        assert "host0-1" in mock.slice_attachments
        plugin.delete_slice_attachment("host0-1")
        assert "host0-1" not in mock.slice_attachments
        plugin.create_network_function("att-a", "att-b")
        assert mock.network_functions == [("att-a", "att-b")]
        plugin.close()
    finally:
        server.stop()


# -- GoogleTpuVsp (in-process, no gRPC needed) --------------------------------

def test_accelerator_type_mapping():
    assert accelerator_type_to_topology("v5litepod-16") == "v5e-16"
    assert accelerator_type_to_topology("v5p-32") == "v5p-32"
    assert accelerator_type_to_topology("v4-8") == "v4-8"
    with pytest.raises(ValueError):
        accelerator_type_to_topology("gpu-8")


def test_google_vsp_tpu_mode_devices():
    platform = FakePlatform(accel=[f"/dev/accel{i}" for i in range(4)],
                            accelerator_type="v5litepod-4")
    dp = DebugIciDataplane()
    vsp = GoogleTpuVsp(platform, dataplane=dp)
    resp = vsp.init({"tpu_mode": True, "tpu_identifier": "x"})
    assert resp["port"] == 50151
    assert dp.events[0] == ("init", "v5e-4")
    devs = vsp.get_devices({})["devices"]
    assert set(devs) == {f"chip-{i}" for i in range(4)}
    # fake /dev/accel* paths are not real chardevs → unhealthy
    assert devs["chip-0"]["healthy"] is False
    assert devs["chip-3"]["coords"] == [1, 1]  # 2x2 slice corner


def test_google_vsp_host_mode_devices():
    from dpu_operator_tpu.platform import PciDevice
    platform = FakePlatform(pci=[
        PciDevice(address="0000:00:04.0", vendor_id="1ae0",
                  device_id="0062")])
    vsp = GoogleTpuVsp(platform)
    vsp.init({"tpu_mode": False})
    devs = vsp.get_devices({})["devices"]
    assert list(devs) == ["0000:00:04.0"]
    assert devs["0000:00:04.0"]["healthy"] is True


def test_host_mode_dual_function_dedups_by_serial():
    """VERDICT r2 #4: a dual-function endpoint (one chip, two PCI
    functions sharing a PCIe serial) must advertise as ONE schedulable
    device (reference: netsec-accelerator.go:36-54)."""
    from dpu_operator_tpu.platform import PciDevice
    platform = FakePlatform(pci=[
        PciDevice(address="0000:5e:00.0", vendor_id="1ae0",
                  device_id="0062", serial="00-11-22-33-44-55-66-77"),
        PciDevice(address="0000:5e:00.1", vendor_id="1ae0",
                  device_id="0062", serial="00-11-22-33-44-55-66-77"),
        PciDevice(address="0000:af:00.0", vendor_id="1ae0",
                  device_id="0062", serial="aa-bb-cc-dd-ee-ff-00-11"),
    ])
    vsp = GoogleTpuVsp(platform)
    vsp.init({"tpu_mode": False})
    devs = vsp.get_devices({})["devices"]
    assert set(devs) == {"0000:5e:00.0", "0000:af:00.0"}
    first = devs["0000:5e:00.0"]
    assert first["functions"] == ["0000:5e:00.0", "0000:5e:00.1"]
    assert first["serial"] == "00-11-22-33-44-55-66-77"
    # stable chip numbering is keyed by serial, not address
    assert first["chip_index"] == 0
    assert devs["0000:af:00.0"]["chip_index"] == 1


def test_host_mode_failed_probe_surfaces_unhealthy():
    """VERDICT r2 #4/#5: host-side health must come from a real probe —
    a dead config-space read (surprise removal) flips the chip Unhealthy,
    including when only a secondary function dies."""
    from dpu_operator_tpu.platform import PciDevice
    platform = FakePlatform(pci=[
        PciDevice(address="0000:5e:00.0", vendor_id="1ae0",
                  device_id="0062", serial="s-1"),
        PciDevice(address="0000:5e:00.1", vendor_id="1ae0",
                  device_id="0062", serial="s-1"),
    ])
    vsp = GoogleTpuVsp(platform)
    vsp.init({"tpu_mode": False})
    assert vsp.get_devices({})["devices"]["0000:5e:00.0"]["healthy"] is True

    platform.set_device_alive("0000:5e:00.1", False)
    assert vsp.get_devices({})["devices"]["0000:5e:00.0"]["healthy"] is False

    platform.set_device_alive("0000:5e:00.1", True)
    platform.set_device_alive("0000:5e:00.0", False)
    assert vsp.get_devices({})["devices"]["0000:5e:00.0"]["healthy"] is False


def test_google_vsp_slice_attachment_programs_dataplane():
    platform = FakePlatform(accel=["/dev/accel0", "/dev/accel1"],
                            accelerator_type="v5litepod-4")
    dp = DebugIciDataplane()
    vsp = GoogleTpuVsp(platform, dataplane=dp)
    vsp.init({"tpu_mode": True})
    att = vsp.create_slice_attachment({"name": "host0-1", "chip_index": 1})
    assert att["ici_ports"]  # derived from topology when not given
    assert ("attach", 1, tuple(att["ici_ports"])) in dp.events
    vsp.delete_slice_attachment({"name": "host0-1"})
    assert ("detach", 1) in dp.events


def test_google_vsp_rejects_bad_attachment_name():
    vsp = GoogleTpuVsp(FakePlatform())
    with pytest.raises(ValueError, match="attachment name"):
        vsp.create_slice_attachment({"name": "bogus"})
