"""Utils tests.

Reference analog: internal/utils/filesystem_mode_detector_test.go (afero
MemMapFs probe of /run/ostree-booted incl. permission-denied) and
path_manager flavour-dependent CNI dirs.
"""

import os

import pytest

from dpu_operator_tpu.utils import FilesystemModeDetector, FsMode, PathManager
from dpu_operator_tpu.utils.cluster_environment import (
    ClusterEnvironment,
    Flavour,
)


def test_fs_mode_rpm_when_absent(tmp_path):
    assert FilesystemModeDetector(str(tmp_path)).detect_mode() == FsMode.RPM


def test_fs_mode_ostree_when_present(tmp_path):
    os.makedirs(tmp_path / "run", exist_ok=True)
    (tmp_path / "run/ostree-booted").write_text("")
    assert FilesystemModeDetector(str(tmp_path)).detect_mode() == FsMode.OSTREE


@pytest.mark.skipif(os.geteuid() == 0, reason="root bypasses permissions")
def test_fs_mode_permission_denied(tmp_path):
    os.makedirs(tmp_path / "run", exist_ok=True)
    probe = tmp_path / "run/ostree-booted"
    probe.write_text("")
    probe.chmod(0o000)
    with pytest.raises(PermissionError):
        FilesystemModeDetector(str(tmp_path)).detect_mode()


def test_path_manager_flavour_dirs(tmp_path):
    pm = PathManager(str(tmp_path))
    assert pm.cni_host_dir("openshift").endswith("var/lib/cni/bin")
    assert pm.cni_host_dir("microshift").endswith("opt/cni/bin")
    assert pm.vendor_plugin_socket().startswith(str(tmp_path))


def test_ensure_socket_dir(tmp_path):
    pm = PathManager(str(tmp_path))
    sock = pm.vendor_plugin_socket()
    pm.ensure_socket_dir(sock)
    assert os.path.isdir(os.path.dirname(sock))


def test_flavour_microshift(kube):
    kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                 "metadata": {"name": "microshift-version",
                              "namespace": "kube-public"}})
    assert ClusterEnvironment(kube).flavour() == Flavour.MICROSHIFT


def test_flavour_openshift(kube):
    kube.create({"apiVersion": "apiextensions.k8s.io/v1",
                 "kind": "CustomResourceDefinition",
                 "metadata": {
                     "name": "clusterversions.config.openshift.io"}})
    assert ClusterEnvironment(kube).flavour() == Flavour.OPENSHIFT


def test_flavour_kind_fallback(kube):
    assert ClusterEnvironment(kube).flavour() == Flavour.KIND
