"""Ring attention + multi-slice collective tests (first-class long-context
and distributed requirements)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpu_operator_tpu.workloads.multislice import (
    dcn_bytes_per_host, flat_allreduce, hierarchical_allreduce,
    make_multislice_mesh)
from dpu_operator_tpu.workloads.mesh import make_mesh
from dpu_operator_tpu.workloads.ring_attention import (full_attention,
                                                       ring_attention)


def _qkv(b=2, s=64, h=4, d=16, dtype=jnp.float32, seed=0):
    keys = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in keys)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(causal):
    mesh = make_mesh(("data", "model"), axis_sizes=(1, 8))
    q, k, v = _qkv()
    ring = ring_attention(mesh, "model", causal=causal)(q, k, v)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_4way_axis():
    mesh = make_mesh(("data", "model"), axis_sizes=(2, 4))
    q, k, v = _qkv(s=32)
    ring = ring_attention(mesh, "model")(q, k, v)
    ref = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_program_size_constant_in_axis():
    """The ring is a fori_loop, not a Python unroll: the lowered program
    must carry ONE collective-permute pair regardless of axis size, so a
    v5p-256-sized axis compiles in the same bounded time as n=4
    (VERDICT r1 weak-item 3)."""
    sizes = {}
    for n in (4, 8):
        mesh = make_mesh(("data", "model"), axis_sizes=(8 // n, n))
        q, k, v = _qkv(s=64 if n == 8 else 32)
        txt = ring_attention(mesh, "model").lower(q, k, v).as_text()
        sizes[n] = (txt.count("collective_permute"), len(txt))
    # one logical permute pair (k and v), not n-1 of them
    assert sizes[4][0] == sizes[8][0] <= 4
    # program text grows marginally (shape literals), not linearly
    assert sizes[8][1] < sizes[4][1] * 1.5


@pytest.mark.slow
def test_ring_scales_to_v5p_sized_axis():
    """VERDICT r2 #8: prove the compile-time claim at scale. A fresh
    process forces a 64-device host platform (v5p-256-class axis: 64
    hosts), lowers + compiles + RUNS the ring at n=8 and n=64, and the
    program must stay constant-size (one collective-permute pair, not
    n-1) with bounded lowering/compile time."""
    import json as _json
    import os
    import subprocess
    import sys

    script = r"""
import os, time, json
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=64"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from dpu_operator_tpu.workloads.mesh import make_mesh
from dpu_operator_tpu.workloads.ring_attention import ring_attention

def measure(n, run=False):
    mesh = make_mesh(("data", "model"), axis_sizes=(64 // n, n))
    keys = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (2, 128, 2, 16), jnp.float32)
               for kk in keys)
    fn = ring_attention(mesh, "model")
    t0 = time.perf_counter()
    low = jax.jit(fn).lower(q, k, v)
    txt = low.as_text()
    out = {"n": n, "permutes": txt.count("collective_permute"),
           "chars": len(txt), "lower_s": time.perf_counter() - t0}
    if run:
        t0 = time.perf_counter()
        compiled = low.compile()
        out["compile_s"] = time.perf_counter() - t0
        result = compiled(q, k, v)
        result.block_until_ready()
        out["sum"] = float(jnp.sum(result))
    return out

print(json.dumps([measure(8), measure(64, run=True)]))
"""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=300,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-2000:]
    n8, n64 = _json.loads(proc.stdout.strip().splitlines()[-1])
    # ONE logical permute pair (k and v) regardless of ring size — and
    # not zero (a fully-replicated lowering would be a silent regression)
    assert 0 < n8["permutes"] == n64["permutes"] <= 4
    # program size constant in axis size (shape literals only)
    assert n64["chars"] < n8["chars"] * 1.5
    # lowering + compile bounded: seconds, not the minutes an unrolled
    # 63-hop ring would take
    assert n64["lower_s"] < max(10.0, 20 * n8["lower_s"])
    assert n64["compile_s"] < 60.0
    # and it actually executed on the 64-device mesh
    assert "sum" in n64


def test_ring_attention_bf16():
    mesh = make_mesh(("data", "model"), axis_sizes=(1, 8))
    q, k, v = _qkv(dtype=jnp.bfloat16)
    ring = ring_attention(mesh, "model")(q, k, v)
    ref = full_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(ring, jnp.float32), np.asarray(ref, jnp.float32),
        atol=5e-2, rtol=5e-2)  # bf16 accumulation tolerance


def test_multislice_mesh_shape():
    mesh = make_multislice_mesh(2)
    assert mesh.shape["dcn"] == 2
    assert mesh.shape["dcn"] * mesh.shape["data"] * mesh.shape["model"] == 8


def test_hierarchical_allreduce_matches_flat():
    mesh = make_multislice_mesh(2)
    x = jax.random.normal(jax.random.key(0), (256,), jnp.float32)
    hier = hierarchical_allreduce(mesh)(x)
    flat = flat_allreduce(mesh)(x)
    np.testing.assert_allclose(np.asarray(hier), np.asarray(flat),
                               rtol=1e-5)


def test_dcn_traffic_model():
    # hierarchical moves 1/n_ici of the flat schedule's DCN bytes
    flat = dcn_bytes_per_host(1 << 20, n_ici=4, n_slices=2,
                              hierarchical=False)
    hier = dcn_bytes_per_host(1 << 20, n_ici=4, n_slices=2)
    assert hier == flat / 4
    assert dcn_bytes_per_host(1 << 20, 4, 1) == 0.0


def test_vsp_multislice_peer_tracking():
    from dpu_operator_tpu.platform.platform import FakePlatform
    from dpu_operator_tpu.vsp.google import GoogleTpuVsp
    vsp = GoogleTpuVsp(FakePlatform(accelerator_type="v5litepod-4"))
    vsp.init({"tpu_mode": True})
    att = vsp.create_slice_attachment(
        {"name": "host0-0", "chip_index": 0,
         "peer_address": "10.0.0.2:50151"})
    assert att["dcn_peers"] == ["10.0.0.2:50151"]
    vsp.create_slice_attachment(
        {"name": "host0-1", "chip_index": 1,
         "peer_address": "10.0.0.3:50151"})
    assert vsp.dcn_peers == {"10.0.0.2:50151", "10.0.0.3:50151"}
    vsp.delete_slice_attachment({"name": "host0-0"})
    assert vsp.dcn_peers == {"10.0.0.3:50151"}


def test_ring_mode_train_step_loss_decreases():
    """Flagship in long-context mode: params replicated, sequence sharded
    over "model", ring attention rotating KV over the ICI ring."""
    from dpu_operator_tpu.workloads import (TransformerConfig,
                                            make_example_batch, make_train_step)
    cfg = TransformerConfig(n_layers=2, max_seq=64, attention="ring",
                            sequence_parallel=True)
    mesh = make_mesh(("data", "model"), axis_sizes=(2, 4))
    step, init_state, place = make_train_step(cfg, mesh)
    params, opt = init_state(jax.random.key(0))
    batch = place(make_example_batch(cfg, batch=4, seq=64))
    losses = []
    for _ in range(5):
        params, opt, loss = step(params, opt, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_ring_mode_matches_standard_forward():
    from dpu_operator_tpu.workloads.model import (TransformerConfig, forward,
                                                  init_params)
    from dpu_operator_tpu.workloads import make_example_batch
    cfg_r = TransformerConfig(n_layers=1, max_seq=32, attention="ring",
                              dtype=jnp.float32)
    cfg_s = TransformerConfig(n_layers=1, max_seq=32, attention="standard",
                              dtype=jnp.float32)
    mesh = make_mesh(("data", "model"), axis_sizes=(1, 8))
    params = init_params(jax.random.key(5), cfg_s)
    batch = make_example_batch(cfg_s, batch=2, seq=32)
    out_r = jax.jit(lambda p, t: forward(p, t, cfg_r, mesh))(
        params, batch["tokens"])
    out_s = jax.jit(lambda p, t: forward(p, t, cfg_s))(params,
                                                       batch["tokens"])
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_s),
                               atol=3e-4, rtol=3e-4)


def test_v5p_256_multislice_group_model():
    """Ladder config 5: 8 x v5p-32 joined over DCN — group accounting and
    the hierarchical schedule's DCN savings at that scale."""
    from dpu_operator_tpu.ici import MultiSliceGroup, SliceTopology
    group = MultiSliceGroup([SliceTopology("v5p-32") for _ in range(8)])
    assert group.num_chips == 256
    assert group.dcn_allreduce_algbw_gbps() > 0
    flat = dcn_bytes_per_host(1 << 30, n_ici=32, n_slices=8,
                              hierarchical=False)
    hier = dcn_bytes_per_host(1 << 30, n_ici=32, n_slices=8)
    assert hier == flat / 32


def test_ulysses_matches_full_attention():
    """All-to-all sequence parallelism: numerics match full attention
    (same tolerance as the ring path), sequence-sharded in and out."""
    import numpy as np

    from dpu_operator_tpu.workloads.mesh import make_mesh
    from dpu_operator_tpu.workloads.ring_attention import full_attention
    from dpu_operator_tpu.workloads.ulysses import ulysses_attention

    mesh = make_mesh(("model",), axis_sizes=(8,))
    B, S, H, D = 2, 256, 8, 32
    keys = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in keys)
    out = ulysses_attention(mesh, "model", block_q=64, block_k=64)(q, k, v)
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)


def test_ulysses_trains():
    """The all-to-all path is differentiable (it rides the flash VJP
    kernel): a train step in attention="ulysses" mode executes and the
    loss is finite."""
    from dpu_operator_tpu.workloads import (TransformerConfig,
                                            make_example_batch, make_mesh,
                                            make_train_step)

    mesh = make_mesh(("data", "model"), axis_sizes=(1, 8))
    cfg = TransformerConfig(vocab=64, d_model=64, n_heads=8, n_layers=2,
                            d_ff=128, max_seq=64, attention="ulysses",
                            flash_block_q=8, flash_block_k=8)
    step, init_state, place = make_train_step(cfg, mesh)
    params, opt = init_state(jax.random.key(0))
    batch = place(make_example_batch(cfg, batch=2, seq=64))
    params, opt, loss = step(params, opt, batch)
    assert jnp.isfinite(loss) and float(loss) > 0
    # params replicate (sequence mode spends "model" on S, not heads)
    leaf = jax.tree_util.tree_leaves(params)[0]
    assert leaf.sharding.is_fully_replicated


def test_ulysses_program_size_invariant():
    """Like the ring: program size must not grow with the axis (two
    all-to-alls regardless of n)."""
    from dpu_operator_tpu.workloads.mesh import make_mesh
    from dpu_operator_tpu.workloads.ulysses import ulysses_attention

    import jax.numpy as _jnp

    sizes = []
    for n in (2, 8):
        mesh = make_mesh(("model",), devices=jax.devices()[:n],
                         axis_sizes=(n,))
        B, S, H, D = 1, 64, 8, 16
        q = _jnp.zeros((B, S, H, D), _jnp.float32)
        fn = ulysses_attention(mesh, "model", block_q=8, block_k=8)
        text = fn.lower(q, q, q).as_text()
        sizes.append(len(text))
    assert sizes[1] < sizes[0] * 1.5, sizes
