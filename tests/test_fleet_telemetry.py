"""Fleet telemetry plane gate (`make fleet-obs-check`).

A seeded 100-node FakeKube fleet (testing/fleet.py
TelemetryFleetHarness) drives the whole plane on injected clocks:

- every node publishes its damped TpuNodeTelemetry digest and the
  informer-fed FleetAggregator rollup converges OBJECT-BY-OBJECT with
  the apiserver;
- a 200-flap storm on one node stays inside the damping budget
  (writes bounded by the damp interval, never O(flaps));
- a silenced node flips to `TelemetryStale` (CR condition + Warning
  Event + exclusion from advertisable totals) and back, via injected
  clocks only;
- a forced relist (watch outage + history compaction + 410 resume)
  leaves the rollup equal to apiserver state;
- a replayed older digest sequence and a future-schema digest are
  ignored by the aggregator;
- the headroom digest carries a monotonic sequence + injectable
  `asOf` clock;
- `tpu_build_info` carries the schema/rule-count identity labels.
"""

from __future__ import annotations

import copy
import time

import pytest

from dpu_operator_tpu.api.types import API_VERSION, \
    TELEMETRY_SCHEMA_VERSION, TpuNodeTelemetry
from dpu_operator_tpu.testing.fleet import TelemetryFleetHarness
from dpu_operator_tpu.utils import metrics
from dpu_operator_tpu.utils.vars import NAMESPACE

pytestmark = pytest.mark.obs

SEED = 20260803


def assert_eventually(cond, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    assert cond(), f"{what} not reached within {timeout}s"


@pytest.fixture
def fleet():
    h = TelemetryFleetHarness(n_nodes=100, seed=SEED)
    h.start()
    yield h
    h.stop()


@pytest.fixture
def small_fleet():
    h = TelemetryFleetHarness(n_nodes=2, seed=SEED)
    h.start()
    yield h
    h.stop()


def _crs(harness):
    return harness.kube.list(API_VERSION, TpuNodeTelemetry.KIND,
                             namespace=NAMESPACE)


# -- publish + rollup convergence ---------------------------------------------

def test_all_nodes_publish_and_rollup_converges(fleet):
    assert fleet.tick_all() == 100
    assert fleet.wait_idle()
    roll = fleet.aggregator.rollup()
    assert roll["nodes"] == {"total": 100, "fresh": 100, "stale": 0}
    crs = _crs(fleet)
    assert len(crs) == 100
    # object-by-object: the rollup's per-node view equals what the
    # apiserver holds — sequence and capacity for every single CR
    for obj in crs:
        name = obj["metadata"]["name"]
        row = roll["perNode"][name]
        assert row["sequence"] == obj["status"]["sequence"]
        assert row["advertisableSlots"] == \
            obj["status"]["headroom"]["advertisableSlots"]
    assert roll["serveSlots"]["total"] == 24 * 100
    assert roll["serveSlots"]["free"] == sum(
        s.free_slots for s in fleet.sources)
    assert roll["freeKvBlocks"] == 512 * 100
    # the digests carry where each node's debug endpoints answer
    assert roll["perNode"]["node-0000"]["metricsAddr"] \
        == "127.0.0.1:18001"


def test_flap_storm_bounded_by_damping_budget(fleet):
    fleet.tick_all()
    assert fleet.wait_idle()
    fleet.advance(10.0)  # leave every publisher's damp window
    before = fleet.status_writes()
    damped_before = metrics.TELEMETRY_DAMPED.total()
    # 200 material flaps over 20 virtual seconds on ONE node: the
    # apiserver cost must be one immediate publish plus one coalesced
    # write per 5s damp window — NEVER O(flaps)
    fleet.storm(node=0, flaps=200, dt=0.1)
    writes = fleet.status_writes() - before
    assert 1 <= writes <= 6, \
        f"200 flaps cost {writes} apiserver writes (budget: <= 6)"
    # every other flap lands back ON the last published state (a flap
    # storm alternates two values), so ~half the flaps register as
    # material-and-damped; the rest are immaterial — either way, no
    # apiserver write
    assert metrics.TELEMETRY_DAMPED.total() - damped_before >= 90
    # the damped tail converges: one trailing tick publishes the final
    # state and the rollup matches it
    fleet.advance(5.1)
    fleet.publishers[0].tick()
    assert fleet.wait_idle()
    src = fleet.sources[0]
    roll = fleet.aggregator.rollup()
    assert roll["perNode"]["node-0000"]["advertisableSlots"] \
        == min(src.free_slots, src.free_kv // 16)


def test_heartbeat_publishes_while_nothing_changes(small_fleet):
    h = small_fleet
    h.tick_all()
    assert h.wait_idle()
    seq0 = h.publishers[0].sequence
    # inside the heartbeat interval, an unchanged digest is silent
    h.advance(10.0)
    assert h.publishers[0].tick() is False
    # past it, the keepalive publishes (staleness liveness signal)
    h.advance(25.0)
    assert h.publishers[0].tick() is True
    assert h.publishers[0].sequence == seq0 + 1


# -- staleness ---------------------------------------------------------------

def test_silenced_node_flips_stale_and_back(fleet):
    fleet.tick_all()
    assert fleet.wait_idle()
    # every node EXCEPT node-0000 keeps heartbeating past the 90s
    # staleness deadline — all on the injected clock
    for _ in range(4):
        fleet.advance(30.0)
        for pub in fleet.publishers[1:]:
            pub.tick()
    assert fleet.wait_idle()
    assert fleet.aggregator.check_staleness() == ["node-0000"]
    roll = fleet.aggregator.rollup()
    assert roll["nodes"] == {"total": 100, "fresh": 99, "stale": 1}
    assert roll["perNode"]["node-0000"]["stale"] is True
    # a silent node contributes NOTHING to advertisable capacity
    assert roll["serveSlots"]["total"] == 24 * 99
    # the judgment is cluster-visible: condition on the CR + Event
    assert_eventually(
        lambda: any(
            c.get("type") == "TelemetryStale"
            and c.get("status") == "True"
            for c in ((fleet.kube.get(
                API_VERSION, TpuNodeTelemetry.KIND, "node-0000",
                namespace=NAMESPACE) or {}).get("status", {})
                .get("conditions") or [])),
        what="TelemetryStale condition")
    events = fleet.kube.list("v1", "Event", namespace=NAMESPACE)
    assert any(e.get("reason") == "TelemetryStale"
               and e.get("type") == "Warning" for e in events)
    # the node comes back: one accepted digest flips it fresh again
    assert fleet.wait_idle()
    fleet.sources[0].free_slots = 7
    assert fleet.publishers[0].tick()
    assert fleet.wait_idle()
    assert fleet.aggregator.check_staleness() == []
    roll = fleet.aggregator.rollup()
    assert roll["nodes"]["stale"] == 0
    assert roll["serveSlots"]["total"] == 24 * 100
    events = fleet.kube.list("v1", "Event", namespace=NAMESPACE)
    assert any(e.get("reason") == "TelemetryFresh" for e in events)


def test_fleet_condition_rows(fleet):
    fleet.tick_all()
    assert fleet.wait_idle()
    cond = fleet.aggregator.conditions()[0]
    assert cond["type"] == "FleetTelemetry"
    assert cond["status"] == "True"
    # silence one node past the deadline -> the condition goes False
    for _ in range(4):
        fleet.advance(30.0)
        for pub in fleet.publishers[1:]:
            pub.tick()
    assert fleet.wait_idle()
    fleet.aggregator.check_staleness()
    cond = fleet.aggregator.conditions()[0]
    assert cond["status"] == "False"
    assert "node-0000" in cond["message"]


# -- forced relist parity -----------------------------------------------------

def test_forced_relist_rollup_equals_apiserver(fleet):
    fleet.tick_all()
    assert fleet.wait_idle()
    informer = fleet.factory.peek(API_VERSION, TpuNodeTelemetry.KIND)
    informer.MAX_STREAM_FAILURES = 10_000
    informer.STREAM_RETRY_S = 0.02
    fleet.kube.block_watches(API_VERSION, TpuNodeTelemetry.KIND)
    # the fleet keeps publishing while the operator's stream is down
    fleet.advance(6.0)
    for i in range(5):
        fleet.sources[i].free_slots = 3 + i
        assert fleet.publishers[i].tick()
    # compaction forces the resume to 410 -> full relist diff
    fleet.kube.compact_history(API_VERSION, TpuNodeTelemetry.KIND)
    fleet.kube.unblock_watches(API_VERSION, TpuNodeTelemetry.KIND)

    def converged():
        roll = fleet.aggregator.rollup()
        return all(
            roll["perNode"].get(o["metadata"]["name"], {})
            .get("sequence") == o["status"]["sequence"]
            and roll["perNode"][o["metadata"]["name"]]
            ["advertisableSlots"]
            == o["status"]["headroom"]["advertisableSlots"]
            for o in _crs(fleet))

    assert_eventually(converged, timeout=15.0,
                      what="rollup == apiserver after forced relist")


# -- sequence / schema discipline --------------------------------------------

def test_replayed_older_sequence_ignored(small_fleet):
    h = small_fleet
    h.tick_all()
    assert h.wait_idle()
    h.advance(6.0)
    h.sources[0].free_slots = 5
    assert h.publishers[0].tick()
    assert h.wait_idle()
    obj = h.kube.get(API_VERSION, TpuNodeTelemetry.KIND, "node-0000",
                     namespace=NAMESPACE)
    assert obj["status"]["sequence"] == 2
    rejected_before = metrics.FLEET_DIGESTS.value(
        outcome="rejected_sequence")
    # a replayed generation-1 read must not roll the rollup back
    stale_read = copy.deepcopy(obj)
    stale_read["status"]["sequence"] = 1
    stale_read["status"]["headroom"]["advertisableSlots"] = 999
    assert h.aggregator.ingest(stale_read) is False
    assert metrics.FLEET_DIGESTS.value(outcome="rejected_sequence") \
        == rejected_before + 1
    roll = h.aggregator.rollup()
    assert roll["perNode"]["node-0000"]["sequence"] == 2
    assert roll["perNode"]["node-0000"]["advertisableSlots"] != 999


def test_future_schema_digest_ignored(small_fleet):
    h = small_fleet
    h.tick_all()
    assert h.wait_idle()
    obj = h.kube.get(API_VERSION, TpuNodeTelemetry.KIND, "node-0000",
                     namespace=NAMESPACE)
    future = copy.deepcopy(obj)
    future["status"]["sequence"] = 99
    future["status"]["schemaVersion"] = TELEMETRY_SCHEMA_VERSION + 1
    assert h.aggregator.ingest(future) is False
    assert h.aggregator.rollup()["perNode"]["node-0000"]["sequence"] \
        == 1


# -- fleet burn rate over summed counters ------------------------------------

def test_fleet_burn_rate_sums_counters(small_fleet):
    h = small_fleet
    h.sources[0].slo = {"serve-ttft": {"total": 1000.0, "bad": 0.0,
                                       "objective": 0.99}}
    h.sources[1].slo = {"serve-ttft": {"total": 500.0, "bad": 0.0,
                                       "objective": 0.99}}
    h.tick_all()
    assert h.wait_idle()
    # one node serves 1000 more requests, 50 bad; the other is idle —
    # the fleet burn must weight by traffic: 50/1000 bad over a 1%
    # budget = burn 5.0 (averaging per-node rates would halve it)
    h.advance(31.0)
    h.sources[0].slo = {"serve-ttft": {"total": 2000.0, "bad": 50.0,
                                       "objective": 0.99}}
    h.tick_all()
    assert h.wait_idle()
    roll = h.aggregator.rollup()
    assert roll["sloBurnRate"]["serve-ttft"] == pytest.approx(5.0)


def test_counter_reset_clamps_to_zero(small_fleet):
    h = small_fleet
    h.tick_all()
    assert h.wait_idle()
    # node 0 restarts: counters reset BELOW the window reference — the
    # delta must clamp to zero, not go negative
    h.advance(31.0)
    h.sources[0].slo = {"serve-ttft": {"total": 10.0, "bad": 0.0,
                                       "objective": 0.99}}
    h.tick_all()
    assert h.wait_idle()
    roll = h.aggregator.rollup()
    assert roll["sloBurnRate"]["serve-ttft"] == 0.0


# -- satellite: headroom digest hardening -------------------------------------

def test_headroom_sequence_monotonic_with_injected_clock():
    from dpu_operator_tpu.workloads.serve import Scheduler, ServeConfig
    wall = [123.5]
    sched = Scheduler(ServeConfig(),
                      headroom_clock=lambda: wall[0])
    h1 = sched.headroom()
    wall[0] = 200.25
    h2 = sched.headroom()
    assert h1["asOf"] == 123.5
    assert h2["asOf"] == 200.25
    assert h2["sequence"] == h1["sequence"] + 1
    # the wire endpoint carries the same fields through DecodeService
    from dpu_operator_tpu.utils.slo import SloEvaluator
    from dpu_operator_tpu.workloads.serve import DecodeService
    svc = DecodeService(sched, evaluator=SloEvaluator())
    digest = svc.headroom()
    assert digest["sequence"] == h2["sequence"] + 1
    assert "asOf" in digest


# -- satellite: build info ----------------------------------------------------

def test_build_info_gauge_registers_identity():
    from dpu_operator_tpu.analysis import ALL_CHECKERS
    from dpu_operator_tpu.api.types import TELEMETRY_SCHEMA_VERSION \
        as TSV
    from dpu_operator_tpu.daemon.handoff import SCHEMA_VERSION
    from dpu_operator_tpu.utils.metrics import BUILD_INFO, \
        set_build_info
    set_build_info("daemon")
    assert BUILD_INFO.value(
        component="daemon",
        telemetry_schema=str(TSV),
        handoff_schema=str(SCHEMA_VERSION),
        opslint_rules=str(len(ALL_CHECKERS))) == 1.0


# -- review-hardening regressions ---------------------------------------------

def test_revival_happens_on_the_accepted_digest_itself(small_fleet):
    """A stale node rejoins advertisable totals the moment a digest is
    ACCEPTED — before any periodic staleness pass runs."""
    h = small_fleet
    h.tick_all()
    assert h.wait_idle()
    h.advance(120.0)
    h.publishers[1].tick()  # node 1 heartbeats; node 0 silent
    assert h.wait_idle()
    assert h.aggregator.check_staleness() == ["node-0000"]
    assert h.aggregator.rollup()["serveSlots"]["total"] == 24
    # resume: ONE accepted digest — no check_staleness in between —
    # restores the node's capacity and flips the condition back
    assert h.publishers[0].tick()
    assert h.wait_idle()
    roll = h.aggregator.rollup()
    assert roll["nodes"]["stale"] == 0
    assert roll["serveSlots"]["total"] == 48
    assert_eventually(
        lambda: any(e.get("reason") == "TelemetryFresh"
                    for e in h.kube.list("v1", "Event",
                                         namespace=NAMESPACE)),
        what="TelemetryFresh event from the ingest path")


def test_publisher_preserves_aggregator_conditions(small_fleet):
    """The digest publish and the TelemetryStale condition share one
    status subresource: a heartbeat must carry the aggregator's
    condition forward, never erase it."""
    h = small_fleet
    h.tick_all()
    assert h.wait_idle()
    h.advance(120.0)
    h.publishers[1].tick()
    assert h.wait_idle()
    h.aggregator.check_staleness()  # writes TelemetryStale=True
    assert h.publishers[0].tick()   # revival publish
    assert h.wait_idle()

    def condition():
        obj = h.kube.get(API_VERSION, TpuNodeTelemetry.KIND,
                         "node-0000", namespace=NAMESPACE)
        for c in (obj.get("status", {}).get("conditions") or []):
            if c.get("type") == "TelemetryStale":
                return c.get("status")
        return None

    assert_eventually(lambda: condition() == "False",
                      what="TelemetryStale=False after revival")
    # two more heartbeat publishes must NOT wipe the condition
    for _ in range(2):
        h.advance(31.0)
        assert h.publishers[0].tick()
    assert h.wait_idle()
    assert condition() == "False"
    obj = h.kube.get(API_VERSION, TpuNodeTelemetry.KIND, "node-0000",
                     namespace=NAMESPACE)
    # and the digest kept flowing alongside it
    assert obj["status"]["sequence"] == h.publishers[0].sequence


def test_damped_counter_counts_changes_not_ticks(small_fleet):
    h = small_fleet
    h.tick_all()
    assert h.wait_idle()
    before = metrics.TELEMETRY_DAMPED.total()
    # one material change inside the damp window...
    h.advance(1.0)
    h.sources[0].free_slots = 1
    h.publishers[0].tick()
    # ...re-observed by three more ticks with NOTHING new
    for _ in range(3):
        h.advance(0.5)
        h.publishers[0].tick()
    assert metrics.TELEMETRY_DAMPED.total() - before == 1


def test_fleet_gauges_zero_when_a_kind_drops_out(small_fleet):
    h = small_fleet
    h.sources[0].quarantined = {"chip": 2}
    h.tick_all()
    assert h.wait_idle()
    assert metrics.FLEET_QUARANTINED.value(kind="chip") == 2.0
    # the chips recover: the kind vanishes from the rollup and the
    # gauge must read 0, not its final value forever
    h.advance(31.0)
    h.sources[0].quarantined = {}
    h.tick_all()
    assert h.wait_idle()
    assert metrics.FLEET_QUARANTINED.value(kind="chip") == 0.0
