"""Runtime performance plane gate (`make profile-check`).

Three layers under one marker:

1. the sampling profiler (utils/profiler.py) — folded output
   byte-deterministic under injected frame/clock/trigger sources,
   self/total semantics, bounded-table drop accounting, and the
   self-metered overhead bound (< 2%) on a genuinely busy scheduler
   loop;
2. the jit compile watch (workloads/jaxwatch.py) — cache-delta compile
   detection, the warm-then-armed retrace sentinel (counter + Warning
   Event + kind=compile flight entries), and ledger re-billing of
   compile wall time with reconciliation still exact, including the
   seeded shape-unstable-executor e2e;
3. the attribution/fleet layer — `tpuctl serve why` verdicts,
   `tpuctl profile` rendering, the telemetry digest's damped
   serving/perf dims, the FleetAggregator rollup + gauges, and the
   bench trend tool.

Everything runs on injected clocks — no wall-clock sleep drives an
assertion (the busy-loop overhead test measures real perf_counter
time, which is the quantity under test, not a synchronization sleep).
"""

import importlib.util
import json
import threading
from pathlib import Path

import pytest

from dpu_operator_tpu import tpuctl
from dpu_operator_tpu.api.types import TELEMETRY_SCHEMA_VERSION
from dpu_operator_tpu.controller.fleet_telemetry import FleetAggregator
from dpu_operator_tpu.daemon.telemetry import TelemetryPublisher
from dpu_operator_tpu.k8s import FakeKube, events
from dpu_operator_tpu.utils import flight, metrics, profiler
from dpu_operator_tpu.workloads import jaxwatch, serve

pytestmark = pytest.mark.profile


@pytest.fixture(autouse=True)
def _clean_jaxwatch():
    """Every test leaves the compile watch disarmed, on the real
    clock, with zeroed counters and no pending ledger seconds."""
    yield
    jaxwatch.reset()


class Clock:
    """Injected clock (the chaos-harness idiom): advance() moves time
    explicitly, so compile costs replay bit-identically."""

    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- profiler: fabricated frame chains ----------------------------------------


class FakeCode:
    def __init__(self, filename, name):
        self.co_filename = filename
        self.co_name = name


class FakeFrame:
    def __init__(self, filename, funcname, back=None):
        self.f_code = FakeCode(filename, funcname)
        self.f_back = back


def chain(*sites):
    """Build a frame chain from root-first (file, fn) pairs; returns
    the LEAF frame (what sys._current_frames yields)."""
    frame = None
    for filename, funcname in sites:
        frame = FakeFrame(filename, funcname, frame)
    return frame


def _profiler(frames, names, **kw):
    clock = Clock()
    p = profiler.SamplingProfiler(
        clock=clock, frames_fn=lambda: frames,
        threads_fn=lambda: names, **kw)
    return p, clock


def test_folded_output_is_byte_deterministic():
    frames = {
        1: chain(("/a/sched.py", "run"), ("/a/sched.py", "step"),
                 ("/a/pool.py", "alloc")),
        2: chain(("/b/informer.py", "loop"), ("/b/informer.py", "poll")),
    }
    names = {1: "decode-service", 2: "informer"}

    def run():
        p, _ = _profiler(frames, names)
        for _ in range(5):
            assert p.sample_once() == 2
        return p.folded()

    a, b = run(), run()
    assert a == b
    assert a == ("decode-service;sched.py:run;sched.py:step;"
                 "pool.py:alloc 5\n"
                 "informer;informer.py:loop;informer.py:poll 5")


def test_self_total_semantics_and_recursion_counted_once():
    # recursive chain: step appears twice, but its TOTAL must count
    # once per sample; only the leaf (alloc) earns SELF
    frames = {7: chain(("/a/s.py", "step"), ("/a/s.py", "retry"),
                       ("/a/s.py", "step"), ("/a/p.py", "alloc"))}
    p, _ = _profiler(frames, {7: "worker"})
    for _ in range(4):
        p.sample_once()
    rows = {r["site"]: r for r in p.snapshot()["threads"]["worker"]}
    assert rows["p.py:alloc"]["self"] == 4
    assert rows["p.py:alloc"]["total"] == 4
    assert rows["s.py:step"]["self"] == 0
    assert rows["s.py:step"]["total"] == 4  # not 8


def test_bounded_tables_drop_instead_of_growing():
    p, _ = _profiler({}, {}, max_stacks=2, max_sites=2)
    dropped_before = metrics.PROFILE_DROPPED.total()
    for i in range(4):
        p.frames_fn = lambda i=i: {
            1: chain(("/x.py", f"fn{i}"), ("/x.py", f"leaf{i}"))}
        p.sample_once()
    snap = p.snapshot()
    assert len(snap["folded"].splitlines()) == 2
    assert len(snap["threads"]["thread-1"]) == 2
    assert snap["dropped"] > 0
    assert metrics.PROFILE_DROPPED.total() > dropped_before


def test_sampler_excludes_its_own_thread_and_never_raises():
    own = threading.get_ident()
    frames = {own: chain(("/me.py", "sampling")),
              5: chain(("/w.py", "work"))}
    p, _ = _profiler(frames, {own: "main", 5: "w"})
    assert p.sample_once() == 1
    assert "me.py:sampling" not in p.folded()
    p.frames_fn = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
    assert p.sample_once() == 0  # swallowed, never raised


def test_top_sites_quantized_for_the_damped_digest():
    frames = {1: chain(("/a.py", "hot")), 2: chain(("/b.py", "cold"))}
    p, _ = _profiler(frames, {1: "t1", 2: "t2"})
    for _ in range(10):
        p.sample_once()
    top = p.top_sites(2)
    assert [r["site"] for r in top] == ["a.py:hot", "b.py:cold"]
    # 10/20 samples each -> 0.5 exactly on the 0.05 grid
    assert all(r["selfFraction"] == 0.5 for r in top)


def test_overhead_stays_under_two_percent_on_a_busy_scheduler():
    """The acceptance bound: sampling a genuinely busy scheduler loop
    (real frames, real perf_counter) every ~50 iterations must keep
    the profiler's self-metered overhead ratio below 0.02."""
    cfg = serve.ServeConfig(slots=4, kv_blocks=64, kv_block_size=16,
                            queue_limit=1024, ttft_bound_s=10.0)
    sched = serve.Scheduler(cfg)
    for i in range(60):
        sched.submit(serve.Request(rid=f"busy{i}", prompt_len=16,
                                   output_len=48,
                                   slo_class=serve.BATCH,
                                   arrival_s=0.0))
    p = profiler.SamplingProfiler()  # real clock + frames
    worker = threading.Thread(target=sched.run, name="serve-busy",
                              daemon=True)
    worker.start()
    last = 0
    while worker.is_alive():
        it = sched.iterations
        if it - last >= 50:
            last = it
            p.sample_once()
    worker.join(timeout=30)
    assert not worker.is_alive()
    snap = p.snapshot()
    assert snap["overheadRatio"] < 0.02, snap
    # the busy thread attributed by NAME, not ident
    assert any(name == "serve-busy" for name in snap["threads"])


def test_debug_profile_handler_merges_jax_counters():
    payload = profiler.debug_handler()
    assert {"running", "samples", "folded", "overheadRatio",
            "jax"} <= set(payload)
    assert {"armed", "compiles", "retraces", "perFn"} \
        <= set(payload["jax"])


# -- jaxwatch: compile detection, warmth, retrace -----------------------------


class FakeArray:
    def __init__(self, shape, dtype="float32"):
        self.shape = shape
        self.dtype = dtype


class FakeJit:
    """Stand-in for a jitted fn: a new (shape, dtype) signature grows
    the trace cache and costs *compile_cost* on the shared clock —
    exactly the observable surface CompiledFnWatch probes."""

    def __init__(self, clock, compile_cost):
        self.clock = clock
        self.compile_cost = compile_cost
        self.seen = set()

    def _cache_size(self):
        return len(self.seen)

    def __call__(self, x):
        sig = (tuple(x.shape), str(x.dtype))
        if sig not in self.seen:
            self.clock.advance(self.compile_cost)
            self.seen.add(sig)
        return x


def test_compile_watch_cache_delta_warmth_and_retrace(kube):
    clock = Clock()
    jaxwatch.reset(clock=clock)
    name = "unit_watch_fn"
    w = jaxwatch.watch(name, FakeJit(clock, 0.25))
    compiles0 = metrics.JAX_COMPILES.value(fn=name)
    retraces0 = metrics.JAX_RETRACES.value(fn=name)

    w(FakeArray((2, 4)))  # first shape: compile, disarmed
    assert (w.compiles, w.retraces, w.warmed) == (1, 0, False)
    assert jaxwatch.drain_compile_seconds() == pytest.approx(0.25)
    assert jaxwatch.drain_compile_seconds() == 0.0  # drained

    w(FakeArray((2, 4)))  # cache hit proves steady state
    assert w.warmed and w.compiles == 1

    jaxwatch.arm()
    events.configure(events.EventRecorder(kube, "tpu-daemon"),
                     events.node_reference("tpu-vm-0"))
    try:
        w(FakeArray((2, 8)))  # armed + warm: THE retrace
        events.flush()
    finally:
        events.reset()
    assert (w.compiles, w.retraces) == (2, 1)
    assert metrics.JAX_COMPILES.value(fn=name) - compiles0 == 2
    assert metrics.JAX_RETRACES.value(fn=name) - retraces0 == 1
    evs = [e for e in kube.list("v1", "Event")
           if e.get("reason") == "RetraceDetected"]
    assert len(evs) == 1
    assert name in evs[0]["message"]
    assert "float32[2,8]" in evs[0]["message"]
    # compile flight entries carry the abstract signature
    ours = [e for e in flight.RECORDER.events(kind="compile")
            if (e.get("attributes") or {}).get("fn") == name]
    assert [e["attributes"]["retrace"] for e in ours] \
        == ["false", "true"]
    assert ours[0]["attributes"]["signature"] == "(float32[2,4])"
    assert ours[1]["duration_s"] == pytest.approx(0.25)


def test_watch_is_transparent_and_signature_truncates():
    clock = Clock()
    jaxwatch.reset(clock=clock)
    w = jaxwatch.watch("unit_proxy_fn", FakeJit(clock, 0.1))
    assert w._cache_size() == 0
    assert w.seen == set()  # attribute proxy into the wrapped fn
    sig = jaxwatch.abstract_signature(
        (FakeArray((2, 4)), 3), {"flag": True})
    assert sig == "(float32[2,4], int:3, bool:True)"
    many = jaxwatch.abstract_signature(
        tuple(FakeArray((1, i + 1)) for i in range(15)), {})
    assert many.endswith(",+3)")


def _watched_run(kube, name, flip_at):
    """The seeded e2e body: a real-clock Scheduler whose executor
    drives a watched fake-jit fn each decode step; *flip_at* switches
    the input shape once mid-run (None = steady state)."""
    clock = Clock()
    jaxwatch.reset(clock=clock)
    w = jaxwatch.watch(name, FakeJit(clock, 0.05))

    class ShapeUnstableExecutor(serve.SimExecutor):
        def __init__(self):
            self.calls = 0

        def step(self, active):
            self.calls += 1
            width = 16 if self.calls == flip_at else 8
            w(FakeArray((1, width)))
            return super().step(active)

    # the serving shell's startup sequence: warm on the working shape,
    # arm the sentinel, drain warmup compile cost out of the pot
    w(FakeArray((1, 8)))
    w(FakeArray((1, 8)))
    assert w.warmed
    jaxwatch.arm()
    jaxwatch.drain_compile_seconds()

    cfg = serve.ServeConfig(slots=2, kv_blocks=32, kv_block_size=16,
                            queue_limit=64, ttft_bound_s=10.0)
    sched = serve.Scheduler(cfg, executor=ShapeUnstableExecutor(),
                            clock=clock)
    events.configure(events.EventRecorder(kube, "tpu-daemon"),
                     events.node_reference("tpu-vm-0"))
    try:
        for i in range(2):
            sched.submit(serve.Request(rid=f"r{i}", prompt_len=8,
                                       output_len=6, arrival_s=0.0))
        assert sched.run(max_steps=10_000) < 10_000
        events.flush()
    finally:
        events.reset()
    assert len(sched.completed) == 2
    return sched, w


def test_e2e_shape_unstable_executor_fires_exactly_one_retrace(kube):
    retraces0 = metrics.JAX_RETRACES.value(fn="e2e_unstable")
    sched, w = _watched_run(kube, "e2e_unstable", flip_at=3)
    assert w.retraces == 1
    assert metrics.JAX_RETRACES.value(fn="e2e_unstable") \
        - retraces0 == 1
    evs = [e for e in kube.list("v1", "Event")
           if e.get("reason") == "RetraceDetected"]
    assert len(evs) == 1 and "e2e_unstable" in evs[0]["message"]
    ours = [e for e in flight.RECORDER.events(kind="compile")
            if (e.get("attributes") or {}).get("fn") == "e2e_unstable"]
    # the warmup compile plus the mid-run retrace, nothing else
    assert [e["attributes"]["retrace"] for e in ours] \
        == ["false", "true"]
    compile_s = sum((e["phases"] or {}).get("compile", 0.0)
                    for e in sched.ledger.entries())
    assert compile_s == pytest.approx(0.05)
    # re-billing kept the ledger exact: phase sums still reconcile
    verdict = sched.ledger.reconcile()
    assert verdict["ok"], verdict


def test_e2e_steady_state_run_produces_zero_retrace_signals(kube):
    retraces0 = metrics.JAX_RETRACES.value(fn="e2e_steady")
    sched, w = _watched_run(kube, "e2e_steady", flip_at=None)
    assert w.retraces == 0
    assert metrics.JAX_RETRACES.value(fn="e2e_steady") == retraces0
    assert not [e for e in kube.list("v1", "Event")
                if e.get("reason") == "RetraceDetected"]
    ours = [e for e in flight.RECORDER.events(kind="compile")
            if (e.get("attributes") or {}).get("fn") == "e2e_steady"]
    assert [e["attributes"]["retrace"] for e in ours] == ["false"]
    assert sum((e["phases"] or {}).get("compile", 0.0)
               for e in sched.ledger.entries()) == 0.0
    assert sched.ledger.reconcile()["ok"]


# -- tpuctl: serve why + profile rendering ------------------------------------


def _span(name, rid, start, dur):
    return {"kind": "serve", "name": name, "duration_s": dur,
            "attributes": {"rid": rid, "start_s": start}}


def _mark(name, rid, **attrs):
    return {"kind": "serve", "name": name,
            "attributes": {"rid": rid, **attrs}}


def test_serve_why_not_found():
    out = tpuctl.render_serve_why([], "ghost")
    assert out["found"] is False and out["verdict"] == "unknown"


@pytest.mark.parametrize("events_fn,expected", [
    (lambda: [_span("serve.decode", "r", 0.0, 1.0),
              _mark("DeadlineExceeded", "r")], "deadline"),
    (lambda: [_span("serve.decode", "r", 0.0, 1.0),
              _mark("RetryScheduled", "r"), _mark("RetryScheduled", "r")],
     "executor-faults"),
    (lambda: [_span("serve.decode", "r", 0.0, 1.0),
              _mark("Preempted", "r"), _mark("Preempted", "r")],
     "preempt-thrash"),
    (lambda: [_span("serve.cow", "r", 0.0, 0.4),
              _span("serve.decode", "r", 0.4, 0.6)], "cow-stall"),
    (lambda: [_span("serve.queued", "r", 0.0, 0.7),
              _span("serve.decode", "r", 0.7, 0.3)], "queue-bound"),
    (lambda: [_span("serve.prefill_chunk", "r", 0.0, 0.6),
              _span("serve.decode", "r", 0.6, 0.4)], "prefill-bound"),
    (lambda: [_span("serve.decode", "r", 0.0, 1.0)], "decode-bound"),
])
def test_serve_why_verdict_ladder(events_fn, expected):
    out = tpuctl.render_serve_why(events_fn(), "r")
    assert out["verdict"] == expected, out["line"]
    assert out["line"].startswith(f"r: {expected}")


def test_serve_why_retrace_coincident_and_rung():
    evs = [_span("serve.decode", "r", 0.0, 1.0),
           {"kind": "compile", "name": "decode_step",
            "attributes": {"fn": "decode_step", "retrace": "true"}}]
    ledger = {"entries": [{"phases": {"compile": 0.31, "decode": 0.7}}]}
    snap = {"degraded": {"rung": 2, "name": "no_spec"}}
    out = tpuctl.render_serve_why(evs, "r", ledger=ledger,
                                  snapshot=snap)
    assert out["verdict"] == "retrace-coincident"
    assert out["retraceCompiles"] == 1
    assert out["compileLedgerSeconds"] == pytest.approx(0.31)
    assert out["degradedRung"] == "no_spec"
    assert "rung no_spec" in out["line"]
    # without the ledger compile evidence the same events fall through
    no_ledger = tpuctl.render_serve_why(evs, "r")
    assert no_ledger["verdict"] == "decode-bound"


def test_render_profile_summary_and_folded():
    snap = {"running": True, "samples": 9, "dropped": 0,
            "overheadRatio": 0.004, "trackedSites": 3,
            "threads": {"w": [{"site": f"s{i}", "self": i, "total": i}
                              for i in range(8)]},
            "folded": "w;a.py:f 9",
            "jax": {"armed": True, "compiles": 1, "retraces": 0,
                    "perFn": {}}}
    out = tpuctl.render_profile(snap)
    assert out["reachable"] and out["samples"] == 9
    assert len(out["threads"]["w"]) == 5  # summary caps rows
    assert out["jax"]["compiles"] == 1
    folded = tpuctl.render_profile(snap, folded=True)
    assert folded == {"format": "folded", "folded": "w;a.py:f 9"}


def test_fleet_top_carries_serving_and_perf():
    out = tpuctl.render_fleet_top({
        "nodes": {"total": 1, "fresh": 1, "stale": 0},
        "serving": {"degradedRungs": {"no_spec": 1}},
        "perf": {"jaxRetraces": 2, "retraceNodes": ["n0"]}})
    assert out["serving"]["degradedRungs"] == {"no_spec": 1}
    assert out["perf"]["retraceNodes"] == ["n0"]


# -- telemetry digest: damped serving/perf dims -------------------------------


def test_digest_serving_and_perf_dims_are_damped(kube):
    state = {"acc": 0.62, "retraces": 0, "samples": 100}
    clock = Clock()
    pub = TelemetryPublisher(
        kube, "tpu-vm-0",
        serving_fn=lambda: {"degradedRung": 0,
                            "degradedRungName": "healthy",
                            "specKMax": 4,
                            "specAcceptanceRate": state["acc"]},
        perf_fn=lambda: {"topSites": [], "samples": state["samples"],
                         "overheadRatio": 0.001, "jaxCompiles": 3,
                         "jaxRetraces": state["retraces"]},
        clock=clock, wall=clock)
    digest = pub.build_digest()
    assert digest["serving"]["specAcceptanceRate"] == 0.62
    assert digest["perf"]["jaxRetraces"] == 0

    assert pub.tick() is True  # first publish always lands
    clock.advance(6.0)
    state["acc"] = 0.64        # inside the 0.05 deadband
    state["samples"] += 500    # infinite band: never material
    assert pub.tick() is False
    clock.advance(6.0)
    state["retraces"] = 1      # a retrace IS material
    assert pub.tick() is True


# -- fleet aggregator: rollup + gauges ----------------------------------------


def _digest_obj(node, seq, rung, acc, compiles, retraces):
    return {"metadata": {"name": node},
            "status": {"schemaVersion": TELEMETRY_SCHEMA_VERSION,
                       "node": node, "sequence": seq,
                       "serving": {"degradedRung": 0,
                                   "degradedRungName": rung,
                                   "specAcceptanceRate": acc},
                       "perf": {"jaxCompiles": compiles,
                                "jaxRetraces": retraces}}}


def test_fleet_rollup_serving_perf_and_zero_on_vanish(kube):
    clock = Clock()
    agg = FleetAggregator(kube, factory=None, clock=clock)
    assert agg.ingest(_digest_obj("n0", 1, "no_spec", 0.5, 10, 2))
    assert agg.ingest(_digest_obj("n1", 1, "healthy", 0.7, 4, 0))
    roll = agg.rollup()
    assert roll["serving"]["degradedRungs"] \
        == {"no_spec": 1, "healthy": 1}
    assert roll["serving"]["specAcceptanceRate"] \
        == pytest.approx(0.6)
    assert roll["perf"] == {"jaxCompiles": 14, "jaxRetraces": 2,
                            "retraceNodes": ["n0"]}
    assert roll["perNode"]["n0"]["degradedRung"] == "no_spec"
    assert roll["perNode"]["n0"]["jaxRetraces"] == 2
    with agg._lock:
        agg._export_locked()
    assert metrics.FLEET_JAX_COMPILES.value() == 14.0
    assert metrics.FLEET_JAX_RETRACES.value() == 2.0
    assert metrics.FLEET_SPEC_ACCEPTANCE.value() \
        == pytest.approx(0.6)
    assert metrics.FLEET_DEGRADED_NODES.value(rung="no_spec") == 1.0
    # n0 climbs back to healthy: the vacated rung must read 0
    assert agg.ingest(_digest_obj("n0", 2, "healthy", 0.5, 10, 2))
    with agg._lock:
        agg._export_locked()
    assert metrics.FLEET_DEGRADED_NODES.value(rung="no_spec") == 0.0
    assert metrics.FLEET_DEGRADED_NODES.value(rung="healthy") == 2.0


# -- bench trend --------------------------------------------------------------


def _bench_trend():
    path = Path(__file__).resolve().parent.parent / "tools" \
        / "bench_trend.py"
    spec = importlib.util.spec_from_file_location("bench_trend", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_trend_directions_and_judgment():
    bt = _bench_trend()
    assert bt.direction("tokens_per_s") == 1
    assert bt.direction("decode_tok_s_b1") == 1
    assert bt.direction("serve_ttft_p99_improvement_0.8") == 1
    assert bt.direction("ttft_p99_s") == -1
    assert bt.direction("train_step_ms") == -1
    assert bt.direction("serve.loads.1.1.preemptions") == -1
    assert bt.direction("kv_occupancy_mean") == 0
    flat = bt.flatten_numeric({"a": {"b": 1, "ok": True}, "c": 2.5,
                               "name": "cpu"})
    assert flat == {"a.b": 1.0, "c": 2.5}
    # last vs median-of-prior, direction-aware, noise-banded
    assert bt.judge([100.0, 102.0, 50.0], 1, 0.10)[0] == "regressed"
    assert bt.judge([1.0, 1.0, 0.5], -1, 0.10)[0] == "improved"
    assert bt.judge([1.0, 1.04], -1, 0.10)[0] == "steady"
    assert bt.judge([5.0, 50.0], 0, 0.10)[0] == "changed"
    assert bt.judge([5.0], 1, 0.10)[0] == "single"


def test_bench_trend_end_to_end_strict_exit(tmp_path, capsys):
    bt = _bench_trend()
    rounds = [
        (1, 0, {"tokens_per_s": 100.0, "ttft_p99_s": 1.0}),
        (2, 0, {"tokens_per_s": 101.0, "ttft_p99_s": 1.01}),
        (3, 1, {"tokens_per_s": 1.0}),  # rc!=0: skipped, not counted
        (4, 0, {"tokens_per_s": 50.0, "ttft_p99_s": 0.5}),
    ]
    for n, rc, parsed in rounds:
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(
            {"n": n, "cmd": "bench", "rc": rc, "tail": "",
             "parsed": parsed}))
    assert bt.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "3 rounds" in out
    assert "regressed (1):" in out and "tokens_per_s" in out
    assert bt.main(["--dir", str(tmp_path), "--strict"]) == 1
    assert bt.main(["--dir", str(tmp_path / "empty")]) == 2
