"""Regression tests for round-2 ADVICE findings: NO_PROXY honored when
re-applying proxies, multi-network full-teardown IPAM release, nfIpam range
containment at admission, and concurrent host-local ADD atomicity."""

import concurrent.futures
import threading

import yaml

from dpu_operator_tpu.api.webhook import (ValidationError,
                                          validate_tpu_operator_config)
from dpu_operator_tpu.cni import NetConfCache
from dpu_operator_tpu.cni.ipam import HostLocalIpam, ipam_add
from dpu_operator_tpu.cni.types import NetConf, PodRequest
from dpu_operator_tpu.daemon import TpuSideManager
from dpu_operator_tpu.k8s.real import RealKube

import pytest


def _kubeconfig(tmp_path, server):
    path = tmp_path / "kubeconfig"
    path.write_text(yaml.safe_dump({
        "current-context": "ctx",
        "contexts": [{"name": "ctx",
                      "context": {"cluster": "c", "user": "u"}}],
        "clusters": [{"name": "c", "cluster": {"server": server}}],
        "users": [{"name": "u", "user": {"token": "t0ken"}}],
    }))
    return str(path)


def test_no_proxy_excludes_apiserver(tmp_path, monkeypatch):
    """ADVICE r2 #1 (medium): NO_PROXY-excluded apiserver traffic must not
    be forced through HTTPS_PROXY after trust_env=False re-application."""
    monkeypatch.setenv("HTTPS_PROXY", "http://proxy.corp:3128")
    monkeypatch.setenv("HTTP_PROXY", "http://proxy.corp:3128")
    monkeypatch.setenv("NO_PROXY", "kubernetes.default.svc,10.0.0.0/8")
    kube = RealKube(_kubeconfig(tmp_path, "https://10.1.2.3:6443"))
    assert not kube.session.proxies, (
        "apiserver in NO_PROXY CIDR must bypass the proxy")
    kube2 = RealKube(
        _kubeconfig(tmp_path, "https://kubernetes.default.svc:443"))
    assert not kube2.session.proxies


def test_proxy_applied_when_not_excluded(tmp_path, monkeypatch):
    monkeypatch.setenv("HTTPS_PROXY", "http://proxy.corp:3128")
    monkeypatch.setenv("NO_PROXY", "10.0.0.0/8")
    monkeypatch.delenv("HTTP_PROXY", raising=False)
    kube = RealKube(_kubeconfig(tmp_path, "https://203.0.113.7:6443"))
    assert kube.session.proxies.get("https") == "http://proxy.corp:3128"


def _full_teardown_req(sandbox):
    return PodRequest(command="DEL", pod_namespace="default", pod_name="nf",
                      sandbox_id=sandbox, netns="/proc/1/ns/net",
                      ifname="", device_id=None,
                      netconf=NetConf(mode="network-function"))


def _bare_manager(tmp_path):
    mgr = TpuSideManager.__new__(TpuSideManager)
    mgr.vsp = None
    mgr.client = None
    mgr._attach_store = {}
    mgr._attach_lock = threading.Lock()
    mgr._chain_store = {}
    mgr._chain_hops = {}
    mgr.ipam_dir = str(tmp_path / "ipam")
    mgr.nf_cache = NetConfCache(str(tmp_path / "nf"))
    return mgr


def test_full_teardown_releases_every_networks_addresses(tmp_path):
    """ADVICE r2 #2: a sandbox attached via two NADs (different ipam +
    network per ifname) must release BOTH host-local allocations on full
    teardown, not just the one load_any() happened to return."""
    mgr = _bare_manager(tmp_path)
    sbx = "sbx-multinet-1234"
    ipam_a = {"type": "host-local", "subnet": "10.10.0.0/24"}
    ipam_b = {"type": "host-local", "subnet": "10.20.0.0/24"}
    ipam_add(ipam_a, mgr.ipam_dir, "net-a", sbx, "net1")
    ipam_add(ipam_b, mgr.ipam_dir, "net-b", sbx, "net2")
    mgr.nf_cache.save(sbx, "net1", {"ipam": ipam_a, "network": "net-a"})
    mgr.nf_cache.save(sbx, "net2", {"ipam": ipam_b, "network": "net-b"})

    mgr._cni_nf_del(_full_teardown_req(sbx))

    alloc_a = HostLocalIpam(mgr.ipam_dir)
    # both subnets hand out their first address again => nothing leaked
    res_a = alloc_a.add(ipam_a, "net-a", "sbx-new", "net1")
    res_b = alloc_a.add(ipam_b, "net-b", "sbx-new", "net1")
    assert res_a["ips"][0]["address"] == "10.10.0.1/24"
    assert res_b["ips"][0]["address"] == "10.20.0.1/24"


def _cfg(nf_ipam):
    return {"apiVersion": "config.tpu.google.com/v1",
            "kind": "TpuOperatorConfig",
            "metadata": {"name": "tpu-operator-config"},
            "spec": {"mode": "auto", "nfIpam": nf_ipam}}


def test_nf_ipam_range_containment_rejected_at_admission():
    """ADVICE r2 #3: reversed or out-of-subnet ranges must fail admission,
    not every subsequent pod ADD."""
    with pytest.raises(ValidationError, match="not in subnet"):
        validate_tpu_operator_config(_cfg(
            {"type": "host-local", "subnet": "10.0.0.0/24",
             "rangeStart": "10.9.0.5"}))
    with pytest.raises(ValidationError, match="not in subnet"):
        validate_tpu_operator_config(_cfg(
            {"type": "host-local", "subnet": "10.0.0.0/24",
             "gateway": "192.168.1.1"}))
    with pytest.raises(ValidationError, match="rangeStart"):
        validate_tpu_operator_config(_cfg(
            {"type": "host-local", "subnet": "10.0.0.0/24",
             "rangeStart": "10.0.0.50", "rangeEnd": "10.0.0.10"}))
    # a well-formed range still passes
    validate_tpu_operator_config(_cfg(
        {"type": "host-local", "subnet": "10.0.0.0/24",
         "rangeStart": "10.0.0.10", "rangeEnd": "10.0.0.50",
         "gateway": "10.0.0.1"}))


def test_concurrent_add_same_owner_single_ip(tmp_path):
    """ADVICE r2 #4: two concurrent ADDs for the same sandbox+ifname
    (overlapping kubelet retries) must converge on ONE address."""
    ipam = HostLocalIpam(str(tmp_path))
    cfg = {"type": "host-local", "subnet": "10.5.0.0/24"}
    barrier = threading.Barrier(8)

    def one_add(_):
        barrier.wait()
        return ipam.add(cfg, "net", "sbx-retry", "net1")["ips"][0]["address"]

    with concurrent.futures.ThreadPoolExecutor(8) as ex:
        got = list(ex.map(one_add, range(8)))
    assert len(set(got)) == 1, f"concurrent retries claimed {set(got)}"
    # and exactly one allocation record exists
    import os
    recs = [f for f in os.listdir(tmp_path / "net") if f != ".lock"]
    assert len(recs) == 1
