"""hack/tunnel_watch.py — outage watch around bench.probe_backend.

The watch must never recreate the unbounded in-process dial it exists to
avoid (probe timeout > 0 enforced, bench's import-time deadline disabled)
and must run its payload from the repo root regardless of the caller's
cwd (a multi-hour wait followed by "can't open bench.py" would exit 0).
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "hack"))

import tunnel_watch  # noqa: E402


@pytest.fixture
def argv(monkeypatch):
    def set_argv(*args):
        monkeypatch.setattr(sys, "argv", ["tunnel_watch", *args])
    return set_argv


@pytest.fixture(autouse=True)
def _restore_bench_deadline(monkeypatch):
    """main() disables bench.DEADLINE_S for the watch; re-registering the
    current value with monkeypatch restores it after each test so the
    mutation can't leak into bench's own tests."""
    import bench
    monkeypatch.setattr(bench, "DEADLINE_S", bench.DEADLINE_S)


def test_payload_runs_from_repo_root_on_recovery(argv, monkeypatch,
                                                 tmp_path, capfd):
    monkeypatch.setattr(tunnel_watch, "probe_backend",
                        lambda **k: "TPU v5 lite")
    monkeypatch.chdir(tmp_path)  # foreign cwd must not matter
    argv("--then", "pwd", "--attempts", "3")
    assert tunnel_watch.main() == 0
    out = capfd.readouterr().out
    assert tunnel_watch.REPO_ROOT in out
    assert "payload rc=0" in out


def test_gives_up_with_exit_3_and_never_sleeps_after_last(argv, monkeypatch):
    calls = {"probe": 0, "sleep": 0}
    monkeypatch.setattr(tunnel_watch, "probe_backend",
                        lambda **k: calls.__setitem__(
                            "probe", calls["probe"] + 1))
    monkeypatch.setattr(tunnel_watch.time, "sleep",
                        lambda s: calls.__setitem__(
                            "sleep", calls["sleep"] + 1))
    argv("--attempts", "3", "--interval", "1")
    assert tunnel_watch.main() == 3
    assert calls["probe"] == 3
    assert calls["sleep"] == 2  # between attempts only


def test_probe_timeout_zero_rejected(argv):
    argv("--probe-timeout", "0")
    with pytest.raises(SystemExit) as e:
        tunnel_watch.main()
    assert e.value.code == 2  # argparse usage error


def test_bench_deadline_disabled_during_watch(argv, monkeypatch):
    # probe_backend gates on bench.DEADLINE_S measured from bench IMPORT;
    # a long watch would silently stop dialing unless main() disables it
    import bench
    monkeypatch.setattr(bench, "DEADLINE_S", 2700.0)
    seen = {}

    def probe(**k):
        seen["deadline_at_probe"] = bench.DEADLINE_S
        return "TPU v5 lite"

    monkeypatch.setattr(tunnel_watch, "probe_backend", probe)
    argv("--then", "true", "--attempts", "1")
    assert tunnel_watch.main() == 0
    assert seen["deadline_at_probe"] == 0
    assert bench.DEADLINE_S == 2700.0  # restored for in-process embedders


def test_attempts_zero_rejected(argv):
    argv("--attempts", "0")
    with pytest.raises(SystemExit) as e:
        tunnel_watch.main()
    assert e.value.code == 2


def test_payload_failure_is_reported_not_masked(argv, monkeypatch, capfd):
    monkeypatch.setattr(tunnel_watch, "probe_backend",
                        lambda **k: "TPU v5 lite")
    argv("--then", "exit 7", "--attempts", "1")
    assert tunnel_watch.main() == 0  # watch succeeded; payload rc printed
    assert "payload rc=7" in capfd.readouterr().out
