"""Workload tests on the 8-device virtual CPU mesh (conftest.py).

The traffic-flow analog (SURVEY.md §4 tier 4): collectives and the flagship
train step must compile and run with real shardings — same SPMD program
shape as on a hardware slice.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpu_operator_tpu.ici import SliceTopology
from dpu_operator_tpu.workloads import (
    TransformerConfig, make_example_batch, make_mesh, make_train_step,
    measure_allreduce_gbps, mesh_for_topology, psum_allreduce, ring_allreduce)


def test_make_mesh_factors_devices():
    mesh = make_mesh(("data", "model"))
    assert mesh.shape["data"] * mesh.shape["model"] == 8
    assert mesh.shape["model"] >= mesh.shape["data"]


def test_mesh_for_topology_matches_slice_shape():
    mesh = mesh_for_topology("v5e-8")  # (2, 4)
    assert dict(mesh.shape) == {"data": 2, "model": 4}


def test_mesh_for_topology_folds_3d_into_2_axes():
    topo = SliceTopology("v5p-8")  # (2, 2, 2)
    mesh = mesh_for_topology(topo)
    assert dict(mesh.shape) == {"data": 4, "model": 2}


def test_mesh_for_topology_degrades_when_fewer_devices():
    mesh = mesh_for_topology("v5e-256")
    assert mesh.devices.size == 8


def test_psum_allreduce_sums_across_axis():
    mesh = make_mesh(("data", "model"), axis_sizes=(2, 4))
    fn = psum_allreduce(mesh, "model")
    x = jnp.arange(16, dtype=jnp.float32)
    out = fn(x)
    # every model-axis shard of the result is the elementwise sum of the
    # four input shards
    expected = np.asarray(x).reshape(4, 4).sum(0)
    np.testing.assert_allclose(np.asarray(out).reshape(4, 4),
                               np.tile(expected, (4, 1)))


def test_ring_allreduce_matches_psum():
    mesh = make_mesh(("data", "model"), axis_sizes=(2, 4))
    x = jax.random.normal(jax.random.key(0), (64,), jnp.float32)
    ring = ring_allreduce(mesh, "model")(x)
    ps = psum_allreduce(mesh, "model")(x)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ps), rtol=1e-5)


@pytest.mark.parametrize("impl", ["psum", "ring"])
def test_measure_allreduce_reports_bandwidth(impl):
    mesh = make_mesh(("data", "model"), axis_sizes=(1, 8))
    r = measure_allreduce_gbps(mesh, "model", mbytes=1.0, iters=2, impl=impl)
    assert r["algbw_gbps"] > 0
    assert r["busbw_gbps"] >= r["algbw_gbps"]  # n=8: busbw = 7/4 algbw
    assert r["axis_size"] == 8


def test_train_step_runs_and_loss_decreases():
    cfg = TransformerConfig(n_layers=2, max_seq=32)
    mesh = make_mesh(("data", "model"), axis_sizes=(2, 4))
    step, init_state, place = make_train_step(cfg, mesh)
    params, opt = init_state(jax.random.key(0))
    batch = place(make_example_batch(cfg, batch=4, seq=32))
    losses = []
    for _ in range(5):
        params, opt, loss = step(params, opt, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]  # memorizing one batch must improve


def test_train_step_params_are_sharded():
    cfg = TransformerConfig(n_layers=1, max_seq=16)
    mesh = make_mesh(("data", "model"), axis_sizes=(2, 4))
    _, init_state, _ = make_train_step(cfg, mesh)
    params, _ = init_state(jax.random.key(0))
    wqkv = params["layers"][0]["wqkv"]
    assert wqkv.sharding.spec == jax.sharding.PartitionSpec(None, "model")
    # each device holds 1/4 of the columns
    shard = wqkv.addressable_shards[0]
    assert shard.data.shape[1] == wqkv.shape[1] // 4


def test_forward_agrees_with_and_without_mesh():
    cfg = TransformerConfig(n_layers=1, max_seq=16, dtype=jnp.float32)
    mesh = make_mesh(("data", "model"), axis_sizes=(2, 4))
    from dpu_operator_tpu.workloads.model import forward, init_params
    params = init_params(jax.random.key(1), cfg)
    batch = make_example_batch(cfg, batch=2, seq=16)
    lo_single = jax.jit(lambda p, t: forward(p, t, cfg))(
        params, batch["tokens"])
    lo_sharded = jax.jit(lambda p, t: forward(p, t, cfg, mesh))(
        params, batch["tokens"])
    np.testing.assert_allclose(np.asarray(lo_single), np.asarray(lo_sharded),
                               atol=2e-4)


def test_remat_train_step_matches_no_remat():
    """jax.checkpoint layers: same numerics, lower activation memory."""
    from dataclasses import replace
    cfg = TransformerConfig(n_layers=2, max_seq=32, dtype=jnp.float32)
    mesh = make_mesh(("data", "model"), axis_sizes=(2, 4))
    losses = {}
    for remat in (False, True):
        c = replace(cfg, remat=remat)
        step, init_state, place = make_train_step(c, mesh)
        params, opt = init_state(jax.random.key(0))
        batch = place(make_example_batch(c, batch=4, seq=32))
        _, _, loss = step(params, opt, batch)
        losses[remat] = float(loss)
    assert abs(losses[True] - losses[False]) < 1e-5


def test_all_to_all_exchange_is_block_transpose():
    """Device i's j-th chunk lands on device j as chunk i (the MoE
    dispatch collective, tiled all-to-all)."""
    import numpy as np

    from dpu_operator_tpu.workloads.collectives import all_to_all_exchange
    from dpu_operator_tpu.workloads.mesh import make_mesh

    n, chunk = 8, 4
    mesh = make_mesh(("data", "model"), axis_sizes=(1, 8))
    # global x: (n*n, chunk); device i holds rows [i*n, (i+1)*n)
    x = jnp.arange(n * n * chunk, dtype=jnp.float32).reshape(n * n, chunk)
    out = np.asarray(all_to_all_exchange(mesh, "model")(x))
    blocks = np.asarray(x).reshape(n, n, chunk)
    expect = blocks.transpose(1, 0, 2).reshape(n * n, chunk)
    np.testing.assert_array_equal(out, expect)


def test_ppermute_hop_rotates_shards():
    import numpy as np

    from dpu_operator_tpu.workloads.collectives import ppermute_hop
    from dpu_operator_tpu.workloads.mesh import make_mesh

    n, chunk = 8, 3
    mesh = make_mesh(("data", "model"), axis_sizes=(1, 8))
    x = jnp.arange(n * chunk, dtype=jnp.float32)
    out = np.asarray(ppermute_hop(mesh, "model")(x))
    expect = np.roll(np.asarray(x).reshape(n, chunk), 1, axis=0).ravel()
    np.testing.assert_array_equal(out, expect)


def test_collective_measurements_report_sane_numbers():
    from dpu_operator_tpu.workloads.collectives import (
        measure_all_to_all_gbps, measure_ppermute_gbps)
    from dpu_operator_tpu.workloads.mesh import make_mesh

    mesh = make_mesh(("data", "model"), axis_sizes=(1, 8))
    for fn in (measure_all_to_all_gbps, measure_ppermute_gbps):
        r = fn(mesh, "model", mbytes=0.5, iters=2)
        assert r["algbw_gbps"] > 0
        assert r["sec_per_iter"] > 0
        assert r["bytes"] > 0
