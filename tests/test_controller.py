"""Operator controller integration tests against FakeKube.

Reference analog: internal/controller/dpuoperatorconfig_controller_test.go
(:116-170) — asserting that applying the CR materializes the daemon DaemonSet,
the NAD, and the injector deployment, for host and tpu modes, and that the
DaemonSet lands only on labelled nodes.
"""

import pytest

from dpu_operator_tpu.api import TpuOperatorConfig, TpuOperatorConfigSpec
from dpu_operator_tpu.controller import (
    ServiceFunctionChainClusterReconciler,
    TpuOperatorConfigReconciler,
)
from dpu_operator_tpu.k8s import Manager
from dpu_operator_tpu.utils import DEFAULT_NAD_NAME, NAMESPACE


@pytest.fixture
def manager(kube, images, tmp_path):
    from dpu_operator_tpu.utils.filesystem_mode_detector import (
        FilesystemModeDetector,
    )
    from dpu_operator_tpu.utils.path_manager import PathManager
    mgr = Manager(kube)
    mgr.add_reconciler(TpuOperatorConfigReconciler(
        images,
        path_manager=PathManager(str(tmp_path)),
        fs_detector=FilesystemModeDetector(str(tmp_path))))
    mgr.add_reconciler(ServiceFunctionChainClusterReconciler())
    mgr.start()
    yield mgr
    mgr.stop()


def _apply_cfg(kube, mode="host"):
    cfg = TpuOperatorConfig(spec=TpuOperatorConfigSpec(mode=mode))
    return kube.create(cfg.to_obj())


def test_reconcile_creates_daemonset(kube, manager):
    _apply_cfg(kube, mode="host")
    assert manager.wait_idle()
    ds = kube.get("apps/v1", "DaemonSet", "tpu-daemon", namespace=NAMESPACE)
    assert ds is not None
    tmpl = ds["spec"]["template"]["spec"]
    assert tmpl["nodeSelector"] == {"tpu": "true"}
    env = {e["name"]: e.get("value") for e in
           tmpl["containers"][0]["env"] if "value" in e}
    assert env["TPU_VSP_IMAGE"] == "TpuVspImage-mock-image"


@pytest.mark.parametrize("mode,cni_mode", [("host", "chip"),
                                           ("tpu", "network-function")])
def test_reconcile_creates_mode_switched_nad(kube, manager, mode, cni_mode):
    _apply_cfg(kube, mode=mode)
    assert manager.wait_idle()
    nad = kube.get("k8s.cni.cncf.io/v1", "NetworkAttachmentDefinition",
                   DEFAULT_NAD_NAME, namespace="default")
    assert nad is not None
    assert f'"mode": "{cni_mode}"' in nad["spec"]["config"]


def test_reconcile_creates_injector_deployment(kube, manager):
    _apply_cfg(kube)
    assert manager.wait_idle()
    dep = kube.get("apps/v1", "Deployment", "network-resources-injector",
                   namespace=NAMESPACE)
    assert dep is not None


def test_daemonset_lands_on_labelled_nodes_only(kube, node_agent, manager):
    node_agent.register_node("worker-0", labels={"tpu": "true"})
    node_agent.register_node("worker-1", labels={})
    _apply_cfg(kube)
    assert manager.wait_idle()
    pods = kube.list("v1", "Pod", namespace=NAMESPACE,
                     label_selector={"app": "tpu-daemon"})
    assert [p["spec"]["nodeName"] for p in pods] == ["worker-0"]


def test_cr_delete_garbage_collects(kube, manager):
    _apply_cfg(kube)
    assert manager.wait_idle()
    kube.delete("config.tpu.openshift.io/v1", "TpuOperatorConfig",
                "tpu-operator-config")
    assert kube.get("apps/v1", "DaemonSet", "tpu-daemon",
                    namespace=NAMESPACE) is None


def test_status_reports_flavour(kube, manager):
    _apply_cfg(kube)
    assert manager.wait_idle()
    obj = kube.get("config.tpu.openshift.io/v1", "TpuOperatorConfig",
                   "tpu-operator-config")
    assert obj["status"]["flavour"] == "kind"
