"""opslint v2 tests: interprocedural lock rules + resource lifecycle.

Per-rule pass/fail fixtures for `lock-order-graph` and
`resource-lifecycle`, the interprocedural guarded-by relaxation in
`lock-discipline`, the structured CLI formats, and the ratchet's
actionable stale-entry message. Fixtures build Modules directly (the
repo-relative path drives rule scoping), mirroring test_opslint.py.
"""

import json
import os
import textwrap

from dpu_operator_tpu.analysis import (LockDisciplineChecker,
                                       LockOrderGraphChecker,
                                       ResourceLifecycleChecker)
from dpu_operator_tpu.analysis.__main__ import main as opslint_main
from dpu_operator_tpu.analysis.core import Module, run_checkers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def check(checker, source, relpath="dpu_operator_tpu/somemod.py"):
    module = Module("/x/" + relpath, relpath, textwrap.dedent(source))
    return [v for v in checker.check(module)
            if not module.suppressed(v.rule, v.line)]


def check_many(checker, sources):
    """sources: {relpath: source} — a multi-module project pass."""
    modules = [Module("/x/" + rel, rel, textwrap.dedent(src))
               for rel, src in sources.items()]
    by_rel = {m.relpath: m for m in modules}
    return [v for v in checker.check_project(modules)
            if not by_rel[v.path].suppressed(v.rule, v.line)]


# -- lock-order-graph ---------------------------------------------------------

_AB_CYCLE = """
    import threading

    class Alpha:
        def __init__(self, beta):
            self._lock = threading.Lock()
            self.beta = Beta(self)

        def poke(self):
            with self._lock:
                self.beta.tick()

        def tock(self):
            with self._lock:
                pass

    class Beta:
        def __init__(self, alpha):
            self._lock = threading.Lock()
            self.alpha = Alpha(self)

        def tick(self):
            with self._lock:
                pass

        def storm(self):
            with self._lock:
                self.alpha.tock()
"""


def test_lock_order_graph_flags_ab_ba_cycle():
    violations = check(LockOrderGraphChecker(), _AB_CYCLE)
    assert [v.rule for v in violations] == ["lock-order-graph"]
    msg = violations[0].message
    assert "Alpha._lock" in msg and "Beta._lock" in msg
    assert "cycle" in msg


def test_lock_order_graph_passes_one_directional_nesting():
    # Alpha -> Beta only: a strict global order, no cycle
    src = _AB_CYCLE.replace("self.alpha.tock()", "pass")
    assert check(LockOrderGraphChecker(), src) == []


def test_lock_order_graph_flags_self_deadlock_through_helper():
    # non-reentrant Lock reacquired through a resolved call chain: the
    # classic "public method calls public method" self-deadlock
    violations = check(LockOrderGraphChecker(), """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()

            def free_count(self):
                with self._lock:
                    return 1

            def snapshot(self):
                with self._lock:
                    return {"free": self.free_count()}
    """)
    assert len(violations) == 1
    assert "Pool._lock" in violations[0].message


def test_lock_order_graph_allows_rlock_reentry():
    assert check(LockOrderGraphChecker(), """
        import threading

        class Sched:
            def __init__(self):
                self._state_lock = threading.RLock()

            def capacity(self):
                with self._state_lock:
                    return 3

            def snapshot(self):
                with self._state_lock:
                    return {"cap": self.capacity()}
    """) == []


def test_lock_order_graph_condition_aliases_to_wrapped_lock():
    # Condition(self._lock) IS self._lock: holding the condition while
    # calling into a `with self._lock:` method is a real self-deadlock
    violations = check(LockOrderGraphChecker(), """
        import threading

        class Queue:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)

            def _len(self):
                with self._lock:
                    return 0

            def get(self):
                with self._cond:
                    return self._len()
    """)
    assert len(violations) == 1
    assert "Queue._lock" in violations[0].message


def test_lock_order_graph_cross_module_cycle():
    """The edge evidence spans modules: serve holds its lock calling
    the pool; the pool (wrongly) calls back into serve under its own
    lock."""
    violations = check_many(LockOrderGraphChecker(), {
        "dpu_operator_tpu/workloads/fake_serve.py": """
            import threading
            from . import fake_pool

            class Sched:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.pool = fake_pool.Pool(self)

                def step(self):
                    with self._lock:
                        self.pool.alloc_blocks()

                def on_free(self):
                    with self._lock:
                        pass
        """,
        "dpu_operator_tpu/workloads/fake_pool.py": """
            import threading
            from . import fake_serve

            class Pool:
                def __init__(self, sched):
                    self.sched = fake_serve.Sched()

                def alloc_blocks(self):
                    with self._lock:
                        return []

                def free_all(self):
                    with self._lock:
                        self.sched.on_free()
        """,
    })
    assert len(violations) == 1
    assert "Sched._lock" in violations[0].message
    assert "Pool._lock" in violations[0].message


def test_lock_order_graph_multi_item_with_orders_sequentially():
    # `with a, b:` acquires b while holding a: combined with the
    # reverse order elsewhere it is the textbook AB/BA deadlock
    violations = check(LockOrderGraphChecker(), """
        import threading

        class Pair:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def forward(self):
                with self._a_lock, self._b_lock:
                    pass

            def backward(self):
                with self._b_lock, self._a_lock:
                    pass
    """)
    assert len(violations) == 1
    assert "Pair._a_lock" in violations[0].message
    assert "Pair._b_lock" in violations[0].message


def test_lock_order_graph_ignores_calls_inside_lambdas():
    # a lambda's body runs when invoked, not where it is defined:
    # holding a lock while BINDING a deferred call must not fabricate
    # an edge (and must not certify the callee as called-under-lock)
    assert check(LockOrderGraphChecker(), """
        import threading

        class Deferred:
            def __init__(self):
                self._lock = threading.Lock()
                self._other_lock = threading.Lock()

            def schedule(self, timer):
                with self._lock:
                    timer(lambda: self._fire())

            def _fire(self):
                with self._other_lock:
                    pass

            def also(self):
                with self._other_lock:
                    pass
    """) == []


def test_guarded_by_lambda_call_site_gives_no_relaxation():
    violations = check(LockDisciplineChecker(), _HELPER_BASE.replace(
        "                self._spill()",
        "                cb = lambda: self._spill()"))
    # the only "call" is deferred: _spill runs lock-free later, so its
    # off-lock guarded write must still fire
    assert [v.rule for v in violations] == ["lock-discipline"]


def test_lock_order_graph_sees_closure_acquisitions():
    # a worker closure handed to a thread is its own lock-flow root:
    # its internal nesting must contribute edges (here: a cycle against
    # the reverse order taken by a method)
    violations = check(LockOrderGraphChecker(), """
        import threading

        class Spawner:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def start(self, spawn):
                def worker():
                    with self._a_lock:
                        with self._b_lock:
                            pass
                spawn(worker)

            def other(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """)
    assert len(violations) == 1
    assert "Spawner._a_lock" in violations[0].message


def test_lock_order_graph_live_repo_is_acyclic():
    assert run_checkers([LockOrderGraphChecker()],
                        ["dpu_operator_tpu"], REPO) == []


# -- lock-discipline: interprocedural relaxation ------------------------------

_HELPER_BASE = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.total = 0

        def bump(self):
            with self._lock:
                self.total += 1
                self._spill()

        def _spill(self):
            self.total = 0
"""


def test_guarded_by_passes_helper_called_only_from_locked_sites():
    # _spill writes a guarded attr off-lock, but its ONLY call site
    # holds the lock: the interprocedural pass proves the contract
    assert check(LockDisciplineChecker(), _HELPER_BASE) == []


def test_guarded_by_flags_helper_with_an_unlocked_call_site():
    src = _HELPER_BASE + """
        def poke(self):
            self._spill()
    """
    violations = check(LockDisciplineChecker(), src)
    assert [v.rule for v in violations] == ["lock-discipline"]
    assert "_spill" in violations[0].message
    assert "total" in violations[0].message


def test_guarded_by_flags_helper_used_as_callback():
    # a method handed off as a VALUE runs on a schedule the call graph
    # cannot see — call-site evidence no longer covers it
    src = _HELPER_BASE + """
        def schedule(self, timer):
            timer(self._spill)
    """
    violations = check(LockDisciplineChecker(), src)
    assert [v.rule for v in violations] == ["lock-discipline"]


def test_guarded_by_relaxation_is_transitive():
    assert check(LockDisciplineChecker(), """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0

            def bump(self):
                with self._lock:
                    self._mid()

            def _mid(self):
                self._spill()

            def _spill(self):
                self.total = 0
    """) == []


def test_guarded_by_public_helpers_get_no_relaxation():
    # public methods are callable from anywhere; call-site evidence
    # inside the package proves nothing
    violations = check(LockDisciplineChecker(), """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0

            def bump(self):
                with self._lock:
                    self.total += 1
                    self.spill()

            def spill(self):
                self.total = 0
    """)
    assert [v.rule for v in violations] == ["lock-discipline"]


def test_guarded_by_cross_module_call_site_counts():
    """The lock-held call site lives in another module: the project
    pass must still prove the helper's contract."""
    assert check_many(LockDisciplineChecker(), {
        "dpu_operator_tpu/workloads/fake_core.py": """
            import threading

            class Table:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.rows = 0

                def _reset(self):
                    self.rows = 0

                def wipe(self):
                    with self._lock:
                        self.rows += 1
        """,
        "dpu_operator_tpu/workloads/fake_driver.py": """
            from .fake_core import Table

            def drain(table):
                t = Table()
                with t._lock:
                    t._reset()
        """,
    }) == []


# -- resource-lifecycle: handles ----------------------------------------------

def test_lifecycle_flags_exception_edge_leak():
    violations = check(ResourceLifecycleChecker(), """
        import socket

        def dial(addr):
            s = socket.socket()
            s.connect(addr)
            s.close()
    """, relpath="dpu_operator_tpu/k8s/pool.py")
    assert [v.rule for v in violations] == ["resource-lifecycle"]
    assert "connect" in violations[0].message


def test_lifecycle_passes_try_finally_release():
    assert check(ResourceLifecycleChecker(), """
        import socket

        def dial(addr):
            s = socket.socket()
            try:
                s.connect(addr)
                return s.recv(1)
            finally:
                s.close()
    """, relpath="dpu_operator_tpu/k8s/pool.py") == []


def test_lifecycle_passes_with_statement():
    assert check(ResourceLifecycleChecker(), """
        import socket

        def dial(addr):
            with socket.socket() as s:
                s.connect(addr)
                return s.recv(1)
    """, relpath="dpu_operator_tpu/k8s/pool.py") == []


def test_lifecycle_passes_ownership_transfer_forms():
    # return, store-into-self, os.fdopen, cleanup-shaped helper
    assert check(ResourceLifecycleChecker(), """
        import os
        import socket

        def make():
            return socket.socket()

        class Client:
            def adopt(self):
                self._sock = socket.socket()

        def claim(path):
            fd = os.open(path, os.O_RDONLY)
            with os.fdopen(fd) as f:
                return f.read()

        def serve(path):
            listener = socket.socket()
            try:
                listener.bind(path)
            except OSError:
                _cleanup_listener(listener, path)
                return None
            return listener

        def _cleanup_listener(listener, path):
            listener.close()
    """, relpath="dpu_operator_tpu/daemon/handoff.py") == []


def test_lifecycle_flags_handler_that_leaks_on_return():
    # the announce._helper_main shape the audit fixed: handler exits
    # without releasing what the try body acquired
    violations = check(ResourceLifecycleChecker(), """
        import os

        def enter(netns):
            try:
                fd = os.open(netns, os.O_RDONLY)
                os.setns(fd, 0)
                os.close(fd)
            except OSError:
                return 0
            return 1
    """, relpath="dpu_operator_tpu/cni/announce.py")
    assert violations, "handler return with a live fd must fire"
    assert all(v.rule == "resource-lifecycle" for v in violations)


def test_lifecycle_flags_retry_loop_rebind():
    # the native_dp shape the audit fixed: one leaked socket per retry
    violations = check(ResourceLifecycleChecker(), """
        import socket
        import time

        def connect(path, deadline):
            while True:
                try:
                    s = socket.socket()
                    s.connect(path)
                    return s
                except OSError:
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.05)
    """, relpath="dpu_operator_tpu/vsp/native_dp.py")
    assert violations
    assert any("reacquired" in v.message or "raise" in v.message
               for v in violations)


def test_lifecycle_passes_close_before_retry():
    assert check(ResourceLifecycleChecker(), """
        import socket
        import time

        def connect(path, deadline):
            while True:
                s = socket.socket()
                try:
                    s.connect(path)
                except OSError:
                    s.close()
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.05)
                    continue
                return s
    """, relpath="dpu_operator_tpu/vsp/native_dp.py") == []


def test_lifecycle_tracks_accept_as_new_socket():
    violations = check(ResourceLifecycleChecker(), """
        import socket

        def serve_one(path):
            listener = socket.socket()
            try:
                listener.bind(path)
                conn, _ = listener.accept()
                data = conn.recv(64)
                return data
            finally:
                listener.close()
    """, relpath="dpu_operator_tpu/daemon/handoff.py")
    assert len(violations) >= 1
    assert any("accept" in v.message for v in violations)


# -- resource-lifecycle: KV owners and slots ----------------------------------

def test_lifecycle_flags_kv_alloc_without_free_on_error_path():
    violations = check(ResourceLifecycleChecker(), """
        def admit(self, req, blocks):
            mapped = self.pool.map_prefix(req.rid, req.keys)
            if self.pool.alloc(req.rid, blocks - mapped) is None:
                return False
            self._active[req.slot] = req
            return True
    """, relpath="dpu_operator_tpu/workloads/serve.py")
    assert [v.rule for v in violations] == ["resource-lifecycle"]
    assert "req.rid" in violations[0].message


def test_lifecycle_passes_kv_rollback_and_transfer():
    # the _admit_locked shape: roll back on failure, transfer the
    # owning object into scheduler state on success
    assert check(ResourceLifecycleChecker(), """
        def admit(self, req, blocks):
            mapped = self.pool.map_prefix(req.rid, req.keys)
            if self.pool.alloc(req.rid, blocks - mapped) is None:
                self.pool.free(req.rid)
                return False
            self._active[req.slot] = req
            return True
    """, relpath="dpu_operator_tpu/workloads/serve.py") == []


def test_lifecycle_passes_kv_release_via_release_locked_hoist():
    assert check(ResourceLifecycleChecker(), """
        def excise(self, req):
            self.pool.alloc(req.rid, 4)
            self._release_locked(req)
    """, relpath="dpu_operator_tpu/workloads/serve.py") == []


def test_lifecycle_flags_slot_pop_without_putback_or_store():
    violations = check(ResourceLifecycleChecker(), """
        def grab(self):
            slot = self._free_slots.pop(0)
            return None
    """, relpath="dpu_operator_tpu/workloads/serve.py")
    assert [v.rule for v in violations] == ["resource-lifecycle"]
    assert "slot" in violations[0].message


def test_lifecycle_passes_slot_claim_and_putback():
    assert check(ResourceLifecycleChecker(), """
        def grab(self, req):
            slot = self._free_slots.pop(0)
            req.slot = slot
            self._active[slot] = req

        def release(self, req):
            self._free_slots.append(req.slot)
    """, relpath="dpu_operator_tpu/workloads/serve.py") == []


def test_lifecycle_pragma_suppresses():
    assert check(ResourceLifecycleChecker(), """
        import socket

        def dial(addr):
            s = socket.socket()  # opslint: disable=resource-lifecycle
            s.connect(addr)
            s.close()
    """, relpath="dpu_operator_tpu/k8s/pool.py") == []


def test_lifecycle_scopes_to_package_non_test_files():
    leaky = """
        import socket

        def dial(addr):
            s = socket.socket()
            s.connect(addr)
            s.close()
    """
    assert check(ResourceLifecycleChecker(), leaky,
                 relpath="tests/test_x.py") == []
    assert check(ResourceLifecycleChecker(), leaky,
                 relpath="tools/helper.py") == []


def test_lifecycle_loop_head_discharge_is_not_resurrected():
    # the loop-head expression discharging the LAST live resource must
    # yield the empty set, not fall back to the pre-head live set
    assert check(ResourceLifecycleChecker(), """
        import socket

        def drain(addr):
            s = socket.socket()
            for item in _cleanup_sock(s):
                handle(item)
            return None

        def _cleanup_sock(s):
            s.close()
            return []
    """, relpath="dpu_operator_tpu/k8s/pool.py") == []


def test_lifecycle_lambda_defines_neither_leak_nor_release():
    # defining `lambda: socket.socket()` acquires nothing here...
    assert check(ResourceLifecycleChecker(), """
        import socket

        def make_factory():
            factory = lambda: socket.socket()
            return factory
    """, relpath="dpu_operator_tpu/k8s/pool.py") == []
    # ...and `cleanup = lambda: s.close()` releases nothing here: the
    # socket is still leaked if the lambda is never invoked
    violations = check(ResourceLifecycleChecker(), """
        import socket

        def dial(addr):
            s = socket.socket()
            cleanup = lambda: s.close()
            return None
    """, relpath="dpu_operator_tpu/k8s/pool.py")
    assert [v.rule for v in violations] == ["resource-lifecycle"]


def test_lifecycle_live_repo_is_clean():
    assert run_checkers([ResourceLifecycleChecker()],
                        ["dpu_operator_tpu"], REPO) == []


# -- CLI formats --------------------------------------------------------------

def _seeded_tree(tmp_path):
    pkg = tmp_path / "dpu_operator_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text("import socket\n")
    return tmp_path


def test_cli_json_format_is_machine_stable(tmp_path, capsys):
    root = str(_seeded_tree(tmp_path))
    assert opslint_main(["--repo-root", root, "--format", "json"]) == 1
    data = json.loads(capsys.readouterr().out)
    assert data["version"] == 1
    (finding,) = data["findings"]
    assert finding["rule"] == "wire-seam"
    assert finding["file"] == "dpu_operator_tpu/bad.py"
    assert finding["line"] == 1
    assert finding["status"] == "new"
    assert "socket" in finding["message"]
    rule_ids = {r["id"] for r in data["rules"]}
    assert {"lock-order-graph", "resource-lifecycle",
            "lock-discipline"} <= rule_ids


def test_cli_sarif_format(tmp_path, capsys):
    root = str(_seeded_tree(tmp_path))
    assert opslint_main(["--repo-root", root, "--format", "sarif"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "opslint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"lock-order-graph", "resource-lifecycle"} <= rule_ids
    (result,) = run["results"]
    assert result["ruleId"] == "wire-seam"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "dpu_operator_tpu/bad.py"
    assert loc["region"]["startLine"] == 1
    assert "suppressions" not in result


def test_cli_sarif_marks_baselined_as_suppressed(tmp_path, capsys):
    root = str(_seeded_tree(tmp_path))
    assert opslint_main(["--repo-root", root, "--write-baseline"]) == 0
    capsys.readouterr()
    assert opslint_main(["--repo-root", root, "--format", "sarif"]) == 0
    doc = json.loads(capsys.readouterr().out)
    (result,) = doc["runs"][0]["results"]
    assert result["suppressions"][0]["kind"] == "external"


def test_cli_json_exit_code_still_gates(tmp_path, capsys):
    root = str(_seeded_tree(tmp_path))
    assert opslint_main(["--repo-root", root, "--write-baseline"]) == 0
    capsys.readouterr()
    assert opslint_main(["--repo-root", root, "--format", "json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["findings"][0]["status"] == "baselined"


# -- ratchet message ----------------------------------------------------------

def test_stale_baseline_message_names_rule_and_file(tmp_path, capsys):
    root = str(_seeded_tree(tmp_path))
    assert opslint_main(["--repo-root", root, "--write-baseline"]) == 0
    (tmp_path / "dpu_operator_tpu" / "bad.py").write_text("import os\n")
    assert opslint_main(["--repo-root", root]) == 0
    out = capsys.readouterr().out
    assert "stale baseline entry" in out
    assert "delete rule `wire-seam` for `dpu_operator_tpu/bad.py`" \
        in out
    assert "--write-baseline" in out  # the rewrite escape hatch


def test_stale_baseline_message_names_overridden_baseline_file(
        tmp_path, capsys):
    root = str(_seeded_tree(tmp_path))
    custom = str(tmp_path / "ci-baseline.json")
    assert opslint_main(["--repo-root", root, "--baseline", custom,
                         "--write-baseline"]) == 0
    (tmp_path / "dpu_operator_tpu" / "bad.py").write_text("import os\n")
    capsys.readouterr()
    assert opslint_main(["--repo-root", root,
                         "--baseline", custom]) == 0
    out = capsys.readouterr().out
    assert "ci-baseline.json" in out
    assert "opslint-baseline.json" not in out


def test_stale_entries_in_json_format(tmp_path, capsys):
    root = str(_seeded_tree(tmp_path))
    assert opslint_main(["--repo-root", root, "--write-baseline"]) == 0
    (tmp_path / "dpu_operator_tpu" / "bad.py").write_text("import os\n")
    capsys.readouterr()
    assert opslint_main(["--repo-root", root, "--format", "json"]) == 0
    data = json.loads(capsys.readouterr().out)
    (stale,) = data["staleBaselineEntries"]
    assert stale["rule"] == "wire-seam"
    assert stale["file"] == "dpu_operator_tpu/bad.py"
