"""KV-cache decode (serving path): correctness against the full forward.

The generate loop must produce EXACTLY the tokens that repeatedly running
the full (non-cached) forward and taking argmax would produce — the
teacher-forced equivalence that proves the cache math (positions, masks,
dynamic_update_slice) is right.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpu_operator_tpu.workloads.decode import (generate, init_kv_cache,
                                               measure_decode, prefill)
from dpu_operator_tpu.workloads.model import (TransformerConfig, forward,
                                              init_params)


@pytest.fixture(scope="module")
def setup():
    cfg = TransformerConfig(vocab=128, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_seq=48, dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _reference_generate(params, cfg, prompt, steps):
    """Oracle: full forward over the growing sequence each step."""
    seq = np.asarray(prompt)
    out = []
    for _ in range(steps):
        logits = forward(params, jnp.asarray(seq), cfg)
        nxt = np.asarray(jnp.argmax(logits[:, -1, :], axis=-1))
        out.append(nxt)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    return np.stack(out, axis=1)


def test_generate_matches_full_forward(setup):
    cfg, params = setup
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab)
    got = np.asarray(generate(params, cfg, prompt, steps=12))
    want = _reference_generate(params, cfg, prompt, steps=12)
    np.testing.assert_array_equal(got, want)


def test_prefill_logits_match_forward(setup):
    cfg, params = setup
    prompt = jax.random.randint(jax.random.key(2), (3, 10), 0, cfg.vocab)
    _, last = prefill(params, cfg, prompt)
    ref = forward(params, prompt, cfg)[:, -1, :]
    np.testing.assert_allclose(np.asarray(last), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_generate_rejects_overflow(setup):
    cfg, params = setup
    prompt = jnp.ones((1, 40), jnp.int32)
    with pytest.raises(ValueError, match="max_seq"):
        generate(params, cfg, prompt, steps=20)


def test_cache_shapes(setup):
    cfg, _ = setup
    cache = init_kv_cache(cfg, batch=3)
    assert len(cache) == cfg.n_layers
    assert cache[0]["k"].shape == (3, cfg.max_seq, cfg.n_heads, cfg.d_head)


def test_moe_decode_matches_forward_when_capacity_covers():
    """MoE serving path: with a capacity factor covering the sequence the
    training forward drops nothing, so decode must match it EXACTLY (the
    only legitimate divergence is capacity dropping, which decode's S=1
    steps never trigger — decode.py module docstring)."""
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_seq=32, dtype=jnp.float32,
                            moe_experts=4, moe_capacity_factor=8.0)
    params = init_params(jax.random.key(3), cfg)
    prompt = jax.random.randint(jax.random.key(4), (2, 4), 0, cfg.vocab)
    got = np.asarray(generate(params, cfg, prompt, steps=6))
    want = _reference_generate(params, cfg, prompt, steps=6)
    np.testing.assert_array_equal(got, want)


def test_measure_decode_smoke(setup):
    cfg, _ = setup
    r = measure_decode(cfg, batch=2, prompt_len=4, steps=8, iters=2)
    assert r["tokens_per_s"] > 0
    assert r["ms_per_token"] > 0


def test_generate_with_tp_sharded_params_matches_unsharded():
    """Multi-chip serving: the same generate() program with params laid
    out tensor-parallel over an 8-way "model" axis produces the identical
    token stream (XLA shards the cache and inserts the collectives)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from dpu_operator_tpu.workloads.mesh import make_mesh
    from dpu_operator_tpu.workloads.model import param_specs

    cfg = TransformerConfig(vocab=128, d_model=32, n_heads=8, n_layers=2,
                            d_ff=64, max_seq=48, dtype=jnp.float32)
    params = init_params(jax.random.key(7), cfg)
    prompt = jax.random.randint(jax.random.key(8), (2, 8), 0, cfg.vocab)
    want = np.asarray(generate(params, cfg, prompt, steps=10))

    mesh = make_mesh(("data", "model"), axis_sizes=(1, 8))
    pshard = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_specs(cfg),
        is_leaf=lambda s: isinstance(s, P))
    sharded = jax.device_put(params, pshard)
    got = np.asarray(generate(sharded, cfg, prompt, steps=10))
    np.testing.assert_array_equal(got, want)


def test_sampling_paths(setup):
    cfg, params = setup
    prompt = jax.random.randint(jax.random.key(9), (2, 6), 0, cfg.vocab)
    greedy = np.asarray(generate(params, cfg, prompt, steps=8))

    # temperature ~0+ with top_k=1 collapses to greedy
    t1 = np.asarray(generate(params, cfg, prompt, steps=8,
                             temperature=0.5, top_k=1,
                             key=jax.random.key(0)))
    np.testing.assert_array_equal(t1, greedy)

    # real sampling: in-range tokens, key-dependent, reproducible
    s_a = np.asarray(generate(params, cfg, prompt, steps=8,
                              temperature=1.0, key=jax.random.key(1)))
    s_b = np.asarray(generate(params, cfg, prompt, steps=8,
                              temperature=1.0, key=jax.random.key(2)))
    s_a2 = np.asarray(generate(params, cfg, prompt, steps=8,
                               temperature=1.0, key=jax.random.key(1)))
    assert ((s_a >= 0) & (s_a < cfg.vocab)).all()
    np.testing.assert_array_equal(s_a, s_a2)
    assert not np.array_equal(s_a, s_b)

    with pytest.raises(ValueError, match="PRNG key"):
        generate(params, cfg, prompt, steps=4, temperature=1.0)


def test_temperature_change_does_not_recompile(setup):
    """temperature rides as a traced scalar: per-request values reuse ONE
    compiled program (a static temperature would recompile per value)."""
    from dpu_operator_tpu.workloads.decode import _generate_compiled

    cfg, params = setup
    prompt = jax.random.randint(jax.random.key(10), (1, 4), 0, cfg.vocab)
    generate(params, cfg, prompt, steps=4, temperature=0.7,
             key=jax.random.key(0))
    before = _generate_compiled._cache_size()
    for t in (0.65, 0.8, 1.3):
        generate(params, cfg, prompt, steps=4, temperature=t,
                 key=jax.random.key(0))
    assert _generate_compiled._cache_size() == before


def test_quantized_decode_matches_bf16_closely():
    """W8A8 serving: per-channel int8 weights + dynamic activation quant
    keep prefill logits close to the bf16 path and greedy generation
    agrees on most tokens (random-init model, loose tolerance — the
    point is the plumbing is faithful, halved weight bytes come free)."""
    import numpy as np

    from dpu_operator_tpu.workloads.decode import (generate, prefill,
                                                   quantize_decode_params)
    from dpu_operator_tpu.workloads.model import (TransformerConfig,
                                                  init_params)

    cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4, n_layers=2,
                            d_ff=128, max_seq=64)
    params = init_params(jax.random.key(0), cfg)
    qparams = quantize_decode_params(params)

    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab)
    _, logits = prefill(params, cfg, prompt)
    _, qlogits = prefill(qparams, cfg, prompt)
    # logits correlate strongly (quantization noise, not garbage)
    a = np.asarray(logits).ravel()
    b = np.asarray(qlogits).ravel()
    corr = np.corrcoef(a, b)[0, 1]
    assert corr > 0.99, corr

    toks = np.asarray(generate(params, cfg, prompt, steps=12))
    qtoks = np.asarray(generate(qparams, cfg, prompt, steps=12))
    agree = (toks == qtoks).mean()
    assert agree > 0.5, agree  # greedy paths can diverge after a miss


def test_quantized_weights_are_int8():
    from dpu_operator_tpu.workloads.decode import quantize_decode_params
    from dpu_operator_tpu.workloads.model import (TransformerConfig,
                                                  init_params)

    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=2, n_layers=1,
                            d_ff=64, max_seq=32)
    q = quantize_decode_params(init_params(jax.random.key(0), cfg))
    assert q["embed"]["q"].dtype == jnp.int8
    assert q["embed"]["scale"].shape == (cfg.vocab, 1)  # per vocab row
    lp = q["layers"][0]
    assert lp["wqkv"]["q"].dtype == jnp.int8
    assert lp["wqkv"]["scale"].shape == (1, 3 * cfg.d_model)
    # norms stay high-precision
    assert lp["ln1"].dtype == cfg.dtype


def test_kv_int8_decode_matches_bf16_cache_closely():
    """KV8: the int8 KV cache (per-token/head scales, dequant fused into
    the attention einsums) keeps the decode trajectory close to the
    bf16-cache path — same weights, only the cache representation
    differs, so agreement should be HIGH, not just correlated."""
    import numpy as np

    from dpu_operator_tpu.workloads.decode import generate
    from dpu_operator_tpu.workloads.model import (TransformerConfig,
                                                  init_params)

    cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4, n_layers=2,
                            d_ff=128, max_seq=64)
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab)
    toks = np.asarray(generate(params, cfg, prompt, steps=16))
    ktoks = np.asarray(generate(params, cfg, prompt, steps=16,
                                kv_int8=True))
    agree = (toks == ktoks).mean()
    assert agree > 0.8, agree


def test_kv_int8_composes_with_w8a8():
    """int8 weights + int8 KV together (the full serving quant stack)
    still track the bf16 reference."""
    import numpy as np

    from dpu_operator_tpu.workloads.decode import (generate,
                                                   quantize_decode_params)
    from dpu_operator_tpu.workloads.model import (TransformerConfig,
                                                  init_params)

    cfg = TransformerConfig(vocab=128, d_model=64, n_heads=4, n_layers=2,
                            d_ff=128, max_seq=64)
    params = init_params(jax.random.key(0), cfg)
    qparams = quantize_decode_params(params)
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab)
    toks = np.asarray(generate(params, cfg, prompt, steps=12))
    qtoks = np.asarray(generate(qparams, cfg, prompt, steps=12,
                                kv_int8=True))
    agree = (toks == qtoks).mean()
    assert agree > 0.5, agree


def test_kv_int8_cache_shapes_and_dtypes():
    from dpu_operator_tpu.workloads.decode import init_kv_cache, prefill
    from dpu_operator_tpu.workloads.model import (TransformerConfig,
                                                  init_params)

    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=2, n_layers=1,
                            d_ff=64, max_seq=32)
    cache = init_kv_cache(cfg, batch=2, kv_int8=True)
    assert cache[0]["k_q"].dtype == jnp.int8
    assert cache[0]["k_s"].shape == (2, 32, 2, 1)
    # prefill stores quantized rows for the prompt span
    params = init_params(jax.random.key(0), cfg)
    prompt = jnp.ones((2, 5), jnp.int32)
    qcache, _ = prefill(params, cfg, prompt, kv_int8=True)
    import numpy as np
    assert np.abs(np.asarray(qcache[0]["k_q"][:, :5])).max() > 0
    assert np.asarray(qcache[0]["k_s"][:, :5]).min() > 0


def _step_generate(params, cfg, prompt, steps, kv_int8=False):
    """Drive the refactored prefill + decode_step pair one iteration at
    a time with a PER-ROW position vector (the serve scheduler's call
    shape) — greedy, like generate()'s temperature-0 path."""
    from dpu_operator_tpu.workloads.decode import decode_step, prefill

    B, P = prompt.shape
    cache, logits = prefill(params, cfg, prompt, kv_int8=kv_int8)
    pos = jnp.full((B,), P, jnp.int32)
    out = []
    for i in range(steps):
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        out.append(np.asarray(tok))
        logits, cache = decode_step(params, cfg, cache, tok, pos + i)
    return np.stack(out, axis=1)


def test_decode_step_token_identical_to_fused_scan(setup):
    """The satellite contract: the scan is now a thin wrapper over the
    same step body, so driving single decode_step iterations (vector
    positions, the serve path) must reproduce the fused generate()
    token stream EXACTLY on a seeded config."""
    cfg, params = setup
    prompt = jax.random.randint(jax.random.key(20), (3, 6), 0, cfg.vocab)
    want = np.asarray(generate(params, cfg, prompt, steps=12))
    got = _step_generate(params, cfg, prompt, steps=12)
    np.testing.assert_array_equal(got, want)


def test_decode_step_token_identical_with_kv_int8(setup):
    cfg, params = setup
    prompt = jax.random.randint(jax.random.key(21), (2, 5), 0, cfg.vocab)
    want = np.asarray(generate(params, cfg, prompt, steps=10,
                               kv_int8=True))
    got = _step_generate(params, cfg, prompt, steps=10, kv_int8=True)
    np.testing.assert_array_equal(got, want)


def test_decode_step_scalar_and_vector_pos_agree(setup):
    """Same values through dynamic_update_slice (scalar pos) and the
    per-row scatter (vector pos): the serve path cannot drift from the
    scan path numerically."""
    from dpu_operator_tpu.workloads.decode import decode_step, prefill

    cfg, params = setup
    prompt = jax.random.randint(jax.random.key(22), (2, 7), 0, cfg.vocab)
    cache, logits = prefill(params, cfg, prompt)
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # decode_step donates its cache: the first call consumes `cache`
    # on donation-capable backends, so the second gets its own copy
    cache2 = jax.tree_util.tree_map(jnp.copy, cache)
    scalar_logits, scalar_cache = decode_step(params, cfg, cache, tok, 7)
    vec_logits, vec_cache = decode_step(params, cfg, cache2, tok,
                                        jnp.full((2,), 7, jnp.int32))
    np.testing.assert_allclose(np.asarray(scalar_logits),
                               np.asarray(vec_logits),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(scalar_cache[0]["k"]),
                               np.asarray(vec_cache[0]["k"]),
                               atol=1e-6, rtol=1e-6)


def test_decode_step_does_not_retrace_across_values(setup):
    """One compiled program per (cfg, shapes): the continuous-batching
    loop feeds new token/position VALUES every iteration and must never
    pay a re-trace."""
    from dpu_operator_tpu.workloads.decode import decode_step, prefill

    cfg, params = setup
    prompt = jax.random.randint(jax.random.key(23), (2, 4), 0, cfg.vocab)
    cache, logits = prefill(params, cfg, prompt)
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    pos = jnp.full((2,), 4, jnp.int32)
    _, cache = decode_step(params, cfg, cache, tok, pos)
    before = decode_step._cache_size()
    for i in range(1, 6):
        _, cache = decode_step(params, cfg, cache,
                               (tok + i) % cfg.vocab, pos + i)
    assert decode_step._cache_size() == before


def test_measure_decode_kv_int8_byte_model():
    """The roofline byte model must charge KV8 at ~1 byte/elem (+ scale
    amortization), not bf16's 2."""
    from dpu_operator_tpu.workloads.decode import measure_decode
    from dpu_operator_tpu.workloads.model import TransformerConfig

    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=2, n_layers=1,
                            d_ff=64, max_seq=32)
    r16 = measure_decode(cfg, batch=1, steps=8, iters=1, best_of=1)
    r8 = measure_decode(cfg, batch=1, steps=8, iters=1, best_of=1,
                        kv_int8=True)
    # same weights; only the kv bytes differ — the model's roofline must
    # shrink by exactly the kv-width delta
    kv16 = 2.0 * cfg.n_layers * cfg.max_seq * cfg.d_model * 2.0
    kv8 = (2.0 * cfg.n_layers * cfg.max_seq * cfg.d_model
           * (1.0 + 4.0 / cfg.d_head))
    from dpu_operator_tpu.workloads.perf import hbm_bandwidth_gbps
    delta_ms = (kv16 - kv8) / hbm_bandwidth_gbps() / 1e9 * 1e3
    # the byte model lives in the HBM leg of the dual roofline; the
    # combined roofline is max(hbm, compute) and this toy config is
    # compute-bound on CPU, so the kv-width delta shows up there only
    got = r16["hbm_ms_per_token"] - r8["hbm_ms_per_token"]
    assert got == pytest.approx(delta_ms, rel=1e-6)
    assert r16["roofline_ms_per_token"] >= r16["hbm_ms_per_token"]
    assert r8["roofline_ms_per_token"] >= r8["hbm_ms_per_token"]


# -- chunked prefill (the schedulable-prefill kernel entry) -------------------


def _chunked_prefill_into(params, cfg, cache, slot, prompt, chunk):
    """Drive prefill_chunk over *prompt* in fixed-width *chunk* pieces
    (final piece padded), returning (cache, last logits)."""
    from dpu_operator_tpu.workloads.decode import prefill_chunk

    logits = None
    off = 0
    while off < len(prompt):
        n = min(chunk, len(prompt) - off)
        padded = np.zeros(chunk, np.int32)
        padded[:n] = prompt[off:off + n]
        cache, logits = prefill_chunk(params, cfg, cache,
                                      jnp.int32(slot),
                                      jnp.asarray(padded),
                                      jnp.int32(off), jnp.int32(n))
        off += n
    return cache, logits


def test_prefill_chunk_cache_and_token_identical_to_prefill(setup):
    """The tentpole kernel contract: chunked prefill writes the SAME
    cache rows as the whole-prompt prefill and its final-chunk logits
    pick the same first token — across chunk widths that divide the
    prompt, straddle it, and cover it whole."""
    cfg, params = setup
    prompt = np.asarray(jax.random.randint(jax.random.key(30), (13,),
                                           0, cfg.vocab))
    ref_cache, ref_logits = prefill(
        params, cfg, jnp.asarray([prompt.tolist()], jnp.int32))
    for chunk in (4, 5, 13, 16):
        cache, logits = _chunked_prefill_into(
            params, cfg, init_kv_cache(cfg, 3), 1, prompt, chunk)
        for layer_ref, layer in zip(ref_cache, cache):
            # f32 on CPU: XLA tiles the per-chunk gemms differently per
            # shape, reordering reductions — values agree to float
            # noise; the TOKEN stream (below, and the generate() test)
            # is the exact contract
            np.testing.assert_allclose(
                np.asarray(layer_ref["k"][0, :len(prompt)]),
                np.asarray(layer["k"][1, :len(prompt)]),
                atol=2e-6, rtol=2e-5, err_msg=str(chunk))
            np.testing.assert_allclose(
                np.asarray(layer_ref["v"][0, :len(prompt)]),
                np.asarray(layer["v"][1, :len(prompt)]),
                atol=2e-6, rtol=2e-5, err_msg=str(chunk))
        assert int(jnp.argmax(logits)) == int(jnp.argmax(ref_logits[0])), \
            chunk


def test_prefill_chunk_generation_identical_to_generate(setup):
    """Chunk-prefill the prompt, then decode_step the continuation: the
    stream must equal the fused generate() scan token for token, for
    every chunk width."""
    from dpu_operator_tpu.workloads.decode import decode_step

    cfg, params = setup
    prompt = np.asarray(jax.random.randint(jax.random.key(31), (11,),
                                           0, cfg.vocab))
    want = np.asarray(generate(
        params, cfg, jnp.asarray([prompt.tolist()], jnp.int32),
        steps=8))[0].tolist()
    for chunk in (3, 6, 11):
        cache, logits = _chunked_prefill_into(
            params, cfg, init_kv_cache(cfg, 2), 0, prompt, chunk)
        toks = [int(jnp.argmax(logits))]
        pos = np.zeros(2, np.int32)
        pos[0] = len(prompt)
        last = np.zeros(2, np.int32)
        last[0] = toks[0]
        for _ in range(7):
            step_logits, cache = decode_step(params, cfg, cache,
                                             jnp.asarray(last),
                                             jnp.asarray(pos))
            t = int(jnp.argmax(step_logits[0]))
            toks.append(t)
            last[0] = t
            pos[0] += 1
        assert toks == want, chunk


def test_prefill_chunk_supports_kv_int8_cache(setup):
    """KV8 slotted caches chunk-prefill too: quantized rows land at the
    offset and the continuation decodes coherently (the chunk attends
    the dequantized cache — decode_step's numerics, so identity is
    with the quantized-attention path, not asserted against the
    bf16-attending whole prefill)."""
    from dpu_operator_tpu.workloads.decode import decode_step

    cfg, params = setup
    prompt = np.asarray(jax.random.randint(jax.random.key(32), (9,),
                                           0, cfg.vocab))
    cache, logits = _chunked_prefill_into(
        params, cfg, init_kv_cache(cfg, 2, kv_int8=True), 1, prompt, 4)
    assert cache[0]["k_q"].dtype == jnp.int8
    assert int(np.asarray(
        jnp.abs(cache[0]["k_s"][1, :len(prompt)])).min()) >= 0
    tok = int(jnp.argmax(logits))
    last = np.zeros(2, np.int32)
    last[1] = tok
    pos = np.zeros(2, np.int32)
    pos[1] = len(prompt)
    step_logits, cache = decode_step(params, cfg, cache,
                                     jnp.asarray(last), jnp.asarray(pos))
    assert np.isfinite(np.asarray(step_logits)).all()


def test_prefill_chunk_does_not_retrace_across_fills(setup):
    """One compiled program per (cfg, cache shape, padded width):
    varying n_valid, offset and slot are traced VALUES — the serve
    loop's chunk queue must never pay a re-trace."""
    from dpu_operator_tpu.workloads.decode import prefill_chunk

    cfg, params = setup
    state = {"cache": init_kv_cache(cfg, 2)}
    chunk = np.arange(8, dtype=np.int32) % cfg.vocab

    def call(slot, off, n):
        # prefill_chunk donates its cache: rebind from the return,
        # exactly as the serve executor does
        state["cache"], logits = prefill_chunk(
            params, cfg, state["cache"], jnp.int32(slot),
            jnp.asarray(chunk), jnp.int32(off), jnp.int32(n))
        return logits

    call(0, 0, 8)
    before = prefill_chunk._cache_size()
    call(0, 8, 3)      # different offset + fill
    call(1, 0, 5)      # different slot
    call(1, 5, 1)      # minimal fill
    assert prefill_chunk._cache_size() == before


def test_measure_decode_rejects_degenerate_slope(monkeypatch):
    """The BENCH noise fix: a collapsed slope (absurd roofline
    fraction) must raise a loud degenerate-measurement error instead
    of being published — the warmup makes it unreachable in practice,
    the assert keeps it unrecordable in principle."""
    from dpu_operator_tpu.workloads import decode as decode_mod
    from dpu_operator_tpu.workloads.model import TransformerConfig

    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=2, n_layers=1,
                            d_ff=64, max_seq=32)
    monkeypatch.setattr(decode_mod, "best_marginal_time",
                        lambda *a, **k: 1e-12, raising=False)
    # measure_decode imports best_marginal_time inside the function, so
    # patch the source module it imports from
    from dpu_operator_tpu.workloads import perf as perf_mod
    monkeypatch.setattr(perf_mod, "best_marginal_time",
                        lambda *a, **k: 1e-12)
    with pytest.raises(ValueError, match="degenerate"):
        measure_decode(cfg, batch=1, steps=8, iters=1, best_of=1,
                       warmup_rounds=0, max_sane_frac=100.0)
