"""Device plugin tests over real v1beta1 protobuf wire traffic.

Reference analog: dpusidemanager_test.go:22-49 (node reports allocatable
after real kubelet registration with mock devices) and deviceplugin.go
Allocate semantics (health validation, env export).
"""

import time

import pytest

from dpu_operator_tpu.daemon.device_handler import TpuDeviceHandler
from dpu_operator_tpu.deviceplugin import DevicePlugin, FakeKubelet
from dpu_operator_tpu.utils.path_manager import PathManager


class StaticHandler:
    def __init__(self, devices):
        self.devices = devices

    def get_devices(self):
        return self.devices


@pytest.fixture
def pm(short_tmp):
    return PathManager(short_tmp)


DEVS = {
    f"chip-{i}": {"id": f"chip-{i}", "healthy": True,
                  "dev_path": f"/dev/accel{i}", "coords": [i % 2, i // 2]}
    for i in range(4)
}


def test_register_and_list_and_watch(pm, kube, node_agent):
    node_agent.register_node("tpu-vm-0", labels={"tpu": "true"})
    kubelet = FakeKubelet(pm, node_agent=node_agent, node_name="tpu-vm-0")
    kubelet.start()
    plugin = DevicePlugin(StaticHandler(dict(DEVS)), path_manager=pm,
                          poll_interval=0.1)
    plugin.start()
    try:
        plugin.register_with_kubelet()
        assert kubelet.wait_for_devices("google.com/tpu", 4)
        node = kube.get("v1", "Node", "tpu-vm-0")
        assert node["status"]["allocatable"]["google.com/tpu"] == "4"
    finally:
        plugin.stop()
        kubelet.stop()


def test_list_and_watch_sends_on_change_only(pm):
    handler = StaticHandler(dict(DEVS))
    kubelet = FakeKubelet(pm)
    kubelet.start()
    plugin = DevicePlugin(handler, path_manager=pm, poll_interval=0.05)
    plugin.start()
    try:
        plugin.register_with_kubelet()
        assert kubelet.wait_for_devices("google.com/tpu", 4)
        # mutate: one chip goes unhealthy → a new list arrives
        handler.devices = dict(DEVS)
        handler.devices["chip-3"] = dict(DEVS["chip-3"], healthy=False)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            devs = kubelet.device_lists.get("google.com/tpu", [])
            if any(d.health == "Unhealthy" for d in devs):
                break
            time.sleep(0.05)
        else:
            pytest.fail("unhealthy transition never streamed")
    finally:
        plugin.stop()
        kubelet.stop()


def test_allocate_returns_devices_mounts_env(pm):
    kubelet = FakeKubelet(pm)
    kubelet.start()
    plugin = DevicePlugin(StaticHandler(dict(DEVS)), path_manager=pm,
                          poll_interval=0.1)
    plugin.start()
    try:
        plugin.register_with_kubelet()
        assert kubelet.wait_for_devices("google.com/tpu", 4)
        resp = kubelet.allocate("google.com/tpu", ["chip-0", "chip-1"])
        car = resp.container_responses[0]
        assert car.envs["TPU_DEVICE_IDS"] == "chip-0,chip-1"
        assert car.envs["TPU_CHIP_COORDS"] == "0,0;1,0"
        assert [d.host_path for d in car.devices] == ["/dev/accel0",
                                                      "/dev/accel1"]
    finally:
        plugin.stop()
        kubelet.stop()


def test_allocate_rejects_unhealthy(pm):
    import grpc
    devs = dict(DEVS)
    devs["chip-2"] = dict(DEVS["chip-2"], healthy=False)
    kubelet = FakeKubelet(pm)
    kubelet.start()
    plugin = DevicePlugin(StaticHandler(devs), path_manager=pm,
                          poll_interval=0.1)
    plugin.start()
    try:
        plugin.register_with_kubelet()
        assert kubelet.wait_for_devices("google.com/tpu", 4)
        with pytest.raises(grpc.RpcError) as err:
            kubelet.allocate("google.com/tpu", ["chip-2"])
        assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION
    finally:
        plugin.stop()
        kubelet.stop()


def test_device_handler_blocks_until_setup():
    class SlowVsp:
        def __init__(self):
            self.num = None

        def set_num_chips(self, n):
            self.num = n

        def get_devices(self):
            return {"0000:00:04.0": {"healthy": True}}

    vsp = SlowVsp()
    h = TpuDeviceHandler(vsp, tpu_mode=False, num_chips=8)
    import threading
    results = {}

    t = threading.Thread(
        target=lambda: results.update(devs=h.get_devices()))
    t.start()
    time.sleep(0.2)
    assert "devs" not in results  # blocked on setup
    h.setup_devices()
    t.join(timeout=5)
    assert vsp.num == 8  # SetNumVfs(8) parity
    assert "0000:00:04.0" in results["devs"]


def test_host_side_enforces_pci_ids():
    class BadVsp:
        def set_num_chips(self, n):
            pass

        def get_devices(self):
            return {"chip-0": {"healthy": True}}

    h = TpuDeviceHandler(BadVsp(), tpu_mode=False)
    h.setup_devices()
    with pytest.raises(ValueError, match="PCI"):
        h.get_devices()


def test_topology_hints_reach_kubelet(pm, node_agent):
    """Devices carrying a numa field advertise TopologyInfo so kubelet's
    Topology Manager can co-locate chips (SURVEY.md §5)."""
    from dpu_operator_tpu.deviceplugin import DevicePlugin
    from dpu_operator_tpu.deviceplugin.fake_kubelet import FakeKubelet

    class Handler:
        def get_devices(self):
            return {
                "chip-0": {"id": "chip-0", "healthy": True,
                           "dev_path": "/dev/accel0", "numa": 0},
                "chip-4": {"id": "chip-4", "healthy": True,
                           "dev_path": "/dev/accel4", "numa": 1},
            }

    kubelet = FakeKubelet(pm)
    kubelet.start()
    plugin = DevicePlugin(Handler(), resource="google.com/tpu",
                          path_manager=pm)
    plugin.poll_interval = 0.1
    try:
        plugin.start()
        plugin.register_with_kubelet()
        assert kubelet.wait_for_devices("google.com/tpu", 2)
        devs = {d.ID: d for d in kubelet.device_lists["google.com/tpu"]}
        assert [n.ID for n in devs["chip-0"].topology.nodes] == [0]
        assert [n.ID for n in devs["chip-4"].topology.nodes] == [1]
    finally:
        plugin.stop()
        kubelet.stop()


def test_preferred_allocation_picks_adjacent_chips():
    """ICI-adjacency-aware allocation: on a v5e-16 (4x4) the preferred
    pair out of the four corners + a center pair is the center pair."""
    from dpu_operator_tpu.deviceplugin.server import _preferred_chips
    devices = {
        "chip-0": {"coords": [0, 0]}, "chip-3": {"coords": [0, 3]},
        "chip-12": {"coords": [3, 0]}, "chip-15": {"coords": [3, 3]},
        "chip-5": {"coords": [1, 1]}, "chip-6": {"coords": [1, 2]},
    }
    picked = _preferred_chips(sorted(devices), [], 2, devices)
    assert sorted(picked) == ["chip-5", "chip-6"]


def test_preferred_allocation_honors_must_include():
    from dpu_operator_tpu.deviceplugin.server import _preferred_chips
    devices = {
        "chip-0": {"coords": [0, 0]}, "chip-1": {"coords": [0, 1]},
        "chip-15": {"coords": [3, 3]}, "chip-14": {"coords": [3, 2]},
    }
    picked = _preferred_chips(sorted(devices), ["chip-15"], 2, devices)
    assert "chip-15" in picked
    assert "chip-14" in picked  # its nearest neighbor


def test_preferred_allocation_over_wire(pm):
    """GetPreferredAllocation RPC end to end through the plugin socket."""
    import grpc
    from dpu_operator_tpu.deviceplugin import DevicePlugin
    from dpu_operator_tpu.deviceplugin import kubelet_pb2 as pb

    class Handler:
        def get_devices(self):
            return {
                f"chip-{i}": {"id": f"chip-{i}", "healthy": True,
                              "coords": [i // 4, i % 4]}
                for i in range(16)
            }

    plugin = DevicePlugin(Handler(), resource="google.com/tpu",
                          path_manager=pm)
    try:
        plugin.start()
        plugin._snapshot()
        channel = grpc.insecure_channel(
            f"unix://{pm.device_plugin_socket('google.com/tpu')}")
        call = channel.unary_unary(
            "/v1beta1.DevicePlugin/GetPreferredAllocation",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.PreferredAllocationResponse.FromString)
        resp = call(pb.PreferredAllocationRequest(container_requests=[
            pb.ContainerPreferredAllocationRequest(
                available_deviceIDs=[f"chip-{i}" for i in range(16)],
                allocation_size=4)]), timeout=5, wait_for_ready=True)
        ids = list(resp.container_responses[0].deviceIDs)
        assert len(ids) == 4
        # the four picked chips form a 2x2 block (total pairwise
        # distance 8 is the minimum for 4 chips on a grid)
        coords = [(int(i.split("-")[1]) // 4, int(i.split("-")[1]) % 4)
                  for i in ids]
        cost = sum(abs(a[0]-b[0]) + abs(a[1]-b[1])
                   for x, a in enumerate(coords) for b in coords[x+1:])
        assert cost == 8
        channel.close()
    finally:
        plugin.stop()


def test_preferred_ici_ports_aligns_with_recent_chips():
    """VERDICT r3 #3: port picks follow the chips kubelet just allocated —
    one port per chip (newest allocation first), so an NF pod's ingress
    and egress ride its own two chips."""
    from dpu_operator_tpu.deviceplugin.server import preferred_ici_ports

    devices = {}
    for chip in range(4):
        for port in ("x+", "x-", "y+", "y-"):
            pid = f"ici-{chip}-{port}"
            devices[pid] = {"id": pid, "chip": chip, "healthy": True}
    available = sorted(devices)

    picked = preferred_ici_ports(available, [], 2, devices,
                                 recent_chips=["chip-2", "chip-3"])
    assert picked[0].startswith("ici-2-")
    assert picked[1].startswith("ici-3-")

    # without affinity info, picks cluster by chip index
    picked = preferred_ici_ports(available, [], 2, devices)
    assert [p.split("-")[1] for p in picked] == ["0", "0"]

    # must_include always survives
    picked = preferred_ici_ports(available, ["ici-1-y-"], 2, devices,
                                 recent_chips=["chip-2", "chip-3"])
    assert "ici-1-y-" in picked


def test_ici_port_handler_health_and_coords():
    """Port health comes from the agent's fault state (a dark link leaves
    allocatable even when unwired) and each port carries its source
    chip's torus coords."""
    from dpu_operator_tpu.daemon.device_handler import IciPortDeviceHandler
    from dpu_operator_tpu.ici import SliceTopology

    topo = SliceTopology("v5e-16")
    faults = {(2, "x+")}

    def prober(chip):
        return [{"port": p, "up": False, "wired": False,
                 "fault": (chip, p) in faults}
                for p in ("x+", "x-", "y+", "y-")]

    handler = IciPortDeviceHandler(lambda: (topo, 0),
                                   link_prober_provider=lambda: prober)
    devs = handler.get_devices()
    assert devs["ici-2-x+"]["healthy"] is False
    assert devs["ici-2-x-"]["healthy"] is True  # unwired-idle is NOT dark
    assert devs["ici-0-x+"]["coords"] == [0, 0]
    assert devs["ici-5-x+"]["coords"] == [1, 1]
    assert devs["ici-5-x+"]["chip"] == 5

    # prober failure reads healthy — flaky telemetry must not blank
    # the allocatable set
    def broken(chip):
        raise ConnectionError("agent down")

    handler2 = IciPortDeviceHandler(lambda: (topo, 0),
                                    link_prober_provider=lambda: broken)
    devs2 = handler2.get_devices()
    assert all(d["healthy"] for d in devs2.values())
