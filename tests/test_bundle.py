"""OLM bundle consistency (VERDICT r2 #10 / missing #6): annotations,
scorecard config, bundle.Dockerfile, and manifest set must agree with each
other and with config/ — the lint an `operator-sdk bundle validate` run
would do (no operator-sdk in this env). Reference: /root/reference/bundle/,
bundle.Dockerfile."""

import os

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUNDLE = os.path.join(REPO, "bundle")


def _load(path):
    with open(path) as f:
        return yaml.safe_load(f)


def test_annotations_paths_exist_and_name_package():
    ann = _load(os.path.join(BUNDLE, "metadata", "annotations.yaml"))
    a = ann["annotations"]
    assert a["operators.operatorframework.io.bundle.package.v1"] == \
        "tpu-operator"
    for key in ("manifests", "metadata"):
        rel = a[f"operators.operatorframework.io.bundle.{key}.v1"]
        assert os.path.isdir(os.path.join(BUNDLE, rel.rstrip("/"))), rel
    sc = a["operators.operatorframework.io.test.config.v1"]
    assert os.path.isfile(os.path.join(BUNDLE, sc.rstrip("/"),
                                       "config.yaml"))


def test_bundle_dockerfile_matches_annotations():
    """Every LABEL in bundle.Dockerfile must equal the corresponding
    annotation (OLM requires the two to agree), and every COPY source
    must exist."""
    ann = _load(os.path.join(BUNDLE, "metadata", "annotations.yaml"))
    labels = {}
    with open(os.path.join(REPO, "bundle.Dockerfile")) as f:
        for line in f:
            line = line.strip()
            if line.startswith("LABEL "):
                k, _, v = line[len("LABEL "):].partition("=")
                labels[k] = v
            elif line.startswith("COPY "):
                src = line.split()[1]
                assert os.path.exists(os.path.join(REPO, src)), src
    for k, v in labels.items():
        assert ann["annotations"].get(k) == v, k


def test_csv_owned_crds_are_shipped():
    csv = _load(os.path.join(
        BUNDLE, "manifests", "tpu-operator.clusterserviceversion.yaml"))
    owned = {c["name"] for c in
             csv["spec"]["customresourcedefinitions"]["owned"]}
    shipped = set()
    for fname in os.listdir(os.path.join(BUNDLE, "manifests")):
        obj = _load(os.path.join(BUNDLE, "manifests", fname))
        if obj.get("kind") == "CustomResourceDefinition":
            shipped.add(obj["metadata"]["name"])
    assert owned == shipped
    assert csv["metadata"]["name"].startswith("tpu-operator.v")
    assert "alm-examples" in csv["metadata"]["annotations"]


def test_bundle_crds_match_config_bases():
    """The bundle ships the SAME CRDs config/crd/bases installs — no
    drift between `make deploy` and the OLM path."""
    bases = os.path.join(REPO, "config", "crd", "bases")
    for fname in os.listdir(bases):
        bundled = os.path.join(BUNDLE, "manifests", fname)
        assert os.path.isfile(bundled), f"{fname} missing from bundle"
        with open(os.path.join(bases, fname)) as a, open(bundled) as b:
            assert a.read() == b.read(), f"{fname} drifted"


def test_scorecard_config_well_formed():
    cfg = _load(os.path.join(BUNDLE, "tests", "scorecard", "config.yaml"))
    assert cfg["kind"] == "Configuration"
    tests = [t for stage in cfg["stages"] for t in stage["tests"]]
    suites = {t["labels"]["suite"] for t in tests}
    assert {"basic", "olm"} <= suites
    for t in tests:
        assert t["entrypoint"][0] == "scorecard-test"
        assert t["image"].startswith("quay.io/operator-framework/")


def test_bundle_services_consistent_with_config():
    """The webhook Service in the bundle and in config/webhook must agree
    on ports (same backing server)."""
    bundled = _load(os.path.join(
        BUNDLE, "manifests", "tpu-operator-webhook-service_v1_service.yaml"))
    with open(os.path.join(REPO, "config", "webhook", "webhook.yaml")) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    cfg_svc = next(d for d in docs if d.get("kind") == "Service")
    assert bundled["spec"]["ports"] == cfg_svc["spec"]["ports"]
    assert (bundled["metadata"]["name"] == cfg_svc["metadata"]["name"]
            == "tpu-operator-webhook-service")


def test_csv_cluster_permissions_match_role_yaml():
    """The CSV's inline clusterPermissions must be byte-for-byte the rules
    the SA's cluster-scoped bindings grant — manager role + metrics-auth
    role (the rules tests/test_rbac.py and tests/test_metrics_auth.py
    enforce) — and its namespaced permissions the leader-election Role.
    An OLM install and a `make deploy` must agree."""
    csv = _load(os.path.join(
        BUNDLE, "manifests", "tpu-operator.clusterserviceversion.yaml"))
    role = _load(os.path.join(REPO, "config", "rbac", "role.yaml"))
    metrics_auth = _load(os.path.join(REPO, "config", "rbac",
                                      "metrics_auth_role.yaml"))
    perms = csv["spec"]["install"]["spec"]["clusterPermissions"]
    assert len(perms) == 1
    assert perms[0]["serviceAccountName"] == \
        "tpu-operator-controller-manager"
    assert perms[0]["rules"] == role["rules"] + metrics_auth["rules"]
    leader = _load(os.path.join(REPO, "config", "rbac",
                                "leader_election_role.yaml"))
    ns_perms = csv["spec"]["install"]["spec"]["permissions"]
    assert len(ns_perms) == 1
    assert ns_perms[0]["serviceAccountName"] == \
        "tpu-operator-controller-manager"
    assert ns_perms[0]["rules"] == leader["rules"]


def test_csv_deployment_matches_manager_yaml():
    """The OLM deployment must run the SAME manager as `make deploy`:
    identical command (incl. --leader-elect) and identical image env
    values — name-only checks would let the two image matrices drift."""
    from dpu_operator_tpu.images.images import _ENV_VARS

    csv = _load(os.path.join(
        BUNDLE, "manifests", "tpu-operator.clusterserviceversion.yaml"))
    dep = csv["spec"]["install"]["spec"]["deployments"][0]
    csv_container = dep["spec"]["template"]["spec"]["containers"][0]

    with open(os.path.join(REPO, "config", "manager",
                           "manager.yaml")) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    mgr = next(d for d in docs if d.get("kind") == "Deployment")
    mgr_container = mgr["spec"]["template"]["spec"]["containers"][0]

    assert csv_container["command"] == mgr_container["command"]
    assert csv_container["image"] == mgr_container["image"]
    csv_env = {e["name"]: e.get("value") for e in csv_container["env"]}
    mgr_env = {e["name"]: e.get("value") for e in mgr_container["env"]}
    for env_name in _ENV_VARS.values():
        assert env_name in csv_env, env_name
        assert csv_env[env_name] == mgr_env[env_name], env_name


def test_csv_webhookdefinitions_match_config_webhook():
    """CSV webhookdefinitions carry the same rules/paths as the raw
    config/webhook registration (the wire-tested one)."""
    csv = _load(os.path.join(
        BUNDLE, "manifests", "tpu-operator.clusterserviceversion.yaml"))
    defs = {d["generateName"]: d for d in csv["spec"]["webhookdefinitions"]}
    with open(os.path.join(REPO, "config", "webhook", "webhook.yaml")) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    n_config_webhooks = sum(
        len(doc.get("webhooks") or []) for doc in docs
        if doc.get("kind", "").endswith("WebhookConfiguration"))
    # two-way: no extra/stale CSV definition either
    assert len(defs) == n_config_webhooks
    for doc in docs:
        if doc["kind"] not in ("ValidatingWebhookConfiguration",
                               "MutatingWebhookConfiguration"):
            continue
        for wh in doc["webhooks"]:
            d = defs[wh["name"]]
            assert d["rules"] == wh["rules"], wh["name"]
            assert d["webhookPath"] == wh["clientConfig"]["service"]["path"]
            expected_type = ("ValidatingAdmissionWebhook"
                             if doc["kind"].startswith("Validating")
                             else "MutatingAdmissionWebhook")
            assert d["type"] == expected_type
            # availability-critical semantics must match too (a flipped
            # failurePolicy would change cluster behavior under webhook
            # outage); absent means the k8s default, Fail
            assert (d.get("failurePolicy", "Fail")
                    == wh.get("failurePolicy", "Fail")), wh["name"]
            assert (d.get("sideEffects") == wh.get("sideEffects")), wh["name"]
            assert (d.get("admissionReviewVersions")
                    == wh.get("admissionReviewVersions")), wh["name"]


# -- package-manifests channel (manifests/, reference parity) ----------------

MANIFESTS = os.path.join(REPO, "manifests")


def test_package_manifests_mirror_the_bundle():
    """manifests/stable must stay byte-identical to bundle/manifests
    (both install formats describe the same operator; reference ships
    both: manifests/ + bundle/). Two-way: an orphan file left in
    stable/ after a bundle manifest is removed fails too."""
    import filecmp
    bundle_files = set(os.listdir(os.path.join(BUNDLE, "manifests")))
    stable_files = set(os.listdir(os.path.join(MANIFESTS, "stable")))
    assert stable_files - {"image-references"} == bundle_files, (
        "manifests/stable and bundle/manifests diverged: "
        f"{stable_files ^ bundle_files}")
    for fname in sorted(bundle_files):
        assert filecmp.cmp(
            os.path.join(BUNDLE, "manifests", fname),
            os.path.join(MANIFESTS, "stable", fname), shallow=False), \
            f"manifests/stable/{fname} drifted from bundle/manifests"


def test_package_channel_points_at_the_csv():
    pkg = _load(os.path.join(MANIFESTS, "tpu-operator.package.yaml"))
    csv = _load(os.path.join(MANIFESTS, "stable",
                             "tpu-operator.clusterserviceversion.yaml"))
    assert pkg["packageName"] == "tpu-operator"
    stable = next(c for c in pkg["channels"] if c["name"] == "stable")
    assert stable["currentCSV"] == csv["metadata"]["name"]


def test_image_references_cover_the_image_matrix():
    """Every image the operator deploys (images.py env matrix) has a
    release-pipeline substitution tag (reference:
    manifests/stable/image-references)."""
    refs = _load(os.path.join(MANIFESTS, "stable", "image-references"))
    tags = {t["name"] for t in refs["spec"]["tags"]}
    # exact tag-name set: one per deployable image + the operator
    assert tags == {"tpu-operator", "tpu-daemon", "tpu-vsp", "tpu-cni",
                    "network-resources-injector", "tpu-cp-agent",
                    "tpu-workload"}
    for t in refs["spec"]["tags"]:
        assert t["from"]["kind"] == "DockerImage"
        assert t["name"] in t["from"]["name"], t
