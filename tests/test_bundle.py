"""OLM bundle consistency (VERDICT r2 #10 / missing #6): annotations,
scorecard config, bundle.Dockerfile, and manifest set must agree with each
other and with config/ — the lint an `operator-sdk bundle validate` run
would do (no operator-sdk in this env). Reference: /root/reference/bundle/,
bundle.Dockerfile."""

import os

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUNDLE = os.path.join(REPO, "bundle")


def _load(path):
    with open(path) as f:
        return yaml.safe_load(f)


def test_annotations_paths_exist_and_name_package():
    ann = _load(os.path.join(BUNDLE, "metadata", "annotations.yaml"))
    a = ann["annotations"]
    assert a["operators.operatorframework.io.bundle.package.v1"] == \
        "tpu-operator"
    for key in ("manifests", "metadata"):
        rel = a[f"operators.operatorframework.io.bundle.{key}.v1"]
        assert os.path.isdir(os.path.join(BUNDLE, rel.rstrip("/"))), rel
    sc = a["operators.operatorframework.io.test.config.v1"]
    assert os.path.isfile(os.path.join(BUNDLE, sc.rstrip("/"),
                                       "config.yaml"))


def test_bundle_dockerfile_matches_annotations():
    """Every LABEL in bundle.Dockerfile must equal the corresponding
    annotation (OLM requires the two to agree), and every COPY source
    must exist."""
    ann = _load(os.path.join(BUNDLE, "metadata", "annotations.yaml"))
    labels = {}
    with open(os.path.join(REPO, "bundle.Dockerfile")) as f:
        for line in f:
            line = line.strip()
            if line.startswith("LABEL "):
                k, _, v = line[len("LABEL "):].partition("=")
                labels[k] = v
            elif line.startswith("COPY "):
                src = line.split()[1]
                assert os.path.exists(os.path.join(REPO, src)), src
    for k, v in labels.items():
        assert ann["annotations"].get(k) == v, k


def test_csv_owned_crds_are_shipped():
    csv = _load(os.path.join(
        BUNDLE, "manifests", "tpu-operator.clusterserviceversion.yaml"))
    owned = {c["name"] for c in
             csv["spec"]["customresourcedefinitions"]["owned"]}
    shipped = set()
    for fname in os.listdir(os.path.join(BUNDLE, "manifests")):
        obj = _load(os.path.join(BUNDLE, "manifests", fname))
        if obj.get("kind") == "CustomResourceDefinition":
            shipped.add(obj["metadata"]["name"])
    assert owned == shipped
    assert csv["metadata"]["name"].startswith("tpu-operator.v")
    assert "alm-examples" in csv["metadata"]["annotations"]


def test_bundle_crds_match_config_bases():
    """The bundle ships the SAME CRDs config/crd/bases installs — no
    drift between `make deploy` and the OLM path."""
    bases = os.path.join(REPO, "config", "crd", "bases")
    for fname in os.listdir(bases):
        bundled = os.path.join(BUNDLE, "manifests", fname)
        assert os.path.isfile(bundled), f"{fname} missing from bundle"
        with open(os.path.join(bases, fname)) as a, open(bundled) as b:
            assert a.read() == b.read(), f"{fname} drifted"


def test_scorecard_config_well_formed():
    cfg = _load(os.path.join(BUNDLE, "tests", "scorecard", "config.yaml"))
    assert cfg["kind"] == "Configuration"
    tests = [t for stage in cfg["stages"] for t in stage["tests"]]
    suites = {t["labels"]["suite"] for t in tests}
    assert {"basic", "olm"} <= suites
    for t in tests:
        assert t["entrypoint"][0] == "scorecard-test"
        assert t["image"].startswith("quay.io/operator-framework/")


def test_bundle_services_consistent_with_config():
    """The webhook Service in the bundle and in config/webhook must agree
    on ports (same backing server)."""
    bundled = _load(os.path.join(
        BUNDLE, "manifests", "tpu-operator-webhook-service_v1_service.yaml"))
    with open(os.path.join(REPO, "config", "webhook", "webhook.yaml")) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    cfg_svc = next(d for d in docs if d.get("kind") == "Service")
    assert bundled["spec"]["ports"] == cfg_svc["spec"]["ports"]
    assert (bundled["metadata"]["name"] == cfg_svc["metadata"]["name"]
            == "tpu-operator-webhook-service")
