"""FakeKube behavior tests — the envtest/Kind-analog foundation."""

import pytest

from dpu_operator_tpu.k8s import FakeKube, FakeNodeAgent
from dpu_operator_tpu.k8s.fake import AlreadyExists, Conflict


def _cm(name, ns="default", data=None):
    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": ns},
            "data": data or {}}


def test_create_get_roundtrip(kube):
    kube.create(_cm("a", data={"k": "v"}))
    got = kube.get("v1", "ConfigMap", "a", namespace="default")
    assert got["data"] == {"k": "v"}
    assert got["metadata"]["uid"]


def test_create_duplicate_raises(kube):
    kube.create(_cm("a"))
    with pytest.raises(AlreadyExists):
        kube.create(_cm("a"))


def test_update_conflict_on_stale_rv(kube):
    kube.create(_cm("a"))
    fresh = kube.get("v1", "ConfigMap", "a", namespace="default")
    kube.update(fresh)
    stale = dict(fresh)
    with pytest.raises(Conflict):
        kube.update(stale)


def test_apply_merges(kube):
    kube.create(_cm("a", data={"k1": "v1"}))
    kube.apply(_cm("a", data={"k2": "v2"}))
    got = kube.get("v1", "ConfigMap", "a", namespace="default")
    assert got["data"] == {"k1": "v1", "k2": "v2"}


def test_watch_sees_existing_and_new(kube):
    kube.create(_cm("a"))
    events = []
    cancel = kube.watch("v1", "ConfigMap", lambda e, o: events.append((e, o["metadata"]["name"])))
    kube.create(_cm("b"))
    assert ("ADDED", "a") in events and ("ADDED", "b") in events
    cancel()
    kube.create(_cm("c"))
    assert all(n != "c" for _, n in events)


def test_pod_scheduling_respects_allocatable(kube):
    agent = FakeNodeAgent(kube)
    agent.start()
    agent.register_node("n0", allocatable={"google.com/tpu": "4"})

    def tpu_pod(name, n):
        return {"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": name, "namespace": "default"},
                "spec": {"containers": [{
                    "name": "c", "image": "x",
                    "resources": {"requests": {"google.com/tpu": str(n)}}}]},
                "status": {"phase": "Pending"}}

    kube.create(tpu_pod("p1", 4))
    agent.sync()
    assert kube.get("v1", "Pod", "p1", namespace="default")["status"]["phase"] == "Running"

    # second pod exceeds capacity → Pending (e2e_test.go:525-593 analog)
    kube.create(tpu_pod("p2", 1))
    agent.sync()
    assert kube.get("v1", "Pod", "p2", namespace="default")["status"]["phase"] == "Pending"

    # free capacity → p2 schedules
    kube.delete("v1", "Pod", "p1", namespace="default")
    agent.sync()
    assert kube.get("v1", "Pod", "p2", namespace="default")["status"]["phase"] == "Running"
    agent.stop()


def test_dangling_owner_reference_is_garbage_collected(kube):
    """Real-apiserver GC parity: an object created whose ownerReference
    uids no longer resolve is collected — the window a cache-fed
    reconciler can hit by re-applying children just after its CR was
    deleted (the real GC controller deletes such orphans too)."""
    owner = kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                         "metadata": {"name": "owner",
                                      "namespace": "default"}})
    uid = owner["metadata"]["uid"]
    kube.delete("v1", "ConfigMap", "owner", namespace="default")
    kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                 "metadata": {"name": "orphan", "namespace": "default",
                              "ownerReferences": [{
                                  "apiVersion": "v1", "kind": "ConfigMap",
                                  "name": "owner", "uid": uid,
                                  "controller": True}]}})
    assert kube.get("v1", "ConfigMap", "orphan",
                    namespace="default") is None

    # a LIVE owner keeps its child; refs without a uid are ignored
    live = kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                        "metadata": {"name": "live",
                                     "namespace": "default"}})
    kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                 "metadata": {"name": "child", "namespace": "default",
                              "ownerReferences": [{
                                  "apiVersion": "v1", "kind": "ConfigMap",
                                  "name": "live",
                                  "uid": live["metadata"]["uid"]}]}})
    assert kube.get("v1", "ConfigMap", "child",
                    namespace="default") is not None
    kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                 "metadata": {"name": "no-uid-ref", "namespace": "default",
                              "ownerReferences": [{
                                  "apiVersion": "v1", "kind": "ConfigMap",
                                  "name": "whatever"}]}})
    assert kube.get("v1", "ConfigMap", "no-uid-ref",
                    namespace="default") is not None
