"""/metrics authentication + authorization (VERDICT r4 #5).

The reference filters its metrics endpoint with
WithAuthenticationAndAuthorization (cmd/main.go:66-70) backed by
TokenReview/SubjectAccessReview and ships metrics_auth/reader RBAC.
Here the same filter runs over the wire: the MetricsServer's
TokenReviewAuth POSTs reviews to the HTTPS apiserver fixture under the
operator SA (whose right to do so comes from metrics_auth_role.yaml),
and scrapers pass only when bound to metrics_reader_role.yaml."""

import os

import pytest
import requests
import yaml

from dpu_operator_tpu.k8s.real import RealKube
from dpu_operator_tpu.utils.metrics import MetricsServer, TokenReviewAuth

from apiserver_fixture import MiniApiServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RBAC_DIR = os.path.join(REPO, "config", "rbac")

SA_SUBJECT = {"kind": "ServiceAccount",
              "name": "tpu-operator-controller-manager",
              "namespace": "tpu-operator-system"}
SA_TOKEN = "operator-sa-token"
SCRAPER_SUBJECT = {"kind": "ServiceAccount", "name": "prometheus-k8s",
                   "namespace": "monitoring"}
SCRAPER_TOKEN = "scraper-token"
RANDO_TOKEN = "unbound-subject-token"


def _rbac_objects():
    objs = []
    for fname in sorted(os.listdir(RBAC_DIR)):
        with open(os.path.join(RBAC_DIR, fname)) as f:
            objs.extend(o for o in yaml.safe_load_all(f)
                        if o and o.get("kind") and o.get("apiVersion"))
    return objs


@pytest.fixture
def stack(tmp_path):
    """apiserver (RBAC enforced) + operator-identity client + secured
    MetricsServer, with the scraper bound to the metrics-reader role."""
    srv = MiniApiServer()
    srv.rbac_enabled = True
    srv.token_subjects[SA_TOKEN] = SA_SUBJECT
    srv.token_subjects[SCRAPER_TOKEN] = SCRAPER_SUBJECT
    srv.token_subjects[RANDO_TOKEN] = {
        "kind": "ServiceAccount", "name": "rando", "namespace": "default"}
    for obj in _rbac_objects():
        srv.kube.create(obj)
    # a cluster admin binds the scraper to the shipped reader role
    srv.kube.create({
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRoleBinding",
        "metadata": {"name": "prometheus-metrics-reader"},
        "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                    "kind": "ClusterRole",
                    "name": "tpu-operator-metrics-reader"},
        "subjects": [SCRAPER_SUBJECT]})
    srv.start()
    client = RealKube(kubeconfig=srv.write_kubeconfig(
        str(tmp_path / "kubeconfig"), token=SA_TOKEN))
    ms = MetricsServer(host="127.0.0.1",
                       auth=TokenReviewAuth(client, ttl=0.0))
    ms.start()
    yield srv, ms
    ms.stop()
    srv.stop()


def _get(port, path, token=None):
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    return requests.get(f"http://127.0.0.1:{port}{path}", headers=headers,
                        timeout=5)


def test_anonymous_metrics_is_401(stack):
    _, ms = stack
    assert _get(ms.port, "/metrics").status_code == 401


def test_garbage_token_is_403(stack):
    _, ms = stack
    assert _get(ms.port, "/metrics", token="no-such-token").status_code \
        == 403


def test_authenticated_but_unbound_subject_is_403(stack):
    """TokenReview passes (known subject) but SubjectAccessReview denies
    (no metrics-reader binding)."""
    _, ms = stack
    assert _get(ms.port, "/metrics", token=RANDO_TOKEN).status_code == 403


def test_bound_scraper_reads_metrics(stack):
    _, ms = stack
    resp = _get(ms.port, "/metrics", token=SCRAPER_TOKEN)
    assert resp.status_code == 200
    assert "tpu_" in resp.text  # actual Prometheus exposition


def test_health_endpoints_stay_open(stack):
    """kubelet probes cannot attach tokens: /healthz and /readyz must not
    require auth (the reference filters only metrics)."""
    _, ms = stack
    assert _get(ms.port, "/healthz").status_code == 200
    assert _get(ms.port, "/readyz").status_code == 200


def test_unauthed_server_still_serves_openly():
    """No auth hook (daemon-local/dev use): /metrics stays open."""
    ms = MetricsServer(host="127.0.0.1")
    ms.start()
    try:
        assert _get(ms.port, "/metrics").status_code == 200
    finally:
        ms.stop()


def test_review_rpcs_require_metrics_auth_role(tmp_path):
    """The operator SA's right to POST reviews comes from
    metrics_auth_role.yaml: strip its binding and the auth filter fails
    CLOSED (503-ish deny), never open."""
    srv = MiniApiServer()
    srv.rbac_enabled = True
    srv.token_subjects[SA_TOKEN] = SA_SUBJECT
    srv.token_subjects[SCRAPER_TOKEN] = SCRAPER_SUBJECT
    for obj in _rbac_objects():
        if obj["metadata"].get("name") == \
                "tpu-operator-metrics-auth-rolebinding":
            continue  # the binding is gone
        srv.kube.create(obj)
    srv.start()
    client = RealKube(kubeconfig=srv.write_kubeconfig(
        str(tmp_path / "kubeconfig"), token=SA_TOKEN))
    ms = MetricsServer(host="127.0.0.1",
                       auth=TokenReviewAuth(client, ttl=0.0))
    ms.start()
    try:
        # even a legitimately-bound scraper is denied: the filter cannot
        # verify anyone without its own review permissions
        assert _get(ms.port, "/metrics",
                    token=SCRAPER_TOKEN).status_code == 403
    finally:
        ms.stop()
        srv.stop()


class _FlakyClient:
    """create() raises once, then delegates to canned review answers."""

    def __init__(self):
        self.fail_next = True
        self.calls = 0

    def create(self, obj):
        self.calls += 1
        if self.fail_next:
            self.fail_next = False
            raise ConnectionError("apiserver blip")
        if obj["kind"] == "TokenReview":
            return dict(obj, status={
                "authenticated": True,
                "user": {"username": "system:serviceaccount:m:prom",
                         "groups": []}})
        return dict(obj, status={"allowed": True})


def test_transient_review_error_is_not_cached():
    """One apiserver blip must deny only THAT scrape — caching the error
    verdict for the TTL would flap the target down for a minute."""
    client = _FlakyClient()
    auth = TokenReviewAuth(client, ttl=3600.0)
    assert auth("tok") is False  # fail closed on the error
    assert auth("tok") is True   # next scrape re-reviews and passes
    assert auth("tok") is True   # and THIS one is served from cache
    assert client.calls == 3     # 1 failed + TR + SAR


def test_cache_never_holds_plaintext_tokens():
    client = _FlakyClient()
    client.fail_next = False
    auth = TokenReviewAuth(client, ttl=3600.0)
    secret = "sa-bearer-token-hunter2"
    assert auth(secret) is True
    assert secret not in auth._cache
    assert all(secret not in k for k in auth._cache)


def test_cache_evicts_oldest_past_1024_tokens():
    """Token churn (rotating SA tokens) must bound the verdict cache:
    the 1025th distinct token pops the OLDEST entry, which then pays a
    fresh review on its next scrape while a younger entry is still
    served from cache."""
    client = _FlakyClient()
    client.fail_next = False
    auth = TokenReviewAuth(client, ttl=3600.0)
    for i in range(1025):
        assert auth(f"token-{i}") is True
    assert len(auth._cache) == 1024
    assert auth._key("token-0") not in auth._cache   # oldest evicted
    assert auth._key("token-1") in auth._cache       # survivor intact
    calls = client.calls
    assert auth("token-1") is True                   # cache hit
    assert client.calls == calls
    assert auth("token-0") is True                   # re-reviewed
    assert client.calls == calls + 2                 # TR + SAR again


# -- least-privilege RBAC (the split files are load-bearing) -----------------

@pytest.fixture
def rbac_clients(tmp_path):
    srv = MiniApiServer()
    srv.rbac_enabled = True
    srv.token_subjects[SA_TOKEN] = SA_SUBJECT
    for obj in _rbac_objects():
        srv.kube.create(obj)
    srv.start()
    sa = RealKube(kubeconfig=srv.write_kubeconfig(
        str(tmp_path / "kc"), token=SA_TOKEN))
    yield srv, sa
    srv.stop()


def test_manager_cannot_touch_foreign_clusterroles(rbac_clients):
    """resourceNames scoping: the operator may manage ITS OWN bindata
    RBAC but cannot delete or edit arbitrary cluster roles — the
    escalation surface VERDICT r4 flagged."""
    srv, sa = rbac_clients
    srv.kube.create({"apiVersion": "rbac.authorization.k8s.io/v1",
                     "kind": "ClusterRole",
                     "metadata": {"name": "cluster-admin-ish"},
                     "rules": []})
    with pytest.raises(requests.HTTPError) as exc:
        sa.delete("rbac.authorization.k8s.io/v1", "ClusterRole",
                  "cluster-admin-ish")
    assert exc.value.response.status_code == 403
    # its own daemon role: create then mutate, both allowed
    sa.create({"apiVersion": "rbac.authorization.k8s.io/v1",
               "kind": "ClusterRole",
               "metadata": {"name": "tpu-daemon"}, "rules": []})
    role = sa.get("rbac.authorization.k8s.io/v1", "ClusterRole",
                  "tpu-daemon")
    role["rules"] = [{"apiGroups": [""], "resources": ["pods"],
                      "verbs": ["get"]}]
    sa.update(role)
    sa.delete("rbac.authorization.k8s.io/v1", "ClusterRole", "tpu-daemon")


def test_leases_are_namespace_scoped(rbac_clients):
    """The leader-election grant is a namespaced Role: leases in the
    operator namespace work, leases elsewhere are forbidden (the old
    cluster-wide grant is gone)."""
    _, sa = rbac_clients
    lease = {"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
             "metadata": {"name": "tpu-operator-leader",
                          "namespace": "tpu-operator-system"},
             "spec": {"holderIdentity": "me"}}
    sa.create(lease)
    with pytest.raises(requests.HTTPError) as exc:
        sa.create({"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                   "metadata": {"name": "x", "namespace": "kube-system"},
                   "spec": {"holderIdentity": "me"}})
    assert exc.value.response.status_code == 403


# -- every /debug/* handler honors the token filter ---------------------------

#: the debug surface the fleet plane federates over: every one of these
#: serves operational detail equivalent to a metrics scrape, so every
#: one must sit behind the SAME token filter
DEBUG_PATHS = ("/debug", "/debug/flight", "/debug/health",
               "/debug/serve", "/debug/serve/ledger",
               "/debug/serve/headroom", "/debug/fleet",
               "/debug/profile", "/debug/history")


@pytest.fixture
def debug_server():
    """A MetricsServer with the full debug surface registered (the
    serve + fleet handlers a production daemon/operator wires) behind
    a deterministic auth callable."""
    ms = MetricsServer(
        host="127.0.0.1",
        auth=lambda token: token == "good-token",
        health_check=lambda: {"healthy": True},
        debug_handlers={
            "/debug/serve": lambda: {"ok": "serve"},
            "/debug/serve/ledger": lambda: {"ok": "ledger"},
            "/debug/serve/headroom": lambda: {"ok": "headroom"},
            "/debug/fleet": lambda: {"ok": "fleet"},
            "/debug/profile": lambda: {"ok": "profile"},
            "/debug/history": lambda: {"ok": "history"},
        })
    ms.start()
    yield ms
    ms.stop()


def test_debug_index_lists_the_whole_surface(debug_server):
    """The parametrization below iterates DEBUG_PATHS; this guard
    asserts that list IS the index — a newly registered handler that
    nobody added to DEBUG_PATHS fails here instead of shipping
    untested."""
    resp = _get(debug_server.port, "/debug", token="good-token")
    assert resp.status_code == 200
    listed = set(resp.json()["debugHandlers"])
    assert listed == set(DEBUG_PATHS) - {"/debug"}


@pytest.mark.parametrize("path", DEBUG_PATHS)
def test_every_debug_endpoint_honors_the_token_filter(
        debug_server, path):
    # unauthenticated read -> 401; wrong token -> 403; never a body
    anon = _get(debug_server.port, path)
    assert anon.status_code == 401, f"{path} served an anonymous read"
    bad = _get(debug_server.port, path, token="wrong")
    assert bad.status_code == 403, f"{path} served a bad token"
    for denied in (anon, bad):
        assert b"ok" not in denied.content
    # the right token reads it
    good = _get(debug_server.port, path, token="good-token")
    assert good.status_code == 200, f"{path} denied a valid token"
