"""Pallas kernel tests (interpret mode on the CPU mesh; same kernels
compile on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpu_operator_tpu.ops import flash_attention, fused_rmsnorm
from dpu_operator_tpu.ops.flash_attention import flash_attention_vjp
from dpu_operator_tpu.workloads.ring_attention import full_attention


def _qkv(b=2, s=64, h=2, d=16, dtype=jnp.float32):
    keys = jax.random.split(jax.random.key(1), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in keys)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_uneven_blocks_rejected():
    q, k, v = _qkv(s=48)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=32, block_k=32)


def test_flash_attention_single_block():
    q, k, v = _qkv(s=32)
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    ref = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_vjp_matches_autodiff(causal):
    """The Pallas backward (recompute-from-lse, two kernels) must agree
    with autodiff through the naive reference."""
    q, k, v = _qkv()

    def loss_flash(q, k, v):
        o = flash_attention_vjp(q, k, v, causal, 16, 16)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(full_attention(q, k, v, causal=causal)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{name} mismatch")


def test_flash_attention_vjp_forward_matches_forward_only():
    q, k, v = _qkv()
    out = flash_attention_vjp(q, k, v, True, 16, 16)
    ref = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def test_fused_rmsnorm_matches_reference():
    x = jax.random.normal(jax.random.key(2), (4, 32, 128), jnp.float32)
    scale = jax.random.normal(jax.random.key(3), (128,)) + 1.0
    out = fused_rmsnorm(x, scale)
    var = np.mean(np.square(np.asarray(x)), -1, keepdims=True)
    ref = np.asarray(x) / np.sqrt(var + 1e-6) * np.asarray(scale)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


def test_fused_rmsnorm_bf16():
    x = jax.random.normal(jax.random.key(4), (8, 64), jnp.bfloat16)
    scale = jnp.ones((64,), jnp.bfloat16)
    out = fused_rmsnorm(x, scale)
    assert out.dtype == jnp.bfloat16
    assert np.isfinite(np.asarray(out, np.float32)).all()
