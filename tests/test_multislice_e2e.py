"""Multi-slice e2e: two TPU-side daemons joined over DCN (VERDICT r3 #2).

The reference's defining topology is two clusters wired through the
operator (host↔DPU channel from VSP Init, marvell/main.go:691-725, driven
end-to-end by e2e_test.go:399-423). The multi-slice analog: two
TpuSideManagers, each with its OWN native agent and slice topology, joined
into a MultiSliceGroup via slice attachments carrying ``peer_address`` —
then the joint group runs the hierarchical DCN allreduce whose compiled
schedule provably moves 1/n_ici the bytes over the DCN axis, and tearing
an attachment down degrades the group cleanly.
"""

import os
import re
import subprocess

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpu_operator_tpu.daemon import TpuSideManager
from dpu_operator_tpu.daemon.slicejoin import join_slices
from dpu_operator_tpu.platform.platform import FakePlatform
from dpu_operator_tpu.platform.vendordetector import TpuDetector
from dpu_operator_tpu.utils.path_manager import PathManager
from dpu_operator_tpu.vsp.google import GoogleTpuVsp
from dpu_operator_tpu.vsp.native_dp import (AgentClient, AgentProcess,
                                            NativeIciDataplane)
from dpu_operator_tpu.vsp.plugin import GrpcPlugin
from dpu_operator_tpu.vsp.rpc import VspChannel, VspServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="session")
def agent_binary():
    subprocess.run(["make", "-C", os.path.join(REPO, "native")], check=True,
                   capture_output=True)
    return os.path.join(REPO, "native", "build", "tpu_cp_agent")


class _Slice:
    """One slice: its own dir, native agent, GoogleTpuVsp, and TPU-side
    manager serving the cross-boundary TCP plane."""

    def __init__(self, root: str, name: str, agent_binary: str):
        self.dir = os.path.join(root, name)
        os.makedirs(self.dir)
        self.pm = PathManager(self.dir)
        self.agent = AgentProcess(agent_binary, self.dir + "/cp.sock",
                                  state_file=self.dir + "/cp.state",
                                  dev_dir=self.dir, allow_regular_dev=True)
        self.agent.start()
        accel = []
        for i in range(4):
            path = f"{self.dir}/accel{i}"
            open(path, "w").close()
            accel.append(path)
        self.agent_client = AgentClient(self.agent.socket_path)
        self.vsp = GoogleTpuVsp(
            FakePlatform(accelerator_type="v5litepod-4", accel=accel),
            dataplane=NativeIciDataplane(self.agent_client),
            comm_port=0)  # ephemeral: two slices share this host in tests
        sock = self.pm.vendor_plugin_socket()
        self.pm.ensure_socket_dir(sock)
        self.vsp_server = VspServer(self.vsp, socket_path=sock)
        self.vsp_server.start()
        det = TpuDetector().detection_result(tpu_mode=True, identifier=name)
        self.mgr = TpuSideManager(
            GrpcPlugin(det, path_manager=self.pm, init_timeout=5.0), self.pm)
        self.mgr.start_vsp()
        self.mgr.setup_devices()
        self.mgr.listen()  # binds the cross-boundary TCP server

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.mgr.bound_port}"

    def stop(self):
        self.mgr.stop()
        self.vsp_server.stop()
        self.agent_client.close()
        self.agent.stop()


@pytest.fixture
def two_slices(short_tmp, agent_binary):
    a = _Slice(short_tmp, "slice-a", agent_binary)
    b = _Slice(short_tmp, "slice-b", agent_binary)
    yield a, b
    b.stop()
    a.stop()


def _join(frm: str, to: str, name: str):
    """Create the peer-carrying slice attachment over the cross-boundary
    plane (what a multi-slice controller — or tpuctl create-attachment
    --peer — does)."""
    channel = VspChannel(frm)
    try:
        channel.wait_ready(5)
        return channel.call("SliceService", "CreateSliceAttachment",
                            {"name": name, "chip_index": 0,
                             "peer_address": to})
    finally:
        channel.close()


def _unjoin(frm: str, name: str):
    channel = VspChannel(frm)
    try:
        channel.call("SliceService", "DeleteSliceAttachment", {"name": name})
    finally:
        channel.close()


def test_two_daemons_join_into_multislice_group(two_slices):
    """Attachments carrying peer_address wire the two slices together;
    walking the peer graph from EITHER member assembles the same joint
    group; teardown degrades it cleanly back to one slice."""
    a, b = two_slices
    # before the join: each daemon reports a lone v5e-4
    solo = join_slices(a.address)
    assert [s.topology for s in solo.group.slices] == ["v5e-4"]
    assert solo.group.num_chips == 4

    _join(a.address, b.address, "host0-0")
    _join(b.address, a.address, "host0-0")

    for seed in (a.address, b.address):
        result = join_slices(seed)
        assert not result.degraded
        assert sorted(result.members) == sorted([a.address, b.address])
        assert result.group.num_chips == 8
        assert [s.topology for s in result.group.slices] == [
            "v5e-4", "v5e-4"]
        assert result.group.dcn_allreduce_algbw_gbps() > 0

    # the native agents each programmed their own slice (4 chips each,
    # chip 0 attached by the join's attachment)
    for s in (a, b):
        chips = s.agent_client.enumerate()
        assert len(chips) == 4
        assert chips[0]["attached"]

    # teardown A's side: the group seen from A degrades to A alone...
    _unjoin(a.address, "host0-0")
    from_a = join_slices(a.address)
    assert from_a.group.num_chips == 4
    assert from_a.members == [a.address]
    # ...B still lists A (one-way), and the walk from B still sees both
    from_b = join_slices(b.address)
    assert from_b.group.num_chips == 8
    _unjoin(b.address, "host0-0")
    assert join_slices(b.address).group.num_chips == 4


def test_dead_peer_degrades_join_instead_of_wedging(two_slices):
    """A peer that died after joining leaves the walk degraded-but-alive:
    the survivors form the group and the dead address is reported."""
    a, b = two_slices
    _join(a.address, b.address, "host0-0")
    b_addr = b.address
    b.stop()

    result = join_slices(a.address, dial_timeout=1.0)
    assert result.degraded
    assert result.unreachable == [b_addr]
    assert result.group.num_chips == 4
    assert result.members == [a.address]

    # restart-b path is covered by the fixture teardown tolerating the
    # double stop
    b.stop()


def _element_count(shape: str) -> int:
    dims = [int(d) for d in shape.split(",") if d]
    count = 1
    for d in dims:
        count *= d
    return count


def test_hierarchical_allreduce_over_joined_group(two_slices):
    """The workload proof on the JOINED group: the combined virtual mesh
    (one axis per slice over DCN, ICI axes within) runs the hierarchical
    allreduce, numerics match the flat psum, and the COMPILED schedule's
    cross-slice all-reduce operates on 1/n_ici-sized shards — the DCN
    axis carries 1/n_ici the bytes, which is the whole point of the
    schedule (workloads/multislice.py)."""
    from dpu_operator_tpu.workloads.multislice import (
        dcn_bytes_per_host, flat_allreduce, hierarchical_allreduce,
        make_multislice_mesh)

    a, b = two_slices
    _join(a.address, b.address, "host0-0")
    _join(b.address, a.address, "host0-0")
    result = join_slices(a.address)
    n_slices = len(result.group.slices)
    assert n_slices == 2

    chips = result.group.num_chips  # 8 — matches the virtual CPU mesh
    devices = jax.devices()[:chips]
    mesh = make_multislice_mesh(n_slices, devices=devices)
    n_ici = mesh.shape["model"]
    assert n_ici > 1

    n = 1 << 14
    x = jnp.arange(n, dtype=jnp.float32)
    hier = hierarchical_allreduce(mesh)
    flat = flat_allreduce(mesh)
    np.testing.assert_allclose(np.asarray(hier(x)), np.asarray(flat(x)),
                               rtol=1e-6)

    # compiled-schedule proof: the hierarchical path's all-reduce (the
    # DCN stage) runs on shards n_ici-times smaller than the flat one's
    # HLO shape precedes the op: `%psum.7 = f32[2048]{0} all-reduce(...)`
    shape_re = re.compile(r"=\s*\w+\[([\d,]*)\](?:\{[^}]*\})?\s+all-reduce\(")

    def allreduce_elems(fn):
        text = fn.lower(x).compile().as_text()
        sizes = [_element_count(m.group(1))
                 for m in shape_re.finditer(text)]
        assert sizes, "no all-reduce in compiled HLO"
        return max(sizes)

    flat_elems = allreduce_elems(flat)
    hier_elems = allreduce_elems(hier)
    assert hier_elems * n_ici == flat_elems, (hier_elems, flat_elems)

    # and the byte model the traffic-flow report publishes agrees
    payload = n * 4
    assert dcn_bytes_per_host(payload, n_ici, n_slices) == pytest.approx(
        dcn_bytes_per_host(payload, n_ici, n_slices,
                           hierarchical=False) / n_ici)


def test_multislice_train_step_shards_batch_over_dcn():
    """Multi-slice data parallelism in the TRAIN STEP (not just the bare
    collective): a mesh with a leading "dcn" axis shards the batch over
    (dcn, data) — each slice takes a shard — while params replicate
    across slices; the step executes and the loss is finite."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from dpu_operator_tpu.workloads import (TransformerConfig,
                                            make_example_batch, make_mesh,
                                            make_train_step)

    mesh = make_mesh(("dcn", "data", "model"), axis_sizes=(2, 2, 2))
    cfg = TransformerConfig(vocab=64, d_model=64, n_heads=4, n_layers=2,
                            d_ff=128, max_seq=16)
    step, init_state, place = make_train_step(cfg, mesh)
    params, opt = init_state(jax.random.key(0))
    batch = place(make_example_batch(cfg, batch=8, seq=16))
    assert batch["tokens"].sharding.spec == P(("dcn", "data"), None)

    # params replicate across dcn: no param leaf's spec names the axis
    def axes_in(spec):
        names = set()
        for part in spec:
            if isinstance(part, str):
                names.add(part)
            elif isinstance(part, (tuple, list)):
                names.update(part)
        return names

    for leaf in jax.tree_util.tree_leaves(params):
        assert "dcn" not in axes_in(leaf.sharding.spec), leaf.sharding
    _, _, loss = step(params, opt, batch)
    assert jnp.isfinite(loss)
    assert float(loss) > 0


def test_tpuctl_slice_group_cli(two_slices):
    """`tpuctl slice-group --daemon-addr` prints the joint group as
    strict JSON (single-slice dcn bound serializes as null, never the
    invalid bare Infinity)."""
    import json

    from dpu_operator_tpu import tpuctl

    a, b = two_slices

    def run(addr):
        args = type("A", (), {"cmd": "slice-group", "daemon_addr": addr,
                              "agent_socket": "", "vsp_socket": ""})()
        out = tpuctl.run(args)
        json.loads(json.dumps(out, allow_nan=False))  # strict-JSON safe
        return out

    solo = run(a.address)
    assert solo["numChips"] == 4
    assert solo["dcnAllreduceAlgbwGbps"] is None  # no DCN leg yet

    _join(a.address, b.address, "host0-0")
    _join(b.address, a.address, "host0-0")
    joined = run(b.address)
    assert joined["numChips"] == 8
    assert joined["slices"] == ["v5e-4", "v5e-4"]
    assert joined["degraded"] is False
    assert joined["dcnAllreduceAlgbwGbps"] > 0
