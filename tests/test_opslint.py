"""opslint analyzer tests: per-rule pass/fail fixtures, pragma and
baseline handling, CLI exit codes.

Each fixture is a minimal snippet that must (or must not) trip exactly
the rule under test; Module is built directly from source so the repo-
relative path — which drives rule scoping — is explicit.
"""

import json
import os
import textwrap

from dpu_operator_tpu.analysis import (ALL_CHECKERS,
                                       ChaosDeterminismChecker,
                                       EventsSeamChecker,
                                       ExceptionHygieneChecker,
                                       HandoffStateDisciplineChecker,
                                       LockDisciplineChecker,
                                       MetricDocParityChecker,
                                       MetricsNamingChecker,
                                       RetryDisciplineChecker,
                                       TraceContextChecker,
                                       WireSeamChecker)
from dpu_operator_tpu.analysis.__main__ import main as opslint_main
from dpu_operator_tpu.analysis.core import Baseline, Module


def check(checker, source, relpath="dpu_operator_tpu/somemod.py"):
    module = Module("/x/" + relpath, relpath, textwrap.dedent(source))
    return [v for v in checker.check(module)
            if not module.suppressed(v.rule, v.line)]


# -- wire-seam ----------------------------------------------------------------

def test_wire_seam_flags_raw_socket_import():
    violations = check(WireSeamChecker(), """
        import socket
    """)
    assert [v.rule for v in violations] == ["wire-seam"]
    assert "socket" in violations[0].message


def test_wire_seam_flags_requests_and_http_client():
    src = """
        import requests
        from http.client import HTTPConnection
    """
    assert len(check(WireSeamChecker(), src)) == 2


def test_wire_seam_allows_the_pool_and_rpc_seams():
    for seam in ("dpu_operator_tpu/k8s/pool.py",
                 "dpu_operator_tpu/vsp/rpc.py"):
        assert check(WireSeamChecker(), "import socket\n",
                     relpath=seam) == []


def test_wire_seam_ignores_tests_and_unrelated_imports():
    assert check(WireSeamChecker(), "import socket\n",
                 relpath="tests/test_x.py") == []
    assert check(WireSeamChecker(), "import json, os\n") == []


# -- trace-context ------------------------------------------------------------

def test_trace_context_flags_seam_without_injection():
    violations = check(TraceContextChecker(), """
        def request(method, path):
            return send(method, path)
    """, relpath="dpu_operator_tpu/k8s/pool.py")
    assert [v.rule for v in violations] == ["trace-context"]
    assert "inject_traceparent" in violations[0].message


def test_trace_context_passes_on_inject_call_or_header_literal():
    with_call = """
        from ..utils import tracing
        def request(method, path):
            tp = tracing.inject_traceparent()
            return send(method, path, tp)
    """
    assert check(TraceContextChecker(), with_call,
                 relpath="dpu_operator_tpu/k8s/pool.py") == []
    # the stdlib-only shim inlines the header instead of calling tracing
    with_literal = """
        def post(payload):
            headers = "Traceparent: " + make_tp()
            return wire(headers, payload)
    """
    assert check(TraceContextChecker(), with_literal,
                 relpath="dpu_operator_tpu/cni/shim.py") == []
    # ... but ONLY the shim: elsewhere a leftover header-name string
    # must not mask a deleted inject call
    assert len(check(TraceContextChecker(), with_literal,
                     relpath="dpu_operator_tpu/k8s/pool.py")) == 1
    # and even in the shim, a docstring or env-key mention is NOT a
    # header build: deleting the injection must fire the rule
    shim_without_header = '''
        """Forwards requests. Used to send a Traceparent: header."""
        import os
        def post(payload):
            tp = os.environ.get("TRACEPARENT", "")
            return wire(payload)
    '''
    assert len(check(TraceContextChecker(), shim_without_header,
                     relpath="dpu_operator_tpu/cni/shim.py")) == 1


def test_trace_context_ignores_non_seam_modules():
    assert check(TraceContextChecker(), "def f():\n    return 1\n") == []


# -- events-seam --------------------------------------------------------------

def test_events_seam_flags_raw_event_construction():
    violations = check(EventsSeamChecker(), """
        def alert(client, node):
            client.create({
                "apiVersion": "v1", "kind": "Event",
                "metadata": {"name": "x"},
                "involvedObject": {"kind": "Node", "name": node},
                "reason": "Oops",
            })
    """)
    assert [v.rule for v in violations] == ["events-seam"]
    assert "k8s/events.py" in violations[0].message


def test_events_seam_flags_event_dict_even_without_create():
    # building the object at all is the violation: it WILL be fed to a
    # client eventually, bypassing the dedup seam
    violations = check(EventsSeamChecker(), """
        EV = {"kind": "Event", "apiVersion": "v1"}
    """)
    assert [v.rule for v in violations] == ["events-seam"]


def test_events_seam_allows_the_recorder_module_and_tests():
    src = 'EV = {"kind": "Event", "apiVersion": "v1"}\n'
    assert check(EventsSeamChecker(), src,
                 relpath="dpu_operator_tpu/k8s/events.py") == []
    assert check(EventsSeamChecker(), src,
                 relpath="tests/test_x.py") == []


def test_events_seam_ignores_other_kinds_and_dynamic_kind():
    src = """
        POD = {"kind": "Pod", "apiVersion": "v1"}
        REF = {"kind": "Node", "name": "n"}
        def mk(kind):
            return {"kind": kind}
    """
    assert check(EventsSeamChecker(), src) == []


# -- retry-discipline ---------------------------------------------------------

def test_retry_discipline_flags_unbounded_sleep_loop():
    violations = check(RetryDisciplineChecker(), """
        import time
        def dial():
            while True:
                try:
                    return connect()
                except OSError:
                    time.sleep(1)
    """)
    assert [v.rule for v in violations] == ["retry-discipline"]


def test_retry_discipline_allows_deadline_bounded_loop():
    src = """
        import time
        def dial(deadline):
            while True:
                try:
                    return connect()
                except OSError:
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.05)
    """
    assert check(RetryDisciplineChecker(), src) == []


def test_retry_discipline_flags_ad_hoc_exponential_backoff():
    """The serving-path retry loop's discipline: RetryPolicy owns the
    backoff curve — a hand-computed `sleep(base * 2 ** attempt)`
    re-derives it without the jitter, cap, or deadline."""
    violations = check(RetryDisciplineChecker(), """
        import time
        def redial(attempt):
            time.sleep(0.05 * 2 ** attempt)
    """)
    assert [v.rule for v in violations] == ["retry-discipline"]
    assert "RetryPolicy.backoff" in violations[0].message


def test_retry_discipline_allows_policy_owned_backoff_sleep():
    # sleeping a RetryPolicy-computed value (no power expression at
    # the call site) is exactly the sanctioned shape
    assert check(RetryDisciplineChecker(), """
        import time
        def redial(policy, attempt):
            time.sleep(policy.backoff(attempt))
    """) == []


def test_retry_discipline_allows_resilience_module_and_plain_loops():
    src = "import time\nwhile True:\n    time.sleep(1)\n"
    assert check(RetryDisciplineChecker(), src,
                 relpath="dpu_operator_tpu/utils/resilience.py") == []
    # non-constant loop test: bounded by its own condition
    assert check(RetryDisciplineChecker(), """
        import time
        def wait(stop):
            while not stop.is_set():
                time.sleep(1)
    """) == []


# -- exception-hygiene --------------------------------------------------------

def test_exception_hygiene_flags_silent_broad_except():
    for handler in ("except Exception:", "except BaseException:",
                    "except:", "except (ValueError, Exception):"):
        violations = check(ExceptionHygieneChecker(), f"""
            def f():
                try:
                    g()
                {handler}
                    pass
        """)
        assert [v.rule for v in violations] == ["exception-hygiene"], handler


def test_exception_hygiene_allows_logged_and_narrow_handlers():
    src = """
        import logging
        log = logging.getLogger(__name__)
        def f():
            try:
                g()
            except Exception:
                log.exception("g failed")
            try:
                g()
            except KeyError:
                pass
            try:
                g()
            except Exception:
                raise RuntimeError("wrapped")
    """
    assert check(ExceptionHygieneChecker(), src) == []


# -- metrics-naming -----------------------------------------------------------

def test_metrics_naming_flags_prefix_and_counter_suffix():
    violations = check(MetricsNamingChecker(), """
        FOO = REGISTRY.counter("daemon_foo", "help")
        BAR = REGISTRY.counter("tpu_bar_count", "help")
        BAZ = REGISTRY.gauge("tpu_baz_total", "help")
    """)
    # daemon_foo fires twice: missing prefix AND missing _total suffix
    assert sorted(v.rule for v in violations) == ["metrics-naming"] * 4


def test_metrics_naming_passes_conventional_names():
    src = """
        A = REGISTRY.counter("tpu_daemon_foo_total", "help")
        B = REGISTRY.gauge("tpu_daemon_bar", "help")
        C = REGISTRY.histogram("tpu_daemon_baz_seconds", "help")
        D = REGISTRY.histogram_vec("tpu_x_seconds", "help", label="verb")
    """
    assert check(MetricsNamingChecker(), src) == []


def test_metrics_naming_ignores_collections_counter():
    assert check(MetricsNamingChecker(), """
        from collections import Counter
        c = Counter("abcabc")
    """) == []


def test_metrics_naming_applies_to_whole_repo_metrics():
    # the live registry in utils/metrics.py must satisfy its own rule
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "dpu_operator_tpu", "utils", "metrics.py")
    with open(path) as fh:
        module = Module(path, "dpu_operator_tpu/utils/metrics.py",
                        fh.read())
    assert list(MetricsNamingChecker().check(module)) == []


# -- chaos-determinism --------------------------------------------------------

def test_chaos_determinism_flags_unseeded_random_and_wall_clock():
    violations = check(ChaosDeterminismChecker(), """
        import pytest, random, time
        @pytest.mark.chaos
        def test_storm():
            jitter = random.random()
            start = time.time()
    """, relpath="tests/test_chaos_x.py")
    assert [v.rule for v in violations] == ["chaos-determinism"] * 2


def test_chaos_determinism_module_level_mark_and_seeded_rng_ok():
    src = """
        import pytest, random
        pytestmark = pytest.mark.chaos
        SEED = 7
        def test_storm():
            rng = random.Random(SEED)
            assert rng.random() < 1.0
    """
    violations = check(ChaosDeterminismChecker(), src,
                       relpath="tests/test_chaos_y.py")
    # random.Random(SEED) is the idiom; rng.random() is seeded state
    assert violations == []


def test_chaos_determinism_ignores_unmarked_tests():
    assert check(ChaosDeterminismChecker(), """
        import random
        def test_plain():
            assert random.random() >= 0
    """, relpath="tests/test_plain.py") == []


def test_chaos_determinism_covers_fault_marked_tests():
    """The hardware-fault storms (`make fault-check`) carry the same
    bit-identical-replay invariant as the chaos matrix."""
    violations = check(ChaosDeterminismChecker(), """
        import pytest, time
        @pytest.mark.fault
        def test_storm():
            start = time.time()
    """, relpath="tests/test_fault_x.py")
    assert [v.rule for v in violations] == ["chaos-determinism"]


def test_chaos_determinism_fault_module_mark_seeded_rng_ok():
    src = """
        import pytest, random
        pytestmark = pytest.mark.fault
        SEED = 20260803
        def test_storm():
            rng = random.Random(SEED)
            assert rng.random() < 1.0
    """
    assert check(ChaosDeterminismChecker(), src,
                 relpath="tests/test_fault_y.py") == []


def test_chaos_determinism_covers_serve_chaos_marked_tests():
    """The serving-path fault storms (`make serve-chaos-check`) promise
    bit-identical traces across runs — the mark joins the invariant
    (and needs its own tuple entry: endswith-matching means
    `serve_chaos` does NOT match `serve`)."""
    violations = check(ChaosDeterminismChecker(), """
        import pytest, random
        @pytest.mark.serve_chaos
        def test_storm():
            jitter = random.random()
    """, relpath="tests/test_serve_chaos_x.py")
    assert [v.rule for v in violations] == ["chaos-determinism"]


def test_chaos_determinism_serve_chaos_module_mark_seeded_rng_ok():
    src = """
        import pytest, random
        pytestmark = pytest.mark.serve_chaos
        SEED = 0x5E17E
        def test_storm():
            rng = random.Random(SEED)
            assert rng.random() < 1.0
    """
    assert check(ChaosDeterminismChecker(), src,
                 relpath="tests/test_serve_chaos_y.py") == []


# -- lock-discipline ----------------------------------------------------------

def test_lock_discipline_flags_off_lock_write_of_guarded_attr():
    violations = check(LockDisciplineChecker(), """
        import threading
        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._conns = []
            def put(self, c):
                with self._lock:
                    self._conns.append(c)
            def drop_all(self):
                self._conns = []
    """)
    assert [v.rule for v in violations] == ["lock-discipline"]
    assert "_conns" in violations[0].message


def test_lock_discipline_allows_consistent_guarding_and_init():
    src = """
        import threading
        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._conns = []
            def put(self, c):
                with self._lock:
                    self._conns.append(c)
            def drop_all(self):
                with self._lock:
                    self._conns = []
            def _prune_locked(self):
                self._conns = [c for c in self._conns if c.ok]
            def try_fast(self):
                self._lock.acquire()
                try:
                    self._conns.append(1)
                finally:
                    self._lock.release()
    """
    assert check(LockDisciplineChecker(), src) == []


def test_lock_discipline_skips_lock_free_classes():
    assert check(LockDisciplineChecker(), """
        class Plain:
            def __init__(self):
                self.x = 0
            def bump(self):
                self.x += 1
    """) == []




# -- handoff-state-discipline -------------------------------------------------

def test_handoff_state_discipline_flags_raw_write_in_state_module():
    violations = check(HandoffStateDisciplineChecker(), """
        def save(path, data):
            with open(path, "w") as f:
                f.write(data)
    """, relpath="dpu_operator_tpu/cni/cache.py")
    assert [v.rule for v in violations] == ["handoff-state-discipline"]
    assert "atomic_write" in violations[0].message


def test_handoff_state_discipline_flags_append_and_mode_keyword():
    src = """
        def touch(path):
            open(path, mode="a").close()
        def binary(path):
            open(path, "wb").close()
    """
    assert len(check(HandoffStateDisciplineChecker(), src,
                     relpath="dpu_operator_tpu/daemon/handoff.py")) == 2


def test_handoff_state_discipline_allows_reads_and_other_modules():
    reads = """
        def load(path):
            with open(path) as f:
                return f.read()
        def load_binary(path):
            with open(path, "rb") as f:
                return f.read()
    """
    assert check(HandoffStateDisciplineChecker(), reads,
                 relpath="dpu_operator_tpu/cni/cache.py") == []
    # non-state modules may open files freely
    assert check(HandoffStateDisciplineChecker(),
                 'open("/tmp/scratch", "w")\n') == []


def test_handoff_state_discipline_flags_os_open_write_flags():
    # os.open(path, O_CREAT|O_EXCL|O_WRONLY) + write is the same torn-
    # write shape as open(path, "w") — the rule must see through it
    violations = check(HandoffStateDisciplineChecker(), """
        import os
        def claim(path, owner):
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                         0o600)
            with os.fdopen(fd, "w") as f:
                f.write(owner)
    """, relpath="dpu_operator_tpu/cni/ipam.py")
    assert [v.rule for v in violations] == ["handoff-state-discipline"]
    assert "os.open" in violations[0].message
    # read-only os.open (flock handles, dir-fsync descriptors) is fine
    assert check(HandoffStateDisciplineChecker(), """
        import os
        def handle(path):
            return os.open(path, os.O_RDONLY)
    """, relpath="dpu_operator_tpu/cni/ipam.py") == []


def test_handoff_state_discipline_ignores_dynamic_modes():
    # a computed mode cannot be judged statically; no false positive
    assert check(HandoffStateDisciplineChecker(), """
        def reopen(path, mode):
            return open(path, mode)
    """, relpath="dpu_operator_tpu/cni/cache.py") == []


# -- pragma -------------------------------------------------------------------

def test_line_pragma_suppresses_one_rule_on_that_line():
    violations = check(ExceptionHygieneChecker(), """
        def f():
            try:
                g()
            except Exception:  # opslint: disable=exception-hygiene
                pass
    """)
    assert violations == []


def test_file_pragma_suppresses_whole_file():
    violations = check(WireSeamChecker(), """\
        # opslint: disable=wire-seam
        import socket
        import requests
    """)
    assert violations == []


def test_pragma_for_other_rule_does_not_suppress():
    violations = check(WireSeamChecker(), """
        import socket  # opslint: disable=retry-discipline
    """)
    assert len(violations) == 1


# -- baseline + CLI -----------------------------------------------------------

def _seeded_tree(tmp_path):
    pkg = tmp_path / "dpu_operator_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text("import socket\n")
    return tmp_path


def test_cli_nonzero_on_seeded_violation_zero_after_baseline(tmp_path):
    root = str(_seeded_tree(tmp_path))
    args = ["--repo-root", root]  # default roots: full scan
    assert opslint_main(args) == 1
    assert opslint_main(args + ["--write-baseline"]) == 0
    assert opslint_main(args) == 0  # baselined: gate stays green
    data = json.loads((tmp_path / "opslint-baseline.json").read_text())
    assert len(data["entries"]) == 1


def test_cli_baseline_ratchet_reports_stale_entries(tmp_path, capsys):
    root = str(_seeded_tree(tmp_path))
    args = ["--repo-root", root]
    assert opslint_main(args + ["--write-baseline"]) == 0
    (tmp_path / "dpu_operator_tpu" / "bad.py").write_text("import os\n")
    assert opslint_main(args) == 0  # fixed: still green...
    out = capsys.readouterr().out
    assert "stale baseline entry" in out  # ...but the ratchet nags


def test_cli_write_baseline_refuses_subset_runs(tmp_path, capsys):
    """A --select/path-limited scan must not truncate the baseline to
    the subset it happened to see, and must not call unscanned entries
    stale."""
    root = str(_seeded_tree(tmp_path))
    assert opslint_main(["--repo-root", root, "--write-baseline"]) == 0
    before = (tmp_path / "opslint-baseline.json").read_text()
    assert opslint_main(["--repo-root", root, "--write-baseline",
                         "--select", "metrics-naming"]) == 2
    assert opslint_main(["--repo-root", root, "--write-baseline",
                         "dpu_operator_tpu/bad.py"]) == 2
    assert (tmp_path / "opslint-baseline.json").read_text() == before
    capsys.readouterr()
    # subset scan sees no wire-seam findings: entries are NOT stale
    assert opslint_main(["--repo-root", root,
                         "--select", "metrics-naming"]) == 0
    assert "stale baseline entry" not in capsys.readouterr().out


def test_cli_select_and_list_rules(tmp_path, capsys):
    root = str(_seeded_tree(tmp_path))
    assert opslint_main(["--repo-root", root,
                         "--select", "metrics-naming"]) == 0  # no wire-seam
    assert opslint_main(["--select", "no-such-rule"]) == 2
    assert opslint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for cls in ALL_CHECKERS:
        assert cls.name in out


def test_baseline_key_survives_line_drift(tmp_path):
    root = _seeded_tree(tmp_path)
    args = ["--repo-root", str(root)]
    assert opslint_main(args + ["--write-baseline"]) == 0
    # unrelated lines above the violation must not invalidate the entry
    (root / "dpu_operator_tpu" / "bad.py").write_text(
        "import os\nimport json\nimport socket\n")
    assert opslint_main(args) == 0


def test_repo_gate_is_green():
    """The acceptance bar: the live repo passes with the checked-in
    (empty) baseline."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert opslint_main(["--repo-root", repo]) == 0
    baseline = Baseline(os.path.join(repo, "opslint-baseline.json"))
    assert baseline.loaded and baseline.entries == set()


# -- list-discipline ----------------------------------------------------------

def test_list_discipline_flags_client_list_in_controller():
    from dpu_operator_tpu.analysis import ListDisciplineChecker
    violations = check(ListDisciplineChecker(), """
        def reconcile(self, client, req):
            pods = client.list("v1", "Pod", namespace="x")
    """, relpath="dpu_operator_tpu/controller/some_controller.py")
    assert [v.rule for v in violations] == ["list-discipline"]
    assert "cached_list" in violations[0].message


def test_list_discipline_flags_self_client_and_kube_receivers():
    from dpu_operator_tpu.analysis import ListDisciplineChecker
    src = """
        def a(self):
            self.client.list("v1", "Node")
        def b(kube):
            kube.list("v1", "Pod")
    """
    assert len(check(ListDisciplineChecker(), src,
                     relpath="dpu_operator_tpu/daemon/sfc_reconciler.py")) \
        == 2


def test_list_discipline_allows_lister_seam_and_other_receivers():
    from dpu_operator_tpu.analysis import ListDisciplineChecker
    src = """
        from ..k8s.informer import cached_list
        def reconcile(self, client, req):
            pods = cached_list(client, "v1", "Pod")
            hops = self.wire_table.list()   # not an apiserver client
            keys = list(pods)               # builtin, no receiver
    """
    assert check(ListDisciplineChecker(), src,
                 relpath="dpu_operator_tpu/controller/c.py") == []


def test_list_discipline_scopes_to_reconciler_modules_only():
    from dpu_operator_tpu.analysis import ListDisciplineChecker
    src = 'def f(client):\n    return client.list("v1", "Node")\n'
    # utils/testing/k8s internals may list raw — the informer itself must
    assert check(ListDisciplineChecker(), src,
                 relpath="dpu_operator_tpu/k8s/informer.py") == []
    assert check(ListDisciplineChecker(), src,
                 relpath="dpu_operator_tpu/utils/drain.py") == []
    assert check(ListDisciplineChecker(), src,
                 relpath="tests/test_x.py") == []


def test_list_discipline_pragma_suppresses():
    from dpu_operator_tpu.analysis import ListDisciplineChecker
    src = ('def f(client):\n'
           '    return client.list("v1", "Node")'
           '  # opslint: disable=list-discipline\n')
    assert check(ListDisciplineChecker(), src,
                 relpath="dpu_operator_tpu/controller/c.py") == []


# -- metric-doc-parity --------------------------------------------------------

def _parity_module(tmp_path, source, doc=None,
                   relpath="dpu_operator_tpu/somemod.py"):
    """A Module rooted in a real tmp repo so the checker can find (or
    miss) doc/observability.md next to it."""
    from dpu_operator_tpu.analysis import MetricDocParityChecker
    if doc is not None:
        (tmp_path / "doc").mkdir(exist_ok=True)
        (tmp_path / "doc" / "observability.md").write_text(doc)
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    module = Module(str(path), relpath, textwrap.dedent(source))
    return [v for v in MetricDocParityChecker().check(module)
            if not module.suppressed(v.rule, v.line)]


def test_metric_doc_parity_flags_undocumented_family(tmp_path):
    violations = _parity_module(tmp_path, """
        GOOD = REGISTRY.counter("tpu_documented_total", "fine")
        BAD = REGISTRY.gauge("tpu_ghost_series", "undocumented")
    """, doc="| `tpu_documented_total{kind}` | counter | fine |\n")
    assert [v.rule for v in violations] == ["metric-doc-parity"]
    assert "tpu_ghost_series" in violations[0].message
    assert "doc/observability.md" in violations[0].message


def test_metric_doc_parity_passes_documented_and_non_tpu_names(tmp_path):
    assert _parity_module(tmp_path, """
        A = REGISTRY.counter("tpu_documented_total", "fine")
        B = REGISTRY.histogram_vec("tpu_breakdown_seconds", "fine",
                                   label="phase")
        C = Histogram("other_namespace_seconds", "not tpu_-prefixed")
    """, doc=(
        "| `tpu_documented_total` | counter | fine |\n"
        "| `tpu_breakdown_seconds{phase}` | histogram | fine |\n")) == []


def test_metric_doc_parity_inert_without_doc_and_outside_package(tmp_path):
    src = 'X = REGISTRY.counter("tpu_ghost_total", "x")\n'
    # no doc/observability.md at the module's root -> rule stays inert
    # (fixture Modules under synthetic paths must not trip it)
    assert _parity_module(tmp_path, src) == []
    assert check(MetricDocParityChecker(), src) == []
    # tests and out-of-package files are not scanned
    assert _parity_module(tmp_path, src, doc="irrelevant\n",
                          relpath="tests/test_x.py") == []
    assert _parity_module(tmp_path, src, doc="irrelevant\n",
                          relpath="tools/helper.py") == []


def test_metric_doc_parity_pragma_suppresses(tmp_path):
    assert _parity_module(tmp_path, """
        X = REGISTRY.counter("tpu_ghost_total", "x")  # opslint: disable=metric-doc-parity
    """, doc="nothing documented\n") == []


def test_metric_doc_parity_whole_registry_is_documented():
    # the live registry must satisfy the rule against the live doc —
    # adding a metric without its observability.md row fails lint
    from dpu_operator_tpu.analysis import MetricDocParityChecker
    from dpu_operator_tpu.analysis.core import run_checkers
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert run_checkers([MetricDocParityChecker()],
                        ["dpu_operator_tpu"], repo) == []


def test_metric_doc_parity_prefix_of_documented_name_still_fires(tmp_path):
    # `tpu_serve_step` must not ride on `tpu_serve_step_breakdown_
    # seconds`'s row — the match is backtick-anchored, not substring
    violations = _parity_module(tmp_path, """
        X = REGISTRY.gauge("tpu_serve_step", "prefix freeloader")
    """, doc="| `tpu_serve_step_breakdown_seconds{phase}` | histogram "
             "| fine |\n")
    assert [v.rule for v in violations] == ["metric-doc-parity"]
    # labeled and bare backticked rows both satisfy the rule
    assert _parity_module(tmp_path, """
        X = REGISTRY.gauge("tpu_serve_step", "now documented")
    """, doc="| `tpu_serve_step{dim}` | gauge | fine |\n") == []


# -- metric-doc-parity: Event-reason catalog parity ---------------------------

def test_event_doc_parity_flags_undocumented_reason(tmp_path):
    violations = _parity_module(tmp_path, """
        from ..k8s import events
        def f():
            events.emit("GhostReason", "a thing happened",
                        type_="Warning", series="x")
    """, doc="| `DocumentedReason` | Warning | when it fires |\n")
    assert [v.rule for v in violations] == ["metric-doc-parity"]
    assert "GhostReason" in violations[0].message
    assert "Event catalog" in violations[0].message


def test_event_doc_parity_passes_documented_reasons(tmp_path):
    assert _parity_module(tmp_path, """
        from ..k8s import events
        from ..utils.watchdog import emit_health_event
        def f(recorder, involved, healthy):
            events.emit("DocumentedReason", "msg", series="x")
            emit_health_event("OtherReason", "msg text", "Warning",
                              series="y")
            recorder.emit(involved,
                          "FlipReasonA" if healthy else "FlipReasonB",
                          "message", type_="Normal")
    """, doc=("| `DocumentedReason` | Warning | row |\n"
              "| `OtherReason` | Warning | row |\n"
              "| `FlipReasonA` / `FlipReasonB` | Normal | row |\n")) \
        == []


def test_event_doc_parity_conditional_reason_needs_both_rows(tmp_path):
    # both branches of a conditional reason are live reasons — each
    # needs its catalog row
    violations = _parity_module(tmp_path, """
        def f(recorder, involved, healthy):
            recorder.emit(involved,
                          "FlipGood" if healthy else "FlipBad",
                          "message")
    """, doc="| `FlipGood` | Normal | only one documented |\n")
    assert [v.rule for v in violations] == ["metric-doc-parity"]
    assert "FlipBad" in violations[0].message


def test_event_doc_parity_ignores_non_reason_shapes(tmp_path):
    # watch event types (ALL-CAPS), Event types (Warning/Normal) and
    # sentence messages never match the reason grammar; _emit fanout
    # helpers with non-reason payloads stay silent
    assert _parity_module(tmp_path, """
        def f(self, obj, recorder, involved):
            self._emit("ADDED", obj)
            self._emit("DELETED", obj)
            recorder.emit(involved, reason_var,
                          "A sentence message with spaces",
                          type_="Warning")
    """, doc="nothing documented\n") == []


def test_event_doc_parity_wrapper_emit_is_scanned(tmp_path):
    # the vsp_rollout-style thin wrapper: reason sits deeper in the
    # positional args, still caught
    violations = _parity_module(tmp_path, """
        def g(self, client, cfg_obj):
            self._emit(client, cfg_obj, "WrappedReason",
                       "a message about it")
    """, doc="no rows\n")
    assert [v.rule for v in violations] == ["metric-doc-parity"]
    assert "WrappedReason" in violations[0].message


def test_event_doc_parity_live_repo_catalog_is_complete():
    # every literal reason emitted through the events seam has its
    # Event-catalog row in doc/observability.md (the Events half of
    # the live-repo-green assertion)
    from dpu_operator_tpu.analysis import MetricDocParityChecker
    from dpu_operator_tpu.analysis.core import run_checkers
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert run_checkers([MetricDocParityChecker()],
                        ["dpu_operator_tpu"], repo) == []
