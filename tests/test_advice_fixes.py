"""Regression tests for round-1 ADVICE findings: mutating-webhook Service
target, leader-lease loss handling, S_ISREG health gating, orphaned NF wire
unwind, and the GetPreferredAllocation must-include contract."""

import threading
import time

import yaml

from dpu_operator_tpu.cni.types import NetConf, PodRequest
from dpu_operator_tpu.daemon import TpuSideManager
from dpu_operator_tpu.deviceplugin.server import _preferred_chips
from dpu_operator_tpu.k8s.real import RealKube
from dpu_operator_tpu.platform.platform import FakePlatform, HardwarePlatform
from dpu_operator_tpu.vsp.google import GoogleTpuVsp


def test_mutating_webhook_targets_existing_service():
    """ADVICE #1: the MutatingWebhookConfiguration must point at a Service
    that is actually defined, or pod resource injection silently never runs
    (failurePolicy: Ignore)."""
    with open("config/webhook/webhook.yaml") as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    services = {d["metadata"]["name"] for d in docs if d["kind"] == "Service"}
    for doc in docs:
        if doc["kind"].endswith("WebhookConfiguration"):
            for wh in doc["webhooks"]:
                svc = wh["clientConfig"]["service"]["name"]
                assert svc in services, (
                    f"webhook {wh['name']} targets undefined Service {svc}")


def _lease_kube():
    """RealKube without a kubeconfig: in-memory Lease store."""
    kube = RealKube.__new__(RealKube)
    store = {}

    def get(api_version, kind, name, namespace=None, **kw):
        return store.get(name)

    def create(obj, **kw):
        name = obj["metadata"]["name"]
        if name in store:
            raise RuntimeError("exists")
        store[name] = obj
        return obj

    def update(obj, **kw):
        store[obj["metadata"]["name"]] = obj
        return obj

    kube.get, kube.create, kube.update = get, create, update
    return kube, store


def test_leader_lease_lost_invokes_on_lost():
    """ADVICE #2: when renewal fails past leaseDurationSeconds, the holder
    must stop (split-brain otherwise)."""
    kube, store = _lease_kube()
    lost = threading.Event()
    cancel = kube.acquire_leader_lease(
        "op-lease", namespace="ns", lease_seconds=1, poll=0.05,
        on_lost=lost.set)
    assert store["op-lease"]["spec"]["holderIdentity"]
    # Apiserver outage: every renewal attempt now fails.
    kube.update = lambda *a, **k: (_ for _ in ()).throw(RuntimeError("down"))
    kube.get = lambda *a, **k: (_ for _ in ()).throw(RuntimeError("down"))
    kube.create = lambda *a, **k: (_ for _ in ()).throw(RuntimeError("down"))
    assert lost.wait(10.0), "on_lost never fired after renewal failures"
    cancel()


def test_leader_lease_renews_while_healthy():
    kube, store = _lease_kube()
    lost = threading.Event()
    cancel = kube.acquire_leader_lease(
        "op-lease", namespace="ns", lease_seconds=1, poll=0.05,
        on_lost=lost.set)
    first = store["op-lease"]["spec"]["renewTime"]
    deadline = time.monotonic() + 5
    while (store["op-lease"]["spec"]["renewTime"] == first
           and time.monotonic() < deadline):
        time.sleep(0.05)
    assert store["op-lease"]["spec"]["renewTime"] != first
    assert not lost.is_set()
    cancel()


def test_regular_file_unhealthy_on_real_platform(tmp_path):
    """ADVICE #3: a stale regular file at /dev/accel* must not be advertised
    as a healthy chip on real hosts; fakes opt in explicitly."""
    dev = tmp_path / "accel0"
    dev.write_text("")
    real = GoogleTpuVsp(HardwarePlatform(str(tmp_path)))
    assert real._chip_healthy(str(dev)) is False
    fake = GoogleTpuVsp(FakePlatform(accel=[str(dev)]))
    assert fake._chip_healthy(str(dev)) is True


class _DelRacingVsp:
    """VSP whose create_network_function races a DEL that empties the
    attach store while the wire RPC is in flight."""

    def __init__(self, mgr_holder, sandbox):
        self.mgr_holder = mgr_holder
        self.sandbox = sandbox
        self.wired = []
        self.unwired = []

    def create_network_function(self, a, b):
        self.mgr_holder[0]._attach_store.pop(self.sandbox, None)
        self.wired.append((a, b))

    def delete_network_function(self, a, b):
        self.unwired.append((a, b))


    def create_slice_attachment(self, att):
        return att

    def delete_slice_attachment(self, name):
        pass

def _nf_req(sandbox, dev):
    return PodRequest(command="ADD", pod_namespace="default", pod_name="nf",
                      sandbox_id=sandbox, netns="/proc/1/ns/net",
                      ifname="net1", device_id=dev,
                      netconf=NetConf(mode="network-function", device_id=dev))


def _bare_manager(vsp):
    mgr = TpuSideManager.__new__(TpuSideManager)
    mgr.vsp = vsp
    mgr.client = None
    mgr._attach_store = {}
    mgr._attach_lock = threading.Lock()
    mgr._chain_store = {}
    mgr._chain_hops = {}
    import tempfile as _tf
    from dpu_operator_tpu.cni import NetConfCache as _NCC
    _d = _tf.mkdtemp(prefix="nf-ipam-")
    mgr.ipam_dir = _d + "/ipam"
    mgr.nf_cache = _NCC(_d + "/nf")
    return mgr


def test_orphaned_nf_wire_unwound_on_concurrent_del():
    """ADVICE #4: if a concurrent DEL removed the sandbox entry while the
    wire was in flight, the successful wire must be undone and the ADD
    must fail (kubelet retries against current state)."""
    import pytest
    holder = []
    vsp = _DelRacingVsp(holder, "sbx-race-1234567890ab")
    mgr = _bare_manager(vsp)
    holder.append(mgr)
    mgr._cni_nf_add(_nf_req("sbx-race-1234567890ab", "chip-0"))
    with pytest.raises(RuntimeError):
        mgr._cni_nf_add(_nf_req("sbx-race-1234567890ab", "chip-1"))
    assert vsp.wired and vsp.unwired == vsp.wired
    assert "sbx-race-1234567890ab" not in mgr._attach_store


class _InterfaceDelRacingVsp:
    """Races a per-interface DEL (not full teardown) against the wire."""

    def __init__(self):
        self.holder = []
        self.wired = []
        self.unwired = []

    def create_network_function(self, a, b):
        mgr = self.holder[0]
        # per-interface DEL for the first attachment lands mid-wire
        mgr._cni_nf_del(_nf_req("sbx-ifdel-123456789012", "chip-0"))
        self.wired.append((a, b))

    def delete_network_function(self, a, b):
        self.unwired.append((a, b))


    def create_slice_attachment(self, att):
        return att

    def delete_slice_attachment(self, name):
        pass

def test_interface_del_mid_wire_unwinds_and_later_del_safe():
    """A per-interface DEL racing the wire must not leave a wired entry
    with a single attachment (later DELs would crash) — the in-flight
    wire is unwound and the surviving attachment stays usable."""
    import pytest
    vsp = _InterfaceDelRacingVsp()
    mgr = _bare_manager(vsp)
    vsp.holder.append(mgr)
    sbx = "sbx-ifdel-123456789012"
    mgr._cni_nf_add(_nf_req(sbx, "chip-0"))
    with pytest.raises(RuntimeError):
        mgr._cni_nf_add(_nf_req(sbx, "chip-1"))
    assert vsp.unwired == vsp.wired
    entry = mgr._attach_store.get(sbx)
    assert entry is not None and not entry["wired"] and not entry["wiring"]
    # the surviving interface's DEL completes cleanly
    mgr._cni_nf_del(_nf_req(sbx, "chip-1"))
    assert sbx not in mgr._attach_store


def test_preferred_allocation_keeps_all_must_includes():
    """ADVICE #5: must-include devices may never be truncated out of the
    GetPreferredAllocation response."""
    devices = {f"chip-{i}": {"coords": [i % 2, i // 2]} for i in range(4)}
    avail = sorted(devices)
    must = ["chip-3", "chip-1"]
    # len(must) == size
    got = _preferred_chips(avail, must, 2, devices)
    assert set(must) <= set(got) and len(got) == 2
    # len(must) > size: return must unmodified rather than dropping one
    got = _preferred_chips(avail, must, 1, devices)
    assert set(must) <= set(got)
    # normal path still honors must within a larger allocation
    got = _preferred_chips(avail, ["chip-2"], 3, devices)
    assert "chip-2" in got and len(got) == 3
