"""Trace-propagation + flight-recorder e2e (`make obs-check`).

One CNI ADD crosses all four process boundaries of a pod-ready request —
CNI shim → daemon CNI server → VSP gRPC → pooled apiserver client — with
`TPU_OPERATOR_TRACE` pointed at a file, and the assertions close the
loop the observability layer promises:

- ONE trace_id stamped by the shim appears in the shim's own span, the
  CNI server span, the VSP *server* span, and the pooled-client span
  (propagated via HTTP Traceparent, thread-pool capture, and gRPC
  metadata respectively);
- after a seeded VSP breaker-open storm (chaos harness, deterministic
  from SEED), the flight recorder still replays the original request's
  spans alongside the breaker transitions — the post-incident snapshot
  works even though the storm came later;
- /metrics renders a valid OpenMetrics exemplar on the CNI latency
  histogram referencing that trace_id.
"""

import json
import os
import urllib.request

import pytest

from dpu_operator_tpu.cni import CniServer, CniShim
from dpu_operator_tpu.k8s.real import RealKube
from dpu_operator_tpu.platform import TpuDetector
from dpu_operator_tpu.testing.chaos import ChaosChannel, Fail, FaultPlan
from dpu_operator_tpu.utils import flight, metrics, resilience, tracing
from dpu_operator_tpu.utils.path_manager import PathManager
from dpu_operator_tpu.vsp import GrpcPlugin, MockTpuVsp, VspServer

from apiserver_fixture import MiniApiServer

pytestmark = pytest.mark.obs

SEED = 1107


def _env(container="tracee2e01", ifname="net1"):
    return {
        "CNI_COMMAND": "ADD",
        "CNI_CONTAINERID": container,
        "CNI_NETNS": "/var/run/netns/x",
        "CNI_IFNAME": ifname,
        "CNI_ARGS": "K8S_POD_NAMESPACE=default;K8S_POD_NAME=tracepod",
    }


def _conf():
    return {"cniVersion": "0.4.0", "name": "tpunfcni-conf",
            "type": "tpu-cni", "mode": "chip", "deviceID": "chip-1",
            "resourceName": "google.com/tpu"}


@pytest.fixture
def stack(short_tmp, tmp_path, monkeypatch):
    """apiserver + pooled RealKube + VSP server/plugin + CNI server whose
    ADD handler touches the VSP and the apiserver — the daemon's real
    pod-ready shape, minus hardware."""
    trace_file = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv("TPU_OPERATOR_TRACE", trace_file)
    tracing.reset_for_tests()
    flight.RECORDER.clear()

    apiserver = MiniApiServer()
    apiserver.start()
    kube = RealKube(kubeconfig=apiserver.write_kubeconfig(
        str(tmp_path / "kubeconfig")))
    assert kube.pool is not None  # the pooled fast lane must be active

    pm = PathManager(short_tmp)
    vsp_sock = pm.vendor_plugin_socket()
    pm.ensure_socket_dir(vsp_sock)
    vsp_server = VspServer(MockTpuVsp(), vsp_sock)
    vsp_server.start()
    det = TpuDetector().detection_result(tpu_mode=True,
                                         identifier="test-tpu")
    plugin = GrpcPlugin(
        det, path_manager=pm, init_timeout=5.0,
        breaker=resilience.CircuitBreaker("vsp", failure_threshold=3,
                                          reset_timeout=3600.0))
    plugin.start(tpu_mode=True)

    def add(pod_req):
        plugin.create_slice_attachment(
            {"name": f"att-{pod_req.sandbox_id[:8]}", "chip_index": 1})
        kube.get("v1", "Pod", pod_req.pod_name, namespace="default")
        return {"cniVersion": pod_req.netconf.cni_version, "ok": True}

    cni_sock = os.path.join(short_tmp, "cni-e2e.sock")
    cni_server = CniServer(cni_sock, add_handler=add)
    cni_server.start()
    try:
        yield {"trace_file": trace_file, "cni_sock": cni_sock,
               "plugin": plugin, "kube": kube}
    finally:
        cni_server.stop()
        plugin.close()
        vsp_server.stop()
        kube.close()
        apiserver.stop()
        tracing.reset_for_tests()


def _records(trace_file):
    with open(trace_file) as fh:
        return [json.loads(line) for line in fh]


def _shim_trace_id(trace_file):
    return next(r["trace_id"] for r in _records(trace_file)
                if r["name"] == "cni.shim")


def test_one_trace_id_crosses_all_four_seams(stack):
    resp = CniShim(stack["cni_sock"]).invoke(_env(), json.dumps(_conf()))
    assert not resp.error

    records = _records(stack["trace_file"])
    shim_spans = [r for r in records if r["name"] == "cni.shim"]
    assert len(shim_spans) == 1
    tid = shim_spans[0]["trace_id"]
    names = {r["name"] for r in records if r["trace_id"] == tid}
    # seam 1→2: the shim's Traceparent header, adopted by the CNI server
    assert "cni.add" in names
    # seam 3: gRPC metadata → VSP server-side span (plus the client span)
    assert "vsp.SliceService.CreateSliceAttachment" in names
    assert "vsp.call" in names
    # seam 4: the pooled apiserver client
    assert "kube.request" in names
    # parent/child links are intact: cni.add's parent is the shim span
    by_name = {r["name"]: r for r in records if r["trace_id"] == tid}
    assert by_name["cni.add"]["parent_id"] == shim_spans[0]["span_id"]
    # and the handler-side spans hang below cni.add (thread-pool capture)
    assert by_name["vsp.call"]["parent_id"] == by_name["cni.add"]["span_id"]


def test_flight_recorder_replays_request_after_breaker_storm(stack):
    resp = CniShim(stack["cni_sock"]).invoke(_env(), json.dumps(_conf()))
    assert not resp.error
    tid = _shim_trace_id(stack["trace_file"])

    # seeded VSP fault storm: every call fails until the breaker opens
    # and short-circuits the rest (deterministic from SEED)
    plugin = stack["plugin"]
    plan = FaultPlan(SEED).script("*", Fail(times=32))
    real_channel = plugin._channel
    plugin._new_channel = lambda: ChaosChannel(real_channel.call,
                                               plan=plan)
    plugin._reconnect()
    for _ in range(8):
        with pytest.raises(Exception):
            plugin.get_devices()
    assert plugin.breaker.is_open

    server = metrics.MetricsServer(host="127.0.0.1")
    server.start()
    try:
        snap = flight.fetch(f"127.0.0.1:{server.port}")
    finally:
        server.stop()
    events = snap["events"]
    # the storm is on the record ...
    assert any(e["kind"] == "breaker"
               and e["attributes"]["to"] == "open" for e in events)
    # ... and the ORIGINAL request still replays from the ring: its CNI,
    # VSP and apiserver spans all carry the shim-minted trace_id, even
    # though no collector was attached when it ran
    replayed = {e["name"] for e in events
                if e["kind"] == "span" and e.get("trace_id") == tid}
    assert {"cni.add", "vsp.call", "kube.request"} <= replayed


def test_metrics_render_exemplar_for_the_traced_request(stack):
    resp = CniShim(stack["cni_sock"]).invoke(_env(), json.dumps(_conf()))
    assert not resp.error
    tid = _shim_trace_id(stack["trace_file"])

    server = metrics.MetricsServer(host="127.0.0.1")
    server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/metrics",
            headers={"Accept": "application/openmetrics-text"})
        body = urllib.request.urlopen(req, timeout=5).read().decode()
    finally:
        server.stop()
    exemplar_lines = [
        line for line in body.splitlines()
        if line.startswith("tpu_daemon_cni_seconds_bucket")
        and f'# {{trace_id="{tid}"}}' in line]
    assert exemplar_lines, (
        "no CNI latency bucket carries this request's exemplar")
    # grammar check: `<sample> # {<labels>} <value>` with a parseable value
    sample, _, exemplar = exemplar_lines[0].partition(" # ")
    assert sample.split()[-1].isdigit()
    assert float(exemplar.rpartition("} ")[-1]) >= 0
    # the kube client histogram carries one too
    assert any(
        line.startswith("tpu_kube_client_request_seconds_bucket")
        and f'trace_id="{tid}"' in line for line in body.splitlines())
