"""Injector webhook tests: mutation logic + HTTP server + control switches.

Reference analog: the NRI behavior e2e_test.go relies on (pods requesting
secondary networks get resources injected) plus webhook validation cases
(e2e_test.go:188-330).
"""

import base64
import json
import urllib.request

import pytest

from dpu_operator_tpu.webhook import (
    CONTROL_SWITCHES_CONFIGMAP, NETWORKS_ANNOTATION,
    RESOURCE_NAME_ANNOTATION, WebhookServer, mutate_pod, parse_network_refs)
from dpu_operator_tpu.utils import vars as v


def _nad_obj(name, resource="google.com/tpu", ns="default"):
    return {
        "apiVersion": "k8s.cni.cncf.io/v1",
        "kind": "NetworkAttachmentDefinition",
        "metadata": {"name": name, "namespace": ns,
                     "annotations": {RESOURCE_NAME_ANNOTATION: resource}},
        "spec": {"config": "{}"},
    }


def _pod(networks, requests=None):
    c = {"name": "w", "image": "x"}
    if requests is not None:
        c["resources"] = {"requests": dict(requests),
                          "limits": dict(requests)}
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "p", "namespace": "default",
                     "annotations": {NETWORKS_ANNOTATION: networks}},
        "spec": {"containers": [c]},
    }


def _apply_patches(pod, patches):
    """Minimal JSON-Patch apply for add/replace on the paths we emit."""
    for p in patches:
        parts = [s for s in p["path"].split("/") if s]
        target = pod
        for part in parts[:-1]:
            target = target[int(part)] if part.isdigit() else target[part]
        target[parts[-1]] = p["value"]
    return pod


# -- parse_network_refs -------------------------------------------------------

def test_parse_refs_short_and_namespaced():
    refs = parse_network_refs("tpunfcni-conf, other-ns/nad2@net2", "default")
    assert refs == [("default", "tpunfcni-conf"), ("other-ns", "nad2")]


def test_parse_refs_duplicates_preserved():
    refs = parse_network_refs("a, a", "ns1")
    assert refs == [("ns1", "a"), ("ns1", "a")]


def test_parse_refs_malformed_raises():
    with pytest.raises(ValueError):
        parse_network_refs("bad//ref", "default")


# -- mutate_pod ---------------------------------------------------------------

def _lookup(nads):
    index = {(n["metadata"]["namespace"], n["metadata"]["name"]): n
             for n in nads}

    def fn(ns, name):
        nad = index.get((ns, name))
        if nad is None:
            return None
        return nad["metadata"]["annotations"].get(RESOURCE_NAME_ANNOTATION)
    return fn


def test_mutate_injects_resource_for_two_attachments():
    pod = _pod("tpunfcni-conf, tpunfcni-conf")
    patches = mutate_pod(pod, _lookup([_nad_obj("tpunfcni-conf")]))
    mutated = _apply_patches(pod, patches)
    res = mutated["spec"]["containers"][0]["resources"]
    assert res["requests"]["google.com/tpu"] == "2"
    assert res["limits"]["google.com/tpu"] == "2"


def test_mutate_respects_existing_requests():
    pod = _pod("tpunfcni-conf", requests={"google.com/tpu": "4"})
    patches = mutate_pod(pod, _lookup([_nad_obj("tpunfcni-conf")]))
    assert patches == []  # existing 4 >= wanted 1: nothing to do


def test_mutate_no_annotation_is_noop():
    pod = {"metadata": {"name": "p"}, "spec": {"containers": [{"name": "c"}]}}
    assert mutate_pod(pod, _lookup([])) == []


def test_mutate_nad_without_resource_is_noop():
    nad = _nad_obj("plain")
    del nad["metadata"]["annotations"][RESOURCE_NAME_ANNOTATION]
    pod = _pod("plain")
    assert mutate_pod(pod, _lookup([nad])) == []


# -- server -------------------------------------------------------------------

def _post(port, path, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=5) as r:
        return json.loads(r.read())


def _review(obj, op="CREATE"):
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": "u1", "operation": op, "object": obj}}


@pytest.fixture
def webhook(kube):
    server = WebhookServer(kube, switch_poll_interval=0.1)
    server.start()
    yield server
    server.stop()


def test_server_mutates_pod(kube, webhook):
    kube.create(_nad_obj("tpunfcni-conf"))
    out = _post(webhook.port, "/mutate", _review(_pod("tpunfcni-conf")))
    assert out["response"]["allowed"] is True
    patches = json.loads(base64.b64decode(out["response"]["patch"]))
    assert any(p["value"].get("google.com/tpu") == "1" for p in patches
               if isinstance(p["value"], dict))


def test_server_control_switch_disables_injection(kube, webhook):
    kube.create(_nad_obj("tpunfcni-conf"))
    kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                 "metadata": {"name": CONTROL_SWITCHES_CONFIGMAP,
                              "namespace": v.NAMESPACE},
                 "data": {"config.json":
                          '{"networkResourceInjection": false}'}})
    webhook.refresh_switches()
    out = _post(webhook.port, "/mutate", _review(_pod("tpunfcni-conf")))
    assert out["response"]["allowed"] is True
    assert "patch" not in out["response"]


def test_server_validates_config_cr(webhook):
    bad = {"apiVersion": "tpu.google.com/v1", "kind": "TpuOperatorConfig",
           "metadata": {"name": "wrong-name"}, "spec": {"mode": "host"}}
    out = _post(webhook.port, "/validate", _review(bad))
    assert out["response"]["allowed"] is False
    assert "singleton" in out["response"]["status"]["message"]
    good = {"apiVersion": "tpu.google.com/v1", "kind": "TpuOperatorConfig",
            "metadata": {"name": "tpu-operator-config"},
            "spec": {"mode": "tpu", "sliceTopology": "v5e-16"}}
    assert _post(webhook.port, "/validate",
                 _review(good))["response"]["allowed"] is True


def test_server_healthz(webhook):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{webhook.port}/healthz", timeout=5) as r:
        assert json.loads(r.read())["ok"] is True


def _two_container_pod(annotations=None):
    return {
        "metadata": {"name": "nf", "namespace": "default",
                     "annotations": dict({
                         "k8s.v1.cni.cncf.io/networks":
                             "tpunfcni-conf, tpunfcni-conf"},
                         **(annotations or {}))},
        "spec": {"containers": [
            {"name": "sidecar", "resources": {}},
            {"name": "worker", "resources": {}},
        ]},
    }


def _nad(ns, name):
    return "google.com/tpu"


def test_injects_into_annotated_container():
    """VERDICT r3 weak #8: a multi-container NF pod names its consuming
    container; the resource lands there, not on the first container."""
    from dpu_operator_tpu.webhook.injector import mutate_pod
    pod = _two_container_pod(
        {"tpu.openshift.io/inject-container": "worker"})
    patches = mutate_pod(pod, _nad)
    paths = {p["path"] for p in patches}
    assert all("/spec/containers/1/" in p for p in paths), paths
    req = next(p for p in patches
               if p["path"].endswith("/1/resources/requests"))
    assert req["value"] == {"google.com/tpu": "2"}


def test_injects_into_container_already_requesting_resource():
    """Without the annotation, a container already holding a partial
    request for the resource is the consumer — top it up there."""
    from dpu_operator_tpu.webhook.injector import mutate_pod
    pod = _two_container_pod()
    pod["spec"]["containers"][1]["resources"] = {
        "requests": {"google.com/tpu": "1"}}
    patches = mutate_pod(pod, _nad)
    req = next(p for p in patches
               if p["path"].endswith("/1/resources/requests"))
    assert req["value"] == {"google.com/tpu": "2"}
    assert not any("/containers/0/" in p["path"] for p in patches)


def test_unknown_target_container_is_an_error():
    import pytest

    from dpu_operator_tpu.webhook.injector import mutate_pod
    pod = _two_container_pod(
        {"tpu.openshift.io/inject-container": "nope"})
    with pytest.raises(ValueError, match="names no container"):
        mutate_pod(pod, _nad)


def test_detects_consumer_by_limits_only():
    """Extended resources are commonly written limits-only; the consumer
    scan must see them (apiserver defaulting copies limits to requests)."""
    from dpu_operator_tpu.webhook.injector import mutate_pod
    pod = _two_container_pod()
    pod["spec"]["containers"][1]["resources"] = {
        "limits": {"google.com/tpu": "1"}}
    patches = mutate_pod(pod, _nad)
    assert all("/containers/1/" in p["path"] for p in patches), patches
