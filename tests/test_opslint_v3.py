"""opslint v3 tests: wire-taint dataflow + blocking-under-lock.

Per-rule pass/fail fixtures covering source seeding (all five ingress
families), interprocedural propagation, sanitizer discharge, guard
recognition, pragma suppression and witness chains — plus the shared
symbol-table satellite (one ProjectIndex build per invocation) and the
lint-gate wall-time bound. Fixtures build Modules directly, mirroring
test_opslint.py / test_opslint_v2.py.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

from dpu_operator_tpu.analysis import (ALL_CHECKERS,
                                       BlockingUnderLockChecker,
                                       WireTaintChecker)
from dpu_operator_tpu.analysis.callgraph import ProjectIndex
from dpu_operator_tpu.analysis.core import (Module, load_modules,
                                            pragma_inventory,
                                            run_checkers_on)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SERVE = "dpu_operator_tpu/workloads/serve.py"
CNI = "dpu_operator_tpu/cni/server.py"
RPC = "dpu_operator_tpu/vsp/rpc.py"
CTRL = "dpu_operator_tpu/controller/some_controller.py"
HANDOFF = "dpu_operator_tpu/daemon/handoff.py"


def check(checker, source, relpath=SERVE):
    module = Module("/x/" + relpath, relpath, textwrap.dedent(source))
    return [v for v in checker.check(module)
            if not module.suppressed(v.rule, v.line)]


def check_many(checker, sources):
    modules = [Module("/x/" + rel, rel, textwrap.dedent(src))
               for rel, src in sources.items()]
    by_rel = {m.relpath: m for m in modules}
    return [v for v in checker.check_project(modules)
            if not by_rel[v.path].suppressed(v.rule, v.line)]


# -- wire-taint: source seeding, one fixture per ingress family ---------------

def test_taint_seeds_http_body_and_flags_alloc_sink():
    violations = check(WireTaintChecker(), """
        import json

        class H:
            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length)
    """)
    assert [v.rule for v in violations] == ["wire-taint"]
    assert "[alloc]" in violations[0].message
    assert "rfile.read" in violations[0].message


def test_taint_seeds_cni_stdin_into_path_sink():
    violations = check(WireTaintChecker(), """
        import json, os

        def handle(raw):
            conf = json.loads(raw)
            path = os.path.join("/var/lib/cni", conf["name"])
            return open(path)
    """, relpath=CNI)
    assert violations and all(v.rule == "wire-taint"
                              for v in violations)
    assert any("[path]" in v.message for v in violations)


def test_taint_seeds_grpc_request_param():
    violations = check(WireTaintChecker(), """
        import subprocess

        def handler(request, context):
            subprocess.run(["tool", request["arg"]])
    """, relpath=RPC)
    assert len(violations) == 1
    assert "[subprocess]" in violations[0].message


def test_taint_seeds_cr_spec_fields_into_log_format():
    violations = check(WireTaintChecker(), """
        import logging

        log = logging.getLogger(__name__)

        def reconcile(cfg):
            log.info("mode is " + cfg.spec.mode)
    """, relpath=CTRL)
    assert len(violations) == 1
    assert "[logfmt]" in violations[0].message


def test_taint_seeds_cr_spec_key_reads():
    violations = check(WireTaintChecker(), """
        def reconcile(obj, topology_map):
            key = obj["spec"]["sliceTopology"]
            return topology_map[key]
    """, relpath=CTRL)
    assert len(violations) == 1
    assert "[index]" in violations[0].message


def test_taint_seeds_handoff_bundle():
    violations = check(WireTaintChecker(), """
        import os

        def adopt(sock, state_dir):
            bundle, size = recv_frame(sock)
            for name in bundle["netconfs"]:
                os.unlink(os.path.join(state_dir, name))
    """, relpath=HANDOFF)
    assert violations
    assert all("[path]" in v.message for v in violations)


# -- wire-taint: interprocedural propagation + witness chains -----------------

def test_taint_propagates_through_resolved_calls_with_witness():
    violations = check(WireTaintChecker(), """
        import json, os

        class Cache:
            def _path(self, sandbox_id):
                return os.path.join("/state", sandbox_id)

            def save(self, sandbox_id, data):
                return open(self._path(sandbox_id), "w")

        class Server:
            def __init__(self):
                self.cache = Cache()

            def handle(self, raw):
                body = json.loads(raw)
                self.cache.save(body["sandbox"], body)
    """, relpath=CNI)
    assert violations
    msg = violations[0].message
    # the witness chain names the interprocedural route
    assert "Server.handle" in msg and "Cache" in msg


def test_taint_return_summary_carries_taint_back_to_caller():
    violations = check(WireTaintChecker(), """
        import json

        def parse(raw):
            return json.loads(raw)

        def serve(raw, conn):
            spec = parse(raw)
            n = int(spec["n"])
            conn.recv(n)
    """, relpath=CNI)
    assert len(violations) == 1
    assert "[alloc]" in violations[0].message


def test_taint_clean_when_callee_sanitizes():
    assert check(WireTaintChecker(), """
        import json
        from ..utils.validate import clamped_int

        def parse(raw):
            spec = json.loads(raw)
            return clamped_int(spec["n"], 0, 4096, "n")

        def serve(raw, conn):
            n = parse(raw)
            conn.recv(n)
    """, relpath=CNI) == []


# -- wire-taint: sanitizer discharge is PER SINK ------------------------------

def test_taint_int_discharges_path_but_not_alloc():
    # int() result cannot traverse a path...
    assert check(WireTaintChecker(), """
        import json, os

        def handle(raw):
            n = int(json.loads(raw)["n"])
            return open(os.path.join("/state", "f-%d" % n))
    """, relpath=CNI) == []
    # ...but it is still an unbounded allocation size
    violations = check(WireTaintChecker(), """
        import json

        def handle(raw, conn):
            n = int(json.loads(raw)["n"])
            conn.recv(n)
    """, relpath=CNI)
    assert len(violations) == 1 and "[alloc]" in violations[0].message


def test_taint_bounded_label_discharges_metric_label():
    flagged = check(WireTaintChecker(), """
        import json
        from ..utils import metrics

        def handle(raw):
            cmd = json.loads(raw)["cmd"]
            metrics.REQUESTS.inc(command=cmd)
    """, relpath=CNI)
    assert len(flagged) == 1 and "[label]" in flagged[0].message
    assert check(WireTaintChecker(), """
        import json
        from ..utils import metrics

        def handle(raw):
            cmd = metrics.bounded_label(json.loads(raw)["cmd"],
                                        {"ADD", "DEL"})
            metrics.REQUESTS.inc(command=cmd)
    """, relpath=CNI) == []


def test_taint_guard_raise_discharges_bounded_kinds():
    assert check(WireTaintChecker(), """
        import json

        def handle(raw, conn):
            n = int(json.loads(raw)["n"])
            if n > 65536:
                raise ValueError("too big")
            conn.recv(n)
    """, relpath=CNI) == []


def test_taint_membership_guard_discharges_everything():
    assert check(WireTaintChecker(), """
        import json, subprocess

        def handle(raw):
            cmd = json.loads(raw)["cmd"]
            if cmd not in ("up", "down"):
                raise ValueError(cmd)
            subprocess.run(["tool", cmd])
    """, relpath=CNI) == []


def test_taint_comprehension_applies_element_sanitizer():
    from dpu_operator_tpu.utils.validate import clamped_int  # noqa: F401
    assert check(WireTaintChecker(), """
        import json
        from ..utils.validate import clamped_int

        def handle(raw, pool):
            ids = tuple(clamped_int(t, 0, 1024, "id")
                        for t in json.loads(raw)["ids"])
            pool.alloc("owner", ids[0])
    """, relpath=CNI) == []


def test_taint_lazy_log_args_pass_format_string_flagged():
    # tainted data as a LAZY %s arg is fine...
    assert check(WireTaintChecker(), """
        import json, logging

        log = logging.getLogger(__name__)

        def handle(raw):
            body = json.loads(raw)
            log.info("got %s", body["name"])
    """, relpath=CNI) == []
    # ...as the format string it is log forgery
    violations = check(WireTaintChecker(), """
        import json, logging

        log = logging.getLogger(__name__)

        def handle(raw):
            body = json.loads(raw)
            log.info("got " + str(body["name"]))
    """, relpath=CNI)
    assert len(violations) == 1 and "[logfmt]" in violations[0].message


def test_taint_pragma_suppresses():
    src = """
        import json

        def handle(raw, conn):
            n = int(json.loads(raw)["n"])
            conn.recv(n)  # opslint: disable=wire-taint
    """
    module = Module("/x/" + CNI, CNI, textwrap.dedent(src))
    violations = [v for v in WireTaintChecker().check(module)
                  if not module.suppressed(v.rule, v.line)]
    assert violations == []


def test_taint_ignores_trusted_modules():
    # the same flow OUTSIDE a registered ingress module is not seeded
    assert check(WireTaintChecker(), """
        import json

        def handle(raw, conn):
            n = int(json.loads(raw)["n"])
            conn.recv(n)
    """, relpath="dpu_operator_tpu/utils/innocuous.py") == []


# -- blocking-under-lock ------------------------------------------------------

BLOCK = "dpu_operator_tpu/utils/somemod.py"


def test_blocking_flags_untimed_queue_get_under_lock():
    violations = check(BlockingUnderLockChecker(), """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self.queue = None

            def drain(self):
                with self._lock:
                    return self.queue.get()
    """, relpath=BLOCK)
    assert [v.rule for v in violations] == ["blocking-under-lock"]
    assert "queue.get" in violations[0].message
    assert "Pump._lock" in violations[0].message


def test_blocking_passes_timeout_bounded_variants():
    assert check(BlockingUnderLockChecker(), """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self.queue = None
                self._evt = threading.Event()

            def drain(self):
                with self._lock:
                    self._evt.wait(5.0)
                    return self.queue.get(timeout=1.0)
    """, relpath=BLOCK) == []


def test_blocking_flags_transitively_reached_sink_with_chain():
    violations = check(BlockingUnderLockChecker(), """
        import threading, time

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()

            def _backoff(self):
                time.sleep(1.0)

            def tick(self):
                with self._lock:
                    self._backoff()
    """, relpath=BLOCK)
    assert len(violations) == 1
    msg = violations[0].message
    assert "Engine.tick" in msg and "Engine._backoff" in msg


def test_blocking_ignores_rlock_and_short_sleeps():
    assert check(BlockingUnderLockChecker(), """
        import threading, time

        class Engine:
            def __init__(self):
                self._lock = threading.RLock()
                self._plain = threading.Lock()

            def tick(self):
                with self._lock:
                    time.sleep(10)   # RLock: out of scope

            def micro(self):
                with self._plain:
                    time.sleep(0.01)  # below the wedge threshold
    """, relpath=BLOCK) == []


def test_blocking_condition_wait_releases_its_own_lock():
    assert check(BlockingUnderLockChecker(), """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)

            def drain(self):
                with self._lock:
                    self._cond.wait()
    """, relpath=BLOCK) == []


def test_blocking_socket_io_under_lock_flagged_and_pragma_works():
    src = """
        import threading

        class Client:
            def __init__(self, sock):
                self._lock = threading.Lock()
                self._sock = sock

            def call(self, payload):
                with self._lock:
                    self._sock.sendall(payload)
    """
    violations = check(BlockingUnderLockChecker(), src, relpath=BLOCK)
    assert len(violations) == 1 and "sendall" in violations[0].message
    suppressed = src.replace(
        "self._sock.sendall(payload)",
        "self._sock.sendall(payload)  "
        "# opslint: disable=blocking-under-lock")
    module = Module("/x/" + BLOCK, BLOCK, textwrap.dedent(suppressed))
    assert [v for v in BlockingUnderLockChecker().check(module)
            if not module.suppressed(v.rule, v.line)] == []


def test_blocking_local_dict_named_requests_is_not_wire():
    assert check(BlockingUnderLockChecker(), """
        import threading

        class Tally:
            def __init__(self):
                self._lock = threading.Lock()

            def fold(self, containers):
                with self._lock:
                    requests = {}
                    for c in containers:
                        requests.update(c)
                    return requests
    """, relpath=BLOCK) == []


# -- satellites: shared build, wall time, inventory ---------------------------

def test_full_run_builds_the_symbol_table_once():
    """Three whole-program passes (lock rules, blocking, taint) must
    share ONE ProjectIndex per invocation."""
    modules = load_modules(["dpu_operator_tpu"], REPO)
    before = ProjectIndex.builds
    run_checkers_on([cls() for cls in ALL_CHECKERS], modules)
    assert ProjectIndex.builds - before <= 1


def test_lint_gate_wall_time_stays_bounded():
    """The CI gate must not crawl as interprocedural passes stack up:
    a full `python -m dpu_operator_tpu.analysis` run (15 rules, whole
    tree) stays well under the bound."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.analysis"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 60.0, f"lint gate took {elapsed:.1f}s"


def test_pragma_inventory_counts_per_rule():
    module = Module("/x/" + BLOCK, BLOCK, textwrap.dedent("""
        import time
        x = 1  # opslint: disable=wire-taint
        y = 2  # opslint: disable=wire-taint,blocking-under-lock
    """))
    inv = pragma_inventory([module])
    assert inv == {"wire-taint": 2, "blocking-under-lock": 1}


def test_cli_sarif_out_writes_stable_artifact(tmp_path):
    out = tmp_path / "opslint.sarif"
    proc = subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.analysis",
         "--sarif-out", str(out)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    rules = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert {"wire-taint", "blocking-under-lock"} <= rules
    assert "pragmas:" in proc.stdout