"""Serving-path fault engine gate (`make serve-chaos-check`).

The contracts under test, end to end: a scripted ChaosExecutor fault
storm (Fail / Oom / poisoned-rid, all seeded, virtual-clock) must cost
exactly its victims — transient step failures take the
retry-with-rebuild path (blocks freed, tokens kept, re-prefill on
readmission) and the recovered stream is bit-identical to an unfaulted
run; a request that exhausts its retry budget is classified POISONED
and excised with a distinct outcome; ingress deadlines are enforced at
admission, at chunk-queue re-entry, and mid-stream (completion wins
the race by construction); and under a sustained storm the
graceful-degradation ladder sheds batch traffic while the interactive
serve-ttft SLO holds, then recovers through hysteresis. Zero KV-block
leaks across 500+ fault/retry/rebuild lifecycles, traces bit-identical
across two runs of the same seed, and the serve-path MTTR series lands
in FAULT_r02.json.

Injected clocks and seeded RNGs only — opslint's chaos-determinism
rule covers the serve_chaos marker, so a wall-clock or unseeded-
entropy call here fails lint before it can flake.
"""

import json
import math
import os
import random
import time

import pytest

from dpu_operator_tpu.testing import chaos
from dpu_operator_tpu.utils import metrics, slo
from dpu_operator_tpu.workloads import degrade, serve

pytestmark = pytest.mark.serve_chaos

SEED = 20260806


def _config(**kw) -> serve.ServeConfig:
    base = dict(slots=4, kv_blocks=64, kv_block_size=16,
                queue_limit=256, ttft_bound_s=1.0)
    base.update(kw)
    return serve.ServeConfig(**base)


def _expected_tokens(req: serve.Request) -> list:
    """The SimExecutor stream is a pure function of (rid, position) —
    the oracle every rebuilt request must still match exactly."""
    return [serve.SimExecutor._token(req, i)
            for i in range(req.output_len)]


def _p99(xs: list) -> float:
    xs = sorted(xs)
    return xs[min(len(xs) - 1, max(0, math.ceil(0.99 * len(xs)) - 1))]


# -- retry-with-rebuild -------------------------------------------------------


def test_transient_step_fault_retries_and_stream_survives_bitwise():
    """One scripted decode-step failure (connection reset — transient)
    must cost its victim ONE retry/rebuild round trip: blocks freed,
    generated tokens kept, re-prefill on readmission — and every
    completed stream, including the victim's, is identical to an
    unfaulted run of the same arrivals."""
    faults_before = metrics.SERVE_EXECUTOR_FAULTS.value(phase="decode")
    retries_before = metrics.SERVE_RETRIES.value(phase="decode")
    plan = chaos.FaultPlan(seed=SEED)
    plan.script("step", chaos.Ok(times=3), chaos.Fail())
    ex = chaos.ChaosExecutor(serve.SimExecutor(), plan=plan)
    sched = serve.Scheduler(_config(), executor=ex)
    reqs = [serve.Request(rid="a", prompt_len=8, output_len=12,
                          slo_class=serve.INTERACTIVE, arrival_s=0.0),
            serve.Request(rid="b", prompt_len=8, output_len=12,
                          slo_class=serve.BATCH, arrival_s=0.0)]
    sched.submit_all(reqs)
    assert sched.run(max_steps=10_000) < 10_000
    assert sched.completed_total == 2 and not sched.failed
    assert sched.retries_total == 1
    faults = [t for t in sched.trace if t[0] == "step_fault"]
    assert faults == [("step_fault", faults[0][1], "decode",
                       faults[0][3], "ConnectionResetError")]
    victim_rid = faults[0][3]
    retries = [t for t in sched.trace if t[0] == "retry"]
    assert retries == [("retry", faults[0][1], victim_rid, 1)]
    # the rebuilt stream equals the pure-function oracle — retry kept
    # the tokens and re-prefill continued the exact same stream
    for req in sched.completed:
        assert req.tokens == _expected_tokens(req)
    victim = next(r for r in sched.completed if r.rid == victim_rid)
    assert victim.retries == 1
    # serve-path MTTR: fault-to-recovery was sampled for the victim
    assert [rid for rid, _ in sched.retry_recoveries] == [victim_rid]
    assert sched.retry_recoveries[0][1] > 0.0
    assert sched.pool.outstanding() == 0
    assert metrics.SERVE_EXECUTOR_FAULTS.value(phase="decode") \
        == faults_before + 1
    assert metrics.SERVE_RETRIES.value(phase="decode") \
        == retries_before + 1


def test_allocation_oom_is_transient_and_takes_the_retry_path():
    """An allocation-time ExecutorOom frees the victim's blocks via
    the SAME rebuild path — which is exactly what an OOM needs — and
    the request still completes."""
    plan = chaos.FaultPlan(seed=SEED)
    plan.script("step", chaos.Ok(times=2), chaos.Oom())
    ex = chaos.ChaosExecutor(serve.SimExecutor(), plan=plan)
    sched = serve.Scheduler(_config(), executor=ex)
    sched.submit(serve.Request(rid="oomed", prompt_len=8, output_len=10,
                               arrival_s=0.0))
    assert sched.run(max_steps=10_000) < 10_000
    assert sched.completed_total == 1 and not sched.failed
    assert sched.retries_total == 1
    (fault,) = [t for t in sched.trace if t[0] == "step_fault"]
    assert fault[4] == "ExecutorOom"
    assert sched.completed[0].tokens \
        == _expected_tokens(sched.completed[0])
    assert sched.pool.outstanding() == 0


class Clock:
    """Injected wall clock (the test_faults idiom): Stall faults call
    ``advance`` so a 2 s executor hang costs zero wall seconds and
    replays bit-identically."""

    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def test_stall_past_the_deadline_on_an_injected_clock_is_excised():
    """A Stall moves the INJECTED clock past the request's deadline
    while the step 'hangs' — the sweep excises the victim with its
    partial tokens the moment the stalled iteration lands, with zero
    wall-clock sleeps anywhere."""
    clock = Clock()
    plan = chaos.FaultPlan(seed=SEED)
    plan.script("step", chaos.Ok(times=2),
                chaos.Stall(2.0, clock.advance))
    ex = chaos.ChaosExecutor(serve.SimExecutor(), plan=plan)
    sched = serve.Scheduler(_config(), executor=ex, clock=clock)
    sched.submit(serve.Request(rid="hung", prompt_len=8, output_len=40,
                               arrival_s=0.0, deadline_budget_s=1.5))
    assert sched.run(max_steps=10_000) < 10_000
    (hung,) = sched.failed
    assert hung.rid == "hung"
    assert hung.reject_reason == "deadline_exceeded"
    assert 0 < len(hung.tokens) < hung.output_len
    assert sched.deadline_exceeded_total == 1
    assert sched.pool.outstanding() == 0
    assert clock.t == pytest.approx(2.0)  # the stall moved ALL time


def test_poisoned_rid_is_excised_within_budget(kube):
    """A rid that deterministically fails EVERY executor call it
    appears in must burn exactly its retry budget and then be excised
    with the distinct ``poisoned`` outcome — one bad request costs one
    stream plus budget, never the scheduler — while an innocent
    request sharing the batch completes untouched."""
    from dpu_operator_tpu.k8s import events

    poisoned_before = metrics.SERVE_POISONED.value()
    outcome_before = metrics.SERVE_REQUESTS.value(
        slo_class=serve.INTERACTIVE, outcome="poisoned")
    events.configure(events.EventRecorder(kube, "tpu-daemon"),
                     events.node_reference("tpu-vm-0"))
    try:
        ex = chaos.ChaosExecutor(serve.SimExecutor()).poison("bad")
        cfg = _config()
        sched = serve.Scheduler(cfg, executor=ex)
        seen: list = []
        sched.submit(serve.Request(rid="good", prompt_len=8,
                                   output_len=8, arrival_s=0.0))
        sched.submit(serve.Request(
            rid="bad", prompt_len=8, output_len=8,
            slo_class=serve.INTERACTIVE, arrival_s=0.0,
            stream=lambda ev, val: seen.append((ev, val))))
        assert sched.run(max_steps=10_000) < 10_000
        events.flush()
    finally:
        events.reset()
    (good,) = sched.completed
    assert good.rid == "good" and good.tokens == _expected_tokens(good)
    (bad,) = sched.failed
    assert bad.rid == "bad" and bad.state == serve.FAILED
    assert bad.reject_reason == "poisoned"
    assert bad not in sched.rejected  # failed is NOT a rejection
    # excised within budget: exactly retry_budget rebuilds, then poison
    assert [t for t in sched.trace if t[0] == "retry"] \
        == [("retry", t[1], "bad", i + 1)
            for i, t in enumerate(
                t for t in sched.trace if t[0] == "retry")]
    assert len([t for t in sched.trace if t[0] == "retry"]) \
        == cfg.retry_budget
    (poison,) = [t for t in sched.trace if t[0] == "poison"]
    assert poison[2] == "bad" and poison[3] == cfg.retry_budget
    assert sched.poisoned_total == 1 and sched.failed_total == 1
    assert sched.pool.outstanding() == 0
    # the stream saw the distinct terminal record, exactly once
    assert seen[-1] == ("failed", "poisoned")
    assert [e for e in seen if e[0] != "token"] \
        == [("failed", "poisoned")]
    assert metrics.SERVE_POISONED.value() == poisoned_before + 1
    assert metrics.SERVE_REQUESTS.value(
        slo_class=serve.INTERACTIVE, outcome="poisoned") \
        == outcome_before + 1
    reasons = {e["reason"] for e in kube.list("v1", "Event")}
    assert "ServeRequestPoisoned" in reasons


def test_batched_step_fault_attributes_the_actual_victim():
    """A PoisonedRid raised out of a BATCHED step carries the rid —
    the scheduler must bill the actual victim, not the latest-admitted
    guess, and the innocent batchmate completes its full stream."""
    ex = chaos.ChaosExecutor(serve.SimExecutor())
    sched = serve.Scheduler(_config(), executor=ex)
    sched.submit(serve.Request(rid="v", prompt_len=8, output_len=20,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.0))
    sched.submit(serve.Request(rid="w", prompt_len=8, output_len=20,
                               arrival_s=0.0))
    # let both admit and decode a little, THEN poison the earlier-
    # admitted one: latest-admitted attribution alone would pick "w"
    for _ in range(4):
        sched.step()
    ex.poison("v")
    assert sched.run(max_steps=10_000) < 10_000
    faults = [t for t in sched.trace if t[0] == "step_fault"]
    assert faults and all(t[3] == "v" and t[4] == "PoisonedRid"
                          for t in faults)
    (bad,) = sched.failed
    assert bad.rid == "v" and bad.reject_reason == "poisoned"
    (w,) = sched.completed
    assert w.rid == "w" and w.tokens == _expected_tokens(w)
    assert sched.pool.outstanding() == 0


# -- the seeded storm: ladder, SLO, determinism -------------------------------


def _storm_run() -> serve.Scheduler:
    """One seeded fault storm: two scripted 2-iteration Fail bursts
    against a mixed open-loop arrival stream — enough consecutive bad
    signals to walk the ladder down twice (the second burst doubles
    the hold-down: same flap window), then a clean tail long enough to
    recover through hysteresis."""
    plan = chaos.FaultPlan(seed=SEED)
    plan.script("step",
                chaos.Ok(times=40), chaos.Fail(times=2),
                chaos.Ok(times=30), chaos.Fail(times=2))
    ex = chaos.ChaosExecutor(serve.SimExecutor(), plan=plan)
    sched = serve.Scheduler(_config(slots=4, kv_blocks=96,
                                    queue_limit=512), executor=ex)
    sched.submit_all(serve.open_loop_arrivals(
        SEED, rate_rps=6.0, horizon_s=8.0, prompt_lens=(8, 32),
        output_lens=(8, 32), interactive_frac=0.5))
    assert sched.run(max_steps=100_000) < 100_000
    return sched


def test_storm_sheds_batch_holds_interactive_slo_and_recovers(kube):
    """The gate's core claim: under a sustained executor-fault storm
    the ladder escalates (shedding batch admissions), the interactive
    serve-ttft SLO HOLDS through the degraded window, and once the
    faults stop the ladder recovers to healthy through hold-down +
    consecutive-good hysteresis — all of it published (Events, trace
    tuples, gauge) and leak-free."""
    from dpu_operator_tpu.k8s import events

    events.configure(events.EventRecorder(kube, "tpu-daemon"),
                     events.node_reference("tpu-vm-0"))
    try:
        sched = _storm_run()
        events.flush()
    finally:
        events.reset()
    # the storm actually fired and the ladder walked both directions
    assert len(sched.executor.plan.injected) == 4
    assert sched.ladder.escalations >= 2
    assert sched.ladder.holddown_doublings >= 1
    assert sched.ladder.rung == degrade.RUNG_HEALTHY  # recovered
    rungs = [t for t in sched.trace if t[0] == "rung"]
    assert any(t[3] > t[2] for t in rungs)  # escalation committed
    assert any(t[3] < t[2] for t in rungs)  # recovery committed
    assert rungs[-1][3] == degrade.RUNG_HEALTHY
    # batch was shed at admission while degraded — with the distinct
    # reason, not folded into queue_full
    shed = [r for r in sched.rejected
            if r.reject_reason == "degraded_shed"]
    assert shed and all(r.slo_class == serve.BATCH for r in shed)
    # the interactive serve-ttft SLO held through the storm
    ttfts = [r.ttft_s for r in sched.completed
             if r.slo_class == serve.INTERACTIVE]
    assert ttfts and _p99(ttfts) <= slo.SERVE_TTFT_SLOW_SECONDS
    # every completed stream — victims included — matches the oracle
    for req in sched.completed:
        assert req.tokens == _expected_tokens(req)
    assert sched.retries_total >= 1
    assert sched.pool.outstanding() == 0
    reasons = {e["reason"] for e in kube.list("v1", "Event")}
    assert {"ServeDegraded", "ServeRecovered"} <= reasons
    assert sched.snapshot()["degraded"]["rung"] == 0


def test_storm_traces_are_bit_identical_across_runs():
    """Two runs of the same storm seed must produce byte-identical
    traces and identical terminal accounting — the determinism
    artifact serve-chaos-check exists to defend. Chaos (FaultPlan
    order + seeded flaky RNG), retry jitter (seeded RetryPolicy), the
    ladder (pure state machine on the virtual clock) and the executor
    (pure token function) all replay exactly."""
    a, b = _storm_run(), _storm_run()
    assert a.trace == b.trace
    assert json.dumps(a.trace) == json.dumps(b.trace)
    assert [r.rid for r in a.completed] == [r.rid for r in b.completed]
    assert [(r.rid, r.reject_reason) for r in a.failed] \
        == [(r.rid, r.reject_reason) for r in b.failed]
    assert [(r.rid, r.reject_reason) for r in a.rejected] \
        == [(r.rid, r.reject_reason) for r in b.rejected]
    assert a.retry_recoveries == b.retry_recoveries
    assert a.ladder.snapshot(a.now) == b.ladder.snapshot(b.now)


# -- 500 fault/retry/rebuild lifecycles: the leak gate + FAULT_r02 ------------


def test_kv_never_leaks_across_500_fault_lifecycles_and_mttr_lands():
    """520 seeded request lifecycles through a flaky executor (seeded
    3% step-fault storm plus two poisoned rids): every request ends
    terminally (completed, poisoned, or shed), every rebuilt stream
    matches the oracle, and the pool returns to EXACTLY zero
    outstanding blocks. The serve-path MTTR series — last transient
    fault to the victim's completion — lands in FAULT_r02.json."""
    plan = chaos.FaultPlan(seed=SEED)
    plan.flaky("step", 0.03, n=8000)
    plan.flaky("begin", 0.01, n=1000)
    ex = chaos.ChaosExecutor(serve.SimExecutor(), plan=plan)
    ex.poison("life100", "life300")
    cfg = _config(slots=6, kv_blocks=96, queue_limit=1000)
    sched = serve.Scheduler(cfg, executor=ex)
    rng = random.Random(SEED)
    t = 0.0
    for i in range(520):
        t += rng.expovariate(8.0)
        sched.submit(serve.Request(
            rid=f"life{i}", prompt_len=rng.randint(4, 64),
            output_len=rng.randint(1, 48),
            slo_class=serve.INTERACTIVE if rng.random() < 0.4
            else serve.BATCH,
            arrival_s=t))
    assert sched.run(max_steps=500_000) < 500_000
    # every lifecycle ended terminally, none vanished: completed,
    # excised (poisoned), or shed by the degraded ladder — a 3% step-
    # fault storm keeps the ladder escalated for real stretches, and
    # batch admissions shed there are clean terminal lifecycles too
    assert (sched.completed_total + sched.failed_total
            + sched.rejected_total) == 520
    assert sched.completed_total >= 300
    assert all(r.reject_reason == "degraded_shed"
               for r in sched.rejected)
    assert sched.ladder.escalations >= 1
    # the storm actually exercised the retry path, hard
    assert sched.retries_total >= 20
    assert len(plan.injected) >= 20
    assert sched.retry_recoveries, "no serve-path MTTR was sampled"
    # both poisoned rids were excised with the distinct outcome; no
    # other classification leaked in (resets are transient by contract)
    failed = {r.rid: r.reject_reason for r in sched.failed}
    assert failed.get("life100") == "poisoned"
    assert failed.get("life300") == "poisoned"
    assert set(failed.values()) == {"poisoned"}
    # rebuilt streams are exact — retry kept tokens, re-prefill
    # continued the same pure-function stream
    retried_done = [r for r in sched.completed if r.retries]
    assert retried_done, "no retried request completed"
    for req in sched.completed:
        assert len(req.tokens) == req.output_len
        assert req.tokens == _expected_tokens(req)
    # THE leak gate: zero outstanding blocks, every slot back
    assert sched.pool.outstanding() == 0
    assert len(sched._free_slots) == cfg.slots
    assert not sched._prefilling

    mttrs = sorted(s for _, s in sched.retry_recoveries)
    artifact = {
        "schema": 1,
        "seed": SEED,
        "lifecycles": 520,
        "completed": sched.completed_total,
        "failed": sched.failed_total,
        "poisoned": sched.poisoned_total,
        "rejected": sched.rejected_total,
        "retries": sched.retries_total,
        "faults_injected": len(plan.injected),
        "retry_budget": cfg.retry_budget,
        "kv_blocks_outstanding": sched.pool.outstanding(),
        "ladder": {
            "escalations": sched.ladder.escalations,
            "holddown_doublings": sched.ladder.holddown_doublings,
            "final_rung": sched.ladder.rung,
        },
        "mttr_s": {
            "count": len(mttrs),
            "mean": round(sum(mttrs) / len(mttrs), 3),
            "p50": round(mttrs[len(mttrs) // 2], 3),
            "max": round(max(mttrs), 3),
        },
    }
    repo_root = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo_root, "FAULT_r02.json"), "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")


# -- degradation-ladder hysteresis (pure state machine) -----------------------


def test_ladder_escalates_only_on_consecutive_bads():
    lad = degrade.DegradationLadder()
    assert lad.observe(0.0, True) is None          # 1 bad: not yet
    assert lad.observe(0.1, False) is None         # reset
    assert lad.observe(0.2, True) is None
    change = lad.observe(0.3, True)                # 2 consecutive
    assert change == degrade.RungChange(0, 1, "degraded")
    assert lad.rung == degrade.RUNG_SHED_BATCH
    assert lad.escalations == 1


def test_ladder_ignores_goods_during_hold_down_then_recovers():
    lad = degrade.DegradationLadder()
    lad.observe(0.0, True)
    lad.observe(0.1, True)                          # rung 1, hold 2 s
    assert lad.rung == 1 and lad.hold_remaining_s(0.1) == 2.0
    # a full recover_after run of goods INSIDE the hold-down: ignored
    for i in range(6):
        assert lad.observe(0.2 + i * 0.1, False) is None
    assert lad.rung == 1
    # after expiry, goods count — and it takes recover_after of them
    now = 2.5
    for i in range(3):
        assert lad.observe(now + i * 0.1, False) is None
    change = lad.observe(now + 0.4, False)
    assert change == degrade.RungChange(1, 0, "recovered")
    assert lad.rung == degrade.RUNG_HEALTHY


def test_ladder_reescalation_in_flap_window_doubles_hold_down():
    lad = degrade.DegradationLadder()
    lad.observe(0.0, True)
    lad.observe(0.1, True)                          # episode 1: hold 2
    lad.observe(1.0, True)
    lad.observe(1.1, True)                          # episode 2: hold 4
    assert lad.rung == 2
    assert lad.holddown_doublings == 1
    assert lad.hold_remaining_s(1.1) == pytest.approx(4.0)
    # outside the flap window the hold-down RESETS to base
    lad.observe(100.0, True)
    lad.observe(100.1, True)
    assert lad.hold_remaining_s(100.1) == 2.0


def test_ladder_hold_down_is_capped_and_top_rung_is_terminal():
    pol = degrade.LadderPolicy(hold_down_base_s=2.0,
                               hold_down_max_s=8.0)
    lad = degrade.DegradationLadder(pol)
    t = 0.0
    for _ in range(10):                             # flap storm
        lad.observe(t, True)
        change = lad.observe(t + 0.1, True)
        t += 1.0
        if lad.rung == degrade.RUNG_INTERACTIVE_ONLY:
            break
    assert lad.rung == degrade.RUNG_INTERACTIVE_ONLY
    # more bads at the top rung: no further escalation, ever
    for _ in range(5):
        assert lad.observe(t, True) is None
        t += 0.1
    assert lad.rung == degrade.RUNG_INTERACTIVE_ONLY
    # the doubling is bounded by the cap
    assert lad._hold_s <= pol.hold_down_max_s
    snap = lad.snapshot(t)
    assert snap["name"] == "interactive_only"
    assert set(snap) == {"rung", "name", "escalations",
                         "holddownDoublings", "holdRemainingS"}


# -- hostile deadline-header parsing ------------------------------------------

#: the traceparent-parser table discipline: every hostile shape a
#: header can take, and what the strict grammar must do with it
HOSTILE_DEADLINES = [
    (None, None),                 # absent header
    (123, None),                  # non-string (already-parsed object)
    (b"100", None),               # bytes, not str
    ("", None),                   # empty
    ("-5", None),                 # negative
    ("+5", None),                 # explicit sign
    ("NaN", None),                # not a number at all
    ("1e3", None),                # scientific notation
    ("1.5", None),                # fractional
    (" 100", None),               # leading whitespace
    ("100 ", None),               # trailing whitespace
    ("0", None),                  # below the floor (zero budget)
    ("86400001", None),           # above the 24 h ceiling
    ("999999999", None),          # absurd magnitude (9 digits)
    ("100\r\nX-Evil: 1", None),   # header-splitting attempt
    ("0x64", None),               # hex
    ("1", 1),                     # floor
    ("1500", 1500),               # a normal budget
    ("86400000", 86_400_000),     # ceiling, inclusive
]


@pytest.mark.parametrize("value,expected", HOSTILE_DEADLINES)
def test_parse_deadline_ms_hostile_table(value, expected):
    """Strict-grammar discipline (the traceparent-parser precedent):
    anything that is not 1-8 ASCII digits inside [1 ms, 24 h] yields
    None — fail OPEN (no deadline) without ever trusting the bytes."""
    assert serve.parse_deadline_ms(value) == expected


# -- deadline enforcement: admission, chunk re-entry, mid-stream --------------


def test_deadline_rejected_at_admission_when_eta_cannot_fit():
    """A deadline the modeled MINIMUM service time already misses is
    excised at admission — zero tokens, zero wasted decode work."""
    sched = serve.Scheduler(_config())
    seen: list = []
    sched.submit(serve.Request(
        rid="late", prompt_len=8, output_len=400, arrival_s=0.0,
        deadline_budget_s=0.05,  # ~400 decode iterations cannot fit
        stream=lambda ev, val: seen.append((ev, val))))
    assert sched.run(max_steps=10_000) < 10_000
    (late,) = sched.failed
    assert late.reject_reason == "deadline_exceeded"
    assert late.tokens == [] and late.first_token_s is None
    assert sched.deadline_exceeded_total == 1
    assert [t for t in sched.trace if t[0] == "deadline"] \
        == [("deadline", 1, "late", 0)]
    assert seen == [("deadline_exceeded", 0)]
    assert sched.pool.outstanding() == 0


def test_deadline_enforced_at_chunk_queue_reentry():
    """A chunked-prefill request whose deadline expires while it still
    sits in the chunk queue is excised THERE — partially prefilled,
    zero tokens — instead of burning the remaining chunk budget on a
    corpse."""
    cfg = _config(slots=4, kv_blocks=96, queue_limit=64,
                  prefill_chunk_tokens=16)
    sched = serve.Scheduler(cfg)
    # two small interactive requests keep decode advancing the clock
    # while the victim's 256-token prompt crawls through the budget
    for i in range(2):
        sched.submit(serve.Request(rid=f"i{i}", prompt_len=8,
                                   output_len=40,
                                   slo_class=serve.INTERACTIVE,
                                   arrival_s=0.0))
    sched.submit(serve.Request(rid="crawl", prompt_len=256,
                               output_len=4, arrival_s=0.0,
                               deadline_budget_s=0.2))
    assert sched.run(max_steps=10_000) < 10_000
    (crawl,) = sched.failed
    assert crawl.rid == "crawl"
    assert crawl.reject_reason == "deadline_exceeded"
    assert crawl.prefilled > 0      # it WAS making chunk progress
    assert crawl.tokens == []       # but never reached decode
    assert len(sched.completed) == 2
    assert sched.pool.outstanding() == 0


def test_deadline_enforced_mid_stream_with_partial_tokens():
    """A deadline that admission's uncontended ETA accepts but batched
    service misses is enforced MID-STREAM: the victim keeps its
    partial tokens on the wire (the terminal record says how many) and
    everything it held is freed."""
    contended = serve.CostModel(decode_base_s=0.02,
                                decode_per_seq_s=0.01)
    sched = serve.Scheduler(_config(), cost_model=contended)
    seen: list = []
    for i in range(3):
        sched.submit(serve.Request(rid=f"bg{i}", prompt_len=8,
                                   output_len=30, arrival_s=0.0))
    # uncontended ETA ~ 30 * decode_s(1) = 0.9 s; 4-deep batched
    # service ~ 30 * decode_s(4) = 1.8 s: admitted, then overtaken
    sched.submit(serve.Request(
        rid="victim", prompt_len=8, output_len=30, arrival_s=0.0,
        slo_class=serve.INTERACTIVE, deadline_budget_s=1.2,
        stream=lambda ev, val: seen.append((ev, val))))
    assert sched.run(max_steps=10_000) < 10_000
    (victim,) = sched.failed
    assert victim.rid == "victim"
    assert victim.reject_reason == "deadline_exceeded"
    assert 0 < len(victim.tokens) < victim.output_len
    assert seen[-1] == ("deadline_exceeded", len(victim.tokens))
    assert len(sched.completed) == 3
    assert sched.pool.outstanding() == 0


def test_completion_wins_the_deadline_race_and_excision_is_idempotent():
    """Two halves of the race discipline: a request whose deadline
    falls INSIDE its final iteration completes (the sweep checks
    completion first — a request with all its tokens is never
    expired); and after a genuine excision, cancel() on the same rid
    is a no-op returning False — no double release."""
    contended = serve.CostModel(decode_base_s=0.02,
                                decode_per_seq_s=0.01)
    mk = [serve.Request(rid=f"r{i}", prompt_len=8, output_len=16,
                        arrival_s=0.0) for i in range(4)]
    base = serve.Scheduler(_config(), cost_model=contended)
    base.submit_all([r.fresh_copy() for r in mk])
    assert base.run(max_steps=10_000) < 10_000
    finish = next(r for r in base.completed if r.rid == "r1").finish_s
    # rerun with r1's deadline strictly BEFORE its finish instant but
    # after the previous iteration — inside the final iteration window
    # (4-deep contention keeps admission's uncontended ETA well below
    # the deadline, so the request IS admitted and the race is real)
    race = serve.Scheduler(_config(), cost_model=contended)
    reqs = [r.fresh_copy() for r in mk]
    reqs[1].deadline_budget_s = finish - 0.005
    race.submit_all(reqs)
    assert race.run(max_steps=10_000) < 10_000
    b = next(r for r in race.completed if r.rid == "r1")
    assert b.finish_s > b.deadline_s      # the race was real
    assert race.deadline_exceeded_total == 0 and not race.failed
    # -- idempotence: excise by deadline, then try to cancel the corpse
    late = serve.Scheduler(_config())
    late.submit(serve.Request(rid="gone", prompt_len=8, output_len=400,
                              arrival_s=0.0, deadline_budget_s=0.05))
    assert late.run(max_steps=10_000) < 10_000
    assert late.failed[0].reject_reason == "deadline_exceeded"
    released_once = late.pool.outstanding()
    assert released_once == 0
    assert late.cancel("gone") is False   # already terminal: no-op
    assert late.pool.outstanding() == 0
    assert late.failed_total == 1 and late.rejected_total == 0


# -- the wire: per-request stream timeout + distinct failed record ------------


def _read_stream(port: int, body: dict, headers: dict = None) -> list:
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        conn.request("POST", "/v1/generate", json.dumps(body), hdrs)
        resp = conn.getresponse()
        assert resp.status == 200, resp.read()
        buf = b""
        while True:
            piece = resp.read(64)
            if not piece:
                break
            buf += piece
        return [json.loads(ln) for ln in buf.decode().splitlines()
                if ln.strip()]
    finally:
        conn.close()


def test_stream_timeout_is_deadline_derived_not_hardwired():
    """Satellite regression: the stream-timeout cap used to be a
    hardwired 30 s. With a caller deadline it must derive from the
    request's budget (plus the grace window) — a wedged scheduler
    releases the connection right after the deadline, not half a
    minute later."""
    sched = serve.Scheduler(_config())
    service = serve.DecodeService(sched)          # default 30 s cap
    port = service.start_http()                   # NO step loop: wedged
    try:
        t0 = time.monotonic()
        lines = _read_stream(port, {"prompt_len": 8, "output_len": 4},
                             headers={"x-tpu-deadline-ms": "200"})
        elapsed = time.monotonic() - t0
    finally:
        service.stop()
    assert lines == [{"error": "stream timeout"}]
    # 0.2 s budget + 0.5 s grace, generous sandbox slack — but
    # nowhere NEAR the 30 s cap the old hardwired timeout would hold
    assert elapsed < 10.0
    assert service.stream_timeout_s == 30.0       # cap still intact


def test_failed_after_admission_is_distinct_on_the_wire():
    """Satellite (b) end-to-end: a contract-breach executor failure on
    an ADMITTED request reaches the client as ``failed: ...`` — never
    as a rejection — and lands in the failed outcome counter."""
    failed_before = metrics.SERVE_REQUESTS.value(
        slo_class=serve.INTERACTIVE, outcome="failed")
    plan = chaos.FaultPlan(seed=SEED)
    plan.script("begin", chaos.Fail(
        exc=lambda: ValueError("chaos: bad spec")))
    ex = chaos.ChaosExecutor(serve.SimExecutor(), plan=plan)
    sched = serve.Scheduler(_config(), executor=ex)
    service = serve.DecodeService(sched, idle_interval_s=0.01)
    service.start()
    port = service.start_http()
    try:
        lines = _read_stream(port, {"rid": "doomed", "prompt_len": 8,
                                    "output_len": 4,
                                    "slo_class": "interactive"})
    finally:
        service.stop()
    assert lines == [{"error": "failed: executor_error"}]
    (doomed,) = [r for r in sched.failed if r.rid == "doomed"]
    assert doomed.state == serve.FAILED
    assert doomed.reject_reason == "executor_error"
    assert doomed not in sched.rejected
    assert metrics.SERVE_REQUESTS.value(
        slo_class=serve.INTERACTIVE, outcome="failed") \
        == failed_before + 1
    assert sched.pool.outstanding() == 0
