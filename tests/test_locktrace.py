"""Runtime lock-order tracing tests.

The headline case: an intentional lock-order inversion (A->B in one
code path, B->A in another) must be detected WITHOUT the run ever
deadlocking — the tracer records acquisition-order edges and finds the
cycle statically in the graph.
"""

import threading

import pytest

from dpu_operator_tpu.testing.locktrace import (LockOrderViolation,
                                                LockTracer, traced)


def test_inversion_is_detected_without_deadlocking():
    tracer = LockTracer()
    with tracer.install():
        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def ab():
            with lock_a:
                with lock_b:
                    pass

        def ba():
            with lock_b:
                with lock_a:
                    pass

        # sequential on purpose: the interleaving that would deadlock
        # never runs, yet the ordering cycle is still recorded
        t1 = threading.Thread(target=ab)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=ba)
        t2.start()
        t2.join()
    with pytest.raises(LockOrderViolation) as exc:
        tracer.assert_no_cycles()
    msg = str(exc.value)
    assert "cycle" in msg and "held while acquiring" in msg


def test_consistent_order_passes():
    with traced() as tracer:
        lock_a = threading.Lock()
        lock_b = threading.Lock()
        for _ in range(3):
            with lock_a:
                with lock_b:
                    pass
    assert tracer.find_cycles() == []
    assert tracer.edges, "nested acquires must record ordering edges"
    assert all("test_locktrace.py" in site
               for edge in tracer.edges for site in edge)


def test_same_site_instance_pair_inversion_is_detected():
    """Two instances of one class (one allocation site) locked while
    holding each other: no global order exists between them, so the
    tracer must flag the self-loop — the classic instance-pair
    deadlock (transfer(a, b) racing transfer(b, a))."""
    tracer = LockTracer()
    with tracer.install():
        class Account:
            def __init__(self):
                self.lock = threading.Lock()  # ONE site for all instances

        a, b = Account(), Account()
        with a.lock:
            with b.lock:
                pass
    with pytest.raises(LockOrderViolation):
        tracer.assert_no_cycles()
    assert any(len(c) == 1 for c in tracer.find_cycles())


def test_rlock_reentry_is_not_an_edge():
    with traced() as tracer:
        lock = threading.RLock()
        with lock:
            with lock:  # re-entry must not self-edge or confuse stacks
                pass
    assert tracer.edges == set()


def test_three_lock_cycle_is_found():
    tracer = LockTracer()
    with tracer.install():
        # distinct lines: locks aggregate by allocation site
        a = threading.Lock()
        b = threading.Lock()
        c = threading.Lock()
        locks = [a, b, c]
        for first, second in ((0, 1), (1, 2), (2, 0)):
            with locks[first]:
                with locks[second]:
                    pass
    cycles = tracer.find_cycles()
    assert len(cycles) == 1 and len(cycles[0]) == 3


def test_uninstall_restores_real_factories():
    real_lock, real_rlock = threading.Lock, threading.RLock
    with LockTracer().install():
        assert threading.Lock is not real_lock
    assert threading.Lock is real_lock
    assert threading.RLock is real_rlock


def test_condition_and_event_work_under_tracing():
    """stdlib sync primitives built on Lock/RLock keep functioning when
    the traced factories are installed (Condition duck-types acquire/
    release/_is_owned on the wrapper)."""
    with traced() as tracer:
        cond = threading.Condition()
        hits = []

        def waiter():
            with cond:
                while not hits:
                    cond.wait(timeout=5.0)

        t = threading.Thread(target=waiter)
        t.start()
        with cond:
            hits.append(1)
            cond.notify_all()
        t.join(timeout=5.0)
        assert not t.is_alive()
    assert tracer.find_cycles() == []


def test_real_component_audit_resilience_seam():
    """Audit slice: drive RetryPolicy + CircuitBreaker (the shared wire
    seam) and the metrics registry under the tracer — the lock orderings
    those components actually take must be acyclic."""
    from dpu_operator_tpu.utils import resilience

    with traced() as tracer:
        breaker = resilience.CircuitBreaker("locktrace-audit",
                                            failure_threshold=2,
                                            reset_timeout=0.01)
        policy = resilience.RetryPolicy(max_attempts=2, base=0.0, cap=0.0,
                                        sleep=lambda s: None)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] % 2:
                raise ConnectionError("boom")
            return "ok"

        results = []

        def worker():
            for _ in range(4):
                try:
                    results.append(policy.call(
                        flaky, site="locktrace-audit", breaker=breaker))
                except (ConnectionError, resilience.BreakerOpen):
                    pass

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert tracer.find_cycles() == []
