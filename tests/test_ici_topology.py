"""ICI topology model tests: slice shapes, torus wiring, bandwidth bounds."""

import pytest

from dpu_operator_tpu.ici import MultiSliceGroup, SliceTopology, slice_shape


@pytest.mark.parametrize("topo,shape", [
    ("v5e-4", (2, 2)),
    ("v5e-16", (4, 4)),
    ("v5e-256", (16, 16)),
    ("v5p-32", (2, 4, 4)),
    ("v5p-64", (4, 4, 4)),
])
def test_slice_shapes(topo, shape):
    assert slice_shape(topo) == shape


def test_v5e_16_wiring():
    s = SliceTopology("v5e-16")
    assert s.num_chips == 16
    # interior chip on a 4x4 torus has 4 outgoing links (x±, y±)
    outs = s.links_from(5)
    assert len(outs) == 4
    assert {l.port for l in outs} == {"x+", "x-", "y+", "y-"}
    # wraparound: chip at (0,0) connects to (3,0) and (0,3)
    from_corner = {(l.dst) for l in s.links_from(0)}
    coords = {s.chips[d].coords for d in from_corner}
    assert (3, 0) in coords and (0, 3) in coords


def test_v5p_32_hosts():
    s = SliceTopology("v5p-32")
    # v5p: 4 chips per host VM → 8 hosts
    assert s.num_hosts == 8
    assert len(s.chips_on_host(0)) == 4
    assert all(len(s.links_from(c.index)) > 0 for c in s.chips)


def test_extent2_dims_not_double_linked():
    s = SliceTopology("v5e-4")  # 2x2
    # each chip: one link per dimension pair, so 2 outgoing per chip
    for c in s.chips:
        assert len(s.links_from(c.index)) == 2


def test_bandwidth_models():
    s = SliceTopology("v5e-16")
    assert s.bisection_bandwidth_gbps() > 0
    # payload-aware ring bound (VERDICT r3 weak #5): large payloads
    # converge to per-link bw * n/(2(n-1)) — just over half link bw...
    big = s.allreduce_algbw_gbps(256 << 20)
    assert 25.0 < big < 55.0
    # ...small payloads are latency-bound and the bound must drop
    small = s.allreduce_algbw_gbps(64 << 10)
    assert small < big / 2
    # more payload never lowers the bound (monotone in bytes)
    assert s.allreduce_algbw_gbps(1 << 20) < big


def test_multislice_group():
    g = MultiSliceGroup([SliceTopology("v5e-16"), SliceTopology("v5e-16")])
    assert g.num_chips == 32
    assert g.dcn_allreduce_algbw_gbps() > 0


def test_ici_ports_on_host():
    s = SliceTopology("v5e-16")
    ports = s.ici_ports_on_host(0)
    # 8 chips on host 0 (v5e: 8 chips/host), 4 ports each
    assert len(ports) == 8 * 4
