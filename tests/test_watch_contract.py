"""Real-vs-Fake watch contract suite.

The informer core consumes ``list_collection`` + ``watch_from`` from
whichever client it is given; consumers can only trust FakeKube if the
fake's watch semantics match RealKube's over the real wire protocol
(MiniApiServer). The same scenarios — add/modify/delete ordering,
resourceVersion resume, relist-after-410, delete-during-disconnect,
resync — run against BOTH clients and assert identical observable
behavior.

Also carries the leader-lease acquisition-cancel regression (satellite:
a shutting-down replica contending a held lease must not hang forever).
"""

from __future__ import annotations

import threading
import time

import pytest

from dpu_operator_tpu.k8s import FakeKube, StaleResourceVersion
from dpu_operator_tpu.k8s.informer import SharedInformer

from utils import assert_eventually


@pytest.fixture(scope="module")
def wire():
    """One MiniApiServer + RealKube per module (TLS handshakes are the
    slow part); each test namespaces its objects by name prefix."""
    import os
    import sys
    import tempfile
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from apiserver_fixture import MiniApiServer

    from dpu_operator_tpu.k8s.real import RealKube
    srv = MiniApiServer().start()
    tmp = tempfile.mkdtemp(prefix="watchct-")
    kube = RealKube(kubeconfig=srv.write_kubeconfig(tmp + "/kc"))
    yield srv, kube
    kube.close()
    srv.stop()


@pytest.fixture(params=["fake", "real"])
def contract(request, wire):
    """(client, backing_store): the client under test and the FakeKube
    that IS the cluster (same object for the fake flavor; the fixture's
    backing store for the real one — outage/compaction injection always
    goes through the backing store)."""
    if request.param == "fake":
        kube = FakeKube()
        return kube, kube
    srv, kube = wire
    return kube, srv.kube


def _cm(name, data=None):
    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": "default"},
            "data": data or {}}


def _collect(client, rv, stop, events, kinds=("v1", "ConfigMap")):
    t = threading.Thread(
        target=lambda: client.watch_from(
            kinds[0], kinds[1],
            lambda e, o: events.append(
                (e, (o.get("metadata") or {}).get("name"))),
            resource_version=rv, stop=stop, timeout=5),
        daemon=True)
    t.start()
    return t


def test_list_collection_returns_resumable_version(contract):
    client, backing = contract
    backing.create(_cm("lc-a"))
    items, rv = client.list_collection("v1", "ConfigMap")
    assert any(o["metadata"]["name"] == "lc-a" for o in items)
    assert rv and int(rv) >= 1
    # events after the snapshot replay from rv — nothing missed, no
    # duplicate of the snapshot itself
    events: list = []
    stop = threading.Event()
    t = _collect(client, rv, stop, events)
    try:
        backing.create(_cm("lc-b"))
        assert_eventually(lambda: ("ADDED", "lc-b") in events)
        assert ("ADDED", "lc-a") not in events, \
            "snapshot object replayed despite resourceVersion resume"
    finally:
        stop.set()
        t.join(timeout=10)


def test_add_modify_delete_ordering(contract):
    client, backing = contract
    _, rv = client.list_collection("v1", "ConfigMap")
    events: list = []
    stop = threading.Event()
    t = _collect(client, rv, stop, events)
    try:
        backing.create(_cm("ord"))
        obj = backing.get("v1", "ConfigMap", "ord", namespace="default")
        obj["data"] = {"v": "2"}
        backing.update(obj)
        backing.delete("v1", "ConfigMap", "ord", namespace="default")
        assert_eventually(lambda: ("DELETED", "ord") in events)
        seq = [e for e, n in events if n == "ord"]
        assert seq == ["ADDED", "MODIFIED", "DELETED"], seq
    finally:
        stop.set()
        t.join(timeout=10)


def test_bookmark_carries_current_version(contract):
    client, backing = contract
    backing.create(_cm("bm"))
    _, rv = client.list_collection("v1", "ConfigMap")
    got: list = []
    stop = threading.Event()

    def on_event(e, o):
        if e == "BOOKMARK":
            got.append((o.get("metadata") or {}).get("resourceVersion"))
            stop.set()
    t = threading.Thread(
        target=lambda: client.watch_from("v1", "ConfigMap", on_event,
                                         resource_version=rv, stop=stop,
                                         timeout=10),
        daemon=True)
    t.start()
    t.join(timeout=15)
    assert got and int(got[0]) >= int(rv)


def test_compacted_resume_raises_410(contract):
    client, backing = contract
    backing.create(_cm("gone-seed"))
    _, rv = client.list_collection("v1", "ConfigMap")
    backing.create(_cm("gone-post"))
    backing.compact_history()
    with pytest.raises(StaleResourceVersion):
        client.watch_from("v1", "ConfigMap", lambda e, o: None,
                          resource_version=rv, timeout=5)


def test_delete_during_disconnect_surfaces_via_informer(contract):
    """An object deleted while no watch is connected must still reach
    consumers — either replayed from history on resume or via the 410
    relist diff. The informer is the consumer contract, so assert
    through it."""
    client, backing = contract
    backing.create(_cm("dd-stays"))
    backing.create(_cm("dd-dies"))
    inf = SharedInformer(client, "v1", "ConfigMap")
    inf.MAX_STREAM_FAILURES = 10_000
    inf.STREAM_RETRY_S = 0.02
    inf.start()
    try:
        assert inf.wait_synced(10)
        events: list = []
        inf.add_handler(
            lambda e, o: events.append((e, o["metadata"]["name"])),
            initial_sync=False)
        backing.block_watches("v1", "ConfigMap")
        backing.delete("v1", "ConfigMap", "dd-dies", namespace="default")
        backing.compact_history("v1", "ConfigMap")
        backing.unblock_watches("v1", "ConfigMap")
        assert_eventually(
            lambda: ("DELETED", "dd-dies") in events,
            message="delete-during-disconnect never surfaced")
        assert inf.store.get("dd-dies", namespace="default") is None
        assert inf.store.get("dd-stays", namespace="default") is not None
    finally:
        inf.stop()
        backing.unblock_watches("v1", "ConfigMap")


def test_resume_within_history_replays_missed_events(contract):
    """Disconnect, mutate, reconnect from the old rv while history still
    holds the events: they replay incrementally — no relist needed."""
    client, backing = contract
    _, rv = client.list_collection("v1", "ConfigMap")
    backing.create(_cm("replay-1"))
    backing.create(_cm("replay-2"))
    events: list = []
    stop = threading.Event()
    t = _collect(client, rv, stop, events)
    try:
        assert_eventually(lambda: ("ADDED", "replay-1") in events
                          and ("ADDED", "replay-2") in events)
    finally:
        stop.set()
        t.join(timeout=10)


# -- leader lease: cancellable acquisition (satellite) ------------------------

def test_lease_acquisition_cancellable_under_held_lease(wire):
    """A replica contending a NEVER-EXPIRING held lease must exit its
    acquisition loop when told to stop (previously an uncancellable
    `while not try_take(): sleep(poll)` — a shutting-down operator hung
    forever)."""
    import datetime
    srv, kube = wire
    far_future = (datetime.datetime.now(datetime.timezone.utc)
                  + datetime.timedelta(days=1)).strftime(
                      "%Y-%m-%dT%H:%M:%S.%fZ")
    srv.kube.create({
        "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
        "metadata": {"name": "held-forever", "namespace": "default"},
        "spec": {"holderIdentity": "the-holder",
                 "leaseDurationSeconds": 10_000_000,
                 "renewTime": far_future}})
    stop = threading.Event()
    result: list = []
    t = threading.Thread(
        target=lambda: result.append(kube.acquire_leader_lease(
            "held-forever", namespace="default", lease_seconds=2,
            poll=0.1, identity="contender", on_lost=lambda: None,
            stop=stop)),
        daemon=True)
    t.start()
    time.sleep(0.5)
    assert t.is_alive(), "contender should still be blocked contending"
    stop.set()
    t.join(timeout=5)
    assert not t.is_alive(), \
        "acquisition loop did not honor the stop event"
    assert result, "cancelled acquisition returned nothing"
    # the returned cancel is a no-op pre-acquisition: calling it is safe
    result[0]()
    # and the holder was never displaced
    lease = srv.kube.get("coordination.k8s.io/v1", "Lease",
                         "held-forever", namespace="default")
    assert lease["spec"]["holderIdentity"] == "the-holder"


def test_returned_cancel_is_idempotent_and_usable(wire):
    """The normal acquired path still returns a working cancel (guard
    against the stop-event refactor breaking acquisition)."""
    srv, kube = wire
    cancel = kube.acquire_leader_lease(
        "free-lease", namespace="default", lease_seconds=2, poll=0.1,
        identity="me", on_lost=lambda: None)
    lease = srv.kube.get("coordination.k8s.io/v1", "Lease",
                         "free-lease", namespace="default")
    assert lease["spec"]["holderIdentity"] == "me"
    cancel()
    cancel()
