"""RBAC enforced over the wire: config/rbac/role.yaml is validated by the
apiserver fixture evaluating ClusterRole/ClusterRoleBinding rules per bearer
token (VERDICT r2 #9 — reference: config/rbac/ is exercised implicitly by
envtest/Kind, kindcluster.go:47-64). The production controller runs under
the operator ServiceAccount's token; removing a rule from role.yaml breaks
these tests.
"""

import os

import pytest
import requests
import yaml

from dpu_operator_tpu.api import TpuOperatorConfig, TpuOperatorConfigSpec
from dpu_operator_tpu.controller import TpuOperatorConfigReconciler
from dpu_operator_tpu.images import DummyImageManager
from dpu_operator_tpu.k8s import Manager
from dpu_operator_tpu.k8s.real import RealKube
from dpu_operator_tpu.utils import DEFAULT_NAD_NAME, NAMESPACE

from apiserver_fixture import MiniApiServer
from utils import assert_eventually

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RBAC_DIR = os.path.join(REPO, "config", "rbac")

#: the operator's identity, per config/rbac/service_account.yaml
SA_SUBJECT = {"kind": "ServiceAccount",
              "name": "tpu-operator-controller-manager",
              "namespace": "tpu-operator-system"}
SA_TOKEN = "operator-sa-token"


def _rbac_objects():
    objs = []
    for fname in sorted(os.listdir(RBAC_DIR)):
        with open(os.path.join(RBAC_DIR, fname)) as f:
            objs.extend(o for o in yaml.safe_load_all(f) if o)
    return objs


@pytest.fixture
def rbac_server():
    srv = MiniApiServer()
    srv.rbac_enabled = True
    srv.token_subjects[SA_TOKEN] = SA_SUBJECT
    srv.token_subjects["intruder-token"] = {
        "kind": "ServiceAccount", "name": "intruder",
        "namespace": "default"}
    for obj in _rbac_objects():
        srv.kube.create(obj)
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture
def sa_kube(rbac_server, tmp_path):
    path = rbac_server.write_kubeconfig(str(tmp_path / "sa-kubeconfig"),
                                        token=SA_TOKEN)
    return RealKube(kubeconfig=path)


@pytest.fixture
def intruder_kube(rbac_server, tmp_path):
    path = rbac_server.write_kubeconfig(str(tmp_path / "i-kubeconfig"),
                                        token="intruder-token")
    return RealKube(kubeconfig=path)


def test_role_grants_the_operator_what_it_uses(sa_kube):
    """Spot-check each rule class the controller depends on."""
    cfg = TpuOperatorConfig(spec=TpuOperatorConfigSpec(mode="host"))
    created = sa_kube.create(cfg.to_obj())          # CR create
    created.setdefault("status", {})["observedGeneration"] = 1
    sa_kube.update_status(created)                  # status subresource
    assert sa_kube.list("v1", "Pod") == []          # core list
    sa_kube.apply({"apiVersion": "v1", "kind": "ConfigMap",
                   "metadata": {"name": "cm", "namespace": "default"},
                   "data": {}})                     # server-side apply
    sa_kube.delete("config.tpu.openshift.io/v1", "TpuOperatorConfig",
                   created["metadata"]["name"])     # delete


def test_unbound_subject_is_forbidden(intruder_kube):
    with pytest.raises(requests.HTTPError) as exc:
        intruder_kube.list("v1", "Pod")
    assert exc.value.response.status_code == 403
    with pytest.raises(requests.HTTPError) as exc:
        intruder_kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                              "metadata": {"name": "x",
                                           "namespace": "default"},
                              "data": {}})
    assert exc.value.response.status_code == 403


def test_subresource_needs_its_own_rule(rbac_server, sa_kube, tmp_path):
    """k8s semantics: a rule on "tpuoperatorconfigs" does NOT cover
    "tpuoperatorconfigs/status" — the role's explicit status rule is what
    makes update_status work. Strip it and status updates 403."""
    role = rbac_server.kube.get("rbac.authorization.k8s.io/v1",
                                "ClusterRole", "tpu-operator-manager-role")
    role["rules"] = [r for r in role["rules"]
                     if "tpuoperatorconfigs/status" not in r["resources"]]
    rbac_server.kube.update(role)
    cfg = TpuOperatorConfig(spec=TpuOperatorConfigSpec(mode="host"))
    created = sa_kube.create(cfg.to_obj())
    created.setdefault("status", {})["observedGeneration"] = 1
    with pytest.raises(requests.HTTPError) as exc:
        sa_kube.update_status(created)
    assert exc.value.response.status_code == 403


def test_controller_runs_under_role_yaml(rbac_server, sa_kube, tmp_path):
    """The production reconcile loop — watch, render, apply, status,
    leases — runs end-to-end under role.yaml's grants. Every API call the
    controller makes is thereby proven covered (the reference gets this
    implicitly from envtest + its RBAC manifests)."""
    from dpu_operator_tpu.utils.filesystem_mode_detector import (
        FilesystemModeDetector,
    )
    from dpu_operator_tpu.utils.path_manager import PathManager

    sa_kube.watch = (lambda av, k, cb, poll=0.2, _w=sa_kube.watch:
                     _w(av, k, cb, poll=0.2))
    mgr = Manager(sa_kube)
    mgr.add_reconciler(TpuOperatorConfigReconciler(
        DummyImageManager(),
        path_manager=PathManager(str(tmp_path)),
        fs_detector=FilesystemModeDetector(str(tmp_path))))
    mgr.start()
    try:
        cfg = TpuOperatorConfig(spec=TpuOperatorConfigSpec(mode="host"))
        sa_kube.create(cfg.to_obj())
        assert_eventually(
            lambda: sa_kube.get("apps/v1", "DaemonSet", "tpu-daemon",
                                namespace=NAMESPACE) is not None,
            timeout=15.0)
        assert_eventually(
            lambda: sa_kube.get("k8s.cni.cncf.io/v1",
                                "NetworkAttachmentDefinition",
                                DEFAULT_NAD_NAME,
                                namespace="default") is not None,
            timeout=15.0)
    finally:
        mgr.stop()


def test_removing_a_rule_from_role_yaml_fails_reconcile(rbac_server,
                                                        sa_kube, tmp_path):
    """The VERDICT done-criterion: strip role.yaml's NAD rule and the same
    reconcile can no longer materialize the NAD (403 over the wire), while
    rule-covered objects still land."""
    from dpu_operator_tpu.utils.filesystem_mode_detector import (
        FilesystemModeDetector,
    )
    from dpu_operator_tpu.utils.path_manager import PathManager

    role = rbac_server.kube.get("rbac.authorization.k8s.io/v1",
                                "ClusterRole", "tpu-operator-manager-role")
    role["rules"] = [
        r for r in role["rules"]
        if "network-attachment-definitions" not in r["resources"]]
    rbac_server.kube.update(role)

    sa_kube.watch = (lambda av, k, cb, poll=0.2, _w=sa_kube.watch:
                     _w(av, k, cb, poll=0.2))
    mgr = Manager(sa_kube)
    mgr.add_reconciler(TpuOperatorConfigReconciler(
        DummyImageManager(),
        path_manager=PathManager(str(tmp_path)),
        fs_detector=FilesystemModeDetector(str(tmp_path))))
    mgr.start()
    try:
        cfg = TpuOperatorConfig(spec=TpuOperatorConfigSpec(mode="host"))
        sa_kube.create(cfg.to_obj())
        # covered resources still reconcile...
        assert_eventually(
            lambda: sa_kube.get("apps/v1", "DaemonSet", "tpu-daemon",
                                namespace=NAMESPACE) is not None,
            timeout=15.0)
        # ...but the NAD is forbidden and never appears (checked through
        # the admin plane — the SA can no longer even GET NADs)
        import time
        time.sleep(1.0)
        assert rbac_server.kube.get("k8s.cni.cncf.io/v1",
                                    "NetworkAttachmentDefinition",
                                    DEFAULT_NAD_NAME,
                                    namespace="default") is None
        with pytest.raises(requests.HTTPError) as exc:
            sa_kube.get("k8s.cni.cncf.io/v1",
                        "NetworkAttachmentDefinition",
                        DEFAULT_NAD_NAME, namespace="default")
        assert exc.value.response.status_code == 403
    finally:
        mgr.stop()


def test_body_kind_cannot_bypass_url_rbac(rbac_server, sa_kube, tmp_path):
    """Privilege-escalation guard: POSTing a ClusterRoleBinding body to a
    granted resource's URL is a 400, not a stored binding."""
    import json as _json

    path = rbac_server.write_kubeconfig(str(tmp_path / "kc2"),
                                        token=SA_TOKEN)
    client = RealKube(kubeconfig=path)
    smuggled = {"apiVersion": "rbac.authorization.k8s.io/v1",
                "kind": "ClusterRoleBinding",
                "metadata": {"name": "escalate", "namespace": "default"},
                "roleRef": {"kind": "ClusterRole",
                            "name": "tpu-operator-manager-role"},
                "subjects": [{"kind": "ServiceAccount", "name": "intruder",
                              "namespace": "default"}]}
    # hand-roll the smuggle: body kind != URL resource
    r = client.session.post(client.base + "/api/v1/namespaces/default/"
                            "configmaps", data=_json.dumps(smuggled),
                            timeout=10)
    assert r.status_code == 400
    assert rbac_server.kube.get("rbac.authorization.k8s.io/v1",
                                "ClusterRoleBinding", "escalate") is None
