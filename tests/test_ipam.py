"""IPAM delegation + sandbox device-wiring records (VERDICT r1 item 2).

Reference parity: sriov.go:423-484 (IPAM ExecAdd + cache-driven DEL unwind),
networkfn.go:233-317 (optional IPAM on NF interfaces), sriov.go:75-140
(SetupVF — the per-sandbox OS wiring whose TPU analog is DeviceWiring).
"""

import threading

import pytest

from dpu_operator_tpu.cni.ipam import (
    HostLocalIpam,
    IpamError,
    StaticIpam,
    ipam_add,
    ipam_del,
)
from dpu_operator_tpu.cni.types import DeviceWiring, NetConf, PodRequest
from dpu_operator_tpu.daemon import TpuSideManager

HOST_LOCAL = {"type": "host-local", "subnet": "10.56.0.0/29",
              "gateway": "10.56.0.1"}


# -- host-local allocator ----------------------------------------------------

def test_host_local_distinct_addresses(tmp_path):
    ipam = HostLocalIpam(str(tmp_path))
    r1 = ipam.add(HOST_LOCAL, "tpunf", "sbx-a", "net1")
    r2 = ipam.add(HOST_LOCAL, "tpunf", "sbx-b", "net1")
    a1 = r1["ips"][0]["address"]
    a2 = r2["ips"][0]["address"]
    assert a1 != a2
    assert a1 == "10.56.0.2/29"  # gateway .1 skipped
    assert r1["ips"][0]["gateway"] == "10.56.0.1"


def test_host_local_idempotent_per_sandbox(tmp_path):
    ipam = HostLocalIpam(str(tmp_path))
    r1 = ipam.add(HOST_LOCAL, "tpunf", "sbx-a", "net1")
    r2 = ipam.add(HOST_LOCAL, "tpunf", "sbx-a", "net1")  # kubelet retry
    assert r1["ips"][0]["address"] == r2["ips"][0]["address"]
    # same sandbox, second interface → different address
    r3 = ipam.add(HOST_LOCAL, "tpunf", "sbx-a", "net2")
    assert r3["ips"][0]["address"] != r1["ips"][0]["address"]


def test_host_local_release_and_reuse(tmp_path):
    ipam = HostLocalIpam(str(tmp_path))
    r1 = ipam.add(HOST_LOCAL, "tpunf", "sbx-a", "net1")
    ipam.delete(HOST_LOCAL, "tpunf", "sbx-a", "net1")
    r2 = ipam.add(HOST_LOCAL, "tpunf", "sbx-c", "net1")
    assert r2["ips"][0]["address"] == r1["ips"][0]["address"]


def test_host_local_exhaustion(tmp_path):
    cfg = {"type": "host-local", "subnet": "10.56.0.0/30",
           "gateway": "10.56.0.1"}  # one usable host (.2)
    ipam = HostLocalIpam(str(tmp_path))
    ipam.add(cfg, "n", "sbx-a", "net1")
    with pytest.raises(IpamError, match="exhausted"):
        ipam.add(cfg, "n", "sbx-b", "net1")


def test_host_local_range_bounds(tmp_path):
    cfg = {"type": "host-local", "subnet": "10.0.0.0/24",
           "rangeStart": "10.0.0.10", "rangeEnd": "10.0.0.11"}
    ipam = HostLocalIpam(str(tmp_path))
    assert ipam.add(cfg, "n", "a", "i")["ips"][0]["address"] == "10.0.0.10/24"
    assert ipam.add(cfg, "n", "b", "i")["ips"][0]["address"] == "10.0.0.11/24"
    with pytest.raises(IpamError):
        ipam.add(cfg, "n", "c", "i")


def test_host_local_survives_restart(tmp_path):
    r1 = HostLocalIpam(str(tmp_path)).add(HOST_LOCAL, "n", "sbx-a", "net1")
    # a fresh allocator over the same dir (daemon restart) must not
    # re-issue the address
    r2 = HostLocalIpam(str(tmp_path)).add(HOST_LOCAL, "n", "sbx-b", "net1")
    assert r1["ips"][0]["address"] != r2["ips"][0]["address"]


def test_sandbox_teardown_releases_all(tmp_path):
    ipam = HostLocalIpam(str(tmp_path))
    ipam.add(HOST_LOCAL, "n", "sbx-a", "net1")
    ipam.add(HOST_LOCAL, "n", "sbx-a", "net2")
    keep = ipam.add(HOST_LOCAL, "n", "sbx-b", "net1")["ips"][0]["address"]
    ipam.delete(HOST_LOCAL, "n", "sbx-a", None)  # full teardown
    got = {ipam.add(HOST_LOCAL, "n", f"sbx-{i}", "net1")["ips"][0]["address"]
           for i in ("c", "d")}
    assert keep not in got and len(got) == 2


# -- static ------------------------------------------------------------------

def test_static_ipam(tmp_path):
    cfg = {"type": "static",
           "addresses": [{"address": "192.168.1.5/24",
                          "gateway": "192.168.1.1"}]}
    r = StaticIpam().add(cfg, "n", "sbx", "net1")
    assert r["ips"][0]["address"] == "192.168.1.5/24"
    with pytest.raises(IpamError):
        StaticIpam().add({"type": "static"}, "n", "sbx", "net1")


def test_dispatch_and_optional(tmp_path):
    assert ipam_add({}, str(tmp_path), "n", "s", "i") is None  # optional
    with pytest.raises(IpamError, match="unsupported"):
        ipam_add({"type": "dhcp"}, str(tmp_path), "n", "s", "i")
    ipam_del({}, str(tmp_path), "n", "s", "i")  # no-op


# -- NF pods over the CNI path (VERDICT done-criterion) ----------------------

class _QuietVsp:
    def __init__(self):
        self.wired, self.unwired = [], []

    def create_network_function(self, a, b):
        self.wired.append((a, b))

    def delete_network_function(self, a, b):
        self.unwired.append((a, b))


    def create_slice_attachment(self, att):
        return att

    def delete_slice_attachment(self, name):
        pass

def _nf_manager(tmp_path):
    mgr = TpuSideManager.__new__(TpuSideManager)
    mgr.vsp = _QuietVsp()
    mgr.client = None
    mgr._attach_store = {}
    mgr._attach_lock = threading.Lock()
    mgr._chain_store = {}
    mgr._chain_hops = {}
    from dpu_operator_tpu.cni import NetConfCache
    mgr.ipam_dir = str(tmp_path / "ipam")
    mgr.nf_cache = NetConfCache(str(tmp_path / "nf"))
    return mgr


def _nf_req(sandbox, dev, ifname="net1", command="ADD"):
    nc = NetConf(mode="network-function", name="tpunf", device_id=dev,
                 ipam=dict(HOST_LOCAL))
    return PodRequest(command=command, pod_namespace="default",
                      pod_name=f"nf-{sandbox}", sandbox_id=sandbox,
                      netns="/proc/1/ns/net", ifname=ifname, device_id=dev,
                      netconf=nc)


def test_nf_pods_get_distinct_addresses_and_del_releases(tmp_path):
    """Two NF pods receive distinct addresses from the NetConf-configured
    IPAM; DEL releases them — the verdict's done-criterion."""
    mgr = _nf_manager(tmp_path)
    sbx_a, sbx_b = "sbx-nf-a-0123456789", "sbx-nf-b-0123456789"
    r_a1 = mgr._cni_nf_add(_nf_req(sbx_a, "chip-0", "net1"))
    r_a2 = mgr._cni_nf_add(_nf_req(sbx_a, "chip-1", "net2"))
    r_b1 = mgr._cni_nf_add(_nf_req(sbx_b, "chip-2", "net1"))
    addrs = {r["ips"][0]["address"] for r in (r_a1, r_a2, r_b1)}
    assert len(addrs) == 3  # all distinct
    assert mgr.vsp.wired  # pod A's pair got wired

    # DEL pod A entirely → its two addresses return to the pool
    mgr._cni_nf_del(_nf_req(sbx_a, "", "net1", command="DEL"))
    r_c = mgr._cni_nf_add(_nf_req("sbx-nf-c-0123456789", "chip-3", "net1"))
    assert r_c["ips"][0]["address"] in {r_a1["ips"][0]["address"],
                                        r_a2["ips"][0]["address"]}


def test_nf_del_after_restart_releases_address(tmp_path):
    """DEL landing after a daemon restart (in-memory attach store lost)
    must still release the pod's addresses from the ADD-time disk cache —
    otherwise pod churn across restarts exhausts the range."""
    mgr = _nf_manager(tmp_path)
    sbx = "sbx-nf-restart-012345"
    addr = mgr._cni_nf_add(_nf_req(sbx, "chip-0"))["ips"][0]["address"]
    mgr2 = _nf_manager(tmp_path)  # same dirs, empty in-memory state
    mgr2._cni_nf_del(_nf_req(sbx, "", command="DEL"))  # full teardown
    # the address is reusable again
    got = mgr2._cni_nf_add(
        _nf_req("sbx-nf-after-0123456", "chip-1"))["ips"][0]["address"]
    assert got == addr


def test_nf_del_uses_add_time_ipam_not_del_stdin(tmp_path):
    """A NAD update between ADD and DEL must not orphan the ADD-time
    allocation: release follows the cached config, not DEL's stdin."""
    mgr = _nf_manager(tmp_path)
    sbx = "sbx-nf-nadupd-012345"
    addr = mgr._cni_nf_add(_nf_req(sbx, "chip-0"))["ips"][0]["address"]
    # DEL arrives with the NAD switched to no-IPAM
    del_req = _nf_req(sbx, "chip-0", command="DEL")
    del_req.netconf.ipam = {}
    mgr._cni_nf_del(del_req)
    got = mgr._cni_nf_add(
        _nf_req("sbx-nf-other-0123456", "chip-1"))["ips"][0]["address"]
    assert got == addr  # released despite DEL stdin lacking the config


def test_nf_add_retry_keeps_address(tmp_path):
    mgr = _nf_manager(tmp_path)
    sbx = "sbx-nf-r-0123456789"
    r1 = mgr._cni_nf_add(_nf_req(sbx, "chip-0"))
    r2 = mgr._cni_nf_add(_nf_req(sbx, "chip-0"))  # kubelet ADD retry
    assert r1["ips"][0]["address"] == r2["ips"][0]["address"]


# -- device wiring records ---------------------------------------------------

def test_device_wiring_record(tmp_path):
    dev = tmp_path / "accel3"
    dev.write_text("")
    lib = tmp_path / "libtpu.so"
    lib.write_text("")
    w = DeviceWiring.for_chip(3, dev_path=str(dev), libtpu_path=str(lib))
    assert w.dev_paths == [str(dev)]
    assert w.env == {"TPU_CHIP_INDEX": "3"}
    assert w.mounts[0]["hostPath"] == str(lib)
    assert w.mounts[0]["readOnly"] is True
    # regular file → no chardev cgroup rule claimed
    assert w.cgroup_rules == []
    rt = DeviceWiring.from_dict(w.to_dict())
    assert rt == w


def test_device_wiring_chardev_rule():
    # /dev/null is a real chardev on any test host: 1:3
    w = DeviceWiring.for_chip(0, dev_path="/dev/null")
    assert w.cgroup_rules == ["c 1:3 rwm"]


# -- exec delegation to real CNI IPAM binaries (VERDICT r4 #6) ---------------

STUB_PLUGIN = """#!/bin/sh
# stub CNI IPAM plugin: records its invocation, answers a fixed result
printf '%s ' "$CNI_COMMAND" "$CNI_CONTAINERID" "$CNI_IFNAME" \\
    "$CNI_NETNS" >> "$RECORD_FILE"
cat >> "$RECORD_FILE"
echo >> "$RECORD_FILE"
if [ "$CNI_COMMAND" = "ADD" ]; then
  echo '{"cniVersion":"0.4.0","ips":[{"version":"4",'
  echo '"address":"10.9.8.7/24","gateway":"10.9.8.1"}],'
  echo '"routes":[{"dst":"0.0.0.0/0"}],"dns":{}}'
fi
exit 0
"""


def _stub_dir(tmp_path, name="test-ipam", script=STUB_PLUGIN):
    d = tmp_path / "cni-bin"
    d.mkdir(exist_ok=True)
    p = d / name
    p.write_text(script)
    p.chmod(0o755)
    return str(d)


def test_exec_ipam_add_and_del_round_trip(tmp_path, monkeypatch):
    """An IPAM type that is neither host-local nor static delegates to
    the real plugin binary on CNI_PATH with the standard CNI contract
    (env + NetConf stdin, result stdout) — sriov.go:423-484 parity."""
    from dpu_operator_tpu.cni.ipam import ipam_add, ipam_del

    record = tmp_path / "record.txt"
    monkeypatch.setenv("CNI_PATH", _stub_dir(tmp_path))
    monkeypatch.setenv("RECORD_FILE", str(record))
    cfg = {"type": "test-ipam", "custom": "knob"}
    result = ipam_add(cfg, str(tmp_path / "data"), "mynet",
                      "sandbox-1", "net1", netns="/var/run/netns/x")
    assert result["ips"][0]["address"] == "10.9.8.7/24"
    assert result["routes"] == [{"dst": "0.0.0.0/0"}]
    ipam_del(cfg, str(tmp_path / "data"), "mynet", "sandbox-1", "net1",
             netns="/var/run/netns/x")
    lines = record.read_text().strip().splitlines()
    add_line, del_line = lines[0], lines[-1]
    assert add_line.startswith("ADD sandbox-1 net1 /var/run/netns/x")
    assert del_line.startswith("DEL sandbox-1 net1 /var/run/netns/x")
    # the NetConf on stdin carried the ipam section with custom keys
    import json as _json
    stdin_conf = _json.loads("{" + add_line.split("{", 1)[1])
    assert stdin_conf["ipam"]["type"] == "test-ipam"
    assert stdin_conf["ipam"]["custom"] == "knob"
    assert stdin_conf["name"] == "mynet"


def test_exec_ipam_plugin_failure_surfaces_cni_error(tmp_path, monkeypatch):
    from dpu_operator_tpu.cni.ipam import IpamError, ipam_add

    fail = ("#!/bin/sh\n"
            "echo '{\"code\": 11, \"msg\": \"lease pool empty\"}'\n"
            "exit 1\n")
    monkeypatch.setenv("CNI_PATH",
                       _stub_dir(tmp_path, "dhcp", script=fail))
    with pytest.raises(IpamError, match="lease pool empty"):
        ipam_add({"type": "dhcp"}, str(tmp_path / "data"), "n",
                 "sbx", "net1")


def test_builtins_stay_in_process_even_with_binary_present(tmp_path,
                                                           monkeypatch):
    """host-local/static allocation records live in the daemon's data
    dir; a host binary of the same name must NOT take over (existing
    allocations would strand)."""
    from dpu_operator_tpu.cni.ipam import ipam_add

    record = tmp_path / "record.txt"
    monkeypatch.setenv("CNI_PATH", _stub_dir(tmp_path, "host-local"))
    monkeypatch.setenv("RECORD_FILE", str(record))
    result = ipam_add({"type": "host-local", "subnet": "10.1.0.0/29"},
                      str(tmp_path / "data"), "n", "sbx", "net1")
    assert result["ips"][0]["address"].startswith("10.1.0.")
    assert not record.exists()  # the binary was never invoked


def test_unknown_type_without_binary_names_cni_path(tmp_path, monkeypatch):
    from dpu_operator_tpu.cni.ipam import IpamError, ipam_add

    monkeypatch.setenv("CNI_PATH", str(tmp_path / "empty"))
    with pytest.raises(IpamError, match="whereabouts.*CNI_PATH"):
        ipam_add({"type": "whereabouts"}, str(tmp_path / "data"), "n",
                 "sbx", "net1")


def test_plugin_type_cannot_be_a_path(tmp_path):
    """A NetConf type like '../../bin/sh' must never resolve to a
    binary — types are bare names."""
    from dpu_operator_tpu.cni.ipam import find_plugin_binary

    assert find_plugin_binary("../etc/passwd",
                              cni_path=str(tmp_path)) is None
    assert find_plugin_binary("/bin/sh", cni_path=str(tmp_path)) is None


def test_exec_ipam_non_object_json_becomes_ipam_error(tmp_path,
                                                      monkeypatch):
    """'null' / bare-string plugin output must raise IpamError (which
    ipam_del swallows defensively), never AttributeError."""
    from dpu_operator_tpu.cni.ipam import IpamError, ipam_add, ipam_del

    null_out = "#!/bin/sh\necho null\nexit 0\n"
    monkeypatch.setenv("CNI_PATH",
                       _stub_dir(tmp_path, "nuller", script=null_out))
    with pytest.raises(IpamError, match="non-object"):
        ipam_add({"type": "nuller"}, str(tmp_path / "d"), "n", "s", "i")
    # DEL path: swallowed, no exception escapes
    ipam_del({"type": "nuller"}, str(tmp_path / "d"), "n", "s", "i")

    bare = "#!/bin/sh\necho '\"pool empty\"'\nexit 1\n"
    monkeypatch.setenv("CNI_PATH",
                       _stub_dir(tmp_path, "barer", script=bare))
    with pytest.raises(IpamError, match="pool empty"):
        ipam_add({"type": "barer"}, str(tmp_path / "d"), "n", "s", "i")


def test_full_teardown_dels_each_ifname_for_exec_plugins(tmp_path,
                                                         monkeypatch):
    """A sandbox with two exec-IPAM interfaces must get one DEL per
    ifname on full teardown — plugins key leases by (containerID,
    ifname), so an empty-ifname DEL would leak both."""
    record = tmp_path / "record.txt"
    monkeypatch.setenv("CNI_PATH", _stub_dir(tmp_path))
    monkeypatch.setenv("RECORD_FILE", str(record))
    mgr = _nf_manager(tmp_path)
    ipam = {"type": "test-ipam"}
    r1 = _nf_req("sbx-exec-0123456789", "chip-0")
    r1.netconf.ipam = ipam
    mgr._cni_nf_add(r1)
    r2 = _nf_req("sbx-exec-0123456789", "chip-1", ifname="net2")
    r2.netconf.ipam = ipam
    mgr._cni_nf_add(r2)
    # full teardown (no deviceID)
    rdel = _nf_req("sbx-exec-0123456789", None, command="DEL")
    rdel.netconf.ipam = ipam
    mgr._cni_nf_del(rdel)
    dels = [l for l in record.read_text().splitlines()
            if l.startswith("DEL ")]
    assert len(dels) == 2
    assert {l.split()[2] for l in dels} == {"net1", "net2"}
