"""The docker-less image executor is itself under test (VERDICT r2 #6 /
r4 #3: `make smoke-images` must be green, must actually RUN the six
entrypoints from materialized rootfs trees, and must catch breakage)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "hack"))

import smoke_images  # noqa: E402


def test_lint_all_dockerfiles_clean():
    for df in sorted(f for f in os.listdir(REPO)
                     if f.startswith("Dockerfile.")):
        assert smoke_images.lint_dockerfile(os.path.join(REPO, df)) == [], df


def test_lint_catches_missing_copy_source(tmp_path):
    df = tmp_path / "Dockerfile.broken"
    df.write_text("FROM python:3.12-slim\n"
                  "COPY not_a_real_dir/ somewhere/\n"
                  'ENTRYPOINT ["python3", "-m", "nope"]\n')
    problems = smoke_images.lint_dockerfile(str(df))
    assert any("not_a_real_dir" in p for p in problems)


def test_lint_catches_missing_entrypoint(tmp_path):
    df = tmp_path / "Dockerfile.noentry"
    df.write_text("FROM python:3.12-slim\nCOPY pyproject.toml ./\n")
    assert any("ENTRYPOINT" in p
               for p in smoke_images.lint_dockerfile(str(df)))


def test_parse_handles_continuations_and_from_stages():
    spec = smoke_images.parse_dockerfile(
        os.path.join(REPO, "Dockerfile.cp-agent"))
    # the ENTRYPOINT spans continuation lines and must parse as JSON argv
    assert spec["entrypoint"][0] == "/usr/local/bin/tpu_cp_agent"
    assert any(fs == "build" for fs, _, _ in spec["copies"])


def test_parse_tracks_final_stage_and_workdir():
    spec = smoke_images.parse_dockerfile(
        os.path.join(REPO, "Dockerfile.daemon"))
    assert spec["workdir"] == "/opt/tpu-operator"
    # the build stage's `COPY native/ native/` must NOT land in the
    # final rootfs; the --from shim copy must
    final_dsts = [dst for _, _, dst in spec["final_copies"]]
    assert "/opt/tpu/tpu-cni" in final_dsts
    assert not any(dst == "native/" for dst in final_dsts)


def test_materialize_rootfs_applies_copy_graph(tmp_path):
    spec = smoke_images.parse_dockerfile(
        os.path.join(REPO, "Dockerfile.daemon"))
    rootfs, workdir = smoke_images.materialize_rootfs(
        str(tmp_path), "daemon", spec)
    # WORKDIR-relative package copy
    assert os.path.exists(os.path.join(
        workdir, "dpu_operator_tpu", "daemon", "tpusidemanager.py"))
    assert os.path.exists(os.path.join(workdir, "pyproject.toml"))
    # absolute-destination multi-stage copy, exec bit preserved
    shim = os.path.join(rootfs, "opt/tpu/tpu-cni")
    assert os.path.exists(shim)
    assert os.access(shim, os.X_OK)
    # the build stage's sources are NOT in the final tree
    assert not os.path.exists(os.path.join(rootfs, "src"))


def test_vsp_image_ships_its_entrypoint_agent(tmp_path):
    """Dockerfile.vsp's ENTRYPOINT names /usr/local/bin/tpu_cp_agent —
    the image must actually ship it (it didn't before round 5; the
    DaemonSet's command override masked the dangling path)."""
    spec = smoke_images.parse_dockerfile(
        os.path.join(REPO, "Dockerfile.vsp"))
    rootfs, _ = smoke_images.materialize_rootfs(
        str(tmp_path), "vsp", spec)
    assert os.path.exists(
        os.path.join(rootfs, "usr/local/bin/tpu_cp_agent"))


def test_missing_package_copy_fails_tree_install(tmp_path):
    """A Dockerfile that forgets to COPY the package must fail at the
    materialized-tree pip install, not pass silently."""
    df = tmp_path / "Dockerfile.incomplete"
    df.write_text("FROM python:3.12-slim\n"
                  "WORKDIR /opt/tpu-operator\n"
                  "COPY pyproject.toml ./\n"
                  'ENTRYPOINT ["python3", "-m", "dpu_operator_tpu"]\n')
    spec = smoke_images.parse_dockerfile(str(df))
    problems = smoke_images.execute_image(str(tmp_path), "incomplete",
                                          spec)
    assert problems, "incomplete COPY graph passed the executor"


@pytest.mark.slow
def test_all_six_entrypoints_execute_from_materialized_trees():
    """The round-5 contract (VERDICT r4 #3): every image's EXACT
    entrypoint runs functionally from a rootfs materialized out of its
    COPY graph — operator --help, daemon one detect pass (fake hardware
    root + mock VSP + fake kubelet), vsp Init through its own cp-agent,
    nri serve+mutate against the HTTPS apiserver fixture, cp-agent
    socket ping, workload --help. Session cost ~2 min (one venv per
    python image)."""
    proc = subprocess.run([sys.executable,
                           os.path.join(REPO, "hack", "smoke_images.py")],
                          capture_output=True, text=True, timeout=900,
                          cwd="/tmp")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [l for l in proc.stdout.splitlines() if l.startswith(
        "Dockerfile.")]
    assert len(lines) == 6, proc.stdout
    assert all(l.endswith(": ok") for l in lines), proc.stdout
