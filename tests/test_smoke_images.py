"""The image-entrypoint smoke harness is itself under test (VERDICT r2 #6:
`make smoke-images` must be green and must actually catch breakage)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "hack"))

import smoke_images  # noqa: E402


def test_lint_all_dockerfiles_clean():
    for df in sorted(f for f in os.listdir(REPO)
                     if f.startswith("Dockerfile.")):
        assert smoke_images.lint_dockerfile(os.path.join(REPO, df)) == [], df


def test_lint_catches_missing_copy_source(tmp_path):
    df = tmp_path / "Dockerfile.broken"
    df.write_text("FROM python:3.12-slim\n"
                  "COPY not_a_real_dir/ somewhere/\n"
                  'ENTRYPOINT ["python3", "-m", "nope"]\n')
    problems = smoke_images.lint_dockerfile(str(df))
    assert any("not_a_real_dir" in p for p in problems)


def test_lint_catches_missing_entrypoint(tmp_path):
    df = tmp_path / "Dockerfile.noentry"
    df.write_text("FROM python:3.12-slim\nCOPY pyproject.toml ./\n")
    assert any("ENTRYPOINT" in p
               for p in smoke_images.lint_dockerfile(str(df)))


def test_parse_handles_continuations_and_from_stages():
    spec = smoke_images.parse_dockerfile(
        os.path.join(REPO, "Dockerfile.cp-agent"))
    # the ENTRYPOINT spans continuation lines and must parse as JSON argv
    assert spec["entrypoint"][0] == "/usr/local/bin/tpu_cp_agent"
    assert any(fs == "build" for fs, _, _ in spec["copies"])


@pytest.mark.slow
def test_full_smoke_harness_green():
    """The real contract: every image's entrypoint runs from a clean venv.
    Session cost ~30 s (venv + pip install once)."""
    proc = subprocess.run([sys.executable,
                           os.path.join(REPO, "hack", "smoke_images.py")],
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
