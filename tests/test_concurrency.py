"""Systematic contention tests for the hand-rolled concurrency seams.

SURVEY.md §5 notes the reference has no race detector (`-race` absent)
and leans on `ginkgo --repeat 4`; our analog was `make test-repeat` plus a
few targeted races. These tests make the contention SYSTEMATIC: every
known-racy seam gets barrier-synchronized thread storms with invariant
checks, so `make test` (and test-repeat's 4x) exercises real interleavings
every run.

Seams covered: host-local IPAM allocation, NF attach/wire claims, chain
hop wiring, CNI server request handling, FakeKube store, device-plugin
allocate-vs-health.
"""

import concurrent.futures
import json
import threading

import pytest

from dpu_operator_tpu.cni import CniServer, CniShim
from dpu_operator_tpu.cni.ipam import HostLocalIpam
from dpu_operator_tpu.k8s import FakeKube


def _storm(n_threads, fn):
    """Run fn(i) on n_threads barrier-released threads; return results,
    re-raising the first exception."""
    barrier = threading.Barrier(n_threads)

    def wrapped(i):
        barrier.wait()
        return fn(i)

    with concurrent.futures.ThreadPoolExecutor(n_threads) as pool:
        futures = [pool.submit(wrapped, i) for i in range(n_threads)]
        return [f.result() for f in futures]


def test_ipam_no_double_allocation_under_storm(short_tmp):
    """32 concurrent ADDs for distinct sandboxes must get 32 distinct
    addresses (the flock around add() is what's under test)."""
    ipam = HostLocalIpam(short_tmp + "/ipam")
    cfg = {"subnet": "10.9.0.0/24"}

    def add(i):
        return ipam.add(cfg, "net", f"sbx-{i}", "net1")["ips"][0]["address"]

    addrs = _storm(32, add)
    assert len(set(addrs)) == 32


def test_ipam_same_sandbox_storm_is_idempotent(short_tmp):
    """Kubelet retries can race the same (sandbox, ifname): all callers
    must converge on ONE address, not leak several."""
    ipam = HostLocalIpam(short_tmp + "/ipam")
    cfg = {"subnet": "10.9.1.0/24"}
    addrs = _storm(16, lambda i: ipam.add(cfg, "net", "sbx", "net1")
                   ["ips"][0]["address"])
    assert len(set(addrs)) == 1
    ipam.delete(cfg, "net", "sbx", "net1")
    # released: the address is allocatable again
    again = ipam.add(cfg, "net", "sbx2", "net1")["ips"][0]["address"]
    assert again == addrs[0]


def test_ipam_add_delete_interleave(short_tmp):
    """Adds and deletes interleaving across sandboxes never corrupt the
    per-IP record files: final state equals the surviving sandboxes."""
    ipam = HostLocalIpam(short_tmp + "/ipam")
    cfg = {"subnet": "10.9.2.0/24"}
    for i in range(8):
        ipam.add(cfg, "net", f"keep-{i}", "net1")

    def churn(i):
        sbx = f"churn-{i}"
        for _ in range(5):
            ipam.add(cfg, "net", sbx, "net1")
            ipam.delete(cfg, "net", sbx, "net1")

    _storm(12, churn)
    survivors = _storm(8, lambda i: ipam.add(cfg, "net", f"keep-{i}",
                                             "net1")["ips"][0]["address"])
    assert len(set(survivors)) == 8  # idempotent re-add, no leaked churn IPs


class _CountingVsp:
    """Records wire/unwire calls; artificially slow to widen race windows."""

    def __init__(self):
        self.lock = threading.Lock()
        self.wired = []
        self.unwired = []
        self.attached = []

    def create_slice_attachment(self, req):
        import time
        time.sleep(0.002)
        with self.lock:
            self.attached.append(req.get("name", ""))
        return dict(req)

    def delete_slice_attachment(self, name):
        return {}

    def create_network_function(self, input_id, output_id):
        import time
        time.sleep(0.002)
        with self.lock:
            self.wired.append((input_id, output_id))

    def delete_network_function(self, input_id, output_id):
        with self.lock:
            self.unwired.append((input_id, output_id))

    def close(self):
        pass


def _nf_req(sandbox, device, ifname):
    from dpu_operator_tpu.cni.types import NetConf

    class Req:
        pass

    r = Req()
    r.sandbox_id = sandbox
    r.device_id = device
    r.ifname = ifname
    r.netns = "/var/run/netns/x"
    r.pod_name = "p"
    r.pod_namespace = "default"
    r.netconf = NetConf(cni_version="0.4.0", name="", mode="network-function",
                        device_id=device)
    return r


@pytest.fixture
def nf_manager(short_tmp, kube):
    from dpu_operator_tpu.daemon import TpuSideManager
    from dpu_operator_tpu.utils.path_manager import PathManager

    mgr = TpuSideManager.__new__(TpuSideManager)
    # minimal wiring for the CNI NF paths (no servers started)
    pm = PathManager(short_tmp)
    mgr.vsp = _CountingVsp()
    mgr.path_manager = pm
    mgr.client = kube
    mgr.ipam_dir = pm.cni_cache_dir() + "/ipam"
    from dpu_operator_tpu.cni import NetConfCache
    mgr.nf_cache = NetConfCache(pm.cni_cache_dir() + "/nf")
    mgr._attach_store = {}
    mgr._attach_lock = threading.Lock()
    mgr._chain_store = {}
    mgr._chain_hops = {}
    return mgr


def test_nf_wire_claim_storm_wires_exactly_once(nf_manager):
    """16 threads racing the 2nd..17th attachment of one sandbox: the NF
    must wire exactly once no matter which thread crosses the 2-attach
    threshold (the `wiring` claim flag under storm)."""
    for round_id in range(4):
        sbx = f"sandbox-{round_id:04d}"
        nf_manager._cni_nf_add(_nf_req(sbx, "chip-0", "net1"))
        before = len(nf_manager.vsp.wired)
        _storm(16, lambda i, s=sbx: nf_manager._cni_nf_add(
            _nf_req(s, f"chip-{1 + i % 3}", f"net{2 + i}")))
        assert len(nf_manager.vsp.wired) == before + 1


def test_nf_add_del_storm_never_leaves_orphan_wire(nf_manager):
    """ADD pairs racing DELs: every wire that happened is eventually
    unwired when the sandbox is torn down — no orphan dataplane state."""
    def cycle(i):
        sbx = f"cyc-{i:04d}"
        nf_manager._cni_nf_add(_nf_req(sbx, "chip-0", "net1"))
        nf_manager._cni_nf_add(_nf_req(sbx, "chip-1", "net2"))
        nf_manager._cni_nf_del(_nf_req(sbx, None, "net1"))

    _storm(16, cycle)
    wired = sorted(nf_manager.vsp.wired)
    unwired = sorted(nf_manager.vsp.unwired)
    assert wired == unwired


def test_cni_server_parallel_requests(short_tmp):
    """The unix-socket CNI server under 24 parallel shims: every request
    gets its own correct response (no cross-talk between connections)."""
    calls = []
    lock = threading.Lock()

    def add(pod_req):
        with lock:
            calls.append(pod_req.sandbox_id)
        return {"cniVersion": "0.4.0", "tpu": {"sbx": pod_req.sandbox_id}}

    sock = short_tmp + "/cni.sock"
    srv = CniServer(sock, add_handler=add)
    srv.start()
    try:
        shim = CniShim(sock)

        def invoke(i):
            resp = shim.invoke(
                {"CNI_COMMAND": "ADD", "CNI_CONTAINERID": f"sbx-{i}",
                 "CNI_NETNS": "/ns", "CNI_IFNAME": "net1",
                 "CNI_ARGS": "K8S_POD_NAMESPACE=d;K8S_POD_NAME=p"},
                json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni"}))
            assert resp.error == ""
            return resp.result["tpu"]["sbx"]

        results = _storm(24, invoke)
        assert sorted(results) == sorted(f"sbx-{i}" for i in range(24))
    finally:
        srv.stop()


def test_fake_kube_store_storm():
    """Concurrent create/update/list/delete on the store: resource
    versions stay monotonic and no write is lost."""
    kube = FakeKube()

    def work(i):
        name = f"cm-{i}"
        kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                     "metadata": {"name": name, "namespace": "default"},
                     "data": {"v": "0"}})
        for v in range(1, 6):
            obj = kube.get("v1", "ConfigMap", name, namespace="default")
            obj["data"]["v"] = str(v)
            kube.update(obj)
        return kube.get("v1", "ConfigMap", name,
                        namespace="default")["data"]["v"]

    results = _storm(16, work)
    assert results == ["5"] * 16
    assert len(kube.list("v1", "ConfigMap", namespace="default")) == 16


def test_device_plugin_allocate_vs_health_storm(short_tmp):
    """Allocate racing health flips: every response is self-consistent —
    either a full allocation of healthy devices or a clean refusal, never
    a partial/corrupt device list."""
    from dpu_operator_tpu.daemon.device_handler import TpuDeviceHandler

    state = {"healthy": True}
    lock = threading.Lock()

    class FlippyVsp:
        def set_num_chips(self, n):
            pass

        def get_devices(self):
            with lock:
                h = state["healthy"]
            return {f"chip-{i}": {"id": f"chip-{i}", "healthy": h,
                                  "dev_path": "", "coords": []}
                    for i in range(4)}

    handler = TpuDeviceHandler(FlippyVsp(), tpu_mode=True)
    handler.setup_devices()

    def flip(i):
        for _ in range(50):
            with lock:
                state["healthy"] = not state["healthy"]

    def read(i):
        views = []
        for _ in range(50):
            devs = handler.get_devices()
            views.append({d["healthy"] for d in devs.values()})
        return views

    flipper = threading.Thread(target=flip, args=(0,))
    flipper.start()
    try:
        for views in _storm(8, read):
            # each snapshot is uniform: all 4 healthy or all 4 not —
            # a torn read would show a mixed set
            assert all(len(v) == 1 for v in views)
    finally:
        flipper.join()


@pytest.fixture
def chain_manager(short_tmp, kube):
    """nf_manager plus the chain-steering state the repair/boundary/
    status paths touch."""
    from dpu_operator_tpu.cni import NetConfCache
    from dpu_operator_tpu.daemon import TpuSideManager
    from dpu_operator_tpu.utils.path_manager import PathManager

    mgr = TpuSideManager.__new__(TpuSideManager)
    pm = PathManager(short_tmp)
    mgr.vsp = _CountingVsp()
    mgr.path_manager = pm
    mgr.client = kube
    mgr.ipam_dir = pm.cni_cache_dir() + "/ipam"
    mgr.nf_cache = NetConfCache(pm.cni_cache_dir() + "/nf")
    mgr._attach_store = {}
    mgr._attach_lock = threading.Lock()
    mgr._chain_store = {}
    mgr._chain_hops = {}
    mgr._degraded_hops = set()
    mgr._repair_pass_lock = threading.Lock()
    mgr._repair_frozen = threading.Event()
    mgr.link_prober = None
    return mgr


def _annotated_nf_pod(kube, name, sfc, index):
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default",
                     "annotations": {"tpu.openshift.io/sfc": sfc,
                                     "tpu.openshift.io/sfc-index":
                                         str(index)}},
        "spec": {"containers": [{"name": "c"}]}})


def _chain_req(sandbox, device, ifname, pod, ports):
    from dpu_operator_tpu.cni.types import NetConf

    class Req:
        pass

    r = Req()
    r.sandbox_id = sandbox
    r.device_id = device
    r.ifname = ifname
    r.netns = "/var/run/netns/x"
    r.pod_name = pod
    r.pod_namespace = "default"
    r.netconf = NetConf(cni_version="0.4.0", name="",
                        mode="network-function", device_id=device)
    r.netconf.ici_ports = list(ports)
    return r


def test_chain_repair_sync_status_storm_no_orphan_wires(chain_manager,
                                                        kube):
    """Repair passes (with flickering link state), boundary-sync spec
    churn, status readers, and sandbox teardowns all racing on one
    chain: after quiescence + full teardown, every wire that ever hit
    the dataplane is also unwired (no orphan steering state) and the
    hop table is empty."""
    import random

    mgr = chain_manager
    kube.create({
        "apiVersion": "config.tpu.openshift.io/v1",
        "kind": "ServiceFunctionChain",
        "metadata": {"name": "storm", "namespace": "default"},
        "spec": {"ingress": "host0-0", "egress": "host0-1",
                 "networkFunctions": [{"name": "a", "image": "i"},
                                      {"name": "b", "image": "i"}]}})
    _annotated_nf_pod(kube, "storm-a", "storm", 0)
    _annotated_nf_pod(kube, "storm-b", "storm", 1)

    flicker = {"down": False}

    def prober(chip):
        return [{"port": "x+", "up": not flicker["down"], "wired": True,
                 "fault": flicker["down"]}]

    mgr.link_prober = prober

    def wire_chain(round_id):
        a, b = f"sA{round_id:03d}00000", f"sB{round_id:03d}00000"
        for sbx, pod, chips, ports in (
                (a, "storm-a", ("chip-0", "chip-1"),
                 ["ici-0-x+", "ici-1-x+"]),
                (b, "storm-b", ("chip-2", "chip-3"),
                 ["ici-2-x+", "ici-3-x+"])):
            mgr._cni_nf_add(_chain_req(sbx, chips[0], "net1", pod, ports))
            mgr._cni_nf_add(_chain_req(sbx, chips[1], "net2", pod, ports))
        return a, b

    for round_id in range(3):
        a, b = wire_chain(round_id)

        def op(i):
            kind = i % 4
            if kind == 0:
                flicker["down"] = bool(random.getrandbits(1))
                mgr.repair_chains()
            elif kind == 1:
                egress = "host0-1" if i % 8 < 4 else "host0-9"
                mgr.sync_chain_boundaries("default", "storm",
                                          ingress="host0-0",
                                          egress=egress, n_nfs=2)
            elif kind == 2:
                mgr.chain_status("default", "storm")
                mgr.get_chains()
            else:
                mgr.repair_chains()

        _storm(12, op)
        flicker["down"] = False
        mgr._cni_nf_del(_chain_req(a, None, "", "storm-a", []))
        mgr._cni_nf_del(_chain_req(b, None, "", "storm-b", []))
        # boundary hops referencing the departed entries drain on the
        # next sync (the reconciler's resync in production)
        mgr.sync_chain_boundaries("default", "storm", ingress="host0-0",
                                  egress="host0-1", n_nfs=2)

    assert mgr._chain_hops == {}, mgr._chain_hops
    orphans = set(mgr.vsp.wired) - set(mgr.vsp.unwired)
    assert not orphans, orphans
