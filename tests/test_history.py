"""Metrics history plane gate (`make history-check`).

The bounded in-process TSDB (utils/history.py) and the trend engine on
top of it (utils/trend.py), driven end-to-end on injected clocks with
zero wall sleeps:

- rings stay inside their hard caps under a 10k-sample storm, with
  evictions counted instead of silent;
- raw -> 10s -> 2m downsampling is EXACT on a seeded series;
- two seeded runs serialize byte-identical /debug/history snapshots;
- counter families store exact windowed rates, histogram families
  exact interpolated quantiles;
- the shared metric-direction vocabulary judges identically in
  tools/bench_trend.py and the live engine (the hoist satellite);
- a seeded chunk-backlog-growth scenario fires EXACTLY one
  TrendAnomaly (Event + kind=trend flight entry + gauge) that clears
  through hold-down hysteresis, while a steady twin fires none;
- the digest's trends block damps: verdict changes publish
  immediately, slope jitter inside the deadband rides heartbeats
  (counted apiserver writes via TelemetryFleetHarness);
- the fleet rollup reflects a node's verdict end-to-end through a
  real digest publish.

The `history` marker carries the chaos-determinism lint invariant:
no wall-clock reads, no unseeded entropy.
"""

from __future__ import annotations

import importlib.util
import json
from pathlib import Path

import pytest

from dpu_operator_tpu.k8s import events
from dpu_operator_tpu.k8s.fake import FakeKube
from dpu_operator_tpu.testing.fleet import TelemetryFleetHarness
from dpu_operator_tpu.utils import flight, history, metrics, trend
from dpu_operator_tpu.utils.metric_direction import direction

pytestmark = pytest.mark.history


class Clock:
    def __init__(self, now: float = 0.0) -> None:
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


@pytest.fixture(autouse=True)
def _reset_event_seam():
    events.flush()  # drain any stragglers before stealing the seam
    events.reset()
    yield
    events.flush()  # don't let this test's emissions leak forward
    events.reset()


def _sampled_history(clock: Clock, **kw) -> history.MetricsHistory:
    return history.MetricsHistory(clock=clock, **kw)


# -- bounded rings ------------------------------------------------------------

def test_rings_bounded_under_10k_sample_storm():
    clock = Clock()
    h = _sampled_history(clock)
    value = [0.0]
    h.register_gauge("g", lambda: value[0])
    for i in range(10_000):
        clock.advance(1.0)
        value[0] = float(i)
        h.sample_once()
    series = h.snapshot()["series"]["g"]
    assert len(series["raw"]) == history.RAW_CAPACITY
    assert len(series["10s"]) == history.MID_CAPACITY
    assert len(series["2m"]) <= history.COARSE_CAPACITY
    # hard entry bound across every ring of every series
    assert h.total_points() <= (history.RAW_CAPACITY
                                + history.MID_CAPACITY
                                + history.COARSE_CAPACITY)
    # the overflow was counted, never silent: 10k raw appends into a
    # 300-cap ring evicted exactly 10k - 300 raw points (plus the
    # flushed 10s buckets past the mid cap)
    assert h.evicted_ring >= 10_000 - history.RAW_CAPACITY
    assert h.samples == 10_000
    # the newest point survived, the oldest was evicted (ring, not
    # reservoir): raw holds exactly the last 300 samples
    assert series["raw"][-1][1] == 9999.0
    assert series["raw"][0][1] == float(10_000 - history.RAW_CAPACITY)


def test_series_cap_refuses_new_label_sets():
    clock = Clock()
    h = _sampled_history(clock, max_series=8)
    h.register_gauge("fam", lambda: {f"k{i:03d}": float(i)
                                     for i in range(50)})
    clock.advance(1.0)
    h.sample_once()
    assert len(h.series_names()) == 8
    assert h.refused_series == 42
    # the cap holds under repetition — refusals keep counting, the
    # series table never grows
    clock.advance(1.0)
    h.sample_once()
    assert len(h.series_names()) == 8
    assert h.refused_series == 84


# -- downsampling -------------------------------------------------------------

def test_downsampling_exact_on_seeded_series():
    clock = Clock()
    h = _sampled_history(clock)
    value = [0.0]
    h.register_gauge("g", lambda: value[0])
    # samples at t=1..130: value t*2 except a spike at t=7
    for t in range(1, 131):
        clock.advance(1.0)
        value[0] = 999.0 if t == 7 else float(t * 2)
        h.sample_once()
    mid = h.points("g", "10s")
    # first 10s bucket covers t=1..9 (bucket floor(t/10)=0), flushed
    # when t=10 arrives; timestamp is the bucket END
    assert mid[0] == (10.0, 18.0, 2.0, 999.0, 9)
    # second bucket t=10..19: last=38, min=20, max=38, n=10
    assert mid[1] == (20.0, 38.0, 20.0, 38.0, 10)
    # the 2m ring: mid buckets 0..11 (t=1..119) cascade into coarse
    # bucket 0, flushed when mid bucket 12 closes; the t=7 spike
    # SURVIVES the double downsample in max
    coarse = h.points("g", "2m")
    assert coarse[0] == (120.0, 238.0, 2.0, 999.0, 119)


def test_counter_stored_as_exact_windowed_rate():
    clock = Clock()
    h = _sampled_history(clock)
    total = [0.0]
    h.register_counter("c_total", lambda: total[0])
    rates = []
    for inc in (10.0, 10.0, 30.0, 0.0):
        clock.advance(2.0)
        total[0] += inc
        h.sample_once()
    # first sight establishes the reference (no window yet): 4 samples
    # store 3 rates, each delta/dt exactly
    assert h.values("c_total") == [5.0, 15.0, 0.0]
    # a counter reset (restart) clamps to zero instead of going
    # negative
    clock.advance(2.0)
    total[0] = 1.0
    h.sample_once()
    assert h.values("c_total")[-1] == 0.0


def test_histogram_stored_as_exact_quantile_snapshots():
    clock = Clock()
    hist = metrics.Histogram("test_history_quantiles_seconds", "d",
                             buckets=(0.1, 0.5, 1.0, 5.0))
    h = _sampled_history(clock)
    h.register_histogram("lat", hist)
    clock.advance(1.0)
    h.sample_once()  # reference snapshot
    for v in [0.05] * 10 + [0.3] * 80 + [0.7] * 10:
        hist.observe(v)
    clock.advance(2.0)
    h.sample_once()
    # 100 obs in the window: p50 interpolates inside (0.1, 0.5]
    # (10 below + 80 in-bucket -> 0.1 + 0.4*(50-10)/80), p95 inside
    # (0.5, 1.0], rate = 100 obs / 2 s
    assert h.values("lat.p50") == [pytest.approx(0.3)]
    assert h.values("lat.p95") == [pytest.approx(0.75)]
    assert h.values("lat.rate") == [pytest.approx(50.0)]
    # idle window: quantiles carry forward (a gap would read as a
    # drop), rate reads 0
    clock.advance(2.0)
    h.sample_once()
    assert h.values("lat.p50")[-1] == pytest.approx(0.3)
    assert h.values("lat.rate")[-1] == 0.0


# -- snapshot determinism -----------------------------------------------------

def test_two_seeded_runs_serialize_byte_identical_snapshots():
    def run() -> str:
        clock = Clock()
        h = _sampled_history(clock)
        value = [1.0]
        total = [0.0]
        h.register_gauge("g", lambda: {"a": value[0],
                                       "b": value[0] * 3.1})
        h.register_counter("c_total", lambda: total[0])
        for i in range(400):
            clock.advance(1.0)
            value[0] += 0.377
            total[0] += float(i % 7)
            h.sample_once()
        return json.dumps(h.snapshot(), sort_keys=True)

    assert run() == run()


# -- direction parity (the bench_trend hoist satellite) -----------------------

def _bench_trend():
    path = Path(__file__).resolve().parent.parent / "tools" \
        / "bench_trend.py"
    spec = importlib.util.spec_from_file_location("bench_trend", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_trend_and_live_engine_share_direction_judgment():
    """The satellite's pin: both consumers of the hoisted vocabulary
    judge the SAME names identically — bench_trend through its import,
    the live engine through its watch() default."""
    bench = _bench_trend()
    names = [
        "serve.tokens_per_s", "serve.ttft_p99_s", "serve.itl_p50_s",
        "spec.acceptance_rate", "decode.improvement", "mfu",
        "kv.leaked_blocks", "prefill.chunk_backlog_tokens",
        "scheduler.preemptions", "cow.copies", "retraces",
        "steps.completed", "cache.hits", "per_s", "unknown.thing",
        "tpu_serve_ttft_seconds.p95", "tpu_serve_spec_acceptance_rate",
    ]
    clock = Clock()
    eng = trend.TrendEngine(_sampled_history(clock))
    for name in names:
        assert bench.direction(name) == direction(name), name
        eng.watch(name)  # default direction = the shared vocabulary
        assert eng._watched[name] == direction(name), name
    # the overrides exist precisely where the name-based judgment
    # would lie: the bare burn-rate family carries the higher-better
    # token "rate", so serving watches pin the whole prefix to -1
    assert direction("tpu_slo_burn_rate") == 1
    assert dict(trend.SERVING_WATCH_PREFIXES)["tpu_slo_burn_rate."] \
        == -1


# -- trend hysteresis: the chunk-backlog scenario -----------------------------

_POLICY = trend.TrendPolicy(escalate_after=3, recover_after=4,
                            hold_down_base_s=30.0,
                            hold_down_max_s=240.0,
                            flap_window_s=120.0)


def _backlog_rig(kube: FakeKube, series: str):
    events.configure(events.EventRecorder(kube, "tpu-daemon"),
                     events.node_reference("worker-0"))
    clock = Clock()
    h = _sampled_history(clock)
    value = [1000.0]
    h.register_gauge(series, lambda: value[0])
    eng = trend.TrendEngine(h, policy=_POLICY)
    eng.watch(series, -1)  # growth is pressure
    return clock, h, value, eng


def _step(clock, h, eng, value, factor: float) -> list:
    clock.advance(1.0)
    value[0] *= factor
    h.sample_once()
    return eng.evaluate_once()


def test_backlog_growth_fires_exactly_one_anomaly_then_clears(kube):
    series = "tpu_serve_prefill_chunk_backlog_tokens"
    clock, h, value, eng = _backlog_rig(kube, series)
    label = metrics.bounded_label(series)
    flight_before = len(flight.RECORDER.events("trend"))

    # 20%/s growth: the verdict goes bad, the hysteresis gate fires
    # ONCE; five more seconds of the same growth fire nothing more
    transitions = []
    fired_at = None
    for _ in range(30):
        out = _step(clock, h, eng, value, 1.2)
        transitions += out
        if out:
            fired_at = clock.now  # the hold-down anchors HERE
            break
    assert fired_at is not None, "anomaly never fired on a 20%/s ramp"
    for _ in range(5):
        transitions += _step(clock, h, eng, value, 1.2)
    assert [t["transition"] for t in transitions] == ["anomaly"]
    assert eng.anomalies() == [series]
    assert metrics.TREND_ANOMALY.value(series=label) == 1.0

    events.flush()
    stored = [e for e in kube.list("v1", "Event")
              if e["reason"] == "TrendAnomaly"]
    assert len(stored) == 1
    assert stored[0]["type"] == "Warning"
    assert series in stored[0]["message"]
    trend_flight = flight.RECORDER.events("trend")[flight_before:]
    assert [e["name"] for e in trend_flight] == ["TrendAnomaly"]
    assert trend_flight[0]["attributes"]["series"] == series

    # plateau: goods during the 30s hold-down (anchored at the FIRE
    # time, mid-ramp) are ignored outright — the series must stay
    # anomalous through the whole hold-down even though the slope
    # reads steady well before it expires
    while clock.now < fired_at + _POLICY.hold_down_base_s:
        transitions += _step(clock, h, eng, value, 1.0)
        assert eng.anomalies() == [series]
    # past the hold-down: recover_after consecutive goods clear it
    for _ in range(_POLICY.recover_after):
        transitions += _step(clock, h, eng, value, 1.0)
    assert eng.anomalies() == []
    assert metrics.TREND_ANOMALY.value(series=label) == 0.0
    assert [t["transition"] for t in transitions] \
        == ["anomaly", "cleared"]
    events.flush()
    cleared = [e for e in kube.list("v1", "Event")
               if e["reason"] == "TrendCleared"]
    assert len(cleared) == 1 and cleared[0]["type"] == "Normal"


def test_steady_twin_fires_no_anomaly(kube):
    series = "tpu_serve_prefill_chunk_backlog_tokens"
    clock, h, value, eng = _backlog_rig(kube, series)
    flight_before = len(flight.RECORDER.events("trend"))
    transitions = []
    for _ in range(80):
        transitions += _step(clock, h, eng, value, 1.0)
    assert transitions == []
    assert eng.anomalies() == []
    events.flush()
    assert [e for e in kube.list("v1", "Event")
            if e["reason"] in ("TrendAnomaly", "TrendCleared")] == []
    assert flight.RECORDER.events("trend")[flight_before:] == []
    verdict = eng.state()["series"][series]["verdict"]
    assert verdict == "steady"


def test_flap_doubles_the_hold_down():
    clock = Clock()
    h = _sampled_history(clock)
    value = [1000.0]
    series = "kv.used"
    h.register_gauge(series, lambda: value[0])
    eng = trend.TrendEngine(h, policy=_POLICY)
    eng.watch(series, -1)

    def until_anomaly(limit: int = 100) -> None:
        for _ in range(limit):
            if any(t["transition"] == "anomaly"
                   for t in _step(clock, h, eng, value, 1.2)):
                return
        raise AssertionError("anomaly never fired")

    def until_cleared(limit: int = 1000) -> float:
        start = clock.now
        for _ in range(limit):
            if any(t["transition"] == "cleared"
                   for t in _step(clock, h, eng, value, 1.0)):
                return clock.now - start
        raise AssertionError("never cleared")

    until_anomaly()
    first_recovery = until_cleared()
    # re-anomaly inside the flap window: the hold-down doubles, so the
    # second recovery takes measurably longer than the first
    until_anomaly()
    second_recovery = until_cleared()
    assert second_recovery > first_recovery + _POLICY.hold_down_base_s / 2


def test_unknown_direction_drifts_but_never_alarms():
    clock = Clock()
    h = _sampled_history(clock)
    value = [100.0]
    h.register_gauge("mystery.dial", lambda: value[0])
    eng = trend.TrendEngine(h, policy=_POLICY)
    eng.watch("mystery.dial")  # no token matches -> direction 0
    transitions = []
    for _ in range(60):
        transitions += _step(clock, h, eng, value, 1.3)
    assert transitions == []
    assert eng.state()["series"]["mystery.dial"]["verdict"] \
        == "drifting"


# -- /debug/history over the wire + tpuctl ------------------------------------

def test_debug_history_serves_snapshot_and_trend_state():
    from dpu_operator_tpu import tpuctl

    clock = Clock()
    h = _sampled_history(clock)
    value = [10.0]
    h.register_gauge("tpu_serve_prefill_chunk_backlog_tokens",
                     lambda: value[0])
    eng = trend.TrendEngine(h, policy=_POLICY)
    eng.watch("tpu_serve_prefill_chunk_backlog_tokens", -1)
    for _ in range(20):
        clock.advance(1.0)
        value[0] *= 1.1
        h.sample_once()
        eng.evaluate_once()
    snap = h.snapshot()
    snap["trend"] = eng.state()

    listing = tpuctl.render_history(snap)
    row = listing["series"]["tpu_serve_prefill_chunk_backlog_tokens"]
    assert row["kind"] == "gauge"
    assert row["points"]["raw"] == 20
    assert row["verdict"] in ("drifting", "anomaly")

    view = tpuctl.render_history(
        snap, family="tpu_serve_prefill_chunk_backlog_tokens")
    srow = view["series"]["tpu_serve_prefill_chunk_backlog_tokens"]
    assert len(srow["sparkline"]) == 20
    assert set(srow["sparkline"]) <= set(tpuctl._BLOCKS)
    assert srow["sparkline"][-1] == tpuctl._BLOCKS[-1]  # rising ramp
    assert srow["trend"] == "▲"
    assert srow["last"] > srow["min"]


def test_tpuctl_trend_arrows_graceful_on_old_snapshots():
    from dpu_operator_tpu import tpuctl

    # an old operator rollup without a trends block renders steady
    # arrows, never an error
    old = tpuctl.render_fleet_top({"nodes": {"total": 1, "fresh": 1,
                                             "stale": 0}})
    assert old["trendArrows"] == {"chunkBacklog": "steady",
                                  "burnRate": "steady"}
    new = tpuctl.render_fleet_top({
        "nodes": {}, "trends": {"chunkBacklogSlope": 0.4,
                                "burnRateSlope": -0.2}})
    assert new["trendArrows"] == {"chunkBacklog": "▲",
                                  "burnRate": "▼"}

    # serve top: rising backlog window -> ▲; an old/short ledger reads
    # steady
    entries = [{"chunkBacklogTokens": 100 + 80 * i, "activeSlots": 4,
                "queuedRequests": 0, "phases": {}} for i in range(8)]
    top = tpuctl.render_serve_top({}, {"entries": entries})
    assert top["trendArrows"]["chunkBacklog"] == "▲"
    assert top["trendArrows"]["activeSlots"] == "steady"
    empty = tpuctl.render_serve_top({}, {})
    assert empty["trendArrows"]["chunkBacklog"] == "steady"


# -- digest damping of the trends block ---------------------------------------

def _trend_block(verdict: str, slope: float, anomalous: bool) -> dict:
    name = "tpu_serve_prefill_chunk_backlog_tokens"
    return {"anomalies": [name] if anomalous else [],
            "series": {name: {"verdict": verdict,
                              "slope": round(slope, 4)}}}


def test_trends_block_damps_jitter_and_publishes_verdict_changes():
    """The satellite's damping contract, against counted apiserver
    writes: the block appearing and a VERDICT change are material
    (publish immediately); slope jitter inside the 0.05 deadband rides
    heartbeats."""
    h = TelemetryFleetHarness(n_nodes=2)
    src, pub = h.sources[0], h.publishers[0]
    h.tick_all()  # first publish always lands
    base = h.status_writes()

    # the trends section appearing is a new dimension: material
    src.trends = _trend_block("steady", 0.01, False)
    h.advance(6.0)
    assert pub.tick() is True
    assert h.status_writes() == base + 1

    # slope jitter inside the deadband: immaterial, no write
    src.trends = _trend_block("steady", 0.03, False)
    h.advance(6.0)
    assert pub.tick() is False
    assert h.status_writes() == base + 1

    # ... but it rides the next heartbeat
    h.advance(31.0)
    assert pub.tick() is True
    assert h.status_writes() == base + 2

    # a verdict change is material on ANY change: immediate publish
    src.trends = _trend_block("anomaly", 0.2, True)
    h.advance(6.0)
    assert pub.tick() is True
    assert h.status_writes() == base + 3
    # the published digest carries the block verbatim
    digest = pub.build_digest()
    assert digest["trends"]["anomalies"] \
        == ["tpu_serve_prefill_chunk_backlog_tokens"]


# -- fleet rollup end-to-end --------------------------------------------------

def test_fleet_rollup_reflects_node_verdict_through_real_publish():
    h = TelemetryFleetHarness(n_nodes=3)
    h.start()
    try:
        name = "tpu_serve_prefill_chunk_backlog_tokens"
        h.sources[0].trends = _trend_block("anomaly", 0.3, True)
        h.sources[1].trends = _trend_block("steady", 0.1, False)
        # node 2 publishes no trends block (an old daemon): it must
        # neither crash the rollup nor count as reporting
        h.tick_all()
        assert h.wait_idle()
        roll = h.aggregator.rollup()
        trends = roll["trends"]
        assert trends["nodesReporting"] == 2
        assert trends["anomalies"] == {name: 1}
        assert trends["chunkBacklogSlope"] == pytest.approx(0.2)
        assert roll["perNode"]["node-0000"]["trendAnomalies"] == [name]
        assert roll["perNode"]["node-0002"]["trendAnomalies"] == []
        with h.aggregator._lock:
            h.aggregator._export_locked()
        label = metrics.bounded_label(name)
        assert metrics.FLEET_TREND_ANOMALIES.value(series=label) == 1.0
        assert metrics.FLEET_TREND_BACKLOG_SLOPE.value() \
            == pytest.approx(0.2)

        # the node recovers: the census entry zeroes instead of going
        # stale forever (zero-on-vanish, like every fleet gauge)
        h.sources[0].trends = _trend_block("steady", 0.0, False)
        h.advance(6.0)
        h.tick_all()
        assert h.wait_idle()
        assert h.aggregator.rollup()["trends"]["anomalies"] == {}
        with h.aggregator._lock:
            h.aggregator._export_locked()
        assert metrics.FLEET_TREND_ANOMALIES.value(series=label) == 0.0
    finally:
        h.stop()


# -- fixtures -----------------------------------------------------------------

@pytest.fixture
def kube():
    return FakeKube()
