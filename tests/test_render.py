"""Render engine tests (reference analog: render.go behaviors — lexical
ordering, missingkey=error, owner refs, apply tolerance)."""

import os

import pytest

from dpu_operator_tpu.k8s import FakeKube
from dpu_operator_tpu.render import (
    RenderError,
    apply_all_from_bindata,
    render_dir,
    render_template,
)


def test_render_template_substitutes():
    assert render_template("name: {{Name}}-x", {"Name": "a"}) == "name: a-x"


def test_render_template_missing_key_errors():
    with pytest.raises(RenderError, match="Nope"):
        render_template("{{Nope}}", {})


def test_render_dir_lexical_order(tmp_path):
    (tmp_path / "02.b.yaml").write_text(
        "apiVersion: v1\nkind: ConfigMap\nmetadata:\n  name: b\n")
    (tmp_path / "01.a.yaml").write_text(
        "apiVersion: v1\nkind: ConfigMap\nmetadata:\n  name: a\n")
    objs = render_dir(str(tmp_path), {})
    assert [o["metadata"]["name"] for o in objs] == ["a", "b"]


def test_apply_all_sets_owner_refs(tmp_path):
    (tmp_path / "01.cm.yaml").write_text(
        "apiVersion: v1\nkind: ConfigMap\nmetadata:\n  name: {{Name}}\n"
        "  namespace: default\n")
    kube = FakeKube()
    owner = kube.create({
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": "owner", "namespace": "default"}})
    applied = apply_all_from_bindata(kube, str(tmp_path), {"Name": "child"},
                                     owner=owner)
    assert applied[0]["metadata"]["ownerReferences"][0]["name"] == "owner"
    # apply twice tolerated (AlreadyExists parity, render.go:84-92)
    apply_all_from_bindata(kube, str(tmp_path), {"Name": "child"}, owner=owner)


def test_owner_gc_cascades(tmp_path):
    (tmp_path / "01.cm.yaml").write_text(
        "apiVersion: v1\nkind: ConfigMap\nmetadata:\n  name: child\n"
        "  namespace: default\n")
    kube = FakeKube()
    owner = kube.create({
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": "owner", "namespace": "default"}})
    apply_all_from_bindata(kube, str(tmp_path), {}, owner=owner)
    assert kube.get("v1", "ConfigMap", "child", namespace="default")
    kube.delete("v1", "ConfigMap", "owner", namespace="default")
    assert kube.get("v1", "ConfigMap", "child", namespace="default") is None
