"""opslint v4 tests: the JAX trace-discipline pass.

Per-rule pass/fail fixtures for retrace-hazard, host-sync-discipline,
donation-discipline and dtype-discipline, plus the PR's satellites:
the live-tree donation regression (the three decode kernels must keep
their donate_argnums), SARIF codeFlows for interprocedural witnesses,
the ``--changed-only`` content-hash cache (byte-identical + strictly
faster), and the tightened 19-rule wall-time bound. Fixtures build
Modules directly, mirroring test_opslint_v3.py.
"""

import ast
import json
import os
import subprocess
import sys
import textwrap
import time

from dpu_operator_tpu.analysis import (ALL_CHECKERS,
                                       BlockingUnderLockChecker,
                                       DonationDisciplineChecker,
                                       DtypeDisciplineChecker,
                                       HostSyncDisciplineChecker,
                                       RetraceHazardChecker)
from dpu_operator_tpu.analysis.__main__ import _sarif_doc
from dpu_operator_tpu.analysis.core import (FileCache, Module,
                                            analysis_stamp,
                                            load_modules,
                                            pragma_inventory,
                                            run_checkers_on)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DECODE = "dpu_operator_tpu/workloads/decode.py"
SERVE = "dpu_operator_tpu/workloads/serve.py"
OPS = "dpu_operator_tpu/ops/quant.py"


def check_many(checker, sources):
    modules = [Module("/x/" + rel, rel, textwrap.dedent(src))
               for rel, src in sources.items()]
    by_rel = {m.relpath: m for m in modules}
    project = getattr(checker, "check_project", None)
    found = project(modules) if project is not None \
        else (v for m in modules for v in checker.check(m))
    return [v for v in found
            if not by_rel[v.path].suppressed(v.rule, v.line)]


def check(checker, source, relpath=DECODE):
    return check_many(checker, {relpath: source})


# -- donation-discipline ------------------------------------------------------

def test_donation_flags_undonated_cache_param():
    violations = check(DonationDisciplineChecker(), """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def decode(params, cfg, cache, x):
            return cache, x
    """)
    assert [v.rule for v in violations] == ["donation-discipline"]
    assert "`cache` (arg 2)" in violations[0].message
    assert "donate_argnums=(2,)" in violations[0].message


def test_donation_passes_with_donate_argnums():
    violations = check(DonationDisciplineChecker(), """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",),
                 donate_argnums=(2,))
        def decode(params, cfg, cache, x):
            return cache, x
    """)
    assert violations == []


def test_donation_sees_wrapper_form_jit():
    violations = check(DonationDisciplineChecker(), """
        import jax

        def make_step():
            def step(opt_state, grads):
                return opt_state, grads
            return jax.jit(step)
    """)
    assert [v.rule for v in violations] == ["donation-discipline"]
    assert "`opt_state`" in violations[0].message

    clean = check(DonationDisciplineChecker(), """
        import jax

        def make_step():
            def step(opt_state, grads):
                return opt_state, grads
            return jax.jit(step, donate_argnums=(0,))
    """)
    assert clean == []


def test_donation_ignores_params_and_static_buffers():
    """Weights are reused across calls (donating them is a bug) and a
    static `cache` name is not a device buffer."""
    violations = check(DonationDisciplineChecker(), """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cache",))
        def f(params, cache, x):
            return x
    """)
    assert violations == []


def test_live_decode_kernels_declare_donation():
    """Regression for the PR's audit fix: the three cache-threading
    decode kernels keep their donate_argnums — dropping one silently
    doubles KV-cache HBM."""
    with open(os.path.join(REPO, DECODE)) as fh:
        tree = ast.parse(fh.read())
    donating = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        for dec in node.decorator_list:
            for sub in ast.walk(dec):
                if isinstance(sub, ast.keyword) \
                        and sub.arg == "donate_argnums":
                    donating.add(node.name)
    assert {"decode_step", "verify_step",
            "prefill_chunk"} <= donating


# -- host-sync-discipline -----------------------------------------------------

HOT = """
    import jax.numpy as jnp
    import numpy as np

    class BatchScheduler:
        def step(self):
            return self._drain()

        def _drain(self):
            logits = jnp.ones((4,))
            return logits.item(){pragma}
"""


def test_host_sync_flags_item_reachable_from_scheduler_step():
    violations = check(HostSyncDisciplineChecker(),
                       HOT.format(pragma=""), relpath=SERVE)
    assert [v.rule for v in violations] == ["host-sync-discipline"]
    assert ".item()" in violations[0].message
    assert "BatchScheduler.step" in violations[0].message
    # the witness chain is structured: entry point first
    assert violations[0].chain
    assert violations[0].chain[0][2].endswith("BatchScheduler.step")


def test_host_sync_pragma_suppresses():
    violations = check(
        HostSyncDisciplineChecker(),
        HOT.format(pragma="  # opslint: disable=host-sync-discipline"),
        relpath=SERVE)
    assert violations == []


def test_host_sync_ignores_off_path_and_host_values():
    violations = check(HostSyncDisciplineChecker(), """
        import jax.numpy as jnp

        class Helper:
            def probe(self):
                return jnp.ones(()).item()

        class BatchScheduler:
            def step(self, row):
                return int(row["count"])
    """, relpath=SERVE)
    assert violations == []


def test_host_sync_flags_coercion_on_device_value_in_executor():
    violations = check(HostSyncDisciplineChecker(), """
        import jax.numpy as jnp
        import numpy as np

        class SlotExecutor:
            def begin(self, logits):
                return np.asarray(jnp.argmax(logits))
    """, relpath=SERVE)
    assert len(violations) == 1
    assert "np.asarray" in violations[0].message


# -- retrace-hazard -----------------------------------------------------------

def test_retrace_flags_python_branch_on_traced_value():
    violations = check(RetraceHazardChecker(), """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def decode(x, cfg):
            if x > 0:
                return x
            return -x
    """)
    assert [v.rule for v in violations] == ["retrace-hazard"]
    assert "`x`" in violations[0].message
    assert "decode" in violations[0].message


def test_retrace_shape_and_structure_queries_are_static():
    violations = check(RetraceHazardChecker(), """
        import jax
        from functools import partial

        def _is_q(w):
            return isinstance(w, dict) and "q" in w

        @partial(jax.jit, static_argnames=("cfg",))
        def decode(x, w, cfg):
            if x.shape[0] > 4:
                x = x[:4]
            if _is_q(w):
                x = x * w["scale"]
            if "k_q" in w:
                x = x + 1
            return x
    """)
    assert violations == []


def test_retrace_propagates_tracedness_through_helpers():
    violations = check(RetraceHazardChecker(), """
        import jax
        from functools import partial

        def _inner(y):
            if y > 0:
                return y
            return -y

        @partial(jax.jit, static_argnames=("cfg",))
        def decode(x, cfg):
            return _inner(x * 2)
    """)
    assert len(violations) == 1
    assert "`y`" in violations[0].message
    assert "_inner" in violations[0].message


def test_retrace_flags_unhashable_static_at_call_site():
    violations = check(RetraceHazardChecker(), """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def decode(params, cfg, x):
            return x

        def run(params, x):
            return decode(params, [1, 2, 3], x)
    """)
    assert len(violations) == 1
    assert "unhashable list" in violations[0].message
    assert "`cfg`" in violations[0].message


def test_retrace_flags_per_call_varying_shape_at_call_site():
    violations = check(RetraceHazardChecker(), """
        import jax
        import jax.numpy as jnp
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def decode(params, cfg, x):
            return x

        def run(params, cfg, n):
            return decode(params, cfg, jnp.zeros((n, 4)))
    """)
    assert len(violations) == 1
    assert "caller parameter `n`" in violations[0].message


def test_retrace_fixed_capacity_shapes_pass():
    violations = check(RetraceHazardChecker(), """
        import jax
        import jax.numpy as jnp
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def decode(params, cfg, x):
            return x

        def run(params, cfg):
            return decode(params, cfg,
                          jnp.zeros((cfg.chunk_capacity, cfg.d_model)))
    """)
    assert violations == []


def test_retrace_flags_len_shape_at_call_site():
    violations = check(RetraceHazardChecker(), """
        import jax
        import jax.numpy as jnp
        from functools import partial

        @partial(jax.jit, static_argnames=("cfg",))
        def decode(params, cfg, x):
            return x

        def run(params, cfg, batch):
            return decode(params, cfg,
                          jnp.zeros((len(batch), 4)))
    """)
    assert len(violations) == 1
    assert "len(...)" in violations[0].message


# -- dtype-discipline ---------------------------------------------------------

def test_dtype_flags_float64_in_workloads():
    violations = check(DtypeDisciplineChecker(), """
        import jax.numpy as jnp

        def kernel(x):
            return x.astype(jnp.float64)
    """)
    assert [v.rule for v in violations] == ["dtype-discipline"]
    assert "float64" in violations[0].message


def test_dtype_flags_dtypeless_float_literal_array():
    violations = check(DtypeDisciplineChecker(), """
        import jax.numpy as jnp

        SCALES = jnp.array([1.0, 0.5])
    """)
    assert len(violations) == 1
    assert "dtype-less" in violations[0].message

    clean = check(DtypeDisciplineChecker(), """
        import jax.numpy as jnp

        SCALES = jnp.array([1.0, 0.5], dtype=jnp.float32)
        IDS = jnp.array([1, 2])
    """)
    assert clean == []


def test_dtype_quantized_dot_general_needs_preferred_element_type():
    violations = check(DtypeDisciplineChecker(), """
        from jax import lax

        def matmul(wq, x, dims):
            return lax.dot_general(wq, x, dims)
    """, relpath=OPS)
    assert len(violations) == 1
    assert "preferred_element_type" in violations[0].message

    clean = check(DtypeDisciplineChecker(), """
        import jax.numpy as jnp
        from jax import lax

        def matmul(wq, x, dims):
            return lax.dot_general(
                wq, x, dims, preferred_element_type=jnp.float32)

        def plain(w, x, dims):
            return lax.dot_general(w, x, dims)
    """, relpath=OPS)
    assert clean == []


def test_dtype_rule_scoped_to_kernel_dirs():
    violations = check(DtypeDisciplineChecker(), """
        import numpy as np

        THRESH = np.float64(1.5)
    """, relpath="dpu_operator_tpu/telemetry/rollup.py")
    assert violations == []


# -- SARIF codeFlows ----------------------------------------------------------

def test_sarif_emits_code_flows_for_witness_chains():
    violations = check_many(BlockingUnderLockChecker(), {SERVE: """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self.queue = None

            def tick(self):
                with self._lock:
                    self._drain()

            def _drain(self):
                return self.queue.get()
    """})
    assert len(violations) == 1
    assert violations[0].chain, "witness chain must be structured"
    doc = _sarif_doc(violations, [], [BlockingUnderLockChecker()])
    results = doc["runs"][0]["results"]
    flows = results[0]["codeFlows"]
    locations = flows[0]["threadFlows"][0]["locations"]
    # every chain frame plus the finding itself, entry first
    assert len(locations) == len(violations[0].chain) + 1
    assert locations[0]["location"]["message"]["text"].startswith("via ")
    last = locations[-1]["location"]
    assert last["physicalLocation"]["region"]["startLine"] \
        == violations[0].line


def test_sarif_code_flows_cover_host_sync_findings():
    violations = check(HostSyncDisciplineChecker(),
                       HOT.format(pragma=""), relpath=SERVE)
    doc = _sarif_doc(violations, [], [HostSyncDisciplineChecker()])
    assert "codeFlows" in doc["runs"][0]["results"][0]


def test_sarif_results_without_chain_have_no_code_flows():
    violations = check(DtypeDisciplineChecker(), """
        import jax.numpy as jnp
        X = jnp.array([1.0])
    """)
    doc = _sarif_doc(violations, [], [DtypeDisciplineChecker()])
    assert "codeFlows" not in doc["runs"][0]["results"][0]


# -- --changed-only cache -----------------------------------------------------

def _run_lint(cache_path):
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "dpu_operator_tpu.analysis",
         "--changed-only", "--cache", str(cache_path),
         "--format", "json"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return proc.stdout, elapsed


def test_changed_only_is_byte_identical_and_faster(tmp_path):
    cache = tmp_path / "opslint-cache.json"
    cold_out, cold_s = _run_lint(cache)
    assert cache.exists(), "first run must persist the cache"
    warm_out, warm_s = _run_lint(cache)
    assert warm_out == cold_out, "cached findings must be identical"
    assert warm_s < cold_s, (
        f"cached re-run must be strictly faster: "
        f"warm {warm_s:.2f}s vs cold {cold_s:.2f}s")


def test_file_cache_replays_only_unchanged_files(tmp_path):
    src_a = textwrap.dedent("""
        import jax.numpy as jnp
        X = jnp.array([1.0])
    """)
    src_b = "Y = 2\n"
    stamp = analysis_stamp(["dtype-discipline"])
    path = tmp_path / "c.json"

    cache = FileCache(str(path), stamp)
    mods = [Module("/x/" + DECODE, DECODE, src_a),
            Module("/x/" + SERVE, SERVE, src_b)]
    first = run_checkers_on([DtypeDisciplineChecker()], mods,
                            cache=cache)
    assert cache.misses == 2 and cache.hits == 0
    assert [v.rule for v in first] == ["dtype-discipline"]
    cache.write()

    # one file edited: only that one is re-scanned, findings replay
    cache2 = FileCache(str(path), stamp)
    mods2 = [Module("/x/" + DECODE, DECODE, src_a),
             Module("/x/" + SERVE, SERVE, src_b + "Z = 3\n")]
    second = run_checkers_on([DtypeDisciplineChecker()], mods2,
                             cache=cache2)
    assert cache2.hits == 1 and cache2.misses == 1
    assert [(v.path, v.line, v.rule, v.message) for v in second] \
        == [(v.path, v.line, v.rule, v.message) for v in first]


def test_file_cache_invalidated_by_rule_set_change(tmp_path):
    path = tmp_path / "c.json"
    cache = FileCache(str(path), analysis_stamp(["a"]))
    cache.store(Module("/x/" + SERVE, SERVE, "X = 1\n"), [])
    cache.write()
    reloaded = FileCache(str(path), analysis_stamp(["a", "b"]))
    assert reloaded.files == {}, "stamp change must drop every entry"


# -- lint gate: 19 rules, bounded wall time, inventory ------------------------

def test_lint_gate_19_rules_under_8_seconds():
    """The tightened bound the v4 pass must respect: the whole-tree
    gate (19 rules, ONE index build, four trace rules sharing one
    model) stays interactive. Best-of-two, because the tripwire is for
    algorithmic blowup (a second index build roughly doubles EVERY
    run) — a single subprocess timing on a loaded box jitters by
    seconds and must not fail the gate on scheduler noise."""
    best = float("inf")
    for _ in range(2):
        t0 = time.monotonic()
        proc = subprocess.run(
            [sys.executable, "-m", "dpu_operator_tpu.analysis"],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        elapsed = time.monotonic() - t0
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "(19 rules)" in proc.stdout
        best = min(best, elapsed)
        if best < 8.0:
            break
    assert best < 8.0, f"lint gate took {best:.1f}s (best of two)"


def test_v4_rules_registered_and_live_tree_green():
    names = {cls.name for cls in ALL_CHECKERS}
    assert {"retrace-hazard", "host-sync-discipline",
            "donation-discipline", "dtype-discipline"} <= names
    assert len(ALL_CHECKERS) == 19


def test_live_tree_pragma_inventory_has_commit_syncs():
    """The executor's per-iteration commit syncs are the justified
    exceptions host-sync-discipline is defined around: they must stay
    visible in the pragma inventory, not silently absorbed."""
    modules = load_modules(["dpu_operator_tpu"], REPO)
    inventory = pragma_inventory(modules)
    assert inventory.get("host-sync-discipline", 0) >= 1
