"""Platform detection tests.

Reference analog: detection scenarios from daemon_test.go:47 (fake product
name → DPU mode) and netsec-accelerator.go:36-75 (host-side PCI scan with
serial dedup; ambiguity errors, vendordetector.go:82-85).
"""

import pytest

from dpu_operator_tpu.platform import (
    DetectorManager,
    FakePlatform,
    FakeVendorDetector,
    PciDevice,
    TpuDetector,
)


def _tpu_pci(addr="0000:00:04.0", dev="0062", serial="GTPU01", vf=False):
    return PciDevice(address=addr, vendor_id="1ae0", device_id=dev,
                     serial=serial, is_vf=vf)


def test_tpu_platform_detected_via_accelerator_type():
    p = FakePlatform(accelerator_type="v5litepod-4")
    res = DetectorManager([TpuDetector()]).detect(p)
    assert res is not None and res.tpu_mode
    assert res.identifier == "v5litepod-4"


def test_tpu_platform_detected_via_accel_devices():
    p = FakePlatform(accel=["/dev/accel0", "/dev/accel1"])
    res = DetectorManager([TpuDetector()]).detect(p)
    assert res.tpu_mode


def test_host_side_detected_via_pci():
    p = FakePlatform(pci=[_tpu_pci()])
    res = DetectorManager([TpuDetector()]).detect(p)
    assert res is not None and not res.tpu_mode
    assert res.identifier == "GTPU01"


def test_host_side_dedups_by_serial():
    # dual-function device shares a serial → one identifier
    p = FakePlatform(pci=[_tpu_pci(addr="0000:00:04.0"),
                          _tpu_pci(addr="0000:00:05.0")])
    res = DetectorManager([TpuDetector()]).detect(p)
    assert res.identifier == "GTPU01"


def test_vfs_ignored():
    p = FakePlatform(pci=[_tpu_pci(vf=True)])
    assert DetectorManager([TpuDetector()]).detect(p) is None


def test_non_google_vendor_ignored():
    p = FakePlatform(pci=[PciDevice(address="0000:00:04.0",
                                    vendor_id="8086", device_id="0062")])
    assert DetectorManager([TpuDetector()]).detect(p) is None


def test_nothing_detected_returns_none():
    assert DetectorManager([TpuDetector()]).detect(FakePlatform()) is None


def test_ambiguous_platform_is_error():
    p = FakePlatform(product="tpu-sim", accelerator_type="v5litepod-4")
    mgr = DetectorManager([TpuDetector(), FakeVendorDetector()])
    with pytest.raises(RuntimeError, match="ambiguous"):
        mgr.detect(p)


def test_fake_detector_product_match():
    p = FakePlatform(product="tpu-sim v5e")
    res = DetectorManager([FakeVendorDetector()]).detect(p)
    assert res.tpu_mode and res.vendor == "fake-tpu"


def test_hardware_platform_reads_dsn_serial(tmp_path):
    """Config-space serial read at DSN_OFFSET (reference: platform.go:46-77
    reads the PCIe Device Serial Number capability at 0x150)."""
    from dpu_operator_tpu.platform.platform import HardwarePlatform

    dev = tmp_path / "sys/bus/pci/devices/0000:5e:00.0"
    dev.mkdir(parents=True)
    cfg = bytearray(0x150)
    cfg[0:2] = b"\xe0\x1a"  # vendor 0x1ae0, little-endian
    cfg += b"\x03\x00\x01\x00"              # DSN capability header
    cfg += bytes([0x77, 0x66, 0x55, 0x44, 0x33, 0x22, 0x11, 0x00])
    (dev / "config").write_bytes(bytes(cfg))

    hw = HardwarePlatform(root=str(tmp_path))
    assert (hw.read_device_serial("0000:5e:00.0")
            == "00-11-22-33-44-55-66-77")
    assert hw.device_alive("0000:5e:00.0") is True


def test_hardware_platform_serial_missing_and_dead_device(tmp_path):
    from dpu_operator_tpu.platform.platform import HardwarePlatform

    dev = tmp_path / "sys/bus/pci/devices/0000:5e:00.0"
    dev.mkdir(parents=True)
    # truncated config space (what non-root readers of some devices see)
    (dev / "config").write_bytes(b"\xe0\x1a" + b"\x00" * 62)
    hw = HardwarePlatform(root=str(tmp_path))
    assert hw.read_device_serial("0000:5e:00.0") == ""
    assert hw.device_alive("0000:5e:00.0") is True

    # surprise-removed endpoint: vendor reads 0xffff
    (dev / "config").write_bytes(b"\xff\xff" + b"\xff" * 62)
    assert hw.device_alive("0000:5e:00.0") is False
    # all-ones DSN region must not fabricate a serial
    (dev / "config").write_bytes(b"\xff" * 0x160)
    assert hw.read_device_serial("0000:5e:00.0") == ""

    assert hw.device_alive("0000:missing") is False
    assert hw.read_device_serial("0000:missing") == ""
