"""Zero-downtime daemon upgrade: live state handoff (make upgrade-check).

The acceptance bar (ISSUE 6): a full daemon->daemon handoff under the
chaos harness shows ZERO pod sandbox re-setups, ZERO chain re-steers and
ZERO spurious kubelet device deletions; the kill-9-mid-transfer case
recovers via `.last-good` with a HandoffFallback flight entry and a
Degraded-then-Healthy transition; an incompatible bundle schema is
rejected (outgoing thaws, incoming cold-starts); and a CNI DEL arriving
during the frozen window is queued and applied exactly once after
adoption. Everything is seeded/deterministic — no wall-clock sleeps
beyond bounded waits on explicit events.
"""

import json
import os
import socket
import threading

import pytest

from dpu_operator_tpu.cni import ChipAllocator, CniServer, NetConfCache
from dpu_operator_tpu.cni.types import CniRequest
from dpu_operator_tpu.daemon import TpuSideManager, handoff
from dpu_operator_tpu.testing.chaos import ChaosVsp, Fail, FaultPlan
from dpu_operator_tpu.utils import flight
from dpu_operator_tpu.utils.path_manager import PathManager

from utils import assert_eventually

pytestmark = pytest.mark.upgrade


# -- shared-dataplane VSP stub ------------------------------------------------
# The real VSP is a separate long-lived process: it (and its programmed
# wires/attachments) outlives the daemon across a handoff. Two stub
# instances over one _Dataplane model exactly that.

class _Dataplane:
    def __init__(self):
        self.wires = []        # programmed NF wire pairs, in order
        self.attachments = {}


class _UpgradeVsp:
    def __init__(self, dataplane, chips=4):
        self.dp = dataplane
        self.chips = chips
        self.created = []      # create_network_function calls BY THIS daemon
        self.deleted = []
        self.attach_calls = []
        self.detach_calls = []

    def get_devices(self):
        return {f"chip-{i}": {"id": f"chip-{i}", "healthy": True,
                              "dev_path": f"/dev/accel{i}",
                              "coords": [i % 2, i // 2, 0]}
                for i in range(self.chips)}

    def set_num_chips(self, count):
        pass

    def create_slice_attachment(self, att):
        self.attach_calls.append(att["name"])
        self.dp.attachments[att["name"]] = att
        return att

    def delete_slice_attachment(self, name):
        self.detach_calls.append(name)
        self.dp.attachments.pop(name, None)

    def create_network_function(self, a, b):
        self.created.append((a, b))
        if (a, b) not in self.dp.wires:
            self.dp.wires.append((a, b))

    def delete_network_function(self, a, b):
        self.deleted.append((a, b))
        if (a, b) in self.dp.wires:
            self.dp.wires.remove((a, b))

    def list_network_functions(self):
        return list(self.dp.wires)


class _Req:
    def __init__(self, sandbox, device, ifname, pod, ns="default"):
        self.sandbox_id = sandbox
        self.device_id = device
        self.ifname = ifname
        self.pod_name = pod
        self.pod_namespace = ns
        self.netns = f"/var/run/netns/{sandbox}"

        class _NC:
            cni_version = "0.4.0"
            name = ""
            ipam = {}
            ici_ports = []
        self.netconf = _NC()


def _nf_pod(kube, name, sfc, index):
    kube.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default",
                     "annotations": {"tpu.openshift.io/sfc": sfc,
                                     "tpu.openshift.io/sfc-index":
                                         str(index)}},
        "spec": {"containers": [{"name": "c"}]},
    })


def _manager(root, vsp, client=None):
    mgr = TpuSideManager(vsp, PathManager(root), client=client)
    mgr.device_handler.setup_devices()
    return mgr


def _del_request(sandbox):
    return CniRequest(
        env={"CNI_COMMAND": "DEL", "CNI_CONTAINERID": sandbox,
             "CNI_NETNS": f"/var/run/netns/{sandbox}", "CNI_IFNAME": "",
             "CNI_ARGS": "K8S_POD_NAMESPACE=default;K8S_POD_NAME=p"},
        config={"cniVersion": "0.4.0", "type": "tpu-cni",
                "mode": "network-function"})


@pytest.fixture(autouse=True)
def _reset_handoff_status():
    handoff.STATUS.reset()
    yield
    handoff.STATUS.reset()


# -- frame protocol -----------------------------------------------------------

def _framed_pair():
    return socket.socketpair()


def test_frame_roundtrip():
    a, b = _framed_pair()
    payload = {"schema": handoff.SCHEMA_VERSION, "x": [1, 2, 3],
               "nested": {"y": "z"}}
    size = handoff.send_frame(a, payload)
    got, got_size = handoff.recv_frame(b)
    assert got == payload and got_size == size
    a.close(); b.close()


def test_frame_truncated_mid_body_is_frame_error():
    a, b = _framed_pair()
    # send a frame, then chop the stream after the header + checksum:
    # the reader must see FrameError (kill -9 mid-transfer), never a
    # partial json or a hang
    body = json.dumps({"big": "x" * 500}).encode()
    import hashlib
    import struct
    header = struct.pack("!4sHI", b"TPUH", handoff.SCHEMA_VERSION,
                         len(body))
    a.sendall(header + hashlib.sha256(body).digest() + body[: len(body) // 2])
    a.close()
    with pytest.raises(handoff.FrameError):
        handoff.recv_frame(b)
    b.close()


def test_frame_checksum_mismatch_is_frame_error():
    a, b = _framed_pair()
    body = b'{"k": "v"}'
    import struct
    header = struct.pack("!4sHI", b"TPUH", handoff.SCHEMA_VERSION,
                         len(body))
    a.sendall(header + b"\x00" * 32 + body)
    with pytest.raises(handoff.FrameError, match="checksum"):
        handoff.recv_frame(b)
    a.close(); b.close()


def test_frame_schema_bump_is_schema_mismatch():
    a, b = _framed_pair()
    handoff.send_frame(a, {"schema": 99},
                       version=handoff.SCHEMA_VERSION + 1)
    with pytest.raises(handoff.SchemaMismatch):
        handoff.recv_frame(b)
    a.close(); b.close()


# -- THE acceptance test: full live handoff under chaos -----------------------

def test_full_handoff_zero_resteer_zero_resetup(kube, short_tmp):
    dataplane = _Dataplane()
    vsp_a = _UpgradeVsp(dataplane)
    outgoing = _manager(short_tmp, vsp_a, client=kube)
    # two NF pods of one chain, each wired from two chip attachments —
    # the dataplane state an upgrade must carry over untouched
    _nf_pod(kube, "my-sfc-nf-a", "my-sfc", 0)
    _nf_pod(kube, "my-sfc-nf-b", "my-sfc", 1)
    outgoing._cni_nf_add(_Req("sandboxAAAA", "chip-0", "net1",
                              "my-sfc-nf-a"))
    outgoing._cni_nf_add(_Req("sandboxAAAA", "chip-1", "net2",
                              "my-sfc-nf-a"))
    outgoing._cni_nf_add(_Req("sandboxBBBB", "chip-2", "net1",
                              "my-sfc-nf-b"))
    outgoing._cni_nf_add(_Req("sandboxBBBB", "chip-3", "net2",
                              "my-sfc-nf-b"))
    assert len(outgoing._chain_hops) == 1  # hop NF0 -> NF1 steered
    wires_before = list(dataplane.wires)
    assert len(wires_before) == 3  # 2 pod-internal NFs + 1 chain hop
    snap_before = outgoing.device_plugin._snapshot()
    assert set(snap_before) == {"chip-0", "chip-1", "chip-2", "chip-3"}
    deletes_before_freeze = len(vsp_a.deleted)

    # outgoing side serves the handoff in the background (what SIGUSR2
    # / tpuctl handoff begin trigger)
    sock_path = outgoing.path_manager.handoff_socket()
    result = {}
    serve = threading.Thread(
        target=lambda: result.setdefault(
            "serve", handoff.serve_handoff(outgoing, sock_path,
                                           timeout=60.0)),
        daemon=True)
    serve.start()
    assert_eventually(lambda: outgoing.cni_server.frozen
                      and os.path.exists(sock_path),
                      message="freeze window never opened")

    # a CNI DEL lands DURING the frozen window: it must queue, then be
    # applied exactly once by the incoming daemon after adoption
    del_response = {}
    del_thread = threading.Thread(
        target=lambda: del_response.setdefault(
            "resp", outgoing.cni_server._handle(
                _del_request("sandboxBBBB"))),
        daemon=True)
    del_thread.start()
    assert_eventually(lambda: len(outgoing.cni_server.frozen_requests())
                      == 1, message="DEL was not queued by the freeze")

    # incoming daemon: same state dirs, same (still-running) dataplane,
    # wrapped in the chaos harness so ANY re-setup/re-steer attempt —
    # create_slice_attachment or create_network_function — fails loudly
    vsp_b_inner = _UpgradeVsp(dataplane)
    plan = FaultPlan(seed=7)
    plan.script("create_network_function", Fail(times=64))
    plan.script("create_slice_attachment", Fail(times=64))
    vsp_b = ChaosVsp(vsp_b_inner, plan=plan)
    incoming = _manager(short_tmp, vsp_b, client=kube)
    assert handoff.adopt_into(incoming, sock_path)
    serve.join(timeout=10)
    del_thread.join(timeout=10)
    assert result.get("serve") == "served"

    # ZERO chain re-steers / pod sandbox re-setups: the incoming daemon
    # made no create calls at all (the chaos scripts would have thrown)
    assert vsp_b_inner.created == []
    assert vsp_b_inner.attach_calls == []
    # the outgoing daemon mutated nothing after the freeze either
    assert len(vsp_a.deleted) == deletes_before_freeze

    # the queued DEL was applied EXACTLY ONCE, by the incoming daemon:
    # sandboxB's NF pair + the chain hop unwired there and only there
    assert del_response["resp"].error == ""
    assert del_response["resp"].result is not None
    pair_b = ("nf-sandboxBBBB-chip-2", "nf-sandboxBBBB-chip-3")
    assert vsp_b_inner.deleted.count(pair_b) == 1
    assert pair_b not in dataplane.wires
    assert "sandboxBBBB" not in incoming._attach_store
    # sandbox A carried over live: still wired, never re-set-up
    assert incoming._attach_store["sandboxAAAA"]["wired"] is True
    assert ("nf-sandboxAAAA-chip-0",
            "nf-sandboxAAAA-chip-1") in dataplane.wires

    # ZERO spurious kubelet device deletions: the adopted snapshot is
    # what ListAndWatch serves, even while the live handler cannot
    # answer yet (chaos: VSP not ready on the incoming side)
    assert incoming.device_plugin.snapshot_devices().keys() \
        == snap_before.keys()
    plan.script("get_devices", Fail(times=4))
    served = incoming.device_plugin._snapshot()
    assert set(served) == set(snap_before)

    # the freeze is fully released on the outgoing side
    assert not outgoing.cni_server.frozen
    # flight recorder: one served + one adopted entry for this handoff
    names = [e["name"] for e in flight.RECORDER.events(kind="handoff")]
    assert "HandoffServed" in names and "HandoffAdopted" in names
    adopted_entry = [e for e in flight.RECORDER.events(kind="handoff")
                     if e["name"] == "HandoffAdopted"][-1]
    assert adopted_entry["attributes"]["adopted_hops"] == 1
    assert adopted_entry["attributes"]["pending_applied"] == 1
    assert adopted_entry["attributes"]["discrepancies"] == 0
    # both roles share this process's STATUS here: serving -> adopted
    # -> served (order of the last two depends on thread scheduling)
    assert set(handoff.STATUS.history[-2:]) == {"adopted", "served"}


# -- kill -9 mid-transfer: .last-good fallback --------------------------------

def test_kill9_mid_transfer_falls_back_to_last_good(kube, short_tmp):
    from dpu_operator_tpu.testing.chaos import truncate_file
    dataplane = _Dataplane()
    vsp_a = _UpgradeVsp(dataplane)
    first = _manager(short_tmp, vsp_a, client=kube)
    _nf_pod(kube, "my-sfc-nf-a", "my-sfc", 0)
    _nf_pod(kube, "my-sfc-nf-b", "my-sfc", 1)
    first._cni_nf_add(_Req("sandboxAAAA", "chip-0", "net1", "my-sfc-nf-a"))
    first._cni_nf_add(_Req("sandboxAAAA", "chip-1", "net2", "my-sfc-nf-a"))
    first._cni_nf_add(_Req("sandboxBBBB", "chip-2", "net1", "my-sfc-nf-b"))
    first._cni_nf_add(_Req("sandboxBBBB", "chip-3", "net2", "my-sfc-nf-b"))
    hops_before = dict(first._chain_hops)
    assert hops_before
    journal = first._chains_file
    # one more flush so .last-good (always one snapshot behind) holds
    # the fully-wired state the crash must be recoverable to
    with first._attach_lock:
        first._save_chains_locked()
    first._flush_chains()
    assert os.path.exists(journal + ".last-good")
    # the crash leaves the primary torn mid-write (seeded truncation)
    truncate_file(journal, seed=3)

    # the outgoing daemon was killed -9 mid-transfer: its handoff
    # socket exists and accepts, but the stream dies after half a frame
    sock_path = first.path_manager.handoff_socket()
    os.makedirs(os.path.dirname(sock_path), exist_ok=True)
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(sock_path)
    listener.listen(1)

    def _die_mid_frame():
        conn, _ = listener.accept()
        import hashlib
        import struct
        body = json.dumps({"schema": handoff.SCHEMA_VERSION,
                           "chains": {}}).encode()
        header = struct.pack("!4sHI", b"TPUH", handoff.SCHEMA_VERSION,
                             len(body))
        conn.sendall(header + hashlib.sha256(body).digest()
                     + body[: len(body) // 2])
        conn.close()  # kill -9: the rest never arrives

    killer = threading.Thread(target=_die_mid_frame, daemon=True)
    killer.start()

    incoming = _manager(short_tmp, _UpgradeVsp(dataplane), client=kube)
    fallback_baseline = len(flight.RECORDER.events(kind="handoff"))
    adopted = handoff.adopt_into(incoming, sock_path)
    killer.join(timeout=5)
    listener.close()
    assert not adopted

    # HandoffFallback flight entry with the truncation reason
    entries = flight.RECORDER.events(kind="handoff")[fallback_baseline:]
    assert [e["name"] for e in entries] == ["HandoffFallback"]
    assert "truncated" in entries[0]["attributes"]["reason"]

    # DEGRADED until the cold-start recovery completes...
    assert incoming.degraded_sites() == [
        f"handoff: {entries[0]['attributes']['reason']}"]
    # ...the .last-good journal recovery rebuilds the wire table...
    incoming._recover_chains()
    assert incoming._chain_hops == hops_before
    assert incoming._attach_store["sandboxAAAA"]["wired"] is True
    # ...then HEALTHY again: the Degraded-then-Healthy transition
    handoff.STATUS.mark_recovered()
    assert incoming.degraded_sites() == []
    assert handoff.STATUS.history == ["fallback", "recovered"]


# -- schema rejection ---------------------------------------------------------

def test_incoming_rejects_bumped_schema_and_cold_starts(kube, short_tmp):
    sock_path = os.path.join(short_tmp, "handoff.sock")
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(sock_path)
    listener.listen(1)
    reject = {}

    def _future_daemon():
        conn, _ = listener.accept()
        handoff.send_frame(conn, {"schema": handoff.SCHEMA_VERSION + 1},
                           version=handoff.SCHEMA_VERSION + 1)
        try:
            # the reject must arrive framed in THIS daemon's (v2)
            # dialect — a v1-framed reply would be unparseable to the
            # very peer whose version mismatched
            reject["frame"], _ = handoff.recv_frame(
                conn, expect_version=handoff.SCHEMA_VERSION + 1)
        finally:
            conn.close()

    server = threading.Thread(target=_future_daemon, daemon=True)
    server.start()
    incoming = _manager(short_tmp, _UpgradeVsp(_Dataplane()), client=kube)
    assert not handoff.adopt_into(incoming, sock_path)
    server.join(timeout=5)
    listener.close()
    # the incoming daemon told the outgoing one WHY (so it can thaw
    # immediately instead of waiting out its timeout)
    assert reject["frame"]["adopted"] is False
    assert "schema" in reject["frame"]["reason"]
    assert handoff.STATUS.degraded_components()
    assert handoff.STATUS.history == ["fallback"]


def test_outgoing_thaws_on_reject_and_dispatches_queued_del(kube,
                                                            short_tmp):
    dataplane = _Dataplane()
    vsp = _UpgradeVsp(dataplane)
    outgoing = _manager(short_tmp, vsp, client=kube)
    outgoing._cni_nf_add(_Req("sandboxCCCC", "chip-0", "net1", "p"))
    outgoing._cni_nf_add(_Req("sandboxCCCC", "chip-1", "net2", "p"))
    sock_path = outgoing.path_manager.handoff_socket()
    result = {}
    serve = threading.Thread(
        target=lambda: result.setdefault(
            "serve", handoff.serve_handoff(outgoing, sock_path,
                                           timeout=60.0)),
        daemon=True)
    serve.start()
    assert_eventually(lambda: outgoing.cni_server.frozen
                      and os.path.exists(sock_path),
                      message="freeze window never opened")
    del_response = {}
    del_thread = threading.Thread(
        target=lambda: del_response.setdefault(
            "resp", outgoing.cni_server._handle(
                _del_request("sandboxCCCC"))),
        daemon=True)
    del_thread.start()
    assert_eventually(lambda: len(outgoing.cni_server.frozen_requests())
                      == 1, message="DEL was not queued")

    # an incoming daemon that cannot adopt (schema from the future)
    client = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    client.connect(sock_path)
    bundle, _ = handoff.recv_frame(client)
    assert bundle["schema"] == handoff.SCHEMA_VERSION
    assert len(bundle["pending_cni"]) == 1
    handoff.send_frame(client, {"adopted": False,
                                "reason": "schema v2 only"})
    client.close()
    serve.join(timeout=10)
    del_thread.join(timeout=10)
    assert result.get("serve") == "aborted"
    # degraded, never wedged: the outgoing daemon thawed and applied
    # the queued DEL itself — exactly once, locally
    assert not outgoing.cni_server.frozen
    assert del_response["resp"].error == ""
    assert "sandboxCCCC" not in outgoing._attach_store
    assert vsp.deleted.count(("nf-sandboxCCCC-chip-0",
                              "nf-sandboxCCCC-chip-1")) == 1


def test_serve_handoff_times_out_and_thaws(kube, short_tmp):
    outgoing = _manager(short_tmp, _UpgradeVsp(_Dataplane()), client=kube)
    sock_path = outgoing.path_manager.handoff_socket()
    assert handoff.serve_handoff(outgoing, sock_path,
                                 timeout=0.2) == "aborted"
    assert not outgoing.cni_server.frozen
    assert not os.path.exists(sock_path)
    # the abort entry is stamped so `tpuctl handoff status` can scope
    # adoption discrepancies to the attempt that produced them
    aborted = [e for e in flight.RECORDER.events(kind="handoff")
               if e.get("name") == "HandoffAborted"]
    assert aborted and aborted[-1]["attributes"].get("handoff_id")


def test_serve_aborts_when_drain_never_completes(short_tmp):
    """A mutation that outlives every drain window must ABORT the
    handoff (thaw, keep serving) — serializing the bundle mid-mutation
    would hand over a wire table missing that mutation's effects, a
    hop neither generation tracks. The serve path re-checks the drain
    after the accept wait (free extra budget) and refuses to cut the
    bundle when it still fails."""
    class _StuckManager:
        def __init__(self):
            self.drain_calls = []
            self.thawed = None

        def freeze_for_handoff(self):
            return False  # something is mid-mutation at the deadline

        def drain_for_handoff(self, timeout=5.0):
            self.drain_calls.append(timeout)
            return False  # ...and it never finishes

        def thaw_after_handoff(self, dispatch_queued=True):
            self.thawed = dispatch_queued

    mgr = _StuckManager()
    sock_path = os.path.join(short_tmp, "handoff.sock")
    results = []
    server = threading.Thread(
        target=lambda: results.append(
            handoff.serve_handoff(mgr, sock_path, timeout=5.0)),
        daemon=True)
    server.start()
    assert_eventually(lambda: os.path.exists(sock_path))
    peer = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    peer.settimeout(5)
    peer.connect(sock_path)
    # no bundle frame may ever arrive: the connection just closes
    assert peer.recv(4096) == b""
    peer.close()
    server.join(timeout=5)
    assert results == ["aborted"]
    assert mgr.drain_calls, "serve path skipped the drain re-check"
    # bundle never sent -> unambiguous abort: queued CNI dispatches
    # locally (this daemon still owns the dataplane)
    assert mgr.thawed is True
    aborted = [e for e in flight.RECORDER.events(kind="handoff")
               if e.get("name") == "HandoffAborted"]
    assert "mid-mutation" in aborted[-1]["attributes"]["reason"]


def test_stale_handoff_socket_fallback_once_then_silent(short_tmp):
    """A handoff socket corpse (outgoing daemon killed -9 before any
    peer connected) records ONE fallback and is then removed — every
    later plain restart cold-starts silently instead of repeating the
    spurious HandoffFallback (metric + degraded window) forever."""
    sock_path = os.path.join(short_tmp, "handoff.sock")
    corpse = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    corpse.bind(sock_path)
    corpse.close()  # bound then closed: file exists, connect refused
    baseline = len(flight.RECORDER.events(kind="handoff"))
    assert handoff.adopt_into(None, sock_path) is False
    entries = flight.RECORDER.events(kind="handoff")[baseline:]
    assert [e["name"] for e in entries] == ["HandoffFallback"]
    assert "not serving" in entries[0]["attributes"]["reason"]
    assert not os.path.exists(sock_path), "socket corpse not removed"
    # the next restart: nothing to adopt, nothing recorded
    handoff.STATUS.reset()
    assert handoff.adopt_into(None, sock_path) is False
    assert len(flight.RECORDER.events(kind="handoff")) == baseline + 1
    assert handoff.STATUS.degraded_components() == []


def test_adopted_pending_cni_rides_dispatch_machinery(short_tmp):
    """Freeze-window requests applied at adoption get the SAME
    semantics they would have had without the freeze: a DEL whose
    state is already gone is idempotent-success (a raw handler call
    would 500 and kubelet would re-drive it forever), and an ADD
    hitting a transient blip gets its bounded in-dispatch retries."""
    from dpu_operator_tpu.cni.types import AlreadyGone, PodRequest
    from dpu_operator_tpu.utils import resilience
    from types import SimpleNamespace

    add_attempts = []

    def flaky_add(req):
        add_attempts.append(1)
        if len(add_attempts) == 1:
            raise ConnectionError("VSP restarting under the daemon")
        return {"cniVersion": "0.4.0", "adopted": True}

    def gone_del(req):
        raise AlreadyGone("state torn down before the handoff")

    server = CniServer(os.path.join(short_tmp, "cni.sock"),
                       add_handler=flaky_add, del_handler=gone_del,
                       retry=resilience.RetryPolicy(
                           max_attempts=3, base=0.001, cap=0.002))
    mgr = SimpleNamespace(cni_server=server)
    del_req = PodRequest.from_cni_request(_del_request("sandboxGONE"))
    add_req = PodRequest.from_cni_request(CniRequest(
        env={"CNI_COMMAND": "ADD", "CNI_CONTAINERID": "sandboxADD",
             "CNI_NETNS": "/var/run/netns/a", "CNI_IFNAME": "net1",
             "CNI_ARGS": "K8S_POD_NAMESPACE=default;K8S_POD_NAME=p"},
        config={"cniVersion": "0.4.0", "type": "tpu-cni",
                "mode": "network-function", "deviceID": "chip-0"}))
    results = handoff._apply_pending_cni(mgr, [
        handoff._pod_req_to_dict(del_req),
        handoff._pod_req_to_dict(add_req)])
    del_out = results[handoff.handoff_key(del_req)]
    assert not del_out.get("error"), del_out
    assert del_out["result"]["cniVersion"] == "0.4.0"
    add_out = results[handoff.handoff_key(add_req)]
    assert not add_out.get("error"), add_out
    assert add_out["result"].get("adopted") is True
    assert len(add_attempts) == 2, "transient ADD was not retried"


# -- adoption discrepancy repair ----------------------------------------------

def test_adoption_restores_netconf_lost_from_disk(kube, short_tmp):
    """Orphan/lost netconf entries are flight-recorded (kind=adoption)
    and repaired from the bundle — the adoption-or-rebuild contract."""
    dataplane = _Dataplane()
    outgoing = _manager(short_tmp, _UpgradeVsp(dataplane), client=kube)
    outgoing._cni_nf_add(_Req("sandboxDDDD", "chip-0", "net1", "p"))
    outgoing._cni_nf_add(_Req("sandboxDDDD", "chip-1", "net2", "p"))
    bundle = handoff.collect_bundle(outgoing)
    # disk loses one cache entry between serialize and adopt (torn fs)
    lost = os.path.join(outgoing.nf_cache.cache_dir,
                        "sandboxDDDD-net1.json")
    os.unlink(lost)
    incoming = _manager(short_tmp, _UpgradeVsp(dataplane), client=kube)
    baseline = len(flight.RECORDER.events(kind="adoption"))
    report = handoff.adopt_bundle(incoming, bundle)
    kinds = [d["kind"] for d in report.discrepancies]
    assert "netconf-missing-on-disk" in kinds
    assert os.path.exists(lost)  # restored from the bundle
    assert json.load(open(lost))["device"] == "chip-0"
    recorded = flight.RECORDER.events(kind="adoption")[baseline:]
    assert any(e["name"] == "netconf-missing-on-disk" for e in recorded)


# -- crash-safe state writes (satellite) --------------------------------------

def test_netconf_cache_save_is_atomic_and_truncation_safe(tmp_path):
    cache = NetConfCache(str(tmp_path / "nf"))
    cache.save("sbx", "net1", {"device": "chip-0"})
    assert cache.load("sbx", "net1") == {"device": "chip-0"}
    # no temp debris left behind by the atomic write
    assert [f for f in os.listdir(cache.cache_dir) if ".tmp" in f] == []
    # a truncated entry (pre-fix crash artifact) must load as None, not
    # poison the DEL path with a JSONDecodeError
    path = os.path.join(cache.cache_dir, "torn-net1.json")
    with open(path, "w") as f:
        f.write('{"device": "chi')
    assert cache.load("torn", "net1") is None
    # and a crash DURING save never tears the visible file: the write
    # lands in a temp file first, so an exception before rename leaves
    # the old content intact
    import dpu_operator_tpu.utils.atomicfile as af
    real_rename = os.rename
    try:
        af.os.rename = lambda *a: (_ for _ in ()).throw(
            OSError("crash before rename"))
        with pytest.raises(OSError):
            cache.save("sbx", "net1", {"device": "NEW"})
    finally:
        af.os.rename = real_rename
    assert cache.load("sbx", "net1") == {"device": "chip-0"}


def test_chip_allocator_poison_recovery_single_winner(tmp_path):
    """Concurrent allocates racing to recover the same empty (poisoned)
    lock must produce exactly one owner: the recovery unlink may never
    delete a contender's freshly-landed valid claim (which would grant
    the chip twice)."""
    alloc = ChipAllocator(str(tmp_path / "alloc"))
    os.makedirs(alloc.alloc_dir, exist_ok=True)
    for round_ in range(20):
        chip = f"chip-{round_}"
        open(os.path.join(alloc.alloc_dir, chip), "w").close()  # poison
        barrier = threading.Barrier(2)
        results = {}

        def claim(owner, chip=chip, barrier=barrier, results=results):
            barrier.wait()
            results[owner] = alloc.allocate(chip, owner)

        threads = [threading.Thread(target=claim, args=(o,))
                   for o in ("sandboxA", "sandboxB")]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        winners = sorted(o for o, ok in results.items() if ok)
        assert len(winners) == 1, (round_, results)
        assert alloc.owner(chip) == winners[0], (round_, results)


def test_chip_allocator_claim_is_crash_safe(tmp_path):
    alloc = ChipAllocator(str(tmp_path / "alloc"))
    assert alloc.allocate("chip-0", "sandboxA")
    assert alloc.owner("chip-0") == "sandboxA"
    assert alloc.allocate("chip-0", "sandboxA")      # idempotent
    assert not alloc.allocate("chip-0", "sandboxB")  # held
    # a kill -9 before the old code's write() left an EMPTY lock file:
    # owner() must read it as unowned and allocate() must recover it
    torn = os.path.join(alloc.alloc_dir, "chip-1")
    open(torn, "w").close()
    assert alloc.owner("chip-1") is None
    assert alloc.allocate("chip-1", "sandboxC")
    assert alloc.owner("chip-1") == "sandboxC"
    # no .claim temp debris
    assert [f for f in os.listdir(alloc.alloc_dir) if ".claim" in f] == []


# -- device plugin socket ownership (satellite) -------------------------------

def test_atomic_claim_falls_back_without_hardlinks(tmp_path, monkeypatch):
    """link(2) is unavailable on some overlay/FUSE mounts (EPERM /
    EOPNOTSUPP): the claim must degrade to the legacy O_CREAT|O_EXCL
    path rather than fail every CNI ADD on the node — the narrower
    crash window it reopens leaves truncated claims the owner checks
    already detect and re-claim."""
    import errno

    import dpu_operator_tpu.utils.atomicfile as af

    def no_link(src, dst, **kw):
        raise OSError(errno.EPERM, "Operation not permitted")

    monkeypatch.setattr(af.os, "link", no_link)
    path = str(tmp_path / "claims" / "chip-0")
    os.makedirs(os.path.dirname(path))
    assert af.atomic_claim(path, "sandboxA") is True
    with open(path) as f:
        assert f.read() == "sandboxA"
    # a contested claim still loses cleanly
    assert af.atomic_claim(path, "sandboxB") is False
    with open(path) as f:
        assert f.read() == "sandboxA"
    # and no temp debris is left behind on either outcome
    assert [n for n in os.listdir(os.path.dirname(path))
            if ".claim" in n] == []


def test_outgoing_plugin_stop_preserves_successor_socket(short_tmp):
    import grpc  # noqa: F401 — skip cleanly if grpc is absent
    from dpu_operator_tpu.deviceplugin import DevicePlugin

    class _Handler:
        def get_devices(self):
            return {}

    pm = PathManager(short_tmp)
    outgoing = DevicePlugin(_Handler(), path_manager=pm)
    outgoing.start()
    sock = outgoing.socket_path
    old_ino = os.stat(sock).st_ino
    # the incoming daemon wipes the stale file and binds a fresh socket
    # at the same path (what _start_locked does)
    incoming = DevicePlugin(_Handler(), path_manager=pm)
    incoming.start()
    new_ino = os.stat(sock).st_ino
    assert new_ino != old_ino
    try:
        # the OUTGOING daemon's shutdown must not delete the successor's
        # socket (grpc-core unlinks the bound path on stop — the guard
        # parks the successor's file across it)
        outgoing.stop()
        assert os.path.exists(sock)
        assert os.stat(sock).st_ino == new_ino
    finally:
        incoming.stop()
    # a normal (sole-owner) stop does clean its own socket up
    assert not os.path.exists(sock)


# -- reconciler pause (freeze window) -----------------------------------------

def test_unexpected_serve_error_still_thaws(kube, short_tmp):
    """An exception that is neither HandoffError nor OSError (a bug in
    bundle collection, a malformed ACK shape) must still thaw the
    outgoing daemon — never leave the freeze parked forever."""
    outgoing = _manager(short_tmp, _UpgradeVsp(_Dataplane()),
                        client=kube)
    real_export = outgoing.export_wire_table
    outgoing.export_wire_table = lambda: (_ for _ in ()).throw(
        TypeError("bug in bundle collection"))
    try:
        sock_path = outgoing.path_manager.handoff_socket()
        result = {}
        serve = threading.Thread(
            target=lambda: result.setdefault(
                "r", handoff.serve_handoff(outgoing, sock_path,
                                           timeout=60.0)),
            daemon=True)
        serve.start()
        assert_eventually(lambda: os.path.exists(sock_path),
                          message="handoff socket never appeared")
        incoming = _manager(short_tmp, _UpgradeVsp(_Dataplane()),
                            client=kube)
        assert not handoff.adopt_into(incoming, sock_path)
        serve.join(timeout=10)
        assert result.get("r") == "aborted"
        assert not outgoing.cni_server.frozen  # thawed, still serving
    finally:
        outgoing.export_wire_table = real_export


def test_content_malformed_bundle_falls_back_not_crashes(kube,
                                                         short_tmp):
    """A bundle that passes the frame checks but carries wrong inner
    shapes must land on the cold-start fallback (HandoffFallback,
    degraded), not crash the incoming daemon's startup."""
    sock_path = os.path.join(short_tmp, "handoff.sock")
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(sock_path)
    listener.listen(1)
    reject = {}

    def _bad_outgoing():
        conn, _ = listener.accept()
        # frame-valid, content-garbage: device snapshot as a list
        handoff.send_frame(conn, {
            "schema": handoff.SCHEMA_VERSION,
            "device_plugins": {"google.com/tpu": ["not", "a", "dict"]},
            "pending_cni": ["not-a-request"]})
        try:
            reject["frame"], _ = handoff.recv_frame(conn)
        finally:
            conn.close()

    server = threading.Thread(target=_bad_outgoing, daemon=True)
    server.start()
    incoming = _manager(short_tmp, _UpgradeVsp(_Dataplane()),
                        client=kube)
    assert not handoff.adopt_into(incoming, sock_path)
    server.join(timeout=5)
    listener.close()
    assert reject["frame"]["adopted"] is False
    assert "adoption failed" in reject["frame"]["reason"]
    assert handoff.STATUS.degraded_components()
    names = [e["name"] for e in flight.RECORDER.events(kind="handoff")]
    assert names[-1] == "HandoffFallback"


def test_tpuctl_style_begin_handoff_runs_stop_hook(kube, short_tmp):
    """AdminService.BeginHandoff (tpuctl) reaches the side manager
    directly, without the Daemon wrapper: the daemon-set
    handoff_on_complete hook must still stop the outgoing process
    after adoption."""
    dataplane = _Dataplane()
    outgoing = _manager(short_tmp, _UpgradeVsp(dataplane), client=kube)
    stopped = threading.Event()
    outgoing.handoff_on_complete = stopped.set
    assert outgoing.begin_handoff(timeout=60.0)  # no explicit hook
    sock_path = outgoing.path_manager.handoff_socket()
    assert_eventually(lambda: os.path.exists(sock_path),
                      message="handoff socket never appeared")
    incoming = _manager(short_tmp, _UpgradeVsp(dataplane), client=kube)
    assert handoff.adopt_into(incoming, sock_path)
    assert stopped.wait(5), "stop hook never ran after adoption"


def test_ambiguous_abort_fails_queued_instead_of_reapplying():
    """unfreeze(dispatch_queued=False) — the bundle reached the peer
    but the ACK was lost: the peer may have applied the queued
    mutations, so re-applying locally could double-steer. They must be
    failed back to kubelet as retryable, untouched locally."""
    applied = []
    srv = CniServer("/unused.sock",
                    add_handler=lambda r: applied.append(r) or {},
                    del_handler=lambda r: applied.append(r) or {})
    srv.freeze()
    resp = {}
    t = threading.Thread(
        target=lambda: resp.setdefault(
            "r", srv._handle(_del_request("sandboxQ"))), daemon=True)
    t.start()
    assert_eventually(lambda: len(srv.frozen_requests()) == 1,
                      message="DEL never queued")
    srv.unfreeze(dispatch_queued=False)
    t.join(5)
    assert applied == []
    assert "retry" in resp["r"].error


def test_mutations_after_served_handoff_fail_fast():
    """After complete_frozen the outgoing daemon's state lives in its
    successor: a late ADD/DEL here must error immediately (kubelet
    retries against the new daemon's socket), never mutate state the
    bundle no longer covers."""
    srv = CniServer("/unused.sock", add_handler=lambda r: {},
                    del_handler=lambda r: {})
    srv.freeze()
    srv.complete_frozen({})
    resp = srv._handle(_del_request("sandboxX"))
    assert "handed off" in resp.error


def test_timed_out_frozen_request_not_applied_on_unfreeze():
    """A queued request whose kubelet caller already received the
    freeze-window timeout error must NOT be silently applied by a
    later unfreeze — kubelet thinks it failed and will re-drive it."""
    applied = []
    srv = CniServer("/unused.sock", add_handler=lambda r: {},
                    del_handler=lambda r: applied.append(r.sandbox_id)
                    or {}, timeout=0.1)
    srv.freeze()
    resp = srv._handle(_del_request("sandboxT"))  # waits 0.1s, errors
    assert "no adoption" in resp.error
    srv.unfreeze()
    assert applied == []


def test_freeze_drains_inflight_cni_dispatch(kube, short_tmp):
    """A CNI ADD already past the freeze check when the freeze begins
    must FINISH before freeze_for_handoff returns — otherwise the
    bundle could be serialized while the dispatch is still mutating
    state it will never capture."""
    mgr = _manager(short_tmp, _UpgradeVsp(_Dataplane()), client=kube)
    entered = threading.Event()
    release = threading.Event()

    def slow_add(req):
        entered.set()
        assert release.wait(5), "dispatch never released"
        return {"cniVersion": "0.4.0"}

    mgr.cni_server.add_handler = slow_add
    add_done = threading.Event()

    def post_add():
        mgr.cni_server._handle(CniRequest(
            env={"CNI_COMMAND": "ADD", "CNI_CONTAINERID": "sandboxZZ",
                 "CNI_NETNS": "/var/run/netns/z", "CNI_IFNAME": "net1",
                 "CNI_ARGS": "K8S_POD_NAMESPACE=default;K8S_POD_NAME=p"},
            config={"cniVersion": "0.4.0", "type": "tpu-cni",
                    "mode": "network-function", "deviceID": "chip-0"}))
        add_done.set()

    threading.Thread(target=post_add, daemon=True).start()
    assert entered.wait(5), "dispatch never started"
    froze = threading.Event()
    threading.Thread(
        target=lambda: (mgr.freeze_for_handoff(), froze.set()),
        daemon=True).start()
    # the freeze must NOT complete while the dispatch is in flight
    assert not froze.wait(0.3)
    release.set()
    assert froze.wait(5), "freeze never completed after drain"
    assert add_done.wait(5)
    assert mgr.cni_server.frozen
    mgr.thaw_after_handoff()


def test_freeze_parks_chain_repair_pass(kube, short_tmp):
    """A repair re-steer during the freeze window would land AFTER the
    bundle's wire table serialized: the adopting daemon's reconcile-
    against-dataplane would drop the hop and the re-steered wire would
    leak, tracked by neither generation. Freeze must park repair (the
    periodic loop and AdminService.RepairChains both funnel through
    repair_chains); an aborted handoff thaws it."""
    mgr = _manager(short_tmp, _UpgradeVsp(_Dataplane()), client=kube)
    passes = []
    mgr.link_prober = lambda port: None
    mgr._repair_chains_locked = \
        lambda probe_cache=None: passes.append(1) or []
    assert mgr.repair_chains() == []
    assert len(passes) == 1
    mgr.freeze_for_handoff()
    assert mgr.repair_chains() == []
    assert len(passes) == 1, "repair pass ran inside the freeze window"
    mgr.thaw_after_handoff()
    mgr.repair_chains()
    assert len(passes) == 2, "repair did not resume after the thaw"


def test_freeze_drains_inflight_repair_pass(kube, short_tmp):
    """A repair pass already past its gate when the freeze begins must
    FINISH before freeze_for_handoff returns — the bundle is never
    serialized mid-re-steer."""
    mgr = _manager(short_tmp, _UpgradeVsp(_Dataplane()), client=kube)
    mgr.link_prober = lambda port: None
    entered, release = threading.Event(), threading.Event()

    def slow_pass(probe_cache=None):
        entered.set()
        assert release.wait(5), "repair pass never released"
        return []

    mgr._repair_chains_locked = slow_pass
    done = threading.Event()
    threading.Thread(target=lambda: (mgr.repair_chains(), done.set()),
                     daemon=True).start()
    assert entered.wait(5), "repair pass never started"
    froze = threading.Event()
    threading.Thread(
        target=lambda: (mgr.freeze_for_handoff(), froze.set()),
        daemon=True).start()
    # the freeze must NOT complete while the pass is mid-re-steer
    assert not froze.wait(0.3)
    release.set()
    assert froze.wait(5), "freeze never completed after repair drain"
    assert done.wait(5)
    mgr.thaw_after_handoff()


def test_pause_drain_waits_for_inflight_reconcile(kube):
    """Manager.pause() parks the worker before its NEXT item;
    drain() must additionally wait out the CURRENT reconcile so the
    handoff bundle never serializes mid-mutation."""
    from dpu_operator_tpu.k8s.manager import Manager, ReconcileResult

    entered = threading.Event()
    release = threading.Event()

    class _Slow:
        watches = ("v1", "ConfigMap")

        def reconcile(self, client, req):
            entered.set()
            assert release.wait(5), "reconcile never released"
            return ReconcileResult()

    mgr = Manager(kube)
    mgr.add_reconciler(_Slow())
    mgr.start()
    try:
        kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                     "metadata": {"name": "cm1", "namespace": "default"}})
        assert entered.wait(5), "reconcile never started"
        mgr.pause()
        drained = threading.Event()
        threading.Thread(
            target=lambda: mgr.drain(timeout=10) and drained.set(),
            daemon=True).start()
        assert not drained.wait(0.3)  # reconcile still mid-flight
        release.set()
        assert drained.wait(5), "drain never observed quiescence"
    finally:
        release.set()
        mgr.resume()
        mgr.stop()


def test_manager_pause_parks_reconciles_until_resume(kube):
    from dpu_operator_tpu.k8s.manager import Manager, ReconcileResult

    seen = []

    class _Rec:
        watches = ("v1", "ConfigMap")

        def reconcile(self, client, req):
            seen.append(req.name)
            return ReconcileResult()

    mgr = Manager(kube)
    mgr.add_reconciler(_Rec())
    mgr.start()
    try:
        mgr.pause()
        assert mgr.paused
        kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                     "metadata": {"name": "cm1", "namespace": "default"}})
        # the event is queued but must NOT be reconciled while paused
        import time
        deadline = time.monotonic() + 0.3
        while time.monotonic() < deadline:
            assert seen == []
            time.sleep(0.02)
        mgr.resume()
        assert_eventually(lambda: seen == ["cm1"],
                          message="queued reconcile after resume")
    finally:
        mgr.stop()


# -- tpuctl -------------------------------------------------------------------

def test_tpuctl_handoff_status_renders_last_handoff():
    from dpu_operator_tpu.tpuctl import handoff_status
    snap = {"events": [
        {"kind": "span", "name": "noise"},
        {"kind": "handoff", "name": "HandoffFallback", "ts": 1.0,
         "attributes": {"reason": "bundle transfer failed: truncated"}},
        # a PREVIOUS handoff's discrepancy still in the ring: must NOT
        # be attributed to the last handoff
        {"kind": "adoption", "name": "chip-allocation-orphan",
         "attributes": {"detail": "stale: belongs to handoff 1",
                        "handoff_id": 1}},
        {"kind": "adoption", "name": "netconf-orphan",
         "attributes": {"detail": "sbx-net1.json: on disk but unknown",
                        "handoff_id": 2}},
        {"kind": "handoff", "name": "HandoffAdopted", "ts": 2.0,
         "duration_s": 0.12,
         "attributes": {"bundle_bytes": 4096, "handoff_id": 2,
                        "adopted_hops": 3,
                        "adopted_sandboxes": 2, "pending_applied": 1,
                        "discrepancies": 1}},
    ]}
    out = handoff_status(snap)
    last = out["lastHandoff"]
    assert last["result"] == "HandoffAdopted"
    assert last["durationSeconds"] == 0.12
    assert last["bundleBytes"] == 4096
    assert last["adoptedHops"] == 3
    assert last["fallbackReason"] == ""
    assert out["history"] == ["HandoffFallback", "HandoffAdopted"]
    assert out["adoptionDiscrepancies"] == [
        {"kind": "netconf-orphan",
         "detail": "sbx-net1.json: on disk but unknown"}]


def test_tpuctl_handoff_status_served_owns_no_adoptions():
    """A daemon that adopted at startup (its discrepancies still in the
    ring) and later SERVED a handoff to its successor: the Served entry
    carries its own handoff_id, so the startup adoption's discrepancies
    must not be listed under it."""
    from dpu_operator_tpu.tpuctl import handoff_status
    snap = {"events": [
        {"kind": "adoption", "name": "netconf-orphan",
         "attributes": {"detail": "from this daemon's own startup",
                        "handoff_id": 1}},
        {"kind": "handoff", "name": "HandoffAdopted", "ts": 1.0,
         "attributes": {"handoff_id": 1, "discrepancies": 1}},
        {"kind": "handoff", "name": "HandoffServed", "ts": 2.0,
         "attributes": {"bundle_bytes": 512, "handoff_id": 2,
                        "pending_cni": 0, "completed": 0}},
    ]}
    out = handoff_status(snap)
    assert out["lastHandoff"]["result"] == "HandoffServed"
    assert out["adoptionDiscrepancies"] == []


def test_tpuctl_handoff_status_unstamped_entry_attributes_nothing():
    """A handoff entry with no handoff_id (a pre-stamp flight ring)
    must not sweep up adoption entries from an earlier handoff."""
    from dpu_operator_tpu.tpuctl import handoff_status
    snap = {"events": [
        {"kind": "adoption", "name": "netconf-orphan",
         "attributes": {"detail": "earlier adoption",
                        "handoff_id": 1}},
        {"kind": "handoff", "name": "HandoffFallback", "ts": 2.0,
         "attributes": {"reason": "truncated"}},
    ]}
    out = handoff_status(snap)
    assert out["lastHandoff"]["result"] == "HandoffFallback"
    assert out["lastHandoff"]["fallbackReason"] == "truncated"
    assert out["adoptionDiscrepancies"] == []


def test_tpuctl_handoff_begin_needs_daemon_addr():
    from dpu_operator_tpu import tpuctl
    with pytest.raises(SystemExit):
        tpuctl.main(["handoff", "begin"])
