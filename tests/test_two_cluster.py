"""Two-cluster e2e: host cluster + TPU-VM cluster joined over TCP.

The reference's signature topology (SURVEY.md §0: x86 OpenShift cluster +
MicroShift on the DPU ARM cores; e2e via cluster-deployment-automation).
Here: two independent FakeKubes — the host side runs HostSideManager whose
CNI ADDs cross the wire to the tpu side's slice service (the IPv6
link-local OPI channel analog), which programs the VSP over the native
agent. Asserts the cross-boundary path end to end, including teardown.
"""

import json
import os
import subprocess

import pytest

from dpu_operator_tpu.cni import CniShim
from dpu_operator_tpu.daemon import HostSideManager, TpuSideManager
from dpu_operator_tpu.k8s import FakeKube, FakeNodeAgent
from dpu_operator_tpu.platform.platform import FakePlatform
from dpu_operator_tpu.platform.vendordetector import TpuDetector
from dpu_operator_tpu.utils.path_manager import PathManager
from dpu_operator_tpu.vsp.google import GoogleTpuVsp
from dpu_operator_tpu.vsp.mock import MockTpuVsp
from dpu_operator_tpu.vsp.native_dp import (AgentClient, AgentProcess,
                                            NativeIciDataplane)
from dpu_operator_tpu.vsp.plugin import GrpcPlugin
from dpu_operator_tpu.vsp.rpc import VspServer

from utils import assert_eventually

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="session")
def agent_binary():
    subprocess.run(["make", "-C", os.path.join(REPO, "native")], check=True,
                   capture_output=True)
    return os.path.join(REPO, "native", "build", "tpu_cp_agent")


def _tpu_pci(addr):
    from dpu_operator_tpu.platform.platform import PciDevice
    return PciDevice(address=addr, vendor_id="1ae0", device_id="0062")


def test_two_cluster_slice_attachment_lifecycle(short_tmp, agent_binary):
    """host CNI ADD → TCP to the tpu-side daemon → VSP → native agent;
    DEL unwinds the attachment on both sides."""
    host_dir = short_tmp + "/host"
    tpu_dir = short_tmp + "/tpu"
    os.makedirs(host_dir)
    os.makedirs(tpu_dir)

    # ---- TPU-VM cluster ----
    tpu_kube = FakeKube()
    tpu_agent = FakeNodeAgent(tpu_kube)
    tpu_agent.start()
    tpu_agent.register_node("tpu-vm-0", labels={"tpu": "true"})
    tpu_pm = PathManager(tpu_dir)
    cp = AgentProcess(agent_binary, tpu_dir + "/cp.sock",
                      state_file=tpu_dir + "/cp.state", dev_dir=tpu_dir,
                      allow_regular_dev=True)
    cp.start()
    for i in range(4):
        open(f"{tpu_dir}/accel{i}", "w").close()
    cp_client = AgentClient(cp.socket_path)
    tpu_vsp = GoogleTpuVsp(
        FakePlatform(accelerator_type="v5litepod-4",
                     accel=[f"{tpu_dir}/accel{i}" for i in range(4)]),
        dataplane=NativeIciDataplane(cp_client))
    tpu_sock = tpu_pm.vendor_plugin_socket()
    tpu_pm.ensure_socket_dir(tpu_sock)
    tpu_vsp_server = VspServer(tpu_vsp, socket_path=tpu_sock)
    tpu_vsp_server.start()
    tpu_det = TpuDetector().detection_result(tpu_mode=True, identifier="t")
    tpu_mgr = TpuSideManager(
        GrpcPlugin(tpu_det, path_manager=tpu_pm, init_timeout=5.0), tpu_pm,
        client=tpu_kube)
    tpu_mgr.device_plugin.poll_interval = 0.1

    # ---- host cluster ----
    host_kube = FakeKube()
    host_pm = PathManager(host_dir)
    host_vsp = MockTpuVsp()  # host-side VSP: enumerates PCIe endpoints
    host_vsp.get_devices = lambda req: {"devices": {
        "0000:00:04.0": {"id": "0000:00:04.0", "healthy": True,
                         "dev_path": "", "coords": [], "chip_index": 0},
        "0000:00:05.0": {"id": "0000:00:05.0", "healthy": True,
                         "dev_path": "", "coords": [], "chip_index": 1},
    }}
    host_sock = host_pm.vendor_plugin_socket()
    host_pm.ensure_socket_dir(host_sock)
    host_vsp_server = VspServer(host_vsp, socket_path=host_sock)
    host_vsp_server.start()
    host_det = TpuDetector().detection_result(tpu_mode=False, identifier="h")
    host_mgr = HostSideManager(
        GrpcPlugin(host_det, path_manager=host_pm, init_timeout=5.0),
        host_pm, client=host_kube)

    try:
        # bring up the tpu side; its slice server binds the VSP-returned
        # port
        tpu_mgr.start_vsp()
        tpu_mgr.setup_devices()
        tpu_mgr.listen()
        assert tpu_mgr.bound_port

        # the host-side VSP's Init response points at the tpu-side daemon
        # (the reference returns the IPv6 link-local IpPort the same way)
        host_vsp.ip = "127.0.0.1"
        host_vsp.port = tpu_mgr.bound_port
        host_mgr.start_vsp()
        host_mgr.setup_devices()
        host_mgr.listen()

        shim = CniShim(host_pm.cni_server_socket())

        def cni(cmd, device):
            return shim.invoke(
                {"CNI_COMMAND": cmd, "CNI_CONTAINERID": "podA",
                 "CNI_NETNS": "/var/run/netns/podA", "CNI_IFNAME": "net1",
                 "CNI_ARGS": "K8S_POD_NAMESPACE=default;K8S_POD_NAME=a"},
                json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                            "mode": "chip", "deviceID": device}))

        resp = cni("ADD", "0000:00:04.0")
        assert resp.error == ""
        assert resp.result["tpu"]["attachment"] == "host0-0"
        # the attachment crossed clusters into the tpu-side VSP + agent
        assert_eventually(lambda: "host0-0" in tpu_vsp.attachments,
                          message="attachment on tpu side")
        states = cp_client.link_state(0)
        assert states and all(s["wired"] for s in states)

        # second pod claiming the same device must be refused host-side
        resp_dup = shim.invoke(
            {"CNI_COMMAND": "ADD", "CNI_CONTAINERID": "podB",
             "CNI_NETNS": "/var/run/netns/podB", "CNI_IFNAME": "net1",
             "CNI_ARGS": "K8S_POD_NAMESPACE=default;K8S_POD_NAME=b"},
            json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                        "mode": "chip", "deviceID": "0000:00:04.0"}))
        assert "already allocated" in resp_dup.error

        # DEL unwinds: host allocator released, tpu-side detached
        resp_del = cni("DEL", "0000:00:04.0")
        assert resp_del.error == ""
        assert "host0-0" not in tpu_vsp.attachments
        assert all(not s["wired"] for s in cp_client.link_state(0))
        resp2 = cni("ADD", "0000:00:04.0")  # device reusable again
        assert resp2.error == ""
    finally:
        host_mgr.stop()
        host_vsp_server.stop()
        tpu_mgr.stop()
        tpu_vsp_server.stop()
        cp_client.close()
        cp.stop()
        tpu_agent.stop()


# -- TCP-plane storms (VERDICT r3 weak #6 / next #8) ------------------------

class _TwoCluster:
    """Reusable host+tpu split (the lifecycle test above, parameterized):
    a tpu-side manager with the native agent behind its cross-boundary TCP
    server, and a host-side manager whose CNI ADDs cross the wire."""

    N_DEVICES = 8

    def __init__(self, root, agent_binary, dial_retries=8,
                 dial_backoff=0.25):
        self.root = root
        self.host_dir = root + "/host"
        self.tpu_dir = root + "/tpu"
        os.makedirs(self.host_dir, exist_ok=True)
        os.makedirs(self.tpu_dir, exist_ok=True)
        self.dial_retries = dial_retries
        self.dial_backoff = dial_backoff

        self.tpu_pm = PathManager(self.tpu_dir)
        self.cp = AgentProcess(agent_binary, self.tpu_dir + "/cp.sock",
                               state_file=self.tpu_dir + "/cp.state",
                               dev_dir=self.tpu_dir, allow_regular_dev=True)
        self.cp.start()
        accel = []
        for i in range(self.N_DEVICES):
            path = f"{self.tpu_dir}/accel{i}"
            open(path, "w").close()
            accel.append(path)
        self.cp_client = AgentClient(self.cp.socket_path)
        self.tpu_vsp = GoogleTpuVsp(
            FakePlatform(accelerator_type="v5litepod-8", accel=accel),
            dataplane=NativeIciDataplane(self.cp_client), comm_port=0)
        tpu_sock = self.tpu_pm.vendor_plugin_socket()
        self.tpu_pm.ensure_socket_dir(tpu_sock)
        self.tpu_vsp_server = VspServer(self.tpu_vsp, socket_path=tpu_sock)
        self.tpu_vsp_server.start()
        tpu_det = TpuDetector().detection_result(tpu_mode=True,
                                                 identifier="t")
        self.tpu_kube = FakeKube()
        self.tpu_mgr = TpuSideManager(
            GrpcPlugin(tpu_det, path_manager=self.tpu_pm,
                       init_timeout=5.0), self.tpu_pm,
            client=self.tpu_kube)
        self.tpu_mgr.start_vsp()
        self.tpu_mgr.setup_devices()
        self.tpu_mgr.listen()
        self.tpu_shim = CniShim(self.tpu_pm.cni_server_socket())

        self.host_pm = PathManager(self.host_dir)
        self.host_vsp = MockTpuVsp()
        devs = {f"0000:00:{4 + i:02x}.0":
                {"id": f"0000:00:{4 + i:02x}.0", "healthy": True,
                 "dev_path": "", "coords": [], "chip_index": i}
                for i in range(self.N_DEVICES)}
        self.host_vsp.get_devices = lambda req: {"devices": dict(devs)}
        self.device_ids = sorted(devs)
        host_sock = self.host_pm.vendor_plugin_socket()
        self.host_pm.ensure_socket_dir(host_sock)
        self.host_vsp_server = VspServer(self.host_vsp,
                                         socket_path=host_sock)
        self.host_vsp_server.start()
        self.host_vsp.ip = "127.0.0.1"
        self.host_vsp.port = self.tpu_mgr.bound_port
        self.host_mgr = self._make_host_mgr()
        self.shim = CniShim(self.host_pm.cni_server_socket())

    def _make_host_mgr(self):
        det = TpuDetector().detection_result(tpu_mode=False, identifier="h")
        mgr = HostSideManager(
            GrpcPlugin(det, path_manager=self.host_pm, init_timeout=5.0),
            self.host_pm, dial_retries=self.dial_retries,
            dial_backoff=self.dial_backoff)
        mgr.start_vsp()
        mgr.setup_devices()
        mgr.listen()
        return mgr

    def restart_host_mgr(self):
        """Daemon restart simulation: fresh manager, empty memory, same
        disk caches and sockets."""
        self.host_mgr.stop()
        self.host_mgr = self._make_host_mgr()
        self.shim = CniShim(self.host_pm.cni_server_socket())

    def cni(self, cmd, device, sandbox):
        return self.shim.invoke(
            {"CNI_COMMAND": cmd, "CNI_CONTAINERID": sandbox,
             "CNI_NETNS": f"/var/run/netns/{sandbox}", "CNI_IFNAME": "net1",
             "CNI_ARGS": "K8S_POD_NAMESPACE=default;K8S_POD_NAME=p"},
            json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                        "mode": "chip", "deviceID": device}))

    def stop(self):
        for closer in (self.host_mgr.stop, self.host_vsp_server.stop,
                       self.tpu_mgr.stop, self.tpu_vsp_server.stop,
                       self.cp_client.close, self.cp.stop):
            try:
                closer()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


@pytest.fixture
def cluster(short_tmp, agent_binary):
    c = _TwoCluster(short_tmp, agent_binary)
    yield c
    c.stop()


def test_concurrent_cross_boundary_adds_and_dels(cluster):
    """8 pods ADD concurrently across the TCP plane — every attachment
    lands tpu-side with its chip wired; concurrent DELs unwind all of it
    (the reference's dial path was never exercised under contention)."""
    import concurrent.futures

    def add(i):
        return cluster.cni("ADD", cluster.device_ids[i], f"storm-{i}")

    with concurrent.futures.ThreadPoolExecutor(max_workers=8) as pool:
        results = list(pool.map(add, range(8)))
    assert [r.error for r in results] == [""] * 8
    names = {r.result["tpu"]["attachment"] for r in results}
    assert names == {f"host0-{i}" for i in range(8)}
    assert set(cluster.tpu_vsp.attachments) == names
    for i in range(8):
        states = cluster.cp_client.link_state(i)
        assert states and all(s["wired"] for s in states)

    def delete(i):
        return cluster.cni("DEL", cluster.device_ids[i], f"storm-{i}")

    with concurrent.futures.ThreadPoolExecutor(max_workers=8) as pool:
        dels = list(pool.map(delete, range(8)))
    assert [r.error for r in dels] == [""] * 8
    assert cluster.tpu_vsp.attachments == {}
    for i in range(8):
        assert all(not s["wired"] for s in cluster.cp_client.link_state(i))


def test_host_del_with_tpu_side_down_releases_local_state(cluster):
    """The tpu-side daemon being down must not wedge DEL: local allocator
    and cache release anyway (hostsidemanager.go's defensive DEL), and the
    device is claimable again once the tpu side returns."""
    assert cluster.cni("ADD", cluster.device_ids[0], "podX").error == ""
    saved_port = cluster.tpu_mgr.bound_port
    cluster.tpu_mgr._slice_server.stop()

    # DEL crosses into a dead TCP endpoint: retry budget burns, then the
    # local state is released regardless
    cluster.host_mgr.dial_retries = 2
    cluster.host_mgr.dial_backoff = 0.01
    resp = cluster.cni("DEL", cluster.device_ids[0], "podX")
    assert resp.error == ""
    assert cluster.host_mgr.allocator.owner(cluster.device_ids[0]) is None
    assert cluster.host_mgr.cache.load("podX", "net1") is None

    # tpu side comes back; the device is immediately reusable
    from dpu_operator_tpu.daemon.tpusidemanager import \
        _SliceServiceForwarder
    from dpu_operator_tpu.vsp.rpc import VspServer as _VS
    revived = _VS(_SliceServiceForwarder(cluster.tpu_mgr.vsp,
                                         manager=cluster.tpu_mgr),
                  tcp_addr=("127.0.0.1", saved_port))
    revived.start()
    try:
        cluster.host_mgr.dial_retries = 8
        resp2 = cluster.cni("ADD", cluster.device_ids[0], "podY")
        assert resp2.error == ""
    finally:
        revived.stop()


def test_host_daemon_restart_between_add_and_del(cluster):
    """ADD, restart the host daemon (fresh memory, same disk), DEL via
    the new process: the disk caches drive the release — attachment
    deleted tpu-side, allocator freed (sriov.go:505-583's rationale)."""
    assert cluster.cni("ADD", cluster.device_ids[2], "podR").error == ""
    assert "host0-2" in cluster.tpu_vsp.attachments

    cluster.restart_host_mgr()
    resp = cluster.cni("DEL", cluster.device_ids[2], "podR")
    assert resp.error == ""
    assert "host0-2" not in cluster.tpu_vsp.attachments
    assert cluster.host_mgr.allocator.owner(cluster.device_ids[2]) is None
    # and the chip is claimable by a new pod through the new daemon
    assert cluster.cni("ADD", cluster.device_ids[2], "podS").error == ""


def test_retry_budget_exhaustion_surfaces_as_cni_error(short_tmp,
                                                       agent_binary):
    """With the tpu side never up, the host's dial retries exhaust and
    the failure surfaces as CNI error JSON (not a hang, not a stack
    trace), with the allocation rolled back for the next attempt."""
    cluster = _TwoCluster(short_tmp + "/x", agent_binary, dial_retries=2,
                          dial_backoff=0.01)
    try:
        cluster.tpu_mgr._slice_server.stop()  # kill the TCP plane
        resp = cluster.cni("ADD", cluster.device_ids[1], "podZ")
        assert resp.error != ""
        assert "unreachable" in resp.error
        # rollback: the device is not leaked to the failed sandbox
        assert cluster.host_mgr.allocator.owner(
            cluster.device_ids[1]) is None
    finally:
        cluster.stop()


def test_external_traffic_enters_and_leaves_the_chain(cluster):
    """External-traffic e2e analog (reference: pod↔NF↔external traffic,
    e2e_test.go:348-513): two HOST-side workload pods hold slice
    attachments (host0-0, host0-1); an SFC with spec.ingress/egress binds
    the NF chain between them; after the NF CNI ADDs on the tpu side, the
    native agent's wire table holds a continuous directed path
    host0-0 → NF0 → NF1 → host0-1 — traffic enters the slice, traverses
    the chain, and leaves it. Tearing down NF0 severs the entry."""
    # 1. host workload pods A and B claim chips 0 and 1 across the wire
    assert cluster.cni("ADD", cluster.device_ids[0], "podA").error == ""
    assert cluster.cni("ADD", cluster.device_ids[1], "podB").error == ""
    assert {"host0-0", "host0-1"} <= set(cluster.tpu_vsp.attachments)

    # 2. the chain binds those attachments as its boundary
    cluster.tpu_kube.create({
        "apiVersion": "config.tpu.openshift.io/v1",
        "kind": "ServiceFunctionChain",
        "metadata": {"name": "ext", "namespace": "default"},
        "spec": {"ingress": "host0-0", "egress": "host0-1",
                 "networkFunctions": [{"name": "fw", "image": "i"},
                                      {"name": "lb", "image": "i"}]}})

    def nf_pod(name, index):
        cluster.tpu_kube.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default",
                         "annotations": {
                             "tpu.openshift.io/sfc": "ext",
                             "tpu.openshift.io/sfc-index": str(index)}},
            "spec": {"containers": [{"name": "c"}]}})

    def nf_add(sandbox, pod, device, ifname):
        return cluster.tpu_shim.invoke(
            {"CNI_COMMAND": "ADD", "CNI_CONTAINERID": sandbox,
             "CNI_NETNS": f"/var/run/netns/{sandbox}",
             "CNI_IFNAME": ifname,
             "CNI_ARGS": f"K8S_POD_NAMESPACE=default;K8S_POD_NAME={pod}"},
            json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                        "mode": "network-function", "deviceID": device}))

    # 3. NF pods wire on the tpu side (chips 2-5)
    nf_pod("ext-fw", 0)
    nf_pod("ext-lb", 1)
    for sandbox, pod, chips in (("sbx-ext-fw00", "ext-fw", (2, 3)),
                                ("sbx-ext-lb00", "ext-lb", (4, 5))):
        r1 = nf_add(sandbox, pod, f"chip-{chips[0]}", "net1")
        assert r1.error == ""
        r2 = nf_add(sandbox, pod, f"chip-{chips[1]}", "net2")
        assert r2.error == "", r2.error

    # 4. a continuous directed path exists from ingress to egress
    wires = cluster.cp_client.list_wires()
    edges = {}
    for src, dst in wires:
        edges.setdefault(src, []).append(dst)
    path, seen, frontier = ["host0-0"], set(), ["host0-0"]
    reached = False
    while frontier:
        node = frontier.pop()
        if node == "host0-1":
            reached = True
            break
        for nxt in edges.get(node, []):
            if nxt not in seen:
                seen.add(nxt)
                frontier.append(nxt)
    assert reached, f"no ingress->egress path in wire table: {wires}"
    # hop bookkeeping: boundary hops -1 and 1, NF-NF hop 0
    status = cluster.tpu_mgr.chain_status("default", "ext")
    assert sorted(h["index"] for h in status) == [-2, -1, 0]

    # 5. NF0 teardown severs the entry (boundary hop -1 and hop 0 gone)
    resp = cluster.tpu_shim.invoke(
        {"CNI_COMMAND": "DEL", "CNI_CONTAINERID": "sbx-ext-fw00",
         "CNI_NETNS": "/var/run/netns/sbx-ext-fw00", "CNI_IFNAME": "",
         "CNI_ARGS": "K8S_POD_NAMESPACE=default;K8S_POD_NAME=ext-fw"},
        json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                    "mode": "network-function"}))
    assert resp.error == ""
    wires_after = dict((s, d) for s, d in cluster.cp_client.list_wires())
    assert "host0-0" not in wires_after
    status = cluster.tpu_mgr.chain_status("default", "ext")
    assert sorted(h["index"] for h in status) == [-2]  # egress hop remains


def test_live_spec_edit_converges_boundary_hops(cluster):
    """Adding spec.ingress/egress to an ALREADY-RUNNING chain converges
    via the reconciler's boundary sync — no pod churn required; removing
    the binding tears the boundary hops back down. Scaling the chain up
    re-steers the egress hop to the new last NF (its key is distinct
    from the NF-NF index space)."""
    from dpu_operator_tpu.daemon.sfc_reconciler import SfcReconciler
    from dpu_operator_tpu.k8s.manager import Request

    assert cluster.cni("ADD", cluster.device_ids[0], "podA").error == ""
    assert cluster.cni("ADD", cluster.device_ids[1], "podB").error == ""
    sfc = {
        "apiVersion": "config.tpu.openshift.io/v1",
        "kind": "ServiceFunctionChain",
        "metadata": {"name": "live", "namespace": "default"},
        "spec": {"networkFunctions": [{"name": "fw", "image": "i"},
                                      {"name": "lb", "image": "i"}]}}
    cluster.tpu_kube.create(sfc)

    def nf(name, index, sandbox, chips):
        cluster.tpu_kube.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default",
                         "annotations": {
                             "tpu.openshift.io/sfc": "live",
                             "tpu.openshift.io/sfc-index": str(index)}},
            "spec": {"containers": [{"name": "c"}]}})
        for ifname, chip in (("net1", chips[0]), ("net2", chips[1])):
            r = cluster.tpu_shim.invoke(
                {"CNI_COMMAND": "ADD", "CNI_CONTAINERID": sandbox,
                 "CNI_NETNS": f"/var/run/netns/{sandbox}",
                 "CNI_IFNAME": ifname,
                 "CNI_ARGS":
                     f"K8S_POD_NAMESPACE=default;K8S_POD_NAME={name}"},
                json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                            "mode": "network-function",
                            "deviceID": f"chip-{chip}"}))
            assert r.error == "", r.error

    nf("live-fw", 0, "sbx-live-fw00", (2, 3))
    nf("live-lb", 1, "sbx-live-lb00", (4, 5))
    mgr = cluster.tpu_mgr
    assert sorted(h["index"] for h in
                  mgr.chain_status("default", "live")) == [0]

    # live edit: bind the boundary; the reconciler resync converges it
    obj = cluster.tpu_kube.get("config.tpu.openshift.io/v1",
                               "ServiceFunctionChain", "live",
                               namespace="default")
    obj["spec"]["ingress"] = "host0-0"
    obj["spec"]["egress"] = "host0-1"
    cluster.tpu_kube.update(obj)
    rec = SfcReconciler(workload_image="w",
                        chain_status_provider=mgr.chain_status,
                        boundary_sync=mgr.sync_chain_boundaries)
    req = Request("config.tpu.openshift.io/v1", "ServiceFunctionChain",
                  "live", "default")
    rec.reconcile(cluster.tpu_kube, req)
    assert sorted(h["index"] for h in
                  mgr.chain_status("default", "live")) == [-2, -1, 0]
    wires = cluster.cp_client.list_wires()
    assert any(src == "host0-0" for src, _ in wires)
    assert any(dst == "host0-1" for _, dst in wires)
    status = cluster.tpu_kube.get(
        "config.tpu.openshift.io/v1", "ServiceFunctionChain", "live",
        namespace="default").get("status", {})
    # NFs aren't Running in this bare kube, so ChainWired stays False,
    # but the hops themselves are all reported
    assert len(status["hops"]) == 3

    # unbind: boundary hops tear back down on the next resync
    obj = cluster.tpu_kube.get("config.tpu.openshift.io/v1",
                               "ServiceFunctionChain", "live",
                               namespace="default")
    obj["spec"].pop("ingress")
    obj["spec"].pop("egress")
    cluster.tpu_kube.update(obj)
    rec.reconcile(cluster.tpu_kube, req)
    assert sorted(h["index"] for h in
                  mgr.chain_status("default", "live")) == [0]
    wires = cluster.cp_client.list_wires()
    assert not any("host0-" in e for w in wires for e in w)


def test_host_side_learns_topology_for_preferred_allocation(cluster):
    """The host daemon learns the slice topology from the TPU-side
    daemon over the cross-boundary plane and decorates its PCIe devices
    with torus coords — host-side GetPreferredAllocation becomes
    topology-aware instead of degenerating to id order."""
    devs = cluster.host_mgr.device_handler.get_devices()
    # v5e-8 is a 2x4 grid: chip_index i -> coords (i//4, i%4)
    for dev_id, info in devs.items():
        ci = info["chip_index"]
        assert info["coords"] == [ci // 4, ci % 4], (dev_id, info)

    # adjacency-aware pick from a genuinely SCATTERED subset: indices
    # 0, 2, 3, 7 — id order would pick 0 (0,0) and 2 (0,2), distance 2;
    # only real coords find an adjacent pair (2-3 or 3-7)
    from dpu_operator_tpu.deviceplugin.server import _preferred_chips
    by_index = {info["chip_index"]: dev_id
                for dev_id, info in devs.items()}
    available = [by_index[i] for i in (0, 2, 3, 7)]
    picked = _preferred_chips(available, [], 2, devs)
    assert sorted(picked) != sorted(available[:2]), (
        "picked the id-order pair — coords were ignored")
    c0 = devs[picked[0]]["coords"]
    c1 = devs[picked[1]]["coords"]
    assert abs(c0[0] - c1[0]) + abs(c0[1] - c1[1]) == 1, (picked, c0, c1)


def test_host_topology_fetch_tolerates_tpu_side_down(short_tmp,
                                                     agent_binary):
    """With the cross-boundary plane dead, device enumeration still
    works (coords just stay absent) — decoration is best-effort and
    must not stall the ListAndWatch poll behind the dial retry budget."""
    import time

    cluster = _TwoCluster(short_tmp + "/t", agent_binary, dial_retries=2,
                          dial_backoff=0.01)
    try:
        cluster.tpu_mgr._slice_server.stop()
        t0 = time.monotonic()
        devs = cluster.host_mgr.device_handler.get_devices()
        elapsed = time.monotonic() - t0
        assert len(devs) == cluster.N_DEVICES
        assert all(not d["coords"] for d in devs.values())
        assert elapsed < 4.0, f"device poll stalled {elapsed:.1f}s"
    finally:
        cluster.stop()
