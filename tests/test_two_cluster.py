"""Two-cluster e2e: host cluster + TPU-VM cluster joined over TCP.

The reference's signature topology (SURVEY.md §0: x86 OpenShift cluster +
MicroShift on the DPU ARM cores; e2e via cluster-deployment-automation).
Here: two independent FakeKubes — the host side runs HostSideManager whose
CNI ADDs cross the wire to the tpu side's slice service (the IPv6
link-local OPI channel analog), which programs the VSP over the native
agent. Asserts the cross-boundary path end to end, including teardown.
"""

import json
import os
import subprocess

import pytest

from dpu_operator_tpu.cni import CniShim
from dpu_operator_tpu.daemon import HostSideManager, TpuSideManager
from dpu_operator_tpu.k8s import FakeKube, FakeNodeAgent
from dpu_operator_tpu.platform.platform import FakePlatform
from dpu_operator_tpu.platform.vendordetector import TpuDetector
from dpu_operator_tpu.utils.path_manager import PathManager
from dpu_operator_tpu.vsp.google import GoogleTpuVsp
from dpu_operator_tpu.vsp.mock import MockTpuVsp
from dpu_operator_tpu.vsp.native_dp import (AgentClient, AgentProcess,
                                            NativeIciDataplane)
from dpu_operator_tpu.vsp.plugin import GrpcPlugin
from dpu_operator_tpu.vsp.rpc import VspServer

from utils import assert_eventually

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="session")
def agent_binary():
    subprocess.run(["make", "-C", os.path.join(REPO, "native")], check=True,
                   capture_output=True)
    return os.path.join(REPO, "native", "build", "tpu_cp_agent")


def _tpu_pci(addr):
    from dpu_operator_tpu.platform.platform import PciDevice
    return PciDevice(address=addr, vendor_id="1ae0", device_id="0062")


def test_two_cluster_slice_attachment_lifecycle(short_tmp, agent_binary):
    """host CNI ADD → TCP to the tpu-side daemon → VSP → native agent;
    DEL unwinds the attachment on both sides."""
    host_dir = short_tmp + "/host"
    tpu_dir = short_tmp + "/tpu"
    os.makedirs(host_dir)
    os.makedirs(tpu_dir)

    # ---- TPU-VM cluster ----
    tpu_kube = FakeKube()
    tpu_agent = FakeNodeAgent(tpu_kube)
    tpu_agent.start()
    tpu_agent.register_node("tpu-vm-0", labels={"tpu": "true"})
    tpu_pm = PathManager(tpu_dir)
    cp = AgentProcess(agent_binary, tpu_dir + "/cp.sock",
                      state_file=tpu_dir + "/cp.state", dev_dir=tpu_dir,
                      allow_regular_dev=True)
    cp.start()
    for i in range(4):
        open(f"{tpu_dir}/accel{i}", "w").close()
    cp_client = AgentClient(cp.socket_path)
    tpu_vsp = GoogleTpuVsp(
        FakePlatform(accelerator_type="v5litepod-4",
                     accel=[f"{tpu_dir}/accel{i}" for i in range(4)]),
        dataplane=NativeIciDataplane(cp_client))
    tpu_sock = tpu_pm.vendor_plugin_socket()
    tpu_pm.ensure_socket_dir(tpu_sock)
    tpu_vsp_server = VspServer(tpu_vsp, socket_path=tpu_sock)
    tpu_vsp_server.start()
    tpu_det = TpuDetector().detection_result(tpu_mode=True, identifier="t")
    tpu_mgr = TpuSideManager(
        GrpcPlugin(tpu_det, path_manager=tpu_pm, init_timeout=5.0), tpu_pm,
        client=tpu_kube)
    tpu_mgr.device_plugin.poll_interval = 0.1

    # ---- host cluster ----
    host_kube = FakeKube()
    host_pm = PathManager(host_dir)
    host_vsp = MockTpuVsp()  # host-side VSP: enumerates PCIe endpoints
    host_vsp.get_devices = lambda req: {"devices": {
        "0000:00:04.0": {"id": "0000:00:04.0", "healthy": True,
                         "dev_path": "", "coords": [], "chip_index": 0},
        "0000:00:05.0": {"id": "0000:00:05.0", "healthy": True,
                         "dev_path": "", "coords": [], "chip_index": 1},
    }}
    host_sock = host_pm.vendor_plugin_socket()
    host_pm.ensure_socket_dir(host_sock)
    host_vsp_server = VspServer(host_vsp, socket_path=host_sock)
    host_vsp_server.start()
    host_det = TpuDetector().detection_result(tpu_mode=False, identifier="h")
    host_mgr = HostSideManager(
        GrpcPlugin(host_det, path_manager=host_pm, init_timeout=5.0),
        host_pm, client=host_kube)

    try:
        # bring up the tpu side; its slice server binds the VSP-returned
        # port
        tpu_mgr.start_vsp()
        tpu_mgr.setup_devices()
        tpu_mgr.listen()
        assert tpu_mgr.bound_port

        # the host-side VSP's Init response points at the tpu-side daemon
        # (the reference returns the IPv6 link-local IpPort the same way)
        host_vsp.ip = "127.0.0.1"
        host_vsp.port = tpu_mgr.bound_port
        host_mgr.start_vsp()
        host_mgr.setup_devices()
        host_mgr.listen()

        shim = CniShim(host_pm.cni_server_socket())

        def cni(cmd, device):
            return shim.invoke(
                {"CNI_COMMAND": cmd, "CNI_CONTAINERID": "podA",
                 "CNI_NETNS": "/var/run/netns/podA", "CNI_IFNAME": "net1",
                 "CNI_ARGS": "K8S_POD_NAMESPACE=default;K8S_POD_NAME=a"},
                json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                            "mode": "chip", "deviceID": device}))

        resp = cni("ADD", "0000:00:04.0")
        assert resp.error == ""
        assert resp.result["tpu"]["attachment"] == "host0-0"
        # the attachment crossed clusters into the tpu-side VSP + agent
        assert_eventually(lambda: "host0-0" in tpu_vsp.attachments,
                          message="attachment on tpu side")
        states = cp_client.link_state(0)
        assert states and all(s["wired"] for s in states)

        # second pod claiming the same device must be refused host-side
        resp_dup = shim.invoke(
            {"CNI_COMMAND": "ADD", "CNI_CONTAINERID": "podB",
             "CNI_NETNS": "/var/run/netns/podB", "CNI_IFNAME": "net1",
             "CNI_ARGS": "K8S_POD_NAMESPACE=default;K8S_POD_NAME=b"},
            json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                        "mode": "chip", "deviceID": "0000:00:04.0"}))
        assert "already allocated" in resp_dup.error

        # DEL unwinds: host allocator released, tpu-side detached
        resp_del = cni("DEL", "0000:00:04.0")
        assert resp_del.error == ""
        assert "host0-0" not in tpu_vsp.attachments
        assert all(not s["wired"] for s in cp_client.link_state(0))
        resp2 = cni("ADD", "0000:00:04.0")  # device reusable again
        assert resp2.error == ""
    finally:
        host_mgr.stop()
        host_vsp_server.stop()
        tpu_mgr.stop()
        tpu_vsp_server.stop()
        cp_client.close()
        cp.stop()
        tpu_agent.stop()
