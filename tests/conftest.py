"""Test config: force an 8-device virtual CPU mesh before JAX loads.

Multi-chip hardware is unavailable in CI; sharded code is validated on
XLA's host-platform virtual devices (the reference's analog trick is
FakePlatform + MockVsp + Kind, SURVEY.md §4).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The axon TPU plugin overrides JAX_PLATFORMS from the shell; pin the
# platform through jax.config, which wins over plugin registration.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

from dpu_operator_tpu.images import DummyImageManager  # noqa: E402
from dpu_operator_tpu.k8s import FakeKube, FakeNodeAgent  # noqa: E402


@pytest.fixture
def short_tmp():
    """Short-prefix temp dir for unix-socket tests (107-char sun_path cap)."""
    import shutil
    import tempfile
    d = tempfile.mkdtemp(prefix="tpuop-", dir="/tmp")
    yield d
    shutil.rmtree(d, ignore_errors=True)


@pytest.fixture
def kube():
    return FakeKube()


@pytest.fixture
def node_agent(kube):
    agent = FakeNodeAgent(kube)
    agent.start()
    yield agent
    agent.stop()


@pytest.fixture
def images():
    return DummyImageManager()


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """Dump fake-cluster state when a test fails (the reference's pod
    diagnostics dump, testcluster.go:341-378)."""
    outcome = yield
    report = outcome.get_result()
    if report.when != "call" or not report.failed:
        return
    from dpu_operator_tpu.k8s import FakeKube
    lines = []
    for i, kube in enumerate(list(FakeKube.instances)):
        lines.append(f"---- fake cluster #{i} state at failure ----")
        for kind in ("Node", "Pod"):
            for obj in kube.list("v1", kind):
                md = obj["metadata"]
                status = obj.get("status", {})
                lines.append(
                    f"{kind} {md.get('namespace', '')}/{md['name']}: "
                    f"phase={status.get('phase', '-')} "
                    f"allocatable={status.get('allocatable', '')}")
    if lines:
        report.sections.append(("fake cluster", "\n".join(lines)))
