"""Driver entry-point helpers (__graft_entry__.py).

The dry run must stand alone: it may be launched with or without
xla_force_host_platform_device_count and must never dial the TPU tunnel
(a down tunnel blocks in-process backend init ~25 min — observed in
round 5). The full dryrun is exercised by the driver and `make graft`;
here the cheap env plumbing is pinned.
"""

import os

import pytest

import __graft_entry__ as graft


@pytest.fixture
def clean_flags(monkeypatch):
    monkeypatch.delenv("XLA_FLAGS", raising=False)


class TestEnsureHostDeviceCount:
    def test_sets_flag_when_unset(self, clean_flags):
        prior = graft._ensure_host_device_count(8)
        assert prior is None  # caller restores by deleting
        assert os.environ["XLA_FLAGS"] == (
            "--xla_force_host_platform_device_count=8")

    def test_noop_when_flag_already_large_enough(self, monkeypatch):
        monkeypatch.setenv(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=16")
        assert graft._ensure_host_device_count(8) is False
        assert os.environ["XLA_FLAGS"] == (
            "--xla_force_host_platform_device_count=16")

    def test_grows_a_too_small_flag_in_place(self, monkeypatch):
        monkeypatch.setenv(
            "XLA_FLAGS",
            "--foo=1 --xla_force_host_platform_device_count=2 --bar=2")
        prior = graft._ensure_host_device_count(8)
        assert prior == (
            "--foo=1 --xla_force_host_platform_device_count=2 --bar=2")
        assert os.environ["XLA_FLAGS"] == (
            "--foo=1 --xla_force_host_platform_device_count=8 --bar=2")

    def test_appends_preserving_other_flags(self, monkeypatch):
        monkeypatch.setenv("XLA_FLAGS", "--foo=1")
        # the return value is the restore contract: dryrun_multichip's
        # finally block puts it back verbatim (None would DELETE the
        # caller's pre-existing flags instead)
        assert graft._ensure_host_device_count(4) == "--foo=1"
        assert os.environ["XLA_FLAGS"] == (
            "--foo=1 --xla_force_host_platform_device_count=4")


def test_entry_returns_jittable_and_args():
    # conftest pinned the CPU platform, so this never dials a tunnel;
    # compile-check the single-chip entry exactly like the driver does
    import jax

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    # full (batch, seq, vocab) logits — entry() builds max_seq=64 inputs
    assert out.ndim == 3 and out.shape[:2] == (4, 64)
