"""Continuous-batching decode service gate (`make serve-check`).

The seeded scheduler harness: two consecutive runs must produce
bit-identical scheduler traces; continuous batching must beat static
batching >=1.5x aggregate tokens/s at the same offered load; an
interactive request admitted under full batch-class load must meet its
TTFT bound via preemption; KV-pool accounting must leak zero blocks
across 500 seeded request lifecycles; and BOTH capacity producers (the
fault gate and the serve-slots handler) must uphold the
zero-spurious-ListAndWatch-deletion contract under churn. Everything is
virtual-clock / seeded-RNG — opslint's chaos-determinism rule covers
the serve marker, so a wall-clock or unseeded-entropy call here fails
lint before it can flake.
"""

import random
import threading
import time

import pytest

from dpu_operator_tpu.utils import metrics, slo
from dpu_operator_tpu.utils import vars as opvars
from dpu_operator_tpu.workloads import serve
from dpu_operator_tpu.workloads.kv_pool import KvBlockPool

pytestmark = pytest.mark.serve

SEED = 20260804


# -- KV block pool ------------------------------------------------------------


def test_pool_allocates_lowest_ids_first_and_reuses_freed():
    pool = KvBlockPool(num_blocks=8, block_size=4)
    assert pool.alloc("a", 3) == [0, 1, 2]
    assert pool.alloc("b", 2) == [3, 4]
    assert pool.free("a") == 3
    # freed blocks go back sorted: the next alloc is deterministic
    assert pool.alloc("c", 4) == [0, 1, 2, 5]
    assert pool.free_blocks() == 2


def test_pool_refuses_overcommit_and_reports_none():
    pool = KvBlockPool(num_blocks=4, block_size=16)
    assert pool.alloc("a", 3) is not None
    assert not pool.can_alloc(2)
    assert pool.alloc("b", 2) is None  # no partial grant
    assert pool.free_blocks() == 1
    assert pool.alloc("b", 1) == [3]


def test_pool_free_is_idempotent_and_unknown_owner_is_noop():
    pool = KvBlockPool(num_blocks=4, block_size=16)
    pool.alloc("a", 2)
    assert pool.free("a") == 2
    assert pool.free("a") == 0
    assert pool.free("ghost") == 0
    assert pool.occupancy() == 0.0


def test_pool_meters_occupancy_and_internal_fragmentation():
    pool = KvBlockPool(num_blocks=10, block_size=10)
    pool.alloc("a", 4)  # 40 slots
    pool.set_used_tokens("a", 25)
    assert pool.occupancy() == pytest.approx(0.4)
    assert pool.internal_fragmentation() == pytest.approx(15 / 40)
    assert metrics.SERVE_KV_BLOCKS.value(state="used") == 4.0
    pool.free("a")
    assert pool.internal_fragmentation() == 0.0
    assert metrics.SERVE_KV_BLOCKS.value(state="used") == 0.0


def test_blocks_for_tokens_is_ceil():
    pool = KvBlockPool(num_blocks=4, block_size=16)
    assert pool.blocks_for_tokens(0) == 0
    assert pool.blocks_for_tokens(1) == 1
    assert pool.blocks_for_tokens(16) == 1
    assert pool.blocks_for_tokens(17) == 2


# -- scheduler: determinism ---------------------------------------------------


def _harness_config(**kw) -> serve.ServeConfig:
    base = dict(slots=4, kv_blocks=64, kv_block_size=16,
                queue_limit=256, ttft_bound_s=1.0)
    base.update(kw)
    return serve.ServeConfig(**base)


def _run_once(seed: int, rate: float = 6.0, horizon: float = 20.0):
    sched = serve.Scheduler(_harness_config(),
                            cost_model=serve.CostModel())
    sched.submit_all(serve.open_loop_arrivals(seed, rate, horizon))
    sched.run()
    return sched


def test_scheduler_trace_bit_identical_across_runs():
    """The acceptance determinism gate: same seed, same config -> the
    scheduler traces (every admit/reject/preempt/decode/complete
    decision) compare EQUAL, and so do the completion timings."""
    a, b = _run_once(SEED), _run_once(SEED)
    assert a.trace == b.trace
    assert [(r.rid, r.finish_s, len(r.tokens)) for r in a.completed] \
        == [(r.rid, r.finish_s, len(r.tokens)) for r in b.completed]
    c = _run_once(SEED + 1)
    assert c.trace != a.trace  # the seed actually drives the trace


def test_idle_scheduler_fast_forwards_to_next_arrival():
    sched = serve.Scheduler(_harness_config())
    sched.submit(serve.Request(rid="late", prompt_len=4, output_len=2,
                               arrival_s=10.0))
    assert sched.step()
    assert sched.now >= 10.0
    sched.run()
    assert sched.completed[0].rid == "late"
    assert sched.step() is False  # drained


# -- scheduler: continuous vs static ------------------------------------------


def test_continuous_beats_static_by_1_5x():
    """The headline: at the same offered load (modeled capacity), the
    iteration-level scheduler sustains >=1.5x the aggregate tokens/s of
    drain-the-whole-batch static batching — mixed output lengths leave
    static's slots idling behind each batch's straggler."""
    cfg = _harness_config(slots=8, kv_blocks=256)
    cm = serve.CostModel()
    peak = cfg.slots / cm.decode_s(cfg.slots)
    arrivals = serve.open_loop_arrivals(
        SEED, rate_rps=peak / 66.0, horizon_s=60.0,
        prompt_lens=(16, 128), output_lens=(4, 128),
        interactive_frac=0.0)
    out = serve.compare_batching(cfg, cm, arrivals)
    assert out["continuous"]["completed"] == len(arrivals)
    assert out["static"]["completed"] == len(arrivals)
    # same requests, same tokens — only the batching policy differs
    assert out["continuous"]["tokens"] == out["static"]["tokens"]
    assert out["speedup"] >= 1.5, out


# -- scheduler: SLO classes and preemption ------------------------------------


def test_interactive_meets_ttft_bound_via_preemption():
    """Full batch-class load (every slot busy, KV pool saturated), then
    an interactive request arrives: batch-class victims are evicted
    (recomputably) and the interactive first token lands within the
    TTFT bound. The victims still complete afterwards with their full
    output — eviction lost no tokens."""
    cfg = _harness_config(slots=2, kv_blocks=16, kv_block_size=16,
                          ttft_bound_s=1.0)
    sched = serve.Scheduler(cfg)
    # two long batch requests hog both slots and 14/16 blocks
    for i in range(2):
        sched.submit(serve.Request(rid=f"hog{i}", prompt_len=48,
                                   output_len=64, slo_class=serve.BATCH,
                                   arrival_s=0.0))
    sched.submit(serve.Request(rid="vip", prompt_len=32, output_len=4,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.5))
    before = metrics.SERVE_PREEMPTIONS.total()
    sched.run()
    assert metrics.SERVE_PREEMPTIONS.total() > before
    assert any(ev[0] == "preempt" for ev in sched.trace)
    done = {r.rid: r for r in sched.completed}
    assert set(done) == {"hog0", "hog1", "vip"}
    vip = done["vip"]
    assert vip.ttft_s is not None and vip.ttft_s <= cfg.ttft_bound_s, \
        vip.ttft_s
    # recomputable eviction: victims kept every generated token
    assert all(len(done[r].tokens) == 64 for r in ("hog0", "hog1"))
    assert sum(done[r].preemptions for r in ("hog0", "hog1")) >= 1
    assert sched.pool.outstanding() == 0


def test_preempted_request_token_stream_is_unchanged():
    """Recompute-on-readmission must splice the stream invisibly: the
    tokens a preempted request ends with equal those of the same
    request served with no interactive pressure at all."""
    def run(with_vip: bool):
        sched = serve.Scheduler(_harness_config(
            slots=1, kv_blocks=8, kv_block_size=16))
        sched.submit(serve.Request(rid="steady", prompt_len=16,
                                   output_len=24,
                                   slo_class=serve.BATCH, arrival_s=0.0))
        if with_vip:
            sched.submit(serve.Request(
                rid="vip", prompt_len=8, output_len=2,
                slo_class=serve.INTERACTIVE, arrival_s=0.1))
        sched.run()
        return {r.rid: r for r in sched.completed}

    calm, stormy = run(False), run(True)
    assert stormy["steady"].preemptions >= 1
    assert stormy["steady"].tokens == calm["steady"].tokens


def test_admission_rejects_when_queue_is_full():
    """Open loop: the world keeps sending after saturation; past the
    per-class queue bound requests are REJECTED and counted — the
    health engine's saturation signal — rather than queued forever."""
    cfg = _harness_config(slots=1, kv_blocks=4, kv_block_size=16,
                          queue_limit=2)
    sched = serve.Scheduler(cfg)
    for i in range(8):
        sched.submit(serve.Request(rid=f"r{i}", prompt_len=8,
                                   output_len=32,
                                   slo_class=serve.BATCH,
                                   arrival_s=0.001 * i))
    before = metrics.SERVE_ADMISSION_REJECTED.total()
    sched.run()
    assert sched.rejected, "queue bound never rejected"
    assert metrics.SERVE_ADMISSION_REJECTED.total() > before
    assert all(r.reject_reason == "queue_full" for r in sched.rejected)
    assert {ev[0] for ev in sched.trace} >= {"reject", "admit",
                                             "complete"}
    # every non-rejected request still completed; nothing leaked
    assert len(sched.completed) + len(sched.rejected) == 8
    assert sched.pool.outstanding() == 0


def test_static_mode_admits_only_into_a_drained_batch():
    sched = serve.Scheduler(_harness_config(slots=2, static=True,
                                            preemption=False))
    for i in range(4):
        sched.submit(serve.Request(rid=f"s{i}", prompt_len=4,
                                   output_len=6, arrival_s=0.0))
    sched.run()
    admits = [ev for ev in sched.trace if ev[0] == "admit"]
    completes = [ev for ev in sched.trace if ev[0] == "complete"]
    assert len(admits) == 4 and len(completes) == 4
    # the second pair admits strictly after BOTH first completions
    second_admit_iter = admits[2][1]
    first_batch_done_iter = max(c[1] for c in completes[:2])
    assert second_admit_iter > first_batch_done_iter


def test_oversize_request_is_rejected_not_wedged():
    """A request whose KV reservation can never fit the pool must be
    rejected at ingest (kv_too_large): left queued it would wedge the
    priority head forever — admission can't satisfy it, and interactive
    priority would even evict innocent running victims first."""
    cfg = _harness_config(slots=2, kv_blocks=8, kv_block_size=16)
    sched = serve.Scheduler(cfg)  # pool holds 128 token slots
    sched.submit(serve.Request(rid="b1", prompt_len=16, output_len=16,
                               slo_class=serve.BATCH, arrival_s=0.0))
    sched.submit(serve.Request(rid="huge", prompt_len=150,
                               output_len=64,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.1))
    sched.submit(serve.Request(rid="b2", prompt_len=8, output_len=8,
                               slo_class=serve.BATCH, arrival_s=0.2))
    steps = sched.run(max_steps=10_000)
    assert steps < 10_000, "scheduler wedged on the oversize request"
    assert {r.rid for r in sched.completed} == {"b1", "b2"}
    (huge,) = sched.rejected
    assert (huge.rid, huge.reject_reason) == ("huge", "kv_too_large")
    # the doomed head never evicted the running victim
    assert sched.completed[0].preemptions == 0 if \
        sched.completed[0].rid == "b1" else True
    assert not any(ev[0] == "preempt" for ev in sched.trace)
    assert sched.pool.outstanding() == 0


def test_real_clock_itl_observes_measured_stall():
    """Under a real clock the serve-tokens SLO must see what actually
    elapsed around the executor — a 3 s decode stall reads as 3 s, not
    as the cost model's ~30 ms."""
    clock = _Clock()

    class StallingExecutor(serve.SimExecutor):
        def step(self, active):
            clock.advance(3.0)
            return super().step(active)

    sched = serve.Scheduler(_harness_config(), clock=clock,
                            executor=StallingExecutor())
    sched.submit(serve.Request(rid="slow", prompt_len=4, output_len=3,
                               arrival_s=0.0))
    before = metrics.SERVE_ITL_SECONDS.count_above(1.0)
    while sched.step():
        pass
    assert len(sched.completed) == 1
    assert metrics.SERVE_ITL_SECONDS.count_above(1.0) >= before + 2


def test_history_limit_bounds_trace_and_results():
    """The production shell caps trace/completed/rejected so a
    long-lived service cannot grow without bound; snapshot totals stay
    monotone across the trim."""
    sched = serve.Scheduler(_harness_config(slots=2))
    sched.history_limit = 8
    for i in range(40):
        sched.submit(serve.Request(rid=f"t{i}", prompt_len=4,
                                   output_len=2, arrival_s=0.01 * i))
    sched.run()
    assert len(sched.trace) <= 8
    assert len(sched.completed) <= 8
    assert sched.completed_total == 40
    assert sched.snapshot()["completed"] == 40


# -- the 500-lifecycle leak gate ----------------------------------------------


def test_kv_pool_never_leaks_across_500_lifecycles():
    """500 seeded request lifecycles — mixed classes, admissions,
    preemptions, completions — and the pool must return to EXACTLY
    zero occupancy with zero outstanding blocks and every accepted
    request completed with its full output."""
    cfg = _harness_config(slots=6, kv_blocks=96, kv_block_size=16,
                          queue_limit=1000)
    sched = serve.Scheduler(cfg)
    rng = random.Random(SEED)
    t = 0.0
    for i in range(500):
        t += rng.expovariate(8.0)
        sched.submit(serve.Request(
            rid=f"life{i}", prompt_len=rng.randint(4, 96),
            output_len=rng.randint(1, 64),
            slo_class=serve.INTERACTIVE if rng.random() < 0.4
            else serve.BATCH,
            arrival_s=t))
    steps = sched.run(max_steps=500_000)
    assert steps < 500_000, "scheduler failed to drain"
    assert len(sched.completed) == 500
    assert all(len(r.tokens) == r.output_len for r in sched.completed)
    assert sched.preemptions > 0  # the storm actually exercised eviction
    assert sched.pool.outstanding() == 0
    assert sched.pool.occupancy() == 0.0
    assert sched.pool.free_blocks() == cfg.kv_blocks
    assert metrics.SERVE_KV_BLOCKS.value(state="used") == 0.0


# -- real tokens through the refactored kernel pair ---------------------------


def _tiny_model():
    import jax

    from dpu_operator_tpu.workloads.model import (TransformerConfig,
                                                  init_params)
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_seq=64)
    return cfg, init_params(jax.random.key(0), cfg)


def test_jax_executor_streams_match_generate():
    """The serve path over the real model: requests interleaved through
    JaxSlotExecutor's per-slot positions — including one forced
    preemption/recompute — must produce token streams identical to the
    fused generate() scan run per request in isolation."""
    import jax
    import numpy as np

    from dpu_operator_tpu.workloads.decode import generate

    cfg, params = _tiny_model()
    specs = [("jA", 7, 0.0, serve.BATCH, 12),
             ("jB", 5, 0.0, serve.BATCH, 9),
             ("jC", 9, 0.05, serve.INTERACTIVE, 6)]
    prompts = {rid: tuple(int(x) for x in np.asarray(
        jax.random.randint(jax.random.key(i + 1), (plen,), 0, cfg.vocab)))
        for i, (rid, plen, _, _, _) in enumerate(specs)}
    # slots=2 with jC interactive forces a preemption of a batch slot
    cfg_s = _harness_config(slots=2, kv_blocks=8, kv_block_size=16)
    sched = serve.Scheduler(
        cfg_s, executor=serve.JaxSlotExecutor(params, cfg,
                                              cfg_s.slots))
    for rid, plen, at, cls, out in specs:
        sched.submit(serve.Request(rid=rid, prompt_len=plen,
                                   output_len=out, slo_class=cls,
                                   arrival_s=at,
                                   prompt=prompts[rid]))
    sched.run()
    done = {r.rid: r for r in sched.completed}
    assert set(done) == {"jA", "jB", "jC"}
    assert sum(r.preemptions for r in done.values()) >= 1
    for rid, plen, _, _, out in specs:
        import jax.numpy as jnp
        want = np.asarray(generate(
            params, cfg, jnp.asarray([prompts[rid]], jnp.int32),
            steps=out))[0].tolist()
        assert done[rid].tokens == want, rid


def test_jax_executor_never_retraces_decode_step():
    import jax.numpy as jnp

    from dpu_operator_tpu.workloads.decode import decode_step

    cfg, params = _tiny_model()
    ex = serve.JaxSlotExecutor(params, cfg, slots=2)
    req = serve.Request(rid="t", prompt_len=4, output_len=8,
                        prompt=(1, 2, 3, 4))
    ex.begin(req, 0)
    ex.step([(0, req)])
    before = decode_step._cache_size()
    for _ in range(5):
        ex.step([(0, req)])
    assert decode_step._cache_size() == before


# -- capacity advertisement: the shared churn regression ----------------------


class _MutableHandler:
    """Raw device handler whose health bits tests flip (the fault
    producer's upstream)."""

    def __init__(self, devices):
        self.devices = devices

    def get_devices(self):
        return {k: dict(v) for k, v in self.devices.items()}


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _fault_producer():
    """The fault gate's judged chip handler over a churning raw feed."""
    from dpu_operator_tpu.faults import FaultEngine, FaultGatedHandler
    clock = _Clock()
    raw = _MutableHandler({f"chip-{i}": {"id": f"chip-{i}",
                                         "healthy": True}
                           for i in range(4)})
    engine = FaultEngine(clock=clock)
    gated = FaultGatedHandler(raw, engine, min_probe_interval=0.0)
    rng = random.Random(SEED)

    def churn(rnd):
        clock.advance(5.0)
        for dev in raw.devices.values():
            dev["healthy"] = rng.random() > 0.3
    return gated, churn


def _serve_producer():
    """The serve-slots handler over a churning (and failing) capacity
    source."""
    from dpu_operator_tpu.deviceplugin.serve_slots import ServeSlotsHandler
    state = {"capacity": 4}

    def capacity():
        if state["capacity"] < 0:
            raise RuntimeError("service unreachable")
        return state["capacity"]

    handler = ServeSlotsHandler(capacity, max_slots=4)
    script = [4, 2, 0, -1, 9, 3, 1, 4, 0, 4]

    def churn(rnd):
        state["capacity"] = script[rnd % len(script)]
    return handler, churn


@pytest.mark.parametrize("producer", ["fault", "serve"])
def test_capacity_churn_emits_zero_spurious_deletions(producer):
    """The shared ListAndWatch contract for every capacity producer:
    across arbitrary capacity/health churn the advertised ID SET NEVER
    CHANGES — capacity moves ride the Healthy/Unhealthy flag only. A
    deletion would make kubelet evict whatever pod holds the resource,
    turning a transient saturation into an outage."""
    from dpu_operator_tpu.deviceplugin.server import DevicePlugin

    handler, churn = (_fault_producer() if producer == "fault"
                      else _serve_producer())
    resource = (opvars.TPU_RESOURCE_NAME if producer == "fault"
                else opvars.SERVE_RESOURCE_NAME)
    plugin = DevicePlugin(handler, resource=resource)
    baseline = None
    health_values_seen = set()
    for rnd in range(20):
        churn(rnd)
        devs = plugin._snapshot()
        resp = plugin._to_pb_list(devs)
        ids = tuple(sorted(d.ID for d in resp.devices))
        if baseline is None:
            baseline = ids
        assert ids == baseline, \
            f"round {rnd}: advertised id set changed {baseline} -> {ids}"
        health_values_seen.update(d.health for d in resp.devices)
    assert "Unhealthy" in health_values_seen  # churn actually bit
    assert "Healthy" in health_values_seen


def test_serve_slots_handler_clamps_capacity():
    from dpu_operator_tpu.deviceplugin.serve_slots import ServeSlotsHandler
    h = ServeSlotsHandler(lambda: 99, max_slots=3)
    devs = h.get_devices()
    assert sorted(devs) == ["serve-slot-0", "serve-slot-1",
                            "serve-slot-2"]
    assert all(d["healthy"] for d in devs.values())
    h2 = ServeSlotsHandler(lambda: -2, max_slots=3)
    assert not any(d["healthy"] for d in h2.get_devices().values())


def test_scheduler_capacity_feeds_serve_slots():
    """End of the seam: scheduler capacity() -> ServeSlotsHandler ->
    healthy-slot count tracks admissions and completions."""
    from dpu_operator_tpu.deviceplugin.serve_slots import ServeSlotsHandler
    cfg = _harness_config(slots=3, kv_blocks=32, typical_tokens=64)
    sched = serve.Scheduler(cfg)
    handler = ServeSlotsHandler(
        lambda: sched.capacity()["advertisableSlots"], max_slots=3)

    def healthy():
        return sum(1 for d in handler.get_devices().values()
                   if d["healthy"])

    assert healthy() == 3
    sched.submit(serve.Request(rid="c0", prompt_len=8, output_len=48,
                               arrival_s=0.0))
    sched.step()
    assert healthy() == 2
    sched.run()
    assert healthy() == 3


# -- health engine: SLOs, heartbeats, events ----------------------------------


def test_serve_slos_are_standing_objectives():
    names = {s.name for s in slo.EVALUATOR._slos}
    assert {"serve-ttft", "serve-tokens"} <= names


def test_serve_ttft_slo_burns_on_slow_first_tokens():
    fast = (slo.AlertRule("page", (slo.BurnWindow("w1", 10.0, 2.0),
                                   slo.BurnWindow("w2", 30.0, 2.0))),)
    clock = _Clock()
    ev = slo.SloEvaluator(clock=clock)
    for s in slo.serve_slos(rules=fast):
        ev.add(s)
    ev.evaluate()
    for _ in range(40):
        clock.advance(1.0)
        metrics.SERVE_TTFT_SECONDS.observe(
            slo.SERVE_TTFT_SLOW_SECONDS * 3)
        ev.evaluate()
    assert ("serve-ttft", "page") in ev.active_alerts()
    # recovery: fast first tokens flush the windows, alert clears
    for _ in range(80):
        clock.advance(1.0)
        for _ in range(10):
            metrics.SERVE_TTFT_SECONDS.observe(0.01)
        ev.evaluate()
    assert ("serve-ttft", "page") not in ev.active_alerts()


def test_scheduler_runs_under_task_scoped_heartbeat():
    from dpu_operator_tpu.utils.watchdog import Watchdog
    clock = _Clock()
    dog = Watchdog(clock=clock)
    hb = dog.register("serve.scheduler", deadline=30.0, periodic=False)
    sched = serve.Scheduler(_harness_config(), heartbeat=hb)
    sched.submit(serve.Request(rid="h0", prompt_len=4, output_len=4,
                               arrival_s=0.0))
    sched.run()
    # task-scoped: idle after the run is healthy no matter how long
    clock.advance(3600.0)
    stalled, _ = dog.check()
    assert stalled == []
    hb.close()


def test_first_tokens_and_preemptions_are_flight_recorded():
    from dpu_operator_tpu.utils import flight
    flight.RECORDER.clear()
    cfg = _harness_config(slots=1, kv_blocks=8)
    sched = serve.Scheduler(cfg)
    sched.submit(serve.Request(rid="f0", prompt_len=8, output_len=16,
                               slo_class=serve.BATCH, arrival_s=0.0))
    sched.submit(serve.Request(rid="f1", prompt_len=4, output_len=2,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.1))
    sched.run()
    kinds = {(e["name"]) for e in flight.RECORDER.events(kind="serve")}
    assert {"FirstToken", "Preempted", "Completed"} <= kinds
    first = [e for e in flight.RECORDER.events(kind="serve")
             if e["name"] == "FirstToken"]
    assert all("ttft_s" in e["attributes"] for e in first)


# -- /debug/serve + tpuctl ----------------------------------------------------


def test_debug_serve_endpoint_and_tpuctl_render():
    from dpu_operator_tpu import tpuctl
    from dpu_operator_tpu.utils import flight
    from dpu_operator_tpu.utils.metrics import MetricsServer

    sched = serve.Scheduler(_harness_config())
    sched.submit(serve.Request(rid="web0", prompt_len=8, output_len=4,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.0))
    sched.run()
    service = serve.DecodeService(sched)
    server = MetricsServer(host="127.0.0.1", port=0,
                           debug_handlers=service.debug_handlers())
    server.start()
    try:
        snap = flight.fetch(f"127.0.0.1:{server.port}",
                            path="/debug/serve")
    finally:
        server.stop()
    assert snap["completed"] == 1
    assert snap["kv"]["usedBlocks"] == 0
    assert snap["capacity"]["slots"] == 4

    events = [{"kind": "serve", "name": "FirstToken", "ts": 100.0,
               "attributes": {"ttft_s": "0.25"}},
              {"kind": "serve", "name": "FirstToken", "ts": 130.0,
               "attributes": {"ttft_s": "0.75"}},
              {"kind": "serve", "name": "FirstToken", "ts": 10.0,
               "attributes": {"ttft_s": "9.9"}},  # outside the window
              {"kind": "span", "name": "not-serve", "ts": 130.0}]
    view = tpuctl.render_serve(snap, events, now=140.0, window_s=60.0)
    assert view["reachable"] is True
    assert view["ttftSamples"] == 2
    assert view["ttftP50Seconds"] == 0.25
    assert view["ttftP99Seconds"] == 0.75
    assert view["scheduler"]["completed"] == 1


def test_tpuctl_serve_status_graceful_when_unreachable():
    from dpu_operator_tpu import tpuctl

    args = type("A", (), {"cmd": "serve", "action": "status",
                          "metrics_addr": "127.0.0.1:1", "token": "",
                          "window": 60.0, "agent_socket": "",
                          "vsp_socket": "", "daemon_addr": ""})()
    out = tpuctl.run(args)
    assert out["reachable"] is False
    assert out["error"]


# -- DecodeService production shell -------------------------------------------


def test_decode_service_drives_scheduler_and_registers_heartbeat():
    from dpu_operator_tpu.utils import watchdog as wd

    sched = serve.Scheduler(_harness_config())
    service = serve.DecodeService(sched, idle_interval_s=0.01)
    service.start()
    try:
        assert any(h["name"] == "serve.scheduler"
                   for h in wd.WATCHDOG.snapshot())
        sched.submit(serve.Request(rid="svc0", prompt_len=4,
                                   output_len=4, arrival_s=0.0))
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not sched.completed:
            threading.Event().wait(0.01)
        assert sched.completed and sched.completed[0].rid == "svc0"
    finally:
        service.stop()
    assert not any(h["name"] == "serve.scheduler"
                   for h in wd.WATCHDOG.snapshot())


def test_snapshot_is_safe_against_a_concurrent_step_loop():
    """/debug/serve is served from the MetricsServer HTTP thread while
    the DecodeService thread mutates _active/_queues: snapshot() must
    never die with 'dictionary changed size during iteration'."""
    sched = serve.Scheduler(_harness_config(slots=4, kv_blocks=64))
    for i in range(300):
        sched.submit(serve.Request(
            rid=f"cc{i}", prompt_len=8, output_len=4,
            slo_class=serve.INTERACTIVE if i % 3 else serve.BATCH,
            arrival_s=0.005 * i))
    errors: list = []
    done = threading.Event()

    def hammer():
        while not done.is_set():
            try:
                sched.snapshot()
                sched.capacity()
            except Exception as e:  # noqa: BLE001 — the assertion
                errors.append(e)
                return

    t = threading.Thread(target=hammer)
    t.start()
    try:
        sched.run()
    finally:
        done.set()
        t.join(timeout=10)
    assert errors == []
    assert sched.completed_total == 300


# -- the serving bench record -------------------------------------------------


def test_bench_serving_record_shape_and_determinism():
    """The BENCH series contract: >=2 load points each carrying p99
    TTFT, zero leaked blocks everywhere, the continuous-vs-static
    speedup, and bit-identical output across two invocations."""
    kw = dict(seed=SEED, loads=(0.6, 1.1), horizon_s=12.0)
    rec = serve.bench_serving(**kw)
    assert serve.bench_serving(**kw) == rec
    assert len(rec["loads"]) == 2
    for row in rec["loads"].values():
        assert row["ttft_p99_s"] >= row["ttft_p50_s"] >= 0.0
        assert row["kv_blocks_leaked"] == 0
        assert row["tokens_per_s"] > 0
    # the >=1.5x acceptance bound is asserted by
    # test_continuous_beats_static_by_1_5x over a full-length horizon;
    # this short-horizon record must still show a real win
    assert rec["continuous_vs_static"]["speedup"] > 1.0
