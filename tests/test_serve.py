"""Continuous-batching decode service gate (`make serve-check`).

The seeded scheduler harness: two consecutive runs must produce
bit-identical scheduler traces; continuous batching must beat static
batching >=1.5x aggregate tokens/s at the same offered load; an
interactive request admitted under full batch-class load must meet its
TTFT bound via preemption; KV-pool accounting must leak zero blocks
across 500 seeded request lifecycles; and BOTH capacity producers (the
fault gate and the serve-slots handler) must uphold the
zero-spurious-ListAndWatch-deletion contract under churn. Everything is
virtual-clock / seeded-RNG — opslint's chaos-determinism rule covers
the serve marker, so a wall-clock or unseeded-entropy call here fails
lint before it can flake.
"""

import dataclasses
import json
import random
import threading
import time

import pytest

from dpu_operator_tpu.utils import metrics, slo
from dpu_operator_tpu.utils import vars as opvars
from dpu_operator_tpu.workloads import serve
from dpu_operator_tpu.workloads.kv_pool import KvBlockPool, chain_keys

pytestmark = pytest.mark.serve

SEED = 20260804

#: BENCH_r07's CPU-calibrated cost model — prefill-heavy, the regime
#: where whole-prompt prefills measurably explode TTFT at 0.8 load
CALIBRATED = serve.CostModel(decode_base_s=0.0007512,
                             decode_per_seq_s=0.0000835,
                             prefill_per_token_s=0.00026168)


# -- KV block pool ------------------------------------------------------------


def test_pool_allocates_lowest_ids_first_and_reuses_freed():
    pool = KvBlockPool(num_blocks=8, block_size=4)
    assert pool.alloc("a", 3) == [0, 1, 2]
    assert pool.alloc("b", 2) == [3, 4]
    assert pool.free("a") == 3
    # freed blocks go back sorted: the next alloc is deterministic
    assert pool.alloc("c", 4) == [0, 1, 2, 5]
    assert pool.free_blocks() == 2


def test_pool_refuses_overcommit_and_reports_none():
    pool = KvBlockPool(num_blocks=4, block_size=16)
    assert pool.alloc("a", 3) is not None
    assert not pool.can_alloc(2)
    assert pool.alloc("b", 2) is None  # no partial grant
    assert pool.free_blocks() == 1
    assert pool.alloc("b", 1) == [3]


def test_pool_free_is_idempotent_and_unknown_owner_is_noop():
    pool = KvBlockPool(num_blocks=4, block_size=16)
    pool.alloc("a", 2)
    assert pool.free("a") == 2
    assert pool.free("a") == 0
    assert pool.free("ghost") == 0
    assert pool.occupancy() == 0.0


def test_pool_meters_occupancy_and_internal_fragmentation():
    pool = KvBlockPool(num_blocks=10, block_size=10)
    pool.alloc("a", 4)  # 40 slots
    pool.set_used_tokens("a", 25)
    assert pool.occupancy() == pytest.approx(0.4)
    assert pool.internal_fragmentation() == pytest.approx(15 / 40)
    assert metrics.SERVE_KV_BLOCKS.value(state="used") == 4.0
    pool.free("a")
    assert pool.internal_fragmentation() == 0.0
    assert metrics.SERVE_KV_BLOCKS.value(state="used") == 0.0


def test_blocks_for_tokens_is_ceil():
    pool = KvBlockPool(num_blocks=4, block_size=16)
    assert pool.blocks_for_tokens(0) == 0
    assert pool.blocks_for_tokens(1) == 1
    assert pool.blocks_for_tokens(16) == 1
    assert pool.blocks_for_tokens(17) == 2


# -- scheduler: determinism ---------------------------------------------------


def _harness_config(**kw) -> serve.ServeConfig:
    base = dict(slots=4, kv_blocks=64, kv_block_size=16,
                queue_limit=256, ttft_bound_s=1.0)
    base.update(kw)
    return serve.ServeConfig(**base)


def _run_once(seed: int, rate: float = 6.0, horizon: float = 20.0):
    sched = serve.Scheduler(_harness_config(),
                            cost_model=serve.CostModel())
    sched.submit_all(serve.open_loop_arrivals(seed, rate, horizon))
    sched.run()
    return sched


def test_scheduler_trace_bit_identical_across_runs():
    """The acceptance determinism gate: same seed, same config -> the
    scheduler traces (every admit/reject/preempt/decode/complete
    decision) compare EQUAL, and so do the completion timings."""
    a, b = _run_once(SEED), _run_once(SEED)
    assert a.trace == b.trace
    assert [(r.rid, r.finish_s, len(r.tokens)) for r in a.completed] \
        == [(r.rid, r.finish_s, len(r.tokens)) for r in b.completed]
    c = _run_once(SEED + 1)
    assert c.trace != a.trace  # the seed actually drives the trace


def test_idle_scheduler_fast_forwards_to_next_arrival():
    sched = serve.Scheduler(_harness_config())
    sched.submit(serve.Request(rid="late", prompt_len=4, output_len=2,
                               arrival_s=10.0))
    assert sched.step()
    assert sched.now >= 10.0
    sched.run()
    assert sched.completed[0].rid == "late"
    assert sched.step() is False  # drained


# -- scheduler: continuous vs static ------------------------------------------


def test_continuous_beats_static_by_1_5x():
    """The headline: at the same offered load (modeled capacity), the
    iteration-level scheduler sustains >=1.5x the aggregate tokens/s of
    drain-the-whole-batch static batching — mixed output lengths leave
    static's slots idling behind each batch's straggler."""
    cfg = _harness_config(slots=8, kv_blocks=256)
    cm = serve.CostModel()
    peak = cfg.slots / cm.decode_s(cfg.slots)
    arrivals = serve.open_loop_arrivals(
        SEED, rate_rps=peak / 66.0, horizon_s=60.0,
        prompt_lens=(16, 128), output_lens=(4, 128),
        interactive_frac=0.0)
    out = serve.compare_batching(cfg, cm, arrivals)
    assert out["continuous"]["completed"] == len(arrivals)
    assert out["static"]["completed"] == len(arrivals)
    # same requests, same tokens — only the batching policy differs
    assert out["continuous"]["tokens"] == out["static"]["tokens"]
    assert out["speedup"] >= 1.5, out


# -- scheduler: SLO classes and preemption ------------------------------------


def test_interactive_meets_ttft_bound_via_preemption():
    """Full batch-class load (every slot busy, KV pool saturated), then
    an interactive request arrives: batch-class victims are evicted
    (recomputably) and the interactive first token lands within the
    TTFT bound. The victims still complete afterwards with their full
    output — eviction lost no tokens."""
    cfg = _harness_config(slots=2, kv_blocks=16, kv_block_size=16,
                          ttft_bound_s=1.0)
    sched = serve.Scheduler(cfg)
    # two long batch requests hog both slots and 14/16 blocks
    for i in range(2):
        sched.submit(serve.Request(rid=f"hog{i}", prompt_len=48,
                                   output_len=64, slo_class=serve.BATCH,
                                   arrival_s=0.0))
    sched.submit(serve.Request(rid="vip", prompt_len=32, output_len=4,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.5))
    before = metrics.SERVE_PREEMPTIONS.total()
    sched.run()
    assert metrics.SERVE_PREEMPTIONS.total() > before
    assert any(ev[0] == "preempt" for ev in sched.trace)
    done = {r.rid: r for r in sched.completed}
    assert set(done) == {"hog0", "hog1", "vip"}
    vip = done["vip"]
    assert vip.ttft_s is not None and vip.ttft_s <= cfg.ttft_bound_s, \
        vip.ttft_s
    # recomputable eviction: victims kept every generated token
    assert all(len(done[r].tokens) == 64 for r in ("hog0", "hog1"))
    assert sum(done[r].preemptions for r in ("hog0", "hog1")) >= 1
    assert sched.pool.outstanding() == 0


def test_preempted_request_token_stream_is_unchanged():
    """Recompute-on-readmission must splice the stream invisibly: the
    tokens a preempted request ends with equal those of the same
    request served with no interactive pressure at all."""
    def run(with_vip: bool):
        sched = serve.Scheduler(_harness_config(
            slots=1, kv_blocks=8, kv_block_size=16))
        sched.submit(serve.Request(rid="steady", prompt_len=16,
                                   output_len=24,
                                   slo_class=serve.BATCH, arrival_s=0.0))
        if with_vip:
            sched.submit(serve.Request(
                rid="vip", prompt_len=8, output_len=2,
                slo_class=serve.INTERACTIVE, arrival_s=0.1))
        sched.run()
        return {r.rid: r for r in sched.completed}

    calm, stormy = run(False), run(True)
    assert stormy["steady"].preemptions >= 1
    assert stormy["steady"].tokens == calm["steady"].tokens


def test_admission_rejects_when_queue_is_full():
    """Open loop: the world keeps sending after saturation; past the
    per-class queue bound requests are REJECTED and counted — the
    health engine's saturation signal — rather than queued forever."""
    cfg = _harness_config(slots=1, kv_blocks=4, kv_block_size=16,
                          queue_limit=2)
    sched = serve.Scheduler(cfg)
    for i in range(8):
        sched.submit(serve.Request(rid=f"r{i}", prompt_len=8,
                                   output_len=32,
                                   slo_class=serve.BATCH,
                                   arrival_s=0.001 * i))
    before = metrics.SERVE_ADMISSION_REJECTED.total()
    sched.run()
    assert sched.rejected, "queue bound never rejected"
    assert metrics.SERVE_ADMISSION_REJECTED.total() > before
    assert all(r.reject_reason == "queue_full" for r in sched.rejected)
    assert {ev[0] for ev in sched.trace} >= {"reject", "admit",
                                             "complete"}
    # every non-rejected request still completed; nothing leaked
    assert len(sched.completed) + len(sched.rejected) == 8
    assert sched.pool.outstanding() == 0


def test_static_mode_admits_only_into_a_drained_batch():
    sched = serve.Scheduler(_harness_config(slots=2, static=True,
                                            preemption=False))
    for i in range(4):
        sched.submit(serve.Request(rid=f"s{i}", prompt_len=4,
                                   output_len=6, arrival_s=0.0))
    sched.run()
    admits = [ev for ev in sched.trace if ev[0] == "admit"]
    completes = [ev for ev in sched.trace if ev[0] == "complete"]
    assert len(admits) == 4 and len(completes) == 4
    # the second pair admits strictly after BOTH first completions
    second_admit_iter = admits[2][1]
    first_batch_done_iter = max(c[1] for c in completes[:2])
    assert second_admit_iter > first_batch_done_iter


def test_oversize_request_is_rejected_not_wedged():
    """A request whose KV reservation can never fit the pool must be
    rejected at ingest (kv_too_large): left queued it would wedge the
    priority head forever — admission can't satisfy it, and interactive
    priority would even evict innocent running victims first."""
    cfg = _harness_config(slots=2, kv_blocks=8, kv_block_size=16)
    sched = serve.Scheduler(cfg)  # pool holds 128 token slots
    sched.submit(serve.Request(rid="b1", prompt_len=16, output_len=16,
                               slo_class=serve.BATCH, arrival_s=0.0))
    sched.submit(serve.Request(rid="huge", prompt_len=150,
                               output_len=64,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.1))
    sched.submit(serve.Request(rid="b2", prompt_len=8, output_len=8,
                               slo_class=serve.BATCH, arrival_s=0.2))
    steps = sched.run(max_steps=10_000)
    assert steps < 10_000, "scheduler wedged on the oversize request"
    assert {r.rid for r in sched.completed} == {"b1", "b2"}
    (huge,) = sched.rejected
    assert (huge.rid, huge.reject_reason) == ("huge", "kv_too_large")
    # the doomed head never evicted the running victim
    assert sched.completed[0].preemptions == 0 if \
        sched.completed[0].rid == "b1" else True
    assert not any(ev[0] == "preempt" for ev in sched.trace)
    assert sched.pool.outstanding() == 0


def test_real_clock_itl_observes_measured_stall():
    """Under a real clock the serve-tokens SLO must see what actually
    elapsed around the executor — a 3 s decode stall reads as 3 s, not
    as the cost model's ~30 ms."""
    clock = _Clock()

    class StallingExecutor(serve.SimExecutor):
        def step(self, active):
            clock.advance(3.0)
            return super().step(active)

    sched = serve.Scheduler(_harness_config(), clock=clock,
                            executor=StallingExecutor())
    sched.submit(serve.Request(rid="slow", prompt_len=4, output_len=3,
                               arrival_s=0.0))
    before = metrics.SERVE_ITL_SECONDS.count_above(1.0)
    while sched.step():
        pass
    assert len(sched.completed) == 1
    assert metrics.SERVE_ITL_SECONDS.count_above(1.0) >= before + 2


def test_history_limit_bounds_trace_and_results():
    """The production shell caps trace/completed/rejected so a
    long-lived service cannot grow without bound; snapshot totals stay
    monotone across the trim."""
    sched = serve.Scheduler(_harness_config(slots=2))
    sched.history_limit = 8
    for i in range(40):
        sched.submit(serve.Request(rid=f"t{i}", prompt_len=4,
                                   output_len=2, arrival_s=0.01 * i))
    sched.run()
    assert len(sched.trace) <= 8
    assert len(sched.completed) <= 8
    assert sched.completed_total == 40
    assert sched.snapshot()["completed"] == 40


# -- the 500-lifecycle leak gate ----------------------------------------------


def test_kv_pool_never_leaks_across_500_lifecycles():
    """500 seeded request lifecycles — mixed classes, admissions,
    preemptions, completions — and the pool must return to EXACTLY
    zero occupancy with zero outstanding blocks and every accepted
    request completed with its full output."""
    cfg = _harness_config(slots=6, kv_blocks=96, kv_block_size=16,
                          queue_limit=1000)
    sched = serve.Scheduler(cfg)
    rng = random.Random(SEED)
    t = 0.0
    for i in range(500):
        t += rng.expovariate(8.0)
        sched.submit(serve.Request(
            rid=f"life{i}", prompt_len=rng.randint(4, 96),
            output_len=rng.randint(1, 64),
            slo_class=serve.INTERACTIVE if rng.random() < 0.4
            else serve.BATCH,
            arrival_s=t))
    steps = sched.run(max_steps=500_000)
    assert steps < 500_000, "scheduler failed to drain"
    assert len(sched.completed) == 500
    assert all(len(r.tokens) == r.output_len for r in sched.completed)
    assert sched.preemptions > 0  # the storm actually exercised eviction
    assert sched.pool.outstanding() == 0
    assert sched.pool.occupancy() == 0.0
    assert sched.pool.free_blocks() == cfg.kv_blocks
    assert metrics.SERVE_KV_BLOCKS.value(state="used") == 0.0


# -- real tokens through the refactored kernel pair ---------------------------


def _tiny_model():
    import jax

    from dpu_operator_tpu.workloads.model import (TransformerConfig,
                                                  init_params)
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_seq=64)
    return cfg, init_params(jax.random.key(0), cfg)


def test_jax_executor_streams_match_generate():
    """The serve path over the real model: requests interleaved through
    JaxSlotExecutor's per-slot positions — including one forced
    preemption/recompute — must produce token streams identical to the
    fused generate() scan run per request in isolation."""
    import jax
    import numpy as np

    from dpu_operator_tpu.workloads.decode import generate

    cfg, params = _tiny_model()
    specs = [("jA", 7, 0.0, serve.BATCH, 12),
             ("jB", 5, 0.0, serve.BATCH, 9),
             ("jC", 9, 0.05, serve.INTERACTIVE, 6)]
    prompts = {rid: tuple(int(x) for x in np.asarray(
        jax.random.randint(jax.random.key(i + 1), (plen,), 0, cfg.vocab)))
        for i, (rid, plen, _, _, _) in enumerate(specs)}
    # slots=2 with jC interactive forces a preemption of a batch slot
    cfg_s = _harness_config(slots=2, kv_blocks=8, kv_block_size=16)
    sched = serve.Scheduler(
        cfg_s, executor=serve.JaxSlotExecutor(params, cfg,
                                              cfg_s.slots))
    for rid, plen, at, cls, out in specs:
        sched.submit(serve.Request(rid=rid, prompt_len=plen,
                                   output_len=out, slo_class=cls,
                                   arrival_s=at,
                                   prompt=prompts[rid]))
    sched.run()
    done = {r.rid: r for r in sched.completed}
    assert set(done) == {"jA", "jB", "jC"}
    assert sum(r.preemptions for r in done.values()) >= 1
    for rid, plen, _, _, out in specs:
        import jax.numpy as jnp
        want = np.asarray(generate(
            params, cfg, jnp.asarray([prompts[rid]], jnp.int32),
            steps=out))[0].tolist()
        assert done[rid].tokens == want, rid


def test_jax_executor_never_retraces_decode_step():
    import jax.numpy as jnp

    from dpu_operator_tpu.workloads.decode import decode_step

    cfg, params = _tiny_model()
    ex = serve.JaxSlotExecutor(params, cfg, slots=2)
    req = serve.Request(rid="t", prompt_len=4, output_len=8,
                        prompt=(1, 2, 3, 4))
    ex.begin(req, 0)
    ex.step([(0, req)])
    before = decode_step._cache_size()
    for _ in range(5):
        ex.step([(0, req)])
    assert decode_step._cache_size() == before


# -- capacity advertisement: the shared churn regression ----------------------


class _MutableHandler:
    """Raw device handler whose health bits tests flip (the fault
    producer's upstream)."""

    def __init__(self, devices):
        self.devices = devices

    def get_devices(self):
        return {k: dict(v) for k, v in self.devices.items()}


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _fault_producer():
    """The fault gate's judged chip handler over a churning raw feed."""
    from dpu_operator_tpu.faults import FaultEngine, FaultGatedHandler
    clock = _Clock()
    raw = _MutableHandler({f"chip-{i}": {"id": f"chip-{i}",
                                         "healthy": True}
                           for i in range(4)})
    engine = FaultEngine(clock=clock)
    gated = FaultGatedHandler(raw, engine, min_probe_interval=0.0)
    rng = random.Random(SEED)

    def churn(rnd):
        clock.advance(5.0)
        for dev in raw.devices.values():
            dev["healthy"] = rng.random() > 0.3
    return gated, churn


def _serve_producer():
    """The serve-slots handler over a churning (and failing) capacity
    source."""
    from dpu_operator_tpu.deviceplugin.serve_slots import ServeSlotsHandler
    state = {"capacity": 4}

    def capacity():
        if state["capacity"] < 0:
            raise RuntimeError("service unreachable")
        return state["capacity"]

    handler = ServeSlotsHandler(capacity, max_slots=4)
    script = [4, 2, 0, -1, 9, 3, 1, 4, 0, 4]

    def churn(rnd):
        state["capacity"] = script[rnd % len(script)]
    return handler, churn


@pytest.mark.parametrize("producer", ["fault", "serve"])
def test_capacity_churn_emits_zero_spurious_deletions(producer):
    """The shared ListAndWatch contract for every capacity producer:
    across arbitrary capacity/health churn the advertised ID SET NEVER
    CHANGES — capacity moves ride the Healthy/Unhealthy flag only. A
    deletion would make kubelet evict whatever pod holds the resource,
    turning a transient saturation into an outage."""
    from dpu_operator_tpu.deviceplugin.server import DevicePlugin

    handler, churn = (_fault_producer() if producer == "fault"
                      else _serve_producer())
    resource = (opvars.TPU_RESOURCE_NAME if producer == "fault"
                else opvars.SERVE_RESOURCE_NAME)
    plugin = DevicePlugin(handler, resource=resource)
    baseline = None
    health_values_seen = set()
    for rnd in range(20):
        churn(rnd)
        devs = plugin._snapshot()
        resp = plugin._to_pb_list(devs)
        ids = tuple(sorted(d.ID for d in resp.devices))
        if baseline is None:
            baseline = ids
        assert ids == baseline, \
            f"round {rnd}: advertised id set changed {baseline} -> {ids}"
        health_values_seen.update(d.health for d in resp.devices)
    assert "Unhealthy" in health_values_seen  # churn actually bit
    assert "Healthy" in health_values_seen


def test_serve_slots_handler_clamps_capacity():
    from dpu_operator_tpu.deviceplugin.serve_slots import ServeSlotsHandler
    h = ServeSlotsHandler(lambda: 99, max_slots=3)
    devs = h.get_devices()
    assert sorted(devs) == ["serve-slot-0", "serve-slot-1",
                            "serve-slot-2"]
    assert all(d["healthy"] for d in devs.values())
    h2 = ServeSlotsHandler(lambda: -2, max_slots=3)
    assert not any(d["healthy"] for d in h2.get_devices().values())


def test_scheduler_capacity_feeds_serve_slots():
    """End of the seam: scheduler capacity() -> ServeSlotsHandler ->
    healthy-slot count tracks admissions and completions."""
    from dpu_operator_tpu.deviceplugin.serve_slots import ServeSlotsHandler
    cfg = _harness_config(slots=3, kv_blocks=32, typical_tokens=64)
    sched = serve.Scheduler(cfg)
    handler = ServeSlotsHandler(
        lambda: sched.capacity()["advertisableSlots"], max_slots=3)

    def healthy():
        return sum(1 for d in handler.get_devices().values()
                   if d["healthy"])

    assert healthy() == 3
    sched.submit(serve.Request(rid="c0", prompt_len=8, output_len=48,
                               arrival_s=0.0))
    sched.step()
    assert healthy() == 2
    sched.run()
    assert healthy() == 3


# -- health engine: SLOs, heartbeats, events ----------------------------------


def test_serve_slos_are_standing_objectives():
    names = {s.name for s in slo.EVALUATOR._slos}
    assert {"serve-ttft", "serve-tokens"} <= names


def test_serve_ttft_slo_burns_on_slow_first_tokens():
    fast = (slo.AlertRule("page", (slo.BurnWindow("w1", 10.0, 2.0),
                                   slo.BurnWindow("w2", 30.0, 2.0))),)
    clock = _Clock()
    ev = slo.SloEvaluator(clock=clock)
    for s in slo.serve_slos(rules=fast):
        ev.add(s)
    ev.evaluate()
    for _ in range(40):
        clock.advance(1.0)
        metrics.SERVE_TTFT_SECONDS.observe(
            slo.SERVE_TTFT_SLOW_SECONDS * 3)
        ev.evaluate()
    assert ("serve-ttft", "page") in ev.active_alerts()
    # recovery: fast first tokens flush the windows, alert clears
    for _ in range(80):
        clock.advance(1.0)
        for _ in range(10):
            metrics.SERVE_TTFT_SECONDS.observe(0.01)
        ev.evaluate()
    assert ("serve-ttft", "page") not in ev.active_alerts()


def test_scheduler_runs_under_task_scoped_heartbeat():
    from dpu_operator_tpu.utils.watchdog import Watchdog
    clock = _Clock()
    dog = Watchdog(clock=clock)
    hb = dog.register("serve.scheduler", deadline=30.0, periodic=False)
    sched = serve.Scheduler(_harness_config(), heartbeat=hb)
    sched.submit(serve.Request(rid="h0", prompt_len=4, output_len=4,
                               arrival_s=0.0))
    sched.run()
    # task-scoped: idle after the run is healthy no matter how long
    clock.advance(3600.0)
    stalled, _ = dog.check()
    assert stalled == []
    hb.close()


def test_first_tokens_and_preemptions_are_flight_recorded():
    from dpu_operator_tpu.utils import flight
    flight.RECORDER.clear()
    cfg = _harness_config(slots=1, kv_blocks=8)
    sched = serve.Scheduler(cfg)
    sched.submit(serve.Request(rid="f0", prompt_len=8, output_len=16,
                               slo_class=serve.BATCH, arrival_s=0.0))
    sched.submit(serve.Request(rid="f1", prompt_len=4, output_len=2,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.1))
    sched.run()
    kinds = {(e["name"]) for e in flight.RECORDER.events(kind="serve")}
    assert {"FirstToken", "Preempted", "Completed"} <= kinds
    first = [e for e in flight.RECORDER.events(kind="serve")
             if e["name"] == "FirstToken"]
    assert all("ttft_s" in e["attributes"] for e in first)


# -- /debug/serve + tpuctl ----------------------------------------------------


def test_debug_serve_endpoint_and_tpuctl_render():
    from dpu_operator_tpu import tpuctl
    from dpu_operator_tpu.utils import flight
    from dpu_operator_tpu.utils.metrics import MetricsServer

    sched = serve.Scheduler(_harness_config())
    sched.submit(serve.Request(rid="web0", prompt_len=8, output_len=4,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.0))
    sched.run()
    service = serve.DecodeService(sched)
    server = MetricsServer(host="127.0.0.1", port=0,
                           debug_handlers=service.debug_handlers())
    server.start()
    try:
        snap = flight.fetch(f"127.0.0.1:{server.port}",
                            path="/debug/serve")
    finally:
        server.stop()
    assert snap["completed"] == 1
    assert snap["kv"]["usedBlocks"] == 0
    assert snap["capacity"]["slots"] == 4

    events = [{"kind": "serve", "name": "FirstToken", "ts": 100.0,
               "attributes": {"ttft_s": "0.25"}},
              {"kind": "serve", "name": "FirstToken", "ts": 130.0,
               "attributes": {"ttft_s": "0.75"}},
              {"kind": "serve", "name": "FirstToken", "ts": 10.0,
               "attributes": {"ttft_s": "9.9"}},  # outside the window
              {"kind": "span", "name": "not-serve", "ts": 130.0}]
    view = tpuctl.render_serve(snap, events, now=140.0, window_s=60.0)
    assert view["reachable"] is True
    assert view["ttftSamples"] == 2
    assert view["ttftP50Seconds"] == 0.25
    assert view["ttftP99Seconds"] == 0.75
    assert view["scheduler"]["completed"] == 1


def test_tpuctl_serve_status_graceful_when_unreachable():
    from dpu_operator_tpu import tpuctl

    args = type("A", (), {"cmd": "serve", "action": "status",
                          "metrics_addr": "127.0.0.1:1", "token": "",
                          "window": 60.0, "agent_socket": "",
                          "vsp_socket": "", "daemon_addr": ""})()
    out = tpuctl.run(args)
    assert out["reachable"] is False
    assert out["error"]


# -- DecodeService production shell -------------------------------------------


def test_decode_service_drives_scheduler_and_registers_heartbeat():
    from dpu_operator_tpu.utils import watchdog as wd

    sched = serve.Scheduler(_harness_config())
    service = serve.DecodeService(sched, idle_interval_s=0.01)
    service.start()
    try:
        assert any(h["name"] == "serve.scheduler"
                   for h in wd.WATCHDOG.snapshot())
        sched.submit(serve.Request(rid="svc0", prompt_len=4,
                                   output_len=4, arrival_s=0.0))
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not sched.completed:
            threading.Event().wait(0.01)
        assert sched.completed and sched.completed[0].rid == "svc0"
    finally:
        service.stop()
    assert not any(h["name"] == "serve.scheduler"
                   for h in wd.WATCHDOG.snapshot())


def test_snapshot_is_safe_against_a_concurrent_step_loop():
    """/debug/serve is served from the MetricsServer HTTP thread while
    the DecodeService thread mutates _active/_queues: snapshot() must
    never die with 'dictionary changed size during iteration'."""
    sched = serve.Scheduler(_harness_config(slots=4, kv_blocks=64))
    for i in range(300):
        sched.submit(serve.Request(
            rid=f"cc{i}", prompt_len=8, output_len=4,
            slo_class=serve.INTERACTIVE if i % 3 else serve.BATCH,
            arrival_s=0.005 * i))
    errors: list = []
    done = threading.Event()

    def hammer():
        while not done.is_set():
            try:
                sched.snapshot()
                sched.capacity()
            except Exception as e:  # noqa: BLE001 — the assertion
                errors.append(e)
                return

    t = threading.Thread(target=hammer)
    t.start()
    try:
        sched.run()
    finally:
        done.set()
        t.join(timeout=10)
    assert errors == []
    assert sched.completed_total == 300


# -- KV pool: prefix sharing + copy-on-write ----------------------------------


def _shared_pool(**kw):
    base = dict(num_blocks=16, block_size=4, sharing=True)
    base.update(kw)
    return KvBlockPool(**base)


def test_chain_keys_match_only_on_identical_prefixes():
    bs = 4
    a = chain_keys((1, 2, 3, 4, 5, 6, 7, 8, 9), bs)
    b = chain_keys((1, 2, 3, 4, 5, 6, 7, 8, 9), bs)
    c = chain_keys((1, 2, 3, 4, 9, 9, 9, 9), bs)
    assert a == b and len(a) == 3
    assert a[0] == c[0]            # shared first block
    assert a[1] != c[1]            # diverged second block
    # a partial tail never matches a full block with the same leading
    # content (length is folded into the tail key)
    d = chain_keys((1, 2, 3), bs)
    e = chain_keys((1, 2, 3, 4), bs)
    assert d[0] != e[0]


def test_map_prefix_shares_blocks_and_free_refcounts_down():
    pool = _shared_pool()
    prompt = (1, 2, 3, 4, 5, 6, 7, 8)          # two full blocks
    keys = chain_keys(prompt, 4)
    assert pool.alloc("a", 2) == [0, 1]
    assert pool.register_prefix("a", keys, len(prompt)) == 2
    assert pool.map_prefix("b", keys) == 2
    assert pool.blocks_of("b") == [0, 1]       # the SAME physical blocks
    assert pool.shared_blocks() == 2
    assert pool.outstanding() == 2             # physically, still two
    assert pool.logical_blocks() == 4          # what no-sharing would pay
    # first free only decrements; blocks stay allocated and indexed
    assert pool.free("a") == 0
    assert pool.outstanding() == 2
    assert pool.map_prefix("c", keys) == 2     # still mappable via b
    assert pool.free("b") == 0
    assert pool.free("c") == 2                 # last reference drains
    assert pool.outstanding() == 0
    assert pool.free_blocks() == 16
    # index died with the blocks: a fresh mapper gets nothing
    assert pool.probe_prefix(keys) == 0


def test_shared_block_never_handed_out_while_referenced():
    pool = _shared_pool(num_blocks=4)
    keys = chain_keys((1, 2, 3, 4), 4)
    pool.alloc("a", 1)
    pool.register_prefix("a", keys, 4)
    pool.map_prefix("b", keys)
    pool.free("a")                              # b still references block 0
    grabbed = pool.alloc("c", 3)
    assert grabbed is not None and 0 not in grabbed
    assert pool.alloc("d", 1) is None           # block 0 is NOT free
    pool.free("b")
    assert pool.alloc("d", 1) == [0]            # now it is
    pool.free("c"), pool.free("d")
    assert pool.outstanding() == 0


def test_divergent_write_copies_exactly_once():
    pool = _shared_pool()
    prompt = (1, 2, 3, 4, 5, 6)                # block 0 full, block 1 tail
    keys = chain_keys(prompt, 4)
    pool.alloc("a", 2)
    pool.register_prefix("a", keys, len(prompt))
    # a's own generated tokens land PAST the tail key's coverage: no
    # copy, and the key stays published
    assert pool.write_token("a", 6) is False
    assert pool.probe_prefix(keys) == 2
    assert pool.map_prefix("b", keys) == 2
    before = pool.cow_copies
    # b's first generated token writes into the shared tail block ->
    # copy-on-write, exactly once; the original keeps serving a
    assert pool.write_token("b", 6) is True
    assert pool.cow_copies == before + 1
    assert pool.blocks_of("b")[1] != pool.blocks_of("a")[1]
    assert pool.blocks_of("b")[0] == pool.blocks_of("a")[0]
    # the copy is exclusive: b's further writes never copy again
    assert pool.write_token("b", 7) is False
    assert pool.cow_copies == before + 1
    pool.free("a"), pool.free("b")
    assert pool.outstanding() == 0


def test_write_inside_key_coverage_unpublishes_exclusive_block():
    pool = _shared_pool()
    prompt = (1, 2, 3, 4)
    keys = chain_keys(prompt, 4)
    pool.alloc("a", 1)
    pool.register_prefix("a", keys, 4)
    assert pool.probe_prefix(keys) == 1
    # an exclusive write INSIDE the covered slots diverges the content
    # from its key: the block must leave the index
    assert pool.write_token("a", 2) is False
    assert pool.probe_prefix(keys) == 0
    pool.free("a")


def test_cow_with_exhausted_pool_returns_none():
    pool = _shared_pool(num_blocks=2)
    keys = chain_keys((1, 2, 3, 4), 4)
    pool.alloc("a", 1)
    pool.register_prefix("a", keys, 4)
    pool.map_prefix("b", keys)
    pool.alloc("c", 1)                          # pool now full
    assert pool.write_token("b", 3) is None     # copy needed, no room
    pool.free("c")
    assert pool.write_token("b", 3) is True     # headroom -> copy lands
    pool.free("a"), pool.free("b")
    assert pool.outstanding() == 0


def test_refcount_invariants_under_seeded_hammering():
    """Seeded storm of map/register/write/free against a small pool:
    refcounts never go negative (free is idempotent), the free list
    never contains a referenced block, and full drain leaves the pool
    pristine."""
    pool = _shared_pool(num_blocks=12, block_size=4)
    rng = random.Random(SEED)
    prompts = [tuple(rng.randrange(100) for _ in range(rng.randint(4, 12)))
               for _ in range(4)]
    live: dict = {}
    for i in range(400):
        op = rng.random()
        if op < 0.5 and len(live) < 5:
            rid = f"h{i}"
            prompt = prompts[rng.randrange(len(prompts))]
            keys = chain_keys(prompt, 4)
            need = pool.blocks_for_tokens(len(prompt) + 4)
            mapped = pool.map_prefix(rid, keys)
            if pool.alloc(rid, need - mapped) is None:
                pool.free(rid)
                continue
            pool.register_prefix(rid, keys, len(prompt))
            live[rid] = len(prompt)
        elif live:
            rid = rng.choice(sorted(live))
            if op < 0.75:
                pool.write_token(rid, live[rid])   # divergence point
            else:
                pool.free(rid)
                pool.free(rid)                     # idempotent re-free
                del live[rid]
        # invariant: nothing on the free list is referenced
        assert not (set(pool._free) & set(pool._refs)), i
        assert all(r >= 1 for r in pool._refs.values()), i
    for rid in sorted(live):
        pool.free(rid)
    assert pool.outstanding() == 0
    assert pool.free_blocks() == 12
    assert pool._refs == {} and pool._index == {} and \
        pool._block_key == {}


# -- chunked prefill: the TTFT-under-load gate --------------------------------


def _load_arrivals(slots, load, horizon=60.0, seed=0):
    """Arrivals at *load* x the modeled capacity of a *slots*-wide
    scheduler — the same capacity model (and seed 0) the BENCH series
    uses, so the gate argues about the exact workload the record
    publishes."""
    prompt_mean, output_mean = (16 + 128) / 2.0, (8 + 128) / 2.0
    per_req = (CALIBRATED.prefill_s(prompt_mean)
               + output_mean * CALIBRATED.decode_s(slots) / slots)
    return serve.open_loop_arrivals(seed, load / per_req, horizon)


def test_chunked_prefill_bounds_ttft_p99_at_0_8_load():
    """THE acceptance gate: at 0.8 offered load on the calibrated cost
    model, whole-prompt prefill explodes TTFT p99 into seconds
    (BENCH_r07 measured 5.19 s); the chunked scheduler must come in
    >=5x lower on the SAME arrivals, hold p99 under the ~1 s bound the
    >=5x-over-5.19s wire gate implies even at its OWN (larger) 0.8
    offered load, and give up no throughput. Everything is virtual-
    time deterministic — these numbers are exact, not statistics."""
    legacy = serve.ServeConfig()                 # the r07 shape
    arrivals = _load_arrivals(legacy.slots, 0.8)
    base = serve.run_open_loop(legacy, CALIBRATED,
                               [r.fresh_copy() for r in arrivals])
    assert base["ttft_p99_s"] > 2.0, \
        "baseline lost its pathology; the gate would prove nothing"
    chunked = serve.chunked_config(CALIBRATED)
    same = serve.run_open_loop(chunked, CALIBRATED,
                               [r.fresh_copy() for r in arrivals])
    assert same["ttft_p99_s"] <= base["ttft_p99_s"] / 5.0, (base, same)
    own = serve.run_open_loop(chunked, CALIBRATED,
                              _load_arrivals(chunked.slots, 0.8))
    assert own["ttft_p99_s"] <= 5.19 / 5.0, own
    assert own["tokens_per_s"] >= base["tokens_per_s"], (base, own)
    for out in (same, own):
        assert out["kv_blocks_leaked"] == 0
        assert out["prefill_chunks"] > 0


def test_chunked_budget_bounds_itl():
    """The budget is the ITL bound's mechanism: even with a queue of
    long prompts prefilling, no iteration advances by more than
    decode + prefill_s(budget) — which the default budget sizes under
    the 0.05 s histogram bucket, so not one observation may land
    above it."""
    cfg = serve.chunked_config(CALIBRATED, slots=4, kv_blocks=256)
    worst = (CALIBRATED.decode_s(cfg.slots)
             + CALIBRATED.prefill_s(cfg.prefill_chunk_tokens))
    assert worst <= 0.05, "budget no longer sized for the ITL bound"
    sched = serve.Scheduler(cfg, cost_model=CALIBRATED)
    sched.submit(serve.Request(rid="d0", prompt_len=8, output_len=64,
                               slo_class=serve.BATCH, arrival_s=0.0))
    for i in range(3):
        sched.submit(serve.Request(rid=f"long{i}", prompt_len=500,
                                   output_len=4, slo_class=serve.BATCH,
                                   arrival_s=0.2))
    before = metrics.SERVE_ITL_SECONDS.count_above(0.05)
    sched.run()
    assert metrics.SERVE_ITL_SECONDS.count_above(0.05) == before
    assert sched.prefill_chunks_total >= 3 * (500 //
                                              cfg.prefill_chunk_tokens)
    assert len(sched.completed) == 4


def test_chunked_trace_is_bit_identical_across_runs():
    def run():
        cfg = serve.chunked_config(CALIBRATED, slots=8, kv_blocks=128)
        sched = serve.Scheduler(cfg, cost_model=CALIBRATED)
        sched.submit_all(serve.prefix_heavy_arrivals(SEED, 12.0, 15.0))
        sched.run()
        return sched
    a, b = run(), run()
    assert a.trace == b.trace
    assert [(r.rid, r.finish_s, r.tokens) for r in a.completed] \
        == [(r.rid, r.finish_s, r.tokens) for r in b.completed]


def test_chunked_tokens_identical_to_atomic_prefill():
    """Chunking only reschedules WHEN prefill work happens — every
    completed request's token stream must equal the legacy atomic
    scheduler's for the same arrivals."""
    def run(chunk_tokens):
        cfg = serve.ServeConfig(slots=4, kv_blocks=128,
                                prefill_chunk_tokens=chunk_tokens)
        sched = serve.Scheduler(cfg, cost_model=CALIBRATED)
        sched.submit_all(serve.open_loop_arrivals(SEED, 10.0, 10.0))
        sched.run()
        return {r.rid: r.tokens for r in sched.completed}
    atomic = run(0)
    for budget in (32, 64, 200):
        assert run(budget) == atomic, budget


def test_chunk_aware_preemption_accounts_discarded_tokens():
    """An interactive arrival evicting a victim caught MID-PREFILL
    must charge the victim's chunk progress as discarded work, and the
    victim must still complete with an unchanged stream."""
    cfg = serve.ServeConfig(slots=1, kv_blocks=32, kv_block_size=16,
                            prefill_chunk_tokens=16)
    sched = serve.Scheduler(cfg, cost_model=CALIBRATED)
    sched.submit(serve.Request(rid="victim", prompt_len=200,
                               output_len=4, slo_class=serve.BATCH,
                               arrival_s=0.0))
    sched.submit(serve.Request(rid="vip", prompt_len=8, output_len=2,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.01))
    before = metrics.SERVE_PREFILL_CHUNK_TOKENS.value(
        outcome="discarded")
    sched.run()
    assert sched.prefill_tokens_discarded > 0
    assert metrics.SERVE_PREFILL_CHUNK_TOKENS.value(
        outcome="discarded") >= before + sched.prefill_tokens_discarded
    preempts = [ev for ev in sched.trace if ev[0] == "preempt"]
    assert preempts and preempts[0][4] == "prefill" \
        and preempts[0][5] > 0
    done = {r.rid: r for r in sched.completed}
    assert set(done) == {"victim", "vip"}
    assert len(done["victim"].tokens) == 4
    assert sched.pool.outstanding() == 0


# -- prefix sharing through the scheduler -------------------------------------


def test_prefix_sharing_cuts_peak_kv_occupancy():
    """The serve-check sharing gate: on the prefix-heavy mix, peak
    physical KV occupancy with sharing is measurably below the
    no-sharing baseline, with zero blocks leaked and the shared-block
    counter proving the mechanism (not workload luck) did it."""
    out = serve.bench_prefix_sharing(seed=SEED, cost_model=CALIBRATED)
    assert out["occupancy_max_with"] <= out["occupancy_max_without"] \
        - 0.1, out
    assert out["kv_blocks_shared"] > 0
    assert out["with_sharing"]["kv_blocks_leaked"] == 0
    assert out["without_sharing"]["kv_blocks_leaked"] == 0
    # the capacity win is allowed to SHOW (sharing admits requests the
    # saturated baseline rejected) but never to lose work
    assert out["with_sharing"]["completed"] \
        >= out["without_sharing"]["completed"]
    assert out["with_sharing"]["rejected"] \
        <= out["without_sharing"]["rejected"]
    assert out["with_sharing"]["kv_prefix_block_hits"] > 0


def test_identical_prompts_trigger_cow_through_scheduler():
    """Two requests with the SAME full prompt: the second maps every
    block including the partial tail, and its first generated token —
    the divergence — copies that tail exactly once."""
    prompt = tuple(range(24))                   # 1.5 blocks of 16
    cfg = serve.ServeConfig(slots=2, kv_blocks=16, kv_block_size=16,
                            prefix_sharing=True,
                            prefill_chunk_tokens=64)
    sched = serve.Scheduler(cfg, cost_model=CALIBRATED)
    sched.submit(serve.Request(rid="orig", prompt_len=len(prompt),
                               output_len=24, slo_class=serve.BATCH,
                               arrival_s=0.0, prompt=prompt))
    # arrival while orig is still RUNNING (just past its ~6 ms prefill:
    # registration happens at prefill completion, and a completed orig
    # would have drained its blocks — and the index — already)
    sched.submit(serve.Request(rid="dup", prompt_len=len(prompt),
                               output_len=8, slo_class=serve.BATCH,
                               arrival_s=0.007, prompt=prompt))
    sched.run()
    assert {r.rid for r in sched.completed} == {"orig", "dup"}
    dup = next(r for r in sched.completed if r.rid == "dup")
    assert dup.shared_tokens == len(prompt)     # tail mapped too
    assert sched.pool.cow_copies == 1
    assert sched.pool.outstanding() == 0
    # sharing is invisible in the streams
    sim = serve.SimExecutor()
    for r in sched.completed:
        assert r.tokens == [sim._token(r, n)
                            for n in range(len(r.tokens))]


def test_kv_pool_never_leaks_across_500_lifecycles_with_sharing():
    """The 500-lifecycle zero-leak sweep, now with sharing AND chunked
    prefill on over prefix-heavy traffic: occupancy returns to exactly
    zero, the prefix index drains with its blocks, and every accepted
    request still completes in full."""
    cfg = serve.chunked_config(CALIBRATED, slots=6, kv_blocks=96,
                               kv_block_size=16, queue_limit=1000)
    sched = serve.Scheduler(cfg, cost_model=CALIBRATED)
    rng = random.Random(SEED)
    prefixes = [tuple(rng.randrange(1000) for _ in range(64))
                for _ in range(3)]
    t = 0.0
    for i in range(500):
        t += rng.expovariate(8.0)
        tail = tuple(rng.randrange(1000)
                     for _ in range(rng.randint(1, 48)))
        prompt = prefixes[rng.randrange(3)] + tail
        sched.submit(serve.Request(
            rid=f"life{i}", prompt_len=len(prompt),
            output_len=rng.randint(1, 48),
            slo_class=serve.INTERACTIVE if rng.random() < 0.4
            else serve.BATCH,
            arrival_s=t, prompt=prompt))
    steps = sched.run(max_steps=500_000)
    assert steps < 500_000, "scheduler failed to drain"
    assert len(sched.completed) == 500
    assert all(len(r.tokens) == r.output_len for r in sched.completed)
    assert sched.pool.prefix_block_hits > 0     # sharing actually fired
    assert sched.pool.outstanding() == 0
    assert sched.pool.occupancy() == 0.0
    assert sched.pool.free_blocks() == cfg.kv_blocks
    assert sched.pool._refs == {} and sched.pool._index == {}
    assert metrics.SERVE_KV_BLOCKS.value(state="used") == 0.0


# -- chunked prefill through the real kernels ---------------------------------


def test_jax_executor_chunked_streams_match_generate():
    """The serve path over the real model WITH chunked prefill:
    budget-sized chunks through decode.prefill_chunk, interleaved with
    decode iterations and a forced preemption, must produce token
    streams identical to the fused generate() scan — across two
    different chunk budgets."""
    import jax
    import numpy as np

    from dpu_operator_tpu.workloads.decode import generate

    cfg, params = _tiny_model()
    specs = [("cA", 11, 0.0, serve.BATCH, 10),
             ("cB", 7, 0.0, serve.BATCH, 8),
             ("cC", 9, 0.05, serve.INTERACTIVE, 5)]
    prompts = {rid: tuple(int(x) for x in np.asarray(
        jax.random.randint(jax.random.key(i + 1), (plen,), 0, cfg.vocab)))
        for i, (rid, plen, _, _, _) in enumerate(specs)}
    import jax.numpy as jnp
    want = {rid: np.asarray(generate(
        params, cfg, jnp.asarray([prompts[rid]], jnp.int32),
        steps=out))[0].tolist()
        for rid, _, _, _, out in specs}
    for budget in (4, 6):
        cfg_s = serve.ServeConfig(slots=2, kv_blocks=8, kv_block_size=16,
                                  prefill_chunk_tokens=budget)
        ex = serve.JaxSlotExecutor(params, cfg, cfg_s.slots,
                                   chunk_tokens=budget)
        sched = serve.Scheduler(cfg_s, executor=ex)
        for rid, plen, at, cls, out in specs:
            sched.submit(serve.Request(rid=rid, prompt_len=plen,
                                       output_len=out, slo_class=cls,
                                       arrival_s=at,
                                       prompt=prompts[rid]))
        sched.run()
        done = {r.rid: r for r in sched.completed}
        assert set(done) == {"cA", "cB", "cC"}
        assert sum(r.preemptions for r in done.values()) >= 1
        for rid in want:
            assert done[rid].tokens == want[rid], (budget, rid)


def test_jax_chunked_prefill_never_retraces_across_chunk_fills():
    import jax.numpy as jnp

    from dpu_operator_tpu.workloads.decode import prefill_chunk

    cfg, params = _tiny_model()
    ex = serve.JaxSlotExecutor(params, cfg, slots=2, chunk_tokens=8)
    req = serve.Request(rid="nt", prompt_len=13, output_len=2,
                        prompt=tuple(range(1, 14)))
    assert ex.prefill_chunk(req, 0, 0, 8) is None
    before = prefill_chunk._cache_size()
    # different fills (5), different slot (1), different offsets — all
    # traced values, zero recompiles
    assert ex.prefill_chunk(req, 0, 8, 5) is not None
    req2 = serve.Request(rid="nt2", prompt_len=6, output_len=2,
                         prompt=tuple(range(2, 8)))
    assert ex.prefill_chunk(req2, 1, 0, 6) is not None
    assert prefill_chunk._cache_size() == before


# -- streaming HTTP ingress ---------------------------------------------------


def _read_ndjson_stream(host, port, body, headers=None):
    import http.client
    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        conn.request("POST", "/v1/generate", json.dumps(body), hdrs)
        resp = conn.getresponse()
        assert resp.status == 200, resp.read()
        assert resp.getheader("Transfer-Encoding") == "chunked"
        lines = []
        buf = b""
        while True:
            piece = resp.read(64)
            if not piece:
                break
            buf += piece
        for line in buf.decode().splitlines():
            if line.strip():
                lines.append(json.loads(line))
        return lines
    finally:
        conn.close()


def test_streaming_ingress_one_token_per_chunk_and_trace_adoption():
    """The wire seam end-to-end: a client POSTs with a W3C traceparent,
    reads a CHUNKED response carrying one token object per flush plus a
    terminal done record, the serve.request span lands in the client's
    trace, and wire-level TTFT is observed."""
    from dpu_operator_tpu.utils import flight, tracing

    sched = serve.Scheduler(_harness_config(slots=2,
                                            kv_blocks=32))
    service = serve.DecodeService(sched, idle_interval_s=0.01)
    service.start()
    port = service.start_http()
    flight.RECORDER.clear()
    trace_id = tracing.new_trace_id()
    parent = f"00-{trace_id}-{tracing.new_span_id()}-01"
    wire_before = metrics.SERVE_WIRE_TTFT_SECONDS.count
    try:
        lines = _read_ndjson_stream(
            "127.0.0.1", port,
            {"rid": "wire0", "prompt_len": 8, "output_len": 5,
             "slo_class": "interactive"},
            headers={"traceparent": parent})
    finally:
        service.stop()
    tokens = [ln["token"] for ln in lines if "token" in ln]
    assert len(tokens) == 5
    assert lines[-1] == {"done": True, "tokens": 5}
    # the scheduler generated exactly this stream
    done = sched.completed[0]
    assert done.rid == "wire0" and done.tokens == tokens
    assert metrics.SERVE_WIRE_TTFT_SECONDS.count == wire_before + 1
    spans = [e for e in flight.RECORDER.events(kind="span")
             if e["name"] == "serve.request"]
    assert spans and spans[0]["trace_id"] == trace_id


def test_admit_clamps_prefix_mapping_to_the_reservation():
    """Review regression: a request whose DECLARED lengths undershoot
    its prompt ids must not map more indexed blocks than its
    reservation — pool.alloc(rid, negative) would kill the step."""
    prompt = tuple(range(64))                   # 4 full blocks of 16
    cfg = serve.ServeConfig(slots=2, kv_blocks=16, kv_block_size=16,
                            prefix_sharing=True,
                            prefill_chunk_tokens=64)
    sched = serve.Scheduler(cfg, cost_model=CALIBRATED)
    sched.submit(serve.Request(rid="full", prompt_len=64, output_len=16,
                               arrival_s=0.0, prompt=prompt))
    # lies about its length: 4-token prompt_len over 64 prompt ids —
    # reservation is 1 block, the index holds 4 matching keys
    sched.submit(serve.Request(rid="liar", prompt_len=4, output_len=4,
                               arrival_s=0.01, prompt=prompt))
    sched.run()
    assert {r.rid for r in sched.completed} == {"full", "liar"}
    liar = next(r for r in sched.completed if r.rid == "liar")
    assert liar.shared_tokens <= 4
    assert sched.pool.outstanding() == 0


def test_poison_request_is_excised_not_retried():
    """Review regression: a request the executor chokes on (a
    prompt-less submit against the JAX executor contract, simulated
    here) is FAILED — slot and blocks freed, stream told, trace notes
    it — and everything behind it still completes. Left queued it
    would re-raise every iteration and wedge the service."""
    class ChokingExecutor(serve.SimExecutor):
        def begin(self, req, slot):
            if req.rid == "poison":
                raise ValueError("no prompt ids")
            return super().begin(req, slot)

        def prefill_chunk(self, req, slot, offset, n):
            if req.rid == "poison":
                raise ValueError("no prompt ids")
            return super().prefill_chunk(req, slot, offset, n)

    before = metrics.SWALLOWED_ERRORS.value(site="serve.executor")
    for chunk_tokens in (0, 32):               # legacy AND chunked path
        sched = serve.Scheduler(
            _harness_config(prefill_chunk_tokens=chunk_tokens),
            executor=ChokingExecutor())
        sched.submit(serve.Request(rid="poison", prompt_len=4,
                                   output_len=2, arrival_s=0.0))
        sched.submit(serve.Request(rid="good", prompt_len=4,
                                   output_len=3, arrival_s=0.0))
        steps = sched.run(max_steps=10_000)
        assert steps < 10_000, "poison request wedged the scheduler"
        assert [r.rid for r in sched.completed] == ["good"]
        (poison,) = sched.failed
        assert poison.reject_reason == "executor_error"
        assert any(ev[0] == "fail" for ev in sched.trace)
        assert sched.pool.outstanding() == 0
    assert metrics.SWALLOWED_ERRORS.value(site="serve.executor") \
        == before + 2


def test_duplicate_rid_is_rejected_while_the_first_is_live():
    """Review regression: pool owners are keyed by rid, so a second
    live request under the same id would merge both requests' block
    accounting (and the first completion would free BOTH). Ingest
    rejects the duplicate; the id becomes reusable after the original
    finishes."""
    sched = serve.Scheduler(_harness_config())
    sched.submit(serve.Request(rid="dup", prompt_len=8, output_len=32,
                               arrival_s=0.0))
    sched.submit(serve.Request(rid="dup", prompt_len=8, output_len=4,
                               arrival_s=0.001))
    sched.run()
    assert len(sched.completed) == 1
    (second,) = sched.rejected
    assert second.reject_reason == "duplicate_rid"
    assert sched.pool.outstanding() == 0
    # after completion the id is free again
    sched.submit(serve.Request(rid="dup", prompt_len=8, output_len=2,
                               arrival_s=sched.now))
    sched.run()
    assert len(sched.completed) == 2


def test_ingress_coerces_prompt_ids_or_400s():
    """Review regression: a non-numeric prompt element must 400 at the
    wire, not detonate chain_keys inside the scheduler loop."""
    import http.client
    sched = serve.Scheduler(_harness_config())
    service = serve.DecodeService(sched, idle_interval_s=0.01)
    service.start()
    port = service.start_http()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/v1/generate",
                     json.dumps({"output_len": 2,
                                 "prompt": ["a", "b"]}),
                     {"Content-Type": "application/json"})
        assert conn.getresponse().status == 400
        conn.close()
        # numeric strings coerce instead of failing
        lines = _read_ndjson_stream(
            "127.0.0.1", port,
            {"rid": "coerce", "output_len": 2, "prompt": ["3", "4"]})
    finally:
        service.stop()
    assert lines[-1] == {"done": True, "tokens": 2}
    assert sched.completed[0].prompt == (3, 4)


def test_cow_exhaustion_proceeds_uncopied_instead_of_livelocking():
    """Review regression: identical-prompt interactive requests admit
    with fresh=0 blocks, so the pool can be FULL when their first
    divergent write needs a CoW block — a stalled token would hold
    blocks forever with nothing preemptible (livelock). The write
    proceeds uncopied (trace: cow_uncopied) and everything drains."""
    prompt = tuple(range(24))
    cfg = serve.ServeConfig(slots=4, kv_blocks=8, kv_block_size=16,
                            prefix_sharing=True,
                            prefill_chunk_tokens=64, queue_limit=16)
    sched = serve.Scheduler(cfg, cost_model=CALIBRATED)
    # orig 2 blocks + hog 5 blocks + dup's 1 fresh (its other 2 are
    # MAPPED) = 8/8: the pool is exactly full when dup's divergent
    # write into the shared tail block wants its CoW copy
    sched.submit(serve.Request(rid="orig", prompt_len=24, output_len=8,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.0, prompt=prompt))
    sched.submit(serve.Request(rid="hog", prompt_len=60, output_len=20,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.01))
    sched.submit(serve.Request(rid="dup", prompt_len=24, output_len=24,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.02, prompt=prompt))
    steps = sched.run(max_steps=50_000)
    assert steps < 50_000, "share-stalled batch livelocked"
    assert len(sched.completed) == 3
    assert any(ev[0] == "cow_uncopied" for ev in sched.trace), \
        "construction no longer reaches the exhausted-CoW branch"
    assert sched.pool.outstanding() == 0


def test_contract_breaching_final_chunk_fails_request_not_leaks():
    """Review regression: prompt ids outliving the declared lengths
    (internal-API misuse) make the 'final' chunk return no token; the
    request must be FAILED — not stranded in _active leaking its slot
    and blocks."""
    import jax
    cfg, params = _tiny_model()
    ex = serve.JaxSlotExecutor(params, cfg, slots=2, chunk_tokens=8)
    sched = serve.Scheduler(
        serve.ServeConfig(slots=2, kv_blocks=8, kv_block_size=16,
                          prefill_chunk_tokens=8), executor=ex)
    sched.submit(serve.Request(rid="liar", prompt_len=4, output_len=2,
                               arrival_s=0.0,
                               prompt=tuple(range(1, 9))))  # 8 ids
    sched.submit(serve.Request(rid="good", prompt_len=4, output_len=2,
                               arrival_s=0.0,
                               prompt=(1, 2, 3, 4)))
    steps = sched.run(max_steps=10_000)
    assert steps < 10_000
    assert [r.rid for r in sched.completed] == ["good"]
    (liar,) = sched.failed
    assert liar.reject_reason == "executor_error"
    assert sched.pool.outstanding() == 0
    assert not sched._active


def test_fragmentation_metric_stays_meaningful_with_sharing():
    """Review regression: per-owner used totals count a shared block's
    slots once per mapper; the fragmentation metric must subtract the
    physical duplicates instead of clamping to 0.0."""
    pool = _shared_pool(num_blocks=8, block_size=16)
    prompt = tuple(range(32))                   # 2 full blocks
    keys = chain_keys(prompt, 16)
    pool.alloc("a", 3)                          # 48 slots, writes 40
    pool.register_prefix("a", keys, 32)
    pool.set_used_tokens("a", 40)
    pool.map_prefix("b", keys)
    pool.alloc("b", 1)                          # 1 fresh block
    pool.set_used_tokens("b", 33)               # 32 shared + 1 own
    # physical: 4 blocks = 64 slots; written: 40 + (33 - 32) = 41
    assert pool.internal_fragmentation() == pytest.approx(
        (64 - 41) / 64)
    pool.free("a"), pool.free("b")


def test_fragmentation_exact_while_a_mapper_is_mid_prefill():
    """Review regression: a mapper that has not accounted its tokens
    yet (mid-chunk-prefill, used=0) must not DEDUCT the shared blocks'
    slots from the written total — per-block max over owners, not a
    blanket refcount subtraction."""
    pool = _shared_pool(num_blocks=8, block_size=16)
    prompt = tuple(range(32))
    keys = chain_keys(prompt, 16)
    pool.alloc("a", 3)
    pool.register_prefix("a", keys, 32)
    pool.set_used_tokens("a", 33)
    pool.map_prefix("b", keys)                  # b: mapped, used 0
    # physical: 3 blocks = 48 slots; written stays a's 33
    assert pool.internal_fragmentation() == pytest.approx(
        (48 - 33) / 48)
    pool.free("a"), pool.free("b")
    assert pool.outstanding() == 0


def test_cancel_excises_a_live_request_everywhere():
    """Review regression: a client abandoning its stream (timeout /
    drop) must not leave the request burning slots, KV and decode
    budget. cancel() reaches pending, queued and active requests."""
    cfg = _harness_config(slots=1, kv_blocks=32)
    sched = serve.Scheduler(cfg)
    sched.submit(serve.Request(rid="run", prompt_len=8, output_len=64,
                               arrival_s=0.0))
    sched.submit(serve.Request(rid="queued", prompt_len=8,
                               output_len=8, arrival_s=0.0))
    sched.submit(serve.Request(rid="later", prompt_len=8, output_len=8,
                               arrival_s=50.0))
    sched.step()                                # run admitted+decoding
    assert sched.cancel("run") is True          # active
    assert sched.cancel("queued") is True       # class queue
    assert sched.cancel("later") is True        # still pending
    assert sched.cancel("ghost") is False
    assert sched.step() is False                # nothing left
    assert sched.pool.outstanding() == 0
    assert {r.reject_reason for r in sched.rejected} == {"cancelled"}
    assert sched.rejected_total == 3
    # the freed id is reusable
    sched.submit(serve.Request(rid="run", prompt_len=8, output_len=2,
                               arrival_s=sched.now))
    sched.run()
    assert sched.completed[-1].rid == "run"


def test_client_disconnect_mid_stream_cancels_the_request():
    """Review regression: a client dropping its connection mid-stream
    (not just timing out) must cancel the request — a BrokenPipe on
    the next flush previously escaped the loop without cancelling,
    and the abandoned request decoded its full output into a queue
    nobody read."""
    import http.client

    class SlowExecutor(serve.SimExecutor):
        def step(self, active):
            threading.Event().wait(0.02)   # stretch the stream out
            return super().step(active)

    sched = serve.Scheduler(_harness_config(), clock=time.monotonic,
                            executor=SlowExecutor())
    service = serve.DecodeService(sched, idle_interval_s=0.005)
    service.start()
    port = service.start_http()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/v1/generate",
                     json.dumps({"rid": "dropper", "prompt_len": 8,
                                 "output_len": 500}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read(32)                      # take a token or two...
        conn.close()                       # ...then hang up
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if any(r.rid == "dropper" and r.reject_reason == "cancelled"
                   for r in sched.rejected):
                break
            threading.Event().wait(0.02)
        assert any(r.rid == "dropper"
                   and r.reject_reason == "cancelled"
                   for r in sched.rejected), "disconnect never cancelled"
    finally:
        service.stop()
    assert sched.pool.outstanding() == 0


def test_chunked_scheduler_rejects_unchunkable_executor_at_init():
    """Review regression: a chunked config over a JaxSlotExecutor built
    without chunk_tokens must fail at CONSTRUCTION, not reject 100% of
    traffic one executor_error at a time."""
    cfg, params = _tiny_model()
    ex = serve.JaxSlotExecutor(params, cfg, slots=2)  # no chunk width
    with pytest.raises(ValueError, match="chunk"):
        serve.Scheduler(serve.ServeConfig(slots=2,
                                          prefill_chunk_tokens=16),
                        executor=ex)
    # the legacy atomic mode still accepts it
    serve.Scheduler(serve.ServeConfig(slots=2), executor=ex)


def test_readmitted_victim_cow_copies_before_reprefill():
    """Review regression: a preempted victim's kept tokens re-prefill
    into positions that can land inside a still-shared tail block; the
    divergence must copy at RE-admission, before the executor touches
    a block another request still maps."""
    prompt = tuple(range(24))                  # tail block covered 8/16
    cfg = serve.ServeConfig(slots=2, kv_blocks=32, kv_block_size=16,
                            prefix_sharing=True,
                            prefill_chunk_tokens=64, queue_limit=16)
    sched = serve.Scheduler(cfg, cost_model=CALIBRATED)
    # twin (long-lived) registers the prompt's blocks; victim maps
    # them (first-token divergence = CoW #1), generates a few tokens,
    # is preempted by vip (its blocks freed, twin's registration
    # survives), then RE-admits while twin still maps the tail: the
    # kept tokens' re-prefill into the re-mapped shared tail must CoW
    # again (#2) — the accounting this regression pins down
    sched.submit(serve.Request(rid="twin", prompt_len=24,
                               output_len=100,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.0, prompt=prompt))
    sched.submit(serve.Request(rid="victim", prompt_len=24,
                               output_len=40, slo_class=serve.BATCH,
                               arrival_s=0.01, prompt=prompt))
    sched.submit(serve.Request(rid="vip", prompt_len=60, output_len=40,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.02))
    sched.run()
    done = {r.rid: r for r in sched.completed}
    assert set(done) == {"victim", "twin", "vip"}
    assert done["victim"].preemptions >= 1
    assert sched.pool.cow_copies >= 2, sched.pool.cow_copies
    assert sched.pool.outstanding() == 0
    sim = serve.SimExecutor()
    for r in sched.completed:
        assert r.tokens == [sim._token(r, n)
                            for n in range(len(r.tokens))]


def test_decode_service_thread_survives_a_step_exception():
    """Backstop for failures _fail_request cannot attribute (a
    batch-wide executor.step blowup): the serving thread logs, counts
    the swallow, and keeps running."""
    class BrokenScheduler(serve.Scheduler):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self.blowups = 0

        def step(self):
            if self.blowups < 3:
                self.blowups += 1
                raise RuntimeError("batch-wide blowup")
            return super().step()

    before = metrics.SWALLOWED_ERRORS.value(site="serve.step")
    sched = BrokenScheduler(_harness_config())
    service = serve.DecodeService(sched, idle_interval_s=0.001)
    service.start()
    try:
        sched.submit(serve.Request(rid="ok", prompt_len=4,
                                   output_len=2, arrival_s=0.0))
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not sched.completed:
            threading.Event().wait(0.01)
        assert sched.completed and sched.completed[0].rid == "ok"
        assert metrics.SWALLOWED_ERRORS.value(site="serve.step") \
            >= before + 3
        assert service._thread is not None and \
            service._thread.is_alive()
    finally:
        service.stop()


def test_streaming_ingress_rejects_bad_and_rejected_requests():
    cfg = _harness_config(slots=1, kv_blocks=2, kv_block_size=16)
    sched = serve.Scheduler(cfg)
    service = serve.DecodeService(sched, idle_interval_s=0.01)
    service.start()
    port = service.start_http()
    try:
        # malformed spec -> 400, not a hung stream
        import http.client
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/v1/generate",
                     json.dumps({"prompt_len": 8}),
                     {"Content-Type": "application/json"})
        assert conn.getresponse().status == 400
        conn.close()
        # valid JSON that is not an object -> 400, not a dropped socket
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/v1/generate", json.dumps([1, 2]),
                     {"Content-Type": "application/json"})
        assert conn.getresponse().status == 400
        conn.close()
        # declared prompt_len disagreeing with the prompt ids -> 400
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/v1/generate",
                     json.dumps({"prompt_len": 3, "output_len": 2,
                                 "prompt": [1, 2, 3, 4]}),
                     {"Content-Type": "application/json"})
        assert conn.getresponse().status == 400
        conn.close()
        # a request the scheduler must reject streams an error record
        lines = _read_ndjson_stream(
            "127.0.0.1", port,
            {"rid": "huge", "prompt_len": 500, "output_len": 5})
    finally:
        service.stop()
    assert lines == [{"error": "rejected: kv_too_large"}]


# -- tpuctl: chunk backlog + shared blocks ------------------------------------


def test_tpuctl_serve_renders_prefill_backlog_and_shared_blocks():
    from dpu_operator_tpu import tpuctl

    cfg = serve.chunked_config(CALIBRATED, slots=2, kv_blocks=32,
                               kv_block_size=16)
    sched = serve.Scheduler(cfg, cost_model=CALIBRATED)
    prompt = tuple(range(40))
    for i in range(2):
        sched.submit(serve.Request(rid=f"view{i}", prompt_len=40,
                                   output_len=4, arrival_s=0.0,
                                   prompt=prompt))
    sched.run()
    snap = sched.snapshot()
    assert snap["prefill"]["chunksTotal"] == sched.prefill_chunks_total
    view = tpuctl.render_serve(snap, [], now=0.0)
    assert view["prefillChunkTokensPerIteration"] \
        == cfg.prefill_chunk_tokens
    assert view["prefillBacklogTokens"] == 0        # drained
    assert "kvSharedBlocks" in view and "kvCowCopies" in view
    assert view["kvLogicalBlocks"] == 0


# -- the serving bench record -------------------------------------------------


def test_bench_serving_record_shape_and_determinism():
    """The BENCH series contract: >=2 load points each carrying p99
    TTFT, zero leaked blocks everywhere, the continuous-vs-static
    speedup, and bit-identical output across two invocations."""
    kw = dict(seed=SEED, loads=(0.6, 1.1), horizon_s=12.0)
    rec = serve.bench_serving(**kw)
    assert serve.bench_serving(**kw) == rec
    assert len(rec["loads"]) == 2
    for row in rec["loads"].values():
        assert row["ttft_p99_s"] >= row["ttft_p50_s"] >= 0.0
        assert row["kv_blocks_leaked"] == 0
        assert row["tokens_per_s"] > 0
    # the >=1.5x acceptance bound is asserted by
    # test_continuous_beats_static_by_1_5x over a full-length horizon;
    # this short-horizon record must still show a real win
    assert rec["continuous_vs_static"]["speedup"] > 1.0


# -- per-iteration cost ledger (the serve-check reconciliation gate) ----------


def test_ledger_reconciles_exactly_in_virtual_time():
    """Under the virtual clock the decomposition is exact: every
    entry's phase sum equals the iteration's virtual advance, prefill
    and decode both carry real spend, and the breakdown metric sees
    every step."""
    breakdown_before = metrics.SERVE_STEP_BREAKDOWN.count()
    sched = serve.Scheduler(
        _harness_config(prefill_chunk_tokens=16),
        cost_model=serve.CostModel())
    sched.submit_all(serve.open_loop_arrivals(SEED, 6.0, 10.0,
                                              id_prefix="lg"))
    sched.run()
    entries = sched.ledger.entries()
    assert entries and len(entries) <= sched.ledger.capacity
    rec = sched.ledger.reconcile(tolerance_s=1e-5, rel=0.0)
    assert rec["checked"] == len(entries)
    assert rec["ok"], rec
    assert set(entries[-1]["phases"]) == set(serve.LEDGER_PHASES)
    assert sum(e["phases"]["prefill"] for e in entries) > 0
    assert sum(e["phases"]["decode"] for e in entries) > 0
    # virtual mode: sched/cow are bookkeeping-only, zero modeled cost
    assert sum(e["phases"]["sched"] for e in entries) == 0
    assert metrics.SERVE_STEP_BREAKDOWN.count() \
        >= breakdown_before + len(serve.LEDGER_PHASES)


def test_ledger_attributes_stall_to_the_stalled_phase():
    """The acceptance case: under a REAL (injected) clock a stalling
    executor's seconds land in the phase that stalled — decode for a
    step() stall, prefill for a chunk stall — measured against the
    clock, not the cost model, and the ledger still reconciles."""
    clock = _Clock()

    class StallingExecutor(serve.SimExecutor):
        def prefill_chunk(self, req, slot, offset, n):
            clock.advance(2.0)
            return super().prefill_chunk(req, slot, offset, n)

        def step(self, active):
            clock.advance(3.0)
            return super().step(active)

    sched = serve.Scheduler(
        _harness_config(slots=2, prefill_chunk_tokens=64),
        clock=clock, executor=StallingExecutor())
    sched.submit(serve.Request(rid="stall", prompt_len=8, output_len=3,
                               arrival_s=0.0))
    while sched.step():
        pass
    assert len(sched.completed) == 1
    entries = sched.ledger.entries()
    assert any(e["phases"]["decode"] >= 3.0 for e in entries)
    assert any(e["phases"]["prefill"] >= 2.0 for e in entries)
    # the stall must NOT be attributed as modeled time elsewhere
    for e in entries:
        assert e["phases"]["sched"] < 1.0
        assert e["phases"]["cow"] < 1.0
    assert sched.ledger.reconcile()["ok"]


def test_ledger_ring_is_bounded():
    sched = serve.Scheduler(_harness_config(slots=2))
    sched.ledger = serve.StepLedger(capacity=8)
    for i in range(40):
        sched.submit(serve.Request(rid=f"lb{i}", prompt_len=4,
                                   output_len=2, arrival_s=0.01 * i))
    sched.run()
    assert sched.iterations > 8
    assert len(sched.ledger.entries()) == 8
    snap = sched.ledger.snapshot()
    assert snap["capacity"] == 8
    assert snap["reconciliation"]["checked"] == 8


# -- deterministic phase-span trees -------------------------------------------


def test_phase_span_tree_bit_identical_across_seeded_runs():
    """Two seeded runs of a preemption-heavy chunked workload produce
    byte-identical serve-kind span trees (names, trace ids, span ids,
    starts, durations, attributes) — the flight ring's wall-clock
    ts/seq are the ONLY run-dependent fields."""
    from dpu_operator_tpu.utils import flight

    arrivals = serve.open_loop_arrivals(SEED, 10.0, 6.0,
                                        prompt_lens=(24, 64),
                                        id_prefix="dt")

    def run_once():
        flight.RECORDER.clear()
        sched = serve.Scheduler(
            _harness_config(slots=2, kv_blocks=16, kv_block_size=8,
                            prefill_chunk_tokens=16),
            cost_model=CALIBRATED)
        sched.submit_all([r.fresh_copy() for r in arrivals])
        sched.run()
        tree = [(e["name"], e.get("trace_id"), e.get("span_id"),
                 e.get("duration_s"),
                 tuple(sorted((e.get("attributes") or {}).items())))
                for e in flight.RECORDER.events(kind="serve")]
        return tree, sched.preemptions

    tree1, preempt1 = run_once()
    tree2, preempt2 = run_once()
    assert preempt1 > 0  # the workload exercises the preempted phase
    assert any(name == "serve.preempted" for name, *_ in tree1)
    assert tree1 == tree2
    assert preempt1 == preempt2


def test_cow_copy_emits_a_phase_span():
    """A divergent write through the scheduler (identical prompts
    under sharing) leaves a serve.cow span joined to the writer's
    trace."""
    from dpu_operator_tpu.utils import flight

    flight.RECORDER.clear()
    prompt = tuple(range(24))                   # 1.5 blocks of 16
    cfg = serve.ServeConfig(slots=2, kv_blocks=16, kv_block_size=16,
                            prefix_sharing=True,
                            prefill_chunk_tokens=64)
    sched = serve.Scheduler(cfg, cost_model=CALIBRATED)
    sched.submit(serve.Request(rid="cw0", prompt_len=len(prompt),
                               output_len=24, slo_class=serve.BATCH,
                               arrival_s=0.0, prompt=prompt))
    # arrives after cw0's prefill registered the chain, while cw0
    # still holds its blocks — cw1's first token diverges the mapped
    # partial tail and copies it
    sched.submit(serve.Request(rid="cw1", prompt_len=len(prompt),
                               output_len=8, slo_class=serve.BATCH,
                               arrival_s=0.007, prompt=prompt))
    sched.run()
    assert sched.pool.cow_copies > 0
    cows = [e for e in flight.RECORDER.events(kind="serve")
            if e["name"] == "serve.cow"]
    assert cows
    assert all(e.get("trace_id") for e in cows)


# -- replica headroom digest --------------------------------------------------


def test_headroom_digest_matches_capacity_and_gauges():
    cfg = serve.ServeConfig(slots=4, kv_blocks=64, kv_block_size=16,
                            prefill_chunk_tokens=16, typical_tokens=64)
    sched = serve.Scheduler(cfg, cost_model=CALIBRATED)
    sched.submit(serve.Request(rid="h0", prompt_len=80, output_len=4,
                               arrival_s=0.0))
    sched.step()  # admitted, mid-prefill: backlog is live
    digest = sched.headroom()
    cap = sched.capacity()
    assert digest["freeSlots"] == cap["freeSlots"] == 3
    assert digest["advertisableSlots"] == cap["advertisableSlots"]
    assert digest["freeKvBlocks"] == cap["freeKvBlocks"]
    assert digest["chunkBacklogTokens"] > 0
    assert digest["queueDepth"] == {"interactive": 0, "batch": 0}
    assert digest["prefixIndexKeys"] == 0
    assert metrics.SERVE_HEADROOM.value(dimension="free_slots") == 3.0
    assert metrics.SERVE_HEADROOM.value(
        dimension="chunk_backlog_tokens") \
        == float(digest["chunkBacklogTokens"])
    sched.run()
    assert sched.headroom()["chunkBacklogTokens"] == 0


def test_headroom_counts_prefix_index_keys():
    prompt = tuple(range(32))
    cfg = serve.ServeConfig(slots=2, kv_blocks=32, kv_block_size=8,
                            prefix_sharing=True,
                            prefill_chunk_tokens=32)
    sched = serve.Scheduler(cfg, cost_model=CALIBRATED)
    sched.submit(serve.Request(rid="pk0", prompt_len=32, output_len=2,
                               arrival_s=0.0, prompt=prompt))
    sched.submit(serve.Request(rid="pk1", prompt_len=32, output_len=8,
                               arrival_s=0.0, prompt=prompt))
    for _ in range(6):
        sched.step()
    # the first prompt registered its chain; the digest reports the
    # affinity signal while blocks are still live
    assert sched.headroom()["prefixIndexKeys"] > 0
    assert metrics.SERVE_HEADROOM.value(
        dimension="prefix_index_keys") > 0
    sched.run()


def test_decode_service_headroom_folds_slo_and_fault_dimensions():
    class FakeEvaluator:
        def active_alerts(self):
            return [("cni-latency", "page"), ("serve-ttft", "page"),
                    ("serve-tokens", "ticket")]

    sched = serve.Scheduler(_harness_config())
    service = serve.DecodeService(sched, evaluator=FakeEvaluator(),
                                  fault_capacity_fn=lambda: 7)
    digest = service.headroom()
    # only serve-* alerts belong to the serving replica's digest
    assert digest["sloAlerts"] == [
        {"slo": "serve-ttft", "severity": "page"},
        {"slo": "serve-tokens", "severity": "ticket"}]
    assert digest["faultGateCapacity"] == 7
    assert metrics.SERVE_HEADROOM.value(
        dimension="slo_alerts_firing") == 2.0
    assert metrics.SERVE_HEADROOM.value(
        dimension="fault_gate_capacity") == 7.0
    # no fault gate wired -> null dimension, gauged as 0
    bare = serve.DecodeService(sched, evaluator=FakeEvaluator())
    assert bare.headroom()["faultGateCapacity"] is None
    assert metrics.SERVE_HEADROOM.value(
        dimension="fault_gate_capacity") == 0.0


def test_ledger_headroom_and_index_served_over_debug_endpoints():
    from dpu_operator_tpu.utils import flight
    from dpu_operator_tpu.utils.metrics import MetricsServer

    sched = serve.Scheduler(_harness_config(prefill_chunk_tokens=16))
    sched.submit(serve.Request(rid="dbg0", prompt_len=8, output_len=2,
                               arrival_s=0.0))
    sched.run()
    service = serve.DecodeService(sched)
    server = MetricsServer(host="127.0.0.1", port=0,
                           debug_handlers=service.debug_handlers())
    server.start()
    addr = f"127.0.0.1:{server.port}"
    try:
        ledger = flight.fetch(addr, path="/debug/serve/ledger")
        assert ledger["entries"] and ledger["reconciliation"]["ok"]
        headroom = flight.fetch(addr, path="/debug/serve/headroom")
        assert headroom["freeSlots"] == 4
        assert "sloAlerts" in headroom
        index = flight.fetch(addr, path="/debug")
        assert set(index["debugHandlers"]) >= {
            "/debug/flight", "/debug/serve", "/debug/serve/ledger",
            "/debug/serve/headroom"}
    finally:
        server.stop()


def test_render_serve_top_folds_ledger_window():
    from dpu_operator_tpu import tpuctl

    sched = serve.Scheduler(_harness_config(slots=1,
                                            prefill_chunk_tokens=16))
    sched.submit(serve.Request(rid="tp0", prompt_len=8, output_len=16,
                               slo_class=serve.BATCH, arrival_s=0.0))
    sched.submit(serve.Request(rid="tp1", prompt_len=4, output_len=2,
                               slo_class=serve.INTERACTIVE,
                               arrival_s=0.05))
    sched.run()
    assert sched.preemptions >= 1
    view = tpuctl.render_serve_top(sched.snapshot(),
                                   sched.ledger.snapshot(), last=50)
    assert view["iterations"] == len(sched.ledger.entries())
    assert view["phaseSeconds"]["decode"] > 0
    assert abs(sum(view["phaseShare"].values()) - 1.0) < 0.01
    assert view["preemptionsPerIteration"] > 0
    assert view["reconciliation"]["ok"]
    assert view["capacity"]["slots"] == 1
    # empty ledger renders, not crashes
    empty = tpuctl.render_serve_top({}, {"entries": []})
    assert empty["iterations"] == 0 and empty["phaseSeconds"] == {}


def test_cancel_closes_the_open_phase_span():
    """An abandoned request's timeline must not end in the dark: a
    cancel mid-decode closes the residency span (outcome=cancelled),
    and a cancel while still queued closes the wait span."""
    from dpu_operator_tpu.utils import flight

    flight.RECORDER.clear()
    sched = serve.Scheduler(_harness_config(slots=1,
                                            prefill_chunk_tokens=16))
    sched.submit(serve.Request(rid="live", prompt_len=8, output_len=50,
                               arrival_s=0.0))
    sched.submit(serve.Request(rid="waiting", prompt_len=8,
                               output_len=4, arrival_s=0.0))
    for _ in range(4):
        sched.step()  # "live" is decoding; "waiting" is slotless
    assert sched.cancel("live") and sched.cancel("waiting")
    events = flight.RECORDER.events(kind="serve")

    def spans(rid, name):
        return [e for e in events if e["name"] == name
                and (e.get("attributes") or {}).get("rid") == rid]

    (decode,) = spans("live", "serve.decode")
    assert decode["attributes"]["outcome"] == "cancelled"
    assert decode["duration_s"] > 0
    (queued,) = spans("waiting", "serve.queued")
    assert queued["attributes"]["outcome"] == "cancelled"
    assert sched.pool.outstanding() == 0
