"""Node-side SFC reconciler tests.

Reference analog: sfc-reconciler tests + e2e_test.go:425-445 (NF pod created
with image and resource assertions) and :525-593 (N+1 SFCs: pending until
capacity frees).
"""

import pytest

from dpu_operator_tpu.api import NetworkFunction, ServiceFunctionChain
from dpu_operator_tpu.daemon import SfcReconciler
from dpu_operator_tpu.k8s import Manager
from dpu_operator_tpu.utils import DEFAULT_NAD_NAME


@pytest.fixture
def manager(kube):
    mgr = Manager(kube)
    mgr.add_reconciler(SfcReconciler(workload_image="default-nf-image"))
    mgr.start()
    yield mgr
    mgr.stop()


def _sfc(name="my-sfc", nfs=None):
    return ServiceFunctionChain(
        name=name,
        network_functions=nfs or [NetworkFunction("nf-a", "quay.io/nf-a:1")],
    ).to_obj()


def test_sfc_creates_nf_pod(kube, manager):
    kube.create(_sfc())
    assert manager.wait_idle()
    pod = kube.get("v1", "Pod", "my-sfc-nf-a", namespace="default")
    assert pod is not None
    c = pod["spec"]["containers"][0]
    assert c["image"] == "quay.io/nf-a:1"
    assert c["resources"]["requests"]["google.com/tpu"] == "2"
    nets = pod["metadata"]["annotations"]["k8s.v1.cni.cncf.io/networks"]
    assert nets == f"{DEFAULT_NAD_NAME}, {DEFAULT_NAD_NAME}"


def test_sfc_delete_garbage_collects_pods(kube, manager):
    kube.create(_sfc())
    assert manager.wait_idle()
    kube.delete("config.tpu.openshift.io/v1", "ServiceFunctionChain",
                "my-sfc", namespace="default")
    assert kube.get("v1", "Pod", "my-sfc-nf-a", namespace="default") is None


def test_sfc_resource_exhaustion_then_unblock(kube, node_agent, manager):
    """4 chips, 2 per NF: third NF stays Pending until an SFC is deleted
    (e2e_test.go:525-593)."""
    node_agent.register_node("tpu-vm-0", labels={"tpu": "true"},
                             allocatable={"google.com/tpu": "4",
                                          "google.com/ici-port": "8"})
    for i in range(3):
        kube.create(_sfc(name=f"sfc-{i}",
                         nfs=[NetworkFunction("nf", f"img-{i}")]))
    assert manager.wait_idle()
    node_agent.sync()
    phases = sorted(
        p["status"]["phase"]
        for p in kube.list("v1", "Pod", namespace="default",
                           label_selector={"app": "tpu-network-function"}))
    assert phases == ["Pending", "Running", "Running"]

    # free one chain → pending pod schedules
    kube.delete("config.tpu.openshift.io/v1", "ServiceFunctionChain",
                "sfc-0", namespace="default")
    node_agent.sync()
    phases = [
        p["status"]["phase"]
        for p in kube.list("v1", "Pod", namespace="default",
                           label_selector={"app": "tpu-network-function"})]
    assert phases == ["Running", "Running"]
