"""ICI fault-domain engine gate (`make fault-check`).

Seeded hardware storms (link flaps, chip deaths, host loss) replayed
through the judged health state machine (healthy -> suspect ->
quarantined -> recovering -> healthy): a flapping link must be HELD
DOWN with exponential hold-down instead of re-admitted per bounce,
every SFC chain must converge to healthy-or-explicitly-Degraded within
a bounded round count, kubelet must observe ZERO spurious ListAndWatch
deletions of healthy devices, quarantines must survive kubelet
restarts / cold restarts / live handoffs, and recovery MTTR lands in
FAULT_r01.json. Injected clocks only — every test replays
bit-identically from its seed (opslint chaos-determinism covers the
fault marker too).
"""

import json
import os
import threading
import time

import pytest

from dpu_operator_tpu.faults import (
    HEALTHY,
    QUARANTINED,
    RECOVERING,
    SUSPECT,
    FaultEngine,
    FaultGatedHandler,
    FaultPolicy,
)
from dpu_operator_tpu.ici import SliceTopology
from dpu_operator_tpu.testing import ChipDead, HardwareStorm, HostLost, LinkFlap
from dpu_operator_tpu.utils import metrics

pytestmark = pytest.mark.fault

SEED = 20260803


class Clock:
    """Injected monotonic clock: tests advance time, nothing sleeps."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def _engine(topo=None, clock=None, policy=None, journal=""):
    return FaultEngine(
        topology_provider=(lambda: topo) if topo is not None else None,
        policy=policy, clock=clock or Clock(), journal_path=journal)


def _wait(predicate, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


# -- state machine: hysteresis both ways --------------------------------------


def test_single_bad_probe_is_suspect_not_withdrawn():
    """One flaky probe must not churn kubelet's allocatable set: the
    unit goes suspect but stays advertised; a good probe heals it."""
    eng = _engine()
    (tr,) = eng.observe_chip("chip-0", False)
    assert (tr.old, tr.new) == (HEALTHY, SUSPECT)
    assert eng.withdrawn_chips() == frozenset()
    (tr,) = eng.observe_chip("chip-0", True)
    assert (tr.old, tr.new) == (SUSPECT, HEALTHY)
    # a heal off one bounce records no MTTR: nothing was quarantined
    assert list(eng.recoveries) == []


def test_consecutive_bad_probes_quarantine_and_holddown_ignores_goods():
    clock = Clock()
    eng = _engine(clock=clock)
    eng.observe_chip("chip-0", False)
    (tr,) = eng.observe_chip("chip-0", False)
    assert tr.new == QUARANTINED
    assert eng.withdrawn_chips() == {"chip-0"}
    # good probes during the hold-down are IGNORED (CrashLoopBackOff
    # style): the unit must not re-enter service on the first bounce up
    clock.advance(5.0)  # hold_down_base is 10s
    assert eng.observe_chip("chip-0", True) == []
    assert eng.state("chip-0") == QUARANTINED
    rows = {r["unit"]: r for r in eng.state_table()}
    assert rows["chip-0"]["holdRemainingSeconds"] == pytest.approx(5.0)


def test_recovery_walks_recovering_to_healthy_and_records_mttr():
    clock = Clock()
    eng = _engine(clock=clock)
    eng.observe_chip("chip-0", False)
    eng.observe_chip("chip-0", False)  # quarantined at t=0
    clock.advance(11.0)  # past the 10s hold-down
    (tr,) = eng.observe_chip("chip-0", True)
    assert tr.new == RECOVERING
    assert eng.withdrawn_chips() == {"chip-0"}  # recovering != in service
    assert eng.observe_chip("chip-0", True) == []
    (tr,) = eng.observe_chip("chip-0", True)  # recover_after=3 goods
    assert tr.new == HEALTHY
    assert eng.withdrawn_chips() == frozenset()
    assert list(eng.recoveries) == [("chip-0", pytest.approx(11.0))]


def test_flap_damping_doubles_holddown_bounded():
    """A unit that bounces during recovery is re-quarantined with a
    DOUBLED hold-down each episode in the flap window, bounded by
    hold_down_max — never re-admitted per bounce."""
    clock = Clock()
    policy = FaultPolicy(hold_down_base=10.0, hold_down_max=35.0,
                         flap_window=10000.0)
    eng = _engine(clock=clock, policy=policy)
    before = metrics.FAULT_FLAP_HOLDDOWNS.value(kind="link")
    eng.observe_link("ici-0-x+", False)
    eng.observe_link("ici-0-x+", False)  # episode 1: hold 10s
    expected = [10.0, 20.0, 35.0, 35.0]  # doubling, then the cap
    for episode_hold in expected[1:]:
        # wait out the current hold, start recovering, then bounce
        clock.advance(policy.hold_down_max + 1.0)
        (tr,) = eng.observe_link("ici-0-x+", True)
        assert tr.new == RECOVERING
        (tr,) = eng.observe_link("ici-0-x+", False)
        assert tr.new == QUARANTINED
        rows = {r["unit"]: r for r in eng.state_table()}
        assert rows["ici-0-x+"]["holdRemainingSeconds"] == \
            pytest.approx(episode_hold)
    assert metrics.FAULT_FLAP_HOLDDOWNS.value(kind="link") - before \
        == len(expected) - 1


def test_flap_window_expiry_resets_damping_level():
    clock = Clock()
    policy = FaultPolicy(flap_window=100.0)
    eng = _engine(clock=clock, policy=policy)
    eng.observe_link("ici-0-x+", False)
    eng.observe_link("ici-0-x+", False)  # episode 1
    clock.advance(11.0)
    eng.observe_link("ici-0-x+", True)   # recovering
    eng.observe_link("ici-0-x+", False)  # episode 2: hold 20s
    rows = {r["unit"]: r for r in eng.state_table()}
    assert rows["ici-0-x+"]["holdRemainingSeconds"] == pytest.approx(20.0)
    # quiet long enough for both episodes to age out of the window
    clock.advance(policy.flap_window + 30.0)
    for _ in range(3):
        eng.observe_link("ici-0-x+", True)
    assert eng.state("ici-0-x+") == HEALTHY
    eng.observe_link("ici-0-x+", False)
    eng.observe_link("ici-0-x+", False)
    rows = {r["unit"]: r for r in eng.state_table()}
    # damping level reset: back to the base hold, not another doubling
    assert rows["ici-0-x+"]["holdRemainingSeconds"] == pytest.approx(10.0)


# -- fault-domain propagation over SliceTopology ------------------------------


def test_dead_chip_darkens_its_links_both_directions():
    topo = SliceTopology.cached("v5e-8")
    eng = _engine(topo=topo)
    eng.observe_chip("chip-0", False)
    eng.observe_chip("chip-0", False)
    dark = eng.dark_link_ids()
    idx = topo.chip_by_id("chip-0").index
    for link in topo.links:
        if link.src == idx or link.dst == idx:
            assert link.id in dark
    # links not touching the dead chip stay bright
    assert any(link.id not in dark for link in topo.links)


def test_host_lost_quarantines_whole_fault_domain_at_once():
    """A lost host is an authoritative signal, not a flaky probe: every
    chip on it quarantines immediately, no per-chip hysteresis."""
    topo = SliceTopology.cached("v5e-16")  # 2 hosts x 8 chips
    eng = _engine(topo=topo)
    transitions = eng.observe_host_lost(1)
    lost = {c.id for c in topo.chips_on_host(1)}
    assert {t.unit for t in transitions} == lost
    assert all(t.new == QUARANTINED for t in transitions)
    assert eng.withdrawn_chips() >= lost
    degraded = eng.slice_degraded()
    assert degraded == {"operational": 8, "total": 16,
                        "chips": sorted(c.id for c in topo.chips_on_host(0))}
    # idempotent: a repeated signal commits nothing new
    assert eng.observe_host_lost(1) == []


def test_disconnected_healthy_chip_is_withdrawn_from_subslice():
    """A chip whose every ICI link is dark cannot join collectives: it
    is withdrawn even though its own health probe reads fine."""
    topo = SliceTopology.cached("v5e-8")
    eng = _engine(topo=topo)
    cut = [link for link in topo.links if link.src == 0 or link.dst == 0]
    for link in cut:
        eng.observe_link(link.id, False)
        eng.observe_link(link.id, False)
    assert eng.state("chip-0") == HEALTHY  # judged per-unit: still fine
    assert "chip-0" in eng.withdrawn_chips()  # but outside the sub-slice
    degraded = eng.slice_degraded()
    assert degraded is not None
    assert degraded["operational"] == topo.num_chips - 1
    assert "chip-0" not in degraded["chips"]


def test_transition_racing_view_computation_is_not_masked():
    """A transition committed while a derived view is being computed
    off-lock must win: the racing reader's stale result is discarded,
    so the next read sees the fresh quarantine instead of serving a
    pre-transition verdict until some unrelated unit transitions."""
    topo = SliceTopology.cached("v5e-8")
    state = {"armed": False, "eng": None}

    def provider():
        if state["armed"]:
            state["armed"] = False
            # commits chip-1's quarantine INSIDE the outer view
            # computation (the provider runs outside the engine lock)
            state["eng"].observe_chip("chip-1", False)
            state["eng"].observe_chip("chip-1", False)
        return topo

    eng = state["eng"] = FaultEngine(topology_provider=provider,
                                     clock=Clock())
    eng.observe_chip("chip-0", False)
    state["armed"] = True
    eng.observe_chip("chip-0", False)  # quarantine; its view races
    assert {"chip-0", "chip-1"} <= eng.withdrawn_chips()


# -- device-plugin gating (gate.py) -------------------------------------------


class _RawHandler:
    def __init__(self, devices):
        self.devices = devices

    def get_devices(self):
        return {k: dict(v) for k, v in self.devices.items()}


def _chip_devs(n=4, healthy=True):
    return {f"chip-{i}": {"id": f"chip-{i}", "healthy": healthy,
                          "dev_path": f"/dev/accel{i}"} for i in range(n)}


def test_gate_feeds_probes_and_serves_judged_verdict():
    clock = Clock()
    eng = _engine(clock=clock)
    raw = _RawHandler(_chip_devs())
    gated = FaultGatedHandler(raw, eng, min_probe_interval=0.0)
    assert all(d["healthy"] for d in gated.get_devices().values())
    # one bad poll: suspect, still advertised (no allocatable churn)
    raw.devices["chip-1"]["healthy"] = False
    devs = gated.get_devices()
    assert devs["chip-1"]["healthy"] is True
    # second bad poll: judged quarantined -> withdrawn, NOT deleted
    devs = gated.get_devices()
    assert devs["chip-1"]["healthy"] is False
    assert set(devs) == set(_chip_devs())
    # the raw bit healing does not re-admit during the hold-down
    raw.devices["chip-1"]["healthy"] = True
    assert gated.get_devices()["chip-1"]["healthy"] is False
    # after the hold-down, recover_after good polls restore it
    clock.advance(11.0)
    for _ in range(2):
        assert gated.get_devices()["chip-1"]["healthy"] is False
    assert gated.get_devices()["chip-1"]["healthy"] is True


def test_gate_translates_local_device_ids_to_global_units(monkeypatch):
    """VSP device ids are LOCAL (chip-<local> on every worker) while
    engine units are GLOBAL topology chips: on worker 1 of a two-host
    slice, losing host 0 must NOT withdraw this host's devices, and a
    local bad chip must quarantine the right global unit."""
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    topo = SliceTopology.cached("v5e-16")
    eng = _engine(topo=topo, clock=Clock())
    raw = _RawHandler(_chip_devs(8))  # this worker's 8 local chips
    gated = FaultGatedHandler(raw, eng, min_probe_interval=0.0)
    eng.observe_host_lost(0)  # the PEER host dies
    devs = gated.get_devices()
    # the surviving host keeps its whole capacity
    assert all(d["healthy"] for d in devs.values())
    # a local fault lands on the right global unit: local chip-3 on
    # worker 1 is global chip-11
    raw.devices["chip-3"]["healthy"] = False
    gated.get_devices()
    devs = gated.get_devices()
    assert devs["chip-3"]["healthy"] is False
    assert eng.state("chip-11") == QUARANTINED
    assert eng.state("chip-3") == QUARANTINED  # host-0 chip, host loss
    # ...and the other local devices are untouched
    assert all(devs[f"chip-{i}"]["healthy"] for i in range(8) if i != 3)


def test_gate_on_worker_does_not_observe_before_topology(monkeypatch):
    """Before the topology is known a worker > 0 cannot attribute its
    local probes to global units — identity-feeding them would pin bad
    bits on HOST 0's chips, which this worker's polls could never
    correct. Raw bits pass through unjudged until the slice shape
    arrives."""
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    eng = _engine(clock=Clock())  # no topology provider yet
    raw = _RawHandler(_chip_devs(4))
    raw.devices["chip-3"]["healthy"] = False
    gated = FaultGatedHandler(raw, eng)
    for _ in range(3):
        devs = gated.get_devices()
    assert devs["chip-3"]["healthy"] is False  # raw bit passed through
    assert eng.state_table() == []  # nothing attributed to host 0

    # same guard with a topology KNOWN but the worker id stale (names
    # no host after a reshape): identity would misattribute too
    monkeypatch.setenv("TPU_WORKER_ID", "9")
    eng2 = _engine(topo=SliceTopology.cached("v5e-8"), clock=Clock())
    gated2 = FaultGatedHandler(raw, eng2, min_probe_interval=0.0)
    for _ in range(3):
        devs = gated2.get_devices()
    assert devs["chip-3"]["healthy"] is False
    assert eng2.state_table() == []


def test_peer_return_recovers_host_lost_quarantine():
    """A peer daemon answering again is the authoritative 'host back'
    signal: its chips walk recovering->healthy on the resync-fed good
    probes (there is no other probe source for remote chips) — a 15 s
    partition must not leave the slice degraded forever."""
    from dpu_operator_tpu.daemon import TpuSideManager

    topo = SliceTopology.cached("v5e-16")
    clock = Clock()
    eng = _engine(topo=topo, clock=clock)
    mgr = _bare_manager(engine=eng)
    mgr.vsp.topology = "v5e-16"
    addr, hop = "10.0.0.9:19000", ("out", "nf1-8")
    for _ in range(TpuSideManager.PEER_LOST_AFTER):
        clock.advance(5.0)
        mgr._note_peer_unreachable(addr, hop)
    assert eng.slice_degraded() is not None
    # peer back, but inside the hold-down: still withdrawn
    mgr._note_peer_reachable(addr, hop)
    assert eng.slice_degraded() is not None
    clock.advance(11.0)  # hold-down expires
    # recovery confirmation is per ROUND: several hops answering in
    # the same pass dedupe to one good probe — only distinct resync
    # rounds walk recovering->healthy
    for _ in range(4):
        mgr._note_peer_reachable(addr, hop)
    assert eng.slice_degraded() is not None, \
        "one resync pass with several hops re-admitted the host"
    for _ in range(3):  # recover_after good resync ROUNDS
        clock.advance(5.0)
        mgr._note_peer_reachable(addr, hop)
    assert eng.slice_degraded() is None
    assert all(eng.state(c.id) == HEALTHY
               for c in topo.chips_on_host(1))


def test_repair_pass_own_transitions_do_not_self_nudge():
    """Transitions committed by the repair loop's own probe pass must
    not re-nudge the loop (the pass repairs right after probing — a
    self-nudge only buys a redundant back-to-back pass); transitions
    from any other thread still nudge."""
    eng = _engine()
    mgr = _bare_manager(engine=eng)
    eng.add_listener(mgr._on_fault_transition)
    done = threading.Event()

    def probe_from_loop():
        eng.observe_link("ici-0-x+", False)  # suspect
        eng.observe_link("ici-0-x+", False)  # quarantined, this thread
        done.set()

    t = threading.Thread(target=probe_from_loop)
    mgr._repair_thread = t
    t.start()
    t.join()
    assert done.is_set()
    assert not mgr._repair_nudge.is_set()  # own pass: no self-nudge
    eng.observe_link("ici-1-x+", False)  # another thread (this one)
    eng.observe_link("ici-1-x+", False)
    assert mgr._repair_nudge.is_set()


def test_gate_rate_limits_probe_feeding_across_pokes():
    """A fault-transition poke re-snapshots ListAndWatch milliseconds
    after the scheduled poll; the re-snapshot must serve the judged
    verdict WITHOUT feeding the raw bits again — otherwise a
    sub-second VSP glitch counts as two 'consecutive' probes and rides
    one poke straight into quarantine."""
    clock = Clock()
    eng = _engine(clock=clock)
    raw = _RawHandler(_chip_devs())
    gated = FaultGatedHandler(raw, eng)  # default min interval
    raw.devices["chip-1"]["healthy"] = False
    gated.get_devices()  # scheduled poll: one bad probe -> suspect
    devs = gated.get_devices()  # poke-triggered re-snapshot, same glitch
    assert eng.state("chip-1") == SUSPECT  # NOT double-counted
    assert devs["chip-1"]["healthy"] is True  # still advertised
    clock.advance(5.0)
    gated.get_devices()  # the next REAL poll is the second probe
    assert eng.state("chip-1") == QUARANTINED


def test_gate_without_engine_passes_raw_bits_through():
    raw = _RawHandler(_chip_devs())
    raw.devices["chip-2"]["healthy"] = False
    devs = FaultGatedHandler(raw, None).get_devices()
    assert devs["chip-2"]["healthy"] is False
    assert devs["chip-0"]["healthy"] is True


def test_fault_transition_nudges_repair_and_pokes_plugins():
    from dpu_operator_tpu.daemon import TpuSideManager

    class _Poked:
        def __init__(self):
            self.pokes = 0

        def poke(self):
            self.pokes += 1

    mgr = TpuSideManager.__new__(TpuSideManager)
    mgr._repair_nudge = threading.Event()
    mgr.device_plugin = _Poked()
    mgr.ici_device_plugin = _Poked()
    eng = _engine(clock=Clock())
    eng.add_listener(mgr._on_fault_transition)
    # suspect changes neither the advertised nor the dark set — poking
    # would make ListAndWatch re-ingest the same raw bit milliseconds
    # later and collapse the poll-cadence hysteresis
    eng.observe_chip("chip-0", False)
    assert not mgr._repair_nudge.is_set()
    assert mgr.device_plugin.pokes == 0
    # quarantine withdraws: NOW kubelet and repair react immediately
    eng.observe_chip("chip-0", False)
    assert mgr._repair_nudge.is_set()
    assert mgr.device_plugin.pokes == 1
    assert mgr.ici_device_plugin.pokes == 1


# -- repair-pass integration: proactive steering + backoff --------------------


class _RecordingVsp:
    topology = "v5e-8"

    def __init__(self):
        self.wired = []
        self.unwired = []

    def create_network_function(self, a, b):
        self.wired.append((a, b))

    def delete_network_function(self, a, b):
        self.unwired.append((a, b))


def _bare_manager(engine=None, vsp=None):
    from dpu_operator_tpu.daemon import TpuSideManager

    mgr = TpuSideManager.__new__(TpuSideManager)
    mgr.vsp = vsp or _RecordingVsp()
    mgr._attach_store = {}
    mgr._attach_lock = threading.Lock()
    mgr._chain_store = {}
    mgr._chain_hops = {}
    mgr._degraded_hops = set()
    mgr._repair_pass_lock = threading.Lock()
    mgr._repair_frozen = threading.Event()
    mgr._repair_nudge = threading.Event()
    mgr._repair_stop = threading.Event()
    mgr._repair_thread = None
    mgr.link_prober = None
    if engine is not None:
        mgr.fault_engine = engine
    return mgr


def _plant_hop(mgr, name, out_id, in_id, out_fallback, in_fallback):
    mgr._chain_store[("default", name)] = {
        0: {"in": "ingress", "out": out_fallback, "sandbox": "sA",
            "ports": []},
        1: {"in": in_fallback, "out": "egress", "sandbox": "sB",
            "ports": []},
    }
    mgr._chain_hops[("default", name, 0)] = (out_id, in_id)


def test_repair_steers_around_quarantined_link_proactively():
    """The engine's judged dark set steers repair even while the wire
    still reads up — a held-down flapper is avoided BEFORE it bounces
    again, and the hop is explicitly degraded."""
    eng = _engine(clock=Clock())
    mgr = _bare_manager(engine=eng)
    _plant_hop(mgr, "ca", "ici-1-x+", "nf-sB-chip-2",
               "nf-sA-chip-1", "nf-sB-chip-2")
    # the prober says the link is UP right now (mid-bounce)
    mgr.link_prober = lambda chip: [
        {"port": "x+", "up": True, "wired": True}]
    assert mgr.repair_chains() == []  # nothing judged dark yet
    eng.observe_link("ici-1-x+", False)
    eng.observe_link("ici-1-x+", False)  # quarantined (held down)
    repaired = mgr.repair_chains()
    assert repaired == [(("default", "ca", 0),
                         ("ici-1-x+", "nf-sB-chip-2"),
                         ("nf-sA-chip-1", "nf-sB-chip-2"))]
    assert ("default", "ca", 0) in mgr._degraded_hops
    assert ("ici-1-x+", "nf-sB-chip-2") in mgr.vsp.unwired
    # idempotent: the re-steered hop carries no dark endpoint
    assert mgr.repair_chains() == []


def test_repair_runs_on_engine_verdicts_with_no_prober():
    """Before the native agent connects there is no prober — the
    engine's dark set alone must still drive steering."""
    eng = _engine(clock=Clock())
    mgr = _bare_manager(engine=eng)
    _plant_hop(mgr, "cb", "ici-2-y+", "in-att", "fallback-out", "in-att")
    eng.observe_link("ici-2-y+", False)
    eng.observe_link("ici-2-y+", False)
    repaired = mgr.repair_chains()
    assert [(old, new) for _, old, new in repaired] == \
        [(("ici-2-y+", "in-att"), ("fallback-out", "in-att"))]


def test_repair_backoff_doubles_idle_and_resets_on_work_or_nudge():
    from dpu_operator_tpu.daemon import TpuSideManager

    next_delay = TpuSideManager._next_repair_delay
    assert next_delay(5.0, 5.0, 40.0, busy=False, nudged=False) == 10.0
    assert next_delay(10.0, 5.0, 40.0, busy=False, nudged=False) == 20.0
    assert next_delay(40.0, 5.0, 40.0, busy=False, nudged=False) == 40.0
    assert next_delay(40.0, 5.0, 40.0, busy=True, nudged=False) == 5.0
    assert next_delay(40.0, 5.0, 40.0, busy=False, nudged=True) == 5.0


def test_repair_loop_fault_nudge_wakes_a_backed_off_loop():
    """A loop parked deep in its idle backoff must react to a
    fault-engine nudge NOW, not at the end of the backed-off wait."""
    mgr = _bare_manager()
    passes = []
    mgr._fault_probe_pass = lambda: ([], {})
    mgr.repair_chains = \
        lambda probe_cache=None: passes.append(1) and []
    # huge base interval: without the nudge no pass would ever run
    mgr.enable_chain_repair(lambda chip: [], interval=600.0,
                            jitter_seed=SEED)
    try:
        mgr._repair_nudge.set()
        assert _wait(lambda: len(passes) >= 1, timeout=10.0), \
            "nudge did not wake the repair loop"
    finally:
        mgr._repair_stop.set()
        mgr._repair_nudge.set()
        mgr._repair_thread.join(timeout=5.0)


def test_raising_prober_is_counted_flight_recorded_not_silent():
    """Satellite regression: a thrice-raising prober must bump
    tpu_daemon_swallowed_errors_total (flight-recorded by the counter),
    skip only the chips it failed for, and never end the pass."""
    from dpu_operator_tpu.utils import flight

    eng = _engine(topo=SliceTopology.cached("v5e-8"), clock=Clock())
    mgr = _bare_manager(engine=eng)
    raises = {"left": 3}

    def prober(chip_index):
        if raises["left"] > 0:
            raises["left"] -= 1
            raise ConnectionError("agent vanished")
        return [{"port": "x+", "up": False, "wired": True}]

    mgr.link_prober = prober
    before = metrics.SWALLOWED_ERRORS.value(site="tpuside.link_probe")
    flight_before = len(flight.RECORDER.events(kind="swallowed_error"))
    transitions, probe_cache = mgr._fault_probe_pass()
    assert metrics.SWALLOWED_ERRORS.value(site="tpuside.link_probe") \
        - before == 3
    assert len(flight.RECORDER.events(kind="swallowed_error")) \
        - flight_before >= 3
    # the pass survived: chips after the three failures WERE probed,
    # and only THEIR answers seed the repair scan's probe cache
    assert any(t.new == SUSPECT for t in transitions)
    assert len(probe_cache) == 5  # 8 local chips minus the 3 failures


def test_fault_probe_pass_skips_worker_not_in_topology(monkeypatch):
    """A TPU_WORKER_ID that names no topology host (stale after a
    reshape, misconfigured env) must skip the probe pass entirely —
    probing the whole slice through the local agent would ingest link
    verdicts this prober has no authority over."""
    monkeypatch.setenv("TPU_WORKER_ID", "7")
    eng = _engine(topo=SliceTopology.cached("v5e-8"), clock=Clock())
    mgr = _bare_manager(engine=eng)
    calls = []
    mgr.link_prober = lambda chip: calls.append(chip) or []
    assert mgr._fault_probe_pass() == ([], {})
    assert calls == []  # no cross-authority probing


def test_raising_pass_feeds_heartbeat_and_keeps_loop_alive():
    mgr = _bare_manager()
    mgr._fault_probe_pass = lambda: ([], {})

    def exploding(probe_cache=None):
        raise RuntimeError("pass bug")

    mgr.repair_chains = exploding

    class _Heartbeat:
        beats = 0

        def beat(self):
            self.beats += 1

    heartbeat = _Heartbeat()
    before = metrics.SWALLOWED_ERRORS.value(site="tpuside.repair_loop")
    assert mgr._repair_tick(heartbeat) is False
    assert metrics.SWALLOWED_ERRORS.value(site="tpuside.repair_loop") \
        - before == 1
    assert heartbeat.beats == 1  # alive-but-degraded, not stalled
    mgr.repair_chains = lambda probe_cache=None: []
    assert mgr._repair_tick(heartbeat) is False  # next tick runs fine


# -- persistence: cold restart journal + live handoff -------------------------


def test_export_adopt_carries_relative_timers_across_clocks():
    """Monotonic clocks do not compare across processes: hold-downs and
    outage epochs ride as remaining/elapsed seconds, so an adopted
    quarantine keeps its hold-down under a totally different clock."""
    c1 = Clock(100.0)
    eng1 = _engine(clock=c1)
    eng1.observe_link("ici-0-x+", False)
    eng1.observe_link("ici-0-x+", False)  # hold until t=110
    c1.advance(4.0)  # 6s of hold remaining
    state = eng1.export_state()

    c2 = Clock(5000.0)
    eng2 = _engine(clock=c2)
    assert eng2.adopt_state(state) == []
    assert eng2.state("ici-0-x+") == QUARANTINED
    c2.advance(2.0)
    assert eng2.observe_link("ici-0-x+", True) == []  # still held
    c2.advance(5.0)  # past the carried remaining hold
    (tr,) = eng2.observe_link("ici-0-x+", True)
    assert tr.new == RECOVERING
    # flap episodes carried too: a bounce now doubles the hold-down
    (tr,) = eng2.observe_link("ici-0-x+", False)
    assert tr.new == QUARANTINED
    rows = {r["unit"]: r for r in eng2.state_table()}
    assert rows["ici-0-x+"]["holdRemainingSeconds"] == pytest.approx(20.0)


def test_journal_roundtrip_and_corruption_starts_clean(tmp_path):
    path = str(tmp_path / "state" / "faults.json")
    clock = Clock()
    eng = _engine(clock=clock, journal=path)
    eng.observe_chip("chip-3", False)
    eng.observe_chip("chip-3", False)  # _commit journals automatically
    assert os.path.exists(path)

    fresh = _engine(clock=Clock(9999.0), journal=path)
    assert fresh.load() == []
    assert fresh.state("chip-3") == QUARANTINED

    with open(path, "w") as f:
        f.write('{"schema": 1, "units": [{"truncat')  # crash mid-write
    broken = _engine(journal=path)
    dropped = broken.load()
    assert dropped and "unreadable" in dropped[0]
    assert broken.state("chip-3") == HEALTHY  # clean start, not a wedge

    # valid JSON with wrong-typed fields: the row is dropped, load()
    # honors its never-raises contract instead of crash-looping the
    # daemon on every restart
    with open(path, "w") as f:
        json.dump({"schema": 1, "units": [
            {"unit": "chip-1", "kind": "chip", "state": QUARANTINED,
             "hold_remaining": "abc"},
            {"unit": "chip-2", "kind": "chip", "state": QUARANTINED,
             "hold_remaining": 5.0},
        ]}, f)
    typed = _engine(journal=path)
    dropped = typed.load()
    assert len(dropped) == 1 and "malformed" in dropped[0]
    assert typed.state("chip-1") == HEALTHY  # bad row dropped whole
    assert typed.state("chip-2") == QUARANTINED  # good row installed


def test_adopt_rejects_unknown_schema_and_drops_unknown_units():
    topo = SliceTopology.cached("v5e-8")
    eng = _engine(topo=topo)
    dropped = eng.adopt_state({"schema": 99, "units": []})
    assert dropped and "schema" in dropped[0]
    dropped = eng.adopt_state({"schema": 1, "units": [
        {"unit": "chip-77", "kind": "chip", "state": QUARANTINED},
        {"unit": "chip-1", "kind": "chip", "state": QUARANTINED},
        {"unit": "bogus", "kind": "gpu", "state": "weird"},
    ]})
    assert len(dropped) == 2  # not-in-topology + malformed
    assert eng.state("chip-77") == HEALTHY
    assert eng.state("chip-1") == QUARANTINED


def test_adoption_republishes_gauges_and_subslice():
    """Adopted verdicts are live state: the quarantine gauge and the
    sub-slice gauge must reflect them immediately — a restarted daemon
    withholding two chips must not read 0 quarantined on /metrics
    until some unrelated unit transitions."""
    topo = SliceTopology.cached("v5e-8")
    src = _engine(topo=topo)
    src.observe_chip("chip-0", False)
    src.observe_chip("chip-0", False)
    src.observe_link("ici-3-y+", False)
    src.observe_link("ici-3-y+", False)
    state = src.export_state()

    metrics.FAULT_QUARANTINED.set(0, kind="chip")
    metrics.FAULT_QUARANTINED.set(0, kind="link")
    fresh = _engine(topo=topo)
    assert fresh.adopt_state(state) == []
    assert metrics.FAULT_QUARANTINED.value(kind="chip") == 1
    assert metrics.FAULT_QUARANTINED.value(kind="link") == 1
    assert metrics.FAULT_SUBSLICE.value() == topo.num_chips - 1


def test_peer_daemon_loss_declares_host_lost():
    """Production wiring for observe_host_lost: a peer daemon
    unreachable for PEER_LOST_AFTER consecutive resync ROUNDS is a
    lost fault domain — its chips quarantine at once; one blip is not
    enough, and a recovered peer resets the count."""
    from dpu_operator_tpu.daemon import TpuSideManager

    topo = SliceTopology.cached("v5e-16")
    clock = Clock()
    eng = _engine(topo=topo, clock=clock)
    mgr = _bare_manager(engine=eng)
    mgr.vsp.topology = "v5e-16"
    addr = "10.0.0.9:19000"
    # the remote ingress endpoint encodes the peer's worker index
    hop = ("nf-local-chip-1", "nf1-8")
    for _ in range(TpuSideManager.PEER_LOST_AFTER - 1):
        clock.advance(5.0)
        mgr._note_peer_unreachable(addr, hop)
    assert eng.withdrawn_chips() == frozenset()  # not yet authoritative
    mgr._note_peer_reachable(addr)  # peer answered: count resets
    for _ in range(TpuSideManager.PEER_LOST_AFTER - 1):
        clock.advance(5.0)
        mgr._note_peer_unreachable(addr, hop)
    assert eng.withdrawn_chips() == frozenset()
    clock.advance(5.0)
    mgr._note_peer_unreachable(addr, hop)  # threshold crossed
    assert eng.withdrawn_chips() == \
        {c.id for c in topo.chips_on_host(1)}
    # a port-addressed remote endpoint resolves through the topology
    eng2 = _engine(topo=topo)
    mgr2 = _bare_manager(engine=eng2)
    mgr2.vsp.topology = "v5e-16"
    assert mgr2._peer_host_of(("out", "ici-9-x+")) == 1
    assert mgr2._peer_host_of(("out", "ici-2-x+")) == 0
    assert mgr2._peer_host_of(("out", "not-a-port-id")) is None
    assert mgr2._peer_host_of(None) is None


def test_peer_failures_within_one_resync_round_count_once():
    """A peer serving several remote hops fails once PER HOP inside
    the same resync pass — that is one round, not three: a single 5 s
    blip against a three-hop peer must not quarantine its host."""
    topo = SliceTopology.cached("v5e-16")
    clock = Clock()
    eng = _engine(topo=topo, clock=clock)
    mgr = _bare_manager(engine=eng)
    mgr.vsp.topology = "v5e-16"
    addr, hop = "10.0.0.9:19000", ("out", "nf1-8")
    for _ in range(6):  # six hops, same pass, same instant
        mgr._note_peer_unreachable(addr, hop)
    assert eng.withdrawn_chips() == frozenset()


def test_host_lost_still_fires_when_resolution_succeeds_late():
    """Host resolution failing at the exact threshold round (hop not
    wired yet) must not lose the signal forever: firing retries every
    round past the threshold."""
    from dpu_operator_tpu.daemon import TpuSideManager

    topo = SliceTopology.cached("v5e-16")
    clock = Clock()
    eng = _engine(topo=topo, clock=clock)
    mgr = _bare_manager(engine=eng)
    mgr.vsp.topology = "v5e-16"
    addr = "10.0.0.9:19000"
    for _ in range(TpuSideManager.PEER_LOST_AFTER):
        clock.advance(5.0)
        mgr._note_peer_unreachable(addr, None)  # host unresolvable
    assert eng.withdrawn_chips() == frozenset()
    clock.advance(5.0)
    mgr._note_peer_unreachable(addr, ("out", "nf1-8"))  # now resolvable
    assert eng.withdrawn_chips() == \
        {c.id for c in topo.chips_on_host(1)}


def test_quarantine_survives_live_handoff_bundle():
    """The handoff bundle's schema-v2 `faults` section: a withdrawn
    chip must NOT briefly re-enter kubelet's allocatable set under the
    incoming daemon; recovery still walks on live probes."""
    from dpu_operator_tpu.daemon import handoff

    c1 = Clock(50.0)
    eng1 = _engine(clock=c1)
    eng1.observe_chip("chip-0", False)
    eng1.observe_chip("chip-0", False)

    class _Mgr:
        pass

    outgoing = _Mgr()
    outgoing.export_fault_state = eng1.export_state
    bundle = handoff.collect_bundle(outgoing)
    assert bundle["schema"] == handoff.SCHEMA_VERSION
    assert bundle["faults"]["units"]

    c2 = Clock(7.0)
    eng2 = _engine(clock=c2)
    incoming = _Mgr()
    incoming.adopt_fault_state = eng2.adopt_state
    report = handoff.adopt_bundle(incoming, bundle)
    assert report.discrepancies == []
    assert eng2.state("chip-0") == QUARANTINED
    # the very FIRST gated snapshot already carries the withdrawal
    gated = FaultGatedHandler(_RawHandler(_chip_devs(2)), eng2,
                              min_probe_interval=0.0)
    assert gated.get_devices()["chip-0"]["healthy"] is False
    # reconciled against fresh probes: actually-fine hardware recovers
    c2.advance(11.0)
    for _ in range(3):
        gated.get_devices()
    assert eng2.state("chip-0") == HEALTHY
    assert gated.get_devices()["chip-0"]["healthy"] is True


def test_malformed_faults_section_lands_as_discrepancy_not_crash():
    from dpu_operator_tpu.daemon import handoff

    eng = _engine()

    class _Mgr:
        pass

    incoming = _Mgr()
    incoming.adopt_fault_state = eng.adopt_state
    report = handoff.adopt_bundle(
        incoming, {"schema": handoff.SCHEMA_VERSION,
                   "faults": {"schema": 42}})
    assert [d["kind"] for d in report.discrepancies] == ["fault-state"]
    assert eng.state_table() == []  # clean start


# -- quarantine survives a kubelet restart (wire-level) -----------------------


def test_quarantine_survives_kubelet_restart(short_tmp):
    """Kubelet restarts while a chip is quarantined: the device must
    stay withdrawn through re-registration (never deleted, never
    briefly Healthy), Allocate must refuse it, and it returns only
    after the full recovering->healthy walk."""
    import grpc

    from dpu_operator_tpu.deviceplugin import DevicePlugin, FakeKubelet
    from dpu_operator_tpu.utils.path_manager import PathManager

    pm = PathManager(short_tmp)
    clock = Clock()
    eng = _engine(clock=clock)
    raw = _RawHandler(_chip_devs())
    # the wire test hammers 0.05s polls under a frozen injected clock,
    # so the probe-feed rate limit (engine-clock based) is disabled
    # here; its behavior has its own dedicated test
    plugin = DevicePlugin(
        FaultGatedHandler(raw, eng, min_probe_interval=0.0),
        path_manager=pm, poll_interval=0.05)
    kubelet = FakeKubelet(pm)
    kubelet.start()
    plugin.start()

    def health(chip):
        devs = kubelet.device_lists.get("google.com/tpu") or []
        by_id = {d.ID: d.health for d in devs}
        return by_id.get(chip)

    try:
        plugin.register_with_kubelet()
        plugin.enable_kubelet_watch(interval=0.1)
        assert kubelet.wait_for_devices("google.com/tpu", 4)
        assert _wait(lambda: health("chip-0") == "Healthy")

        raw.devices["chip-0"]["healthy"] = False  # VSP health bit drops
        assert _wait(lambda: health("chip-0") == "Unhealthy")
        assert eng.state("chip-0") == QUARANTINED
        raw.devices["chip-0"]["healthy"] = True  # the raw bit heals...
        assert health("chip-0") == "Unhealthy"   # ...hold-down stands

        kubelet.restart()
        assert _wait(lambda: plugin.reregistrations >= 1)
        assert kubelet.wait_for_devices("google.com/tpu", 4)
        # re-registered against the JUDGED view: still withdrawn
        assert health("chip-0") == "Unhealthy"
        with pytest.raises(grpc.RpcError) as err:
            kubelet.allocate("google.com/tpu", ["chip-0"])
        assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION
        # healthy neighbors allocate fine throughout
        resp = kubelet.allocate("google.com/tpu", ["chip-1"])
        assert resp.container_responses[0].envs["TPU_DEVICE_IDS"] == \
            "chip-1"

        clock.advance(11.0)  # hold-down expires; good polls accumulate
        assert _wait(lambda: health("chip-0") == "Healthy")
        assert eng.state("chip-0") == HEALTHY
    finally:
        plugin.stop()
        kubelet.stop()


# -- status surfaces: CR condition, /healthz, admin RPC, tpuctl ---------------


def test_slice_degraded_condition_on_sfc_cr(kube):
    from dpu_operator_tpu.daemon.sfc_reconciler import SfcReconciler
    from dpu_operator_tpu.k8s.manager import Request

    verdict = {"value": {"operational": 6, "total": 8,
                         "chips": [f"chip-{i}" for i in range(6)]}}
    rec = SfcReconciler(workload_image="w",
                        chain_status_provider=lambda ns, n: [],
                        slice_degraded_provider=lambda: verdict["value"])
    kube.create({
        "apiVersion": "config.tpu.openshift.io/v1",
        "kind": "ServiceFunctionChain",
        "metadata": {"name": "chain", "namespace": "default",
                     "generation": 1},
        "spec": {"networkFunctions": [{"name": "fw", "image": "img"}]},
    })
    req = Request("config.tpu.openshift.io/v1", "ServiceFunctionChain",
                  "chain", "default")
    rec.reconcile(kube, req)
    obj = kube.get("config.tpu.openshift.io/v1", "ServiceFunctionChain",
                   "chain", namespace="default")
    conds = {c["type"]: c for c in obj["status"]["conditions"]}
    assert conds["SliceDegraded"]["status"] == "True"
    assert conds["SliceDegraded"]["reason"] == "IciFaultDomain"
    assert "6/8" in conds["SliceDegraded"]["message"]
    # back to full capacity: the condition disappears (stable shape)
    verdict["value"] = None
    rec.reconcile(kube, req)
    obj = kube.get("config.tpu.openshift.io/v1", "ServiceFunctionChain",
                   "chain", namespace="default")
    assert "SliceDegraded" not in {
        c["type"] for c in obj["status"]["conditions"]}


def test_quarantine_emits_events_and_degraded_component(kube):
    from dpu_operator_tpu.k8s import events

    events.configure(events.EventRecorder(kube, "tpu-daemon"),
                     events.node_reference("tpu-vm-0"))
    try:
        topo = SliceTopology.cached("v5e-8")
        eng = _engine(topo=topo)
        mgr = _bare_manager(engine=eng)
        eng.observe_chip("chip-0", False)
        eng.observe_chip("chip-0", False)
        eng.observe_link("ici-3-y+", False)
        eng.observe_link("ici-3-y+", False)
        events.flush()
        reasons = {e["reason"] for e in kube.list("v1", "Event")}
        assert {"ChipQuarantined", "LinkQuarantined",
                "SliceDegraded"} <= reasons
        assert "faults:slice-degraded" in mgr.degraded_sites()
        status = mgr.fault_status()
        assert status["enabled"] is True
        assert status["sliceDegraded"]["operational"] == 7
        states = {r["unit"]: r["state"] for r in status["units"]}
        assert states["chip-0"] == QUARANTINED
    finally:
        events.reset()


def test_tpuctl_faults_renders_state_table_and_transitions():
    from dpu_operator_tpu import tpuctl
    from dpu_operator_tpu.vsp.rpc import VspServer

    eng = _engine(clock=Clock())
    eng.observe_link("ici-1-x+", False)
    eng.observe_link("ici-1-x+", False)

    class _Admin:
        def get_faults(self, req):
            return {"enabled": True, "units": eng.state_table(),
                    "sliceDegraded": eng.slice_degraded()}

    server = VspServer(_Admin(), tcp_addr=("127.0.0.1", 0))
    server.start()
    try:
        args = type("A", (), {
            "cmd": "faults",
            "daemon_addr": f"127.0.0.1:{server.bound_port}",
            "metrics_addr": "127.0.0.1:1",  # unreachable: table-only
            "token": "", "agent_socket": "", "vsp_socket": ""})()
        out = tpuctl.run(args)
        states = {r["unit"]: r["state"] for r in out["units"]}
        assert states["ici-1-x+"] == QUARANTINED
        assert out["lastTransitions"] == []  # flight fetch degraded
    finally:
        server.stop()

    # render folds flight `fault` entries, newest 20, other kinds out
    flight_events = [
        {"kind": "fault", "ts": float(i),
         "attributes": {"unit": f"u{i}", "to": QUARANTINED,
                        "reason": "r"}}
        for i in range(25)
    ] + [{"kind": "handoff", "ts": 99.0, "attributes": {"unit": "x"}}]
    view = tpuctl.render_faults({"enabled": True, "units": [],
                                 "sliceDegraded": None}, flight_events)
    assert len(view["lastTransitions"]) == 20
    assert view["lastTransitions"][-1]["unit"] == "u24"
    assert all(t["to"] == QUARANTINED for t in view["lastTransitions"])


def test_tpuctl_faults_needs_daemon_addr():
    from dpu_operator_tpu import tpuctl

    args = type("A", (), {"cmd": "faults", "daemon_addr": "",
                          "metrics_addr": "", "token": "",
                          "agent_socket": "", "vsp_socket": ""})()
    with pytest.raises(SystemExit, match="daemon-addr"):
        tpuctl.run(args)


# -- the acceptance storm -----------------------------------------------------

ROUND_S = 5.0
MAX_ROUNDS = 40
CONVERGE_BOUND = 32


def test_seeded_hardware_storm_converges_and_records_mttr():
    """The gate's centerpiece: a seeded storm of link flaps (one link
    bouncing repeatedly — it must be HELD DOWN, not re-admitted per
    bounce), a chip death-and-return, and a whole host dropping out,
    played over a v5e-16 slice with live SFC chains. Every chain must
    be healthy-or-explicitly-Degraded every round after repair, the
    advertised device set must never shrink (zero spurious ListAndWatch
    deletions), no lock-order cycle may form, everything must converge
    to healthy within a bounded round count once the storm passes, and
    recovery MTTR lands in FAULT_r01.json."""
    from dpu_operator_tpu.testing.locktrace import LockTracer

    FLAP = "ici-1-x+"
    flap_before = metrics.FAULT_FLAP_HOLDDOWNS.value(kind="link")
    tracer = LockTracer()
    with tracer.install():
        topo = SliceTopology.cached("v5e-16")
        clock = Clock()
        eng = _engine(topo=topo, clock=clock)
        storm = HardwareStorm(topo, seed=SEED)
        storm.add(
            # two interleaved scripts => 2-round down periods at rounds
            # {1,2}, {5,6}, {9,10}: a genuine flapper (a single-round
            # bounce is absorbed by suspect-state hysteresis by design)
            LinkFlap(FLAP, bounces=3, start=1, period=4),
            LinkFlap(FLAP, bounces=3, start=2, period=4),
            ChipDead("chip-12", at=2, until=8),
            HostLost(1, at=12, duration=6),
        ).random_flaps(3, bounces=2, horizon=12)

        mgr = _bare_manager(engine=eng)
        mgr.link_prober = storm.prober
        _plant_hop(mgr, "ca", FLAP, "nf-sB-chip-2",
                   "nf-sA-chip-1", "nf-sB-chip-2")
        _plant_hop(mgr, "cb", "ici-12-y+", "ici-13-y-",
                   "nf-sC-chip-12", "nf-sD-chip-13")
        gated = FaultGatedHandler(
            _RawHandler({c.id: {"id": c.id, "healthy": True}
                         for c in topo.chips}), eng)

        safe_chips = [c.id for c in topo.chips_on_host(0)
                      if c.id != "chip-1"]  # chip-1 owns the flap link
        ids_baseline: set = set()
        spurious_deletion_rounds: list = []
        unconverged_chain_rounds: list = []
        held_while_up = False
        converged_at = None
        for rnd in range(1, MAX_ROUNDS + 1):
            storm.advance()
            clock.advance(ROUND_S)
            if rnd == 12:
                # the authoritative fault-domain signal arrives with
                # the outage (peer daemon gone), not via hysteresis
                eng.observe_host_lost(1)
            # probe surfaces exactly as the daemon feeds them: chip
            # health through the gate, link state through the prober
            for chip in topo.chips:
                gated.inner.devices[chip.id]["healthy"] = \
                    storm.chip_healthy(chip.index)
            devs = gated.get_devices()
            for chip in topo.chips:
                eng.ingest_link_probe(chip.index,
                                      storm.prober(chip.index))
            mgr.repair_chains()

            # zero spurious ListAndWatch deletions: the id set NEVER
            # shrinks, and untouched still-connected chips stay Healthy
            if not ids_baseline:
                ids_baseline = set(devs)
            elif set(devs) != ids_baseline:
                spurious_deletion_rounds.append(rnd)
            for cid in safe_chips:
                if not devs[cid]["healthy"]:
                    spurious_deletion_rounds.append((rnd, cid))

            # flap damping: the storm says the wire is UP mid-bounce
            # but the engine holds the link down
            if storm.link_up(FLAP) and eng.state(FLAP) == QUARANTINED:
                held_while_up = True
                assert FLAP in eng.dark_link_ids()

            # every chain healthy-or-EXPLICITLY-degraded after repair
            dark = eng.dark_link_ids()
            for hop_key, ids in mgr._chain_hops.items():
                clean = not any(e in dark for e in ids)
                if not (clean or hop_key in mgr._degraded_hops):
                    unconverged_chain_rounds.append((rnd, hop_key))

            if storm.quiet() and converged_at is None \
                    and all(r["state"] == HEALTHY
                            for r in eng.state_table()) \
                    and eng.slice_degraded() is None:
                converged_at = rnd
                break
    tracer.assert_no_cycles()  # zero wedged locks across the storm

    assert spurious_deletion_rounds == []
    assert unconverged_chain_rounds == []
    assert held_while_up, "flapping link was re-admitted per bounce"
    assert converged_at is not None and converged_at <= CONVERGE_BOUND, \
        f"storm did not converge within {CONVERGE_BOUND} rounds"
    holddowns = metrics.FAULT_FLAP_HOLDDOWNS.value(kind="link") \
        - flap_before
    assert holddowns >= 1  # the flapper's hold-down doubled
    assert eng.recoveries, "no recovery MTTR was recorded"
    recovered_units = {u for u, _ in eng.recoveries}
    assert FLAP in recovered_units
    assert "chip-12" in recovered_units
    # both chains ended explicitly degraded (steered off dark links)
    assert {("default", "ca", 0), ("default", "cb", 0)} \
        <= mgr._degraded_hops

    mttrs = sorted(s for _, s in eng.recoveries)
    artifact = {
        "seed": SEED,
        "topology": topo.topology,
        "round_seconds": ROUND_S,
        "rounds_to_converge": converged_at,
        "converge_bound_rounds": CONVERGE_BOUND,
        "storm": {"link_flap_rounds": [1, 2, 5, 6, 9, 10],
                  "chip_dead": {"unit": "chip-12", "rounds": [2, 8]},
                  "host_lost": {"host": 1, "rounds": [12, 18]},
                  "random_flaps": 3},
        "spurious_listandwatch_deletions": 0,
        "flap_holddowns": holddowns,
        "lock_order_cycles": 0,
        "recoveries": len(eng.recoveries),
        "mttr_s": {
            "mean": round(sum(mttrs) / len(mttrs), 3),
            "p50": round(mttrs[len(mttrs) // 2], 3),
            "max": round(max(mttrs), 3),
        },
        "per_unit_mttr_s": {u: round(s, 3)
                            for u, s in sorted(eng.recoveries)},
    }
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo_root, "FAULT_r01.json"), "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
