"""North-star end-to-end: examples/*.yaml through the whole framework.

Port of the reference's two-cluster e2e suite (e2e_test/e2e_test.go) onto
the fake backbone: operator reconcile → daemon → real device-plugin +
CNI wire traffic → GoogleTpuVsp over the NATIVE C++ control agent → SFC NF
pods wired into the ICI mesh → JAX allreduce (the traffic-flow analog,
:348-513) — all hardware-free, like the reference's Kind+Fake tier.
"""

import json
import os
import subprocess
import time

import pytest
import yaml

from dpu_operator_tpu.api.types import TpuOperatorConfig
from dpu_operator_tpu.controller.tpuoperatorconfig_controller import (
    TpuOperatorConfigReconciler)
from dpu_operator_tpu.daemon import TpuSideManager
from dpu_operator_tpu.deviceplugin.fake_kubelet import FakeKubelet
from dpu_operator_tpu.k8s.manager import Manager
from dpu_operator_tpu.cni import CniShim
from dpu_operator_tpu.platform.platform import FakePlatform
from dpu_operator_tpu.platform.vendordetector import TpuDetector
from dpu_operator_tpu.utils import vars as v
from dpu_operator_tpu.utils.filesystem_mode_detector import (
    FilesystemModeDetector)
from dpu_operator_tpu.utils.path_manager import PathManager
from dpu_operator_tpu.vsp.google import GoogleTpuVsp
from dpu_operator_tpu.vsp.native_dp import (AgentClient, AgentProcess,
                                            NativeIciDataplane)
from dpu_operator_tpu.vsp.plugin import GrpcPlugin
from dpu_operator_tpu.vsp.rpc import VspServer
from dpu_operator_tpu.webhook import WebhookServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")


def _load_example(name):
    with open(os.path.join(EXAMPLES, name)) as f:
        return yaml.safe_load(f)


@pytest.fixture(scope="session")
def agent_binary():
    subprocess.run(["make", "-C", os.path.join(REPO, "native")], check=True,
                   capture_output=True)
    return os.path.join(REPO, "native", "build", "tpu_cp_agent")


@pytest.fixture
def stack(kube, node_agent, images, short_tmp, agent_binary):
    """Full tpu-side stack on one fake node: operator manager + daemon
    side-manager with GoogleTpuVsp over the native agent + fake kubelet."""
    pm = PathManager(short_tmp)
    node_agent.register_node("tpu-vm-0", labels={"tpu": "true"})
    kubelet = FakeKubelet(pm, node_agent=node_agent, node_name="tpu-vm-0")
    kubelet.start()

    # operator control plane
    op_mgr = Manager(kube)
    op_mgr.add_reconciler(TpuOperatorConfigReconciler(
        images, path_manager=pm,
        fs_detector=FilesystemModeDetector(short_tmp)))
    op_mgr.start()

    # native control agent + GoogleTpuVsp on the vendor-plugin socket
    agent = AgentProcess(agent_binary, short_tmp + "/cp.sock",
                         state_file=short_tmp + "/cp.state",
                         dev_dir=short_tmp, allow_regular_dev=True)
    agent.start()
    accel = []
    for i in range(4):
        path = f"{short_tmp}/accel{i}"
        open(path, "w").close()
        accel.append(path)
    client_cp = AgentClient(agent.socket_path)
    platform = FakePlatform(accelerator_type="v5litepod-16", accel=accel)
    vsp_impl = GoogleTpuVsp(platform,
                            dataplane=NativeIciDataplane(client_cp))
    sock = pm.vendor_plugin_socket()
    pm.ensure_socket_dir(sock)
    vsp_server = VspServer(vsp_impl, socket_path=sock)
    vsp_server.start()

    det = TpuDetector().detection_result(tpu_mode=True, identifier="e2e")
    mgr = TpuSideManager(GrpcPlugin(det, path_manager=pm, init_timeout=5.0),
                        pm, client=kube, workload_image="default-workload")
    mgr.device_plugin.poll_interval = 0.1
    mgr.start_vsp()
    mgr.setup_devices()
    mgr.listen()
    mgr.serve()

    webhook = WebhookServer(kube, switch_poll_interval=60.0)
    webhook.start()

    yield {
        "kube": kube, "agent_client": client_cp, "pm": pm, "mgr": mgr,
        "kubelet": kubelet, "vsp": vsp_impl, "webhook": webhook,
        "op_mgr": op_mgr, "node_agent": node_agent,
    }

    webhook.stop()
    mgr.stop()
    vsp_server.stop()
    client_cp.close()
    agent.stop()
    op_mgr.stop()
    kubelet.stop()


def _cni(shim, command, container, ifname, device):
    return shim.invoke(
        {"CNI_COMMAND": command, "CNI_CONTAINERID": container,
         "CNI_NETNS": f"/var/run/netns/{container}", "CNI_IFNAME": ifname,
         "CNI_ARGS": "K8S_POD_NAMESPACE=default;K8S_POD_NAME=p"},
        json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                    "mode": "network-function", "deviceID": device}))


def test_north_star_sfc_to_allreduce(stack):
    """examples/tpu.yaml + examples/sfc.yaml → operator renders the node
    plumbing, SFC NF pods schedule against real device-plugin allocatable,
    CNI wires each pod's two attachments through GoogleTpuVsp into the
    native agent, and the slice runs a JAX allreduce."""
    kube = stack["kube"]

    # 1. operator config reconciles into node plumbing
    kube.create(_load_example("tpu.yaml"))
    assert stack["op_mgr"].wait_idle(10)
    assert kube.get("apps/v1", "DaemonSet", "tpu-daemon",
                    namespace=v.NAMESPACE) is not None
    assert kube.get("k8s.cni.cncf.io/v1", "NetworkAttachmentDefinition",
                    v.DEFAULT_NAD_NAME, namespace="default") is not None

    # 2. device plugin advertises the 4 local chips of the v5e-16 slice
    assert stack["kubelet"].wait_for_devices("google.com/tpu", 4)
    node = kube.get("v1", "Node", "tpu-vm-0")
    assert node["status"]["allocatable"]["google.com/tpu"] == "4"

    # 3. SFC CR → NF pods (2 chips each; e2e_test.go:425-445 assertions)
    kube.create(_load_example("sfc.yaml"))
    deadline = time.monotonic() + 10
    pods = []
    while time.monotonic() < deadline:
        pods = [p for p in kube.list("v1", "Pod", namespace="default")
                if p["metadata"].get("labels", {}).get("app")
                == "tpu-network-function"]
        if len(pods) == 2:
            break
        time.sleep(0.05)
    assert len(pods) == 2
    for pod in pods:
        res = pod["spec"]["containers"][0]["resources"]
        assert res["requests"]["google.com/tpu"] == "2"
        assert pod["metadata"]["annotations"][
            "k8s.v1.cni.cncf.io/networks"].count(v.DEFAULT_NAD_NAME) == 2
        assert pod["status"]["phase"] == "Running"  # 4 chips cover 2 pods

    # 4. kubelet allocates chips; CNI ADD x2 per pod wires the NF through
    #    the native agent
    shim = CniShim(stack["pm"].cni_server_socket())
    chip = 0
    for pod in pods:
        sandbox = "sbx-" + pod["metadata"]["name"]
        stack["kubelet"].allocate("google.com/tpu",
                                  [f"chip-{chip}", f"chip-{chip + 1}"])
        r1 = _cni(shim, "ADD", sandbox, "net1", f"chip-{chip}")
        assert r1.error == ""
        r2 = _cni(shim, "ADD", sandbox, "net2", f"chip-{chip + 1}")
        assert r2.error == ""
        assert r2.result["tpu"]["networkFunction"] is True
        chip += 2

    # 5. the native agent holds two NF wires (one per pod)
    # (enumerate proves the slice is programmed as v5e-16)
    chips = stack["agent_client"].enumerate()
    assert len(chips) == 16

    # 6. traffic-flow analog: allreduce over the slice mesh shape
    from dpu_operator_tpu.workloads import (measure_allreduce_gbps,
                                            mesh_for_topology)
    mesh = mesh_for_topology("v5e-16")  # degrades to the 8 CPU devices
    result = measure_allreduce_gbps(mesh, "model", mbytes=0.5, iters=2)
    assert result["algbw_gbps"] > 0


def _cni_nf(shim, command, container, ifname, device, pod, ici_ports=()):
    return shim.invoke(
        {"CNI_COMMAND": command, "CNI_CONTAINERID": container,
         "CNI_NETNS": f"/var/run/netns/{container}", "CNI_IFNAME": ifname,
         "CNI_ARGS": f"K8S_POD_NAMESPACE=default;K8S_POD_NAME={pod}"},
        json.dumps({"cniVersion": "0.4.0", "type": "tpu-cni",
                    "mode": "network-function", "deviceID": device,
                    "iciPorts": list(ici_ports)}))


def test_sfc_chain_steered_over_allocated_ici_ports(stack):
    """VERDICT r2 #2 end-to-end: NF pods request google.com/ici-port: 2
    alongside chips, kubelet Allocate returns the port ids (TPU_ICI_PORTS
    env), the runtime passes them into the CNI (NetConf iciPorts), and the
    chain hop lands in the NATIVE agent's wire table addressed by the
    allocated ports — not by topology inference."""
    kube, kubelet = stack["kube"], stack["kubelet"]
    kube.create(_load_example("tpu.yaml"))
    assert stack["op_mgr"].wait_idle(10)
    assert kubelet.wait_for_devices("google.com/tpu", 4)

    from dpu_operator_tpu.ici import SliceTopology
    n_ports = len(SliceTopology("v5e-16").ici_ports_on_host(0))
    assert kubelet.wait_for_devices("google.com/ici-port", n_ports)
    node = kube.get("v1", "Node", "tpu-vm-0")
    assert node["status"]["allocatable"]["google.com/ici-port"] == str(n_ports)

    kube.create(_load_example("sfc.yaml"))
    deadline = time.monotonic() + 10
    pods = []
    while time.monotonic() < deadline:
        pods = [p for p in kube.list("v1", "Pod", namespace="default")
                if p["metadata"].get("labels", {}).get("app")
                == "tpu-network-function"]
        if len(pods) == 2 and all(p["status"].get("phase") == "Running"
                                  for p in pods):
            break
        time.sleep(0.05)
    assert len(pods) == 2
    pods.sort(key=lambda p: int(
        p["metadata"]["annotations"]["tpu.openshift.io/sfc-index"]))
    for pod in pods:
        res = pod["spec"]["containers"][0]["resources"]
        assert res["requests"]["google.com/ici-port"] == "2"
        assert pod["status"]["phase"] == "Running"

    shim = CniShim(stack["pm"].cni_server_socket())
    pod_ports = {}
    chip = 0
    for i, pod in enumerate(pods):
        name = pod["metadata"]["name"]
        # admission order per pod: chips first, then ports via the
        # plugin's OWN GetPreferredAllocation (VERDICT r3 #3: the test
        # no longer hand-picks ports; a real kubelet would not)
        kubelet.allocate("google.com/tpu", [f"chip-{chip}",
                                            f"chip-{chip + 1}"])
        resp, ports = kubelet.allocate_preferred("google.com/ici-port", 2)
        pod_ports[name] = ports
        # co-allocation: the plugin aligned each port with one of the
        # pod's chips, ingress on the first, egress on the second
        assert ports[0].startswith(f"ici-{chip}-"), ports
        assert ports[1].startswith(f"ici-{chip + 1}-"), ports
        envs = dict(resp.container_responses[0].envs)
        assert envs["TPU_ICI_PORTS"] == ",".join(ports)
        sandbox = "sbx-" + name
        r1 = _cni_nf(shim, "ADD", sandbox, "net1", f"chip-{chip}", name,
                     ici_ports=envs["TPU_ICI_PORTS"].split(","))
        assert r1.error == ""
        r2 = _cni_nf(shim, "ADD", sandbox, "net2", f"chip-{chip + 1}", name,
                     ici_ports=envs["TPU_ICI_PORTS"].split(","))
        assert r2.error == ""
        chip += 2

    a_ports = pod_ports[pods[0]["metadata"]["name"]]
    b_ports = pod_ports[pods[1]["metadata"]["name"]]
    wires = stack["agent_client"].list_wires()
    # the hop between NF 0 and NF 1 is addressed by the ALLOCATED ports:
    # upstream egress (a's 2nd port) -> downstream ingress (b's 1st port)
    assert (a_ports[1], b_ports[0]) in wires, wires


def test_webhook_validation_cases(stack):
    """Port of e2e_test.go:188-330 webhook validation matrix."""
    wh = stack["webhook"]

    def validate(obj):
        return wh.review_validate({"request": {"uid": "u", "object": obj,
                                               "operation": "CREATE"}})

    ok = TpuOperatorConfig().to_obj()
    assert validate(ok)["response"]["allowed"] is True
    bad_name = TpuOperatorConfig(name="other").to_obj()
    assert validate(bad_name)["response"]["allowed"] is False
    bad_mode = TpuOperatorConfig().to_obj()
    bad_mode["spec"]["mode"] = "gpu"
    assert validate(bad_mode)["response"]["allowed"] is False
    bad_topo = TpuOperatorConfig().to_obj()
    bad_topo["spec"]["sliceTopology"] = "v9z-1"
    assert validate(bad_topo)["response"]["allowed"] is False


def test_secondary_network_pod_via_injector(stack):
    """Workload pod with a secondary-network annotation gets TPU resources
    injected (e2e_test.go:399-423 analog: the pods can then be scheduled
    against allocatable chips)."""
    kube = stack["kube"]
    kube.create({
        "apiVersion": "k8s.cni.cncf.io/v1",
        "kind": "NetworkAttachmentDefinition",
        "metadata": {"name": v.DEFAULT_NAD_NAME, "namespace": "default",
                     "annotations": {"k8s.v1.cni.cncf.io/resourceName":
                                     "google.com/tpu"}},
        "spec": {"config": "{}"}})
    pod = _load_example("my-pod.yaml")
    out = stack["webhook"].review_mutate(
        {"request": {"uid": "u", "object": pod}})
    assert out["response"]["allowed"] is True
    import base64
    patches = json.loads(base64.b64decode(out["response"]["patch"]))
    kinds = {p["path"]: p["value"] for p in patches}
    assert kinds["/spec/containers/0/resources/requests"][
        "google.com/tpu"] == "1"


def test_sfc_resource_exhaustion_n_plus_one(stack):
    """e2e_test.go:525-593: one more SFC than capacity leaves its pod
    Pending; deleting an earlier SFC unblocks it."""
    kube = stack["kube"]
    assert stack["kubelet"].wait_for_devices("google.com/tpu", 4)

    def sfc(name, nf):
        return {"apiVersion": "config.tpu.openshift.io/v1",
                "kind": "ServiceFunctionChain",
                "metadata": {"name": name, "namespace": "default"},
                "spec": {"networkFunctions": [{"name": nf, "image": "i"}]}}

    kube.create(sfc("sfc-1", "nf-a"))  # 2 chips
    kube.create(sfc("sfc-2", "nf-b"))  # 2 chips -> node full
    kube.create(sfc("sfc-3", "nf-c"))  # must stay Pending

    def phase(name):
        pod = kube.get("v1", "Pod", name, namespace="default")
        return pod["status"]["phase"] if pod else None

    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if (phase("sfc-1-nf-a") == "Running"
                and phase("sfc-2-nf-b") == "Running"
                and phase("sfc-3-nf-c") == "Pending"):
            break
        time.sleep(0.05)
    assert phase("sfc-3-nf-c") == "Pending"

    kube.delete("config.tpu.openshift.io/v1", "ServiceFunctionChain", "sfc-1",
                namespace="default")
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        stack["node_agent"].sync()  # scheduler pass after capacity freed
        if phase("sfc-3-nf-c") == "Running":
            break
        time.sleep(0.05)
    assert phase("sfc-3-nf-c") == "Running"


def test_chain_self_heals_on_ici_link_failure(stack):
    """Fault-injected e2e: an SFC hop steered over allocated ici-ports is
    re-wired through the NATIVE agent when its link is forced down —
    the wire table swaps to the degraded attachment endpoint and the dead
    hop disappears (the reference's chain rules have no repair path)."""
    kube, kubelet = stack["kube"], stack["kubelet"]
    kube.create(_load_example("tpu.yaml"))
    assert stack["op_mgr"].wait_idle(10)
    assert kubelet.wait_for_devices("google.com/tpu", 4)

    from dpu_operator_tpu.ici import SliceTopology
    n_ports = len(SliceTopology("v5e-16").ici_ports_on_host(0))
    assert kubelet.wait_for_devices("google.com/ici-port", n_ports)

    kube.create(_load_example("sfc.yaml"))
    deadline = time.monotonic() + 10
    pods = []
    while time.monotonic() < deadline:
        pods = [p for p in kube.list("v1", "Pod", namespace="default")
                if p["metadata"].get("labels", {}).get("app")
                == "tpu-network-function"]
        if len(pods) == 2 and all(p["status"].get("phase") == "Running"
                                  for p in pods):
            break
        time.sleep(0.05)
    assert len(pods) == 2
    pods.sort(key=lambda p: int(
        p["metadata"]["annotations"]["tpu.openshift.io/sfc-index"]))

    shim = CniShim(stack["pm"].cni_server_socket())
    sandboxes, pod_ports = [], []
    chip = 0
    for i, pod in enumerate(pods):
        name = pod["metadata"]["name"]
        # chips first, then plugin-preferred ports: each pod's ports land
        # on its OWN chips (far-end ports of unattached chips are unwired
        # and cannot carry a hop)
        kubelet.allocate("google.com/tpu", [f"chip-{chip}",
                                            f"chip-{chip + 1}"])
        _, ports = kubelet.allocate_preferred("google.com/ici-port", 2)
        assert ports[0].startswith(f"ici-{chip}-"), ports
        assert ports[1].startswith(f"ici-{chip + 1}-"), ports
        pod_ports.append(ports)
        sandbox = "sbx-heal-" + name
        sandboxes.append(sandbox)
        for ifname, dev in (("net1", f"chip-{chip}"),
                            ("net2", f"chip-{chip + 1}")):
            r = _cni_nf(shim, "ADD", sandbox, ifname, dev, name,
                        ici_ports=ports)
            assert r.error == ""
        chip += 2

    agent = stack["agent_client"]
    hop = (pod_ports[0][1], pod_ports[1][0])
    assert hop in agent.list_wires()

    # force the upstream egress link down and run a repair pass; the
    # agent is shared session state, so ALWAYS restore the link
    import re as _re
    m = _re.match(r"^ici-(\d+)-(.+)$", hop[0])
    agent.set_link(int(m.group(1)), m.group(2), up=False)
    try:
        mgr = stack["mgr"]
        mgr.link_prober = agent.link_state
        repaired = mgr.repair_chains()
        assert len(repaired) == 1

        wires = agent.list_wires()
        assert hop not in wires
        fallback = (f"nf-{sandboxes[0][:12]}-chip-1", hop[1])
        assert fallback in wires

        # observability (VERDICT r3 #5): tpuctl get-chains over the
        # admin plane shows the re-steered hop as DEGRADED...
        from dpu_operator_tpu import tpuctl
        args = type("A", (), {
            "cmd": "get-chains",
            "daemon_addr": f"127.0.0.1:{mgr.bound_port}",
            "agent_socket": "", "vsp_socket": ""})()
        chains = tpuctl.run(args)["chains"]
        assert [(c["namespace"], c["name"]) for c in chains] == [
            ("default", "my-sfc")]
        assert chains[0]["hops"] == [
            {"index": 0, "input": fallback[0], "output": fallback[1],
             "degraded": True}]

        # ...and the SFC CR status surfaces ChainDegraded through the
        # reconciler's live provider (driven synchronously here; the
        # daemon's manager resyncs the same path every 5 s)
        from dpu_operator_tpu.daemon.sfc_reconciler import SfcReconciler
        from dpu_operator_tpu.k8s.manager import Request
        rec = SfcReconciler(workload_image="w",
                            chain_status_provider=mgr.chain_status)
        rec.reconcile(kube, Request("config.tpu.openshift.io/v1",
                                    "ServiceFunctionChain", "my-sfc",
                                    "default"))
        obj = kube.get("config.tpu.openshift.io/v1",
                       "ServiceFunctionChain", "my-sfc",
                       namespace="default")
        conds = {c["type"]: c["status"]
                 for c in obj["status"]["conditions"]}
        assert conds["ChainDegraded"] == "True"
        assert conds["NFsReady"] == "True"
    finally:
        agent.set_link(int(m.group(1)), m.group(2), up=True)


def test_dark_port_leaves_allocatable_and_is_never_preferred(stack):
    """VERDICT r3 #3: a fault-injected ICI link makes its port Unhealthy
    (the ici-port parity of the reference's Allocate gating,
    deviceplugin.go:127-129): node allocatable drops, a new SFC pod's
    plugin-preferred allocation never returns the dark port, and a direct
    Allocate of it is refused."""
    import grpc

    kube, kubelet = stack["kube"], stack["kubelet"]
    kube.create(_load_example("tpu.yaml"))
    assert stack["op_mgr"].wait_idle(10)
    assert kubelet.wait_for_devices("google.com/tpu", 4)

    from dpu_operator_tpu.ici import SliceTopology
    n_ports = len(SliceTopology("v5e-16").ici_ports_on_host(0))
    assert kubelet.wait_for_devices("google.com/ici-port", n_ports)

    mgr, agent = stack["mgr"], stack["agent_client"]
    # wire the prober the way serve() does when the agent socket is local
    mgr.link_prober = agent.link_state
    ici_dp = mgr.ici_device_plugin

    # darken chip-2's first port: the next pod's chips will be 2 and 3,
    # so without health gating this would be the FIRST preferred pick
    dark = "ici-2-x+"
    agent.set_link(2, "x+", up=False)
    try:
        ici_dp.refresh()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            devs = {d.ID: d.health
                    for d in kubelet.device_lists["google.com/ici-port"]}
            if devs.get(dark) == "Unhealthy":
                break
            time.sleep(0.05)
        else:
            raise AssertionError("dark port never went Unhealthy")
        # healthy count (node allocatable) drops by one
        node = kube.get("v1", "Node", "tpu-vm-0")
        assert node["status"]["allocatable"]["google.com/ici-port"] == str(
            n_ports - 1)

        # a new pod admits: chips first, then plugin-preferred ports —
        # the dark port is excluded even though its chip is the pod's
        kubelet.allocate("google.com/tpu", ["chip-2", "chip-3"])
        _, ports = kubelet.allocate_preferred("google.com/ici-port", 2)
        assert dark not in ports
        assert ports[0].startswith("ici-2-"), ports  # still co-located
        assert ports[1].startswith("ici-3-"), ports

        # direct Allocate of the dark port is refused at admission
        with pytest.raises(grpc.RpcError) as err:
            kubelet.allocate("google.com/ici-port", [dark])
        assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION
    finally:
        agent.set_link(2, "x+", up=True)
        ici_dp.refresh()
