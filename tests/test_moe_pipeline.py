"""Expert parallelism (MoE) + pipeline parallelism on the virtual mesh.

Completes the parallelism matrix the framework advertises (dp/tp/sp/ring
were rounds 1-2): ep = switch-style experts sharded over "model"
(workloads/moe.py), pp = GPipe microbatch pipelining over a "pipe" axis
with ppermute hops (workloads/pipeline.py). The operator side is
unchanged — these prove the programmed slice topology carries both.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dpu_operator_tpu.workloads.mesh import make_mesh
from dpu_operator_tpu.workloads.model import TransformerConfig
from dpu_operator_tpu.workloads import moe


def test_single_expert_moe_equals_dense_ffn():
    """E=1 routes every token to the one expert with gate 1.0, so the MoE
    FFN must equal the dense FFN with the same weights exactly."""
    rng = jax.random.key(0)
    d, f = 16, 32
    params = moe.init_moe_params(rng, d, f, n_experts=1, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(1), (2, 8, d), jnp.float32)
    out, aux = moe.moe_ffn(params, x, capacity_factor=1.0)
    dense = jax.nn.gelu(x @ params["w1"][0]) @ params["w2"][0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               atol=1e-5, rtol=1e-5)
    assert float(aux) == pytest.approx(1.0)  # E * f_e * P_e = 1 * 1 * 1


def test_moe_capacity_drops_overflow_tokens():
    """Tokens past an expert's static capacity fall back to the residual
    path (output contribution 0) instead of breaking static shapes."""
    rng = jax.random.key(0)
    d, f = 8, 16
    params = moe.init_moe_params(rng, d, f, n_experts=2, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(1), (1, 64, d), jnp.float32)
    out, _ = moe.moe_ffn(params, x, capacity_factor=0.25)
    # expected survivors: per expert, min(routed count, static capacity)
    cap = moe.moe_capacity(64, 2, 0.25)
    idx = jnp.argmax(x.reshape(64, d) @ params["wg"], axis=-1)
    counts = jnp.bincount(idx, length=2)
    expected = int(jnp.sum(jnp.minimum(counts, cap)))
    nonzero_rows = int(jnp.sum(jnp.any(out[0] != 0, axis=-1)))
    assert nonzero_rows == expected
    assert expected < 64  # the tiny capacity really dropped tokens


def test_moe_capacity_is_mxu_aligned():
    assert moe.moe_capacity(64, 2, 0.25) == 8
    assert moe.moe_capacity(1000, 8, 1.25) == 160  # ceil(156.25) -> 157 -> 160
    assert moe.moe_capacity(4, 4, 1.0) == 8        # floor of 8


def test_moe_train_step_ep_sharded_loss_decreases():
    """Full train step with experts sharded over "model" (ep): loss falls
    and the expert weights really carry the ep spec."""
    from dpu_operator_tpu.workloads import (make_example_batch,
                                            make_train_step)
    from dpu_operator_tpu.workloads.model import param_specs
    from jax.sharding import PartitionSpec as P

    cfg = TransformerConfig(n_layers=2, d_model=32, n_heads=4, d_ff=64,
                            max_seq=32, vocab=128, moe_experts=8)
    specs = param_specs(cfg)
    assert specs["layers"][1]["moe"]["w1"] == P("model", None, None)
    assert "w1" not in specs["layers"][1]
    assert specs["layers"][0]["w1"] == P(None, "model")  # dense layer keeps tp

    mesh = make_mesh(("data", "model"), axis_sizes=(2, 4))
    step, init_state, place = make_train_step(cfg, mesh)
    params, opt = init_state(jax.random.key(0))
    batch = place(make_example_batch(cfg, batch=4))
    losses = []
    for _ in range(5):
        params, opt, loss = step(params, opt, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_moe_ring_mode_replicates_experts():
    from dpu_operator_tpu.workloads.model import param_specs
    from jax.sharding import PartitionSpec as P

    cfg = TransformerConfig(n_layers=2, attention="ring", moe_experts=4)
    specs = param_specs(cfg)
    assert specs["layers"][1]["moe"]["w1"] == P()


# -- pipeline parallelism -----------------------------------------------------

def _pp_cfg(**kw):
    base = dict(n_layers=4, d_model=32, n_heads=4, d_ff=64, max_seq=16,
                vocab=64, dtype=jnp.float32)
    base.update(kw)
    return TransformerConfig(**base)


def test_pipeline_forward_matches_sequential():
    """The pipelined forward (4 stages x 4 microbatches over ppermute)
    must equal running the same stacked layers sequentially."""
    from dpu_operator_tpu.workloads import pipeline

    cfg = _pp_cfg()
    mesh = make_mesh(("pipe", "data"), axis_sizes=(4, 2))
    params = pipeline.init_pipeline_params(jax.random.key(0), cfg,
                                           n_stages=4)
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, cfg.vocab)
    fwd = pipeline.make_pipeline_forward(cfg, mesh, n_micro=4)
    with jax.sharding.use_mesh(mesh) if hasattr(
            jax.sharding, "use_mesh") else mesh:
        piped = jax.jit(fwd)(params, tokens)
    ref = pipeline.sequential_forward(cfg, params, tokens)
    np.testing.assert_allclose(np.asarray(piped), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_pipeline_train_step_loss_decreases():
    from dpu_operator_tpu.workloads import make_example_batch, pipeline

    cfg = _pp_cfg(dtype=jnp.bfloat16)
    mesh = make_mesh(("pipe", "data"), axis_sizes=(4, 2))
    step, init_state, place = pipeline.make_pipeline_train_step(
        cfg, mesh, n_micro=4)
    params, opt = init_state(jax.random.key(0))
    batch = place(make_example_batch(cfg, batch=8, seq=16))
    losses = []
    for _ in range(6):
        params, opt, loss = step(params, opt, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_pipeline_rejects_uneven_layer_split():
    from dpu_operator_tpu.workloads import pipeline

    with pytest.raises(ValueError, match="stages"):
        pipeline.init_pipeline_params(jax.random.key(0),
                                      _pp_cfg(n_layers=5), n_stages=4)


def test_pipeline_program_one_hop_per_tick():
    """The lowered pipeline carries ppermute hops (neighbor transfers on
    the programmed ICI path), not all-gathers of the whole activation
    set."""
    from dpu_operator_tpu.workloads import pipeline

    cfg = _pp_cfg()
    mesh = make_mesh(("pipe", "data"), axis_sizes=(4, 2))
    params = pipeline.init_pipeline_params(jax.random.key(0), cfg, 4)
    tokens = jnp.zeros((8, 16), jnp.int32)
    fwd = pipeline.make_pipeline_forward(cfg, mesh, n_micro=4)
    txt = jax.jit(fwd).lower(params, tokens).as_text()
    assert "collective-permute" in txt or "collective_permute" in txt
