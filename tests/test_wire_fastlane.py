"""Wire-path fast lane: pooled apiserver client, coalesced journal
writes, and the SFC reconciler's batched pod listing (ISSUE 1 tentpole).

The pool is exercised against the real HTTPS MiniApiServer (keep-alive
reuse, stale-socket reconnect); journal coalescing and LIST batching are
asserted at the call-count level — the behaviors the bench's
`wire_requests_per_conn` counter and the journal metrics guard.
"""

import threading

import pytest

from apiserver_fixture import MiniApiServer
from dpu_operator_tpu.daemon.sfc_reconciler import SfcReconciler
from dpu_operator_tpu.daemon.tpusidemanager import TpuSideManager
from dpu_operator_tpu.k8s.fake import FakeKube
from dpu_operator_tpu.k8s.manager import Request
from dpu_operator_tpu.k8s.real import RealKube
from dpu_operator_tpu.utils import metrics


@pytest.fixture()
def wire_kube(tmp_path):
    srv = MiniApiServer().start()
    kube = RealKube(kubeconfig=srv.write_kubeconfig(
        str(tmp_path / "kubeconfig")))
    yield kube
    kube.close()
    srv.stop()


# -- pooled client ------------------------------------------------------------
def test_pool_reuses_one_connection_across_requests(wire_kube):
    kube = wire_kube
    assert kube.pool is not None, "direct HTTPS must ride the pool"
    kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                 "metadata": {"name": "cm", "namespace": "default"},
                 "data": {"a": "1"}})
    for _ in range(10):
        assert kube.get("v1", "ConfigMap", "cm",
                        namespace="default") is not None
    stats = kube.connection_stats()
    assert stats["connections_opened"] == 1
    assert stats["requests"] == 11
    assert stats["requests_per_connection"] > 1


def test_pool_reconnects_on_stale_socket(wire_kube):
    kube = wire_kube
    kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                 "metadata": {"name": "cm2", "namespace": "default"},
                 "data": {}})
    # kill the idle pooled socket under the client — the apiserver
    # dropping a keep-alive connection while it idles
    with kube.pool._lock:
        assert kube.pool._idle
        for conn in kube.pool._idle:
            conn.sock.close()
    assert kube.get("v1", "ConfigMap", "cm2",
                    namespace="default") is not None
    stats = kube.connection_stats()
    assert stats["stale_reconnects"] >= 1
    assert stats["connections_opened"] == 2  # one fresh dial, not a storm


def test_pool_retry_bypasses_other_stale_idle_sockets(wire_kube):
    """An idle timeout kills EVERY parked socket at once: the retry
    after the first stale hit must dial fresh, not check out the next
    (equally dead) idle connection."""
    kube = wire_kube
    kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                 "metadata": {"name": "cm3", "namespace": "default"},
                 "data": {}})
    # park a second connection, then kill both while they idle. Two
    # parallel GETs do NOT guarantee two connections — the first can
    # return its socket to the pool before the second checks out and
    # both ride one conn (observed ~1/6 runs) — so gate each round on
    # a barrier and retry until the pool really holds two.
    import concurrent.futures as cf
    barrier = threading.Barrier(2)

    def synced_get(_):
        barrier.wait(timeout=10)
        return kube.get("v1", "ConfigMap", "cm3", namespace="default")

    for _ in range(20):
        with cf.ThreadPoolExecutor(2) as ex:
            list(ex.map(synced_get, range(2)))
        with kube.pool._lock:
            if len(kube.pool._idle) >= 2:
                break
    with kube.pool._lock:
        assert len(kube.pool._idle) >= 2, \
            "never parked two idle connections"
        for conn in kube.pool._idle:
            conn.sock.close()
    assert kube.get("v1", "ConfigMap", "cm3",
                    namespace="default") is not None


def test_duplicate_cni_del_does_not_deadlock(tmp_path):
    """A DEL for a sandbox with no in-memory entry (duplicate/defensive
    DEL) must complete: _flush_chains re-acquires _attach_lock, so the
    entry-None path has to release the lock first (review finding)."""
    from dpu_operator_tpu.cni import NetConfCache
    from dpu_operator_tpu.cni.types import NetConf, PodRequest

    mgr = _lean_mgr(tmp_path)
    mgr.ipam_dir = str(tmp_path / "ipam")
    mgr.nf_cache = NetConfCache(str(tmp_path / "nf"))
    req = PodRequest(command="DEL", pod_namespace="default",
                     pod_name="p", sandbox_id="sbx-none", netns="",
                     ifname="net1", device_id="chip-0",
                     netconf=NetConf.from_dict({"cniVersion": "0.4.0",
                                                "type": "tpu-cni"}))
    done = []
    t = threading.Thread(target=lambda: done.append(
        mgr._cni_nf_del(req)), daemon=True)
    t.start()
    t.join(timeout=10)
    assert done == [{}], "duplicate DEL deadlocked"


def test_pool_timeout_is_not_retried_as_stale(wire_kube):
    """A per-request timeout is a caller DEADLINE (the leader lease
    sizes one attempt per renew period): the pool must surface it
    within the bound, never burn a second attempt on a fresh dial."""
    import time

    kube = wire_kube
    kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                 "metadata": {"name": "slow", "namespace": "default"},
                 "data": {}})
    kube.get("v1", "ConfigMap", "slow", namespace="default")  # warm conn
    # stall the apiserver by patching the fixture's backing store
    from dpu_operator_tpu.k8s import fake as fake_mod
    orig = fake_mod.FakeKube.get

    def slow_get(self, *a, **kw):
        time.sleep(1.0)
        return orig(self, *a, **kw)

    fake_mod.FakeKube.get = slow_get
    try:
        t0 = time.monotonic()
        with pytest.raises(Exception) as exc:
            kube.get("v1", "ConfigMap", "slow", namespace="default",
                     timeout=0.2)
        elapsed = time.monotonic() - t0
    finally:
        fake_mod.FakeKube.get = orig
    assert isinstance(exc.value, TimeoutError)
    assert elapsed < 0.8, f"timeout doubled by a retry: {elapsed:.2f}s"
    assert kube.connection_stats()["stale_reconnects"] == 0


def test_pool_latency_histogram_observes_per_verb(wire_kube):
    before = metrics.KUBE_REQUEST_SECONDS.labels("get").count
    wire_kube.get("v1", "ConfigMap", "absent", namespace="default")
    assert metrics.KUBE_REQUEST_SECONDS.labels("get").count == before + 1
    rendered = "\n".join(metrics.KUBE_REQUEST_SECONDS._render())
    assert 'verb="get"' in rendered


def test_pool_concurrent_requests_are_consistent(wire_kube):
    kube = wire_kube
    kube.create({"apiVersion": "v1", "kind": "ConfigMap",
                 "metadata": {"name": "cc", "namespace": "default"},
                 "data": {"k": "v"}})
    errors = []

    def worker():
        try:
            for _ in range(20):
                got = kube.get("v1", "ConfigMap", "cc",
                               namespace="default")
                assert got["data"]["k"] == "v"
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    stats = kube.connection_stats()
    # 81 requests over at most 4 parallel sockets: reuse must dominate
    assert stats["requests"] == 81
    assert stats["connections_opened"] <= 4


def test_pool_preserves_base_url_path_prefix():
    """Proxied apiserver endpoints carry a path prefix
    (https://host/k8s/clusters/c-abc): the pool must re-apply it to the
    base-relative paths RealKube passes."""
    import ssl

    from dpu_operator_tpu.k8s.pool import HttpsConnectionPool

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    pool = HttpsConnectionPool("https://h:1/k8s/clusters/c-abc/", ctx)
    assert pool.path_prefix == "/k8s/clusters/c-abc"
    assert HttpsConnectionPool("https://h:1", ctx).path_prefix == ""


def test_pool_decodes_gzip_responses():
    """The pool advertises Accept-Encoding: gzip (apiserver compresses
    big LISTs) and must decode transparently."""
    import gzip

    from dpu_operator_tpu.k8s.pool import _decode_body

    body = b'{"items": []}'
    assert _decode_body({"Content-Encoding": "gzip"},
                        gzip.compress(body)) == body
    assert _decode_body({}, body) == body
    assert _decode_body({"content-encoding": "GZIP"},
                        gzip.compress(body)) == body


def test_topology_cache_is_bounded():
    """Topology strings reach cached() from remote peers (slicejoin):
    the prototype cache must evict, not grow forever."""
    from dpu_operator_tpu.ici import SliceTopology

    for n in (4, 8, 16, 32):
        for gen in ("v5e", "v5p", "v2", "v3", "v4", "v6e"):
            SliceTopology.cached(f"{gen}-{n}")
    assert len(SliceTopology._CACHE) <= SliceTopology._CACHE_MAX
    # cache still functions after eviction pressure
    s = SliceTopology.cached("v5e-16")
    assert s.num_chips == 16


def test_bench_p95_is_not_the_max():
    """Nearest-rank p95 at n=20 (the default pod count) must pick the
    19th sample, not the max (int(0.95*20)=19 was off by one)."""
    import bench

    samples = list(range(1, 21))
    assert bench._p95(samples) == 19
    assert bench._p95([5.0]) == 5.0
    assert bench._p95(list(range(1, 11))) == 10  # ceil(9.5)-1 = idx 9


# -- journal coalescing -------------------------------------------------------
def _lean_mgr(tmp_path):
    m = TpuSideManager.__new__(TpuSideManager)
    m.vsp = None
    m.client = None
    m._attach_store = {}
    m._attach_lock = threading.Lock()
    m._chain_store = {}
    m._chain_hops = {}
    m._degraded_hops = set()
    m._chains_file = str(tmp_path / "cache" / "chains.json")
    return m


def test_journal_coalesces_mutation_batch_into_one_write(tmp_path):
    mgr = _lean_mgr(tmp_path)
    flushes0 = metrics.JOURNAL_FLUSHES.value()
    with mgr._attach_lock:
        for i in range(10):
            mgr._chain_hops[("default", "sfc", i)] = (f"a{i}", f"b{i}")
            mgr._save_chains_locked()  # 10 mutations...
    mgr._flush_chains()  # ...one writer
    assert metrics.JOURNAL_FLUSHES.value() == flushes0 + 1
    import json
    with open(mgr._chains_file) as f:
        assert len(json.load(f)["hops"]) == 10


def test_journal_flush_is_noop_when_clean(tmp_path):
    import os
    mgr = _lean_mgr(tmp_path)
    with mgr._attach_lock:
        mgr._chain_hops[("default", "s", 0)] = ("a", "b")
        mgr._save_chains_locked()
    mgr._flush_chains()
    mtime = os.path.getmtime(mgr._chains_file)
    flushes = metrics.JOURNAL_FLUSHES.value()
    mgr._flush_chains()  # nothing dirty: no write
    assert metrics.JOURNAL_FLUSHES.value() == flushes
    assert os.path.getmtime(mgr._chains_file) == mtime


def test_journal_roundtrips_through_recovery(tmp_path):
    """Coalesced writes must still persist exactly what recovery needs
    (same contract the per-mutation journal had)."""
    mgr = _lean_mgr(tmp_path)
    with mgr._attach_lock:
        mgr._chain_hops[("default", "sfc", 0)] = ("out0", "in1")
        mgr._degraded_hops.add(("default", "sfc", 0))
        mgr._save_chains_locked()
    mgr._flush_chains()

    class _NoListVsp:
        pass  # no list_network_functions: journal trusted as-is

    fresh = _lean_mgr(tmp_path)
    fresh.vsp = _NoListVsp()
    fresh._recover_chains()
    assert fresh._chain_hops[("default", "sfc", 0)] == ("out0", "in1")
    assert ("default", "sfc", 0) in fresh._degraded_hops


# -- reconciler LIST batching -------------------------------------------------
class _CountingKube(FakeKube):
    def __init__(self):
        super().__init__()
        self.calls = []

    def get(self, api_version, kind, name, namespace=None, **kw):
        self.calls.append(("get", kind, name))
        return super().get(api_version, kind, name, namespace=namespace)

    def list(self, api_version, kind, namespace=None, label_selector=None):
        self.calls.append(("list", kind, tuple(sorted(
            (label_selector or {}).items()))))
        return super().list(api_version, kind, namespace=namespace,
                            label_selector=label_selector)


def test_reconciler_lists_nf_pods_once_per_chain():
    from dpu_operator_tpu.api.types import API_VERSION

    kube = _CountingKube()
    kube.create({"apiVersion": API_VERSION, "kind": "ServiceFunctionChain",
                 "metadata": {"name": "chain", "namespace": "default"},
                 "spec": {"networkFunctions": [
                     {"name": "f0"}, {"name": "f1"}, {"name": "f2"}]}})
    rec = SfcReconciler(workload_image="img")
    rec.reconcile(kube, Request(API_VERSION, "ServiceFunctionChain",
                                "chain", namespace="default"))
    pod_gets = [c for c in kube.calls if c[0] == "get" and c[1] == "Pod"]
    pod_lists = [c for c in kube.calls if c[0] == "list" and c[1] == "Pod"]
    assert not pod_gets, "per-NF pod GETs must be batched into the LIST"
    assert pod_lists == [("list", "Pod", (("sfc", "chain"),))]
    # created NF pods carry the label the LIST selects on
    pods = kube.list("v1", "Pod", namespace="default",
                     label_selector={"sfc": "chain"})
    assert sorted(p["metadata"]["name"] for p in pods) == [
        "chain-f0", "chain-f1", "chain-f2"]
    # second pass sees all three as existing without any Pod GET
    kube.calls.clear()
    rec.reconcile(kube, Request(API_VERSION, "ServiceFunctionChain",
                                "chain", namespace="default"))
    assert not [c for c in kube.calls
                if c[0] == "get" and c[1] == "Pod"]
