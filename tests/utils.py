"""Shared test helpers.

Port of the reference's AssertEventually with second-chance timing
diagnostics (internal/testutils/utils.go:31-58): when the condition only
becomes true after the deadline, fail with how late it was — turning
flaky-timeout failures into actionable reports.
"""

from __future__ import annotations

import time
from typing import Callable


def assert_eventually(condition: Callable[[], bool], timeout: float = 10.0,
                      interval: float = 0.05, message: str = "") -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if condition():
            return
        time.sleep(interval)
    # second chance: did it become true just after the deadline?
    late_deadline = time.monotonic() + timeout
    while time.monotonic() < late_deadline:
        if condition():
            late_by = time.monotonic() - deadline
            raise AssertionError(
                f"{message or 'condition'} became true {late_by:.2f}s AFTER "
                f"the {timeout}s deadline — raise the timeout or fix the "
                f"slowness")
        time.sleep(interval)
    raise AssertionError(
        f"{message or 'condition'} never became true within "
        f"{timeout}s (nor in the {timeout}s grace window)")
