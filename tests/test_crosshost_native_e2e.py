"""Cross-host SFC chains over REAL native agents, end to end.

The deepest tier of the round-5 cross-host story: two hosts, each with
its own C++ tpu_cp_agent (crash-safe state file), GoogleTpuVsp over the
native dataplane, and a full TpuSideManager on real sockets — sharing
one FakeKube. Proves on the actual dataplane what
tests/test_sfc_crosshost.py proves against mocks:

- a hop between NFs on different hosts lands in BOTH agents' wire
  tables (the egress half on the upstream host, the ingress half on the
  peer);
- link-fault repair re-steers the hop and MIRRORS the re-steer into the
  peer agent;
- a daemon restart re-runs VSP Init (now idempotent in the agent — a
  clearing re-Init used to erase live wiring) and the journal recovery
  reconciles against the agent's preserved wire table, so teardown of
  pre-restart hops still unwires both dataplanes.
"""

import os
import subprocess

import pytest

from dpu_operator_tpu.daemon import TpuSideManager
from dpu_operator_tpu.k8s import FakeKube
from dpu_operator_tpu.platform import FakePlatform
from dpu_operator_tpu.platform.vendordetector import TpuDetector
from dpu_operator_tpu.utils.path_manager import PathManager
from dpu_operator_tpu.vsp.google import GoogleTpuVsp
from dpu_operator_tpu.vsp.native_dp import (AgentClient, AgentProcess,
                                            NativeIciDataplane)
from dpu_operator_tpu.vsp.plugin import GrpcPlugin
from dpu_operator_tpu.vsp.rpc import VspServer

from test_sfc_crosshost import _Req, _nf_pod, _sfc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def agent_binary():
    subprocess.run(["make", "-C", os.path.join(REPO, "native")],
                   check=True, capture_output=True)
    return os.path.join(REPO, "native", "build", "tpu_cp_agent")


class _Host:
    """One host: native agent + GoogleTpuVsp(native dataplane) + full
    TpuSideManager with a kube client and node identity."""

    def __init__(self, root: str, name: str, agent_binary: str, kube):
        self.name = name
        self.kube = kube
        self.dir = os.path.join(root, name)
        os.makedirs(self.dir)
        self.pm = PathManager(self.dir)
        self.agent = AgentProcess(agent_binary, self.dir + "/cp.sock",
                                  state_file=self.dir + "/cp.state",
                                  dev_dir=self.dir, allow_regular_dev=True)
        self.agent.start()
        accel = []
        for i in range(4):
            path = f"{self.dir}/accel{i}"
            open(path, "w").close()
            accel.append(path)
        self.agent_client = AgentClient(self.agent.socket_path)
        self.vsp = GoogleTpuVsp(
            FakePlatform(accelerator_type="v5litepod-4", accel=accel),
            dataplane=NativeIciDataplane(self.agent_client), comm_port=0)
        sock = self.pm.vendor_plugin_socket()
        self.pm.ensure_socket_dir(sock)
        self.vsp_server = VspServer(self.vsp, socket_path=sock)
        self.vsp_server.start()
        self.mgr = None
        self._start_manager()

    def _start_manager(self):
        det = TpuDetector().detection_result(tpu_mode=True,
                                             identifier=self.name)
        self.mgr = TpuSideManager(
            GrpcPlugin(det, path_manager=self.pm, init_timeout=5.0),
            self.pm, client=self.kube, node_name=self.name)
        self.mgr.start_vsp()
        self.mgr.setup_devices()
        self.mgr.listen()
        self.mgr._advertise_address()

    def restart_manager(self):
        """The daemon process restarting: everything in-memory is lost;
        the VSP (separate pod) and its agent keep running."""
        self.mgr.stop()
        self._start_manager()

    def wires(self):
        return self.agent_client.list_wires()

    def stop(self):
        self.mgr.stop()
        self.vsp_server.stop()
        self.agent_client.close()
        self.agent.stop()


@pytest.fixture
def two_hosts(short_tmp, agent_binary):
    kube = FakeKube()
    for node in ("host-a", "host-b"):
        kube.create({"apiVersion": "v1", "kind": "Node",
                     "metadata": {"name": node}})
    a = _Host(short_tmp, "host-a", agent_binary, kube)
    b = _Host(short_tmp, "host-b", agent_binary, kube)
    yield kube, a, b
    b.stop()
    a.stop()


def _wire_nf(mgr, sandbox, pod, chips, ports):
    mgr._cni_nf_add(_Req(sandbox, chips[0], "net1", pod,
                         ici_ports=ports))
    mgr._cni_nf_add(_Req(sandbox, chips[1], "net2", pod,
                         ici_ports=ports))


def test_cross_host_hop_in_both_agent_wire_tables(two_hosts):
    kube, a, b = two_hosts
    _sfc(kube, "nx", ["f0", "f1"])
    _nf_pod(kube, "nx-f0", "nx", 0, "host-a")
    _nf_pod(kube, "nx-f1", "nx", 1, "host-b")
    _wire_nf(a.mgr, "sbxNA0000000", "nx-f0", ["chip-0", "chip-1"],
             ["ici-0-x+", "ici-1-x+"])
    _wire_nf(b.mgr, "sbxNB0000000", "nx-f1", ["chip-2", "chip-3"],
             ["ici-2-x+", "ici-3-x+"])
    a.mgr.sync_cross_host_hops("default", "nx")
    hop = ("ici-1-x+", "ici-2-x+")
    assert hop in a.wires()  # egress half programmed in A's dataplane
    assert hop in b.wires()  # ingress half programmed in B's dataplane


def test_link_fault_repair_mirrors_into_peer_agent(two_hosts):
    kube, a, b = two_hosts
    _sfc(kube, "nr", ["f0", "f1"])
    _nf_pod(kube, "nr-f0", "nr", 0, "host-a")
    _nf_pod(kube, "nr-f1", "nr", 1, "host-b")
    _wire_nf(a.mgr, "sbxNA1111111", "nr-f0", ["chip-0", "chip-1"],
             ["ici-0-x+", "ici-1-x+"])
    _wire_nf(b.mgr, "sbxNB1111111", "nr-f1", ["chip-2", "chip-3"],
             ["ici-2-x+", "ici-3-x+"])
    a.mgr.sync_cross_host_hops("default", "nr")
    old = ("ici-1-x+", "ici-2-x+")
    assert old in a.wires() and old in b.wires()
    # the allocated egress port's physical link goes dark on host A
    a.agent_client.set_link(1, "x+", up=False)
    a.mgr.link_prober = a.agent_client.link_state
    repaired = a.mgr.repair_chains()
    assert [k for k, _, _ in repaired] == [("default", "nr", 0)]
    steered = ("nf-sbxNA1111111-chip-1", "ici-2-x+")
    # BOTH dataplanes now steer the repaired pair; the dead pair is gone
    assert steered in a.wires() and old not in a.wires()
    assert steered in b.wires() and old not in b.wires()


def test_daemon_restart_recovers_against_agent_ground_truth(two_hosts):
    kube, a, b = two_hosts
    _sfc(kube, "ns", ["f0", "f1"])
    _nf_pod(kube, "ns-f0", "ns", 0, "host-a")
    _nf_pod(kube, "ns-f1", "ns", 1, "host-b")
    _wire_nf(a.mgr, "sbxNA2222222", "ns-f0", ["chip-0", "chip-1"],
             ["ici-0-x+", "ici-1-x+"])
    _wire_nf(b.mgr, "sbxNB2222222", "ns-f1", ["chip-2", "chip-3"],
             ["ici-2-x+", "ici-3-x+"])
    a.mgr.sync_cross_host_hops("default", "ns")
    hop = ("ici-1-x+", "ici-2-x+")
    assert hop in a.wires()

    a.restart_manager()
    # re-Init did NOT wipe the agent (idempotent same-topology init) and
    # recovery restored the hop from journal ∩ agent wire table
    assert hop in a.wires()
    hop_key = ("default", "ns", 0)
    assert a.mgr._chain_hops[hop_key] == hop
    assert a.mgr._remote_hops[hop_key]  # remote marker survived too

    # teardown of the pre-restart sandbox unwires BOTH dataplanes
    a.mgr._cni_nf_del(_Req("sbxNA2222222", None, "net1", "ns-f0"))
    assert hop not in a.wires()
    assert hop not in b.wires()
    assert hop_key not in a.mgr._chain_hops
