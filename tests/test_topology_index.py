"""Topology index consistency: the precomputed adjacency indexes must be
views of the same wiring the list scans used to derive (wire-path fast
lane, ISSUE 1 satellite).

Every indexed lookup is checked against a reference scan over the flat
``links``/``chips`` lists for a grid of v5e (2D mesh/torus) and v5p (3D
torus) shapes, including the extent-2 dimensions whose links must stay
deduplicated, plus the memoized-construction cache's independence
guarantees.
"""

import pytest

from dpu_operator_tpu.ici import SliceTopology
from dpu_operator_tpu.ici.topology import PORTS_PER_CHIP

#: v5e: 2D shapes incl. extent-2 dims (2x2, 2x4) and tori (8x8);
#: v5p: 3D shapes incl. the 4x4x4 full cube and extent-2 dims (2x2x2)
GRID = ["v5e-4", "v5e-8", "v5e-16", "v5e-64", "v5p-8", "v5p-16",
        "v5p-32", "v5p-64"]


@pytest.mark.parametrize("topology", GRID)
def test_links_from_matches_scan(topology):
    s = SliceTopology(topology)
    for chip in s.chips:
        scan = [l for l in s.links if l.src == chip.index]
        assert s.links_from(chip.index) == scan


@pytest.mark.parametrize("topology", GRID)
def test_host_indexes_match_scan(topology):
    s = SliceTopology(topology)
    for host in range(s.num_hosts):
        assert s.chips_on_host(host) == [
            c for c in s.chips if c.host == host]
        local = {c.index for c in s.chips_on_host(host)}
        assert s.ici_ports_on_host(host) == [
            l for l in s.links if l.src in local]


@pytest.mark.parametrize("topology", GRID)
def test_id_maps_resolve_every_element(topology):
    s = SliceTopology(topology)
    for c in s.chips:
        assert s.chip_by_id(c.id) is c
    for l in s.links:
        assert s.link_by_id(l.id) is l
    assert s.chip_by_id("chip-9999") is None
    assert s.link_by_id("ici-0-nope") is None


@pytest.mark.parametrize("topology", GRID)
def test_extent2_dims_stay_deduplicated(topology):
    """Extent-2 dimensions produce ONE link pair per neighbor couple —
    no duplicate (src, dst, dim) either in the flat list or through the
    indexes."""
    s = SliceTopology(topology)
    triples = [(l.src, l.dst, l.dim) for l in s.links]
    assert len(triples) == len(set(triples))
    # the per-chip degree the index reports must match the torus rule:
    # one port per extent>=3 dimension direction, one per extent-2 dim,
    # zero on extent-1 dims
    want_degree = sum(
        0 if extent == 1 else (1 if extent == 2 else 2)
        for extent in s.shape)
    for chip in s.chips:
        assert len(s.links_from(chip.index)) == want_degree
        assert want_degree <= PORTS_PER_CHIP[s.generation]


def test_cached_returns_equal_but_independent_state():
    a = SliceTopology.cached("v5e-16")
    b = SliceTopology.cached("v5e-16")
    fresh = SliceTopology("v5e-16")
    assert a is not b
    assert a.chips == b.chips == fresh.chips
    assert a.links == b.links == fresh.links
    assert a.to_dict() == fresh.to_dict()
    # mutating one clone's lists must not leak into the other (or into
    # a later cache hit)
    a.links.append("junk")
    a.chips.pop()
    assert "junk" not in b.links
    assert len(b.chips) == 16
    c = SliceTopology.cached("v5e-16")
    assert "junk" not in c.links and len(c.chips) == 16


def test_cached_to_dict_copies_are_independent():
    s = SliceTopology.cached("v5p-8")
    d1 = s.to_dict()
    d1["chips"][0]["id"] = "poisoned"
    d1["links"].clear()
    d2 = s.to_dict()
    assert d2["chips"][0]["id"] == "chip-0"
    assert len(d2["links"]) == len(s.links)


@pytest.mark.parametrize("topology", ["v5e-16", "v5p-32"])
def test_cached_matches_fresh_across_generations(topology):
    assert (SliceTopology.cached(topology).to_dict()
            == SliceTopology(topology).to_dict())
